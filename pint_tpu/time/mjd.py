"""Exact MJD handling: string parsing, UTC/TDB -> device ticks.

The device-side time coordinate is **int64 ticks of 2^-32 s since
MJD 51544.5 TDB** (J2000).  All conversions here are exact integer /
rational arithmetic on the host (python bigints — no float rounding at
all until the final tick quantization of 2^-32 s ~ 0.23 ns), replacing the
reference's longdouble + astropy (jd1, jd2) machinery
(reference: src/pint/pulsar_mjd.py:255-365 ``str_to_mjds``/``mjds_to_str``).

UTC MJDs follow the "pulsar_mjd" convention (reference pulsar_mjd.py:86):
the fractional part is the fraction of an 86400-s day even on leap-second
days (times *during* a leap second are unrepresentable, as in tempo).
"""

from __future__ import annotations

import numpy as np

from pint_tpu.time.scales import TT_MINUS_TAI, tai_minus_utc, tdb_minus_tt_seconds

#: MJD of the tick epoch (J2000, TDB scale)
EPOCH_MJD = 51544
EPOCH_FRAC = 0.5  # epoch is MJD 51544.5

TICKS_PER_SEC_INT = 2**32
SECS_PER_DAY_INT = 86400

#: ticks value of the epoch itself (by construction zero)
MJD_EPOCH_TICKS = 0

# TT-TAI = 32.184 s exactly; as an exact rational in ticks:
_TT_MINUS_TAI_TICKS = (32184 * TICKS_PER_SEC_INT) // 1000  # exact: 32.184*2^32


def mjd_string_to_day_frac(s: str):
    """Parse an MJD string to (int day, int frac_num, int frac_den).

    Exact decimal parsing: "53478.2858714192189" ->
    (53478, 2858714192189, 10**13).  Handles sign, D/E exponents
    (tempo par files use Fortran 'D'), and bare integers.
    """
    s = s.strip().upper().replace("D", "E")
    if "E" in s:
        # exponent form: normalize via decimal shifting, exactly
        mant, exp = s.split("E")
        exp = int(exp)
    else:
        mant, exp = s, 0
    neg = mant.startswith("-")
    mant = mant.lstrip("+-")
    if "." in mant:
        ipart, fpart = mant.split(".")
    else:
        ipart, fpart = mant, ""
    digits = (ipart + fpart) or "0"
    # value = digits * 10^(exp - len(fpart))
    shift = exp - len(fpart)
    num = int(digits)
    if neg:
        num = -num
    if shift >= 0:
        num *= 10**shift
        den = 1
    else:
        den = 10 ** (-shift)
    day, rem = divmod(num, den)  # floor division: rem >= 0 even for neg
    return int(day), int(rem), int(den)


def _day_frac_to_ticks_tdb(day, frac_num, frac_den, extra_sec_exact=0):
    """Exact: ticks since epoch for a TDB-scale (day + frac) MJD.

    extra_sec_exact: additional seconds as an exact (num, den) tuple or int.
    """
    # (day - EPOCH) days + frac - 0.5 day, all over frac_den, in ticks:
    # ticks = ((day-51544)*86400 + (frac_num/frac_den)*86400 - 43200) * 2^32
    base = (day - EPOCH_MJD) * SECS_PER_DAY_INT - 43200
    t = base * TICKS_PER_SEC_INT * frac_den
    t += frac_num * SECS_PER_DAY_INT * TICKS_PER_SEC_INT
    if isinstance(extra_sec_exact, tuple):
        en, ed = extra_sec_exact
        # round((t/frac_den) + (en/ed)*2^32) with a common denominator
        t = t * ed + en * TICKS_PER_SEC_INT * frac_den
        den = frac_den * ed
    else:
        t += extra_sec_exact * TICKS_PER_SEC_INT * frac_den
        den = frac_den
    # round-half-away-from-zero on the exact rational t/den
    q, r = divmod(t, den)
    if 2 * r >= den:
        q += 1
    return q


def mjd_to_ticks_tdb(day: int, frac_num: int, frac_den: int) -> int:
    """Ticks for an MJD already in the TDB scale (e.g. PEPOCH with UNITS TDB)."""
    return _day_frac_to_ticks_tdb(day, frac_num, frac_den)


def mjd_to_ticks_utc(day, frac_num, frac_den, clock_offset_sec=0.0):
    """Ticks (TDB) for a UTC pulsar-MJD, through the full scale chain.

    clock_offset_sec: observatory clock correction (obs->UTC), float64
    seconds (clock corrections are ~us — f64 exact enough by 9 orders).
    UTC -> TAI: integer leap seconds; TAI -> TT: +32.184 s (exact rational);
    TT -> TDB: harmonic series in f64 (see scales.py accuracy note).
    """
    leap = int(tai_minus_utc(day))
    # TT ticks, exactly
    tt_ticks = _day_frac_to_ticks_tdb(
        day, frac_num, frac_den, extra_sec_exact=(leap * 1000 + 32184, 1000)
    )
    # clock correction + TDB-TT in float (both small): convert to ticks
    tt_sec_f64 = tt_ticks / float(TICKS_PER_SEC_INT)
    dtdb = tdb_minus_tt_seconds(tt_sec_f64)
    small = float(dtdb) + float(clock_offset_sec)
    return tt_ticks + int(round(small * TICKS_PER_SEC_INT))


def mjd_float_to_ticks_tdb(mjd) -> np.ndarray:
    """Vectorized: float64 TDB MJD(s) -> int64 ticks (0.23 ns quantization).

    For programmatic epochs (simulation grids etc.); f64 MJD resolution is
    ~10 us at MJD ~5e4, so exactness is moot — use the string path for
    precision inputs.
    """
    mjd = np.asarray(mjd, dtype=np.float64)
    # int64 tick range covers +/-2^31 s around J2000: MJD ~ 26690..76398
    if np.any(mjd < 26690.0) or np.any(mjd > 76398.0):
        raise ValueError(
            "MJD outside the representable tick range (26690..76398, "
            "i.e. +/-68 yr around J2000)"
        )
    day = np.floor(mjd).astype(np.int64)
    frac = mjd - day
    base = (day - EPOCH_MJD) * SECS_PER_DAY_INT * TICKS_PER_SEC_INT
    off = np.round(
        frac * (SECS_PER_DAY_INT * float(TICKS_PER_SEC_INT))
    ).astype(np.int64) - 43200 * TICKS_PER_SEC_INT
    return base + off


def ticks_to_mjd_tdb(ticks):
    """Ticks -> (int day, longdouble frac in [0,1)) in the TDB scale."""
    ticks = np.asarray(ticks, dtype=np.int64)
    total = ticks + np.int64(43200) * np.int64(TICKS_PER_SEC_INT)
    day_ticks = np.int64(SECS_PER_DAY_INT) * np.int64(TICKS_PER_SEC_INT)
    day = total // day_ticks
    rem = total - day * day_ticks
    frac = rem.astype(np.longdouble) / np.longdouble(day_ticks)
    return (day + EPOCH_MJD).astype(np.int64), frac


def ticks_to_mjd_string_utc(ticks: int, clock_offset_sec: float = 0.0,
                            ndigits: int = 16) -> str:
    """Invert the UTC->TDB chain: TDB ticks -> site-UTC pulsar-MJD string
    (for .tim writing; reference: toa.py:566 format_toa_line).

    clock_offset_sec is subtracted (the same offset mjd_to_ticks_utc
    added).  Exact integer arithmetic except the small TDB-TT + clock
    terms (~ms), which are f64 — sub-ns on the output."""
    ticks = int(ticks)
    tdb_sec = ticks / float(TICKS_PER_SEC_INT)
    dtdb = float(tdb_minus_tt_seconds(tdb_sec))
    tt_ticks = ticks - int(round((dtdb + clock_offset_sec)
                                 * TICKS_PER_SEC_INT))
    # TT -> TAI -> UTC; leap lookup from the TT day, re-checked on the
    # UTC day (they can differ across a midnight boundary)
    day_guess = int(
        np.floor(tt_ticks / float(TICKS_PER_SEC_INT) / SECS_PER_DAY_INT
                 + EPOCH_MJD + EPOCH_FRAC)
    )
    for _ in range(2):
        leap = int(tai_minus_utc(day_guess))
        utc_ticks = tt_ticks - _TT_MINUS_TAI_TICKS \
            - leap * TICKS_PER_SEC_INT
        total = utc_ticks + 43200 * TICKS_PER_SEC_INT
        day = total // (SECS_PER_DAY_INT * TICKS_PER_SEC_INT) + EPOCH_MJD
        if day == day_guess:
            break
        day_guess = int(day)
    return _total_ticks_to_mjd_string(total, ndigits)


def _total_ticks_to_mjd_string(total: int, ndigits: int) -> str:
    """Midnight-based tick count -> decimal MJD string, rounding the
    fraction with carry into the day (shared by the TDB and UTC string
    paths)."""
    day_ticks = SECS_PER_DAY_INT * TICKS_PER_SEC_INT
    day, rem = divmod(total, day_ticks)
    scaled = rem * 10**ndigits
    q, r = divmod(scaled, day_ticks)
    if 2 * r >= day_ticks:
        q += 1
        if q == 10**ndigits:
            q = 0
            day += 1
    return f"{day + EPOCH_MJD}.{q:0{ndigits}d}"


def ticks_to_mjd_string_tdb(ticks: int, ndigits: int = 16) -> str:
    """One tick value -> decimal MJD string with ndigits fractional digits."""
    total = int(ticks) + 43200 * TICKS_PER_SEC_INT
    return _total_ticks_to_mjd_string(total, ndigits)
