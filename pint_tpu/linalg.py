"""Device linear-algebra helpers for correlated-noise likelihoods.

Counterpart of the reference's Woodbury/Sherman-Morrison helpers
(reference: src/pint/utils.py:3024 sherman_morrison_dot, :3074
woodbury_dot).  The covariance is C = N + U Phi U^T with N diagonal;
all quantities are computed through the rank-K capacity matrix
Sigma = Phi^-1 + U^T N^-1 U so nothing O(N^2) is ever formed.
Pure jax, differentiable, vmappable.

``phi`` may be either a (K,) vector — the classic independent-weights
case, Phi = diag(phi) — or a full (K, K) prior covariance matrix.  The
dense form carries the cross-pulsar GWB structure of :mod:`pint_tpu.gw`
(Hellings–Downs-coupled Fourier blocks across a stacked multi-pulsar
basis) through the SAME solver, so the single-pulsar and PTA
likelihoods cannot drift apart.

``U`` may be either a dense (N, K) array or a :class:`StructuredU` —
the segment-id representation of an ECORR epoch-indicator block
(built by :class:`pint_tpu.residuals.Residuals` when eligible), whose
0/1 products are carried by ``jax.ops.segment_sum`` instead of dense
matmuls.  The dense path is the fallback for everything else — the
GW dense-phi sector always passes dense arrays — and both paths are
brute-force-verified equivalent (tests/test_design.py).

Every contraction additionally accepts ``toa=`` — a
:class:`pint_tpu.parallel.mesh.RowShard` pinning the TOA (N) axis
onto a device mesh.  The O(N (P+K)^2) gram assembly then decomposes
into per-shard partial contractions plus a small-(P+K) cross-device
reduction (the rank-reduced Woodbury structure of arXiv 1210.0584):
the sharding constraints make XLA's SPMD partitioner carry the
N-axis blocks shard-local and insert one psum-class all-reduce per
(K, K)/(P, K) product.  ``toa=None`` (the default) leaves every
trace byte-identical to the unsharded build — the caller's jit key
must carry the mesh (``mesh_jit_key``) exactly because the two
builds differ.  Segment-sum ECORR epoch blocks must not straddle
shard boundaries for the reduction to stay shard-local; the
alignment contract lives in ``mesh.toa_shard_plan`` and the fitter
entry (docs/sharding.md).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from pint_tpu.guard import SolveDiag

__all__ = ["woodbury_chi2_logdet", "gls_normal_solve",
           "WoodburyPre", "woodbury_precompute",
           "woodbury_chi2_logdet_pre", "woodbury_solve",
           "StructuredU", "structured_from_dense_blocks", "su_to_dense",
           "su_dense_rows",
           "su_pad_rows", "basis_ncols", "noise_gram_precompute",
           "KronPhi", "KronGram", "kron_gw_blocks", "kron_phi_dense",
           "kron_gram_precompute", "kron_chi2_logdet_pre",
           "kron_chi2_logdet",
           "NormalBlocks", "normal_blocks", "normal_blocks_delta",
           "normal_blocks_shift", "normal_solve_from_blocks",
           "woodbury_pre_append", "noise_gram_append",
           "kron_gram_append"]

#: floor on basis weights: a zero weight (e.g. ECORR 0) means infinite
#: prior precision on that column — the coefficient is pinned to zero and
#: the logdet contributions cancel, instead of 1/phi producing NaNs.
#: 1e-30 (not smaller): TPU's float32-pair f64 emulation loses precision
#: below the f32 subnormal range (~1e-38), and 1/phi must stay finite
_PHI_FLOOR = 1e-30


class StructuredU(NamedTuple):
    """Structure-aware Woodbury basis: an ECORR epoch-indicator block
    carried as per-TOA segment ids instead of a dense 0/1 matrix, with
    the dense remainder (Fourier red-noise columns, mean-offset column)
    on either side.

    Column layout is ``[pre | ecorr epochs | post]`` — the SAME column
    order as the dense basis it replaces, so phi vectors, noise-
    coefficient slices (``noise_dimensions``) and the mean-offset
    column position are untouched.  Every contraction a Woodbury path
    needs (``U^T y``, ``U x``, ``U^T diag(w) U``) replaces the epoch
    block's dense matmuls with ``jax.ops.segment_sum`` / gathers: the
    ``N x K_e`` indicator products drop from O(N K_e K) to O(N K_d).

    All four fields are arrays, so a StructuredU is an ordinary pytree
    leaf-bundle of the fit-data dict — dynamic under shared traces.
    ``eslot`` is a zeros-(K_e,) shape carrier: in-trace code reads the
    STATIC epoch count from its shape (segment counts must be static
    for XLA), while rows outside any epoch carry segment id K_e and
    fall off the end of the ``[:K_e]`` slice."""

    pre: jnp.ndarray    # (N, K_pre) dense columns before the block
    seg: jnp.ndarray    # (N,) int32 epoch id, K_e = "no epoch"
    eslot: jnp.ndarray  # (K_e,) zeros — static epoch-count carrier
    post: jnp.ndarray   # (N, K_post) dense columns after the block


def basis_ncols(U) -> int:
    """Total column count of a dense or structured basis."""
    if isinstance(U, StructuredU):
        return (U.pre.shape[1] + U.eslot.shape[0] + U.post.shape[1])
    return U.shape[1]


def structured_from_dense_blocks(pre, seg, n_epoch, post):
    """Build a StructuredU from concrete blocks (host-side)."""
    return StructuredU(
        pre=jnp.asarray(pre),
        seg=jnp.asarray(seg, dtype=jnp.int32),
        eslot=jnp.zeros(int(n_epoch), dtype=jnp.float64),
        post=jnp.asarray(post),
    )


def su_to_dense(su: StructuredU):
    """Materialize the dense (N, K) basis — the fallback/verification
    form (woodbury_precompute, brute-force tests)."""
    n = su.seg.shape[0]
    k_e = su.eslot.shape[0]
    ecorr = (su.seg[:, None] == jnp.arange(k_e)[None, :]).astype(
        jnp.float64)
    return jnp.concatenate([su.pre, ecorr, su.post], axis=1)


def su_dense_rows(su: StructuredU, rows):
    """Materialize a row subset of the dense (len(rows), K) basis —
    the streaming append path's delta-row slice (ΔN rows of a basis it
    never needs in full)."""
    rows = jnp.asarray(rows)
    k_e = su.eslot.shape[0]
    ecorr = (su.seg[rows][:, None] == jnp.arange(k_e)[None, :]).astype(
        jnp.float64)
    return jnp.concatenate([su.pre[rows], ecorr, su.post[rows]], axis=1)


def su_pad_rows(su: StructuredU, n_rows: int):
    """Append ``n_rows`` zero rows (outside every epoch) — the wideband
    stacked [time; DM] system's DM block sees no noise basis."""
    k_e = su.eslot.shape[0]
    return StructuredU(
        pre=jnp.concatenate(
            [su.pre, jnp.zeros((n_rows, su.pre.shape[1]))], axis=0),
        seg=jnp.concatenate(
            [su.seg, jnp.full(n_rows, k_e, dtype=jnp.int32)]),
        eslot=su.eslot,
        post=jnp.concatenate(
            [su.post, jnp.zeros((n_rows, su.post.shape[1]))], axis=0),
    )


def _rows(toa, x):
    """Apply a RowShard's leading-axis constraint (identity when
    ``toa`` is None — the unsharded trace is byte-identical)."""
    return x if toa is None else toa.rows(x)


def _ut_dot(U, y, toa=None):
    """``U^T @ y`` for dense or structured U; y is (N,) or (N, M).
    With ``toa``, the N-axis contraction reduces per shard then
    all-reduces over the K axis (sharding-constraint psum)."""
    y = _rows(toa, y)
    if not isinstance(U, StructuredU):
        return _rows(toa, U).T @ y
    k_e = U.eslot.shape[0]
    seg_part = jax.ops.segment_sum(y, _rows(toa, U.seg),
                                   num_segments=k_e + 1)[:k_e]
    return jnp.concatenate([_rows(toa, U.pre).T @ y, seg_part,
                            _rows(toa, U.post).T @ y],
                           axis=0)


def _u_dot(U, x, toa=None):
    """``U @ x`` for dense or structured U; x is (K,) or (K, M).  The
    output carries the TOA axis, so with ``toa`` it is constrained
    back onto the mesh (x itself is small and replicated)."""
    if not isinstance(U, StructuredU):
        return _rows(toa, U) @ x
    k_pre = U.pre.shape[1]
    k_e = U.eslot.shape[0]
    x_pre = x[:k_pre]
    x_e = x[k_pre:k_pre + k_e]
    x_post = x[k_pre + k_e:]
    # out-of-epoch rows (seg == k_e) must gather zero
    x_e_ext = jnp.concatenate(
        [x_e, jnp.zeros((1,) + x_e.shape[1:], dtype=x_e.dtype)], axis=0)
    return (_rows(toa, U.pre) @ x_pre + x_e_ext[_rows(toa, U.seg)]
            + _rows(toa, U.post) @ x_post)


def _weighted_gram(U, w, toa=None):
    """``U^T diag(w) U`` for dense or structured U — THE capacity-gram
    build.  Structured path: the epoch block's products become one
    scalar segment-sum (diagonal block) plus segment-sums of the
    weighted dense columns (cross blocks).  With ``toa`` the (K, K)
    gram assembles from shard-local partial grams plus one
    all-reduce — the dominant saving of the sharded GLS fit."""
    w = _rows(toa, w)
    if not isinstance(U, StructuredU):
        U = _rows(toa, U)
        return (U.T * w[None, :]) @ U
    k_e = U.eslot.shape[0]
    U = StructuredU(pre=_rows(toa, U.pre), seg=_rows(toa, U.seg),
                    eslot=U.eslot, post=_rows(toa, U.post))
    pre_w = U.pre * w[:, None]
    post_w = U.post * w[:, None]
    g_pp = U.pre.T @ pre_w
    g_p_post = U.pre.T @ post_w
    g_post_post = U.post.T @ post_w
    g_pe = jax.ops.segment_sum(pre_w, U.seg,
                               num_segments=k_e + 1)[:k_e].T
    g_e_post = jax.ops.segment_sum(post_w, U.seg,
                                   num_segments=k_e + 1)[:k_e]
    g_ee = jnp.diag(jax.ops.segment_sum(w, U.seg,
                                        num_segments=k_e + 1)[:k_e])
    return jnp.block([
        [g_pp, g_pe, g_p_post],
        [g_pe.T, g_ee, g_e_post],
        [g_p_post.T, g_e_post.T, g_post_post],
    ])


def _phi_terms(phi, jitter=None):
    """Normalize a basis prior to its solver form.

    Returns ``(phi_inv, logdet_phi)`` where ``phi_inv`` is the (K, K)
    inverse-prior term to ADD to ``U^T N^-1 U`` — ``diag(1/phi)`` for a
    (K,) weight vector, a dense Cholesky inverse for a (K, K) prior
    covariance (the GWB cross-pulsar block structure).  Both forms
    floor the diagonal at ``_PHI_FLOOR`` so pinned-to-zero columns stay
    finite.

    jitter: optional traced scalar — the guard layer's degradation
    ladder escalates the dense path's per-diagonal relative jitter
    above its 1e-12 baseline when a Cholesky NaNs anyway (TPU ~49-bit
    pivot roundoff on a deeply rank-deficient prior)."""
    phi = jnp.asarray(phi)
    if phi.ndim == 2:
        # per-column relative jitter before the Cholesky: physically
        # meaningful dense priors are rank-deficient (a monopole ORF
        # is rank 1, dipole rank 3, so kron(ORF, diag(phi_gw)) has an
        # exact null space whose pivots are negative roundoff —
        # cho_factor would NaN).  The jitter must be relative to EACH
        # diagonal entry, never a global scale: a stacked PTA prior
        # legitimately spans ~60 orders of magnitude (1e30 offset
        # columns next to ~1e-28 GW mode weights), and Cholesky of the
        # block structure preserves that separation exactly while a
        # global floor (or an eigh pseudo-inverse, whose absolute
        # eigenvalue error is eps * ||phi||) would destroy the small
        # blocks.  1e-12 sits above accumulated f64 pivot roundoff and
        # pins null-space coefficients to ~zero variance — the dense
        # analogue of the vector-phi _PHI_FLOOR.
        k = phi.shape[0]
        d = jnp.abs(jnp.diag(phi)) + _PHI_FLOOR
        rel = 1e-12 if jitter is None else jnp.maximum(1e-12, jitter)
        phi = phi + rel * jnp.diag(d)
        cf = jax.scipy.linalg.cho_factor(phi, lower=True)
        phi_inv = jax.scipy.linalg.cho_solve(cf, jnp.eye(k))
        logdet_phi = 2.0 * jnp.sum(jnp.log(jnp.diag(cf[0])))
        return phi_inv, logdet_phi
    phi = jnp.maximum(phi, _PHI_FLOOR)
    return jnp.diag(1.0 / phi), jnp.sum(jnp.log(phi))


def _capacity(sigma, U, phi, jitter=None, toa=None):
    """THE capacity-matrix construction every Woodbury path shares:
    ``(nvec, cho_factor(U^T N^-1 U + Phi^-1), logdet Phi)``.  A
    conditioning or masking change here reaches chi2/logdet, solve,
    and precompute identically.

    jitter: optional traced scalar (guard degradation ladder) — adds a
    per-diagonal relative ridge to the capacity matrix before its
    Cholesky, the same escalation the dense prior gets in
    :func:`_phi_terms`.  The chi^2/logdet of a jittered solve is the
    exact answer for a slightly-regularized covariance, not the
    original — the serving rung is recorded in fit meta so degraded
    results are never mistaken for clean ones."""
    phi_inv, logdet_phi = _phi_terms(phi, jitter=jitter)
    nvec = _rows(toa, sigma**2)
    sigma_cap = _weighted_gram(U, 1.0 / nvec, toa=toa) + phi_inv
    if jitter is not None:
        d = jnp.abs(jnp.diag(sigma_cap))
        sigma_cap = sigma_cap + jitter * jnp.diag(d)
    cf = jax.scipy.linalg.cho_factor(sigma_cap, lower=True)
    return nvec, cf, logdet_phi


def woodbury_chi2_logdet(r, sigma, U, phi, valid=None, jitter=None,
                         toa=None):
    """(chi2, logdet C) for C = diag(sigma^2) + U Phi U^T.

    chi2 = r^T C^-1 r via the Woodbury identity; logdet via the matrix
    determinant lemma with the Cholesky of Sigma (reference:
    utils.woodbury_dot, utils.py:3074).  ``phi`` is a (K,) weight
    vector (Phi diagonal) or a (K, K) prior covariance (the stacked
    cross-pulsar GWB structure).

    valid: optional boolean mask excluding bucketing pad rows from the
    white logdet term (their ~1e-32 weights already vanish from every
    other reduction, but their log sigma^2 would shift — and, with
    EFAC free, bias — the log-likelihood).  jitter: optional traced
    scalar, the guard ladder's capacity/prior ridge (see
    :func:`_capacity`).  toa: optional
    :class:`pint_tpu.parallel.mesh.RowShard` sharding the N axis over
    a device mesh (module docstring).
    """
    nvec, cf, logdet_phi = _capacity(sigma, U, phi, jitter=jitter,
                                     toa=toa)
    ninv_r = _rows(toa, r) / nvec
    ut_ninv_r = _ut_dot(U, ninv_r, toa=toa)
    x = jax.scipy.linalg.cho_solve(cf, ut_ninv_r)
    chi2 = jnp.sum(r * ninv_r) - jnp.sum(ut_ninv_r * x)
    log_nvec = jnp.log(nvec)
    if valid is not None:
        log_nvec = jnp.where(valid, log_nvec, 0.0)
    logdet = (
        jnp.sum(log_nvec)
        + logdet_phi
        + 2.0 * jnp.sum(jnp.log(jnp.diag(cf[0])))
    )
    return chi2, logdet


def woodbury_solve(sigma, U, phi, y, toa=None):
    """C^-1 y for C = diag(sigma^2) + U Phi U^T, with y a vector (N,)
    or a matrix (N, M) of right-hand sides.  The cross-correlation
    engine (:mod:`pint_tpu.gw.os`) whitens residuals and GW bases
    through this; ``phi`` follows the vector/dense convention of
    :func:`woodbury_chi2_logdet`, ``toa`` the RowShard convention of
    the module docstring."""
    nvec, cf, _ = _capacity(sigma, U, phi, toa=toa)
    y2 = y if y.ndim == 2 else y[:, None]
    ninv_y = _rows(toa, y2) / nvec[:, None]
    x = jax.scipy.linalg.cho_solve(cf, _ut_dot(U, ninv_y, toa=toa))
    out = ninv_y - _u_dot(U, x, toa=toa) / nvec[:, None]
    return out if y.ndim == 2 else out[:, 0]


class WoodburyPre(NamedTuple):
    """Values-independent pieces of the Woodbury solve, prebuilt
    host-side (eagerly, OUTSIDE any trace) when sigma/U/phi are known
    constants — the chi^2-grid case where all noise parameters sit
    frozen in the closed-over base values.  Without this, every grid
    compile hands XLA an all-constant ``(U^T N^-1 U + Phi^-1)`` build
    plus its Cholesky to constant-fold from (n_toa, n_basis) inputs —
    the multi-GFLOP fold behind the BENCH_r05 constant-folding alarm
    (the same alarm class the eager ``_U_ext`` fix in residuals.py
    silenced)."""

    nvec: jnp.ndarray      # (N,) sigma^2
    U: jnp.ndarray         # (N, K)
    chol_lower: jnp.ndarray  # (K, K) lower Cholesky of the capacity mat
    logdet: jnp.ndarray    # scalar logdet C


def woodbury_precompute(sigma, U, phi):
    """Eagerly build the capacity-matrix Cholesky and logdet for
    constant (sigma, U, phi).  Call OUTSIDE jit with concrete arrays;
    the result is a small pytree whose in-trace footprint is (N, K) +
    (K, K) constants instead of a foldable (N, K) x (N, K) matmul.
    ``phi`` may be a (K,) weight vector or a dense (K, K) prior
    covariance (stacked GWB structure), like
    :func:`woodbury_chi2_logdet`.  A :class:`StructuredU` basis is
    densified here — the precompute runs ONCE, host-side, where the
    dense contraction is cheap and the WoodburyPre layout stays
    uniform."""
    sigma = jnp.asarray(sigma)
    if isinstance(U, StructuredU):
        U = su_to_dense(U)
    U = jnp.asarray(U)
    nvec, cf, logdet_phi = _capacity(sigma, U, phi)
    chol = cf[0]
    logdet = (
        jnp.sum(jnp.log(nvec))
        + logdet_phi
        + 2.0 * jnp.sum(jnp.log(jnp.diag(chol)))
    )
    return WoodburyPre(nvec, U, chol, logdet)


def woodbury_chi2_logdet_pre(r, pre: WoodburyPre):
    """(chi2, logdet) against a :func:`woodbury_precompute` result —
    only the r-dependent work stays in the trace."""
    ninv_r = r / pre.nvec
    ut_ninv_r = pre.U.T @ ninv_r
    x = jax.scipy.linalg.cho_solve((pre.chol_lower, True), ut_ninv_r)
    chi2 = jnp.sum(r * ninv_r) - jnp.sum(ut_ninv_r * x)
    return chi2, pre.logdet


def noise_gram_precompute(sigma, U, phi):
    """Eagerly build the constant block of the GLS normal matrix,
    ``U^T diag(sigma^-2) U + Phi^-1`` — the (K, K) piece that does NOT
    depend on the design matrix.  Call OUTSIDE jit with concrete
    (sigma, U, phi) when no fitted parameter touches the noise model:
    per Gauss-Newton iteration only the J-dependent blocks (P x P and
    P x K) remain to build, instead of the full (N, K+P) x (K+P)
    weighted gram — the dominant per-point matmul of a chi^2 grid.
    ``U`` may be dense or a :class:`StructuredU`."""
    sigma = jnp.asarray(sigma)
    phi_inv, _ = _phi_terms(phi)
    return _weighted_gram(U, 1.0 / sigma**2) + phi_inv


def gls_normal_solve(r, J, sigma, U, phi, pre=None, gram=None,
                     guard_eps=None, with_health=False, toa=None):
    """Solve the noise-augmented GLS normal equations (reference:
    GLSFitter.fit_toas, fitter.py:2164-2204).

    Minimizes (r - J d - U a)^T N^-1 (r - J d - U a) + a^T Phi^-1 a over
    (d, a).  Returns (dpar, cov, noise_coeffs, chi2) where dpar is the
    parameter *step* to ADD to the current vector for resid functions
    with J = d resid/d param (so the step applied is -d), cov is the
    parameter covariance block, noise_coeffs are the basis amplitudes a,
    and chi2 is the Woodbury chi^2 of r against C = N + U Phi U^T.

    pre: optional :class:`WoodburyPre` for the chi^2 evaluation when
    (sigma, U, phi) are trace-time constants (the chi^2-grid path) —
    keeps XLA from constant-folding the capacity matrix per compile.

    gram: optional precomputed ``U^T diag(w) U + Phi^-1`` block
    (:func:`noise_gram_precompute`) under the same constancy contract
    as ``pre`` — the normal matrix is then assembled from the small
    J-dependent blocks only, dropping the O(N (P+K)^2) weighted gram
    to O(N P (P+K)) per iteration, and the chi^2 reuses the gram's
    Cholesky (it IS the Woodbury capacity matrix) instead of
    rebuilding the weighted gram.  It may also arrive as a dynamic
    data-pytree leaf (the fitter's frozen-noise fast path), which
    keeps trace sharing intact.  Gram callers must pass a vector
    ``phi`` (the dense-prior GWB sector goes through the dense path).

    ``phi`` may be a (K,) weight vector or a dense (K, K) prior
    covariance (stacked cross-pulsar GWB structure) — the inverse
    prior enters the normal matrix as a block either way.

    guard_eps: optional traced scalar, the guard degradation ladder's
    escalation knob — raises the pseudo-inverse relative cutoff above
    its 1e-16 baseline AND ridges the Woodbury capacity/prior
    Choleskys (:func:`_capacity`).  Dynamic, so escalating costs zero
    new compiles.  with_health: additionally return a
    :class:`pint_tpu.guard.SolveDiag` (truncated-direction count +
    condition proxy from the eigh spectrum already in hand).

    toa: optional :class:`pint_tpu.parallel.mesh.RowShard` — every
    N-axis product (the J^T W J / J^T W U / U^T W U blocks and both
    right-hand sides) assembles shard-local and all-reduces at the
    small (P+K) edge, so a 20-year single-pulsar gram parallelizes
    across devices (module docstring).
    """
    n_par = J.shape[1]
    nb = basis_ncols(U)
    r = _rows(toa, r)
    J = _rows(toa, J)
    nvec = _rows(toa, sigma**2)
    w = 1.0 / nvec
    if gram is not None and nb:
        # constant-gram fast path: only the design-dependent blocks
        # are built per call; the (K, K) noise block is data
        Jw = J * w[:, None]
        a_jj = J.T @ Jw
        a_ju = _ut_dot(U, Jw, toa=toa).T  # (P, K)
        mtcm = jnp.block([[a_jj, a_ju],
                          [a_ju.T, gram]])
        rhs = jnp.concatenate([Jw.T @ r, _ut_dot(U, w * r, toa=toa)])
    elif isinstance(U, StructuredU):
        # structured normal equations: the ECORR epoch block of
        # M = [J | U] enters every product through segment-sums
        # (_ut_dot/_weighted_gram) instead of dense (N, K_e) matmuls
        Jw = J * w[:, None]
        a_jj = J.T @ Jw
        a_ju = _ut_dot(U, Jw, toa=toa).T  # (P, K)
        a_uu = _weighted_gram(U, w, toa=toa)
        phi_inv, _ = _phi_terms(phi)
        mtcm = jnp.block([[a_jj, a_ju],
                          [a_ju.T, a_uu + phi_inv]])
        rhs = jnp.concatenate([Jw.T @ r, _ut_dot(U, w * r, toa=toa)])
    else:
        M = jnp.concatenate([J, _rows(toa, U)], axis=1) if nb else J
        mtn = (M * w[:, None]).T
        if nb:
            phi_inv, _ = _phi_terms(phi)
            phi_inv_full = jnp.zeros(
                (n_par + nb, n_par + nb)).at[n_par:, n_par:].set(phi_inv)
        else:
            phi_inv_full = jnp.zeros((n_par, n_par))
        mtcm = mtn @ M + phi_inv_full
        rhs = mtn @ r
    # column normalization for conditioning (reference
    # normalize_designmatrix, utils.py:2879)
    norm = jnp.sqrt(jnp.diag(mtcm))
    norm = jnp.where(norm == 0, 1.0, norm)
    mtcm_n = mtcm / jnp.outer(norm, norm)
    # symmetric eigendecomposition with a pseudo-inverse cutoff instead
    # of Cholesky: the reference falls back to SVD when cho_factor fails
    # (fitter.py:2204); on TPU the f32-pair f64 emulation (~49-bit)
    # makes near-degenerate normal matrices fail Cholesky outright, so
    # the fallback is the main path here.  mtcm_n has unit diagonal, so
    # eigenvalues are O(1)..O(P) and the cutoff is a clean relative one.
    w, Q = jnp.linalg.eigh(mtcm_n)
    wmax = jnp.max(w)
    cut = 1e-16 if guard_eps is None else jnp.maximum(1e-16, guard_eps)
    w_inv = jnp.where(w > cut * wmax, 1.0 / w, 0.0)
    xhat = (Q @ (w_inv * (Q.T @ (rhs / norm)))) / norm
    cov_full = (Q * w_inv[None, :]) @ Q.T / jnp.outer(norm, norm)
    if nb:
        if pre is not None:
            chi2, _ = woodbury_chi2_logdet_pre(r, pre)
        elif gram is not None:
            # the precomputed gram IS the Woodbury capacity matrix
            # (U^T N^-1 U + Phi^-1 == _capacity's sigma_cap), so the
            # chi^2 comes from its Cholesky directly — rebuilding the
            # O(N K^2) weighted gram per iteration through
            # woodbury_chi2_logdet would undo exactly the saving the
            # gram path exists for.  The guard ladder's escalation
            # ridge is applied in-trace the way _capacity does it
            # (per-diagonal relative), so rung behaviour matches the
            # dense path.  Contract: gram callers carry a vector phi
            # (the fitter's frozen-noise leaves), where _phi_terms
            # ignores the jitter and the match is exact.
            cap = gram
            if guard_eps is not None:
                cap = cap + guard_eps * jnp.diag(jnp.abs(jnp.diag(cap)))
            cf = jax.scipy.linalg.cho_factor(cap, lower=True)
            ninv_r = r / nvec
            ut_ninv_r = _ut_dot(U, ninv_r, toa=toa)
            x = jax.scipy.linalg.cho_solve(cf, ut_ninv_r)
            chi2 = jnp.sum(r * ninv_r) - jnp.sum(ut_ninv_r * x)
        else:
            chi2, _ = woodbury_chi2_logdet(r, sigma, U, phi,
                                           jitter=guard_eps, toa=toa)
    else:
        chi2 = jnp.sum((r / sigma) ** 2)
    out = (
        -xhat[:n_par],
        cov_full[:n_par, :n_par],
        xhat[n_par:],
        chi2,
    )
    if with_health:
        kept_min = jnp.min(jnp.where(w_inv > 0.0, w, wmax))
        diag = SolveDiag(
            n_truncated=jnp.sum(w_inv == 0.0).astype(jnp.int32),
            cond_log10=jnp.log10(wmax / jnp.maximum(kept_min, 1e-300)),
        )
        out = out + (diag,)
    return out


# --------------------------------------------------------------------------
# Kronecker-structured stacked-array prior (the GWB cross-pulsar block)
# --------------------------------------------------------------------------

class KronPhi(NamedTuple):
    """The stacked PTA basis prior in its structured form:

        Phi = blockdiag_a(diag(phi_noise[a]))  (+)  kron(orf, diag(phi_gw))

    over column layout ``[pulsar-major noise columns | pulsar-major GW
    Fourier columns]`` — exactly the dense (K, K) prior
    :mod:`pint_tpu.gw.common` hands :func:`woodbury_chi2_logdet`, but
    carried as its three generating factors instead of the materialized
    matrix.  The GW sector is block-diagonal PER FREQUENCY under the
    frequency-major permutation: mode i's (N_psr, N_psr) block is
    ``phi_gw[i] * orf``, so the prior's Cholesky/inverse/logdet cost
    O(n_freq * N_psr^3) instead of O(K^3) (:func:`kron_gw_blocks`),
    and the full covariance solve decomposes into per-pulsar Woodbury
    reductions plus one GW-sector capacity solve
    (:func:`kron_chi2_logdet`) — the rank-reduced two-level structure
    of arXiv 1210.0584 applied across the array.

    All three fields are arrays (an ordinary pytree — dynamic under
    shared traces, differentiable wrt every field):

    - ``orf``: (P, P) cross-pulsar correlation of the common process;
    - ``phi_gw``: (m2,) per-frequency common-process weights [s^2];
    - ``phi_noise``: (P, nb) per-pulsar own-basis weights, padded to a
      common width — a 0 weight means "absent pad column" and is
      pinned exactly like the vector-phi ``_PHI_FLOOR`` convention."""

    orf: jnp.ndarray
    phi_gw: jnp.ndarray
    phi_noise: jnp.ndarray


class KronGram(NamedTuple):
    """Per-pulsar noise-gram products of the kron-structured solve —
    everything that depends on (r, sigma, U, F) but NOT on the prior
    weights.  Precomputed once (host-side, eagerly) when no sampled
    parameter touches sigma, these leaves ride the data pytree across
    HMC draws: a posterior evaluation then costs O(P nb^3 + (P m2)^3)
    with no O(N) contraction at all (gw/hmc reuses one gram across
    every draw of every chain).  Built in-trace from dynamic sigma
    when a white-noise parameter IS sampled — same code path, the
    gradient simply flows through the gram."""

    g_uu: jnp.ndarray     # (P, nb, nb)  U^T W U
    g_uf: jnp.ndarray     # (P, nb, m2)  U^T W F
    g_ff: jnp.ndarray     # (P, m2, m2)  F^T W F
    b_u: jnp.ndarray      # (P, nb)      U^T W r
    b_f: jnp.ndarray      # (P, m2)      F^T W r
    rr: jnp.ndarray       # (P,)         r^T W r
    ld_white: jnp.ndarray  # (P,)        sum_valid log sigma^2


def kron_gram_precompute(r, sigma, U, F, valid=None) -> KronGram:
    """The per-pulsar weighted-gram products over padded per-pulsar
    stacks ``r (P, N), sigma (P, N), U (P, N, nb), F (P, N, m2)``.

    Pad rows must carry zero r/U/F entries (their sigma is arbitrary
    but finite — ``gw.common.PAD_SIGMA_S`` by convention), so every
    contraction here is EXACT regardless of padding; only the white
    logdet needs the ``valid`` row mask."""
    w = 1.0 / sigma**2
    g_uu = jnp.einsum("pni,pn,pnj->pij", U, w, U)
    g_uf = jnp.einsum("pni,pn,pnj->pij", U, w, F)
    g_ff = jnp.einsum("pni,pn,pnj->pij", F, w, F)
    b_u = jnp.einsum("pni,pn,pn->pi", U, w, r)
    b_f = jnp.einsum("pni,pn,pn->pi", F, w, r)
    rr = jnp.einsum("pn,pn,pn->p", r, w, r)
    log_nvec = jnp.log(sigma**2)
    if valid is not None:
        log_nvec = jnp.where(valid, log_nvec, 0.0)
    return KronGram(g_uu=g_uu, g_uf=g_uf, g_ff=g_ff, b_u=b_u,
                    b_f=b_f, rr=rr, ld_white=jnp.sum(log_nvec, axis=1))


def kron_gw_blocks(kp: KronPhi, jitter=None):
    """The per-frequency (N_psr, N_psr) blocks of the GW prior sector
    — the O(n_freq * N_psr^2) routing the kron structure exists for.

    Under the frequency-major permutation ``kron(orf, diag(phi_gw))``
    is block-diagonal: mode i's (P, P) block is ``phi_gw[i] * orf``.
    Each block gets the SAME per-diagonal relative jitter the dense
    path's :func:`_phi_terms` applies to the materialized (K, K)
    prior (``rel * (|diag| + _PHI_FLOOR)`` with ``rel = max(1e-12,
    jitter)``), so the kron path evaluates the IDENTICAL jittered
    model the dense reference does — the two differ only in roundoff.

    Returns ``blocks (m2, P, P)`` — never their inverses: the capacity
    algebra downstream (:func:`kron_chi2_logdet_pre`) is arranged so
    the prior is only ever MULTIPLIED, which is what keeps a rank-1
    monopole ORF (exact null space; the dense path's inverse-prior
    route loses ~kappa*eps there) numerically clean."""
    orf = kp.orf
    phi_gw = kp.phi_gw
    p = orf.shape[0]
    rel = 1e-12 if jitter is None else jnp.maximum(1e-12, jitter)
    blocks = phi_gw[:, None, None] * orf[None, :, :]
    d = jnp.abs(phi_gw[:, None] * jnp.diag(orf)[None, :]) + _PHI_FLOOR
    return blocks + rel * (d[:, :, None] * jnp.eye(p)[None, :, :])


def kron_phi_dense(kp: KronPhi):
    """Materialize the dense (K, K) prior a :class:`KronPhi` stands
    for, in the stacked column layout ``[pulsar-major noise columns |
    pulsar-major GW columns]`` — the brute-force verification form
    (tests) and the bridge to :func:`woodbury_chi2_logdet`'s 2-D phi."""
    p, nb = kp.phi_noise.shape
    m2 = kp.phi_gw.shape[0]
    k = p * nb + p * m2
    phi = jnp.zeros((k, k))
    phi = phi.at[:p * nb, :p * nb].set(jnp.diag(kp.phi_noise.ravel()))
    gw = jnp.kron(kp.orf, jnp.diag(kp.phi_gw))
    return phi.at[p * nb:, p * nb:].set(gw)


def kron_chi2_logdet_pre(pre: KronGram, kp: KronPhi, jitter=None):
    """(chi2, logdet C) of the stacked array against precomputed
    per-pulsar grams — the prior-weight-dependent half of
    :func:`kron_chi2_logdet`, and the per-draw program of gw/hmc.

    Two-level Woodbury: with C = blockdiag_a(C_a) + G Phi_gw G^T
    (C_a each pulsar's own noise covariance, G the block-diagonal GW
    basis), the generalized matrix-determinant/SMW pair that never
    inverts the prior:

        chi2    = sum_a r_a^T C_a^-1 r_a
                  -  X^T Phi_gw (I + M Phi_gw)^-1 X
        logdet  = sum_a logdet C_a + logdet(I + M Phi_gw)

    where X stacks the per-pulsar ``F_a^T C_a^-1 r_a`` and M =
    blockdiag_a(F_a^T C_a^-1 F_a).  Phi_gw enters ONLY through
    products assembled from its per-frequency (P, P) blocks
    (:func:`kron_gw_blocks`), never through Phi_gw^-1: the identities
    hold for ARBITRARY (even exactly singular) priors, so a rank-1
    monopole ORF costs no conditioning — ``I + M Phi_gw`` has
    eigenvalues >= 1 — where the dense reference's explicit
    ``Phi^-1`` route loses ~kappa*eps = 1e-4 of every digit the
    1e-12 jitter scale implies.  Every inner solve is a per-pulsar
    (nb, nb) Cholesky; the one cross-pulsar factorization is the
    (P*m2, P*m2) LU of I + M Phi_gw — never the dense (K, K).
    ``jitter``: the guard ladder's escalation scalar — raises the
    per-frequency prior blocks' relative ridge and per-diagonal-
    ridges the per-pulsar capacity Choleskys, the
    :func:`_capacity`/:func:`_phi_terms` convention."""
    p, nb = kp.phi_noise.shape
    m2 = kp.phi_gw.shape[0]
    phi_n = jnp.maximum(kp.phi_noise, _PHI_FLOOR)

    if nb:
        def one(g_uu, g_uf, g_ff, b_u, b_f, rr, ld_white, phi_row):
            cap = g_uu + jnp.diag(1.0 / phi_row)
            if jitter is not None:
                cap = cap + jitter * jnp.diag(jnp.abs(jnp.diag(cap)))
            cf = jax.scipy.linalg.cho_factor(cap, lower=True)
            x_u = jax.scipy.linalg.cho_solve(cf, b_u)
            x_uf = jax.scipy.linalg.cho_solve(cf, g_uf)
            chi2_a = rr - b_u @ x_u
            x_a = b_f - g_uf.T @ x_u
            m_a = g_ff - g_uf.T @ x_uf
            ld_a = (ld_white + jnp.sum(jnp.log(phi_row))
                    + 2.0 * jnp.sum(jnp.log(jnp.diag(cf[0]))))
            return chi2_a, x_a, m_a, ld_a

        chi2_d, x, m, ld_d = jax.vmap(one)(
            pre.g_uu, pre.g_uf, pre.g_ff, pre.b_u, pre.b_f, pre.rr,
            pre.ld_white, phi_n)
    else:
        chi2_d, x, m, ld_d = pre.rr, pre.b_f, pre.g_ff, pre.ld_white

    blocks = kron_gw_blocks(kp, jitter=jitter)
    # pulsar-major scatters: Phi_gw from its frequency-diagonal
    # blocks, M from its per-pulsar diagonal blocks
    pm = p * m2
    phi_mat = jnp.einsum("iab,ij->aibj", blocks,
                         jnp.eye(m2)).reshape(pm, pm)
    m_blk = jnp.einsum("aij,ab->aibj", m, jnp.eye(p)).reshape(pm, pm)
    t = jnp.eye(pm) + m_blk @ phi_mat
    x_flat = x.reshape(pm)
    # Phi (I + M Phi)^-1 is symmetric (push Phi through the inverse),
    # so one LU solve serves the quadratic form
    corr = x_flat @ (phi_mat @ jnp.linalg.solve(t, x_flat))
    chi2 = jnp.sum(chi2_d) - corr
    logdet = jnp.sum(ld_d) + jnp.linalg.slogdet(t)[1]
    return chi2, logdet


def kron_chi2_logdet(r, sigma, U, F, kp: KronPhi, valid=None,
                     jitter=None):
    """(chi2, logdet C) for the stacked-array covariance

        C = blockdiag_a(diag(sigma_a^2) + U_a diag(phi_noise[a]) U_a^T)
            + blockdiag_a(F_a) kron(orf, diag(phi_gw)) blockdiag_a(F_a)^T

    over padded per-pulsar stacks — the kron-structured equivalent of
    :func:`woodbury_chi2_logdet` with the materialized dense prior
    (brute-force-verified equal; tests/test_kron_hmc.py).  Arguments
    follow :func:`kron_gram_precompute`'s padded-stack conventions;
    ``valid`` masks pad rows out of the white logdet term exactly like
    the dense path's ``valid``."""
    return kron_chi2_logdet_pre(
        kron_gram_precompute(r, sigma, U, F, valid=valid), kp,
        jitter=jitter)


# --------------------------------------------------------------------------
# streaming appends: rank-k updates to the precomputes (arXiv 1210.0584)
# --------------------------------------------------------------------------
#
# An appended observing epoch touches the N-row system only through
# row sums: every block of the GLS normal matrix and every capacity
# matrix is a sum over TOA rows, so DeltaN new rows are a rank-k
# correction assembled in O(DeltaN (P+K)^2) — never a re-factorization
# of the N-row gram.  Pad-sentinel rows flipped real by
# ``compile_cache.append_toas`` carried weight ~1e-32 before the flip;
# the updates below either downdate them exactly (woodbury_pre_append,
# noise_gram_append — so the result matches a from-scratch precompute
# to roundoff) or document the ~1e-32-relative residue as below the
# streaming path's 1e-10 consistency budget (NormalBlocks, whose
# capture runs on the already-flipped data anyway).

class NormalBlocks(NamedTuple):
    """The GLS normal-equation system reduced to its N-free summary —
    everything :func:`gls_normal_solve`'s constant-gram path needs,
    with the O(N) row contractions already folded in.  Captured once
    after a converged fit (``normal_blocks``), then kept current
    across streaming appends by pure rank-k row updates
    (:func:`normal_blocks_delta`) and linearization re-anchoring
    (:func:`normal_blocks_shift`): the incremental refit
    (:func:`normal_solve_from_blocks`) costs O((P+K)^3) with NO term
    proportional to N — the O(P^2 DeltaN) append economics of arXiv
    1210.0584.  All blocks are defined at a fixed linearization point
    (the parameter vector r and J were evaluated at); the shift keeps
    them first-order exact after a step, and the caller bounds drift
    by periodic recapture."""

    a_jj: jnp.ndarray   # (P, P) J^T W J
    a_ju: jnp.ndarray   # (P, K) J^T W U
    gram: jnp.ndarray   # (K, K) U^T W U + Phi^-1 (Woodbury capacity)
    y_j: jnp.ndarray    # (P,)  J^T W r
    y_u: jnp.ndarray    # (K,)  U^T W r
    rr: jnp.ndarray     # ()    r^T W r


def normal_blocks(r, J, sigma, U, phi, valid=None):
    """Capture the :class:`NormalBlocks` summary from full-size arrays
    — the one O(N) pass of the streaming path, run at stream-prepare
    time (and periodic recapture) under a shared trace.

    ``valid`` masks bucketing pad rows to EXACTLY zero weight, so a
    capture over a padded bucket equals one over the real rows alone
    bit-for-bit — without it pad rows contribute their ~1e-32 sentinel
    weights like everywhere else.  ``U`` may be dense or a
    :class:`StructuredU`; ``phi`` must be a (K,) weight vector (the
    frozen-noise gram contract of :func:`gls_normal_solve` — the
    dense-prior GWB sector streams through :func:`kron_gram_append`
    instead)."""
    w = 1.0 / sigma**2
    if valid is not None:
        w = jnp.where(valid, w, 0.0)
    nb = basis_ncols(U)
    Jw = J * w[:, None]
    a_jj = J.T @ Jw
    if nb:
        a_ju = _ut_dot(U, Jw).T
        phi_inv, _ = _phi_terms(phi)
        gram = _weighted_gram(U, w) + phi_inv
        y_u = _ut_dot(U, w * r)
    else:
        p = J.shape[1]
        a_ju = jnp.zeros((p, 0))
        gram = jnp.zeros((0, 0))
        y_u = jnp.zeros((0,))
    return NormalBlocks(a_jj=a_jj, a_ju=a_ju, gram=gram,
                        y_j=Jw.T @ r, y_u=y_u,
                        rr=jnp.sum(r * w * r))


def normal_blocks_delta(nb_pre: NormalBlocks, r_d, J_d, sigma_d, U_d,
                        valid_d=None):
    """Fold DeltaN appended rows into a :class:`NormalBlocks` — the
    rank-k update.  Every block is a row sum, so the delta rows simply
    ADD; rows masked off by ``valid_d`` (the fixed-size stream-block
    padding) carry exactly zero weight and vanish from every product,
    which is what lets the delta program run at ONE static shape
    (``$PINT_TPU_STREAM_BLOCK``) regardless of the actual nightly
    DeltaN — zero recompiles.  ``U_d`` is the dense (DeltaN, K) basis
    rows of the appended TOAs evaluated against the FROZEN basis
    anchoring (span-frozen Fourier comb, existing ECORR epochs — see
    docs/streaming.md); structure growth (a new epoch column) must
    fall back to full re-prepare upstream, it cannot be expressed
    here."""
    w = 1.0 / sigma_d**2
    if valid_d is not None:
        w = jnp.where(valid_d, w, 0.0)
    Jw = J_d * w[:, None]
    k = nb_pre.gram.shape[0]
    if k:
        return NormalBlocks(
            a_jj=nb_pre.a_jj + J_d.T @ Jw,
            a_ju=nb_pre.a_ju + Jw.T @ U_d,
            gram=nb_pre.gram + U_d.T @ (U_d * w[:, None]),
            y_j=nb_pre.y_j + Jw.T @ r_d,
            y_u=nb_pre.y_u + U_d.T @ (w * r_d),
            rr=nb_pre.rr + jnp.sum(r_d * w * r_d),
        )
    return nb_pre._replace(a_jj=nb_pre.a_jj + J_d.T @ Jw,
                           y_j=nb_pre.y_j + Jw.T @ r_d,
                           rr=nb_pre.rr + jnp.sum(r_d * w * r_d))


def normal_blocks_shift(nb_pre: NormalBlocks, dpar):
    """Re-anchor the linearization after the parameter vector moved by
    ``dpar`` (the step ADDED to the vector, i.e. the first element of
    :func:`normal_solve_from_blocks`'s return).  To first order
    r -> r + J dpar, so only the r-dependent blocks move — and they
    move through the gram blocks already in hand:

        y_j += A_jj dpar,   y_u += A_ju^T dpar,
        rr  += 2 dpar^T y_j_old + dpar^T A_jj dpar.

    Exact for a truly linear model; for the real (mildly nonlinear)
    timing model the quadratic residue is what periodic recapture
    (``$PINT_TPU_STREAM_RECAPTURE``) bounds."""
    rr = (nb_pre.rr + 2.0 * jnp.dot(dpar, nb_pre.y_j)
          + dpar @ nb_pre.a_jj @ dpar)
    return nb_pre._replace(y_j=nb_pre.y_j + nb_pre.a_jj @ dpar,
                           y_u=nb_pre.y_u + nb_pre.a_ju.T @ dpar,
                           rr=rr)


def normal_solve_from_blocks(nb_pre: NormalBlocks, guard_eps=None,
                             with_health=False):
    """:func:`gls_normal_solve` evaluated from a :class:`NormalBlocks`
    summary — the SAME normalization, eigh pseudo-inverse cutoff, and
    gram-Cholesky chi^2 as the constant-gram path there (so streamed
    and batch fits agree to roundoff), with every N-sized contraction
    already folded into the blocks.  Returns ``(dpar, cov,
    noise_coeffs, chi2)`` (+ SolveDiag when ``with_health``) under
    gls_normal_solve's sign convention: ``dpar`` is the step to ADD."""
    n_par = nb_pre.a_jj.shape[0]
    k = nb_pre.gram.shape[0]
    if k:
        mtcm = jnp.block([[nb_pre.a_jj, nb_pre.a_ju],
                          [nb_pre.a_ju.T, nb_pre.gram]])
        rhs = jnp.concatenate([nb_pre.y_j, nb_pre.y_u])
    else:
        mtcm = nb_pre.a_jj
        rhs = nb_pre.y_j
    norm = jnp.sqrt(jnp.diag(mtcm))
    norm = jnp.where(norm == 0, 1.0, norm)
    mtcm_n = mtcm / jnp.outer(norm, norm)
    w, Q = jnp.linalg.eigh(mtcm_n)
    wmax = jnp.max(w)
    cut = 1e-16 if guard_eps is None else jnp.maximum(1e-16, guard_eps)
    w_inv = jnp.where(w > cut * wmax, 1.0 / w, 0.0)
    xhat = (Q @ (w_inv * (Q.T @ (rhs / norm)))) / norm
    cov_full = (Q * w_inv[None, :]) @ Q.T / jnp.outer(norm, norm)
    if k:
        # chi^2 from the capacity Cholesky, exactly the gram fast path
        # of gls_normal_solve: rr - y_u^T cap^-1 y_u with the guard
        # ladder's per-diagonal relative ridge
        cap = nb_pre.gram
        if guard_eps is not None:
            cap = cap + guard_eps * jnp.diag(jnp.abs(jnp.diag(cap)))
        cf = jax.scipy.linalg.cho_factor(cap, lower=True)
        x = jax.scipy.linalg.cho_solve(cf, nb_pre.y_u)
        chi2 = nb_pre.rr - jnp.sum(nb_pre.y_u * x)
    else:
        chi2 = nb_pre.rr
    out = (
        -xhat[:n_par],
        cov_full[:n_par, :n_par],
        xhat[n_par:],
        chi2,
    )
    if with_health:
        kept_min = jnp.min(jnp.where(w_inv > 0.0, w, wmax))
        diag = SolveDiag(
            n_truncated=jnp.sum(w_inv == 0.0).astype(jnp.int32),
            cond_log10=jnp.log10(wmax / jnp.maximum(kept_min, 1e-300)),
        )
        out = out + (diag,)
    return out


def woodbury_pre_append(pre: WoodburyPre, row0, sigma_rows, u_rows,
                        logdet_phi=None):
    """Extend a :class:`WoodburyPre` with appended rows WITHOUT
    re-factorizing the N-row system: the bucket-interior append flips
    ``pad_toas``'s sentinel rows at ``[row0, row0 + DeltaN)`` to real
    data, so the capacity matrix moves by the rank-k difference of the
    outgoing sentinel rows and the incoming real rows,

        Sigma' = L L^T - U_old^T W_old U_old + U_new^T W_new U_new,

    re-Choleskied at O(K^3) — N enters only through the (DeltaN, K)
    row products.  The sentinel downdate is carried EXACTLY (the old
    rows still sit in ``pre``), so the result matches a from-scratch
    :func:`woodbury_precompute` over the flipped data to roundoff.
    The logdet moves by the white-row swap plus the capacity
    determinant ratio; ``logdet_phi`` is NOT needed because it cancels
    in the difference.  ``row0`` may be traced (dynamic-slice
    addressing), DeltaN is static from ``sigma_rows.shape`` — one
    shared executable serves every append in the bucket."""
    dn = sigma_rows.shape[0]
    u_rows = jnp.asarray(u_rows)
    nvec_new = jnp.asarray(sigma_rows) ** 2
    nvec_old = jax.lax.dynamic_slice_in_dim(pre.nvec, row0, dn)
    u_old = jax.lax.dynamic_slice_in_dim(pre.U, row0, dn, axis=0)
    cap_old = pre.chol_lower @ pre.chol_lower.T
    cap = (cap_old
           - u_old.T @ (u_old / nvec_old[:, None])
           + u_rows.T @ (u_rows / nvec_new[:, None]))
    cf = jax.scipy.linalg.cho_factor(cap, lower=True)
    logdet = (pre.logdet
              + jnp.sum(jnp.log(nvec_new)) - jnp.sum(jnp.log(nvec_old))
              + 2.0 * jnp.sum(jnp.log(jnp.diag(cf[0])))
              - 2.0 * jnp.sum(jnp.log(jnp.diag(pre.chol_lower))))
    return WoodburyPre(
        nvec=jax.lax.dynamic_update_slice_in_dim(
            pre.nvec, nvec_new, row0, 0),
        U=jax.lax.dynamic_update_slice_in_dim(pre.U, u_rows, row0, 0),
        chol_lower=cf[0],
        logdet=logdet,
    )


def noise_gram_append(gram, row0, sigma_rows, u_rows, sigma_old_rows,
                      u_old_rows):
    """Extend a :func:`noise_gram_precompute` result with appended
    rows: the (K, K) gram moves by the same sentinel-out/real-in
    rank-k difference as :func:`woodbury_pre_append` (the gram IS the
    capacity matrix), and since the gram is carried unfactored the
    update is pure row arithmetic — O(DeltaN K^2), no Cholesky here
    (``gls_normal_solve`` factors it in-trace).  The caller passes the
    outgoing sentinel rows explicitly (``sigma_old_rows`` /
    ``u_old_rows``) because the gram, unlike a WoodburyPre, does not
    retain its rows; ``row0`` is accepted for signature symmetry and
    unused."""
    del row0
    u_rows = jnp.asarray(u_rows)
    u_old_rows = jnp.asarray(u_old_rows)
    w_new = 1.0 / jnp.asarray(sigma_rows) ** 2
    w_old = 1.0 / jnp.asarray(sigma_old_rows) ** 2
    return (gram
            - u_old_rows.T @ (u_old_rows * w_old[:, None])
            + u_rows.T @ (u_rows * w_new[:, None]))


def kron_gram_append(pre: KronGram, pulsar, row0, r_rows, sigma_rows,
                     u_rows, f_rows):
    """Extend a :func:`kron_gram_precompute` result with rows appended
    to ONE pulsar of the stacked array.  Kron pad rows carry exactly
    zero r/U/F by contract (module docstring there), so the outgoing
    pad rows contributed NOTHING to the gram products and the update
    is purely additive — only the white logdet swaps the pad rows'
    masked-out zeros for the new rows' log sigma^2.  O(DeltaN (nb +
    m2)^2) on pulsar ``pulsar``'s (nb, nb)/(nb, m2)/(m2, m2) blocks;
    every other pulsar's blocks are untouched.  ``pulsar`` and
    ``row0`` may be traced."""
    w = 1.0 / jnp.asarray(sigma_rows) ** 2
    u_rows = jnp.asarray(u_rows)
    f_rows = jnp.asarray(f_rows)
    r_rows = jnp.asarray(r_rows)
    uw = u_rows * w[:, None]
    fw = f_rows * w[:, None]

    def bump(stack, delta):
        old = jax.lax.dynamic_index_in_dim(stack, pulsar, 0,
                                           keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            stack, old + delta, pulsar, 0)

    return KronGram(
        g_uu=bump(pre.g_uu, uw.T @ u_rows),
        g_uf=bump(pre.g_uf, uw.T @ f_rows),
        g_ff=bump(pre.g_ff, fw.T @ f_rows),
        b_u=bump(pre.b_u, uw.T @ r_rows),
        b_f=bump(pre.b_f, fw.T @ r_rows),
        rr=bump(pre.rr, jnp.sum(r_rows * w * r_rows)),
        ld_white=bump(pre.ld_white,
                      jnp.sum(jnp.log(jnp.asarray(sigma_rows) ** 2))),
    )
