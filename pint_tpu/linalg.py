"""Device linear-algebra helpers for correlated-noise likelihoods.

Counterpart of the reference's Woodbury/Sherman-Morrison helpers
(reference: src/pint/utils.py:3024 sherman_morrison_dot, :3074
woodbury_dot).  The covariance is C = N + U diag(phi) U^T with N
diagonal; all quantities are computed through the rank-K capacity
matrix Sigma = Phi^-1 + U^T N^-1 U so nothing O(N^2) is ever formed.
Pure jax, differentiable, vmappable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["woodbury_chi2_logdet", "gls_normal_solve"]

#: floor on basis weights: a zero weight (e.g. ECORR 0) means infinite
#: prior precision on that column — the coefficient is pinned to zero and
#: the logdet contributions cancel, instead of 1/phi producing NaNs.
#: 1e-30 (not smaller): TPU's float32-pair f64 emulation loses precision
#: below the f32 subnormal range (~1e-38), and 1/phi must stay finite
_PHI_FLOOR = 1e-30


def woodbury_chi2_logdet(r, sigma, U, phi):
    """(chi2, logdet C) for C = diag(sigma^2) + U diag(phi) U^T.

    chi2 = r^T C^-1 r via the Woodbury identity; logdet via the matrix
    determinant lemma with the Cholesky of Sigma (reference:
    utils.woodbury_dot, utils.py:3074).
    """
    phi = jnp.maximum(phi, _PHI_FLOOR)
    nvec = sigma**2
    ninv_r = r / nvec
    ut_ninv_r = U.T @ ninv_r
    sigma_cap = (U.T * (1.0 / nvec)[None, :]) @ U + jnp.diag(1.0 / phi)
    cf = jax.scipy.linalg.cho_factor(sigma_cap, lower=True)
    x = jax.scipy.linalg.cho_solve(cf, ut_ninv_r)
    chi2 = jnp.sum(r * ninv_r) - jnp.sum(ut_ninv_r * x)
    logdet = (
        jnp.sum(jnp.log(nvec))
        + jnp.sum(jnp.log(phi))
        + 2.0 * jnp.sum(jnp.log(jnp.diag(cf[0])))
    )
    return chi2, logdet


def gls_normal_solve(r, J, sigma, U, phi):
    """Solve the noise-augmented GLS normal equations (reference:
    GLSFitter.fit_toas, fitter.py:2164-2204).

    Minimizes (r - J d - U a)^T N^-1 (r - J d - U a) + a^T Phi^-1 a over
    (d, a).  Returns (dpar, cov, noise_coeffs, chi2) where dpar is the
    parameter *step* to ADD to the current vector for resid functions
    with J = d resid/d param (so the step applied is -d), cov is the
    parameter covariance block, noise_coeffs are the basis amplitudes a,
    and chi2 is the Woodbury chi^2 of r against C = N + U Phi U^T.
    """
    phi = jnp.maximum(phi, _PHI_FLOOR)
    n_par = J.shape[1]
    M = jnp.concatenate([J, U], axis=1) if U.shape[1] else J
    nvec = sigma**2
    mtn = (M * (1.0 / nvec)[:, None]).T
    phi_inv_full = jnp.concatenate(
        [jnp.zeros(n_par), 1.0 / phi]
    ) if U.shape[1] else jnp.zeros(n_par)
    mtcm = mtn @ M + jnp.diag(phi_inv_full)
    rhs = mtn @ r
    # column normalization for conditioning (reference
    # normalize_designmatrix, utils.py:2879)
    norm = jnp.sqrt(jnp.diag(mtcm))
    norm = jnp.where(norm == 0, 1.0, norm)
    mtcm_n = mtcm / jnp.outer(norm, norm)
    # symmetric eigendecomposition with a pseudo-inverse cutoff instead
    # of Cholesky: the reference falls back to SVD when cho_factor fails
    # (fitter.py:2204); on TPU the f32-pair f64 emulation (~49-bit)
    # makes near-degenerate normal matrices fail Cholesky outright, so
    # the fallback is the main path here.  mtcm_n has unit diagonal, so
    # eigenvalues are O(1)..O(P) and the cutoff is a clean relative one.
    w, Q = jnp.linalg.eigh(mtcm_n)
    w_inv = jnp.where(w > 1e-16 * jnp.max(w), 1.0 / w, 0.0)
    xhat = (Q @ (w_inv * (Q.T @ (rhs / norm)))) / norm
    cov_full = (Q * w_inv[None, :]) @ Q.T / jnp.outer(norm, norm)
    if U.shape[1]:
        chi2, _ = woodbury_chi2_logdet(r, sigma, U, phi)
    else:
        chi2 = jnp.sum((r / sigma) ** 2)
    return (
        -xhat[:n_par],
        cov_full[:n_par, :n_par],
        xhat[n_par:],
        chi2,
    )
