"""Device linear-algebra helpers for correlated-noise likelihoods.

Counterpart of the reference's Woodbury/Sherman-Morrison helpers
(reference: src/pint/utils.py:3024 sherman_morrison_dot, :3074
woodbury_dot).  The covariance is C = N + U diag(phi) U^T with N
diagonal; all quantities are computed through the rank-K capacity
matrix Sigma = Phi^-1 + U^T N^-1 U so nothing O(N^2) is ever formed.
Pure jax, differentiable, vmappable.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["woodbury_chi2_logdet", "gls_normal_solve",
           "WoodburyPre", "woodbury_precompute",
           "woodbury_chi2_logdet_pre"]

#: floor on basis weights: a zero weight (e.g. ECORR 0) means infinite
#: prior precision on that column — the coefficient is pinned to zero and
#: the logdet contributions cancel, instead of 1/phi producing NaNs.
#: 1e-30 (not smaller): TPU's float32-pair f64 emulation loses precision
#: below the f32 subnormal range (~1e-38), and 1/phi must stay finite
_PHI_FLOOR = 1e-30


def woodbury_chi2_logdet(r, sigma, U, phi, valid=None):
    """(chi2, logdet C) for C = diag(sigma^2) + U diag(phi) U^T.

    chi2 = r^T C^-1 r via the Woodbury identity; logdet via the matrix
    determinant lemma with the Cholesky of Sigma (reference:
    utils.woodbury_dot, utils.py:3074).

    valid: optional boolean mask excluding bucketing pad rows from the
    white logdet term (their ~1e-32 weights already vanish from every
    other reduction, but their log sigma^2 would shift — and, with
    EFAC free, bias — the log-likelihood).
    """
    phi = jnp.maximum(phi, _PHI_FLOOR)
    nvec = sigma**2
    ninv_r = r / nvec
    ut_ninv_r = U.T @ ninv_r
    sigma_cap = (U.T * (1.0 / nvec)[None, :]) @ U + jnp.diag(1.0 / phi)
    cf = jax.scipy.linalg.cho_factor(sigma_cap, lower=True)
    x = jax.scipy.linalg.cho_solve(cf, ut_ninv_r)
    chi2 = jnp.sum(r * ninv_r) - jnp.sum(ut_ninv_r * x)
    log_nvec = jnp.log(nvec)
    if valid is not None:
        log_nvec = jnp.where(valid, log_nvec, 0.0)
    logdet = (
        jnp.sum(log_nvec)
        + jnp.sum(jnp.log(phi))
        + 2.0 * jnp.sum(jnp.log(jnp.diag(cf[0])))
    )
    return chi2, logdet


class WoodburyPre(NamedTuple):
    """Values-independent pieces of the Woodbury solve, prebuilt
    host-side (eagerly, OUTSIDE any trace) when sigma/U/phi are known
    constants — the chi^2-grid case where all noise parameters sit
    frozen in the closed-over base values.  Without this, every grid
    compile hands XLA an all-constant ``(U^T N^-1 U + Phi^-1)`` build
    plus its Cholesky to constant-fold from (n_toa, n_basis) inputs —
    the multi-GFLOP fold behind the BENCH_r05 constant-folding alarm
    (the same alarm class the eager ``_U_ext`` fix in residuals.py
    silenced)."""

    nvec: jnp.ndarray      # (N,) sigma^2
    U: jnp.ndarray         # (N, K)
    chol_lower: jnp.ndarray  # (K, K) lower Cholesky of the capacity mat
    logdet: jnp.ndarray    # scalar logdet C


def woodbury_precompute(sigma, U, phi):
    """Eagerly build the capacity-matrix Cholesky and logdet for
    constant (sigma, U, phi).  Call OUTSIDE jit with concrete arrays;
    the result is a small pytree whose in-trace footprint is (N, K) +
    (K, K) constants instead of a foldable (N, K) x (N, K) matmul."""
    phi = jnp.maximum(jnp.asarray(phi), _PHI_FLOOR)
    sigma = jnp.asarray(sigma)
    U = jnp.asarray(U)
    nvec = sigma**2
    sigma_cap = (U.T * (1.0 / nvec)[None, :]) @ U + jnp.diag(1.0 / phi)
    chol = jax.scipy.linalg.cho_factor(sigma_cap, lower=True)[0]
    logdet = (
        jnp.sum(jnp.log(nvec))
        + jnp.sum(jnp.log(phi))
        + 2.0 * jnp.sum(jnp.log(jnp.diag(chol)))
    )
    return WoodburyPre(nvec, U, chol, logdet)


def woodbury_chi2_logdet_pre(r, pre: WoodburyPre):
    """(chi2, logdet) against a :func:`woodbury_precompute` result —
    only the r-dependent work stays in the trace."""
    ninv_r = r / pre.nvec
    ut_ninv_r = pre.U.T @ ninv_r
    x = jax.scipy.linalg.cho_solve((pre.chol_lower, True), ut_ninv_r)
    chi2 = jnp.sum(r * ninv_r) - jnp.sum(ut_ninv_r * x)
    return chi2, pre.logdet


def gls_normal_solve(r, J, sigma, U, phi, pre=None):
    """Solve the noise-augmented GLS normal equations (reference:
    GLSFitter.fit_toas, fitter.py:2164-2204).

    Minimizes (r - J d - U a)^T N^-1 (r - J d - U a) + a^T Phi^-1 a over
    (d, a).  Returns (dpar, cov, noise_coeffs, chi2) where dpar is the
    parameter *step* to ADD to the current vector for resid functions
    with J = d resid/d param (so the step applied is -d), cov is the
    parameter covariance block, noise_coeffs are the basis amplitudes a,
    and chi2 is the Woodbury chi^2 of r against C = N + U Phi U^T.

    pre: optional :class:`WoodburyPre` for the chi^2 evaluation when
    (sigma, U, phi) are trace-time constants (the chi^2-grid path) —
    keeps XLA from constant-folding the capacity matrix per compile.
    """
    phi = jnp.maximum(phi, _PHI_FLOOR)
    n_par = J.shape[1]
    M = jnp.concatenate([J, U], axis=1) if U.shape[1] else J
    nvec = sigma**2
    mtn = (M * (1.0 / nvec)[:, None]).T
    phi_inv_full = jnp.concatenate(
        [jnp.zeros(n_par), 1.0 / phi]
    ) if U.shape[1] else jnp.zeros(n_par)
    mtcm = mtn @ M + jnp.diag(phi_inv_full)
    rhs = mtn @ r
    # column normalization for conditioning (reference
    # normalize_designmatrix, utils.py:2879)
    norm = jnp.sqrt(jnp.diag(mtcm))
    norm = jnp.where(norm == 0, 1.0, norm)
    mtcm_n = mtcm / jnp.outer(norm, norm)
    # symmetric eigendecomposition with a pseudo-inverse cutoff instead
    # of Cholesky: the reference falls back to SVD when cho_factor fails
    # (fitter.py:2204); on TPU the f32-pair f64 emulation (~49-bit)
    # makes near-degenerate normal matrices fail Cholesky outright, so
    # the fallback is the main path here.  mtcm_n has unit diagonal, so
    # eigenvalues are O(1)..O(P) and the cutoff is a clean relative one.
    w, Q = jnp.linalg.eigh(mtcm_n)
    w_inv = jnp.where(w > 1e-16 * jnp.max(w), 1.0 / w, 0.0)
    xhat = (Q @ (w_inv * (Q.T @ (rhs / norm)))) / norm
    cov_full = (Q * w_inv[None, :]) @ Q.T / jnp.outer(norm, norm)
    if U.shape[1]:
        if pre is not None:
            chi2, _ = woodbury_chi2_logdet_pre(r, pre)
        else:
            chi2, _ = woodbury_chi2_logdet(r, sigma, U, phi)
    else:
        chi2 = jnp.sum((r / sigma) ** 2)
    return (
        -xhat[:n_par],
        cov_full[:n_par, :n_par],
        xhat[n_par:],
        chi2,
    )
