"""Model frame-conversion helpers (reference: src/pint/modelutils.py
``model_ecliptic_to_equatorial:60`` / ``model_equatorial_to_ecliptic:8``)
— thin, logged wrappers over ``TimingModel.as_ICRS``/``as_ECL``
(pint_tpu/models/timing_model.py), kept for reference API parity."""

from __future__ import annotations

from pint_tpu.logging import log


def model_ecliptic_to_equatorial(model, force=False):
    """Return an ICRS (equatorial) version of an ecliptic-frame model;
    pass through (with a log message) when already equatorial."""
    if model.has_component("AstrometryEquatorial") and not force:
        log.info("model is already equatorial; returning unchanged")
        return model
    return model.as_ICRS()


def model_equatorial_to_ecliptic(model, ecl="IERS2010", force=False):
    """Return an ecliptic-frame version of an equatorial model; pass
    through (with a log message) when already ecliptic."""
    if model.has_component("AstrometryEcliptic") and not force:
        log.info("model is already ecliptic; returning unchanged")
        return model
    return model.as_ECL(ecl=ecl)
