"""Logging subsystem: leveled, deduplicating, env-controllable.

Behavioral counterpart of the reference's loguru-based setup
(reference: src/pint/logging.py — dedup/once filters, verbosity
control, $LOGURU_LEVEL env), on the stdlib ``logging`` module so it
composes with host applications:

- ``log`` — the package logger (``pint_tpu``); modules do
  ``from pint_tpu.logging import log`` and use ``log.info`` etc.
- ``setup(level=..., dedup=True)`` — install a console handler; the
  level falls back to ``$PINT_TPU_LOG`` (default WARNING).
- ``DedupFilter`` — suppresses repeats of the same message beyond
  ``max_repeats`` (the reference's dedup filter); ``log_once`` is the
  hard once-only helper.
- ``capture_warnings(True)`` — routes ``warnings.warn`` through the
  logger so library warnings obey the same verbosity/dedup policy
  (the reference forwards warnings into loguru the same way).
"""

from __future__ import annotations

import logging as _logging
import os
import warnings as _warnings

__all__ = ["log", "setup", "log_once", "DedupFilter", "capture_warnings"]

log = _logging.getLogger("pint_tpu")


class DedupFilter(_logging.Filter):
    """Allow each distinct (level, message) only ``max_repeats`` times
    (reference logging.py dedup behavior)."""

    def __init__(self, max_repeats=1):
        super().__init__()
        self.max_repeats = max_repeats
        self._counts: dict = {}

    def filter(self, record):
        key = (record.levelno, record.getMessage())
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        # annotate the last allowed emission — but only when something
        # was actually repeated (max_repeats == 1 means silent dedup)
        if n + 1 == self.max_repeats and self.max_repeats > 1:
            record.msg = f"{record.getMessage()} [further repeats hidden]"
            record.args = ()
        return n < self.max_repeats


_handler = None
_dedup = None


def setup(level=None, dedup=True, max_repeats=1, stream=None):
    """Install (or reconfigure) the console handler.

    level: int or name; default $PINT_TPU_LOG or WARNING.
    Returns the package logger."""
    global _handler, _dedup
    if level is None:
        level = os.environ.get("PINT_TPU_LOG", "WARNING")
    if isinstance(level, str):
        level = getattr(_logging, level.upper())
    if _handler is None:
        _handler = _logging.StreamHandler(stream)
        _handler.setFormatter(_logging.Formatter(
            "%(levelname)s (%(name)s): %(message)s"))
        log.addHandler(_handler)
    elif stream is not None:
        _handler.setStream(stream)
    if _dedup is not None:
        _handler.removeFilter(_dedup)
        _dedup = None
    if dedup:
        _dedup = DedupFilter(max_repeats=max_repeats)
        _handler.addFilter(_dedup)
    log.setLevel(level)
    return log


_once_seen: set = set()


def log_once(level, msg, *args):
    """Emit a message exactly once per process (the reference's
    ``log.log(..., once=True)`` pattern)."""
    key = (level, msg)
    if key in _once_seen:
        return
    _once_seen.add(key)
    log.log(level if isinstance(level, int)
            else getattr(_logging, str(level).upper()), msg, *args)


def capture_warnings(enable=True):
    """Route warnings.warn through the package logger (and back)."""
    _logging.captureWarnings(enable)
    pywarn = _logging.getLogger("py.warnings")
    if enable:
        for h in log.handlers:
            if h not in pywarn.handlers:
                pywarn.addHandler(h)
    else:
        for h in list(pywarn.handlers):
            pywarn.removeHandler(h)


def get_verbosity_args(parser):
    """Attach the reference-style -v/-q CLI verbosity flags."""
    parser.add_argument("-v", "--verbose", action="count", default=0,
                        help="increase logging verbosity (-v, -vv)")
    parser.add_argument("-q", "--quiet", action="count", default=0,
                        help="decrease logging verbosity")
    return parser


def apply_verbosity(args):
    """Map parsed -v/-q counts onto a logging level and install it."""
    base = _logging.WARNING
    level = base - 10 * getattr(args, "verbose", 0) \
        + 10 * getattr(args, "quiet", 0)
    return setup(level=max(_logging.DEBUG, min(_logging.CRITICAL, level)))
