"""Minimal FITS binary-table reader (pure numpy, no astropy).

Counterpart of the reference's use of ``astropy.io.fits`` for photon
event files (reference: src/pint/fits_utils.py:1-127
``read_fits_event_mjds_tuples``, src/pint/event_toas.py).  Implements
exactly the subset the photon path needs: 2880-byte header blocks,
keyword cards, and BINTABLE extensions with scalar numeric columns
(big-endian, as the standard requires).
"""

from __future__ import annotations

import numpy as np

__all__ = ["FitsHDU", "read_fits", "read_events", "write_events"]

_BLOCK = 2880
_CARD = 80

#: TFORM letter -> numpy big-endian dtype
_TFORM = {
    "L": "i1", "B": "u1", "I": ">i2", "J": ">i4", "K": ">i8",
    "E": ">f4", "D": ">f8",
}


class FitsHDU:
    def __init__(self, header, data=None, columns=None):
        self.header = header
        self.data = data  # dict column name -> array (tables)
        self.columns = columns or []

    @property
    def name(self):
        return str(self.header.get("EXTNAME", "")).strip()


def _parse_header(chunk_iter):
    """Consume header blocks; return (header dict, bytes consumed)."""
    header = {}
    nbytes = 0
    done = False
    while not done:
        block = next(chunk_iter)
        nbytes += _BLOCK
        for i in range(0, _BLOCK, _CARD):
            card = block[i:i + _CARD].decode("ascii", errors="replace")
            key = card[:8].strip()
            if key == "END":
                done = True
                break
            if not key or card[8] != "=":
                continue
            val = card[10:]
            # strip inline comment (respect quoted strings)
            if val.lstrip().startswith("'"):
                s = val.lstrip()[1:]
                out = []
                j = 0
                while j < len(s):
                    if s[j] == "'":
                        if j + 1 < len(s) and s[j + 1] == "'":
                            out.append("'")
                            j += 2
                            continue
                        break
                    out.append(s[j])
                    j += 1
                header[key] = "".join(out).rstrip()
                continue
            val = val.split("/")[0].strip()
            if val in ("T", "F"):
                header[key] = val == "T"
            else:
                try:
                    header[key] = int(val)
                except ValueError:
                    try:
                        header[key] = float(val)
                    except ValueError:
                        header[key] = val
    return header, nbytes


def read_fits(path):
    """Read all HDUs; table HDUs get a {column: ndarray} data dict."""
    with open(path, "rb") as f:
        raw = f.read()
    hdus = []
    pos = 0

    def blocks():
        nonlocal pos
        while pos < len(raw):
            b = raw[pos:pos + _BLOCK]
            pos += _BLOCK
            yield b

    it = blocks()
    while pos < len(raw):
        try:
            header, _ = _parse_header(it)
        except StopIteration:
            break
        bitpix = abs(int(header.get("BITPIX", 8)))
        naxes = [
            int(header.get(f"NAXIS{i + 1}", 0))
            for i in range(int(header.get("NAXIS", 0)))
        ]
        datasize = (
            bitpix // 8 * int(np.prod(naxes)) if naxes else 0
        ) * max(1, int(header.get("GCOUNT", 1)))
        datasize += int(header.get("PCOUNT", 0)) * bitpix // 8
        data = None
        columns = []
        if header.get("XTENSION", "").startswith("BINTABLE") and naxes:
            row_bytes, nrows = naxes[0], naxes[1]
            table_raw = raw[pos:pos + row_bytes * nrows]
            ncols = int(header.get("TFIELDS", 0))
            data = {}
            offset = 0
            for c in range(1, ncols + 1):
                tform = str(header.get(f"TFORM{c}", "")).strip()
                ttype = str(header.get(f"TTYPE{c}", f"COL{c}")).strip()
                # repeat count + letter (e.g. '1D', 'D', '2E')
                rep = ""
                j = 0
                while j < len(tform) and tform[j].isdigit():
                    rep += tform[j]
                    j += 1
                letter = tform[j:j + 1]
                repeat = int(rep) if rep else 1
                columns.append(ttype)
                if letter in _TFORM:
                    dt = np.dtype(_TFORM[letter])
                    width = dt.itemsize * repeat
                    arr = np.ndarray(
                        (nrows, repeat), dtype=dt,
                        buffer=table_raw,
                        offset=offset,
                        strides=(row_bytes, dt.itemsize),
                    )
                    arr = arr.astype(dt.newbyteorder("="))
                    data[ttype] = arr[:, 0] if repeat == 1 else arr
                elif letter == "A":
                    width = repeat
                    arr = np.ndarray(
                        (nrows,), dtype=f"S{repeat}",
                        buffer=table_raw, offset=offset,
                        strides=(row_bytes,),
                    )
                    data[ttype] = np.char.decode(arr, "ascii")
                elif letter == "X":
                    # bit array (e.g. Fermi FT1 EVENT_CLASS '32X'):
                    # ceil(repeat/8) bytes per row, kept as raw uint8
                    width = (repeat + 7) // 8
                    arr = np.ndarray(
                        (nrows, width), dtype=np.uint8,
                        buffer=table_raw, offset=offset,
                        strides=(row_bytes, 1),
                    )
                    data[ttype] = arr.copy()
                else:
                    raise ValueError(
                        f"unsupported TFORM {tform!r} for {ttype}"
                    )
                offset += width
        # skip data (padded to block size)
        pos += (datasize + _BLOCK - 1) // _BLOCK * _BLOCK
        hdus.append(FitsHDU(header, data, columns))
    return hdus


def read_events(path, extname="EVENTS", columns=None):
    """(header, {column: array}) of the named table extension."""
    for hdu in read_fits(path):
        if hdu.data is not None and hdu.name.upper() == extname.upper():
            if columns:
                missing = [c for c in columns if c not in hdu.data]
                if missing:
                    raise KeyError(
                        f"columns {missing} not in {extname} "
                        f"(has {list(hdu.data)})"
                    )
            return hdu.header, hdu.data
    raise KeyError(f"no {extname} extension in {path}")


def write_events(path, time_s, mjdref=(56000, 0.0), timesys="TDB",
                 timeref="SOLARSYSTEM", extra_cols=None,
                 extname="EVENTS", extra_header=None, timezero=0.0):
    """Minimal standards-compliant event-FITS writer: empty primary HDU
    + one BINTABLE with a TIME column (f64 MET seconds) and optional
    extra f64 columns (reference analogue: photonphase --outfile, which
    writes PULSE_PHASE/ORBIT_PHASE columns via astropy.io.fits;
    scripts/photonphase.py:90).  extra_header: additional scalar cards
    for the table header."""

    def card(key, val, quote=False):
        if quote:
            v = f"'{val}'"
        elif isinstance(val, bool):
            v = "T" if val else "F"
        else:
            v = str(val)
        return f"{key:<8s}= {v:>20s}{'':50s}"[:80].encode()

    def block(cards):
        data = b"".join(cards) + b"END" + b" " * 77
        return data + b" " * ((-len(data)) % _BLOCK)

    primary = block([
        card("SIMPLE", True), card("BITPIX", 8), card("NAXIS", 0),
    ])
    cols = [("TIME", np.asarray(time_s, dtype=">f8"))]
    for name, arr in (extra_cols or {}).items():
        cols.append((name, np.asarray(arr, dtype=">f8")))
    nrows = len(time_s)
    row_bytes = 8 * len(cols)
    cards = [
        card("XTENSION", "BINTABLE", quote=True),
        card("BITPIX", 8), card("NAXIS", 2),
        card("NAXIS1", row_bytes), card("NAXIS2", nrows),
        card("PCOUNT", 0), card("GCOUNT", 1),
        card("TFIELDS", len(cols)),
        card("EXTNAME", extname, quote=True),
        card("MJDREFI", mjdref[0]), card("MJDREFF", mjdref[1]),
        card("TIMESYS", timesys, quote=True),
        card("TIMEREF", timeref, quote=True),
        card("TIMEZERO", float(timezero)),
    ]
    for key, val in (extra_header or {}).items():
        cards.append(card(key, val, quote=isinstance(val, str)))
    for i, (name, _) in enumerate(cols, start=1):
        cards.append(card(f"TTYPE{i}", name, quote=True))
        cards.append(card(f"TFORM{i}", "D", quote=True))
    table = np.empty((nrows, len(cols)), dtype=">f8")
    for i, (_, arr) in enumerate(cols):
        table[:, i] = arr
    raw = table.tobytes()
    raw += b"\x00" * ((-len(raw)) % _BLOCK)
    with open(path, "wb") as f:
        f.write(primary + block(cards) + raw)
