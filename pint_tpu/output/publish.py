"""LaTeX timing-solution summary tables.

Counterpart of the reference publish module (reference:
src/pint/output/publish.py:1-321 ``publish``): render a fitted model +
TOAs as a self-contained LaTeX table — dataset summary, fitted
parameters with uncertainties, derived quantities.
"""

from __future__ import annotations

import numpy as np

__all__ = ["publish"]


def _fmt_unc(val, unc, max_digits=18):
    """'value(unc)' notation with 2 significant digits of uncertainty,
    e.g. 245.4261196(32)."""
    if unc is None or unc <= 0 or not np.isfinite(unc):
        return f"{val:.10g}"
    import math

    expo = int(math.floor(math.log10(unc)))
    ndec = max(0, min(max_digits, -(expo - 1)))
    u2 = int(round(unc / 10 ** (expo - 1)))
    if u2 >= 100:  # rounding pushed to 3 digits (e.g. 0.0999 -> 100)
        u2 = 10
        ndec = max(0, ndec - 1)
    return f"{val:.{ndec}f}({u2})"


def publish(model, toas=None, fitter=None, include_dmx=False):
    """Return a LaTeX table string (reference publish.py ``publish``)."""
    rows = []
    psr = model.meta.get("PSR", "PSR")
    rows.append(r"\begin{table}")
    rows.append(rf"\caption{{Timing solution for {psr}}}")
    rows.append(r"\begin{tabular}{ll}")
    rows.append(r"\hline\hline")
    rows.append(r"Parameter & Value \\")
    rows.append(r"\hline")
    rows.append(r"\multicolumn{2}{c}{Data summary} \\")
    if toas is not None:
        rows.append(rf"Number of TOAs & {len(toas)} \\")
        mjds = toas.mjd_float
        rows.append(
            rf"MJD range & {mjds.min():.1f}--{mjds.max():.1f} \\"
        )
    for key, label in (("EPHEM", "Solar system ephemeris"),
                       ("CLK", "Clock standard"),
                       ("UNITS", "Time units"),
                       ("TRES", r"Weighted RMS residual ($\mu$s)"),
                       ("CHI2", r"$\chi^2$"),
                       ("NTOA", "TOAs in fit")):
        if key in model.meta:
            rows.append(rf"{label} & {model.meta[key]} \\")
    rows.append(r"\hline")
    rows.append(r"\multicolumn{2}{c}{Fitted parameters} \\")
    params = model.params
    for name in model.free_params:
        if not include_dmx and name.startswith("DMX"):
            continue
        p = params[name]
        val = model.values.get(name, np.nan)
        disp = p.format(val) if p.kind in ("angle", "mjd") else \
            _fmt_unc(val / p.scale if p.scale != 1 else val,
                     (p.uncertainty / p.scale if p.scale != 1
                      else p.uncertainty) if p.uncertainty else None)
        safe = name.replace("_", r"\_")
        rows.append(rf"{safe} & {disp} \\")
    # derived quantities when the spin params exist
    if "F0" in model.values and "F1" in model.values:
        import pint_tpu.derived_quantities as dq

        f0 = float(model.values["F0"])
        f1 = float(model.values["F1"])
        rows.append(r"\hline")
        rows.append(r"\multicolumn{2}{c}{Derived quantities} \\")
        rows.append(rf"Spin period $P$ (s) & {1.0 / f0:.12g} \\")
        if f1 < 0:
            rows.append(
                rf"Characteristic age $\tau_c$ (yr) & "
                rf"{dq.pulsar_age_yr(f0, f1):.3g} \\"
            )
            rows.append(
                rf"Surface field $B_s$ (G) & "
                rf"{dq.pulsar_B_gauss(f0, f1):.3g} \\"
            )
            rows.append(
                rf"$\dot E$ (erg/s) & {dq.pulsar_edot(f0, f1):.3g} \\"
            )
    rows.append(r"\hline")
    rows.append(r"\end{tabular}")
    rows.append(r"\end{table}")
    return "\n".join(rows) + "\n"
