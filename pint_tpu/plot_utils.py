"""Plotting helpers (reference: src/pint/plot_utils.py — phaseogram:11,
phaseogram_binned:98, plot_priors:225).

Matplotlib figures built from plain arrays; all functions accept
``axes=None``/``plotfile=None`` so they are usable headlessly (Agg) and
from the photon scripts (photonphase/fermiphase ``--plot``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["phaseogram", "phaseogram_binned", "plot_priors"]


def _doubled(phases):
    """Phases twice over [0, 2) — the standard two-cycle display."""
    p = np.asarray(phases, np.float64) % 1.0
    return np.concatenate([p, p + 1.0])


def phaseogram(mjds, phases, weights=None, title=None, bins=100,
               rotate=0.0, size=5, alpha=0.25, width=6, maxphs=2.0,
               plotfile=None, axes=None):
    """Scatter phaseogram: photon phase (x, two cycles) vs time (y),
    with the summed profile histogram on top (reference phaseogram)."""
    import matplotlib.pyplot as plt

    mjds = np.asarray(mjds, np.float64)
    ph = (_doubled(np.asarray(phases) + rotate))
    yy = np.concatenate([mjds, mjds])
    ww = None if weights is None else np.concatenate(
        [np.asarray(weights)] * 2)

    if axes is None:
        fig, (ax1, ax2) = plt.subplots(
            2, 1, sharex=True, figsize=(width, 8),
            gridspec_kw={"height_ratios": [1, 3]})
    else:
        ax1, ax2 = axes
        fig = ax1.figure
    ax1.hist(ph, bins=2 * bins, range=(0, maxphs), weights=ww,
             histtype="step", color="k")
    ax1.set_ylabel("counts" if weights is None else "weighted counts")
    if title:
        ax1.set_title(title)
    ax2.scatter(ph, yy, s=size, c="k" if ww is None else ww,
                alpha=alpha, marker=".")
    ax2.set_xlim(0, maxphs)
    ax2.set_xlabel("pulse phase")
    ax2.set_ylabel("MJD")
    if plotfile is not None:
        fig.savefig(plotfile)
        plt.close(fig)
    return fig


def phaseogram_binned(mjds, phases, weights=None, title=None, bins=64,
                      rotate=0.0, ntime=32, plotfile=None, axes=None):
    """2-D binned phaseogram (time rows x phase columns) plus summed
    profile (reference phaseogram_binned)."""
    import matplotlib.pyplot as plt

    mjds = np.asarray(mjds, np.float64)
    ph = (np.asarray(phases, np.float64) + rotate) % 1.0
    w = None if weights is None else np.asarray(weights, np.float64)
    ph2, t2 = _doubled(ph), np.concatenate([mjds, mjds])
    w2 = None if w is None else np.concatenate([w, w])
    H, xe, ye = np.histogram2d(
        t2, ph2, bins=[ntime, 2 * bins],
        range=[[mjds.min(), mjds.max()], [0, 2]], weights=w2)

    if axes is None:
        fig, (ax1, ax2) = plt.subplots(
            2, 1, sharex=True, figsize=(6, 8),
            gridspec_kw={"height_ratios": [1, 3]})
    else:
        ax1, ax2 = axes
        fig = ax1.figure
    prof = H.sum(axis=0)
    centers = 0.5 * (ye[:-1] + ye[1:])
    ax1.step(centers, prof, where="mid", color="k")
    ax1.set_ylabel("counts" if weights is None else "weighted counts")
    if title:
        ax1.set_title(title)
    ax2.imshow(H, origin="lower", aspect="auto",
               extent=[0, 2, mjds.min(), mjds.max()], cmap="Greys")
    ax2.set_xlabel("pulse phase")
    ax2.set_ylabel("MJD")
    if plotfile is not None:
        fig.savefig(plotfile)
        plt.close(fig)
    return fig


def plot_priors(model, chains, burnin=0, bins=100, scale=False,
                plotfile=None):
    """Posterior histograms per fitted parameter with the prior pdf
    overplotted where a parameter carries one (reference plot_priors;
    priors live on Param.prior, pint_tpu/models/parameter.py)."""
    import matplotlib.pyplot as plt

    names = list(chains.keys())
    fig, axs = plt.subplots(len(names), figsize=(8, 2.5 * len(names)),
                            squeeze=False)
    for ax, name in zip(axs[:, 0], names):
        samples = np.asarray(chains[name])[burnin:]
        counts, edges, _ = ax.hist(samples, bins=bins, density=True,
                                   histtype="step", color="k",
                                   label="posterior")
        par = model.params.get(name)
        prior = getattr(par, "prior", None) if par is not None else None
        if prior is not None and hasattr(prior, "lnpdf"):
            x = np.linspace(edges[0], edges[-1], 400)
            pdf = np.exp([float(prior.lnpdf(v)) for v in x])
            if scale:
                pdf *= counts.max() / max(pdf.max(), 1e-300)
            ax.plot(x, pdf, color="C0", label="prior")
        ax.set_ylabel(name)
        ax.legend(loc="best", fontsize=8)
    if plotfile is not None:
        fig.savefig(plotfile)
        plt.close(fig)
    return fig
