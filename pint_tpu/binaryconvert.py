"""Binary-model parameterization conversion with uncertainty propagation.

Counterpart of the reference binaryconvert module (reference:
src/pint/binaryconvert.py:544 ``convert_binary`` and the _from_ELL1 /
_to_ELL1 / _SINI_to_SHAPMAX / _M2SINI_to_orthometric family; Lange et
al. 2001 Eqns 1-3 for ELL1, Freire & Wex 2010 for the orthometric
Shapiro parameters).

TPU redesign: instead of the reference's ufloat (first-order pairwise
error propagation), uncertainties propagate through the exact Jacobian
of the whole conversion map, computed with ``jax.jacfwd`` — correlated
input covariance would drop in for free.
"""

from __future__ import annotations

import warnings
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import SECS_PER_DAY, T_SUN_S
from pint_tpu.models.timing_model import TimingModel

__all__ = ["convert_binary"]

#: which parameterization family each binary model belongs to
_ELL1_FAMILY = {"ELL1", "ELL1H", "ELL1K"}
_DD_FAMILY = {"DD", "DDH", "DDS", "DDGR", "DDK", "BT"}


def _ell1_to_dd(v):
    """(EPS1, EPS2, TASC, PB, EPS1DOT, EPS2DOT) ->
    (ECC, OM, T0, EDOT, OMDOT); Lange+ 2001 Eq 1-3."""
    eps1, eps2, tasc, pb, eps1dot, eps2dot = v
    ecc = jnp.sqrt(eps1**2 + eps2**2)
    om = jnp.arctan2(eps1, eps2)
    om = jnp.where(om < 0, om + 2 * jnp.pi, om)
    t0 = tasc + pb * om / (2 * jnp.pi)
    ecc_safe = jnp.where(ecc == 0, 1.0, ecc)
    edot = (eps1dot * eps1 + eps2dot * eps2) / ecc_safe
    omdot = (eps2 * eps1dot - eps1 * eps2dot) / ecc_safe**2
    return jnp.stack([ecc, om, t0, edot, omdot])


def _dd_to_ell1(v):
    """(ECC, OM, T0, PB, EDOT, OMDOT) ->
    (EPS1, EPS2, TASC, EPS1DOT, EPS2DOT)."""
    ecc, om, t0, pb, edot, omdot = v
    eps1 = ecc * jnp.sin(om)
    eps2 = ecc * jnp.cos(om)
    tasc = t0 - pb * om / (2 * jnp.pi)
    eps1dot = edot * jnp.sin(om) + ecc * jnp.cos(om) * omdot
    eps2dot = edot * jnp.cos(om) - ecc * jnp.sin(om) * omdot
    return jnp.stack([eps1, eps2, tasc, eps1dot, eps2dot])


def _m2sini_to_orthometric(v):
    """(M2 [Msun], SINI) -> (H3 [s], H4 [s], STIGMA); Freire & Wex
    2010 Eq 12-13."""
    m2, sini = v
    cosi = jnp.sqrt(1.0 - sini**2)
    stigma = sini / (1.0 + cosi)
    h3 = T_SUN_S * m2 * stigma**3
    h4 = h3 * stigma
    return jnp.stack([h3, h4, stigma])


def _orthometric_to_m2sini(v):
    """(H3 [s], STIGMA) -> (M2 [Msun], SINI)."""
    h3, stigma = v
    m2 = h3 / (T_SUN_S * stigma**3)
    sini = 2.0 * stigma / (1.0 + stigma**2)
    return jnp.stack([m2, sini])


def _sini_to_shapmax(v):
    return jnp.stack([-jnp.log(1.0 - v[0])])


def _shapmax_to_sini(v):
    return jnp.stack([1.0 - jnp.exp(-v[0])])


def _propagate(fn, values, uncs):
    """Apply fn and propagate *uncorrelated* input uncertainties through
    its Jacobian: sigma_out = sqrt(J diag(sigma_in^2) J^T) diagonal."""
    x = jnp.asarray(values, dtype=jnp.float64)
    out = np.asarray(fn(x))
    J = np.asarray(jax.jacfwd(fn)(x))
    var_in = np.array([0.0 if u is None else float(u) ** 2 for u in uncs])
    var_out = (J**2) @ var_in
    sig_out = np.sqrt(var_out)
    has = [
        bool((np.abs(J[i]) > 0) @ np.array([u is not None for u in uncs]))
        for i in range(len(out))
    ]
    return out, [s if h else None for s, h in zip(sig_out, has)]


def _get(model, name, default=0.0):
    val = model.values.get(name, np.nan)
    if isinstance(val, float) and np.isnan(val):
        val = default
    return float(val), model.params[name].uncertainty \
        if name in model.params else None


def convert_binary(model: TimingModel, output: str, nharms=None,
                   use_stigma=False, kom_deg=None,
                   lossy=False) -> TimingModel:
    """Return a new TimingModel with the binary component converted to
    the ``output`` parameterization (reference: convert_binary,
    binaryconvert.py:544).  Conversion is done at the par level: the
    non-binary part round-trips untouched.

    ELL1H extras (reference NHARMS/useSTIGMA args): ``nharms`` emits an
    NHARMS line; ``use_stigma=True`` emits STIGMA instead of H4.
    DDK extra: ``kom_deg`` supplies the longitude of the ascending node
    (not derivable from any other parameterization); KIN is derived
    from SINI.

    A conversion that would *drop physics* — a parameter the input
    binary engine models but the output one cannot represent (e.g.
    DD->ELL1 sheds GAMMA/DR/DTH/A0/B0) — raises ``ValueError`` unless
    ``lossy=True``, matching the reference's refuse-to-shed semantics
    (binaryconvert.py:544 raises on non-representable conversions)
    rather than silently demoting parameters to metadata."""
    output = output.upper()
    current = model.meta.get("BINARY", "").upper()
    if not current:
        raise ValueError("model has no BINARY component")
    if current == output:
        return model

    par_lines = []
    # binary params to strip from the original par
    strip = {
        "BINARY", "ECC", "OM", "T0", "TASC", "EPS1", "EPS2", "EPS1DOT",
        "EPS2DOT", "EDOT", "OMDOT", "M2", "SINI", "SHAPMAX", "H3", "H4",
        "STIGMA", "NHARMS", "LNEDOT", "MTOT", "KIN", "KOM", "K96",
    }
    for line in model.as_parfile().splitlines():
        key = line.split()[0].upper() if line.split() else ""
        if key not in strip:
            par_lines.append(line)
    par_lines.append(f"BINARY {output}")

    def emit(name, val, unc, fit, fmt="%.15g"):
        s = f"{name} {fmt % val}"
        if fit or unc is not None:
            s += f" {'1' if fit else '0'}"
        if unc is not None:
            s += f" {fmt % unc}"
        par_lines.append(s)

    params = model.params
    fitset = set(model.free_params)

    # --- eccentricity / epoch block -------------------------------------
    ell1_in = current in _ELL1_FAMILY
    ell1_out = output in _ELL1_FAMILY
    pb, pb_unc = _get(model, "PB")
    if pb == 0.0 and "FB0" in model.values:
        fb0, _ = _get(model, "FB0")
        pb = 1.0 / fb0
    if ell1_in and not ell1_out:
        (e1, u1), (e2, u2) = _get(model, "EPS1"), _get(model, "EPS2")
        tasc, utasc = _get(model, "TASC")
        e1d, u1d = _get(model, "EPS1DOT")
        e2d, u2d = _get(model, "EPS2DOT")
        out, uncs = _propagate(
            _ell1_to_dd,
            [e1, e2, tasc, pb, e1d, e2d],
            [u1, u2, utasc, pb_unc, u1d, u2d],
        )
        ecc, om, t0, edot, omdot = out
        fit = any(n in fitset for n in ("EPS1", "EPS2", "TASC"))
        emit("ECC", ecc, uncs[0], "EPS1" in fitset)
        emit("OM", np.rad2deg(om),
             np.rad2deg(uncs[1]) if uncs[1] is not None else None,
             "EPS2" in fitset)
        par_lines.append(
            f"T0 {t0 / SECS_PER_DAY + 51544.5:.15f}"
            + (" 1" if "TASC" in fitset else "")
        )
        if edot != 0.0:
            emit("EDOT", edot, uncs[3], "EPS1DOT" in fitset)
        if omdot != 0.0:
            emit("OMDOT", np.rad2deg(omdot) * 365.25 * SECS_PER_DAY,
                 None, "EPS2DOT" in fitset)
    elif ell1_out and not ell1_in:
        ecc, ue = _get(model, "ECC")
        om, uo = _get(model, "OM")  # radians internally
        t0, ut0 = _get(model, "T0")
        edot, ued = _get(model, "EDOT")
        omdot, uod = _get(model, "OMDOT")
        out, uncs = _propagate(
            _dd_to_ell1,
            [ecc, om, t0, pb, edot, omdot],
            [ue, uo, ut0, pb_unc, ued, uod],
        )
        eps1, eps2, tasc, e1d, e2d = out
        emit("EPS1", eps1, uncs[0], "ECC" in fitset)
        emit("EPS2", eps2, uncs[1], "OM" in fitset)
        par_lines.append(
            f"TASC {tasc / SECS_PER_DAY + 51544.5:.15f}"
            + (" 1" if "T0" in fitset else "")
        )
        if e1d != 0.0 or e2d != 0.0:
            emit("EPS1DOT", e1d, uncs[3], "EDOT" in fitset)
            emit("EPS2DOT", e2d, uncs[4], "EDOT" in fitset)
    else:
        # same family: copy the eccentricity block through
        for name in ("ECC", "OM", "T0", "TASC", "EPS1", "EPS2",
                     "EPS1DOT", "EPS2DOT", "EDOT", "OMDOT", "LNEDOT"):
            if name in params and not (
                isinstance(model.values.get(name, np.nan), float)
                and np.isnan(model.values.get(name, np.nan))
            ):
                p = params[name]
                unc = p.uncertainty
                if p.kind == "mjd":
                    par_lines.append(
                        f"{name} "
                        f"{model.values[name] / SECS_PER_DAY + 51544.5:.15f}"
                        + (" 1" if name in fitset else "")
                    )
                else:
                    emit(name, model.values[name] / p.scale,
                         unc / p.scale if unc is not None else None,
                         name in fitset)

    # --- Shapiro block ---------------------------------------------------
    ortho_in = current in ("ELL1H", "DDH")
    ortho_out = output in ("ELL1H", "DDH")
    m2, um2 = _get(model, "M2")
    sini, usini = _get(model, "SINI")
    sini_fit = "SINI" in fitset
    if current == "DDK":
        # DDK carries the inclination as KIN (radians internally)
        kin, ukin = _get(model, "KIN")
        if kin != 0:
            sini = float(np.sin(kin))
            usini = (abs(np.cos(kin)) * ukin) if ukin else None
            sini_fit = "KIN" in fitset
    elif current == "DDS":
        shapmax, ush = _get(model, "SHAPMAX")
        if shapmax != 0:
            out, uncs = _propagate(_shapmax_to_sini, [shapmax], [ush])
            sini, usini = out[0], uncs[0]
            sini_fit = "SHAPMAX" in fitset
    elif ortho_in:
        h3, uh3 = _get(model, "H3")
        stigma, ust = _get(model, "STIGMA")
        if stigma == 0.0:
            h4, uh4 = _get(model, "H4")
            if h3 != 0 and h4 != 0:
                stigma, ust = h4 / h3, None
        if h3 != 0 and stigma != 0:
            out, uncs = _propagate(
                _orthometric_to_m2sini, [h3, stigma], [uh3, ust]
            )
            m2, um2 = out[0], uncs[0]
            sini, usini = out[1], uncs[1]
            sini_fit = "STIGMA" in fitset or "H4" in fitset

    # (m2, sini) now hold the effective Shapiro pair whatever the input
    # parameterization; emit the output's own representation
    if output == "DDS":
        if sini > 0:
            out, uncs = _propagate(_sini_to_shapmax, [sini], [usini])
            emit("SHAPMAX", out[0], uncs[0], sini_fit)
        if m2 != 0:
            emit("M2", m2, um2, "M2" in fitset)
    elif ortho_out:
        if m2 != 0 and sini != 0:
            out, uncs = _propagate(
                _m2sini_to_orthometric, [m2, sini], [um2, usini]
            )
            h3, h4, stigma = out
            emit("H3", h3, uncs[0], "M2" in fitset)
            if output == "ELL1H" and not use_stigma:
                emit("H4", h4, uncs[1], sini_fit)
            else:
                emit("STIGMA", stigma, uncs[2], sini_fit)
        if output == "ELL1H" and nharms is not None:
            par_lines.append(f"NHARMS {int(nharms)}")
    elif output == "DDK":
        if m2 != 0:
            emit("M2", m2, um2, "M2" in fitset)
        # KIN from the effective SINI (DT92 convention); KOM is not
        # derivable from any other parameterization
        if sini != 0:
            kin = np.degrees(np.arcsin(min(sini, 1.0)))
            emit("KIN", kin, None, sini_fit)
        if kom_deg is None:
            warnings.warn(
                "convert_binary: DDK needs KOM (ascending-node "
                "longitude), which no other parameterization carries; "
                "writing KOM 0 — supply kom_deg/--kom for real use")
        emit("KOM", float(kom_deg) if kom_deg is not None else 0.0,
             None, False)
    else:
        if m2 != 0:
            emit("M2", m2, um2, "M2" in fitset)
        if sini != 0 and output != "DDGR":
            emit("SINI", sini, usini, sini_fit)

    if output == "DDGR" and "MTOT" in model.values:
        v, u = _get(model, "MTOT")
        emit("MTOT", v, u, "MTOT" in fitset)

    from pint_tpu.models.builder import get_model

    new = get_model("\n".join(par_lines) + "\n")

    # physics the input engine modeled but the output engine cannot
    # represent lands in __unknown__ metadata on the re-parse; that is
    # a silent loss of signal, not a parameterization change
    def _had_physics(name):
        # a zero-valued, frozen parameter is absent physics (engines
        # register e.g. GAMMA/DR/DTH at 0.0 by default) — dropping it
        # loses nothing; a nonzero value or an actively-fit one does
        v = model.values.get(name, np.nan)
        if isinstance(v, float) and (np.isnan(v) or v == 0.0):
            return name in fitset
        return True

    dropped = sorted(
        k for k in new.meta.get("__unknown__", {})
        if k in model.params and _had_physics(k)
    )
    if dropped:
        msg = (
            f"converting {current} -> {output} drops parameters the "
            f"{output} engine cannot represent: {dropped}"
        )
        if not lossy:
            raise ValueError(
                msg + " — pass lossy=True (convert_parfile: --lossy) "
                "to shed them deliberately")
        warnings.warn(msg + " (lossy=True: carried as metadata only)")
    return new
