"""Device-truth profiling: per-program accounting, phase split, memory
watermarks (``$PINT_TPU_PROFILE``).

The telemetry layer (spans/counters) records *that* a fit happened and
how long the host waited; this module records *where* that time went.
Every jitted program that resolves through the shared-jit registry
(:func:`pint_tpu.compile_cache.shared_jit`) is wrapped in a thin proxy
that — only while the profile gate is on — attributes each call to four
phases and accumulates a per-program record:

- **trace_s** — jax tracing/lowering/compile work during the call,
  measured as the delta of the telemetry compile counters
  (``jit.compile_seconds``) across it.  Zero on the warm path.
- **dispatch_s** — the remainder of the wall time the call itself
  took: argument processing + enqueueing the executable.  Under async
  dispatch this is microseconds.
- **device_s** — the wait inside ``jax.block_until_ready`` on the
  call's outputs: device execution (plus any not-yet-retired work
  queued before the call — see docs/telemetry.md for what this timing
  does and does NOT mean).  Log-bucketed into a per-program
  :class:`~pint_tpu.telemetry.LogHistogram` for p50/p95/p99 readout.
- **bytes** — cumulative argument / result pytree bytes.

With the gate OFF (the default) a profiled call is one env read, one
branch, and the raw jitted call — the async dispatch path pays
nothing, which is what keeps the gate safe to leave in production hot
paths.  The gate never changes the traced program, so flipping it can
never force a recompile (regression-tested).

On the first *compiling* profiled call of each program the proxy also
captures XLA's own ``cost_analysis()`` FLOP/byte estimates (via
``Lowered.cost_analysis`` — no extra backend compile, verified to tick
zero compile-monitoring events) and reconciles them against the
analytic cost model a caller registered (:mod:`pint_tpu.flops` via
``set_analytic_flops``): disagreement beyond 2x in either direction
emits the ``profile.flops_mismatch`` counter plus a structured record.

Memory watermarks: :func:`sample_memory` publishes live-buffer bytes
(``jax.live_arrays``) and, where the backend exposes
``device.memory_stats()`` (TPU/GPU), device bytes-in-use and
peak-bytes gauges.  While profiling is on it is sampled automatically
at telemetry span boundaries (rate-limited) via the span hook.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
import time
import warnings
from collections import OrderedDict

from pint_tpu import telemetry
from pint_tpu.lint import sanitizer as _sanitizer

__all__ = [
    "PROFILE_ENV", "enabled", "configure", "profiled",
    "wrap_program", "programs", "table_lines", "reset",
    "sample_memory", "flush_programs", "set_trace_hook",
]

PROFILE_ENV = "PINT_TPU_PROFILE"

_lock = threading.RLock()

#: program-label hook the request tracer registers
#: (:mod:`pint_tpu.obs.trace` — it must register itself because the
#: obs package initializer imports back from pint_tpu, so profiling
#: cannot import it).  Called with the program label on EVERY proxied
#: dispatch; the tracer's implementation is a single thread-local
#: read when no collection scope is active, so the hot path stays
#: gate-independent cheap.
_trace_note_program = None


def set_trace_hook(fn):
    """Register (or clear, with ``None``) the per-dispatch program
    label hook — lets a batched device span name the programs that
    actually ran for it."""
    global _trace_note_program
    _trace_note_program = fn

#: None = follow the env var (read per call — a dict lookup, so a
#: subprocess harness or a with-block controls it); True/False = forced
_override = None


def enabled() -> bool:
    """Whether the profile gate is on (env var or programmatic)."""
    if _override is not None:
        return _override
    raw = os.environ.get(PROFILE_ENV)
    if not raw:
        return False
    return raw.strip().lower() in ("1", "true", "yes", "on")


def configure(enabled=None):
    """Force the gate on/off programmatically; ``None`` returns control
    to ``$PINT_TPU_PROFILE``.  Returns the module for chaining."""
    global _override
    _override = None if enabled is None else bool(enabled)
    import sys

    return sys.modules[__name__]


@contextlib.contextmanager
def profiled(on=True):
    """Context manager: the profile gate forced on (off) inside the
    block, previous state restored after — bench's one-extra-profiled-
    call phase probe and the datacheck smoke."""
    global _override
    prev = _override
    _override = bool(on)
    try:
        yield
    finally:
        _override = prev


# --------------------------------------------------------------------------
# per-program registry
# --------------------------------------------------------------------------

class ProgramStats:
    """Cumulative device-truth record of one registry program."""

    __slots__ = ("label", "key_hash", "calls", "compiles", "arg_bytes",
                 "result_bytes", "trace_s", "dispatch_s", "device_s",
                 "hist", "analytic_flops", "xla_flops", "xla_bytes",
                 "cost_checked", "mesh", "runs")

    #: recent run ids attributed to this program (ledger join); small
    #: and ordered-unique — a warm service touches each program from
    #: many runs, and the record only needs the recent tail
    _RUNS_CAP = 8

    def __init__(self, label, key_hash):
        self.label = label
        self.key_hash = key_hash
        self.mesh = None           # parallel.mesh.mesh_desc record
        self.runs: list = []       # recent run ids (most recent last)
        self.calls = 0
        self.compiles = 0          # calls during which a compile ticked
        self.arg_bytes = 0
        self.result_bytes = 0
        self.trace_s = 0.0
        self.dispatch_s = 0.0
        self.device_s = 0.0
        self.hist = telemetry.LogHistogram()   # per-call device_s
        self.analytic_flops = None  # flops.py estimate per call
        self.xla_flops = None       # XLA cost_analysis() per call
        self.xla_bytes = None
        self.cost_checked = False

    def snapshot(self) -> dict:
        h = self.hist.snapshot()
        return {
            "label": self.label,
            "key": self.key_hash,
            "calls": self.calls,
            "compiles": self.compiles,
            "arg_bytes": self.arg_bytes,
            "result_bytes": self.result_bytes,
            "trace_s": round(self.trace_s, 6),
            "dispatch_s": round(self.dispatch_s, 6),
            "device_s": round(self.device_s, 6),
            "device_p50_s": h["p50"],
            "device_p95_s": h["p95"],
            "device_p99_s": h["p99"],
            "analytic_flops": self.analytic_flops,
            "xla_flops": self.xla_flops,
            "xla_bytes": self.xla_bytes,
            "mesh": self.mesh,
            "runs": list(self.runs),
        }

    def note_run(self, run_id):
        if run_id in self.runs:
            return
        self.runs.append(run_id)
        del self.runs[:-self._RUNS_CAP]


#: program id -> ProgramStats, LRU order.  Bounded by the same
#: reasoning as compile_cache's registry cap (keys are structural now
#: — the grid's dataset fingerprint is retired — but a long-lived
#: service still cycles structures), sized above it so stats outlive
#: the jit entries they describe.
_programs: "OrderedDict[str, ProgramStats]" = OrderedDict()

_PROGRAMS_CAP = 512


def _register(label, key) -> ProgramStats:
    key_hash = hashlib.blake2b(
        repr(key).encode(), digest_size=4).hexdigest()
    pid = f"{label}#{key_hash}"
    with _lock:
        st = _programs.get(pid)
        if st is None:
            st = _programs[pid] = ProgramStats(label, key_hash)
            while len(_programs) > _PROGRAMS_CAP:
                _programs.popitem(last=False)
        else:
            _programs.move_to_end(pid)
        return st


def programs() -> list:
    """Snapshot of every program record (dicts, registry order).
    Snapshots are built under the lock — the per-program histogram is
    mutated by concurrent profiled calls."""
    with _lock:
        return [st.snapshot() for st in _programs.values()]


def reset():
    """Drop all program records (tests)."""
    with _lock:
        _programs.clear()


# --------------------------------------------------------------------------
# the profiled proxy
# --------------------------------------------------------------------------

def _tree_bytes(tree) -> int:
    try:
        from jax.tree_util import tree_leaves

        return sum(int(getattr(leaf, "nbytes", 0) or 0)
                   for leaf in tree_leaves(tree))
    except Exception:
        return 0


def _attach_cost(st, jitted, args, kwargs):
    """Capture XLA's cost_analysis for this program (once), and
    reconcile against the registered analytic model.  Uses
    ``Lowered.cost_analysis`` — a host-side retrace plus HLO cost
    analysis, no backend compile (and zero compile-monitoring events,
    verified) — so it is safe on the path that just compiled anyway."""
    st.cost_checked = True
    try:
        ca = jitted.lower(*args, **kwargs).cost_analysis()
    except Exception:
        return
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return
    try:
        st.xla_flops = float(ca.get("flops", 0.0))
        st.xla_bytes = float(ca.get("bytes accessed", 0.0))
    except (TypeError, ValueError):
        return
    a, x = st.analytic_flops, st.xla_flops
    if a and x and (x > 2.0 * a or x < 0.5 * a):
        telemetry.counter_add("profile.flops_mismatch")
        telemetry.emit({
            "type": "flops_mismatch", "program": st.label,
            "key": st.key_hash, "analytic_flops": a, "xla_flops": x,
            "ratio": round(x / a, 3),
        })


def _profiled_call(jitted, st, args, kwargs):
    import jax

    telemetry.compile_stats()  # listener installed before any timing
    c0 = telemetry.counter_get("jit.compile_seconds")
    e0 = telemetry.counter_get("jit.compile_events")
    t0 = time.perf_counter()
    out = jitted(*args, **kwargs)
    t1 = time.perf_counter()
    jax.block_until_ready(out)
    t2 = time.perf_counter()
    call_wall = t1 - t0
    trace_s = min(max(
        telemetry.counter_get("jit.compile_seconds") - c0, 0.0),
        call_wall)
    dispatch_s = max(call_wall - trace_s, 0.0)
    device_s = t2 - t1
    compiled = telemetry.counter_get("jit.compile_events") - e0 > 0
    run_id = telemetry.current_run_id()
    with _lock:
        st.calls += 1
        if compiled:
            st.compiles += 1
        st.trace_s += trace_s
        st.dispatch_s += dispatch_s
        st.device_s += device_s
        st.hist.record(device_s)
        st.arg_bytes += _tree_bytes(args) + _tree_bytes(kwargs)
        st.result_bytes += _tree_bytes(out)
        if run_id is not None:
            st.note_run(run_id)
    telemetry.counter_add("profile.calls")
    telemetry.counter_add("profile.trace_s", trace_s)
    telemetry.counter_add("profile.dispatch_s", dispatch_s)
    telemetry.counter_add("profile.device_s", device_s)
    # the active run accumulates its own phase split (the ledger's
    # per-fit trace/dispatch/device attribution)
    telemetry.run_note_phase(trace_s, dispatch_s, device_s)
    # mirrored into the shared histogram surface so percentiles read
    # out through telemetry.gauges() even with spans disabled
    telemetry.hist_record(f"program.{st.label}.device_s", device_s)
    if compiled and not st.cost_checked:
        _attach_cost(st, jitted, args, kwargs)
    return out


def _arg_spec(args):
    """The abstract argument spec of one call: array leaves become
    ``jax.ShapeDtypeStruct`` (keeping a NamedSharding when the caller
    committed one — sharded programs must re-lower against the same
    layout); non-array leaves (python scalars, None holes) pass
    through verbatim so weak-typed avals survive.  This is what AOT
    export re-lowers each program from
    (:func:`pint_tpu.compile_cache.export_executables`)."""
    import jax

    def to_spec(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sharding = getattr(x, "sharding", None)
            if not isinstance(sharding, jax.sharding.NamedSharding):
                sharding = None
            try:
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sharding)
            except Exception:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree.map(to_spec, args)


class _ProfiledProgram:
    """Callable proxy around a registry jit entry.  Gate off: one
    branch, then the raw call (no sync — async dispatch preserved).
    Gate on: phase-split timing at the device boundary.  Every other
    attribute (``lower`` for AOT warmup, etc.) forwards to the
    underlying jitted callable.

    The proxy also records abstract argument specs — the shapes AOT
    export re-lowers this program from.  One registry entry serves
    MULTIPLE shapes (keys are structure-only; jax's aval cache
    specializes underneath), so the spec record is a list: the hot
    ``__call__`` path captures only the first call's spec (one slot
    load + None check steady-state), while the cold ``lower()`` path
    (AOT warmup sweeps every warmed shape through it) appends each
    distinct spec it sees."""

    __slots__ = ("_jitted", "_stats", "_aot_specs")

    #: distinct shapes exportable per program — a warm sweep is a
    #: handful; anything bigger means a caller forgot to bucket
    _AOT_SPEC_CAP = 8

    def __init__(self, jitted, stats):
        object.__setattr__(self, "_jitted", jitted)
        object.__setattr__(self, "_stats", stats)
        object.__setattr__(self, "_aot_specs", None)

    def _record_spec(self, args):
        try:
            spec = _arg_spec(args)
        except Exception:
            object.__setattr__(self, "_aot_specs", [])  # don't retry
            return
        specs = object.__getattribute__(self, "_aot_specs")
        if specs is None:
            specs = []
            object.__setattr__(self, "_aot_specs", specs)
        if len(specs) < self._AOT_SPEC_CAP and \
                all(repr(spec) != repr(s) for s in specs):
            specs.append(spec)

    def __call__(self, *args, **kwargs):
        if self._aot_specs is None and not kwargs:
            self._record_spec(args)
        # ledger: attribute this dispatch to the active run (one
        # thread-local read when no run is live — gate-independent,
        # so `pinttrace --runs` lists a run's programs even with
        # profiling off)
        telemetry.run_note_program(self._stats.label)
        if _trace_note_program is not None:
            _trace_note_program(self._stats.label)
        if not _sanitizer.ACTIVE:
            if not enabled():
                return self._jitted(*args, **kwargs)
            return _profiled_call(self._jitted, self._stats, args,
                                  kwargs)
        # recompile sanitizer live: bracket the dispatch in a
        # thread-local scope so the compile listener can attribute
        # any backend compile to THIS program; a violation surfaces
        # (raise or warning) only after the underlying call finished,
        # OUTSIDE the finally, so the sanitizer can never mask an
        # in-flight exception from the call itself.  Under a
        # warnings-as-errors filter the warn-mode warning escalates
        # to an error AFTER the result computed — that is the
        # filter's explicit request, not a sanitizer crash
        scope = _sanitizer.begin_dispatch(self._stats)
        try:
            if not enabled():
                out = self._jitted(*args, **kwargs)
            else:
                out = _profiled_call(self._jitted, self._stats, args,
                                     kwargs)
        finally:
            outcome = _sanitizer.end_dispatch(scope, args, kwargs)
        if isinstance(outcome, Exception):
            raise outcome
        if outcome is not None:
            warnings.warn(outcome, RuntimeWarning, stacklevel=2)
        return out

    def lower(self, *args, **kwargs):
        """Forward to the jit's ``lower``, recording the spec — AOT
        warmup (`warm_compile`) lowers without ever calling, and a
        multi-shape warm sweep must leave every shape exportable."""
        if not kwargs:
            self._record_spec(args)
        return self._jitted.lower(*args, **kwargs)

    @property
    def aot_specs(self):
        """The recorded argument specs (list), or None when the
        program was never called/lowered (or capture failed)."""
        specs = object.__getattribute__(self, "_aot_specs")
        return specs if specs else None

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_jitted"), name)

    @property
    def stats(self) -> ProgramStats:
        return object.__getattribute__(self, "_stats")

    def set_analytic_flops(self, flops_per_call):
        """Register the flops.py cost-model estimate for ONE call of
        this program — the reconciliation baseline for XLA's
        cost_analysis."""
        self._stats.analytic_flops = float(flops_per_call)
        return self

    def set_mesh(self, desc):
        """Record the device mesh this program runs over
        (:func:`pint_tpu.parallel.mesh.mesh_desc` — device count +
        axis layout; None for single-device).  Shown by
        ``pinttrace --programs`` / ``datacheck --profile`` so the
        record says what actually ran sharded."""
        self._stats.mesh = desc
        return self


def wrap_program(jitted, *, key, label):
    """Wrap a jitted callable in the profiling proxy, registering (or
    re-attaching to) its per-program record."""
    return _ProfiledProgram(jitted, _register(label, key))


# --------------------------------------------------------------------------
# memory watermarks
# --------------------------------------------------------------------------

_mem_lock = threading.Lock()
_mem_last_sample = 0.0
_live_peak = 0


def _backend_initialized() -> bool:
    """Whether a jax backend is ALREADY up, without initializing one.
    On a hung device tunnel backend init blocks forever (the r03-r05
    pathology) and no except-clause can catch a hang — so anything
    that runs automatically (the span hook) must check first."""
    try:
        import sys

        xb = getattr(sys.modules.get("jax._src.xla_bridge"),
                     "_backends", None)
        return bool(xb)
    except Exception:
        return False


def sample_memory() -> dict:
    """Sample live-buffer bytes and (where the backend exposes
    ``memory_stats``) device memory; publish as gauges, track the
    live-buffer peak across the session.  Returns what was sampled.
    Never initializes a backend that is not already up (checked, not
    assumed: the span hook can fire from pure-host spans like
    ephem.load before any jitted call, and touching a hung tunnel
    would block forever)."""
    global _live_peak
    out = {}
    if not _backend_initialized():
        return out
    try:
        import jax

        live = sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
        out["live_buffer_bytes"] = live
        with _mem_lock:
            _live_peak = max(_live_peak, live)
            peak = _live_peak
        telemetry.gauge_set("profile.live_buffer_bytes", live)
        telemetry.gauge_set("profile.live_buffer_peak_bytes", peak)
        dev = jax.devices()[0]
        stats_fn = getattr(dev, "memory_stats", None)
        stats = stats_fn() if callable(stats_fn) else None
        if stats:
            in_use = stats.get("bytes_in_use")
            peak_dev = stats.get("peak_bytes_in_use")
            if in_use is not None:
                out["device_bytes_in_use"] = int(in_use)
                telemetry.gauge_set("profile.device_bytes_in_use",
                                    int(in_use))
            if peak_dev is not None:
                out["device_peak_bytes"] = int(peak_dev)
                telemetry.gauge_set("profile.device_peak_bytes",
                                    int(peak_dev))
    except Exception:
        pass  # a watermark sample must never take the caller down
    return out


# --------------------------------------------------------------------------
# telemetry hooks: span-boundary sampling + flush mirror
# --------------------------------------------------------------------------

@telemetry.add_span_hook
def _span_hook(name, dur_s):
    """On every span exit while profiling is on: the span's latency
    into a log-bucketed histogram (p50/p95/p99 via telemetry.gauges()),
    plus a rate-limited memory-watermark sample."""
    global _mem_last_sample
    if not enabled():
        return
    telemetry.hist_record(f"span.{name}", dur_s)
    now = time.monotonic()
    if now - _mem_last_sample >= 0.25:
        _mem_last_sample = now
        sample_memory()


@telemetry.add_flush_hook
def flush_programs():
    """Mirror the program registry into the JSONL sink (one
    ``{"type": "program", ...}`` record per program, cumulative — the
    last record per program wins at aggregation).  Runs on every
    telemetry.flush(); a no-op when nothing was profiled."""
    for snap in programs():
        if snap["calls"]:
            telemetry.emit({"type": "program", **snap})


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------

def _fmt_bytes(n):
    if n is None:
        return "-"
    v = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(v) < 1024.0 or unit == "GB":
            return f"{v:.0f}B" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0


def _fmt_ms(s):
    return "-" if s is None else f"{s * 1e3:.2f}"


def _fmt_mesh(desc):
    """Compact mesh layout: ``pulsar8`` / ``pulsar4·grid2`` / ``-``."""
    if not desc or not desc.get("axes"):
        return "-"
    return "·".join(f"{name}{size}"
                    for name, size in desc["axes"].items())


def table_lines(snapshots=None, indent=""):
    """Render program records as table lines — the ONE place the
    format lives, shared by ``datacheck --profile`` (in-process
    registry) and ``pinttrace --programs`` (trace records)."""
    snaps = programs() if snapshots is None else snapshots
    snaps = [s for s in snaps if s.get("calls")]
    if not snaps:
        return [f"{indent}(no profiled programs recorded)"]
    lines = [
        f"{indent}{'PROGRAM':<34s} {'CALLS':>6s} {'COMP':>5s} "
        f"{'DEV_P50MS':>9s} {'DEV_P99MS':>9s} {'DEV_TOT_S':>9s} "
        f"{'ARGS':>9s} {'FLOPS(XLA)':>11s} {'MESH':>12s}"
    ]
    for s in sorted(snaps, key=lambda s: -(s.get("device_s") or 0.0)):
        name = f"{s['label']}#{s['key']}"
        if len(name) > 34:
            name = name[:31] + "..."
        xf = s.get("xla_flops")
        lines.append(
            f"{indent}{name:<34s} {s['calls']:>6d} "
            f"{s.get('compiles', 0):>5d} "
            f"{_fmt_ms(s.get('device_p50_s')):>9s} "
            f"{_fmt_ms(s.get('device_p99_s')):>9s} "
            f"{(s.get('device_s') or 0.0):>9.4f} "
            f"{_fmt_bytes(s.get('arg_bytes')):>9s} "
            f"{('%.3g' % xf) if xf else '-':>11s} "
            f"{_fmt_mesh(s.get('mesh')):>12s}"
        )
    return lines
