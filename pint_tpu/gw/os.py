"""Batched pair-wise optimal statistic for a GW background.

The optimal statistic (the frequentist cross-correlation estimator of
the GWB amplitude; see the PTA GW-analysis framework of
arXiv:2607.06834 and the correlated-noise formulation of
arXiv:1107.5366) combines, over every pulsar pair (a, b),

    rho_ab    = r_a^T C_a^-1 F_a phihat F_b^T C_b^-1 r_b / N_ab,
    N_ab      = tr[phihat M_a phihat M_b],
    sigma_ab  = N_ab^-1/2,

with ``M_a = F_a^T C_a^-1 F_a``, ``F_a`` the common-frequency GW
Fourier basis, ``C_a`` the pulsar's own noise covariance (white +
intrinsic basis, applied through the Woodbury capacity matrix — never
an O(n^2) dense solve), and ``phihat`` the unit-amplitude template
spectrum.  The array-wide amplitude estimate and S/N are the
ORF-weighted combinations

    Ahat^2 = sum_ab Gamma_ab rho_ab / sigma_ab^2
             / sum_ab Gamma_ab^2 / sigma_ab^2,
    S/N    = sum_ab Gamma_ab rho_ab / sigma_ab^2
             / sqrt(sum_ab Gamma_ab^2 / sigma_ab^2).

Execution model: the per-pulsar whitening (z_a, M_a) is ONE vmapped
program over the padded pulsar axis; the pair stage is ONE vmapped
program over all N(N-1)/2 pairs, shardable over the ``pulsar_mesh``'s
device axis.  Both trace through
:func:`pint_tpu.compile_cache.shared_jit` on purely structural keys —
a second same-shaped array performs zero new XLA compiles (regression-
tested via the telemetry compile counter).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import flops as _flops
from pint_tpu import telemetry
from pint_tpu.gw.common import (PAD_SIGMA_S, build_pulsar_data,
                                gwb_phi)
from pint_tpu.gw.orf import orf_matrix, pair_indices
from pint_tpu.linalg import woodbury_solve
from pint_tpu.telemetry import span

__all__ = ["OptimalStatistic", "OSResult"]

#: the supermassive-black-hole-binary background spectral index, the
#: default OS template (gamma = 13/3)
GWB_GAMMA = 13.0 / 3.0


class OSResult(NamedTuple):
    """One optimal-statistic evaluation over the whole array."""

    ahat2: float          # amplitude^2 estimate (template units)
    snr: float            # array S/N of the cross-correlations
    sigma_ahat2: float    # 1-sigma uncertainty of ahat2
    rho: np.ndarray       # (P,) per-pair correlation amplitudes
    sig: np.ndarray       # (P,) per-pair 1-sigma uncertainties
    pairs: np.ndarray     # (P, 2) pulsar index pairs
    orf_vals: np.ndarray  # (P,) ORF at each pair's separation

    @property
    def ahat(self):
        """sqrt of the amplitude estimate (nan when ahat2 < 0 — a
        perfectly legitimate noise-dominated outcome)."""
        return float(np.sqrt(self.ahat2)) if self.ahat2 > 0 else float("nan")


def _zm_one(r, sigma, U, phi, F):
    """One pulsar's whitened projections: z = F^T C^-1 r and
    M = F^T C^-1 F through :func:`pint_tpu.linalg.woodbury_solve`
    (one capacity-matrix Cholesky, multi-RHS; z falls out of C^-1 F by
    symmetry of C)."""
    CF = woodbury_solve(sigma, U, phi, F)   # (n, m) = C^-1 F
    z = CF.T @ r                            # (m,)  = F^T C^-1 r
    M = F.T @ CF                            # (m, m)
    return z, M


def _pair_num_den(z, M, phihat, i, j):
    """One pair's cross-power and normalization."""
    num = z[i] @ (phihat * z[j])
    den = jnp.einsum("i,ij,j,ji->", phihat, M[i], phihat, M[j])
    return num, den


def _os_program(r, sigma, U, phi, F, phihat, ii, jj, gvals, wmask):
    """The whole optimal statistic as one program: vmapped per-pulsar
    whitening, vmapped pair combination, ORF-weighted reduction.
    ``wmask`` marks real pairs (False on sharding pad pairs)."""
    z, M = jax.vmap(_zm_one)(r, sigma, U, phi, F)
    num, den = jax.vmap(
        lambda i, j: _pair_num_den(z, M, phihat, i, j))(ii, jj)
    den = jnp.maximum(den, 1e-300)
    rho = num / den
    sig = 1.0 / jnp.sqrt(den)
    w = jnp.where(wmask, 1.0, 0.0)
    snum = jnp.sum(w * gvals * num)
    sden = jnp.sum(w * gvals**2 * den)
    ahat2 = snum / sden
    snr = snum / jnp.sqrt(sden)
    sigma_ahat2 = 1.0 / jnp.sqrt(sden)
    return rho, sig, ahat2, snr, sigma_ahat2


def _phi_with_red(phi, red_mask, red_freqs, red_df, log10_amp, gamma):
    """Replace each pulsar's intrinsic red-noise block of ``phi`` with
    the power law at one posterior draw's (log10_amp, gamma)."""
    from pint_tpu.models.noise import powerlaw

    pl = powerlaw(red_freqs, 10.0 ** log10_amp[:, None],
                  gamma[:, None]) * red_df[:, None]
    return jnp.where(red_mask, pl, phi)


def _os_program_marg(r, sigma, U, phi, F, phihat, ii, jj, gvals,
                     wmask, red_mask, red_freqs, red_df, amps, gams):
    """Noise-marginalized OS: one draw's red-noise (log10_amp, gamma)
    per pulsar -> phi -> the full OS; vmapped over the draw axis."""

    def one(amp_d, gam_d):
        phi_d = _phi_with_red(phi, red_mask, red_freqs, red_df,
                              amp_d, gam_d)
        _, _, ahat2, snr, sig_a = _os_program(
            r, sigma, U, phi_d, F, phihat, ii, jj, gvals, wmask)
        return ahat2, snr, sig_a

    return jax.vmap(one)(amps, gams)


class OptimalStatistic:
    """The pair-wise optimal statistic of a pulsar array.

    pairs: ``[(TimingModel, TOAs), ...]``; or ``batch=`` a
    :class:`pint_tpu.parallel.PTABatch` to reuse prepared models.
    ``gamma`` is the template spectral index (default 13/3, the SMBHB
    background); ``orf``: 'hd' | 'monopole' | 'dipole' | callable.
    ``marginalize_timing`` folds each pulsar's normalized timing
    design matrix into its noise basis at effectively-infinite prior
    variance, so fitted timing parameters cannot absorb GW power
    asymmetrically between pulsars.
    """

    def __init__(self, pairs=None, *, batch=None, nmodes=10,
                 gamma=GWB_GAMMA, orf="hd", tspan_s=None,
                 marginalize_timing=True):
        with span("gw.os.build", nmodes=nmodes,
                  orf=orf if isinstance(orf, str) else "custom"):
            data, pos, freqs, df, resids = build_pulsar_data(
                pairs, batch=batch, nmodes=nmodes, tspan_s=tspan_s,
                marginalize_timing=marginalize_timing)
        self.data = data
        self.names = [d.name for d in data]
        self.n_pulsars = k = len(data)
        self.nmodes = int(nmodes)
        self.gamma = float(gamma)
        self.pos = pos
        self.orf_kind = orf
        self.orf = np.asarray(orf_matrix(pos, orf))
        self.freqs = np.asarray(freqs)
        self.df = float(df)
        self._prepareds = [r.prepared for r in resids]
        # padded per-pulsar stacks
        n_max = max(d.r.shape[0] for d in data)
        nb_max = max(d.U.shape[1] for d in data)
        m2 = 2 * self.nmodes
        r = np.zeros((k, n_max))
        sigma = np.full((k, n_max), PAD_SIGMA_S)
        U = np.zeros((k, n_max, nb_max))
        phi = np.zeros((k, nb_max))
        F = np.zeros((k, n_max, m2))
        for a, d in enumerate(data):
            n, nb = d.U.shape
            r[a, :n] = d.r
            sigma[a, :n] = d.sigma
            U[a, :n, :nb] = d.U
            phi[a, :nb] = d.phi
            F[a, :n, :] = d.F
        self.r, self.sigma = jnp.asarray(r), jnp.asarray(sigma)
        self.U, self.phi = jnp.asarray(U), jnp.asarray(phi)
        self.F = jnp.asarray(F)
        self.n_toas = np.array([d.r.shape[0] for d in data])
        ii, jj = pair_indices(k)
        self._ii, self._jj = ii, jj
        self.n_pairs = len(ii)
        self._gvals = self.orf[ii, jj]

    def common_process(self):
        """A :class:`pint_tpu.gw.CommonProcess` likelihood over the
        SAME per-pulsar data this statistic was built from (no second
        build/jacfwd pass), with matching nmodes/ORF."""
        from pint_tpu.gw.common import CommonProcess

        return CommonProcess(
            nmodes=self.nmodes, orf=self.orf_kind,
            _prebuilt=(self.data, self.pos, self.freqs, self.df))

    # -- template spectrum ----------------------------------------------------
    def _phihat(self):
        """Unit-amplitude template spectrum (Ahat^2 scales it)."""
        return jnp.asarray(
            np.asarray(gwb_phi(self.freqs, 1.0, self.gamma, self.df)))

    # -- the one-shot OS ------------------------------------------------------
    #: pair-axis partition rules: the four per-pair arrays ride the
    #: ``pair`` axis (a 1-d mesh of any name serves — see
    #: parallel.mesh.resolve_axis); everything per-pulsar is
    #: replicated and handled by _os_program's inner vmap
    @staticmethod
    def _pair_rules():
        from jax.sharding import PartitionSpec as P

        return ((r"^(ii|jj|gvals|wmask)$", P("pair")),)

    def _pair_arrays(self, mesh):
        """(ii, jj, gvals, wmask) as device arrays, padded to a
        device-count multiple (pad pairs: index (0, 1) — a valid pair
        — at zero ORF weight with ``wmask=False``, inert in every
        weighted reduction) and sharded over the mesh's pair axis
        through the shared partition-rule layer."""
        from pint_tpu.parallel import mesh as _mesh

        arrs = {
            "ii": jnp.asarray(self._ii), "jj": jnp.asarray(self._jj),
            "gvals": jnp.asarray(self._gvals),
            "wmask": jnp.asarray(np.ones(len(self._ii), dtype=bool)),
        }
        if mesh is not None:
            ndev = _mesh.axis_size(mesh, "pair")
            n_pad = _mesh.pad_to_multiple(len(self._ii), ndev)
            _mesh.record_pad_waste("pair", len(self._ii), n_pad)
            arrs["ii"] = _mesh.pad_leading(arrs["ii"], n_pad, fill=0)
            arrs["jj"] = _mesh.pad_leading(arrs["jj"], n_pad, fill=1)
            arrs["gvals"] = _mesh.pad_leading(arrs["gvals"], n_pad,
                                              mode="zero")
            arrs["wmask"] = _mesh.pad_leading(arrs["wmask"], n_pad,
                                              fill=False)
            arrs = _mesh.shard_args(mesh, self._pair_rules(), arrs)
        return arrs["ii"], arrs["jj"], arrs["gvals"], arrs["wmask"]

    def compute(self, mesh=None) -> OSResult:
        """Evaluate the OS over every pair; optionally shard the pair
        axis over a device mesh (:func:`pint_tpu.parallel.pulsar_mesh`
        works — the axis name is immaterial, pairs ride it).  The mesh
        participates in the jit key: one registry entry per layout, a
        second same-shaped sharded call compiles nothing."""
        from pint_tpu.parallel import mesh as _mesh

        fn = _cc.shared_jit(
            _os_program,
            key=("gw.os.program",) + _mesh.mesh_jit_key(mesh),
            label="gw.os.program"
                  + (":sharded" if mesh is not None else ""))
        fn.set_mesh(_mesh.mesh_desc(mesh))
        ii, jj, gvals, wmask = self._pair_arrays(mesh)
        with span("gw.os.compute", n_pulsars=self.n_pulsars,
                  n_pairs=self.n_pairs, nmodes=self.nmodes,
                  sharded=mesh is not None):
            rho, sig, ahat2, snr, sig_a = fn(
                self.r, self.sigma, self.U, self.phi, self.F,
                self._phihat(), ii, jj, gvals, wmask)
            rho = np.asarray(rho)[: self.n_pairs]
            sig = np.asarray(sig)[: self.n_pairs]
        telemetry.record_transfer(rho)
        telemetry.counter_add(
            "gw.os.flops_est",
            _flops.os_flops(self.n_pulsars, int(self.n_toas.max()),
                            int(self.U.shape[2]), 2 * self.nmodes,
                            self.n_pairs))
        return OSResult(
            ahat2=float(ahat2), snr=float(snr),
            sigma_ahat2=float(sig_a), rho=rho, sig=sig,
            pairs=np.stack([self._ii, self._jj], axis=1),
            orf_vals=np.asarray(self._gvals),
        )

    # -- noise-marginalized OS ------------------------------------------------
    def _red_noise_layout(self):
        """Padded (mask, freqs, df) locating each pulsar's intrinsic
        red-noise block inside its phi vector — host-side metadata for
        the in-trace phi replacement."""
        k, nb_max = self.phi.shape
        mask = np.zeros((k, nb_max), dtype=bool)
        freqs = np.ones((k, nb_max))
        dfs = np.zeros(k)
        found = False
        for a, prep in enumerate(self._prepareds):
            dims = prep.noise_dimensions()
            if "PLRedNoise" not in dims:
                continue
            start, nb = dims["PLRedNoise"]
            ctx = prep.ctx["PLRedNoise"]
            mask[a, start:start + nb] = True
            freqs[a, start:start + nb] = np.asarray(ctx["freqs"])[:nb]
            dfs[a] = float(ctx["df"])
            found = True
        if not found:
            raise ValueError(
                "noise_marginalized: no pulsar in the array carries a "
                "PLRedNoise component to marginalize over")
        return jnp.asarray(mask), jnp.asarray(freqs), jnp.asarray(dfs)

    def noise_marginalized(self, log10_amp_draws, gamma_draws):
        """OS over posterior draws of the per-pulsar intrinsic
        red-noise (log10_amp, gamma) — e.g. the columns of an MCMC
        chain.  Each array is (n_draws, n_pulsars); a (n_draws,)
        array broadcasts one common draw across pulsars.  Returns
        (ahat2 (n_draws,), snr (n_draws,), sigma_ahat2 (n_draws,)).

        White-noise parameters stay at the values the statistic was
        built with (sigma enters the whitening, not the basis) —
        standard practice for the noise-marginalized OS, where the
        red-noise/GWB covariance is the dominant systematic."""
        amps = np.asarray(log10_amp_draws, np.float64)
        gams = np.asarray(gamma_draws, np.float64)
        if amps.ndim == 1:
            amps = np.repeat(amps[:, None], self.n_pulsars, axis=1)
        if gams.ndim == 1:
            gams = np.repeat(gams[:, None], self.n_pulsars, axis=1)
        red_mask, red_freqs, red_df = self._red_noise_layout()
        fn = _cc.shared_jit(_os_program_marg,
                            key=("gw.os.program_marg",))
        ii, jj, gvals, wmask = self._pair_arrays(None)
        with span("gw.os.noise_marginalized",
                  n_pulsars=self.n_pulsars, n_draws=amps.shape[0]):
            ahat2, snr, sig_a = fn(
                self.r, self.sigma, self.U, self.phi, self.F,
                self._phihat(), ii, jj, gvals, wmask,
                red_mask, red_freqs, red_df,
                jnp.asarray(amps), jnp.asarray(gams))
        return np.asarray(ahat2), np.asarray(snr), np.asarray(sig_a)
