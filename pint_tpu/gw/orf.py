"""Overlap-reduction functions: the angular correlation signature of a
common gravitational-wave process across a pulsar-timing array.

An isotropic GW background correlates the timing residuals of every
pulsar pair with a coefficient that depends only on the pair's angular
separation zeta — the Hellings–Downs curve (Hellings & Downs 1983; the
correlated-noise PTA formulation is van Haasteren & Levin,
arXiv:1107.5366).  Clock errors correlate as a monopole, ephemeris
errors as a dipole; fitting all three ORFs is the standard PTA
systematics triage.

Conventions (matching the NANOGrav/enterprise normalization):

- cross-correlation: with x = (1 - cos zeta) / 2,
  ``Gamma(zeta) = 3/2 x ln x - x/4 + 1/2``
- auto-correlation: ``Gamma(0) = 1`` — the pulsar term doubles the
  zero-lag power, so the diagonal is 1 while the zeta -> 0 limit of the
  cross term is 1/2 (the famous discontinuity).
- endpoints: ``Gamma(pi) = 1/4``, ``Gamma(pi/2) ~ -0.1449``.

Everything here is pure array math (works on numpy or jax inputs, is
vmappable, and traces cleanly inside jit); the ORF matrix of an N-pulsar
array is a dense, symmetric, positive-semidefinite (N, N) constant of
the array geometry.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["pulsar_positions", "angular_separation_matrix",
           "hellings_downs", "monopole", "dipole", "orf_matrix",
           "pair_indices", "ORF_KINDS"]


def pulsar_positions(models) -> np.ndarray:
    """(N, 3) SSB->pulsar ICRS unit vectors from each model's current
    astrometry (RAJ/DECJ or ELONG/ELAT), via
    :func:`pint_tpu.models.astrometry.psr_dir_static`."""
    from pint_tpu.models.astrometry import psr_dir_static

    return np.stack([psr_dir_static(m) for m in models], axis=0)


def angular_separation_matrix(pos):
    """(N, N) pairwise angular separations [rad] from (N, 3) unit
    vectors.  arccos of the clipped dot product — robust at the
    zeta = 0 diagonal and for antipodal pairs."""
    pos = jnp.asarray(pos)
    cosz = jnp.clip(pos @ pos.T, -1.0, 1.0)
    zeta = jnp.arccos(cosz)
    # the self-separation is exactly 0; unit-vector roundoff otherwise
    # leaves arccos(1 - 1e-16) ~ 1e-8 and the auto-correlation falls
    # into the cross branch (HD diagonal would read 0.5, not 1)
    n = zeta.shape[0]
    return zeta * (1.0 - jnp.eye(n))


def hellings_downs(zeta, auto=None):
    """Hellings–Downs ORF at separation ``zeta`` [rad].

    Cross-correlation for zeta > 0; ``auto`` (default from
    ``zeta == 0``: the co-located limit 1/2 plus the pulsar term 1/2,
    i.e. 1) overrides the zeta = 0 value — pass ``auto=0.5`` for the
    distinct-but-co-located-pulsars limit."""
    zeta = jnp.asarray(zeta, dtype=jnp.float64)
    x = (1.0 - jnp.cos(zeta)) / 2.0
    # ln x is singular at the diagonal; evaluate on a floored argument
    # and select the limit value there (x ln x -> 0 as x -> 0, so the
    # cross-term limit is exactly 1/2)
    x_safe = jnp.where(x > 0.0, x, 1.0)
    cross = 1.5 * x * jnp.log(x_safe) - x / 4.0 + 0.5
    zero_val = 1.0 if auto is None else auto
    return jnp.where(x > 0.0, cross, zero_val)


def monopole(zeta, auto=None):
    """Monopole ORF (clock-error signature): 1 for every pair."""
    zeta = jnp.asarray(zeta, dtype=jnp.float64)
    return jnp.ones_like(zeta)


def dipole(zeta, auto=None):
    """Dipole ORF (ephemeris-error signature): cos zeta, with the
    auto-correlation pinned to 1 (+ pulsar term) like Hellings–Downs."""
    zeta = jnp.asarray(zeta, dtype=jnp.float64)
    zero_val = 1.0 if auto is None else auto
    return jnp.where(zeta > 0.0, jnp.cos(zeta), zero_val)


ORF_KINDS = {
    "hd": hellings_downs,
    "hellings_downs": hellings_downs,
    "monopole": monopole,
    "dipole": dipole,
}


def orf_matrix(pos, kind="hd"):
    """Dense (N, N) ORF matrix from (N, 3) pulsar unit vectors.

    The diagonal is the full auto-correlation (pulsar term included,
    so 1 for HD/dipole) — this is the matrix whose Cholesky correlates
    GWB injections and whose off-diagonal drives the optimal
    statistic.  ``kind``: 'hd' | 'monopole' | 'dipole', or a callable
    ``orf(zeta)``."""
    fn = ORF_KINDS.get(kind, kind) if isinstance(kind, str) else kind
    if not callable(fn):
        raise ValueError(
            f"unknown ORF kind {kind!r} (have {sorted(ORF_KINDS)})")
    zeta = angular_separation_matrix(pos)
    n = zeta.shape[0]
    eye = jnp.eye(n)
    # off-diagonal entries must take the CROSS branch even at exactly
    # zero separation: two DISTINCT pulsars with identical catalog
    # coordinates (cos zeta rounds to 1 below ~2e-8 rad) correlate at
    # the co-located limit (HD: 1/2), not the pulsar-term-inclusive
    # auto value — only the diagonal carries the pulsar term.  Custom
    # callables without the builtins' ``auto`` override keep their own
    # zeta = 0 convention on off-diagonal coincident pairs.
    cross_auto = {hellings_downs: 0.5, dipole: 1.0,
                  monopole: 1.0}.get(fn)
    off = fn(zeta) if cross_auto is None else fn(zeta, auto=cross_auto)
    g = off * (1.0 - eye) + jnp.diag(fn(jnp.zeros(n)))
    # exact symmetry (arccos/cos roundoff can leave last-ulp asymmetry
    # that a Cholesky-based injection would amplify into complaints)
    return (g + g.T) / 2.0


def pair_indices(n):
    """(ii, jj) index arrays over the N(N-1)/2 unordered distinct
    pairs, i < j, row-major — the pair axis every OS program vmaps
    over."""
    ii, jj = np.triu_indices(n, k=1)
    return ii.astype(np.int64), jj.astype(np.int64)
