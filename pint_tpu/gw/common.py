"""Common red process (CRN/GWB) likelihood across a pulsar array.

The cross-pulsar extension of the single-pulsar Woodbury likelihood
(van Haasteren & Levin, arXiv:1107.5366): a gravitational-wave
background adds, on top of every pulsar's own noise, a shared power-law
Fourier process whose cross-pulsar covariance is the overlap-reduction
function.  Over the STACKED residual vector of the whole array the
covariance is

    C = diag(sigma^2) + U Phi U^T,

with U the block-diagonal concatenation of every pulsar's noise basis
followed by every pulsar's common-frequency GW Fourier basis, and Phi
block-structured: diagonal per-pulsar noise weights, plus a dense GWB
sector ``Gamma (x) diag(phi_gw)`` (Kronecker of the ORF matrix with the
power-law spectrum).  That dense-prior form goes through the SAME
:func:`pint_tpu.linalg.woodbury_chi2_logdet` solver as every
single-pulsar fit — ``phi`` is simply 2-D — so the per-pulsar and PTA
likelihoods share one code path.

The Fourier machinery is the one implementation in
:mod:`pint_tpu.models.noise` (``fourier_basis`` / ``powerlaw`` /
``toa_fourier_basis``); the GW bases of all pulsars are evaluated at
COMMON frequencies k/T over the array-wide span, on the absolute TDB
time axis, so the process is phase-coherent across pulsars.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import faults as _faults
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.gw.orf import orf_matrix, pulsar_positions
from pint_tpu.linalg import (KronPhi, kron_chi2_logdet,
                             woodbury_chi2_logdet)
from pint_tpu.models.noise import powerlaw, toa_fourier_basis
from pint_tpu.residuals import MEAN_OFFSET_WEIGHT, Residuals
from pint_tpu.telemetry import span

__all__ = ["PulsarGWData", "build_pulsar_data", "common_tspan_s",
           "CommonProcess", "gwb_phi"]

#: pad-row sigma [s] for the padded per-pulsar stacks: weight 1e-32,
#: in line with compile_cache.PAD_ERROR_US (1e22 us) — sigma^2 = 1e32
#: survives the TPU float32-pair f64 emulation (1e30 s would square to
#: 1e60 and saturate the high word)
PAD_SIGMA_S = 1e16


def common_tspan_s(toas_list) -> float:
    """Array-wide observing span [s]: max - min TDB second over every
    pulsar's TOAs — the T whose k/T harmonics the common process
    lives on."""
    lo = min(float(t.ticks.min()) for t in toas_list) / 2**32
    hi = max(float(t.ticks.max()) for t in toas_list) / 2**32
    return hi - lo


def gwb_phi(freqs, amp, gamma, df):
    """Per-mode GWB prior weights [s^2]: the shared power-law PSD
    integrated over one frequency bin — the same
    :func:`pint_tpu.models.noise.powerlaw` convention every intrinsic
    red-noise component uses."""
    return powerlaw(freqs, amp, gamma) * df


class PulsarGWData(NamedTuple):
    """One pulsar's ingredients for the cross-correlation engine, all
    concrete numpy/jax arrays at the model's current parameter values."""

    r: np.ndarray       # (n,) time residuals [s], mean-subtracted
    sigma: np.ndarray   # (n,) noise-scaled uncertainties [s]
    U: np.ndarray       # (n, nb) own noise basis + offset/timing cols
    phi: np.ndarray     # (nb,) own basis weights [s^2]
    F: np.ndarray       # (n, 2*nmodes) common-frequency GW basis
    name: str


def _timing_design(resid: Residuals) -> np.ndarray:
    """Column-normalized timing-model design matrix (n, n_free) of one
    pulsar, for marginalizing the fitted timing model out of the GW
    statistics (the van Haasteren G-matrix, realized as basis columns
    at MEAN_OFFSET_WEIGHT prior variance).  Eager jacfwd — no XLA
    compile is triggered at build time.

    free_timing_params, NOT free_params: a free noise parameter (EFAC
    etc.) has a pure-roundoff residual derivative (~1e-22 column norm
    through the weighted mean) that unit normalization would amplify
    into an arbitrary full-magnitude direction projected out of every
    GW statistic — the same reason the fitters' design matrices
    exclude noise parameters."""
    names = list(resid.model.free_timing_params)
    n = len(resid.toas)
    if not names:
        return np.zeros((n, 0))
    base = resid._values()
    data = resid._data()

    def f(vec):
        values = dict(base)
        for i, k in enumerate(names):
            values[k] = vec[i]
        return resid.time_resids_at(values, data)

    vec0 = jnp.asarray([float(resid.model.values[k]) for k in names])
    J = np.asarray(jax.jacfwd(f)(vec0))
    norm = np.linalg.norm(J, axis=0)
    norm[norm == 0.0] = 1.0
    return J / norm


def build_pulsar_data(
    pairs: Optional[Sequence[Tuple]] = None,
    *,
    batch=None,
    nmodes: int = 10,
    tspan_s: Optional[float] = None,
    marginalize_timing: bool = True,
) -> Tuple[List[PulsarGWData], np.ndarray, np.ndarray, float,
           List[Residuals]]:
    """Assemble every pulsar's (r, sigma, U, phi, F) plus the array
    geometry.

    pairs: ``[(TimingModel, TOAs), ...]``; or pass ``batch=`` a
    :class:`pint_tpu.parallel.PTABatch` to reuse its prepared models.
    Returns ``(data_list, positions (N, 3), freqs (2*nmodes,), df,
    resids)`` — the :class:`Residuals` list rides along so callers can
    reach the prepared models (noise layout metadata) without a second
    prepare pass.
    """
    if batch is not None:
        resids = list(batch.resids)
        models = [p.model for p in batch.prepareds]
    elif pairs:
        resids = [Residuals(t, m, track_mode="nearest")
                  for m, t in pairs]
        models = [r.model for r in resids]
    else:
        raise ValueError("build_pulsar_data needs pairs or batch=")
    if len(resids) < 2:
        raise ValueError(
            f"a cross-correlation analysis needs >= 2 pulsars, got "
            f"{len(resids)}")
    toas_list = [r.toas for r in resids]
    T = float(tspan_s) if tspan_s else common_tspan_s(toas_list)
    pos = pulsar_positions(models)
    out = []
    freqs = None
    for resid in resids:
        prep = resid.prepared
        values = resid._values()
        r = np.asarray(resid.time_resids, dtype=np.float64)
        sigma = np.asarray(resid.scaled_errors, dtype=np.float64)
        U = np.asarray(prep.noise_basis, dtype=np.float64)
        phi = np.asarray(prep.noise_weights_fn(values),
                         dtype=np.float64)
        n = len(resid.toas)
        cols = [U, np.ones((n, 1))]
        ws = [phi, np.array([MEAN_OFFSET_WEIGHT])]
        if marginalize_timing:
            J = _timing_design(resid)
            cols.append(J)
            ws.append(np.full(J.shape[1], MEAN_OFFSET_WEIGHT))
        U_ext = np.concatenate(cols, axis=1)
        phi_ext = np.concatenate(ws)
        F, fgrid = toa_fourier_basis(resid.toas, nmodes, tspan_s=T)
        if freqs is None:
            freqs = fgrid
        out.append(PulsarGWData(
            r=r, sigma=sigma, U=U_ext, phi=phi_ext, F=F,
            name=str(resid.model.meta.get("PSR", "?"))))
    return out, pos, freqs, float(freqs[0]), resids


# --------------------------------------------------------------------------
# stacked CRN/GWB likelihood
# --------------------------------------------------------------------------

def _crn_lnlike_one(r, sigma, U_full, phi_noise, orf, freqs, df,
                    n_toa, log10_amp, gamma):
    """Log-likelihood of the stacked array under noise + an
    ORF-correlated common power-law process.  Pure function of dynamic
    arrays — one trace serves every same-shaped PTA.  Returns
    (lnlike, health) with health the on-device finiteness verdict
    (chi2, logdet) riding the same compiled program."""
    amp = 10.0 ** log10_amp
    phi_gw = gwb_phi(freqs, amp, gamma, df)
    kn = phi_noise.shape[0]
    ktot = U_full.shape[1]
    gw_block = jnp.kron(orf, jnp.diag(phi_gw))
    phi_dense = jnp.zeros((ktot, ktot))
    phi_dense = phi_dense.at[:kn, :kn].set(jnp.diag(phi_noise))
    phi_dense = phi_dense.at[kn:, kn:].set(gw_block)
    chi2, logdet = woodbury_chi2_logdet(r, sigma, U_full, phi_dense)
    lnl = -0.5 * (chi2 + logdet) - 0.5 * n_toa * jnp.log(2.0 * jnp.pi)
    health = (jnp.isfinite(chi2), jnp.isfinite(logdet))
    return lnl, health


_crn_lnlike_vec = jax.vmap(
    _crn_lnlike_one,
    in_axes=(None, None, None, None, None, None, None, None, 0, 0),
)


def _kron_lnlike_one(r, sigma, U, F, valid, phi_noise, orf, freqs, df,
                     n_toa, log10_amp, gamma):
    """The kron-structured twin of :func:`_crn_lnlike_one`: the same
    stacked-array likelihood, evaluated over padded PER-PULSAR stacks
    through :func:`pint_tpu.linalg.kron_chi2_logdet` instead of the
    materialized dense (K, K) prior — per-pulsar Woodbury reductions
    plus per-frequency (N_psr, N_psr) prior blocks, never an O(K^3)
    factorization or an O(N_tot K^2) stacked gram.  Same return
    contract (lnlike, health); brute-force-verified equal to the dense
    path (tests/test_kron_hmc.py)."""
    amp = 10.0 ** log10_amp
    phi_gw = gwb_phi(freqs, amp, gamma, df)
    kp = KronPhi(orf=orf, phi_gw=phi_gw, phi_noise=phi_noise)
    chi2, logdet = kron_chi2_logdet(r, sigma, U, F, kp, valid=valid)
    lnl = -0.5 * (chi2 + logdet) - 0.5 * n_toa * jnp.log(2.0 * jnp.pi)
    health = (jnp.isfinite(chi2), jnp.isfinite(logdet))
    return lnl, health


_kron_lnlike_vec = jax.vmap(
    _kron_lnlike_one,
    in_axes=(None, None, None, None, None, None, None, None, None,
             None, 0, 0),
)


def _crn_lnlike_grid_fn(r, sigma, U_full, phi_noise, orf, freqs, df,
                        n_toa, log10_amps, gammas, pts_valid):
    """The dense grid program: vmapped point sweep PLUS the on-device
    non-finite count over the REAL (non-pad) points — the bad-point
    counter no longer needs a host-side pass over the returned
    surface, so a sharded grid never syncs per point.  ``pts_valid``
    masks edge-repeated pad points out of the count (they duplicate a
    real point's verdict)."""
    lnls, _health = _crn_lnlike_vec(r, sigma, U_full, phi_noise, orf,
                                    freqs, df, n_toa, log10_amps,
                                    gammas)
    n_bad = jnp.sum(jnp.where(pts_valid, ~jnp.isfinite(lnls), False))
    return lnls, n_bad


def _kron_lnlike_grid_fn(r, sigma, U, F, valid, phi_noise, orf, freqs,
                         df, n_toa, log10_amps, gammas, pts_valid):
    """The kron grid program — :func:`_crn_lnlike_grid_fn`'s
    structured twin."""
    lnls, _health = _kron_lnlike_vec(r, sigma, U, F, valid, phi_noise,
                                     orf, freqs, df, n_toa,
                                     log10_amps, gammas)
    n_bad = jnp.sum(jnp.where(pts_valid, ~jnp.isfinite(lnls), False))
    return lnls, n_bad


class CommonProcess:
    """The PTA likelihood with an ORF-correlated common red process.

    Timing parameters are held at each model's current values (their
    linearized freedom is marginalized through the design-matrix
    columns when ``marginalize_timing``); the two live parameters are
    the common process's ``(log10_amp, gamma)``.  ``orf``: 'hd' |
    'monopole' | 'dipole' | a callable — 'monopole'/'dipole' give the
    clock-error / ephemeris-error systematics fits of the standard PTA
    triage.

    Every jitted entry point routes through
    :func:`pint_tpu.compile_cache.shared_jit`, keyed purely on
    structure: a second same-shaped PTA performs zero new XLA
    compiles.
    """

    def __init__(self, pairs=None, *, batch=None, nmodes=10, orf="hd",
                 tspan_s=None, marginalize_timing=True, kron=None,
                 _prebuilt=None):
        with span("gw.common.build", nmodes=nmodes,
                  orf=orf if isinstance(orf, str) else "custom"):
            self.resids = None
            if _prebuilt is not None:
                # per-pulsar data already assembled by a sibling
                # engine (OptimalStatistic.common_process) — skip the
                # second build_pulsar_data pass (and its per-pulsar
                # eager jacfwd timing-design sweep)
                data, pos, freqs, df = _prebuilt
            else:
                data, pos, freqs, df, resids = build_pulsar_data(
                    pairs, batch=batch, nmodes=nmodes,
                    tspan_s=tspan_s,
                    marginalize_timing=marginalize_timing)
                # kept for gradient-based samplers (gw/hmc builds its
                # per-pulsar noise-weight maps from the prepared
                # models); None on the _prebuilt fast path
                self.resids = resids
            self.data = data
            self.names = [d.name for d in data]
            self.n_pulsars = len(data)
            self.nmodes = int(nmodes)
            self.pos = pos
            self.orf_kind = orf
            self.orf = _faults.corrupt_orf(
                jnp.asarray(np.asarray(orf_matrix(pos, orf))))
            self.freqs = jnp.asarray(freqs)
            self.df = jnp.float64(df)
            # stacked vectors (ragged concatenation — no padding)
            self.r = jnp.asarray(np.concatenate([d.r for d in data]))
            self.sigma = jnp.asarray(
                np.concatenate([d.sigma for d in data]))
            self.phi_noise = jnp.asarray(
                np.concatenate([d.phi for d in data]))
            self.n_toa_total = int(self.r.shape[0])
            # ``kron=None`` follows the $PINT_TPU_KRON_PHI gate; the
            # resolved flag is part of every lnlike/lnlike_grid jit
            # key (the two paths are different traced programs —
            # tools/check_jit_gates.py).  Each path's array layout is
            # materialized LAZILY on first use: the dense stacked
            # U_full is O(N_tot x K) of mostly block-diagonal zeros —
            # a kron-served instance must not keep it resident (it is
            # the allocation the kron path exists to avoid), and a
            # dense-served instance skips the padded kron stacks.
            self._kron = (_cc.kron_phi_default() if kron is None
                          else bool(kron))
            self._U_full = None
            self._kron_data = None

    @property
    def U_full(self):
        """The dense stacked (N_tot, K) basis — built on first access
        (the dense lnlike path, the reference tests)."""
        if self._U_full is None:
            n_tot = self.n_toa_total
            kn = self.phi_noise.shape[0]
            m2 = 2 * self.nmodes
            U = np.zeros((n_tot, kn + self.n_pulsars * m2))
            row = col = 0
            for k, d in enumerate(self.data):
                n, nb = d.U.shape
                U[row:row + n, col:col + nb] = d.U
                U[row:row + n, kn + k * m2: kn + (k + 1) * m2] = d.F
                row += n
                col += nb
            self._U_full = jnp.asarray(U)
        return self._U_full

    @property
    def kron_data(self):
        """Kron-structured per-pulsar stacks, built on first access:
        the SAME model as the dense prior, carried as padded (P, ...)
        arrays the structured solver (linalg.KronPhi) consumes.  Pad
        rows have zero r/U/F entries (every contraction exact) and
        PAD_SIGMA_S sigmas; pad columns carry zero weights (the
        _PHI_FLOOR pinning) — exactness asserted in
        tests/test_kron_hmc.py."""
        if self._kron_data is None:
            data = self.data
            n_max = max(d.r.shape[0] for d in data)
            nb_max = max(d.U.shape[1] for d in data)
            m2 = 2 * self.nmodes
            p = self.n_pulsars
            r_pad = np.zeros((p, n_max))
            sig_pad = np.full((p, n_max), PAD_SIGMA_S)
            valid = np.zeros((p, n_max), dtype=bool)
            U_pad = np.zeros((p, n_max, nb_max))
            F_pad = np.zeros((p, n_max, m2))
            phi_pad = np.zeros((p, nb_max))
            for k, d in enumerate(data):
                n, nb = d.U.shape
                r_pad[k, :n] = d.r
                sig_pad[k, :n] = d.sigma
                valid[k, :n] = True
                U_pad[k, :n, :nb] = d.U
                F_pad[k, :n, :] = d.F
                phi_pad[k, :nb] = d.phi
            self._kron_data = {
                "r": jnp.asarray(r_pad),
                "sigma": jnp.asarray(sig_pad),
                "U": jnp.asarray(U_pad), "F": jnp.asarray(F_pad),
                "valid": jnp.asarray(valid),
                "phi_noise": jnp.asarray(phi_pad),
            }
        return self._kron_data

    def _lnlike_jit(self):
        fn = _kron_lnlike_one if self._kron else _crn_lnlike_one
        return _cc.shared_jit(
            fn, key=("gw.common.lnlike", self._kron),
            label="gw.common.lnlike" + (":kron" if self._kron else ""))

    def _lnlike_args(self, log10_amp, gamma):
        """Positional args of the active lnlike program (kron padded
        stacks vs dense stacked arrays)."""
        common = (self.orf, self.freqs, self.df,
                  jnp.float64(self.n_toa_total),
                  jnp.float64(log10_amp), jnp.float64(gamma))
        if self._kron:
            kd = self.kron_data
            return (kd["r"], kd["sigma"], kd["U"], kd["F"],
                    kd["valid"], kd["phi_noise"]) + common
        return (self.r, self.sigma, self.U_full,
                self.phi_noise) + common

    def lnlike(self, log10_amp, gamma, check=True):
        """Log-likelihood at one (log10 amplitude, spectral index).

        check: a non-finite likelihood (degenerate prior past the
        dense-phi jitter, corrupted inputs) raises a structured
        :class:`pint_tpu.guard.FitDivergedError` instead of silently
        handing a sampler NaN; pass check=False for raw -inf/NaN
        semantics."""
        with span("gw.common.lnlike", n_pulsars=self.n_pulsars,
                  nmodes=self.nmodes, kron=self._kron):
            out, health = self._lnlike_jit()(
                *self._lnlike_args(log10_amp, gamma))
            # the check honors the guard gate — PINT_TPU_GUARD=0
            # restores raw -inf/NaN semantics like check=False
            if check and _guard.enabled():
                telemetry.counter_add("guard.checks")
            if check and _guard.enabled() \
                    and not np.isfinite(float(out)):
                telemetry.counter_add("guard.trips")
                telemetry.counter_add("guard.trip.gw_lnlike")
                raise _guard.FitDivergedError(
                    "gw.common.lnlike",
                    health={"chi2_finite": bool(health[0]),
                            "logdet_finite": bool(health[1])},
                    detail=f"lnlike({log10_amp}, {gamma}) non-finite")
            return float(out)

    #: lnlike_grid partition rules: the point-axis arrays (the two
    #: grids plus the pad-point mask of the on-device bad count) ride
    #: the ``grid`` axis; every stacked-array/basis leaf is explicitly
    #: replicated (each device evaluates its grid points against the
    #: full array), so rule resolution covers EVERY leaf of the call —
    #: one table serves the dense and kron argument layouts
    _GRID_RULES = (
        (r"^(log10_amps|gammas|pts_valid)$", "grid"),
        (r"^(r|sigma|U_full|U|F|valid|phi_noise|orf|freqs)$", None),
    )

    def lnlike_grid(self, log10_amps, gammas, mesh=None):
        """(A, G) log-likelihood surface over the outer product of the
        two 1-d grids — one vmapped program.  Non-finite grid points
        are counted ON DEVICE (the count returns alongside the grid as
        a second program output — no host-side pass over the surface,
        so a sharded grid never syncs per point; edge-repeated pad
        points are masked out of the count) and warned about
        (``guard.trip.gw_lnlike_grid``), never silently returned as a
        clean-looking surface.

        mesh: a device mesh — the flattened point axis is padded to a
        device multiple (edge-repeated; the pad points are sliced off
        the returned surface) and sharded.  A 1-d mesh shards points
        over its single axis (the original ``grid`` contract); a
        MULTI-AXIS mesh (e.g. the 2-D ``pulsar x grid`` layout a
        full-PTA scan shares with ``PTABatch.chisq_grid``) shards the
        point axis over the product of ALL its axes, so the dense
        hyperparameter surface runs as one program across the whole
        pod slice with no idle sub-mesh.  The mesh participates in
        the jit key, so a second same-shaped sharded call compiles
        nothing and ``mesh=None`` behaves exactly as before."""
        from jax.sharding import PartitionSpec as P

        from pint_tpu.parallel import mesh as _mesh

        log10_amps = np.atleast_1d(np.asarray(log10_amps, np.float64))
        gammas = np.atleast_1d(np.asarray(gammas, np.float64))
        aa, gg = np.meshgrid(log10_amps, gammas, indexing="ij")
        grid_fn = (_kron_lnlike_grid_fn if self._kron
                   else _crn_lnlike_grid_fn)
        fn = _cc.shared_jit(
            grid_fn,
            key=("gw.common.lnlike_grid", self._kron)
                + _mesh.mesh_jit_key(mesh),
            fn_token="gw.common.lnlike_grid",
            label="gw.common.lnlike_grid"
                  + (":kron" if self._kron else "")
                  + (":sharded" if mesh is not None else ""))
        fn.set_mesh(_mesh.mesh_desc(mesh))
        n_pts = aa.size
        amps_flat, gams_flat = (jnp.asarray(aa.ravel()),
                                jnp.asarray(gg.ravel()))
        if self._kron:
            kd = self.kron_data
            args = {"r": kd["r"], "sigma": kd["sigma"], "U": kd["U"],
                    "F": kd["F"], "valid": kd["valid"],
                    "phi_noise": kd["phi_noise"]}
        else:
            args = {"r": self.r, "sigma": self.sigma,
                    "U_full": self.U_full,
                    "phi_noise": self.phi_noise}
        args.update({
            "orf": self.orf, "freqs": self.freqs, "df": self.df,
            "n_toa": jnp.float64(self.n_toa_total),
            "log10_amps": amps_flat, "gammas": gams_flat,
            "pts_valid": jnp.ones(n_pts, dtype=bool),
        })
        if mesh is not None:
            names = tuple(str(n) for n in mesh.axis_names)
            if len(names) == 1:
                ndev = _mesh.axis_size(mesh, "grid")
                point_spec = P("grid")
            else:
                # multi-axis mesh: the point axis rides EVERY axis
                # (one PartitionSpec dim over the axis tuple), so the
                # full device product serves the scan
                ndev = int(mesh.devices.size)
                point_spec = P(names)
            n_pad = _mesh.pad_to_multiple(n_pts, ndev)
            _mesh.record_pad_waste("grid", n_pts, n_pad)
            for k in ("log10_amps", "gammas"):
                args[k] = _mesh.pad_leading(args[k], n_pad,
                                            mode="edge")
            # pad points are edge clones — mask them out of the
            # on-device bad count so a clone can't double-report
            args["pts_valid"] = _mesh.pad_leading(
                args["pts_valid"], n_pad, mode="zero")
            rules = tuple(
                (pat, point_spec if ax else None)
                for pat, ax in self._GRID_RULES)
            args = _mesh.shard_args(mesh, rules, args)
        with telemetry.run_scope("lnlike_grid",
                                 n_pulsars=self.n_pulsars,
                                 n_points=n_pts), \
            span("gw.common.lnlike_grid", n_pulsars=self.n_pulsars,
                 n_points=n_pts, sharded=mesh is not None,
                 kron=self._kron):
            out, n_bad_dev = fn(*args.values())
        surf = np.asarray(out)[:n_pts].reshape(aa.shape)
        n_bad = int(n_bad_dev)
        if n_bad:
            import warnings

            if _guard.enabled():
                telemetry.counter_add("guard.trips")
                telemetry.counter_add("guard.trip.gw_lnlike_grid",
                                      n_bad)
            warnings.warn(
                f"lnlike_grid: {n_bad}/{surf.size} non-finite grid "
                "points (degenerate prior or corrupted inputs)")
        return surf
