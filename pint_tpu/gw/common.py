"""Common red process (CRN/GWB) likelihood across a pulsar array.

The cross-pulsar extension of the single-pulsar Woodbury likelihood
(van Haasteren & Levin, arXiv:1107.5366): a gravitational-wave
background adds, on top of every pulsar's own noise, a shared power-law
Fourier process whose cross-pulsar covariance is the overlap-reduction
function.  Over the STACKED residual vector of the whole array the
covariance is

    C = diag(sigma^2) + U Phi U^T,

with U the block-diagonal concatenation of every pulsar's noise basis
followed by every pulsar's common-frequency GW Fourier basis, and Phi
block-structured: diagonal per-pulsar noise weights, plus a dense GWB
sector ``Gamma (x) diag(phi_gw)`` (Kronecker of the ORF matrix with the
power-law spectrum).  That dense-prior form goes through the SAME
:func:`pint_tpu.linalg.woodbury_chi2_logdet` solver as every
single-pulsar fit — ``phi`` is simply 2-D — so the per-pulsar and PTA
likelihoods share one code path.

The Fourier machinery is the one implementation in
:mod:`pint_tpu.models.noise` (``fourier_basis`` / ``powerlaw`` /
``toa_fourier_basis``); the GW bases of all pulsars are evaluated at
COMMON frequencies k/T over the array-wide span, on the absolute TDB
time axis, so the process is phase-coherent across pulsars.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import faults as _faults
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.gw.orf import orf_matrix, pulsar_positions
from pint_tpu.linalg import woodbury_chi2_logdet
from pint_tpu.models.noise import powerlaw, toa_fourier_basis
from pint_tpu.residuals import MEAN_OFFSET_WEIGHT, Residuals
from pint_tpu.telemetry import span

__all__ = ["PulsarGWData", "build_pulsar_data", "common_tspan_s",
           "CommonProcess", "gwb_phi"]

#: pad-row sigma [s] for the padded per-pulsar stacks: weight 1e-32,
#: in line with compile_cache.PAD_ERROR_US (1e22 us) — sigma^2 = 1e32
#: survives the TPU float32-pair f64 emulation (1e30 s would square to
#: 1e60 and saturate the high word)
PAD_SIGMA_S = 1e16


def common_tspan_s(toas_list) -> float:
    """Array-wide observing span [s]: max - min TDB second over every
    pulsar's TOAs — the T whose k/T harmonics the common process
    lives on."""
    lo = min(float(t.ticks.min()) for t in toas_list) / 2**32
    hi = max(float(t.ticks.max()) for t in toas_list) / 2**32
    return hi - lo


def gwb_phi(freqs, amp, gamma, df):
    """Per-mode GWB prior weights [s^2]: the shared power-law PSD
    integrated over one frequency bin — the same
    :func:`pint_tpu.models.noise.powerlaw` convention every intrinsic
    red-noise component uses."""
    return powerlaw(freqs, amp, gamma) * df


class PulsarGWData(NamedTuple):
    """One pulsar's ingredients for the cross-correlation engine, all
    concrete numpy/jax arrays at the model's current parameter values."""

    r: np.ndarray       # (n,) time residuals [s], mean-subtracted
    sigma: np.ndarray   # (n,) noise-scaled uncertainties [s]
    U: np.ndarray       # (n, nb) own noise basis + offset/timing cols
    phi: np.ndarray     # (nb,) own basis weights [s^2]
    F: np.ndarray       # (n, 2*nmodes) common-frequency GW basis
    name: str


def _timing_design(resid: Residuals) -> np.ndarray:
    """Column-normalized timing-model design matrix (n, n_free) of one
    pulsar, for marginalizing the fitted timing model out of the GW
    statistics (the van Haasteren G-matrix, realized as basis columns
    at MEAN_OFFSET_WEIGHT prior variance).  Eager jacfwd — no XLA
    compile is triggered at build time.

    free_timing_params, NOT free_params: a free noise parameter (EFAC
    etc.) has a pure-roundoff residual derivative (~1e-22 column norm
    through the weighted mean) that unit normalization would amplify
    into an arbitrary full-magnitude direction projected out of every
    GW statistic — the same reason the fitters' design matrices
    exclude noise parameters."""
    names = list(resid.model.free_timing_params)
    n = len(resid.toas)
    if not names:
        return np.zeros((n, 0))
    base = resid._values()
    data = resid._data()

    def f(vec):
        values = dict(base)
        for i, k in enumerate(names):
            values[k] = vec[i]
        return resid.time_resids_at(values, data)

    vec0 = jnp.asarray([float(resid.model.values[k]) for k in names])
    J = np.asarray(jax.jacfwd(f)(vec0))
    norm = np.linalg.norm(J, axis=0)
    norm[norm == 0.0] = 1.0
    return J / norm


def build_pulsar_data(
    pairs: Optional[Sequence[Tuple]] = None,
    *,
    batch=None,
    nmodes: int = 10,
    tspan_s: Optional[float] = None,
    marginalize_timing: bool = True,
) -> Tuple[List[PulsarGWData], np.ndarray, np.ndarray, float,
           List[Residuals]]:
    """Assemble every pulsar's (r, sigma, U, phi, F) plus the array
    geometry.

    pairs: ``[(TimingModel, TOAs), ...]``; or pass ``batch=`` a
    :class:`pint_tpu.parallel.PTABatch` to reuse its prepared models.
    Returns ``(data_list, positions (N, 3), freqs (2*nmodes,), df,
    resids)`` — the :class:`Residuals` list rides along so callers can
    reach the prepared models (noise layout metadata) without a second
    prepare pass.
    """
    if batch is not None:
        resids = list(batch.resids)
        models = [p.model for p in batch.prepareds]
    elif pairs:
        resids = [Residuals(t, m, track_mode="nearest")
                  for m, t in pairs]
        models = [r.model for r in resids]
    else:
        raise ValueError("build_pulsar_data needs pairs or batch=")
    if len(resids) < 2:
        raise ValueError(
            f"a cross-correlation analysis needs >= 2 pulsars, got "
            f"{len(resids)}")
    toas_list = [r.toas for r in resids]
    T = float(tspan_s) if tspan_s else common_tspan_s(toas_list)
    pos = pulsar_positions(models)
    out = []
    freqs = None
    for resid in resids:
        prep = resid.prepared
        values = resid._values()
        r = np.asarray(resid.time_resids, dtype=np.float64)
        sigma = np.asarray(resid.scaled_errors, dtype=np.float64)
        U = np.asarray(prep.noise_basis, dtype=np.float64)
        phi = np.asarray(prep.noise_weights_fn(values),
                         dtype=np.float64)
        n = len(resid.toas)
        cols = [U, np.ones((n, 1))]
        ws = [phi, np.array([MEAN_OFFSET_WEIGHT])]
        if marginalize_timing:
            J = _timing_design(resid)
            cols.append(J)
            ws.append(np.full(J.shape[1], MEAN_OFFSET_WEIGHT))
        U_ext = np.concatenate(cols, axis=1)
        phi_ext = np.concatenate(ws)
        F, fgrid = toa_fourier_basis(resid.toas, nmodes, tspan_s=T)
        if freqs is None:
            freqs = fgrid
        out.append(PulsarGWData(
            r=r, sigma=sigma, U=U_ext, phi=phi_ext, F=F,
            name=str(resid.model.meta.get("PSR", "?"))))
    return out, pos, freqs, float(freqs[0]), resids


# --------------------------------------------------------------------------
# stacked CRN/GWB likelihood
# --------------------------------------------------------------------------

def _crn_lnlike_one(r, sigma, U_full, phi_noise, orf, freqs, df,
                    n_toa, log10_amp, gamma):
    """Log-likelihood of the stacked array under noise + an
    ORF-correlated common power-law process.  Pure function of dynamic
    arrays — one trace serves every same-shaped PTA.  Returns
    (lnlike, health) with health the on-device finiteness verdict
    (chi2, logdet) riding the same compiled program."""
    amp = 10.0 ** log10_amp
    phi_gw = gwb_phi(freqs, amp, gamma, df)
    kn = phi_noise.shape[0]
    ktot = U_full.shape[1]
    gw_block = jnp.kron(orf, jnp.diag(phi_gw))
    phi_dense = jnp.zeros((ktot, ktot))
    phi_dense = phi_dense.at[:kn, :kn].set(jnp.diag(phi_noise))
    phi_dense = phi_dense.at[kn:, kn:].set(gw_block)
    chi2, logdet = woodbury_chi2_logdet(r, sigma, U_full, phi_dense)
    lnl = -0.5 * (chi2 + logdet) - 0.5 * n_toa * jnp.log(2.0 * jnp.pi)
    health = (jnp.isfinite(chi2), jnp.isfinite(logdet))
    return lnl, health


_crn_lnlike_vec = jax.vmap(
    _crn_lnlike_one,
    in_axes=(None, None, None, None, None, None, None, None, 0, 0),
)


class CommonProcess:
    """The PTA likelihood with an ORF-correlated common red process.

    Timing parameters are held at each model's current values (their
    linearized freedom is marginalized through the design-matrix
    columns when ``marginalize_timing``); the two live parameters are
    the common process's ``(log10_amp, gamma)``.  ``orf``: 'hd' |
    'monopole' | 'dipole' | a callable — 'monopole'/'dipole' give the
    clock-error / ephemeris-error systematics fits of the standard PTA
    triage.

    Every jitted entry point routes through
    :func:`pint_tpu.compile_cache.shared_jit`, keyed purely on
    structure: a second same-shaped PTA performs zero new XLA
    compiles.
    """

    def __init__(self, pairs=None, *, batch=None, nmodes=10, orf="hd",
                 tspan_s=None, marginalize_timing=True,
                 _prebuilt=None):
        with span("gw.common.build", nmodes=nmodes,
                  orf=orf if isinstance(orf, str) else "custom"):
            if _prebuilt is not None:
                # per-pulsar data already assembled by a sibling
                # engine (OptimalStatistic.common_process) — skip the
                # second build_pulsar_data pass (and its per-pulsar
                # eager jacfwd timing-design sweep)
                data, pos, freqs, df = _prebuilt
            else:
                data, pos, freqs, df, _ = build_pulsar_data(
                    pairs, batch=batch, nmodes=nmodes,
                    tspan_s=tspan_s,
                    marginalize_timing=marginalize_timing)
            self.data = data
            self.names = [d.name for d in data]
            self.n_pulsars = len(data)
            self.nmodes = int(nmodes)
            self.pos = pos
            self.orf_kind = orf
            self.orf = _faults.corrupt_orf(
                jnp.asarray(np.asarray(orf_matrix(pos, orf))))
            self.freqs = jnp.asarray(freqs)
            self.df = jnp.float64(df)
            # stacked vectors (ragged concatenation — no padding)
            self.r = jnp.asarray(np.concatenate([d.r for d in data]))
            self.sigma = jnp.asarray(
                np.concatenate([d.sigma for d in data]))
            self.phi_noise = jnp.asarray(
                np.concatenate([d.phi for d in data]))
            n_tot = self.r.shape[0]
            kn = self.phi_noise.shape[0]
            m2 = 2 * self.nmodes
            U = np.zeros((n_tot, kn + self.n_pulsars * m2))
            row = col = 0
            for k, d in enumerate(data):
                n, nb = d.U.shape
                U[row:row + n, col:col + nb] = d.U
                U[row:row + n, kn + k * m2: kn + (k + 1) * m2] = d.F
                row += n
                col += nb
            self.U_full = jnp.asarray(U)
            self.n_toa_total = n_tot

    def _lnlike_jit(self):
        return _cc.shared_jit(_crn_lnlike_one,
                              key=("gw.common.lnlike",))

    def lnlike(self, log10_amp, gamma, check=True):
        """Log-likelihood at one (log10 amplitude, spectral index).

        check: a non-finite likelihood (degenerate prior past the
        dense-phi jitter, corrupted inputs) raises a structured
        :class:`pint_tpu.guard.FitDivergedError` instead of silently
        handing a sampler NaN; pass check=False for raw -inf/NaN
        semantics."""
        with span("gw.common.lnlike", n_pulsars=self.n_pulsars,
                  nmodes=self.nmodes):
            out, health = self._lnlike_jit()(
                self.r, self.sigma, self.U_full, self.phi_noise,
                self.orf, self.freqs, self.df,
                jnp.float64(self.n_toa_total),
                jnp.float64(log10_amp), jnp.float64(gamma))
            # the check honors the guard gate — PINT_TPU_GUARD=0
            # restores raw -inf/NaN semantics like check=False
            if check and _guard.enabled():
                telemetry.counter_add("guard.checks")
            if check and _guard.enabled() \
                    and not np.isfinite(float(out)):
                telemetry.counter_add("guard.trips")
                telemetry.counter_add("guard.trip.gw_lnlike")
                raise _guard.FitDivergedError(
                    "gw.common.lnlike",
                    health={"chi2_finite": bool(health[0]),
                            "logdet_finite": bool(health[1])},
                    detail=f"lnlike({log10_amp}, {gamma}) non-finite")
            return float(out)

    #: lnlike_grid partition rules: the two point-axis arrays ride the
    #: ``grid`` axis; every stacked-array/basis leaf is explicitly
    #: replicated (each device evaluates its grid points against the
    #: full array), so rule resolution covers EVERY leaf of the call
    _GRID_RULES = (
        (r"^(log10_amps|gammas)$", "grid"),
        (r"^(r|sigma|U_full|phi_noise|orf|freqs)$", None),
    )

    def lnlike_grid(self, log10_amps, gammas, mesh=None):
        """(A, G) log-likelihood surface over the outer product of the
        two 1-d grids — one vmapped program.  Non-finite grid points
        are counted (``guard.trip.gw_lnlike_grid``) and warned about,
        never silently returned as a clean-looking surface.

        mesh: a device mesh — the flattened point axis is padded to a
        device multiple (edge-repeated; the pad points are sliced off
        the returned surface) and sharded.  A 1-d mesh shards points
        over its single axis (the original ``grid`` contract); a
        MULTI-AXIS mesh (e.g. the 2-D ``pulsar x grid`` layout a
        full-PTA scan shares with ``PTABatch.chisq_grid``) shards the
        point axis over the product of ALL its axes, so the dense
        hyperparameter surface runs as one program across the whole
        pod slice with no idle sub-mesh.  The mesh participates in
        the jit key, so a second same-shaped sharded call compiles
        nothing and ``mesh=None`` behaves exactly as before."""
        from jax.sharding import PartitionSpec as P

        from pint_tpu.parallel import mesh as _mesh

        log10_amps = np.atleast_1d(np.asarray(log10_amps, np.float64))
        gammas = np.atleast_1d(np.asarray(gammas, np.float64))
        aa, gg = np.meshgrid(log10_amps, gammas, indexing="ij")
        fn = _cc.shared_jit(
            _crn_lnlike_vec,
            key=("gw.common.lnlike_grid",) + _mesh.mesh_jit_key(mesh),
            fn_token="gw.common.lnlike_grid",
            label="gw.common.lnlike_grid"
                  + (":sharded" if mesh is not None else ""))
        fn.set_mesh(_mesh.mesh_desc(mesh))
        n_pts = aa.size
        amps_flat, gams_flat = (jnp.asarray(aa.ravel()),
                                jnp.asarray(gg.ravel()))
        args = {
            "r": self.r, "sigma": self.sigma, "U_full": self.U_full,
            "phi_noise": self.phi_noise, "orf": self.orf,
            "freqs": self.freqs, "df": self.df,
            "n_toa": jnp.float64(self.n_toa_total),
            "log10_amps": amps_flat, "gammas": gams_flat,
        }
        if mesh is not None:
            names = tuple(str(n) for n in mesh.axis_names)
            if len(names) == 1:
                ndev = _mesh.axis_size(mesh, "grid")
                point_spec = P("grid")
            else:
                # multi-axis mesh: the point axis rides EVERY axis
                # (one PartitionSpec dim over the axis tuple), so the
                # full device product serves the scan
                ndev = int(mesh.devices.size)
                point_spec = P(names)
            n_pad = _mesh.pad_to_multiple(n_pts, ndev)
            _mesh.record_pad_waste("grid", n_pts, n_pad)
            for k in ("log10_amps", "gammas"):
                args[k] = _mesh.pad_leading(args[k], n_pad,
                                            mode="edge")
            rules = tuple(
                (pat, point_spec if ax else None)
                for pat, ax in self._GRID_RULES)
            args = _mesh.shard_args(mesh, rules, args)
        with telemetry.run_scope("lnlike_grid",
                                 n_pulsars=self.n_pulsars,
                                 n_points=n_pts), \
            span("gw.common.lnlike_grid", n_pulsars=self.n_pulsars,
                 n_points=n_pts, sharded=mesh is not None):
            out, _health = fn(*args.values())
        surf = np.asarray(out)[:n_pts].reshape(aa.shape)
        n_bad = int(np.count_nonzero(~np.isfinite(surf)))
        if n_bad:
            import warnings

            if _guard.enabled():
                telemetry.counter_add("guard.trips")
                telemetry.counter_add("guard.trip.gw_lnlike_grid",
                                      n_bad)
            warnings.warn(
                f"lnlike_grid: {n_bad}/{surf.size} non-finite grid "
                "points (degenerate prior or corrupted inputs)")
        return surf
