"""Gradient-based GWB posterior sampling — the "from grid to
posterior" engine (ROADMAP item 3; the discovery-style inference
framework of arXiv 2607.06834).

The 2-D CRN grid of :class:`pint_tpu.gw.common.CommonProcess` fixes
every pulsar's intrinsic noise and scans two hyperparameters.  This
module makes the stacked-array likelihood a first-class gradient
target instead: :class:`GWBPosterior` maps a parameter vector
``theta = (gwb log10_A, gwb gamma, per-pulsar sampled noise params)``
to the log posterior with ``jax.grad`` flowing through the
kron-structured Woodbury solve (:func:`pint_tpu.linalg
.kron_chi2_logdet_pre`), and :func:`run_nuts` samples it with every
chain vmapped into ONE shared-jit scan program.

Sampler design (and what it deliberately is not): ``run_nuts`` is the
NUTS-class gradient sampler in its static-trajectory form —
multi-step leapfrog trajectories with uniformly jittered length,
endpoint Metropolis acceptance, dual-averaging step-size adaptation
(Hoffman & Gelman 2014's algorithm 5) inside the scan, and a diagonal
metric from per-parameter scales.  The no-U-turn DYNAMIC termination
is deliberately not implemented: per-chain data-dependent trajectory
lengths under ``vmap`` run every chain to the worst case anyway while
breaking the fixed-shape scan that gives zero recompiles across
chains and chunks — the static-jittered trajectory keeps the gradient
core, the adaptation, and the shapes.

Performance structure: when no sampled parameter touches sigma (the
amp/gamma + per-pulsar red-noise configuration of the flagship run),
the per-pulsar weighted grams are precomputed ONCE host-side
(:func:`pint_tpu.linalg.kron_gram_precompute` — the same frozen
noise-gram idea the PR-5 fit path uses) and ride the chunk program as
dynamic data leaves, so one posterior gradient costs
O(P nb^3 + (P m2)^3) with no O(N_toa) contraction at all.  Sampled
white-noise parameters (EFAC etc.) switch the gram into the trace —
same algebra, the gradient simply flows through it.

Iteration records ride the scan's ys through
``compile_cache.iterate_fixed(trace_of=)`` (the PR-10 flight-recorder
hook): they ARE the chain, so they are always materialized; the
``$PINT_TPU_ITER_TRACE`` gate controls only whether per-draw
``iter_trace`` telemetry records are additionally emitted host-side.
Checkpoint/resume follows the PR-4 contract (atomic writes validated
against the posterior fingerprint; a killed run loses at most one
chunk).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import faults as _faults
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.gw.common import CommonProcess, gwb_phi
from pint_tpu.linalg import (KronPhi, kron_chi2_logdet_pre,
                             kron_gram_precompute,
                             woodbury_chi2_logdet)
from pint_tpu.telemetry import span

__all__ = ["GWBPosterior", "run_nuts", "NUTSResult",
           "DEFAULT_BOUNDS", "DEFAULT_SCALES"]

#: prior bounds per parameter name (uniform prior; the posterior peak
#: therefore coincides with the likelihood peak, which is what the
#: grid-consistency acceptance compares).  Overridable per call.
DEFAULT_BOUNDS = {
    "gwb_log10_A": (-18.0, -11.0),
    "gwb_gamma": (0.0, 7.0),
    "TNREDAMP": (-20.0, -10.0),
    "TNREDGAM": (0.0, 7.0),
}
_FALLBACK_BOUNDS = (-30.0, 30.0)

#: diagonal-metric scales per parameter name (the sampler's mass
#: matrix is diag(1/scale^2); log-amplitudes and spectral indices are
#: already O(1)-scaled coordinates, which is why a fixed diagonal
#: metric works where the raw-parameter MCMC needed per-param ball
#: scales).  Overridable per call.
DEFAULT_SCALES = {
    "gwb_log10_A": 0.3,
    "gwb_gamma": 0.4,
    "TNREDAMP": 0.4,
    "TNREDGAM": 0.5,
}
_FALLBACK_SCALE = 0.2


def _probe_changes(fn, values, name, delta):
    """Host-side build-time probe: does perturbing ``values[name]`` by
    ``delta`` change ``fn(values)``?  Classifies a sampled parameter
    as sigma-affecting (white noise) vs basis-weight-affecting (red /
    ECORR) without hard-coding component knowledge."""
    base = np.asarray(fn(values))
    pert = dict(values)
    pert[name] = float(values[name]) + delta
    return not np.allclose(base, np.asarray(fn(pert)), rtol=0.0,
                           atol=0.0, equal_nan=True)


class GWBPosterior:
    """The differentiable stacked-array GWB posterior.

    theta layout: ``[gwb_log10_A, gwb_gamma] + [one entry per
    (pulsar, name) in sample order]`` — ``sample`` names per-pulsar
    noise parameters (default: the power-law red-noise amplitude and
    index) included for every pulsar whose model carries them.

    Built on a :class:`~pint_tpu.gw.common.CommonProcess` constructed
    from pairs/batch (NOT the ``_prebuilt`` fast path — the per-pulsar
    prepared models supply the in-trace noise-weight maps).  The
    likelihood path follows the CommonProcess's kron/dense selection:
    kron (default) evaluates through the structured solver; dense
    exists for the gradient-equivalence tests.
    """

    def __init__(self, crn: CommonProcess,
                 sample=("TNREDAMP", "TNREDGAM"), bounds=None,
                 scales=None):
        if crn.resids is None:
            raise ValueError(
                "GWBPosterior needs a CommonProcess built from "
                "pairs/batch (resids attached); the _prebuilt fast "
                "path carries no prepared models")
        self.crn = crn
        self.kron = bool(crn._kron)
        self.param_names = ["gwb_log10_A", "gwb_gamma"]
        self.noise_params = []  # (pulsar_idx, param_name)
        self._base_values = []
        sigma_dynamic = False
        for k, resid in enumerate(crn.resids):
            self._base_values.append(
                {n: jnp.float64(float(v))
                 for n, v in resid.model.values.items()})
            prep = resid.prepared
            for name in sample:
                if name not in resid.model.values:
                    continue
                self.noise_params.append((k, name))
                self.param_names.append(f"{crn.names[k]}:{name}")
                vals = {n: float(v)
                        for n, v in resid.model.values.items()}
                if _probe_changes(
                        lambda v: prep.scaled_sigma_fn(v), vals,
                        name, 1e-3):
                    sigma_dynamic = True
        self.ndim = len(self.param_names)
        self.sigma_dynamic = sigma_dynamic
        # per-pulsar noise-weight column counts inside the extended
        # basis (U_ext = [noise basis | offset | timing cols]): the
        # sampled weights replace exactly the leading nb_noise entries
        # of each padded phi row
        self._nb_noise = [
            int(np.asarray(r.prepared.noise_basis).shape[1])
            for r in crn.resids]
        b = dict(DEFAULT_BOUNDS)
        b.update(bounds or {})
        s = dict(DEFAULT_SCALES)
        s.update(scales or {})

        def look(table, full_name, fallback):
            short = full_name.split(":")[-1]
            return table.get(full_name, table.get(short, fallback))

        self.bounds = np.asarray(
            [look(b, n, _FALLBACK_BOUNDS) for n in self.param_names],
            dtype=np.float64)
        self.scales = np.asarray(
            [look(s, n, _FALLBACK_SCALE) for n in self.param_names],
            dtype=np.float64)
        kd = crn.kron_data
        self._data = {
            "orf": crn.orf, "freqs": crn.freqs, "df": crn.df,
            "n_toa": jnp.float64(crn.n_toa_total),
            "phi0": kd["phi_noise"],
            "lo": jnp.asarray(self.bounds[:, 0]),
            "hi": jnp.asarray(self.bounds[:, 1]),
        }
        if self.kron and not sigma_dynamic:
            # the frozen noise-gram reuse: every draw of every chain
            # shares ONE set of per-pulsar weighted grams
            self._data["gram"] = kron_gram_precompute(
                kd["r"], kd["sigma"], kd["U"], kd["F"],
                valid=kd["valid"])
        elif self.kron:
            self._data.update(
                {k: kd[k] for k in ("r", "sigma", "U", "F", "valid")})
        # scales and bounds are part of the identity: the sampler's
        # inv_mass (scales^2) is CLOSED OVER by the chunk program (a
        # static of the trace — shared_jit's key-must-cover contract),
        # and a checkpoint written under different bounds must be
        # refused, not resumed into a mixed-bounds chain
        self.fingerprint = _cc.fingerprint((
            "gw.hmc", self.param_names, self.kron,
            self.sigma_dynamic,
            np.asarray(self.scales), np.asarray(self.bounds),
            np.asarray(kd["r"]), np.asarray(kd["sigma"]),
            np.asarray(kd["phi_noise"]), np.asarray(crn.orf)))

    # -- theta -> model ingredients ------------------------------------------

    def _values_at(self, theta, k):
        """Pulsar k's values dict with its sampled params overridden."""
        values = dict(self._base_values[k])
        for j, (pi, name) in enumerate(self.noise_params):
            if pi == k:
                values[name] = theta[2 + j]
        return values

    def _phi_noise_at(self, theta, phi0):
        """(P, nb) padded noise-weight rows at ``theta`` — each
        pulsar's prepared ``noise_weights_fn`` re-evaluated in-trace
        (the host loop unrolls over pulsars at trace build), scattered
        over the fixed offset/timing-column tail of ``phi0``."""
        rows = []
        for k, resid in enumerate(self.crn.resids):
            w = resid.prepared.noise_weights_fn(self._values_at(theta,
                                                               k))
            rows.append(phi0[k].at[:self._nb_noise[k]].set(w))
        return jnp.stack(rows)

    def _sigma_at(self, theta, sigma0):
        """(P, N) padded sigma rows at ``theta`` (only reached when a
        sampled parameter is sigma-affecting)."""
        rows = []
        for k, resid in enumerate(self.crn.resids):
            s = resid.prepared.scaled_sigma_fn(self._values_at(theta,
                                                               k))
            rows.append(sigma0[k].at[:s.shape[0]].set(s))
        return jnp.stack(rows)

    # -- the log posterior ----------------------------------------------------

    def lnprob(self, theta, data):
        """Log posterior (uniform prior inside ``bounds``) — a pure
        traceable function of (theta, data); ``jax.grad`` flows
        through the kron solve into every sampled parameter.  Outside
        the bounds the value is -inf and the likelihood is evaluated
        at the clipped point (finite everywhere, so the gradient the
        leapfrog uses at the boundary stays usable)."""
        lo, hi = data["lo"], data["hi"]
        inside = jnp.all((theta >= lo) & (theta <= hi))
        th = jnp.clip(theta, lo, hi)
        amp = 10.0 ** th[0]
        phi_gw = gwb_phi(data["freqs"], amp, th[1], data["df"])
        phi_noise = self._phi_noise_at(th, data["phi0"])
        kp = KronPhi(orf=data["orf"], phi_gw=phi_gw,
                     phi_noise=phi_noise)
        if self.kron and not self.sigma_dynamic:
            chi2, logdet = kron_chi2_logdet_pre(data["gram"], kp)
        elif self.kron:
            sigma = self._sigma_at(th, data["sigma"])
            gram = kron_gram_precompute(data["r"], sigma, data["U"],
                                        data["F"],
                                        valid=data["valid"])
            chi2, logdet = kron_chi2_logdet_pre(gram, kp)
        else:
            # the dense reference path (gradient-equivalence tests):
            # the same theta-dependent prior, materialized (K, K)
            chi2, logdet = self._dense_chi2_logdet(th, kp)
        lnl = (-0.5 * (chi2 + logdet)
               - 0.5 * data["n_toa"] * jnp.log(2.0 * jnp.pi))
        return jnp.where(inside, lnl, -jnp.inf)

    def _dense_chi2_logdet(self, th, kp):
        """Dense-path twin of the kron evaluation: stacked ragged
        arrays, materialized prior, one (K, K) factorization — the
        independent reference the kron gradients are verified
        against."""
        crn = self.crn
        phi_parts, sig_parts = [], []
        for k, resid in enumerate(crn.resids):
            values = self._values_at(th, k)
            d = crn.data[k]
            w = resid.prepared.noise_weights_fn(values)
            nb_n = self._nb_noise[k]
            phi_parts.append(jnp.concatenate(
                [w, jnp.asarray(d.phi[nb_n:])]))
            if self.sigma_dynamic:
                sig_parts.append(resid.prepared.scaled_sigma_fn(values))
            else:
                sig_parts.append(jnp.asarray(d.sigma))
        phi_noise = jnp.concatenate(phi_parts)
        sigma = jnp.concatenate(sig_parts)
        kn = phi_noise.shape[0]
        ktot = crn.U_full.shape[1]
        gw_block = jnp.kron(kp.orf, jnp.diag(kp.phi_gw))
        phi_dense = jnp.zeros((ktot, ktot))
        phi_dense = phi_dense.at[:kn, :kn].set(jnp.diag(phi_noise))
        phi_dense = phi_dense.at[kn:, kn:].set(gw_block)
        return woodbury_chi2_logdet(crn.r, sigma, crn.U_full,
                                    phi_dense)

    def data(self):
        """The dynamic data pytree of the chunk program."""
        return self._data

    def center(self):
        """A reasonable chain center: bounds midpoint for the GWB
        hyperparameters, each model's CURRENT value for sampled
        per-pulsar parameters (clipped into bounds)."""
        c = np.empty(self.ndim)
        c[0] = -14.5
        c[1] = 13.0 / 3.0
        for j, (k, name) in enumerate(self.noise_params):
            c[2 + j] = float(self.crn.resids[k].model.values[name])
        return np.clip(c, self.bounds[:, 0] + 1e-6,
                       self.bounds[:, 1] - 1e-6)

    def initial_chains(self, n_chains, seed=0, center=None,
                       ball=0.1):
        """(n_chains, ndim) starting points: a scaled Gaussian ball
        around :meth:`center`, clipped inside the prior support."""
        rng = np.random.default_rng(seed)
        c = self.center() if center is None else np.asarray(center)
        x0 = c[None, :] + ball * self.scales[None, :] * \
            rng.standard_normal((int(n_chains), self.ndim))
        return np.clip(x0, self.bounds[None, :, 0] + 1e-9,
                       self.bounds[None, :, 1] - 1e-9)


class NUTSResult(NamedTuple):
    """What :func:`run_nuts` returns."""

    samples: np.ndarray       # (num_samples, n_chains, ndim)
    lnprob: np.ndarray        # (num_samples, n_chains)
    accept_rate: float        # post-warmup mean acceptance
    step_size: np.ndarray     # (n_chains,) adapted step sizes
    divergences: int          # post-warmup divergent transitions
    warmup_samples: np.ndarray  # (num_warmup, n_chains, ndim)

    def flat(self):
        """(num_samples * n_chains, ndim) flattened posterior."""
        s = np.asarray(self.samples)
        return s.reshape(-1, s.shape[-1])

    def max_posterior(self):
        """(theta, lnp) at the best sampled point."""
        lnp = np.asarray(self.lnprob)
        i, j = np.unravel_index(np.argmax(lnp), lnp.shape)
        return np.asarray(self.samples[i, j]), float(lnp[i, j])


# dual-averaging constants (Hoffman & Gelman 2014, algorithm 5)
_DA_GAMMA = 0.05
_DA_T0 = 10.0
_DA_KAPPA = 0.75
#: energy-error threshold marking a transition divergent
_DIVERGENCE_DH = 1000.0


def _chunk_body(lnprob_v, inv_mass, n_leapfrog, target_accept,
                constrain):
    """Build the one-draw transition ``carry -> carry`` (vmapped over
    chains) the chunk scan iterates.  Everything data-dependent
    arrives through the carry/data pytrees; the closure holds only
    structure (ndim-independent python floats and the vmapped
    posterior)."""

    def one_chain(key, x, lnp, g, log_eps, hbar, log_eps_bar, mu,
                  it, data, warmup):
        k_p, k_len, k_acc, k_next = jax.random.split(key, 4)
        adapting = it < warmup
        eps = jnp.where(adapting, jnp.exp(log_eps),
                        jnp.exp(log_eps_bar))
        p0 = jax.random.normal(k_p, x.shape) / jnp.sqrt(inv_mass)
        n_steps = jax.random.randint(k_len, (), 1, n_leapfrog + 1)

        def leap(carry, i):
            xi, pi, gi = carry
            active = i < n_steps
            ph = pi + 0.5 * eps * gi
            xn = xi + eps * inv_mass * ph
            lnp_n, gn = jax.value_and_grad(
                lambda q: lnprob_v(q, data))(xn)
            pn = ph + 0.5 * eps * gn
            new = (jnp.where(active, xn, xi),
                   jnp.where(active, pn, pi),
                   jnp.where(active, gn, gi))
            return new, jnp.where(active, lnp_n, -jnp.inf)

        (x1, p1, g1), lnps = jax.lax.scan(
            leap, (x, p0, g), jnp.arange(n_leapfrog))
        # the endpoint's log posterior is the last ACTIVE step's ys
        # entry (inactive steps never move x) — no extra evaluation
        lnp1 = jnp.take(lnps, n_steps - 1)
        h0 = -lnp + 0.5 * jnp.sum(p0 * p0 * inv_mass)
        h1 = -lnp1 + 0.5 * jnp.sum(p1 * p1 * inv_mass)
        dh = h0 - h1
        acc_prob = jnp.where(jnp.isfinite(dh),
                             jnp.exp(jnp.minimum(0.0, dh)), 0.0)
        # a trajectory that EXITS the prior support (lnp1 = -inf) is
        # an ordinary rejection, not an integrator failure — only a
        # finite-endpoint energy blow-up (or NaN) counts as divergent,
        # so the diagnostic means what samplers mean by it
        divergent = jnp.logical_or(
            jnp.isnan(dh),
            jnp.logical_and(-dh > _DIVERGENCE_DH,
                            jnp.isfinite(lnp1)))
        accept = jnp.log(jax.random.uniform(k_acc)) < dh
        x_new = jnp.where(accept, x1, x)
        lnp_new = jnp.where(accept, lnp1, lnp)
        g_new = jnp.where(accept, g1, g)
        # dual averaging (warmup only; frozen to the averaged step
        # afterwards — all branches traced, one program)
        t = it + 1.0
        hbar_n = ((1.0 - 1.0 / (t + _DA_T0)) * hbar
                  + (target_accept - acc_prob) / (t + _DA_T0))
        log_eps_n = mu - jnp.sqrt(t) / _DA_GAMMA * hbar_n
        eta = t ** (-_DA_KAPPA)
        log_eps_bar_n = eta * log_eps_n + (1.0 - eta) * log_eps_bar
        hbar = jnp.where(adapting, hbar_n, hbar)
        log_eps = jnp.where(adapting, log_eps_n, log_eps)
        log_eps_bar = jnp.where(adapting, log_eps_bar_n, log_eps_bar)
        return (k_next, x_new, lnp_new, g_new, log_eps, hbar,
                log_eps_bar, acc_prob, divergent, eps)

    v_chain = jax.vmap(
        one_chain,
        in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, None))

    def body(carry):
        (keys, x, lnp, g, log_eps, hbar, log_eps_bar, mu, acc, div,
         eps_used, it, data, warmup) = carry
        (keys, x, lnp, g, log_eps, hbar, log_eps_bar, acc, div,
         eps_used) = v_chain(keys, x, lnp, g, log_eps, hbar,
                             log_eps_bar, mu, it, data, warmup)
        if constrain is not None:
            x = constrain(x)
        return (keys, x, lnp, g, log_eps, hbar, log_eps_bar, mu, acc,
                div, eps_used, it + 1.0, data, warmup)

    return body


def _draw_record(_prev, new):
    """Per-draw flight-recorder record — also the chain itself (the
    scan's ys ARE the samples, so the record is always materialized;
    the $PINT_TPU_ITER_TRACE gate controls only host-side telemetry
    emission)."""
    (_keys, x, lnp, _g, _le, _hb, _leb, _mu, acc, div, eps_used,
     _it, _data, _warmup) = new
    return {"theta": x, "lnp": lnp, "accept": acc,
            "divergent": div, "eps": eps_used,
            "ok": jnp.all(jnp.isfinite(x), axis=-1)
            & jnp.isfinite(lnp)}


def run_nuts(posterior: GWBPosterior, *, num_warmup=300,
             num_samples=500, n_chains=4, seed=0, x0=None,
             num_leapfrog=12, target_accept=0.8, step_size0=0.02,
             chunk=None, mesh=None, checkpoint=None):
    """Sample a :class:`GWBPosterior`: every chain one row of ONE
    vmapped scan program, dual-averaged step size, jittered-length
    leapfrog trajectories (module docstring for exactly what this is
    and is not).

    The run is cut into equal ``chunk``-draw scans of one shared-jit
    program (structure in the key, everything else dynamic): after
    the first chunk compiles, every further chunk of every chain —
    warmup or sampling, fresh or resumed — performs ZERO new XLA
    compiles (telemetry-counter regression-tested).  ``mesh`` holds
    the chain axis on the ``walker`` mesh axis via the shared
    chain-axis rule (:func:`pint_tpu.parallel.mesh
    .chain_constrainer`); n_chains must divide accordingly.

    checkpoint: optional path — samples + full sampler state are
    atomic-written after every chunk (PR-4 contract, validated
    against the posterior fingerprint), and an existing file resumes
    mid-run losing at most one chunk (``faults`` kill-site
    ``hmc.chunk`` exercises exactly that in the chaos tests)."""
    from pint_tpu.parallel import mesh as _mesh

    total = int(num_warmup) + int(num_samples)
    if chunk is None:
        chunk = min(64, total)
    chunk = max(1, int(chunk))
    n_chunks = -(-total // chunk)
    padded_total = n_chunks * chunk
    constrain = _mesh.chain_constrainer(
        mesh, n_chains, requested_by="run_nuts: n_chains")
    scan_flag = _cc.scan_iters_default()
    lnprob = posterior.lnprob
    data = dict(posterior.data())
    warmup_f = jnp.float64(num_warmup)
    inv_mass = jnp.asarray(posterior.scales**2)
    nd = posterior.ndim

    if x0 is None:
        x0 = posterior.initial_chains(n_chains, seed=seed)
    x0 = jnp.asarray(x0, dtype=jnp.float64)
    if x0.shape != (n_chains, nd):
        raise ValueError(
            f"run_nuts: x0 shape {x0.shape} != (n_chains, ndim) = "
            f"({n_chains}, {nd})")

    body = _chunk_body(lnprob, inv_mass, int(num_leapfrog),
                       float(target_accept), constrain)

    def chunk_program(carry):
        return _cc.iterate_fixed(body, carry, chunk, scan=scan_flag,
                                 trace_of=_draw_record)

    runner = _cc.shared_jit(
        chunk_program,
        key=("gw.hmc.chunk", int(chunk), int(num_leapfrog),
             float(target_accept), scan_flag, posterior.kron)
            + _mesh.mesh_jit_key(mesh),
        fn_token=("gw.hmc", posterior.fingerprint),
        label="gw.hmc.chunk" + (":sharded" if mesh is not None
                                else ""))
    runner.set_mesh(_mesh.mesh_desc(mesh))

    fp = _cc.fingerprint((posterior.fingerprint, int(n_chains),
                          int(nd), int(num_leapfrog), int(chunk),
                          int(num_warmup), int(num_samples),
                          float(step_size0), float(target_accept)))

    mu0 = jnp.full(n_chains, math.log(10.0 * float(step_size0)))
    thetas, lnps, accs, divs, epss = [], [], [], [], []
    done_chunks = 0
    carry = None
    if checkpoint is not None:
        loaded = _guard.load_checkpoint(checkpoint, fingerprint=fp)
        if loaded is not None:
            arrays, _head = loaded
            done_chunks = int(arrays["done_chunks"][()])
            thetas = [arrays["theta"]]
            lnps = [arrays["lnp"]]
            accs = [arrays["accept"]]
            divs = [arrays["divergent"]]
            epss = [arrays["eps"]]
            carry = (jnp.asarray(arrays["keys"]),
                     jnp.asarray(arrays["x"]),
                     jnp.asarray(arrays["c_lnp"]),
                     jnp.asarray(arrays["g"]),
                     jnp.asarray(arrays["log_eps"]),
                     jnp.asarray(arrays["hbar"]),
                     jnp.asarray(arrays["log_eps_bar"]),
                     mu0,
                     jnp.asarray(arrays["acc"]),
                     jnp.asarray(arrays["div"]),
                     jnp.asarray(arrays["eps_state"]),
                     jnp.float64(float(arrays["it"][()])),
                     data, warmup_f)
            telemetry.counter_add("hmc.resumes")
    if carry is None:
        # fresh start only: the initial posterior + gradient over all
        # chains (a resume restores these from the checkpoint)
        keys = jax.random.split(jax.random.PRNGKey(int(seed)),
                                n_chains)
        lnp0, g0 = jax.vmap(jax.value_and_grad(
            lambda q: lnprob(q, data)))(x0)
        carry = (keys, x0, lnp0, g0,
                 jnp.full(n_chains, math.log(float(step_size0))),
                 jnp.zeros(n_chains),
                 jnp.full(n_chains, math.log(float(step_size0))),
                 mu0, jnp.zeros(n_chains),
                 jnp.zeros(n_chains, bool),
                 jnp.full(n_chains, float(step_size0)),
                 jnp.float64(0.0), data, warmup_f)

    iter_trace = _cc.iter_trace_default()
    with telemetry.run_scope("hmc", chains=int(n_chains),
                             ndim=int(nd), total=total,
                             kron=posterior.kron), \
            span("gw.hmc.run", chains=int(n_chains), total=total):
        for _ci in range(done_chunks, n_chunks):
            carry, rec = runner(carry)
            thetas.append(np.asarray(rec["theta"]))
            lnps.append(np.asarray(rec["lnp"]))
            accs.append(np.asarray(rec["accept"]))
            divs.append(np.asarray(rec["divergent"]))
            epss.append(np.asarray(rec["eps"]))
            # a partial final chunk still scans `chunk` draws (fixed
            # shapes = zero recompiles) but only the first `real` are
            # returned — the ledger reports completed draws, never
            # the padded surplus
            real = min(chunk, total - _ci * chunk)
            telemetry.counter_add("hmc.draws", real * n_chains)
            telemetry.counter_add("hmc.chunks")
            n_div = int(np.sum(divs[-1][:real]))
            if n_div:
                telemetry.counter_add("hmc.divergences", n_div)
            if iter_trace:
                base = len(thetas[:-1]) and sum(
                    t.shape[0] for t in thetas[:-1])
                for i in range(real):
                    telemetry.emit({
                        "type": "iter_trace", "program": "gw.hmc",
                        "i": int(base + i),
                        "lnp": float(np.median(lnps[-1][i])),
                        "lnp_min": float(np.min(lnps[-1][i])),
                        "lnp_max": float(np.max(lnps[-1][i])),
                        "accept": float(np.mean(accs[-1][i])),
                        "eps": float(np.mean(epss[-1][i])),
                        "n_divergent": int(np.sum(divs[-1][i])),
                        "ok": bool(np.all(
                            np.isfinite(thetas[-1][i]))),
                    })
            if checkpoint is not None:
                (keys_c, x_c, lnp_c, g_c, le_c, hb_c, leb_c, _mu,
                 acc_c, div_c, eps_c, it_c, _d, _w) = carry
                _guard.save_checkpoint(
                    checkpoint,
                    {"theta": np.concatenate(thetas, axis=0),
                     "lnp": np.concatenate(lnps, axis=0),
                     "accept": np.concatenate(accs, axis=0),
                     "divergent": np.concatenate(divs, axis=0),
                     "eps": np.concatenate(epss, axis=0),
                     "done_chunks": np.int64(_ci + 1),
                     "keys": np.asarray(keys_c),
                     "x": np.asarray(x_c),
                     "c_lnp": np.asarray(lnp_c),
                     "g": np.asarray(g_c),
                     "log_eps": np.asarray(le_c),
                     "hbar": np.asarray(hb_c),
                     "log_eps_bar": np.asarray(leb_c),
                     "acc": np.asarray(acc_c),
                     "div": np.asarray(div_c),
                     "eps_state": np.asarray(eps_c),
                     "it": np.float64(float(it_c))},
                    fingerprint=fp,
                    meta={"total": total, "chunk": chunk})
                _faults.maybe_kill("hmc.chunk")

    theta_all = np.concatenate(thetas, axis=0)[:padded_total]
    lnp_all = np.concatenate(lnps, axis=0)
    acc_all = np.concatenate(accs, axis=0)
    div_all = np.concatenate(divs, axis=0)
    eps_all = np.concatenate(epss, axis=0)
    nw = int(num_warmup)
    ns = int(num_samples)
    post = slice(nw, nw + ns)
    # chain health: the guard-gated host verdict (raw semantics with
    # $PINT_TPU_GUARD=0, like the ensemble sampler)
    if _guard.enabled():
        telemetry.counter_add("guard.checks")
        ok = (np.all(np.isfinite(theta_all[post]))
              and np.any(np.isfinite(lnp_all[post])))
        if not ok:
            telemetry.counter_add("guard.trips")
            telemetry.counter_add("guard.trip.hmc")
            raise _guard.FitDivergedError(
                "gw.hmc.run_nuts",
                health={"positions_finite": bool(
                    np.all(np.isfinite(theta_all[post]))),
                    "any_finite_lnp": bool(
                        np.any(np.isfinite(lnp_all[post])))},
                detail="HMC chains diverged (non-finite positions "
                       "or every draw at lnp=-inf)")
    return NUTSResult(
        samples=theta_all[post],
        lnprob=lnp_all[post],
        accept_rate=float(np.mean(acc_all[post])),
        step_size=np.asarray(eps_all[-1]),
        divergences=int(np.sum(div_all[post])),
        warmup_samples=theta_all[:nw],
    )
