"""Cross-pulsar gravitational-wave engine.

Layers (docs/gw.md):

- :mod:`pint_tpu.gw.orf` — overlap-reduction functions (Hellings–Downs,
  monopole, dipole) as dense (N, N) matrices of the array geometry;
- :mod:`pint_tpu.gw.common` — the common red process (CRN/GWB)
  likelihood: ORF-coupled cross-pulsar Fourier blocks through the
  dense-prior extension of :mod:`pint_tpu.linalg`'s Woodbury solver;
- :mod:`pint_tpu.gw.os` — the pair-wise optimal statistic, vmapped
  over all N(N-1)/2 pairs and shardable over a device mesh, plus the
  noise-marginalized variant vmapped over posterior draws;
- injection lives in :func:`pint_tpu.simulation.add_gwb` (HD-correlated
  Fourier coefficients across the whole array).
"""

from pint_tpu.gw.common import (CommonProcess, build_pulsar_data,
                                common_tspan_s, gwb_phi)
from pint_tpu.gw.hmc import (GWBPosterior, NUTSResult, run_nuts)
from pint_tpu.gw.orf import (angular_separation_matrix, dipole,
                             hellings_downs, monopole, orf_matrix,
                             pair_indices, pulsar_positions)
from pint_tpu.gw.os import GWB_GAMMA, OptimalStatistic, OSResult

__all__ = [
    "hellings_downs", "monopole", "dipole", "orf_matrix",
    "angular_separation_matrix", "pair_indices", "pulsar_positions",
    "CommonProcess", "build_pulsar_data", "common_tspan_s", "gwb_phi",
    "OptimalStatistic", "OSResult", "GWB_GAMMA",
    "GWBPosterior", "NUTSResult", "run_nuts",
]
