"""Chi^2 grids as one batched XLA program.

Counterpart of the reference gridutils (reference: src/pint/gridutils.py:
166 ``grid_chisq``), where each grid point deep-copies the model and
refits in a ProcessPoolExecutor worker.  Here the whole grid is
``vmap(fit_one)`` — grid parameters frozen at their grid values, the
remaining free parameters refit by a fixed number of Gauss-Newton WLS
steps — compiled once and executed as a single device program (the
north-star design: BASELINE config 3).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import telemetry
from pint_tpu.models.timing_model import frozen_delay_default, \
    hybrid_design_default
from pint_tpu.residuals import Residuals

__all__ = ["grid_chisq", "grid_chisq_vectorized", "make_grid_fn",
           "grid_chisq_tuple", "grid_chisq_derived",
           "grid_chisq_derived_tuple"]


def _make_fit_one(prepared, resids, grid_params, fit_params, n_steps,
                  scan=None, trace=False):
    """Build the pure per-point function ``fit_one(grid_vec, dyn) ->
    (chi2, fitted_values)`` — or, with ``trace`` (the
    ``$PINT_TPU_ITER_TRACE`` flight recorder, resolved by the CALLER
    and folded into the jit key), ``(chi2, fitted_values,
    iter_trace)`` where the trace stacks one
    :func:`pint_tpu.compile_cache.gn_trace_record` per GN iteration —
    plus its dynamic-leaf pytree ``dyn``.
    Returns ``(fit_one, dyn, partition_record)``.

    Everything dataset-derived — the residual data pytree, the base
    parameter values, the starting fit vector, and the host-side
    frozen-noise precomputes (sigma / Woodbury Cholesky / noise gram)
    — rides ``dyn`` as DYNAMIC arguments of the trace, the same
    ``fn(values, data)`` contract every other step program honors.
    The trace bakes in only structure, so (a) the shared-jit key needs
    no content fingerprint (two same-shaped grids over different data
    share one executable) and (b) XLA's constant folder never sees the
    (n_toa, n_basis) dataset it used to chew through on every grid
    compile (the BENCH_r04/r05 stall).

    scan: the fixed-count GN iteration style
    (:func:`pint_tpu.compile_cache.iterate_fixed` — resolved by the
    CALLER at build time and folded into the jit key)."""

    base_values = {k: jnp.float64(v) for k, v in prepared.model.values.items()}
    correlated = prepared.model.has_correlated_errors

    # structure-aware hot path (see fitter.py / design_matrix.md):
    # components owning neither a gridded nor a refit parameter are
    # evaluated ONCE host-side and enter the traced per-point step as
    # precomputed data — a (M2, SINI) grid stops re-interpolating the
    # SSB ephemeris and clock chain per point AND stops handing XLA
    # the whole frozen chain to constant-fold on every grid compile
    active = tuple(grid_params) + tuple(fit_params)
    frozen_names = (prepared.frozen_delay_split(active)
                    if frozen_delay_default() else ())
    frozen, tzr_frozen = prepared.frozen_delay_leaves(frozen_names)
    data = dict(resids._data())
    if frozen is not None:
        data["frozen"] = frozen
        if tzr_frozen is not None:
            data["tzr_frozen"] = tzr_frozen
    # hybrid design partition over the REFIT parameters only (grid
    # parameters are constants of each point): jacfwd tangent width
    # drops from len(fit_params) to the nonlinear remainder
    if hybrid_design_default():
        partition = prepared.design_partition(fit_params,
                                              frozen=frozen_names)
    else:
        partition = ((), tuple(fit_params))
    # introspection record for bench/datacheck (the jitted grid fn
    # itself can't carry attributes): what this grid build chose
    partition_record = {
        "n_linear": len(partition[0]),
        "n_nonlinear": len(partition[1]),
        "n_frozen": len(frozen_names),
        "frozen": tuple(frozen_names),
        "linear": tuple(partition[0]),
        "nonlinear": tuple(partition[1]),
    }

    # host-side prebuild of the values-independent noise solve (the
    # same treatment as the eager _U_ext build in residuals.py): when
    # no gridded or refit parameter touches the noise model, sigma, U
    # and phi are trace-time CONSTANTS — leaving them in the trace
    # hands XLA an all-constant (U^T N^-1 U + Phi^-1) build + Cholesky
    # to constant-fold from (n_toa, n_basis) inputs on EVERY grid
    # compile (the multi-GFLOP fold behind the BENCH_r05 alarm)
    noise_owned = {
        p.name
        for c in prepared.model.noise_components
        for p in c.params
    }
    sigma_frozen = noise_owned.isdisjoint(
        set(grid_params) | set(fit_params))
    pre = None
    sigma_const = None
    U_const = phi_const = gram_const = None
    if sigma_frozen:
        sigma_const = resids.sigma_fn(base_values)  # eager, concrete
        if correlated:
            from pint_tpu.linalg import (noise_gram_precompute,
                                         woodbury_precompute)

            U_const, phi_const = resids._noise_basis_phi(base_values)
            pre = woodbury_precompute(sigma_const, U_const, phi_const)
            # constant block of the normal matrix: per GN iteration
            # only the J-dependent blocks remain to assemble
            gram_const = noise_gram_precompute(sigma_const, U_const,
                                               phi_const)

    # which optional leaves dyn carries is a function of STRUCTURE
    # (sigma_frozen/correlated above), never of values — so the traced
    # program's shape is covered by the structural key
    fit0 = jnp.array(
        [prepared.model.values[k] for k in fit_params], dtype=jnp.float64
    )
    dyn = {"data": data, "base_values": base_values, "fit0": fit0}
    if sigma_const is not None:
        dyn["sigma_const"] = sigma_const
    if pre is not None:
        # U_const is data["U_ext"] by construction (the eager extended
        # basis) — the trace reads it from the data pytree; only the
        # precomputed Cholesky/phi/gram need their own leaves
        dyn["pre"] = pre
        dyn["phi_const"] = phi_const
        dyn["gram_const"] = gram_const
    has_sigma = sigma_const is not None
    has_pre = pre is not None

    def fit_one(grid_vec, d):
        base = d["base_values"]
        data = d["data"]

        def values_of(fit_vec):
            values = dict(base)
            for i, name in enumerate(grid_params):
                values[name] = grid_vec[i]
            for i, name in enumerate(fit_params):
                values[name] = fit_vec[i]
            return values

        def rj_of(fit_vec):
            """(r, J) over fit_params at one grid point — the hybrid
            analytic/AD build (fitter.resid_and_design)."""
            from pint_tpu.fitter import resid_and_design

            grid_sub = {name: grid_vec[i]
                        for i, name in enumerate(grid_params)}

            def resid_of(sub):
                values = dict(base)
                values.update(grid_sub)
                values.update(sub)
                return resids.time_resids_at(values, data)

            def linear_of(sub):
                values = dict(base)
                values.update(grid_sub)
                values.update(sub)
                return resids.linear_design_at(values, data,
                                               partition[0])

            return resid_and_design(fit_params, fit_vec, partition,
                                    resid_of, linear_of)

        def gn_step(fit_vec):
            """One GN refit step -> (new_vec, chi2 at the input
            point).  The solvers compute chi^2 regardless (it is one
            reduction of the whitened residual they already hold), so
            the gate-off caller dropping it leaves the traced program
            identical to the pre-flight-recorder build."""
            values = values_of(fit_vec)
            sigma = (d["sigma_const"] if has_sigma
                     else resids.sigma_at(values, data))
            rj = rj_of(fit_vec)
            if correlated:
                from pint_tpu.linalg import gls_normal_solve

                if has_pre:
                    U, phi = data["U_ext"], d["phi_const"]
                    dpar, _cov, _nc, chi2 = gls_normal_solve(
                        rj[0], rj[1], sigma, U, phi, pre=d["pre"],
                        gram=d["gram_const"])
                else:
                    U, phi = resids._noise_basis_phi_at(values, data)
                    dpar, _cov, _nc, chi2 = gls_normal_solve(
                        rj[0], rj[1], sigma, U, phi)
                return fit_vec + dpar, chi2
            from pint_tpu.fitter import wls_gn_solve

            new_vec, chi2, _, _ = wls_gn_solve(None, fit_vec, sigma,
                                               rj=rj)
            return new_vec, chi2

        vec = d["fit0"]
        tr = None
        if fit_params:  # all-params-gridded case: plain chi2 evaluation
            if trace:
                def body(carry):
                    return gn_step(carry[0])

                (vec, _), tr = _cc.iterate_fixed(
                    body, (vec, jnp.float64(jnp.inf)), n_steps,
                    scan=scan,
                    trace_of=lambda p, n: _cc.gn_trace_record(
                        p[0], n[0], n[1]))
            else:
                vec = _cc.iterate_fixed(lambda v: gn_step(v)[0], vec,
                                        n_steps, scan=scan)
        values = values_of(vec)
        if has_pre:
            from pint_tpu.linalg import woodbury_chi2_logdet_pre

            r = resids.time_resids_at(values, data)
            chi2, _ = woodbury_chi2_logdet_pre(r, d["pre"])
        elif has_sigma and not correlated:
            r = resids.time_resids_at(values, data)
            chi2 = jnp.sum((r / d["sigma_const"]) ** 2)
        else:
            chi2 = resids.chi2_at(values, data)
        if trace:
            return chi2, vec, tr
        return chi2, vec

    return fit_one, dyn, partition_record


def _grid_rules():
    """The grid-axis partition-rule table: the (npoints, k) grid-value
    array is sharded on its point axis; the dataset pytree (``dyn`` —
    batch, ctx, noise precomputes) is replicated onto every device."""
    from jax.sharding import PartitionSpec as P

    return ((r"^grid_values$", P("grid")),
            (r"^dyn(/|$)", None))


def make_grid_fn(toas, model, grid_params, n_steps=3, mesh=None):
    """Compile once, call many times: returns (fn, fit_params,
    partition) where fn(grid_values (n,k)) -> (chi2 (n,), fitted
    (n, nfree)) and partition records the structure choice this build
    made (n_linear / n_nonlinear / n_frozen + the name tuples — bench
    and datacheck introspection).  Lets callers (bench, repeated
    scans) reuse the jitted program.

    The jitted grid is registry-shared (compile_cache.shared_jit) on a
    STRUCTURE-ONLY key: the dataset (and every host-side precompute
    derived from it) rides the trace as dynamic leaves, so two
    same-shaped grids over DIFFERENT data — or over different base
    values — share one trace and one executable, and a rebuild over
    new data never recompiles.  (The content-fingerprint key the
    baked-constant design needed is retired with it.)

    mesh: a device mesh (:func:`pint_tpu.parallel.mesh.make_mesh`,
    axis ``grid``) — grid points are padded to a device multiple
    (edge-repeated; outputs sliced back to the requested count) and
    sharded over the mesh, the dataset replicated.  The mesh
    participates in the jit key, so a second same-shaped sharded call
    compiles nothing; ``mesh=None`` keys and behaves exactly as
    before."""
    from pint_tpu.parallel import mesh as _mesh

    resids = Residuals(toas, model)
    prepared = resids.prepared
    grid_params = list(grid_params)
    if any(p in ("ECC", "EDOT") for p in grid_params):
        # gridded eccentricity ranges are arbitrary, so the static
        # Newton depth must cover the full e < 0.97 unroll — the
        # prepare-time class only covers the base value.  Refit-only
        # ECC keeps its class: a grid refit is a local Gauss-Newton
        # polish around base values (the fitter path re-verifies the
        # class post-fit; a vmapped grid point cannot).
        resids.ensure_kepler_depth(float("nan"))
    fit_params = [p for p in model.free_timing_params if p not in grid_params]
    scan = _cc.scan_iters_default()
    trace = _cc.iter_trace_default()
    fit_one, dyn, partition = _make_fit_one(
        prepared, resids, grid_params, fit_params, n_steps, scan=scan,
        trace=trace)
    label = (f"grid.fit_one:{'+'.join(grid_params)}"
             + (":sharded" if mesh is not None else ""))
    key = ("grid.fit_one", resids._structure_key(),
           tuple(grid_params), tuple(fit_params), int(n_steps),
           # the gates change the traced program (partition + frozen
           # leaves derive deterministically from them + the free set;
           # scan-vs-unroll is a different iteration body; the
           # iter-trace gate adds the per-iteration ys output)
           hybrid_design_default(), frozen_delay_default(), scan,
           trace) \
        + _mesh.mesh_jit_key(mesh)
    jitted = _cc.shared_jit(
        jax.vmap(fit_one, in_axes=(0, None)), key=key,
        fn_token="grid.make_grid_fn",
        label=label)
    jitted.set_mesh(_mesh.mesh_desc(mesh))

    def _unpack(out, n=None):
        """Strip (and publish) the flight-recorder trace from a grid
        call's outputs; ``n`` slices padded point rows off every
        output (the sharded path).  The trace stays on device until a
        telemetry sink actually wants the decoded record."""
        if trace:
            chi2, fitted, tr = out
        else:
            (chi2, fitted), tr = out, None
        if n is not None:
            chi2, fitted = chi2[:n], fitted[:n]
            if tr is not None:
                tr = jax.tree.map(lambda x: x[:n], tr)
        if tr is not None:
            fn.last_iter_trace = tr
            if telemetry.sink_active():
                telemetry.emit(telemetry.iter_trace_record(
                    label, _cc.decode_gn_trace(tr), kind="grid",
                    n_points=int(np.shape(chi2)[0]),
                    n_steps=int(n_steps)))
        return chi2, fitted

    if mesh is None:
        def fn(grid_values):
            with telemetry.run_scope("grid",
                                     grid_params=list(grid_params)):
                return _unpack(jitted(grid_values, dyn))

        return fn, fit_params, partition

    ndev = _mesh.axis_size(mesh, "grid")
    rules = _grid_rules()
    # the dataset is call-invariant: replicate it onto the mesh ONCE
    # at build time, not per call (only the grid values vary)
    dyn_sharded = _mesh.shard_args(mesh, rules, {"dyn": dyn})["dyn"]

    def fn(grid_values):
        with telemetry.run_scope("grid",
                                 grid_params=list(grid_params),
                                 sharded=True):
            n = int(np.shape(grid_values)[0])
            n_pad = _mesh.pad_to_multiple(n, ndev)
            _mesh.record_pad_waste("grid", n, n_pad)
            gv = _mesh.pad_leading(grid_values, n_pad, mode="edge")
            gv = _mesh.shard_args(mesh, rules, {"grid_values": gv})[
                "grid_values"]
            return _unpack(jitted(gv, dyn_sharded), n=n)

    return fn, fit_params, partition


def grid_chisq_vectorized(
    toas, model, grid_params, grid_values, n_steps=3, chunk=None,
    mesh=None
):
    """chi^2 over an (npoints, len(grid_params)) array of grid values.

    Returns (chi2 array (npoints,), fitted free params (npoints, nfree)).
    The whole grid runs as vmap(fit_one) in one jit; ``chunk`` bounds
    device memory for very large grids; ``mesh`` shards the point axis
    over devices (see :func:`make_grid_fn`).
    """
    grid_values = jnp.asarray(grid_values, dtype=jnp.float64)
    fn, _, _ = make_grid_fn(toas, model, grid_params, n_steps,
                            mesh=mesh)
    # ONE ledger run for the whole surface: the per-call scopes the
    # grid callable opens join this outer one, so a chunked grid is
    # one run (with one iter_trace record per chunk), not one per
    # chunk
    with telemetry.run_scope("grid", grid_params=list(grid_params),
                             n_points=int(grid_values.shape[0])):
        if chunk is None or grid_values.shape[0] <= chunk:
            chi2, fitted = fn(grid_values)
        else:
            outs = [
                fn(grid_values[i : i + chunk])
                for i in range(0, grid_values.shape[0], chunk)
            ]
            chi2 = jnp.concatenate([o[0] for o in outs])
            fitted = jnp.concatenate([o[1] for o in outs])
    return np.asarray(chi2), np.asarray(fitted)


def grid_chisq_tuple(toas, model, param_names, points, n_steps=3,
                     chunk=None, mesh=None):
    """chi^2 at an explicit list of parameter tuples instead of a dense
    mesh (reference: gridutils.tuple_chisq, gridutils.py:588) — e.g.
    the points of a Monte-Carlo scan or a confidence contour.

    Failure semantics (reference WrappedFitter, gridutils.py:52-114,
    which swallows per-point fit exceptions in the process pool): here
    every point runs inside one vmapped XLA program, so a pathological
    point cannot raise — a diverged refit or unphysical parameter
    combination yields NaN/inf chi2 for that point only, which is the
    same contract (inspect and mask downstream).

    Returns (chi2 (npoints,), fitted free params (npoints, nfree))."""
    return grid_chisq_vectorized(
        toas, model, list(param_names), np.asarray(points, np.float64),
        n_steps=n_steps, chunk=chunk, mesh=mesh)


def grid_chisq(toas, model, param_names, param_arrays, n_steps=3,
               chunk=None, mesh=None):
    """Dense mesh grid like the reference API: param_arrays are 1-D axes;
    returns chi2 with shape (len(axis1), len(axis2), ...).  Per-point
    failure semantics: see grid_chisq_tuple."""
    axes = [np.asarray(a, dtype=np.float64) for a in param_arrays]
    pts = np.array(list(itertools.product(*axes)))
    chi2, _ = grid_chisq_vectorized(
        toas, model, param_names, pts, n_steps=n_steps, chunk=chunk,
        mesh=mesh
    )
    return chi2.reshape([len(a) for a in axes])


def grid_chisq_derived(toas, model, param_names, parfuncs, grid_arrays,
                       n_steps=3, chunk=None):
    """chi^2 over a grid of *derived* coordinates (reference:
    gridutils.grid_chisq_derived, gridutils.py:392).

    param_names: the real model parameters held fixed per point;
    parfuncs: same-length list of callables mapping the grid coordinate
    tuple -> that parameter's value (e.g. grid over (Mtot, q) while the
    model is fit in (M2, SINI)); grid_arrays: 1-D axes of the derived
    coordinates.

    Returns (chi2 shaped like the mesh, param_values (npoints, k))."""
    axes = [np.asarray(a, dtype=np.float64) for a in grid_arrays]
    mesh = np.array(list(itertools.product(*axes)))
    chi2, pvals = grid_chisq_derived_tuple(
        toas, model, param_names, parfuncs, mesh, n_steps=n_steps,
        chunk=chunk)
    return chi2.reshape([len(a) for a in axes]), pvals


def grid_chisq_derived_tuple(toas, model, param_names, parfuncs, points,
                             n_steps=3, chunk=None):
    """Derived-coordinate chi^2 at an explicit list of coordinate
    tuples (reference: gridutils.tuple_chisq_derived, gridutils.py:773).
    Returns (chi2 (npoints,), param_values (npoints, k))."""
    pts = np.asarray(points, np.float64)
    pvals = np.stack(
        [np.asarray([f(*pt) for pt in pts], dtype=np.float64)
         for f in parfuncs], axis=1)
    chi2, _ = grid_chisq_vectorized(
        toas, model, list(param_names), pvals, n_steps=n_steps,
        chunk=chunk)
    return np.asarray(chi2), pvals
