"""Scenario corpus: the workload factory (ROADMAP item 1).

``spec``   — deterministic scenario grammar (model families x noise
             processes x cadence patterns x fault corruptions) and the
             default >=100-scenario corpus.
``parity`` — differential parity harness: every scenario through our
             stack and (when mounted) the reference PINT, with
             class-scaled tolerances and structured verdicts.
``replay`` — the corpus as standing soak load for ``pintserve``.
``cli``    — the ``pintcorpus`` generate/run/report/replay entry point.
"""

from pint_tpu.corpus.spec import (  # noqa: F401
    CLASSES,
    Scenario,
    build_class,
    default_corpus,
    scenario_seed,
)
from pint_tpu.corpus.parity import (  # noqa: F401
    CLASS_TOL,
    Verdict,
    parity_one,
    reference_available,
    run_parity,
    summarize,
)
