"""Deterministic scenario generator: the corpus grammar.

A :class:`Scenario` is a fully-specified synthetic pulsar dataset —
par text + cadence + noise/fault plan + a seed — that realizes to a
reproducible ``(model, toas)`` pair through :mod:`pint_tpu.simulation`.
Scenario classes compose four orthogonal axes:

- **model family**: spin-only, astrometry, binary (ELL1/DD), JUMP/FD,
  DMX, chromatic CMX windows, solar wind, glitch, WaveX;
- **noise process**: white (EFAC/EQUAD), ECORR epochs, power-law red /
  DM-GP / band / system noise (drawn via the disjoint
  :func:`pint_tpu.simulation.substream` convention, so every process
  has its own stream);
- **cadence pattern**: uniform, fuzzed, clustered epochs, multi- or
  dual-frequency;
- **corruption**: an optional :mod:`pint_tpu.faults` spec the parity
  harness injects while realizing (``faulted`` class).

Every draw is keyed by ``scenario_seed(base_seed, klass, index)`` —
regenerating a corpus with the same seed is bit-identical, and the
streams of distinct scenarios/classes never alias (CRC-keyed
SeedSequence, never builtin ``hash``).

The default corpus (``default_corpus``) is 16 classes x 7 scenarios =
112 scenarios — the >=100 / >=8-class acceptance floor of ROADMAP
item 1 with headroom.  The ``multi_night_campaign`` class additionally
carries an append plan (``nights`` x ``night_ntoa`` cadence keys) the
streaming replay (:func:`pint_tpu.corpus.replay.replay_appends`)
realizes night by night through ``POST /v1/datasets/<id>/append``.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Callable, Dict, List

import numpy as np

from pint_tpu import telemetry

__all__ = ["Scenario", "CLASSES", "build_class", "default_corpus",
           "scenario_seed", "write_corpus", "load_manifest"]


def scenario_seed(base_seed, klass, index) -> int:
    """The scenario's stream key: deterministic in (base_seed, class,
    index), stable across processes (CRC32, not builtin ``hash``)."""
    return int(
        (int(base_seed) * 2_000_003
         + zlib.crc32(str(klass).encode("utf-8")) * 131
         + int(index)) & 0x7FFFFFFF
    )


class Scenario:
    """One reproducible synthetic dataset.

    ``cadence`` keys: start_mjd, duration_days, ntoa, error_us,
    freq_mhz (scalar | list cycled per TOA), obs, flags (uniform
    per-TOA flag dict), flag_cycle ({key: [values...]} assigned
    cyclically per TOA — multi-system selectors), fuzz_days,
    multifreq, clustered; campaign classes add nights, night_ntoa,
    night_gap_days (consumed by :meth:`realize_nights`, ignored by
    :meth:`realize`).
    ``fault``: a :mod:`pint_tpu.faults` spec string, or None.
    ``correlated``: realize() draws the model's correlated components
    from per-component disjoint substreams of ``seed``.
    """

    def __init__(self, name, klass, seed, par, cadence,
                 correlated=False, fault=None):
        self.name = str(name)
        self.klass = str(klass)
        self.seed = int(seed)
        self.par = str(par)
        self.cadence = dict(cadence)
        self.correlated = bool(correlated)
        self.fault = fault

    # -- realization ----------------------------------------------------------
    def realize(self, add_noise=True, add_correlated=None):
        """Build ``(model, toas)``.  ``add_noise=False`` yields the
        clean zero-residual realization (same cadence draws — the
        fuzz stream is shared), the parity harness's truth arm."""
        from pint_tpu import simulation as sim
        from pint_tpu.models.builder import get_model

        model = get_model(self.par)
        c = self.cadence
        rng = sim.substream(self.seed, "white")
        n = int(c["ntoa"])
        if c.get("clustered"):
            n = max(n // 4, 1) * 4
        freq = c.get("freq_mhz", 1400.0)
        if isinstance(freq, (list, tuple)):
            reps = int(np.ceil(n / len(freq)))
            freq = np.tile(np.asarray(freq, np.float64), reps)[:n]
        flags = c.get("flags")
        cycle = c.get("flag_cycle")
        if cycle:
            flags = [dict(flags or {}) for _ in range(n)]
            for key, vals in cycle.items():
                for i, f in enumerate(flags):
                    f[key] = str(vals[i % len(vals)])
        if c.get("clustered"):
            epochs = np.linspace(
                c["start_mjd"], c["start_mjd"] + c["duration_days"],
                n // 4)
            mjds = np.repeat(epochs, 4) + np.tile(
                np.arange(4) * 0.1 / 86400.0, n // 4)
            toas = sim.make_fake_toas_fromMJDs(
                mjds, model, freq_mhz=freq, obs=c.get("obs", "@"),
                error_us=c.get("error_us", 1.0), add_noise=add_noise,
                rng=rng, flags=flags)
        else:
            toas = sim.make_fake_toas_uniform(
                c["start_mjd"], c["start_mjd"] + c["duration_days"],
                n, model, freq_mhz=freq, obs=c.get("obs", "@"),
                error_us=c.get("error_us", 1.0), add_noise=add_noise,
                rng=rng, flags=flags,
                fuzz_days=c.get("fuzz_days", 0.0),
                multifreq=c.get("multifreq", False))
        if add_correlated if add_correlated is not None \
                else (self.correlated and add_noise):
            sim.add_correlated_noise(
                toas, model, per_component_seed=self.seed)
        telemetry.counter_add("corpus.realized")
        return model, toas

    def realize_nights(self, model=None):
        """The campaign append plan: one TOAs object per night (the
        ``nights`` / ``night_ntoa`` / ``night_gap_days`` cadence keys;
        empty list for non-campaign classes), each from its own
        disjoint substream, starting after the base span.  Every night
        routes through :func:`pint_tpu.faults.corrupt_append_toas` —
        a harness that injected this scenario's ``glitch_toas`` fault
        spec gets the glitch-shaped nights the triage must
        quarantine; with no fault active the hook is a no-op."""
        from pint_tpu import faults
        from pint_tpu import simulation as sim
        from pint_tpu.models.builder import get_model

        c = self.cadence
        nights = int(c.get("nights", 0))
        if not nights:
            return []
        if model is None:
            model = get_model(self.par)
        gap = float(c.get("night_gap_days", 1.0))
        k = int(c.get("night_ntoa", 4))
        base_end = float(c["start_mjd"]) + float(c["duration_days"])
        out = []
        for night in range(nights):
            rng = sim.substream(self.seed, f"night{night}")
            s0 = base_end + gap * (night + 1)
            t = sim.make_fake_toas_uniform(
                s0, s0 + 0.2, k, model,
                freq_mhz=c.get("freq_mhz", 1400.0),
                obs=c.get("obs", "@"),
                error_us=c.get("error_us", 1.0),
                add_noise=True, rng=rng, flags=c.get("flags"))
            out.append(faults.corrupt_append_toas(t, night=night))
        return out

    # -- persistence ----------------------------------------------------------
    def write(self, outdir):
        """Write ``<name>.par`` / ``<name>.tim`` under outdir; returns
        (par_path, tim_path)."""
        from pint_tpu.toa import write_tim

        os.makedirs(outdir, exist_ok=True)
        par_path = os.path.join(outdir, self.name + ".par")
        tim_path = os.path.join(outdir, self.name + ".tim")
        with open(par_path, "w") as f:
            f.write(self.par)
        _, toas = self.realize()
        write_tim(toas, tim_path)
        return par_path, tim_path

    def to_manifest(self) -> dict:
        return {
            "name": self.name, "class": self.klass, "seed": self.seed,
            "par": self.par, "cadence": self.cadence,
            "correlated": self.correlated, "fault": self.fault,
        }

    @classmethod
    def from_manifest(cls, d) -> "Scenario":
        return cls(d["name"], d["class"], d["seed"], d["par"],
                   d["cadence"], correlated=d.get("correlated", False),
                   fault=d.get("fault"))


# --------------------------------------------------------------------------
# par-text building blocks
# --------------------------------------------------------------------------

def _base_par(rng, name, mid, free_spin=True, ecliptic=False,
              elat=None):
    """Shared par prologue: pulsar name, sky position, spin, DM."""
    f0 = rng.uniform(50.0, 600.0)
    f1 = -(10.0 ** rng.uniform(-16.5, -14.5))
    dm = rng.uniform(5.0, 60.0)
    fit = "1" if free_spin else "0"
    if ecliptic:
        elong = rng.uniform(0.0, 360.0)
        elat = rng.uniform(-5.0, 5.0) if elat is None else elat
        pos = f"ELONG {elong:.6f}\nELAT {elat:.6f}\n"
    else:
        ra_h = rng.uniform(0.0, 24.0)
        dec = rng.uniform(-60.0, 60.0)
        pos = (f"RAJ {int(ra_h):02d}:{int((ra_h % 1) * 60):02d}:"
               f"{(ra_h * 3600) % 60:07.4f}\n"
               f"DECJ {int(dec):+03d}:{int(abs(dec) % 1 * 60):02d}:00\n")
    return (f"PSR {name}\n{pos}"
            f"F0 {f0!r} {fit}\nF1 {f1!r} {fit}\n"
            f"PEPOCH {mid:.1f}\nDM {dm:.4f}\n"
            f"TZRMJD {mid:.1f}\nTZRSITE @\nTZRFRQ 1400\n"
            f"UNITS TDB\nEPHEM builtin\n")


def _cadence(start=54000.0, days=1000.0, ntoa=32, **kw):
    c = {"start_mjd": float(start), "duration_days": float(days),
         "ntoa": int(ntoa), "error_us": 1.0, "obs": "@",
         "freq_mhz": 1400.0}
    c.update(kw)
    return c


# --------------------------------------------------------------------------
# scenario classes
# --------------------------------------------------------------------------

def _cls_spin(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    if rng.random() < 0.5:
        par += f"F2 {rng.uniform(-1e-26, 1e-26)!r} 1\n"
    return Scenario(name, "spin", seed, par,
                    _cadence(ntoa=30, fuzz_days=rng.uniform(0, 3.0)))


def _cls_astrometry(rng, seed, name):
    par = _base_par(rng, name, 54600.0)
    par += (f"PMRA {rng.uniform(-20, 20):.3f} 1\n"
            f"PMDEC {rng.uniform(-20, 20):.3f} 1\n"
            "POSEPOCH 54600\n")
    return Scenario(name, "astrometry", seed, par,
                    _cadence(days=1200.0, ntoa=36, obs="gbt"))


def _cls_binary(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    if rng.random() < 0.5:
        pb = rng.uniform(2.0, 40.0)
        par += (f"BINARY ELL1\nPB {pb:.6f} 1\n"
                f"A1 {rng.uniform(1.0, 20.0):.6f} 1\n"
                f"TASC {54500.0 + rng.uniform(0, pb):.6f} 1\n"
                f"EPS1 {rng.uniform(-1e-4, 1e-4)!r} 1\n"
                f"EPS2 {rng.uniform(-1e-4, 1e-4)!r} 1\n")
    else:
        pb = rng.uniform(5.0, 60.0)
        par += (f"BINARY DD\nPB {pb:.6f} 1\n"
                f"A1 {rng.uniform(2.0, 25.0):.6f} 1\n"
                f"T0 {54500.0 + rng.uniform(0, pb):.6f} 1\n"
                f"ECC {rng.uniform(0.05, 0.5):.6f} 1\n"
                f"OM {rng.uniform(0, 360):.4f} 1\n")
    return Scenario(name, "binary", seed, par, _cadence(ntoa=40))


def _cls_jumps(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    par += (f"JUMP -fe L-wide {rng.uniform(-1e-4, 1e-4)!r} 1\n"
            f"FD1 {rng.uniform(-1e-5, 1e-5)!r} 1\n")
    # the JUMP selects only half the TOAs (a full-coverage jump is
    # degenerate with the absolute phase); three frequencies against a
    # period-2 flag cycle keep FD1 and the JUMP mask non-degenerate
    return Scenario(
        name, "jumps", seed, par,
        _cadence(ntoa=36, obs="gbt",
                 freq_mhz=[430.0, 1400.0, 800.0],
                 flag_cycle={"fe": ["S-wide", "L-wide"]}))


def _cls_dmx(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    edges = np.linspace(53995.0, 55005.0, 4)
    for i in range(3):
        par += (f"DMX_{i + 1:04d} {rng.uniform(-5e-3, 5e-3)!r} 1\n"
                f"DMXR1_{i + 1:04d} {edges[i]:.1f}\n"
                f"DMXR2_{i + 1:04d} {edges[i + 1]:.1f}\n")
    return Scenario(name, "dmx", seed, par,
                    _cadence(ntoa=36, freq_mhz=[430.0, 1400.0]))


def _cls_rednoise(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    par += (f"TNREDAMP {rng.uniform(-14.0, -13.0):.3f}\n"
            f"TNREDGAM {rng.uniform(2.0, 5.0):.3f}\nTNREDC 10\n"
            "EFAC -f all 1.0\n")
    return Scenario(name, "rednoise", seed, par,
                    _cadence(ntoa=36, flags={"f": "all"}),
                    correlated=True)


def _cls_dmgp(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    par += (f"TNDMAMP {rng.uniform(-13.8, -13.0):.3f}\n"
            f"TNDMGAM {rng.uniform(2.0, 4.5):.3f}\nTNDMC 8\n")
    return Scenario(name, "dmgp", seed, par,
                    _cadence(ntoa=36, freq_mhz=[430.0, 1400.0]),
                    correlated=True)


def _cls_chromatic(rng, seed, name):
    # piecewise chromatic windows — the ChromaticCMX port
    par = _base_par(rng, name, 54500.0)
    par += "TNCHROMIDX 4.0\n"
    edges = np.linspace(53995.0, 55005.0, 3)
    for i in range(2):
        par += (f"CMX_{i + 1:04d} {rng.uniform(-0.02, 0.02)!r} 1\n"
                f"CMXR1_{i + 1:04d} {edges[i]:.1f}\n"
                f"CMXR2_{i + 1:04d} {edges[i + 1]:.1f}\n")
    return Scenario(name, "chromatic", seed, par,
                    _cadence(ntoa=36, freq_mhz=[430.0, 1400.0]))


def _cls_solarwind(rng, seed, name):
    # low ecliptic latitude: the sun-angle sweep NE_SW is fit from
    par = _base_par(rng, name, 54500.0, ecliptic=True)
    par += f"NE_SW {rng.uniform(4.0, 12.0):.3f} 1\n"
    return Scenario(name, "solarwind", seed, par,
                    _cadence(days=1100.0, ntoa=36, obs="gbt",
                             freq_mhz=[430.0, 1400.0]))


def _cls_glitch(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    par += (f"GLEP_1 {rng.uniform(54300.0, 54700.0):.2f}\n"
            f"GLF0_1 {rng.uniform(1e-8, 1e-6)!r} 1\n"
            f"GLF1_1 {rng.uniform(-1e-14, 0.0)!r} 1\n"
            "GLPH_1 0.0\n")
    return Scenario(name, "glitch", seed, par, _cadence(ntoa=36))


def _cls_ecorr(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    par += (f"EFAC -be guppi {rng.uniform(0.9, 1.3):.3f}\n"
            f"EQUAD -be guppi {rng.uniform(0.1, 0.6):.3f}\n"
            f"ECORR -be guppi {rng.uniform(0.2, 0.8):.3f}\n")
    return Scenario(name, "ecorr", seed, par,
                    _cadence(ntoa=32, clustered=True,
                             flags={"be": "guppi"}),
                    correlated=True)


def _cls_bandnoise(rng, seed, name):
    # the PLBandNoise port: independent power law per frequency band
    par = _base_par(rng, name, 54500.0)
    par += (f"TNBANDAMP FREQ 300 900 {rng.uniform(-13.6, -13.0):.3f}\n"
            f"TNBANDGAM FREQ 300 900 {rng.uniform(2.0, 4.0):.3f}\n"
            f"TNBANDAMP FREQ 900 2000 "
            f"{rng.uniform(-14.0, -13.4):.3f}\n"
            f"TNBANDGAM FREQ 900 2000 {rng.uniform(1.5, 3.5):.3f}\n"
            "TNBANDC 6\n")
    return Scenario(name, "bandnoise", seed, par,
                    _cadence(ntoa=36, freq_mhz=[430.0, 1400.0]),
                    correlated=True)


def _cls_sysnoise(rng, seed, name):
    # the PLSystemNoise port: per-observing-system power law by flag
    par = _base_par(rng, name, 54500.0)
    par += (f"TNSYSAMP -sys ao_430 {rng.uniform(-13.6, -13.0):.3f}\n"
            f"TNSYSGAM -sys ao_430 {rng.uniform(2.0, 4.0):.3f}\n"
            f"TNSYSAMP -sys gbt_800 {rng.uniform(-14.0, -13.4):.3f}\n"
            f"TNSYSGAM -sys gbt_800 {rng.uniform(1.5, 3.5):.3f}\n"
            "TNSYSC 6\n")
    return Scenario(
        name, "sysnoise", seed, par,
        _cadence(ntoa=36,
                 flag_cycle={"sys": ["ao_430", "gbt_800"]}),
        correlated=True)


def _cls_wavex(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    par += ("WXEPOCH 54500\nWXFREQ_0001 0.002\n"
            f"WXSIN_0001 {rng.uniform(-2e-6, 2e-6)!r} 1\n"
            f"WXCOS_0001 {rng.uniform(-2e-6, 2e-6)!r} 1\n")
    return Scenario(name, "wavex", seed, par, _cadence(ntoa=32))


def _cls_campaign(rng, seed, name):
    # the streaming demo class (docs/streaming.md): a base backlog
    # plus a nightly append plan sized to stay INSIDE the base TOA
    # bucket (30 base -> bucket 64; <= 7 nights x 4 TOAs = 28 added),
    # so the steady-state append path is exercised, not the boundary
    # fallback.  ~Half the draws arm a ``glitch_toas`` fault spec the
    # append replay injects while realizing nights — the triage must
    # quarantine those nights, never absorb them.
    par = _base_par(rng, name, 54500.0)
    par += f"EFAC -f camp {rng.uniform(0.95, 1.15):.3f}\n"
    fault = None
    if rng.random() < 0.5:
        fault = (f"glitch_toas:night={int(rng.integers(2, 4))}"
                 f":offset_us={rng.uniform(60.0, 120.0):.1f}"
                 f":ramp_us_per_day={rng.uniform(20.0, 60.0):.1f}")
    return Scenario(
        name, "multi_night_campaign", seed, par,
        _cadence(ntoa=30, days=800.0, obs="gbt",
                 flags={"f": "camp"},
                 nights=int(rng.integers(4, 8)), night_ntoa=4,
                 night_gap_days=float(rng.uniform(1.0, 3.0))),
        fault=fault)


def _cls_faulted(rng, seed, name):
    par = _base_par(rng, name, 54500.0)
    kind = "nan_resid" if rng.random() < 0.5 else "inf_sigma"
    idx = int(rng.integers(0, 30))
    return Scenario(name, "faulted", seed, par, _cadence(ntoa=30),
                    fault=f"{kind}:index={idx}")


#: the class registry: name -> builder(rng, seed, name) -> Scenario.
#: Adding a class = one entry here (+ a CLASS_TOL row in parity);
#: docs/corpus.md walks through it.
CLASSES: Dict[str, Callable] = {
    "spin": _cls_spin,
    "astrometry": _cls_astrometry,
    "binary": _cls_binary,
    "jumps": _cls_jumps,
    "dmx": _cls_dmx,
    "rednoise": _cls_rednoise,
    "dmgp": _cls_dmgp,
    "chromatic": _cls_chromatic,
    "solarwind": _cls_solarwind,
    "glitch": _cls_glitch,
    "ecorr": _cls_ecorr,
    "bandnoise": _cls_bandnoise,
    "sysnoise": _cls_sysnoise,
    "wavex": _cls_wavex,
    "multi_night_campaign": _cls_campaign,
    "faulted": _cls_faulted,
}


def build_class(klass, base_seed=0, count=7) -> List[Scenario]:
    """``count`` scenarios of one class, each from its own disjoint
    stream."""
    from pint_tpu.simulation import substream

    builder = CLASSES[klass]
    out = []
    for i in range(int(count)):
        seed = scenario_seed(base_seed, klass, i)
        rng = substream(seed, "spec")
        out.append(builder(rng, seed, f"{klass}-{i:03d}"))
        telemetry.counter_add("corpus.generated")
    return out


def default_corpus(base_seed=0, per_class=7,
                   classes=None) -> List[Scenario]:
    """The standard corpus: every registered class x ``per_class``
    (default 15 x 7 = 105 scenarios)."""
    out = []
    for klass in (classes or CLASSES):
        out.extend(build_class(klass, base_seed=base_seed,
                               count=per_class))
    return out


# --------------------------------------------------------------------------
# on-disk corpus
# --------------------------------------------------------------------------

def write_corpus(scenarios, outdir) -> str:
    """Write every scenario's par/tim pair plus ``manifest.json``;
    returns the manifest path."""
    os.makedirs(outdir, exist_ok=True)
    entries = []
    for s in scenarios:
        par_path, tim_path = s.write(outdir)
        e = s.to_manifest()
        e["par_path"] = os.path.basename(par_path)
        e["tim_path"] = os.path.basename(tim_path)
        entries.append(e)
    path = os.path.join(outdir, "manifest.json")
    with open(path, "w") as f:
        json.dump({"scenarios": entries}, f, indent=1)
    return path


def load_manifest(path) -> List[Scenario]:
    with open(path) as f:
        data = json.load(f)
    return [Scenario.from_manifest(e) for e in data["scenarios"]]
