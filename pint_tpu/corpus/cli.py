"""``pintcorpus`` — generate / run / report / replay the scenario
corpus.

- ``pintcorpus generate [--out DIR] [--seed N] [--per-class K]
  [--class NAME ...]`` — write every scenario's par/tim pair plus
  ``manifest.json``.
- ``pintcorpus run [--out DIR | --seed N] [--class NAME ...]
  [--mode auto|oracle|reference] [--verdicts PATH]`` — the parity
  harness over a corpus (an on-disk manifest, or generated in
  memory), per-class verdict table on stdout, JSONL verdict records.
- ``pintcorpus report VERDICTS.jsonl`` — re-render the table from a
  saved verdict file.
- ``pintcorpus replay [--requests N] [--seed N]`` — the serve-plane
  soak mix (sanitizer armed, SLO engine fed).  With ``--stream``: a
  ``multi_night_campaign`` scenario's appends streamed through
  ``POST /v1/datasets/<id>/append`` instead (sanitizer armed after
  the warm night; zero violations is the pass bar).

``--out`` defaults to ``$PINT_TPU_CORPUS_DIR`` when set.  Exit code:
0 when nothing failed (skips are not failures), 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main"]

ENV_DIR = "PINT_TPU_CORPUS_DIR"


def _corpus_from_args(args):
    from pint_tpu.corpus.spec import default_corpus, load_manifest

    out = getattr(args, "out", None) or os.environ.get(ENV_DIR)
    if out and os.path.exists(os.path.join(out, "manifest.json")):
        scenarios = load_manifest(os.path.join(out, "manifest.json"))
        if args.klass:
            scenarios = [s for s in scenarios
                         if s.klass in set(args.klass)]
        return scenarios
    return default_corpus(base_seed=args.seed,
                          per_class=getattr(args, "per_class", 7),
                          classes=args.klass or None)


def _print_table(summary, file=sys.stdout):
    print(f"{'class':<12s} {'scenarios':>9s} {'pass':>5s} "
          f"{'fail':>5s} {'skip':>5s}", file=file)
    for klass in sorted(summary):
        row = summary[klass]
        print(f"{klass:<12s} {row['scenarios']:>9d} "
              f"{row['pass']:>5d} {row['fail']:>5d} "
              f"{row['skip']:>5d}", file=file)


def _cmd_generate(args) -> int:
    from pint_tpu.corpus.spec import default_corpus, write_corpus

    out = args.out or os.environ.get(ENV_DIR)
    if not out:
        print("generate needs --out (or $PINT_TPU_CORPUS_DIR)",
              file=sys.stderr)
        return 2
    scenarios = default_corpus(base_seed=args.seed,
                               per_class=args.per_class,
                               classes=args.klass or None)
    path = write_corpus(scenarios, out)
    classes = sorted({s.klass for s in scenarios})
    print(f"wrote {len(scenarios)} scenarios "
          f"({len(classes)} classes) -> {path}")
    return 0


def _cmd_run(args) -> int:
    from pint_tpu.corpus.parity import run_parity, summarize

    scenarios = _corpus_from_args(args)
    verdicts = run_parity(scenarios, mode=args.mode)
    if args.verdicts:
        with open(args.verdicts, "w") as f:
            for v in verdicts:
                f.write(json.dumps(v.to_json()) + "\n")
    summary = summarize(verdicts)
    _print_table(summary)
    failed = [v for v in verdicts if v.status == "fail"]
    for v in failed[:10]:
        bad = {k: c for k, c in v.checks.items() if not c.get("ok")}
        print(f"FAIL {v.scenario} [{v.klass}] "
              f"{v.detail or json.dumps(bad)}", file=sys.stderr)
    return 1 if failed else 0


def _cmd_report(args) -> int:
    from pint_tpu.corpus.parity import Verdict, summarize

    verdicts = []
    with open(args.verdicts) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            verdicts.append(Verdict(d["scenario"], d["class"],
                                    d["mode"], d["status"],
                                    checks=d.get("checks"),
                                    detail=d.get("detail", "")))
    _print_table(summarize(verdicts))
    return 1 if any(v.status == "fail" for v in verdicts) else 0


def _cmd_replay(args) -> int:
    from pint_tpu.corpus.replay import (DEFAULT_MIX, default_mix,
                                        replay_appends, replay_mix)

    if args.stream:
        from pint_tpu.corpus.spec import build_class

        scenario = build_class("multi_night_campaign",
                               base_seed=args.seed, count=1)[0]
        stats = replay_appends(scenario,
                               slo_p99_ms=args.slo_p99_ms)
        print(json.dumps({k: v for k, v in stats.items()
                          if k != "slo"}, indent=1))
        verdict = (stats["slo"] or {}).get("verdict", "off")
        print(f"slo verdict: {verdict}")
        ok = (stats["errors"] == 0
              and stats["sanitizer_violations"] == 0)
        return 0 if ok else 1

    classes = tuple(args.klass) if args.klass else DEFAULT_MIX
    mix = default_mix(base_seed=args.seed, classes=classes)
    stats = replay_mix(mix, n_requests=args.requests,
                       slo_p99_ms=args.slo_p99_ms)
    print(json.dumps({k: v for k, v in stats.items()
                      if k != "slo"}, indent=1))
    verdict = (stats["slo"] or {}).get("verdict", "off")
    print(f"slo verdict: {verdict}")
    ok = (stats["errors"] == 0
          and stats["sanitizer_violations"] == 0)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pintcorpus",
        description="scenario corpus: generate / parity / replay")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write par/tim + manifest")
    g.add_argument("--out", default=None)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--per-class", type=int, default=7,
                   dest="per_class")
    g.add_argument("--class", action="append", dest="klass",
                   default=None, help="restrict to a scenario class")
    g.set_defaults(fn=_cmd_generate)

    r = sub.add_parser("run", help="parity harness over a corpus")
    r.add_argument("--out", default=None,
                   help="corpus dir with manifest.json (else "
                        "generate in memory)")
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--per-class", type=int, default=7,
                   dest="per_class")
    r.add_argument("--class", action="append", dest="klass",
                   default=None)
    r.add_argument("--mode", default=None,
                   choices=("auto", "oracle", "reference"))
    r.add_argument("--verdicts", default=None,
                   help="write JSONL verdict records here")
    r.set_defaults(fn=_cmd_run)

    p = sub.add_parser("report", help="summarize a verdict file")
    p.add_argument("verdicts")
    p.set_defaults(fn=_cmd_report)

    y = sub.add_parser("replay", help="serve-plane soak mix")
    y.add_argument("--requests", type=int, default=60)
    y.add_argument("--seed", type=int, default=0)
    y.add_argument("--class", action="append", dest="klass",
                   default=None)
    y.add_argument("--slo-p99-ms", type=float, default=500.0,
                   dest="slo_p99_ms")
    y.add_argument("--stream", action="store_true",
                   help="stream a multi_night_campaign scenario's "
                        "appends through POST /v1/datasets/<id>/"
                        "append (sanitizer armed after night 0)")
    y.set_defaults(fn=_cmd_replay)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
