"""Differential parity harness: every scenario, two arms, one verdict.

Two modes, selected by ``$PINT_TPU_CORPUS_MODE`` (``auto`` default):

- **reference** — the scenario's par/tim pair runs through the mounted
  reference PINT (``$PINT_TPU_CORPUS_REFERENCE``, default
  ``/root/reference``) in a subprocess AND through our stack; residuals
  must agree pointwise at the class tolerance and fitted parameters
  within quoted uncertainties.  Skipped (never silently passed) when
  the reference tree is not mounted.
- **oracle** — always available: the scenario's own injected truth is
  the reference.  The harness asserts (1) bit-identical regeneration
  (seed determinism), (2) the clean realization's residuals vanish at
  the class tolerance (phase-inversion parity), (3) a fit from truth
  on the noisy realization recovers every free parameter within the
  class sigma budget with a sane chi2/dof (statistical parity), and
  (4) for ``faulted`` scenarios, that the corruption is *detected*
  (non-finite residuals / structured error), not silently fit through.

Tolerances are **class-scaled** (``CLASS_TOL``): a DD binary's 2-pass
phase inversion legitimately leaves ~100x the residual of a spin-only
scenario, and correlated-noise classes need a wider post-fit chi2/dof
band because the white-noise dof estimate is only approximate.  One
global tolerance would either mask real spin-class regressions or
flake on binaries — docs/corpus.md records the per-class rationale.

Every run ticks ``corpus.parity.*`` telemetry counters and yields
structured :class:`Verdict` records (JSON-serializable), the CLI's
report rows.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

import numpy as np

from pint_tpu import telemetry

__all__ = ["CLASS_TOL", "Verdict", "run_parity", "parity_one",
           "summarize", "reference_available", "reference_mode"]

ENV_REFERENCE = "PINT_TPU_CORPUS_REFERENCE"
ENV_MODE = "PINT_TPU_CORPUS_MODE"

#: class-scaled tolerances: clean-realization residual bound [ns],
#: fit-recovery sigma budget, post-fit chi2/dof band, and the
#: reference-arm residual agreement bound [us]
_DEFAULT_TOL = {"resid_ns": 50.0, "nsigma": 5.0,
                "chi2_dof": (0.2, 3.0), "ref_resid_us": 0.05}
CLASS_TOL: Dict[str, dict] = {
    "spin": {},
    "astrometry": {},
    "jumps": {},
    "dmx": {},
    "wavex": {},
    "chromatic": {},
    "solarwind": {},
    # 2-pass phase inversion through the orbital kepler solve leaves
    # larger (still sub-us) closure residuals
    "binary": {"resid_ns": 2000.0, "nsigma": 6.0},
    "glitch": {"resid_ns": 200.0},
    # correlated classes: the injected process inflates the white-dof
    # chi2 estimate, and GLS absorbs it only up to basis truncation
    "rednoise": {"nsigma": 6.0, "chi2_dof": (0.1, 4.0)},
    "dmgp": {"nsigma": 6.0, "chi2_dof": (0.1, 4.0)},
    "ecorr": {"nsigma": 6.0, "chi2_dof": (0.1, 4.0)},
    "bandnoise": {"nsigma": 6.0, "chi2_dof": (0.1, 4.0)},
    "sysnoise": {"nsigma": 6.0, "chi2_dof": (0.1, 4.0)},
    # spin+EFAC base; the append plan (and its optional glitch_toas
    # fault) only matters to the streaming replay, which injects the
    # fault itself — parity sees an ordinary clean base realization
    "multi_night_campaign": {},
    # the fault must be DETECTED; no numeric tolerances apply
    "faulted": {},
}


def class_tol(klass) -> dict:
    t = dict(_DEFAULT_TOL)
    t.update(CLASS_TOL.get(klass, {}))
    return t


class Verdict:
    """One scenario's parity outcome: pass/fail/skip + per-check
    details."""

    def __init__(self, scenario, klass, mode, status, checks=None,
                 detail=""):
        self.scenario = scenario
        self.klass = klass
        self.mode = mode
        self.status = status  # "pass" | "fail" | "skip"
        self.checks = dict(checks or {})
        self.detail = detail

    def to_json(self) -> dict:
        return {"scenario": self.scenario, "class": self.klass,
                "mode": self.mode, "status": self.status,
                "checks": self.checks, "detail": self.detail}

    def __repr__(self):
        return (f"Verdict({self.scenario} [{self.klass}] "
                f"{self.mode}:{self.status})")


# --------------------------------------------------------------------------
# reference arm
# --------------------------------------------------------------------------

def reference_path() -> str:
    return os.environ.get(ENV_REFERENCE, "/root/reference")


def reference_mode() -> str:
    """``oracle`` | ``reference`` | ``auto`` (the env knob,
    host-only)."""
    return os.environ.get(ENV_MODE, "auto").strip().lower() or "auto"


_REF_OK: Optional[bool] = None

_REF_PROBE = "import pint, pint.models, pint.toa\nprint(pint.__version__)"

_REF_SCRIPT = r"""
import json, sys
import numpy as np
import pint.models, pint.toa, pint.fitter, pint.residuals
par, tim, fit = sys.argv[1], sys.argv[2], int(sys.argv[3])
m = pint.models.get_model(par)
t = pint.toa.get_TOAs(tim, model=m)
r = pint.residuals.Residuals(t, m)
out = {"resid_us": (r.time_resids.to_value("us")).tolist()}
if fit:
    f = pint.fitter.Fitter.auto(t, m)
    f.fit_toas()
    out["chi2"] = float(f.resids.chi2)
    out["params"] = {
        p: [float(getattr(f.model, p).value),
            float(getattr(f.model, p).uncertainty_value or 0.0)]
        for p in f.model.free_params}
print(json.dumps(out))
"""


def _reference_env() -> dict:
    env = dict(os.environ)
    src = os.path.join(reference_path(), "src")
    root = src if os.path.isdir(src) else reference_path()
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return env


def reference_available() -> bool:
    """True when the mounted reference PINT imports in a subprocess
    (probed once per process)."""
    global _REF_OK
    if _REF_OK is None:
        if not os.path.isdir(reference_path()):
            _REF_OK = False
        else:
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", _REF_PROBE],
                    env=_reference_env(), capture_output=True,
                    timeout=120)
                _REF_OK = proc.returncode == 0
            except (OSError, subprocess.TimeoutExpired):
                _REF_OK = False
    return _REF_OK


def run_reference(par_path, tim_path, fit=True, timeout=600) -> dict:
    """One scenario through the reference PINT in a subprocess;
    returns its residuals [us] and fitted params.  Raises
    RuntimeError on a reference-side failure."""
    proc = subprocess.run(
        [sys.executable, "-c", _REF_SCRIPT, str(par_path),
         str(tim_path), "1" if fit else "0"],
        env=_reference_env(), capture_output=True, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            "reference PINT run failed: "
            + proc.stderr.decode(errors="replace")[-2000:])
    return json.loads(proc.stdout.decode().strip().splitlines()[-1])


# --------------------------------------------------------------------------
# our arm
# --------------------------------------------------------------------------

def _fit_ours(model, toas, maxiter=4):
    """Fit with the noise-appropriate fitter; returns (fitter,
    chi2)."""
    from pint_tpu.fitter import GLSFitter, WLSFitter

    cls = GLSFitter if model.has_correlated_errors else WLSFitter
    f = cls(toas, model)
    chi2 = f.fit_toas(maxiter=maxiter)
    return f, float(chi2)


def _oracle_checks(scenario) -> Dict[str, dict]:
    """The oracle-mode check battery; each entry carries ok + data."""
    from pint_tpu import faults
    from pint_tpu.residuals import Residuals

    tol = class_tol(scenario.klass)
    checks: Dict[str, dict] = {}

    # 1. seed determinism: two realizations are bit-identical
    _, t1 = scenario.realize()
    _, t2 = scenario.realize()
    same = bool(np.array_equal(t1.ticks, t2.ticks))
    checks["determinism"] = {"ok": same}

    # 2. clean-realization residual closure
    model, clean = scenario.realize(add_noise=False)
    r = Residuals(clean, model, subtract_mean=False,
                  track_mode="nearest")
    wmax = float(np.max(np.abs(np.asarray(r.time_resids)))) * 1e9
    checks["clean_residuals"] = {"ok": wmax <= tol["resid_ns"],
                                 "max_ns": wmax,
                                 "tol_ns": tol["resid_ns"]}

    if scenario.klass == "faulted":
        # 3f. the corruption must be detected, not fit through
        truth = {}
        m2, noisy = scenario.realize()  # generation itself is clean
        try:
            faults.clear()
            for part in scenario.fault.split(","):
                bits = part.split(":")
                params = dict(b.split("=", 1) for b in bits[1:])
                faults.inject(bits[0],
                              **{k: int(v) for k, v in params.items()})
            rr = Residuals(noisy, m2, track_mode="nearest")
            resid = np.asarray(rr.time_resids)
            # the corrupted dataset pytree (faults hook in at _data)
            batch = rr._data()["batch"]
            detected = (not np.all(np.isfinite(resid))
                        or not np.all(np.isfinite(
                            np.asarray(batch.error_s)))
                        or not np.all(np.isfinite(
                            np.asarray(batch.freq_mhz))))
            truth = {"ok": bool(detected), "fault": scenario.fault}
        except (FloatingPointError, ValueError, RuntimeError) as e:
            # a structured guard error IS detection
            truth = {"ok": True, "fault": scenario.fault,
                     "raised": type(e).__name__}
        finally:
            faults.clear()
        checks["fault_detected"] = truth
        return checks

    # 3. statistical parity: fit from truth on the noisy realization;
    # every free parameter within the class sigma budget, chi2/dof in
    # the class band
    model, noisy = scenario.realize()
    truth_vals = {p: model.values[p] for p in model.free_params}
    f, chi2 = _fit_ours(model, noisy)
    dof = len(noisy) - len(model.free_params) - 1
    lo, hi = tol["chi2_dof"]
    worst = 0.0
    worst_p = ""
    for p in f.model.free_params:
        unc = f.model.params[p].uncertainty
        if not unc or not np.isfinite(unc):
            continue
        ns = abs(f.model.values[p] - truth_vals[p]) / unc
        if ns > worst:
            worst, worst_p = float(ns), p
    ok = (worst <= tol["nsigma"]
          and lo <= chi2 / max(dof, 1) <= hi)
    checks["fit_recovery"] = {
        "ok": bool(ok), "worst_nsigma": worst, "worst_param": worst_p,
        "nsigma_tol": tol["nsigma"], "chi2_dof": chi2 / max(dof, 1),
        "chi2_dof_band": [lo, hi]}
    return checks


def _reference_checks(scenario, workdir) -> Dict[str, dict]:
    """The reference-mode battery: residual + fit-parameter agreement
    against the mounted reference PINT."""
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    tol = class_tol(scenario.klass)
    par_path, tim_path = scenario.write(workdir)
    ref = run_reference(par_path, tim_path,
                        fit=scenario.klass != "faulted")

    checks: Dict[str, dict] = {}
    from pint_tpu.models.builder import get_model

    model = get_model(par_path)
    toas = get_TOAs(tim_path)
    r = Residuals(toas, model)
    ours_us = np.asarray(r.time_resids) * 1e6
    ref_us = np.asarray(ref["resid_us"], dtype=np.float64)
    # both arms subtract their weighted mean; compare the shapes
    dmax = float(np.max(np.abs(ours_us - ref_us)))
    checks["residual_agreement"] = {
        "ok": dmax <= tol["ref_resid_us"], "max_us": dmax,
        "tol_us": tol["ref_resid_us"]}

    if "params" in ref:
        f, _ = _fit_ours(model, toas)
        worst = 0.0
        worst_p = ""
        for p, (rv, ru) in ref["params"].items():
            if p not in f.model.values:
                continue
            unc = max(float(ru) or 0.0,
                      float(f.model.params[p].uncertainty or 0.0))
            if unc <= 0 or not np.isfinite(unc):
                continue
            ns = abs(f.model.values[p] - rv) / unc
            if ns > worst:
                worst, worst_p = float(ns), p
        checks["fit_agreement"] = {
            "ok": worst <= tol["nsigma"], "worst_nsigma": worst,
            "worst_param": worst_p, "nsigma_tol": tol["nsigma"]}
    return checks


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def parity_one(scenario, mode=None, workdir=None) -> Verdict:
    """Run one scenario's parity battery; never raises — a crashed
    battery is a fail verdict with the exception in ``detail``."""
    mode = (mode or reference_mode())
    if mode == "auto":
        mode = "reference" if reference_available() else "oracle"
    telemetry.counter_add("corpus.parity.run")
    with telemetry.span("corpus.parity", scenario=scenario.name,
                        klass=scenario.klass, mode=mode):
        if mode == "reference" and not reference_available():
            telemetry.counter_add("corpus.parity.skip")
            return Verdict(scenario.name, scenario.klass, mode,
                           "skip",
                           detail=f"reference PINT not mounted at "
                                  f"{reference_path()}")
        try:
            if mode == "reference":
                import tempfile

                if workdir is None:
                    with tempfile.TemporaryDirectory(
                            prefix="pint_tpu_corpus_") as td:
                        checks = _reference_checks(scenario, td)
                else:
                    checks = _reference_checks(scenario, workdir)
            else:
                checks = _oracle_checks(scenario)
        except Exception as e:  # noqa: BLE001 — verdict, not crash
            telemetry.counter_add("corpus.parity.fail")
            return Verdict(scenario.name, scenario.klass, mode,
                           "fail",
                           detail=f"{type(e).__name__}: {e}")
    ok = all(c.get("ok") for c in checks.values())
    telemetry.counter_add(
        "corpus.parity.pass" if ok else "corpus.parity.fail")
    return Verdict(scenario.name, scenario.klass, mode,
                   "pass" if ok else "fail", checks=checks)


def run_parity(scenarios, mode=None, workdir=None) -> List[Verdict]:
    return [parity_one(s, mode=mode, workdir=workdir)
            for s in scenarios]


def summarize(verdicts) -> Dict[str, dict]:
    """Per-class rollup: {class: {pass, fail, skip, scenarios}}."""
    out: Dict[str, dict] = {}
    for v in verdicts:
        row = out.setdefault(
            v.klass, {"pass": 0, "fail": 0, "skip": 0,
                      "scenarios": 0})
        row["scenarios"] += 1
        row[v.status] += 1
    return out
