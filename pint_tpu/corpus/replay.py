"""Corpus replay: the scenario mix as standing ``pintserve`` soak load.

The load half of ROADMAP item 2: a deterministic slice of the corpus
is registered on an in-process replica and a mixed fit/lnlike/
residuals stream (the same 70/20/10 mix as ``bench.py``'s serve
metric) is fired over real loopback HTTP with

- the **recompile sanitizer** armed (:mod:`pint_tpu.lint.sanitizer`) —
  after warmup, ANY compile during the stream is a violation; and
- the **SLO engine** given objectives (:mod:`pint_tpu.obs.slo`) — the
  stream's latencies feed the rolling windows and the final verdict
  rides the stats.

Returns a structured stats dict (requests, rps, errors, sanitizer
violations, SLO verdict) — consumed by ``bench_corpus_replay``, the
``pintcorpus replay`` CLI and the soak tests.  Telemetry:
``corpus.replay.requests`` / ``corpus.replay.errors`` /
``corpus.replay.violations`` / ``corpus.replay.appends``.

:func:`replay_appends` is the STREAMING replay mode (``pintcorpus
replay --stream``): a ``multi_night_campaign`` scenario's base
backlog is registered on an in-process replica, then each night's
arrivals stream through ``POST /v1/datasets/<id>/append`` — the
first (compile-bearing) night warms the append surface, the
sanitizer arms, and every steady-state night must append with ZERO
recompile violations.  The scenario's optional ``glitch_toas``
fault spec is injected while nights are realized, so the replay
also exercises the triage-quarantine path end to end.
"""

from __future__ import annotations

import time
from typing import List, Optional

from pint_tpu import telemetry

__all__ = ["replay_mix", "default_mix", "replay", "replay_appends"]

#: the default replay slice: cheap, structurally diverse classes —
#: white-noise WLS, a binary, piecewise DM, and a correlated-noise GLS
#: dataset, so the stream spans distinct program structures
DEFAULT_MIX = ("spin", "binary", "dmx", "rednoise")


def default_mix(base_seed=0, classes=DEFAULT_MIX) -> List:
    """One scenario per mix class (deterministic in ``base_seed``)."""
    from pint_tpu.corpus.spec import build_class

    return [build_class(k, base_seed=base_seed, count=1)[0]
            for k in classes]


def _mixed_op(i):
    """The bench-aligned deterministic 70/20/10 fit/lnlike/residuals
    mix."""
    m = i % 10
    if m < 7:
        return "fit"
    if m < 9:
        return "lnlike"
    return "residuals"


def replay_mix(scenarios=None, n_requests=60, flush_ms=2.0,
               max_batch=8, slo_p99_ms=None, slo_avail=None,
               maxiter=2) -> dict:
    """Fire ``n_requests`` of the mixed stream at an in-process
    replica loaded with ``scenarios`` (default :func:`default_mix`),
    sanitizer armed after warmup.  Returns the stats dict; raises
    only on setup failure — request errors are counted, not raised."""
    import tempfile

    from pint_tpu.fleet.client import RetryClient
    from pint_tpu.lint import sanitizer
    from pint_tpu.obs import slo as _slo
    from pint_tpu.serve.server import Server

    scenarios = list(scenarios or default_mix())
    if not scenarios:
        raise ValueError("replay needs at least one scenario")

    srv = Server(flush_ms=flush_ms, max_batch=max_batch,
                 queue_max=4096, deadline_ms=0)
    port = srv.start(port=0)
    was_armed = sanitizer.armed()
    try:
        # each scenario rides in as its written par/tim pair — the
        # replica ingests exactly what the corpus persists, so replay
        # exercises the tim round-trip too
        with tempfile.TemporaryDirectory(
                prefix="pint_tpu_replay_") as td:
            for s in scenarios:
                _, tim_path = s.write(td)
                srv.registry.load(s.name, par=s.par, tim=tim_path)
        ids = [s.name for s in scenarios]
        # warm every (op, dataset) program so the armed stream is
        # honestly zero-compile
        for ds in ids:
            srv.warmup(ds, ops=("fit", "lnlike", "residuals"),
                       maxiter=maxiter)
        if slo_p99_ms is not None or slo_avail is not None:
            _slo.reset(p99_ms=slo_p99_ms, avail=slo_avail)
        v0 = len(sanitizer.violations())
        sanitizer.arm(note="corpus.replay")

        # the shared fleet client: bounded retry/backoff honoring
        # Retry-After — the one request loop every soak path uses
        client = RetryClient("127.0.0.1", port, timeout=120)
        ok = 0
        errors = 0
        t0 = time.time()
        for i in range(int(n_requests)):
            op = _mixed_op(i)
            ds = ids[i % len(ids)]
            body = {"dataset": ds}
            if op == "fit":
                body["maxiter"] = maxiter
            try:
                status, r, _ = client.post(f"/v1/{op}", body)
                if status == 200 and r.get("status") == "ok":
                    ok += 1
                else:
                    errors += 1
            except OSError:
                errors += 1
            telemetry.counter_add("corpus.replay.requests")
        wall = time.time() - t0
        client.close()
        violations = len(sanitizer.violations()) - v0
        slo_doc = _slo.tracker().verdict_doc()
    finally:
        if not was_armed:
            sanitizer.disarm()
        srv.stop()
    if errors:
        telemetry.counter_add("corpus.replay.errors", errors)
    if violations:
        telemetry.counter_add("corpus.replay.violations", violations)
    stats = {
        "datasets": ids,
        "requests": int(n_requests),
        "ok": ok,
        "errors": errors,
        "wall_s": wall,
        "rps": (int(n_requests) / wall) if wall > 0 else 0.0,
        "sanitizer_violations": violations,
        "slo": slo_doc,
    }
    telemetry.emit({"type": "corpus_replay", **{
        k: v for k, v in stats.items() if k != "slo"}})
    return stats


def replay(scenarios=None, **kw) -> dict:
    """Alias of :func:`replay_mix` (the name the CLI/docs use)."""
    return replay_mix(scenarios=scenarios, **kw)


def replay_appends(scenario=None, flush_ms=2.0, max_batch=4,
                   maxiter=3, slo_p99_ms=None) -> dict:
    """Stream one campaign's nightly appends through
    ``POST /v1/datasets/<id>/append`` on an in-process replica.

    Night 0 is the warm append (the capture/delta/refit programs
    compile there, exactly once per structure); the recompile
    sanitizer arms after it, so ANY compile on the remaining nights
    is a violation.  The scenario's ``glitch_toas`` fault (when
    drawn) is injected only while the nights are realized — the
    corrupted nights reach the replica as ordinary data and the
    triage must quarantine them.  Returns the stats dict; request
    errors are counted, not raised."""
    import os
    import tempfile

    from pint_tpu import faults
    from pint_tpu.corpus.spec import build_class
    from pint_tpu.fleet.client import RetryClient
    from pint_tpu.lint import sanitizer
    from pint_tpu.obs import slo as _slo
    from pint_tpu.serve.server import Server
    from pint_tpu.toa import write_tim

    if scenario is None:
        scenario = build_class("multi_night_campaign", base_seed=0,
                               count=1)[0]
    # realize the nights FIRST (fault injected only around this —
    # the serve plane must see the glitch as data, not as an armed
    # fault, or the batcher would bypass its stacked cache)
    try:
        if scenario.fault:
            for fname, params in faults.parse(scenario.fault).items():
                faults.inject(fname, **params)
        nights = scenario.realize_nights()
    finally:
        faults.clear()
    if not nights:
        raise ValueError(
            f"scenario {scenario.name!r} has no append plan "
            "(streaming replay needs a campaign class)")

    srv = Server(flush_ms=flush_ms, max_batch=max_batch,
                 queue_max=1024, deadline_ms=0)
    port = srv.start(port=0)
    was_armed = sanitizer.armed()
    appends_ok = 0
    errors = 0
    modes = []
    verdicts = []
    quarantined = 0
    version = None
    freshness_s = None
    try:
        with tempfile.TemporaryDirectory(
                prefix="pint_tpu_stream_") as td:
            _, tim_path = scenario.write(td)
            srv.registry.load(scenario.name, par=scenario.par,
                              tim=tim_path)
            client = RetryClient("127.0.0.1", port, timeout=300)
            v0 = len(sanitizer.violations())
            t0 = time.time()
            for night, delta in enumerate(nights):
                path = os.path.join(td, f"night{night:02d}.tim")
                write_tim(delta, path)
                try:
                    status, r, _ = client.post(
                        f"/v1/datasets/{scenario.name}/append",
                        {"tim": path, "maxiter": maxiter})
                except OSError:
                    errors += 1
                    continue
                if status != 200:
                    errors += 1
                    continue
                appends_ok += 1
                modes.append(r.get("mode"))
                verdicts.append(r.get("verdict"))
                quarantined += len(r.get("quarantined") or ())
                version = r.get("version")
                freshness_s = r.get("freshness_s")
                telemetry.counter_add("corpus.replay.appends")
                if night == 0:
                    # the cold night is done: everything after this
                    # is the steady-state append path — arm the
                    # sanitizer and start the SLO windows here, so
                    # neither gate charges the one-time compiles
                    sanitizer.arm(note="corpus.replay.appends")
                    v0 = len(sanitizer.violations())
                    if slo_p99_ms is not None:
                        _slo.reset(p99_ms=slo_p99_ms)
            wall = time.time() - t0
            client.close()
        violations = len(sanitizer.violations()) - v0
        slo_doc = _slo.tracker().verdict_doc()
    finally:
        if not was_armed:
            sanitizer.disarm()
        srv.stop()
    if errors:
        telemetry.counter_add("corpus.replay.errors", errors)
    if violations:
        telemetry.counter_add("corpus.replay.violations", violations)
    stats = {
        "dataset": scenario.name,
        "fault": scenario.fault,
        "nights": len(nights),
        "appends_ok": appends_ok,
        "errors": errors,
        "wall_s": wall,
        "modes": modes,
        "verdicts": verdicts,
        "quarantined": quarantined,
        "final_version": version,
        "freshness_s": freshness_s,
        "sanitizer_violations": violations,
        "slo": slo_doc,
    }
    telemetry.emit({"type": "corpus_replay_appends", **{
        k: v for k, v in stats.items() if k != "slo"}})
    return stats
