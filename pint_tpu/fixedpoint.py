"""Exact fixed-point phase accumulation for TPU.

Why this module exists: the TPU backend emulates float64 at ~49-bit
effective precision (adds observed up to 16 ulps off correctly-rounded
IEEE), which silently breaks error-free transformations — the double-double
kernels in :mod:`pint_tpu.dd` are only trustworthy on backends with real
IEEE f64 (CPU).  Integer arithmetic, however, is *bit-exact* on TPU
(int64/uint64 are emulated with int32 pairs; integer emulation cannot lose
bits).  So the one precision-critical product in all of pulsar timing —

    phase_turns = F0 * t      (~700 Hz x ~6e8 s = 4e11 turns,
                               needed to ~1e-6 turns => ~2.5e-16 relative)

is computed here in exact fixed point, while every smaller term stays in
plain float64, where even sloppy 2^-49 arithmetic is more than enough:

    F0 * delay        <= ~7e5 turns  -> err ~1e-9  turns
    F1 * dt^2 / 2     <= ~2e7 turns  -> err ~4e-8  turns (young pulsars)
    binary/glitch/wave phases: smaller still.

The reference package gets the same guarantee from numpy longdouble
(reference: src/pint/pulsar_mjd.py:47-59; conftest.py:49 hard-requires
eps < 2e-19); this module is the TPU-native replacement.

Representations
---------------
- **time ticks**: int64, units of 2^-32 s since a model reference epoch.
  Range +/-2^31 s ~ +/-68 yr; resolution 0.23 ns (1.6e-7 turns at 716 Hz).
  TOA times become exact integers at host ingest and stay static across a
  fit — only F0 varies through this path.
- **frequency**: int64, units of 2^-52 Hz (max representable 2048 Hz,
  above the fastest known pulsar at 716 Hz; any IEEE f64 frequency
  >= 1.0 Hz is represented exactly, slower ones to 2.2e-16 Hz —
  worst case 7e-8 turns over 20 yr).
- **phase**: (int64 integer turns, float64 fractional turns in [-0.5,0.5)),
  the same split the reference's Phase class uses (src/pint/phase.py:7-116)
  so residuals survive catastrophic cancellation.

Differentiation: fixed-point values are piecewise-constant in their inputs,
so :func:`phase_f0_t` carries a ``jax.custom_jvp`` whose tangent is the
analytic float64 derivative d(phase) = t * dF0 — exactly the precision a
design matrix needs, without autodiff ever touching integer ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TICKS_PER_SEC = float(2**32)  #: time resolution: 2^-32 s per tick
FREQ_SCALE = float(2**52)  #: frequency resolution: 2^-52 Hz per unit

_MASK32 = jnp.uint64(0xFFFFFFFF)


def seconds_to_ticks_f64(sec):
    """Round float64 seconds to int64 ticks.

    Accurate to ~1 tick for |sec| < ~1e6 s even on TPU's sloppy f64; host
    ingest (which handles the full +/-68 yr range) converts via longdouble
    instead (:func:`pint_tpu.dd` / ingest layer), never through this.
    """
    return jnp.round(jnp.asarray(sec, jnp.float64) * TICKS_PER_SEC).astype(jnp.int64)


def ticks_to_seconds(ticks):
    """Ticks to float64 seconds (rel err ~2^-49 on TPU; fine for every
    non-F0 term — see module docstring error budget)."""
    return jnp.asarray(ticks).astype(jnp.float64) * (1.0 / TICKS_PER_SEC)


def freq_to_fix(f0):
    """Round a float64 frequency (Hz) to int64 units of 2^-52 Hz."""
    return jnp.round(jnp.asarray(f0, jnp.float64) * FREQ_SCALE).astype(jnp.int64)


def mul_64x64_128(a, b):
    """Exact signed 64x64 -> 128-bit product as (hi: int64, lo: uint64).

    Schoolbook with 32-bit limbs in uint64 accumulators; every partial
    product is < 2^64 and every add wraps mod 2^64 — bit-exact on TPU's
    int32-pair emulation.  Signedness via the two's-complement identity
    a_s * b_s = a_u * b_u - 2^64 * ((a<0)? b_u : 0) - 2^64 * ((b<0)? a_u : 0).
    """
    au = a.astype(jnp.uint64)
    bu = b.astype(jnp.uint64)
    a0 = au & _MASK32
    a1 = au >> jnp.uint64(32)
    b0 = bu & _MASK32
    b1 = bu >> jnp.uint64(32)

    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1

    mid = (p00 >> jnp.uint64(32)) + (p01 & _MASK32) + (p10 & _MASK32)
    lo = (p00 & _MASK32) | ((mid & _MASK32) << jnp.uint64(32))
    hi_u = (
        p11
        + (p01 >> jnp.uint64(32))
        + (p10 >> jnp.uint64(32))
        + (mid >> jnp.uint64(32))
    )
    corr = jnp.where(a < 0, bu, jnp.uint64(0)) + jnp.where(b < 0, au, jnp.uint64(0))
    hi = (hi_u - corr).astype(jnp.int64)
    return hi, lo


def phase_f0_t_raw(f0_fix, t_ticks):
    """Exact F0*t: (integer turns int64, fractional turns f64 in [-0.5,0.5)).

    The product f0_fix * t_ticks has units 2^-84 turns (2^-52 Hz x 2^-32 s).
    Integer turns = product >> 84 = hi >> 20 (lo holds only bits < 2^64).
    Fraction = bits 20..83 as uint64 / 2^64 (the dropped low 20 bits are
    < 2^-64 turns, far below the f64 conversion's own 2^-53).
    """
    hi, lo = mul_64x64_128(f0_fix, t_ticks)
    n = hi >> jnp.int64(20)
    frac_bits = (hi.astype(jnp.uint64) << jnp.uint64(44)) | (lo >> jnp.uint64(20))
    frac = frac_bits.astype(jnp.float64) * (1.0 / float(2**64))
    carry = frac >= 0.5
    n = jnp.where(carry, n + 1, n)
    frac = jnp.where(carry, frac - 1.0, frac)
    return n, frac


@jax.custom_jvp
def phase_f0_t(f0, t_ticks):
    """Exact pulse phase F0*t, differentiable in F0.

    f0: float64 Hz (quantized internally to 2^-52 Hz, exact for any IEEE
    f64 value >= 1.0 Hz); t_ticks: int64 ticks since the reference epoch.
    Returns (n: int64 integer turns, frac: float64 in [-0.5, 0.5)).

    Out-of-range inputs POISON the result with NaN frac instead of
    silently wrapping: the fixed-point representation holds f0 < 2^11 Hz
    (freq_to_fix is a 2^52-scaled int64) and |F0*t| < ~2^43 turns (the
    128-bit product carries 84 fraction bits).  Without the guard a
    garbage F0 (e.g. a diverged fit step or a wild grid point) wraps
    modulo 2^64 and can come back as a *perfect-looking* phase - chi2 0
    at a nonsense parameter value.
    """
    n, frac = phase_f0_t_raw(freq_to_fix(f0), t_ticks)
    expect = f0 * ticks_to_seconds(t_ticks)
    bad = (
        ~jnp.isfinite(expect)
        | (jnp.abs(expect) >= float(2**43))
        | (f0 <= 0.0)
        | (f0 >= 2048.0)
    )
    return jnp.where(bad, 0, n), jnp.where(bad, jnp.nan, frac)


@phase_f0_t.defjvp
def _phase_f0_t_jvp(primals, tangents):
    f0, t_ticks = primals
    df0, _ = tangents  # t_ticks is integer: its tangent is float0
    n, frac = phase_f0_t(f0, t_ticks)
    dfrac = ticks_to_seconds(t_ticks) * df0
    dn = jnp.zeros(n.shape, dtype=jax.dtypes.float0)
    return (n, frac), (dn, dfrac)


def renorm_phase(n, frac):
    """Re-center (n, frac) after float64 terms were added to frac, so frac
    is back in [-0.5, 0.5); multi-turn offsets roll into n."""
    # floor(frac + 0.5), not round(): half-to-even would leave frac == +0.5
    shift = jnp.floor(frac + 0.5)
    return n + shift.astype(jnp.int64), frac - shift


def backend_f64_is_ieee(backend=None):
    """Cheap runtime selftest: does the active backend's f64 support
    error-free transformations (i.e. correctly-rounded IEEE adds)?

    True on real-IEEE backends (CPU), False on TPU's ~49-bit f64
    emulation (measured; TPU_PRECISION.md).  Gates whether dd
    arithmetic (pint_tpu.dd) may be trusted beyond plain f64 on this
    device."""
    import numpy as np

    def probe(a, b):
        s = a + b
        bb = s - a
        err = (a - (s - bb)) + (b - bb)  # Knuth two_sum error term
        return s, err

    # pintlint: allow=PTL101 -- backend-pinned precision probe: the
    # explicit backend= targeting has no shared_jit equivalent, and
    # the probe must run on the device under test, not the default
    jprobe = jax.jit(probe, backend=backend)
    # pairs whose exact sum needs > 53 bits: the error term is nonzero
    # under IEEE and must reconstruct the exact value
    a = jnp.float64(1.0)
    b = jnp.float64(1e-17)
    s, err = jprobe(a, b)
    # exact: s = 1.0, err = 1e-17 under correct rounding
    ok = (float(s) == 1.0) and (float(err) == 1e-17)
    # a second, adversarial pair
    a2 = jnp.float64(4e11)
    b2 = jnp.float64(-1.2345678901234567e-5)
    s2, e2 = jprobe(a2, b2)
    exact = np.float64(4e11) + np.float64(-1.2345678901234567e-5)
    ok &= float(s2) == float(exact)
    return bool(ok)
