"""Downhill fitters: step-halving Gauss-Newton with optional
gradient-based noise-parameter fitting.

Counterpart of the reference DownhillFitter family (reference:
src/pint/fitter.py:982-1612): propose a WLS/GLS step, then
``take_step(lambda)`` with lambda-halving until chi^2 decreases; the
halving search runs as a ``lax.while_loop`` inside the jitted step, so a
full downhill iteration is one device program.  The white-noise-fitting
stage (reference ``_fit_noise``, fitter.py:1230) maximizes the analytic
``Residuals.lnlikelihood`` over free noise parameters with ``jax.grad``
supplying exact gradients (the reference uses hand-derived gradients +
scipy Newton-CG; here autodiff replaces the hand derivatives) and
``jax.hessian`` for uncertainties (the reference uses numdifftools).
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.fitter import Fitter, GLSFitter, WLSFitter, WidebandTOAFitter
from pint_tpu.telemetry import span

__all__ = ["DownhillWLSFitter", "DownhillGLSFitter",
           "WidebandDownhillFitter"]


class _DownhillMixin:
    """Adds the lambda-halving acceptance loop around a solver step and
    the optional noise-fitting stage."""

    #: 16 halvings reach lambda ~ 1.5e-5: a GN step along the Shapiro
    #: degeneracy can overshoot SINI past 1 by 1e-3-relative (measured
    #: on B1855 12.5yr wb: SINI=0.99918, dpar=+0.27 — lambda must fall
    #: below ~3e-3 before the stepped model is even valid)
    max_halvings = 16
    #: stop when chi2 decrease falls below this (reference fitter.py:1078)
    min_chi2_decrease = 1e-2

    def _retrace(self):
        """Key BOTH jitted steps (plain + halving) through the shared
        registry; the halving step is the one fit_toas drives."""
        super()._retrace()
        self._halving_jit = _cc.shared_jit(
            self._halving_step,
            key=("downhill.halving", type(self).__name__,
                 self._traced_free, self.max_halvings,
                 getattr(self, "threshold", None), self._guard_on,
                 self._iter_trace,
                 self._partition, self._frozen_names,
                 self._noise_frozen,
                 self.resids._structure_key()),
            donate_argnums=_cc.donation_argnums((0,)),
            label=f"downhill.halving:{type(self).__name__}")

    def _warm_entry(self):
        """warm_compile AOT-compiles the halving step — the downhill
        hot path (fitter.Fitter.warm_compile supplies the loop)."""
        return self._halving_jit

    def _chi2_at(self, values, data):
        return self.resids.chi2_at(values, data)

    def _halving_step(self, vec, base_values, data):
        """Propose dpar at vec, then find the largest lambda in
        {1, 1/2, 1/4, ...} whose step decreases chi^2.  Returns
        (new_vec, chi2_old, chi2_new, cov, health) — health is the
        propose step's guard record (empty tuple with the guard off);
        the in-trace halving itself is divergence-tolerant (a NaN
        chi^2 keeps halving, below)."""
        new_vec, chi2_old, dpar, cov, health = self._propose(
            vec, base_values, data)

        def chi2_of(v):
            return self._chi2_at(self._merged(base_values, v), data)

        def cond(carry):
            lam, chi2_new, n = carry
            # NOT(new < old), not (new >= old): a NaN chi2 (invalid
            # stepped model, e.g. SINI pushed past 1) must count as
            # "worse" and keep halving — `NaN >= x` is False and would
            # end the loop with the invalid step still rejected but
            # all remaining lambdas untried (measured hang-up on the
            # B1855 12.5yr wideband set; reference analogue: invalid
            # model parameters reject the step, fitter.py:1049-1057)
            return jnp.logical_and(
                jnp.logical_not(chi2_new < chi2_old),
                n < self.max_halvings
            )

        def body(carry):
            lam, _, n = carry
            lam = lam * 0.5
            return lam, chi2_of(vec + lam * dpar), n + 1

        lam0 = jnp.float64(1.0)
        lam, chi2_new, n = jax.lax.while_loop(
            cond, body, (lam0, chi2_of(vec + dpar), jnp.int32(0))
        )
        # if even the smallest lambda failed, stay put (reference keeps
        # the best state, fitter.py:1049-1057)
        ok = chi2_new < chi2_old
        lam = jnp.where(ok, lam, 0.0)
        chi2_new = jnp.where(ok, chi2_new, chi2_old)
        return vec + lam * dpar, chi2_old, chi2_new, cov, health

    def _iterate(self, maxiter, guard_eps=0.0, rung="baseline"):
        """One ladder rung of the downhill loop (fitter.Fitter._iterate
        contract): the in-trace lambda-halving already rejects
        chi^2-raising and NaN steps, so the guard's job here is the
        propose-solve health plus last-good tracking."""
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        data = self._guard_data(guard_eps)
        cov = None
        n_iter = 0
        health = ()
        self.converged = False
        last_good = np.array(
            [self.model.values[k] for k in self._traced_free])
        for _ in range(maxiter):
            vec_in = np.asarray(vec)  # pre-donation snapshot
            vec, chi2_old, chi2_new, cov, health = self._halving_jit(
                vec, base, data)
            n_iter += 1
            if np.isfinite(float(chi2_old)):
                last_good = vec_in
            if self._iter_trace:
                # the flight-recorder entry reads the ACCEPTED chi^2
                # (chi2_new — what the halving search served), so a
                # stalled search shows as chi2 plateau + zero step
                self._note_iteration(float(chi2_new), vec_in, vec,
                                     health, guard_eps, rung)
            self._check_step_health(health, last_good, n_iter)
            if float(chi2_old) - float(chi2_new) \
                    < self.min_chi2_decrease:
                self.converged = True
                break
        return vec, cov, (), n_iter, health

    def fit_toas(self, maxiter=20, fit_noise=False, noise_maxiter=100):
        if not self.model.free_timing_params:
            raise ValueError("no free timing parameters to fit")
        with telemetry.run_scope(
                "fit", fitter=type(self).__name__,
                n_toa=len(self.toas),
                fingerprint=self._inputs_fingerprint()), \
            span("downhill_fit", fitter=type(self).__name__,
                 n_toa=len(self.toas),
                 n_free=len(self.model.free_timing_params),
                 maxiter=maxiter) as sp:
            if tuple(self.model.free_timing_params) != getattr(
                    self, "_traced_free", ()):
                self._retrace()
            else:
                telemetry.counter_add("fitter.jit_cache_hits")
                self._refresh_frozen()
            self._iter_entries = [] if self._iter_trace else None
            vec, cov_np, n_iter, health, rung = \
                self._fit_with_depth_guard(
                    lambda: self._guard_rungs(maxiter))
            flops_est = self._fit_flops_est(n_iter)
            telemetry.counter_add("fitter.iterations", n_iter)
            telemetry.counter_add("fit.flops_est", flops_est)
            sp.set(n_iter=n_iter, converged=self.converged,
                   flops_est=flops_est)
            self._record_guard(rung, health, sp)
            self._emit_iter_trace(rung)
            self._update_fit_meta()
            self._post_fit()
        if fit_noise:
            self.fit_noise(maxiter=noise_maxiter)
        return float(self.resids.chi2)

    # -- noise-parameter fitting ---------------------------------------------
    @property
    def free_noise_params(self):
        return self.model.free_noise_params

    def fit_noise(self, maxiter=100):
        """Maximize lnlikelihood over the free noise parameters
        (reference _fit_noise, fitter.py:1230).  Timing parameters stay
        fixed; uncertainties from the inverse Hessian."""
        names = self.free_noise_params
        if not names:
            raise ValueError(
                "no free noise parameters (unfreeze EFAC/EQUAD/ECORR/... "
                "params to fit them)"
            )
        base = self.prepared._values_pytree()
        data = self.resids._data()

        def neg_lnl(v, base_values, fit_data):
            values = dict(base_values)
            for i, n in enumerate(names):
                values[n] = v[i]
            return -self.resids.lnlikelihood_at(values, fit_data)

        # base values and dataset are dynamic arguments, so the traced
        # gradient is shared across same-structure fitters (and across
        # repeated fit_noise calls) through the registry
        val_grad = _cc.shared_jit(
            jax.value_and_grad(neg_lnl),
            key=("downhill.fit_noise", type(self).__name__,
                 tuple(names), self.resids._structure_key()),
            fn_token="downhill.fit_noise")
        x = np.array([self.model.values[n] for n in names], dtype=np.float64)

        from scipy.optimize import minimize

        def fun(v):
            f, g = val_grad(jnp.asarray(v), base, data)
            return float(f), np.asarray(g, dtype=np.float64)

        with span("fit_noise", n_noise=len(names), maxiter=maxiter):
            res = minimize(
                fun, x, jac=True, method="L-BFGS-B",
                options={"maxiter": maxiter},
            )
        # a DIVERGED L-BFGS-B (non-finite optimum) must never poison
        # model.values: keep the last-good (input) values, flag, warn.
        # Mere maxiter exhaustion (success=False, status=1, finite
        # improved x) is NOT divergence — discarding the finite
        # optimum would regress the pre-guard behavior; it writes back
        # with noise_fit_ok=False and a "not_converged" flag instead.
        diverged = (not np.all(np.isfinite(res.x))
                    or not np.isfinite(res.fun))
        self.noise_fit_ok = bool(res.success) and not diverged
        if diverged:
            telemetry.counter_add("guard.trips")
            telemetry.counter_add("guard.trip.noise_fit")
            self.model.meta["GUARD_NOISE_FIT"] = "diverged"
            self.noise_covariance = None
            warnings.warn(
                f"fit_noise diverged (success={res.success}, "
                f"fun={res.fun!r}); keeping pre-fit noise values — see "
                "model.meta['GUARD_NOISE_FIT']")
            return -np.inf
        if not res.success:
            telemetry.counter_add("guard.trip.noise_fit_not_converged")
            self.model.meta["GUARD_NOISE_FIT"] = "not_converged"
            warnings.warn(
                f"fit_noise did not converge ({res.message}); writing "
                "back the finite partial optimum — see "
                "model.meta['GUARD_NOISE_FIT']")
        else:
            # a later clean fit clears the flag (the meta lands in the
            # output par file and must describe THIS fit)
            self.model.meta.pop("GUARD_NOISE_FIT", None)
        x = res.x
        for i, n in enumerate(names):
            self.model.values[n] = float(x[i])
        # uncertainties: inverse Hessian of -lnL at the optimum.  A
        # NaN/inf Hessian passes np.linalg.inv WITHOUT LinAlgError and
        # yields garbage uncertainties — pinv with an explicit
        # finiteness check, and noise_covariance = None plus a
        # diagnostic when it fails
        H = np.asarray(
            jax.hessian(lambda v: neg_lnl(v, base, data))(jnp.asarray(x)))
        hinv = None
        if np.all(np.isfinite(H)):
            try:
                hinv = np.linalg.pinv(H)
            except np.linalg.LinAlgError:
                hinv = None
        if hinv is not None and np.all(np.isfinite(hinv)):
            errs = np.sqrt(np.clip(np.diag(hinv), 0, None))
            params = self.model.params
            for i, n in enumerate(names):
                params[n].uncertainty = float(errs[i])
            self.noise_covariance = hinv
        else:
            self.noise_covariance = None
            telemetry.counter_add("guard.trip.noise_hessian")
            warnings.warn(
                "fit_noise: non-finite/singular Hessian at the optimum "
                "— noise uncertainties not updated "
                "(noise_covariance=None)")
        return -float(res.fun)


class DownhillWLSFitter(_DownhillMixin, WLSFitter):
    """Step-halving WLS (reference DownhillWLSFitter, fitter.py:1379)."""

    def _propose(self, vec, base_values, data):
        new_vec, chi2, dpar, cov, health = WLSFitter._step(
            self, vec, base_values, data)
        return new_vec, \
            self._chi2_at(self._merged(base_values, vec), data), \
            dpar, cov, health


class DownhillGLSFitter(_DownhillMixin, GLSFitter):
    """Step-halving GLS (reference DownhillGLSFitter, fitter.py:1527)."""

    def _propose(self, vec, base_values, data):
        new_vec, chi2, dpar, cov, _ncoef, health = GLSFitter._step(
            self, vec, base_values, data)
        return new_vec, chi2, dpar, cov, health


class WidebandDownhillFitter(_DownhillMixin, WidebandTOAFitter):
    """Step-halving wideband fitter (reference WidebandDownhillFitter,
    fitter.py:1812)."""

    def _propose(self, vec, base_values, data):
        new_vec, chi2, dpar, cov, _ncoef, health = \
            WidebandTOAFitter._step(self, vec, base_values, data)
        return new_vec, chi2, dpar, cov, health
