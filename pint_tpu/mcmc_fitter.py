"""Photon-domain MCMC fitting: timing (+template) params against the
photon likelihood.

Counterpart of the reference MCMCFitter family (reference:
src/pint/mcmc_fitter.py:110-682 ``MCMCFitter``/
``MCMCFitterBinnedTemplate``/``MCMCFitterAnalyticTemplate``,
``lnposterior`` at :282): the posterior is priors + the Kerr (2011)
weighted photon likelihood of template(phase).  TPU redesign: the
phase-at-photons computation AND the template density are one jitted
function of the parameter vector, so every walker step of the ensemble
sampler (:mod:`pint_tpu.sampler`) evaluates the full photon likelihood
on device; autodiff gradients are available for HMC-style samplers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import flops as _flops
from pint_tpu import telemetry
from pint_tpu.bayesian import UniformPrior
from pint_tpu.sampler import EnsembleSampler
from pint_tpu.telemetry import span

__all__ = ["MCMCFitter", "MCMCFitterAnalyticTemplate",
           "MCMCFitterBinnedTemplate", "CompositeMCMCFitter"]


class MCMCFitter:
    """Sample timing parameters against the photon-template likelihood.

    template: LCTemplate (analytic, reference
    MCMCFitterAnalyticTemplate) or a binned profile given as an array
    of bin heights (reference MCMCFitterBinnedTemplate).
    """

    def __init__(self, toas, model, template, weights=None, priors=None,
                 width_sigma=10.0, fit_template=False):
        self.toas = toas
        self.model = model
        self.prepared = model.prepare(toas)
        self.template = template
        self.fit_template = bool(fit_template)
        if weights is None:
            wf = toas.get_flag_values("weight", default=None, astype=float)
            if any(w is not None for w in wf):
                weights = np.array(
                    [1.0 if w is None else w for w in wf]
                )
        self.weights = None if weights is None else jnp.asarray(weights)
        self.param_names = list(model.free_params)
        self.nparams = len(self.param_names)
        self.priors = {}
        priors = priors or {}
        for name in self.param_names:
            if name in priors:
                self.priors[name] = priors[name]
                continue
            # per-parameter priors attached on the Param itself win
            # over the uncertainty-derived default (reference: each
            # Parameter carries a Prior object)
            pprior = getattr(model.params[name], "prior", None)
            if pprior is not None:
                self.priors[name] = pprior
                continue
            unc = model.params[name].uncertainty
            val = float(model.values[name])
            if not unc:
                raise ValueError(
                    f"no uncertainty for {name}; pass an explicit prior"
                )
            w = width_sigma * float(unc)
            self.priors[name] = UniformPrior(val - w, val + w)
        self._base = self.prepared._values_pytree()
        self._binned = isinstance(template, (list, np.ndarray,
                                             jnp.ndarray))
        if self._binned:
            bins = jnp.asarray(np.asarray(template, dtype=np.float64))
            bins = bins / jnp.mean(bins)  # normalize to density

            def density(phi, _params=None):
                idx = jnp.clip(
                    (phi % 1.0 * bins.shape[0]).astype(jnp.int32),
                    0, bins.shape[0] - 1,
                )
                return bins[idx]

            self._density = density
            self._n_template = 0
        else:
            self._density = template.density
            self._n_template = template.n_params if fit_template else 0

    # -- the posterior --------------------------------------------------------
    def _phases_fn(self, values):
        _, frac = self.prepared._phase_raw(values)
        return frac % 1.0

    def lnposterior(self, vec):
        values = dict(self._base)
        for i, name in enumerate(self.param_names):
            values[name] = vec[i]
        lnp = 0.0
        for i, name in enumerate(self.param_names):
            lnp = lnp + self.priors[name].lnpdf(vec[i])
        phi = self._phases_fn(values)
        if self._n_template:
            f = self._density(phi, vec[self.nparams:])
        elif self._binned:
            f = self._density(phi)
        else:
            f = self._density(phi, jnp.asarray(self.template.params))
        if self.weights is None:
            lnl = jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
        else:
            lnl = jnp.sum(
                jnp.log(jnp.maximum(
                    self.weights * f + (1.0 - self.weights), 1e-300
                ))
            )
        return lnp + lnl

    def _sampler_jit_key(self):
        """Registry identity of this fitter's posterior: the chain
        program bakes in the model structure, the base values, the
        template, the weights and the priors — all fingerprinted, so a
        second identically-configured MCMCFitter (or every chunk of an
        autocorr run) reuses ONE compiled chain instead of retracing
        the posterior per instance."""
        def _prior_sig(p):
            try:
                items = vars(p).items()
            except TypeError:  # __slots__ priors: fall back to repr
                return repr(p)
            return repr(sorted(
                (k, v) for k, v in items
                if isinstance(v, (int, float, str, bool))))

        priors = [
            (n, type(p).__name__, _prior_sig(p))
            for n, p in sorted(self.priors.items())
        ]
        tpl = (np.asarray(self.template, dtype=np.float64)
               if self._binned else np.asarray(self.template.params))
        return ("mcmc.lnposterior",
                _cc.model_structure_key(self.model),
                tuple(self.param_names), self._n_template,
                _cc.fingerprint((self._base, self.weights, tpl, priors,
                                 # the photon dataset itself: lnposterior
                                 # closes over prepared.batch, so two
                                 # same-config fitters on different
                                 # events must NOT share a trace
                                 self.prepared.batch)))

    # -- driver ---------------------------------------------------------------
    def lnlike_only(self, vec):
        """Photon likelihood without the prior terms (used by the
        composite multi-dataset fitter, which counts priors once)."""
        values = dict(self._base)
        for i, name in enumerate(self.param_names):
            values[name] = vec[i]
        phi = self._phases_fn(values)
        if self._n_template:
            f = self._density(phi, vec[self.nparams:])
        elif self._binned:
            f = self._density(phi)
        else:
            f = self._density(phi, jnp.asarray(self.template.params))
        if self.weights is None:
            return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
        return jnp.sum(jnp.log(jnp.maximum(
            self.weights * f + (1.0 - self.weights), 1e-300)))

    def fit_toas(self, nwalkers=32, nsteps=500, seed=0, burn_frac=0.25,
                 autocorr=False, burnin=None, checkpoint=None):
        """Run the ensemble sampler; set model values to the
        max-posterior sample (reference MCMCFitter.fit_toas maxpost).
        Returns the max-posterior lnL.

        ``autocorr=True`` samples in chunks until the emcee
        convergence criterion is met (chain > 50 tau, tau stable to
        10%%) with ``nsteps`` as the cap (reference event_optimize
        run_sampler_autocorr); the default burn-in is then
        ``5 * max(tau)`` rather than a fraction of the cap.
        ``burnin`` (absolute steps) overrides either default.

        ``checkpoint`` (autocorr runs only): path for per-chunk
        atomic chain snapshots; an existing checkpoint resumes the
        run, validated against this fitter's posterior fingerprint
        (``_sampler_jit_key``) so a chain from a different model/
        dataset/prior configuration can never be silently reused."""
        ndim = self.nparams + self._n_template
        center = np.array(
            [self.model.values[n] for n in self.param_names]
            + (list(self.template.params) if self._n_template else [])
        )
        scales = []
        for name in self.param_names:
            p = self.priors[name]
            scales.append(
                (p.hi - p.lo) / 100.0 if isinstance(p, UniformPrior)
                else p.sigma
            )
        scales += [0.01] * self._n_template
        s = EnsembleSampler(self.lnposterior, nwalkers=nwalkers,
                            seed=seed, jit_key=self._sampler_jit_key())
        x0 = s.initial_ball(center, np.array(scales))
        with span("mcmc.sample", nwalkers=nwalkers, nsteps=nsteps,
                  n_toa=len(self.toas), autocorr=autocorr) as sp:
            if autocorr:
                _, self.converged, self.tau = s.run_mcmc_autocorr(
                    x0, chunk=max(50, nsteps // 10), maxsteps=nsteps,
                    checkpoint=checkpoint)
                chain_len = int(np.asarray(s.chain).shape[0])
                burn = (int(burnin) if burnin is not None
                        else int(min(5 * np.max(self.tau),
                                     chain_len // 2))
                        if np.all(np.isfinite(self.tau))
                        else chain_len // 4)
            else:
                s.run_mcmc(x0, nsteps)
                chain_len = int(nsteps)
                burn = (int(burnin) if burnin is not None
                        else int(burn_frac * nsteps))
            flops_est = _flops.mcmc_flops(nwalkers * chain_len,
                                          len(self.toas))
            telemetry.counter_add("fit.flops_est", flops_est)
            sp.set(chain_len=chain_len, flops_est=flops_est)
        best, lnp = s.max_posterior()
        for i, name in enumerate(self.param_names):
            self.model.values[name] = float(best[i])
        if self._n_template:
            self.template.params = np.asarray(best[self.nparams:])
        if burn >= chain_len:
            import warnings

            warnings.warn(
                f"burn-in {burn} >= chain length {chain_len} (autocorr "
                "run converged early?); using chain_len//2 so the "
                "uncertainty sample stays meaningful")
            burn = chain_len // 2
        flat = s.flatchain(burn=burn)
        params = self.model.params
        for i, name in enumerate(self.param_names):
            params[name].uncertainty = float(flat[:, i].std())
        self.sampler = s
        return lnp


class MCMCFitterAnalyticTemplate(MCMCFitter):
    """Named variant requiring an analytic LCTemplate (reference
    MCMCFitterAnalyticTemplate) — MCMCFitter auto-detects, this class
    just validates the intent at construction."""

    def __init__(self, toas, model, template, **kw):
        if isinstance(template, (list, np.ndarray, jnp.ndarray)):
            raise TypeError(
                "MCMCFitterAnalyticTemplate needs an LCTemplate; use "
                "MCMCFitterBinnedTemplate for binned profiles")
        super().__init__(toas, model, template, **kw)


class MCMCFitterBinnedTemplate(MCMCFitter):
    """Named variant requiring a binned profile array (reference
    MCMCFitterBinnedTemplate)."""

    def __init__(self, toas, model, template, **kw):
        if not isinstance(template, (list, np.ndarray, jnp.ndarray)):
            raise TypeError(
                "MCMCFitterBinnedTemplate needs an array of bin "
                "heights; use MCMCFitterAnalyticTemplate for "
                "LCTemplate objects")
        super().__init__(toas, model, template, **kw)


class CompositeMCMCFitter:
    """Joint photon-likelihood MCMC over several event datasets sharing
    one timing model (reference: the composite fitter behind
    event_optimize_multiple).  Each dataset carries its own template
    and photon weights; the timing parameters (and their priors,
    counted once) are common."""

    def __init__(self, toas_list, model, templates, weights_list=None,
                 priors=None, width_sigma=10.0):
        if weights_list is None:
            weights_list = [None] * len(toas_list)
        if len(templates) != len(toas_list):
            raise ValueError("one template per dataset required")
        self.model = model
        self.fitters = [
            MCMCFitter(t, model, tpl, weights=w, priors=priors,
                       width_sigma=width_sigma)
            for t, tpl, w in zip(toas_list, templates, weights_list)
        ]
        f0 = self.fitters[0]
        self.param_names = f0.param_names
        self.nparams = f0.nparams
        self.priors = f0.priors

    def lnposterior(self, vec):
        lnp = 0.0
        for i, name in enumerate(self.param_names):
            lnp = lnp + self.priors[name].lnpdf(vec[i])
        for f in self.fitters:
            lnp = lnp + f.lnlike_only(vec)
        return lnp

    def fit_toas(self, nwalkers=32, nsteps=500, seed=0, burn_frac=0.25):
        center = np.array(
            [self.model.values[n] for n in self.param_names])
        scales = []
        for name in self.param_names:
            p = self.priors[name]
            scales.append(
                (p.hi - p.lo) / 100.0 if isinstance(p, UniformPrior)
                else p.sigma
            )
        s = EnsembleSampler(
            self.lnposterior, nwalkers=nwalkers, seed=seed,
            jit_key=("mcmc.composite",) + tuple(
                f._sampler_jit_key() for f in self.fitters))
        x0 = s.initial_ball(center, np.array(scales))
        with span("mcmc.sample", nwalkers=nwalkers, nsteps=nsteps,
                  composite=len(self.fitters)):
            s.run_mcmc(x0, nsteps)
        best, lnp = s.max_posterior()
        for i, name in enumerate(self.param_names):
            self.model.values[name] = float(best[i])
        burn = int(burn_frac * nsteps)
        flat = s.flatchain(burn=burn)
        params = self.model.params
        for i, name in enumerate(self.param_names):
            params[name].uncertainty = float(flat[:, i].std())
        self.sampler = s
        return lnp
