"""TOA ingest: .tim parsing, host container, device batch.

Counterpart of the reference's data layer (reference: src/pint/toa.py:109
``get_TOAs``, :1183 ``TOAs``), redesigned around the TPU split:

- **host side** (this module, numpy + exact integer time math): parse
  ``.tim`` files (tempo2 / Princeton / ITOA line formats and the command
  set MODE/FORMAT/TIME/EFAC/EQUAD/PHASE/JUMP/SKIP/INCLUDE/END, reference
  toa.py:441,471,701), apply observatory clock chains, convert to TDB
  ticks, evaluate observatory & solar-system geometry per TOA.
- **device side**: :class:`TOABatch`, a frozen struct-of-arrays pytree
  (int64 ticks + float64 geometry) that the jitted delay/phase chain
  consumes.  Per-flag boolean masks are resolved at model-prep time, so
  the reference's repeated "Select TOA Mask" cost (profiling/README.txt:60,
  10.8 s) becomes a one-time ingest step.

No astropy Table, no per-TOA python objects on the hot path.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

import jax.numpy as jnp

from pint_tpu.obs import get_observatory
from pint_tpu.time.mjd import (
    mjd_string_to_day_frac,
    mjd_to_ticks_tdb,
    mjd_to_ticks_utc,
)

__all__ = ["TOA", "TOAs", "TOABatch", "get_TOAs", "read_tim"]


@dataclass
class TOA:
    """One parsed TOA (host side only; never reaches the device)."""

    mjd_day: int
    frac_num: int
    frac_den: int
    error_us: float
    freq_mhz: float
    obs: str
    flags: dict = field(default_factory=dict)
    name: str = ""


# --- tim file parsing -------------------------------------------------------


def _toa_line_format(line: str, tempo2_mode: bool = False) -> str:
    """Classify a TOA data line (reference behavior: toa.py:441).

    Stateful like the reference: after a ``FORMAT 1`` command every data
    line is Tempo2; otherwise Princeton is the legacy default, with
    Parkes/ITOA recognized by their fixed-column signatures.
    """
    if not line.strip():
        return "Blank"
    if line.startswith(("C ", "c ", "#", "CC ")):
        return "Comment"
    first = line.split()[0] if line.split() else ""
    if first.upper() in _COMMANDS:
        return "Command"
    if tempo2_mode or len(line) > 80:
        return "Tempo2"
    if line.startswith(" ") and len(line) > 41 and line[41] == ".":
        return "Parkes"
    if (
        len(line) > 25
        and line[0].isalpha()
        and line[1].isalpha()
        and line[14:15] == "."
    ):
        return "ITOA"
    return "Princeton"


_COMMANDS = {
    "FORMAT",
    "MODE",
    "TIME",
    "EFAC",
    "EQUAD",
    "EMAX",
    "EMIN",
    "FMAX",
    "FMIN",
    "SKIP",
    "NOSKIP",
    "END",
    "PHASE",
    "PHA1",
    "PHA2",
    "JUMP",
    "INCLUDE",
    "INFO",
    "TRACK",
}


def _parse_line(line: str, fmt: str):
    """One data line -> TOA (without command-state effects applied)."""
    if fmt == "Tempo2":
        parts = line.split()
        if len(parts) < 5:
            raise ValueError(f"bad tempo2 TOA line: {line!r}")
        name, freq, mjd, err, obs = parts[:5]
        flags = {}
        i = 5
        while i < len(parts):
            tok = parts[i]
            if tok.startswith("-") and not _is_number(tok):
                key = tok.lstrip("-")
                if i + 1 < len(parts):
                    flags[key] = parts[i + 1]
                    i += 2
                else:
                    flags[key] = ""
                    i += 1
            else:
                i += 1
        d, n, den = mjd_string_to_day_frac(mjd)
        return TOA(d, n, den, float(err), float(freq), obs, flags, name)
    if fmt == "Princeton":
        obs = line[0]
        name = line[2:15].strip()
        freq = float(line[15:24])
        d, n, den = mjd_string_to_day_frac(line[24:44])
        err = float(line[44:53])
        flags = {}
        dmc = line[68:78].strip()
        if dmc:
            flags["ddm"] = dmc
        return TOA(d, n, den, err, freq, obs, flags, name)
    if fmt == "Parkes":
        name = line[1:25].strip()
        freq = float(line[25:34])
        d, n, den = mjd_string_to_day_frac(line[34:55])
        # phase offset at line[55:63] (rarely used)
        err = float(line[63:71])
        obs = line[79] if len(line) > 79 else line.strip()[-1]
        return TOA(d, n, den, err, freq, obs, {}, name)
    if fmt == "ITOA":
        name = line[0:2]
        d, n, den = mjd_string_to_day_frac(line[9:28])
        err = float(line[28:34])
        freq = float(line[34:45])
        obs = line[57:59].strip()
        return TOA(d, n, den, err, freq, obs, {}, name)
    raise ValueError(f"unhandled TOA format {fmt}")


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def read_tim(path, _depth=0):
    """Parse a .tim file -> list[TOA], applying command state
    (TIME/EFAC/EQUAD/PHASE/JUMP/SKIP/INCLUDE; reference toa.py:701)."""
    toas = []
    state = {
        "time_offset_s": 0.0,
        "efac": 1.0,
        "equad_us": 0.0,
        "phase": 0.0,
        "jump": 0,
        "njumps": 0,
        "skip": False,
        "emax": None,
        "emin": None,
        "fmax": None,
        "fmin": None,
        "info": None,
        "fmt_tempo2": False,
    }
    _read_tim_into(path, toas, state, _depth)
    return toas


def _parse_flags(s: str) -> dict:
    """Parse the '-key value' tail of a tempo2 TOA line."""
    parts = s.split()
    flags = {}
    i = 0
    while i < len(parts):
        tok = parts[i]
        if tok.startswith("-") and not _is_number(tok):
            key = tok.lstrip("-")
            if i + 1 < len(parts):
                flags[key] = parts[i + 1]
                i += 2
            else:
                flags[key] = ""
                i += 1
        else:
            i += 1
    return flags


def _read_tim_into(path, toas, state, depth):
    if depth > 5:
        raise ValueError("INCLUDE nesting too deep")
    with open(path, "rb") as fb:
        text = fb.read()
    raw_lines = text.decode(errors="replace").split("\n")
    # native batch parse of every line (tempo2 data lines come back
    # with status 0; commands/other formats fall through to Python)
    native = None
    try:
        from pint_tpu.native import parse_tim_lines_native

        # Offsets are computed on the raw *bytes* (never on re-encoded
        # decoded text: a non-UTF-8 byte decodes to U+FFFD which would
        # re-encode as 3 bytes and silently shift every later line).
        nl = np.flatnonzero(np.frombuffer(text, np.uint8) == 0x0A)
        offs = np.concatenate((
            [0], nl + 1, [len(text) + 1]
        )).astype(np.int64)
        if len(offs) - 1 != len(raw_lines):  # paranoia: fall to Python
            raise ValueError("line count mismatch")
        # pad so the final line's +1 newline slot is in bounds; the C
        # side strips trailing newlines itself
        native = parse_tim_lines_native(text + b"\n", offs)
    except Exception:
        native = None
    for lineno, raw in enumerate(raw_lines):
        line = raw.rstrip("\r")
        if not line.strip():
            continue
        fmt = _toa_line_format(line, state["fmt_tempo2"])
        if fmt in ("Blank", "Comment"):
            continue
        if fmt == "Command":
            parts = line.split()
            cmd = parts[0].upper()
            arg = parts[1] if len(parts) > 1 else None
            if cmd == "FORMAT":
                state["fmt_tempo2"] = arg == "1"
            elif cmd == "MODE":
                pass  # MODE 1 (errors in us) is the only supported mode
            elif cmd == "TIME":
                state["time_offset_s"] += float(arg or 0.0)
            elif cmd == "EFAC":
                state["efac"] = float(arg or 1.0)
            elif cmd == "EQUAD":
                state["equad_us"] = float(arg or 0.0)
            elif cmd == "EMAX":
                state["emax"] = float(arg)
            elif cmd == "EMIN":
                state["emin"] = float(arg)
            elif cmd == "FMAX":
                state["fmax"] = float(arg)
            elif cmd == "FMIN":
                state["fmin"] = float(arg)
            elif cmd in ("PHASE", "PHA1", "PHA2"):
                state["phase"] += float(arg or 0.0)
            elif cmd == "JUMP":
                if state["jump"]:
                    state["jump"] = 0
                else:
                    state["njumps"] += 1
                    state["jump"] = state["njumps"]
            elif cmd == "SKIP":
                state["skip"] = True
            elif cmd == "NOSKIP":
                state["skip"] = False
            elif cmd == "INFO":
                state["info"] = arg
            elif cmd == "INCLUDE":
                sub = os.path.join(os.path.dirname(str(path)), arg)
                _read_tim_into(sub, toas, state, depth + 1)
            elif cmd == "END":
                return
            continue
        if state["skip"]:
            continue
        if (
            fmt == "Tempo2"
            and native is not None
            and native["status"][lineno] == 0
        ):
            # native fast path: exact integer MJD split done in C; only
            # the name token and flag substring touch Python
            fo = int(native["flags_off"][lineno])
            toa = TOA(
                int(native["day"][lineno]),
                int(native["frac_num"][lineno]),
                int(native["frac_den"][lineno]),
                float(native["err_us"][lineno]),
                float(native["freq_mhz"][lineno]),
                native["sites"][lineno].decode(),
                _parse_flags(line[fo:]) if fo >= 0 else {},
                line.split(None, 1)[0],
            )
        else:
            try:
                toa = _parse_line(line, fmt)
            except (ValueError, IndexError) as e:
                warnings.warn(
                    f"skipping unparseable TOA line {line!r}: {e}"
                )
                continue
        if state["emax"] is not None and toa.error_us > state["emax"]:
            continue
        if state["emin"] is not None and toa.error_us < state["emin"]:
            continue
        if state["fmax"] is not None and toa.freq_mhz > state["fmax"]:
            continue
        if state["fmin"] is not None and toa.freq_mhz < state["fmin"]:
            continue
        toa.error_us = toa.error_us * state["efac"]
        if state["equad_us"]:
            toa.error_us = float(
                np.hypot(toa.error_us, state["equad_us"])
            )
        if state["time_offset_s"]:
            toa.flags["to"] = repr(state["time_offset_s"])
        if state["phase"]:
            toa.flags["padd"] = repr(state["phase"])
        if state["jump"]:
            toa.flags["tim_jump"] = str(state["jump"])
        if state["info"]:
            toa.flags.setdefault("info", state["info"])
        toas.append(toa)


# --- host container ---------------------------------------------------------


class TOAs:
    """Host-side TOA table (struct of numpy arrays + python flag dicts)."""

    # class-level defaults for objects revived via object.__new__ paths
    # (slicing/merge/cache); __init__ and get_TOAs set the real values
    include_clock = True
    include_bipm = False
    bipm_version = "BIPM2019"

    def __init__(self, toa_list, ephem="builtin", planets=False,
                 include_clock=True, include_bipm=False,
                 bipm_version="BIPM2019"):
        if not toa_list:
            raise ValueError("no TOAs")
        self.ephem = ephem
        self.planets = planets
        # retained so re-reads (e.g. pintk's tim editor) can reproduce
        # the same clock/BIPM preparation
        self.include_clock = include_clock
        self.include_bipm = include_bipm
        self.bipm_version = bipm_version
        n = len(toa_list)
        self.flags = [dict(t.flags) for t in toa_list]
        self.names = [t.name for t in toa_list]
        self.error_us = np.array([t.error_us for t in toa_list])
        self.freq_mhz = np.array([t.freq_mhz for t in toa_list])
        self.freq_mhz[self.freq_mhz == 0.0] = np.inf  # 0 => infinite freq
        self.obs_names = [get_observatory(t.obs).name for t in toa_list]
        obs_unique = sorted(set(self.obs_names))
        self.obs_index = np.array(
            [obs_unique.index(o) for o in self.obs_names], dtype=np.int64
        )
        self.obs_list = obs_unique

        # clock corrections per observatory group (host, float64 seconds)
        mjd_float = np.array(
            [t.mjd_day + t.frac_num / t.frac_den for t in toa_list]
        )
        self.mjd_float = mjd_float
        clock = np.zeros(n)
        if include_clock:
            for io, oname in enumerate(obs_unique):
                obs = get_observatory(oname)
                m = self.obs_index == io
                if not obs.is_barycenter:
                    clock[m] = obs.clock_corrections_sec(mjd_float[m])
        # TT(BIPMxxxx) realization offsets ride the same additive path
        # (reference: bipm_correction, observatory/__init__.py:253)
        if include_bipm:
            from pint_tpu.obs.clock import find_bipm_correction

            bipm = find_bipm_correction(bipm_version)
            if bipm is None:
                warnings.warn(
                    f"CLK TT({bipm_version}) requested but no "
                    "tai2tt_bipmXXXX.clk data found in "
                    "$PINT_TPU_CLOCK_DIR; using TT(TAI) (the BIPM "
                    "realization differs by ~27 us + slow drift)"
                )
            else:
                topo = np.array([
                    not get_observatory(o).is_barycenter
                    for o in self.obs_names])
                clock[topo] += bipm.evaluate_sec(mjd_float[topo])
        # TIME command offsets ride the clock path too
        for i, fl in enumerate(self.flags):
            if "to" in fl:
                clock[i] += float(fl["to"])
        self.clock_sec = clock

        # UTC(site)->TDB ticks (exact integer path per TOA)
        ticks = np.empty(n, dtype=np.int64)
        for i, t in enumerate(toa_list):
            obs = get_observatory(self.obs_names[i])
            scale = t.flags.get("timescale", "utc").lower()
            if scale not in ("utc", "tt", "tdb"):
                raise ValueError(
                    f"TOA {i}: unsupported -timescale {scale!r} "
                    "(utc|tt|tdb) — e.g. TIMESYS=TAI event files must "
                    "be converted first; silently treating it as UTC "
                    "would shift times by the ~37 s leap-second total"
                )
            if obs.is_barycenter or scale == "tdb":
                # already in the TDB scale (barycentered data, or photon
                # events with TIMESYS=TDB); TIME offsets still apply
                ticks[i] = mjd_to_ticks_tdb(
                    t.mjd_day, t.frac_num, t.frac_den
                ) + int(round(clock[i] * 2**32))
            elif scale == "tt":
                # photon-event TT (e.g. NICER MET): only the small
                # TDB-TT harmonic term remains
                from pint_tpu.time.scales import tdb_minus_tt_seconds

                tt = mjd_to_ticks_tdb(t.mjd_day, t.frac_num, t.frac_den)
                dtdb = float(tdb_minus_tt_seconds(tt / 2**32))
                ticks[i] = tt + int(round(
                    (dtdb + clock[i]) * 2**32
                ))
            else:
                ticks[i] = mjd_to_ticks_utc(
                    t.mjd_day, t.frac_num, t.frac_den,
                    clock_offset_sec=clock[i],
                )
        self.ticks = ticks
        self._compute_posvels()

    def __len__(self):
        return len(self.flags)

    def _compute_posvels(self):
        """Observatory & solar-system geometry at each TOA (reference:
        toa.py:2323 compute_posvels)."""
        from pint_tpu.ephem import body_posvel_ssb

        n = len(self)
        self.ssb_obs_pos = np.zeros((n, 3))
        self.ssb_obs_vel = np.zeros((n, 3))
        for io, oname in enumerate(self.obs_list):
            obs = get_observatory(oname)
            m = self.obs_index == io
            if getattr(obs, "needs_flags", False):
                fl = [f for f, take in zip(self.flags, m) if take]
                pv = obs.posvel_ssb(self.ticks[m], ephem=self.ephem,
                                    flags=fl)
            else:
                pv = obs.posvel_ssb(self.ticks[m], ephem=self.ephem)
            self.ssb_obs_pos[m] = pv.pos
            self.ssb_obs_vel[m] = pv.vel
        sun = body_posvel_ssb("sun", self.ticks, self.ephem)
        self.obs_sun_pos = sun.pos - self.ssb_obs_pos
        self.planet_pos = {}
        if self.planets:
            for b in ("venus", "mars", "jupiter", "saturn", "uranus", "neptune"):
                pv = body_posvel_ssb(b, self.ticks, self.ephem)
                self.planet_pos[b] = pv.pos - self.ssb_obs_pos

    def get_flag_values(self, flag, default=None, astype=str):
        return [astype(f[flag]) if flag in f else default for f in self.flags]

    # -- pulse numbers (reference: toa.py:1709 get_pulse_numbers,
    # :1984 compute_pulse_numbers, delta_pulse_number column :1272) ----------
    def get_pulse_numbers(self):
        """Per-TOA absolute pulse numbers from ``-pn`` flags (float64,
        NaN where absent), or None when no TOA carries one."""
        if not any("pn" in f for f in self.flags):
            return None
        return np.array(
            [float(f["pn"]) if "pn" in f else np.nan for f in self.flags]
        )

    def get_delta_pulse_numbers(self):
        """Accumulated PHASE-command / ``-padd`` phase offsets (turns),
        zero where absent."""
        return np.array([float(f.get("padd", 0.0)) for f in self.flags])

    def compute_pulse_numbers(self, model):
        """Assign ``-pn`` flags = nearest-integer absolute pulse number
        under ``model`` (reference toa.py:1984): the anchor for
        TRACK -2 style phase-connected fitting."""
        from pint_tpu.residuals import Residuals

        r = Residuals(self, model, subtract_mean=False,
                      track_mode="nearest")
        n, frac = r.prepared._phase_jit(r._values())
        pn = np.asarray(n, dtype=np.int64)
        for f, p in zip(self.flags, pn):
            f["pn"] = repr(int(p))
        return pn

    def wideband_dm_data(self):
        """Measured wideband DM data from ``-pp_dm``/``-pp_dme`` flags
        (reference: WidebandDMResiduals.get_dm_data, residuals.py:128).

        Returns (dm [pc cm^-3], dm_error, valid_mask), full TOA length
        with NaN where the flags are absent."""
        dm = np.array(
            self.get_flag_values("pp_dm", default=np.nan, astype=float)
        )
        dme = np.array(
            self.get_flag_values("pp_dme", default=np.nan, astype=float)
        )
        valid = np.isfinite(dm)
        if np.any(valid & ~np.isfinite(dme)):
            bad = np.flatnonzero(valid & ~np.isfinite(dme))
            raise ValueError(
                f"{len(bad)} TOAs carry -pp_dm but no finite -pp_dme "
                f"uncertainty (first at index {bad[0]}); a NaN sigma "
                "would silently poison the wideband fit"
            )
        return dm, dme, valid

    # -- selection / merging (reference: toa.py:1384 __getitem__,
    # :2699 merge_TOAs) ------------------------------------------------------
    def __getitem__(self, index):
        """Sub-TOAs by int, slice, boolean mask, or integer array —
        without re-running ingest (the prepared arrays are sliced)."""
        n = len(self)
        if isinstance(index, (int, np.integer)):
            if not -n <= index < n:
                raise IndexError(index)
            idx = np.array([index % n])
        elif isinstance(index, slice):
            idx = np.arange(n)[index]
        else:
            idx = np.asarray(index)
            if idx.dtype == bool:
                if idx.shape != (n,):
                    raise IndexError(
                        f"boolean mask of shape {idx.shape} against "
                        f"{n} TOAs")
                idx = np.flatnonzero(idx)
            else:
                idx = idx.astype(np.int64)
        return self._sliced(idx)

    def _sliced(self, idx):
        new = object.__new__(TOAs)
        new.ephem = self.ephem
        new.planets = self.planets
        new.include_clock = self.include_clock
        new.include_bipm = self.include_bipm
        new.bipm_version = self.bipm_version
        new.flags = [dict(self.flags[i]) for i in idx]
        new.names = [self.names[i] for i in idx]
        for arr in ("error_us", "freq_mhz", "mjd_float", "clock_sec",
                    "ticks", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos"):
            setattr(new, arr, getattr(self, arr)[idx])
        new.obs_names = [self.obs_names[i] for i in idx]
        obs_unique = sorted(set(new.obs_names))
        new.obs_index = np.array(
            [obs_unique.index(o) for o in new.obs_names], dtype=np.int64)
        new.obs_list = obs_unique
        new.planet_pos = {b: p[idx] for b, p in self.planet_pos.items()}
        return new

    @classmethod
    def merge(cls, toas_list):
        """Concatenate prepared TOAs objects (reference merge_TOAs,
        toa.py:2699).  All inputs must share ephem/planets settings."""
        if not toas_list:
            raise ValueError("nothing to merge")
        first = toas_list[0]
        for t in toas_list[1:]:
            if t.ephem != first.ephem or t.planets != first.planets:
                raise ValueError(
                    "cannot merge TOAs prepared with different "
                    f"ephem/planets settings: {t.ephem}/{t.planets} vs "
                    f"{first.ephem}/{first.planets}")
        new = object.__new__(cls)
        new.ephem = first.ephem
        new.planets = first.planets
        new.include_clock = first.include_clock
        new.include_bipm = first.include_bipm
        new.bipm_version = first.bipm_version
        new.flags = [dict(f) for t in toas_list for f in t.flags]
        new.names = [x for t in toas_list for x in t.names]
        for arr in ("error_us", "freq_mhz", "mjd_float", "clock_sec",
                    "ticks", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos"):
            setattr(new, arr, np.concatenate(
                [getattr(t, arr) for t in toas_list]))
        new.obs_names = [x for t in toas_list for x in t.obs_names]
        obs_unique = sorted(set(new.obs_names))
        new.obs_index = np.array(
            [obs_unique.index(o) for o in new.obs_names], dtype=np.int64)
        new.obs_list = obs_unique
        new.planet_pos = {}
        if first.planets:
            for b in first.planet_pos:
                new.planet_pos[b] = np.concatenate(
                    [t.planet_pos[b] for t in toas_list])
        return new

    def to_batch(self) -> "TOABatch":
        planets = (
            np.stack(
                [self.planet_pos[b] for b in
                 ("venus", "mars", "jupiter", "saturn", "uranus", "neptune")],
                axis=0,
            )
            if self.planets
            else np.zeros((0, len(self), 3))
        )
        return TOABatch(
            ticks=jnp.asarray(self.ticks),
            freq_mhz=jnp.asarray(self.freq_mhz),
            error_s=jnp.asarray(self.error_us * 1e-6),
            ssb_obs_pos=jnp.asarray(self.ssb_obs_pos),
            ssb_obs_vel=jnp.asarray(self.ssb_obs_vel),
            obs_sun_pos=jnp.asarray(self.obs_sun_pos),
            planet_pos=jnp.asarray(planets),
        )


class TOABatch(NamedTuple):
    """Device-side struct-of-arrays TOA batch (a JAX pytree).

    ticks: int64 TDB arrival time at the observatory, 2^-32 s since J2000.
    Geometry in light-seconds / ls-per-sec, ICRS axes:
    ssb_obs_pos/vel (N,3); obs_sun_pos (N,3); planet_pos (6,N,3) in the
    order venus, mars, jupiter, saturn, uranus, neptune (empty if not
    loaded with planets=True).
    """

    ticks: jnp.ndarray
    freq_mhz: jnp.ndarray
    error_s: jnp.ndarray
    ssb_obs_pos: jnp.ndarray
    ssb_obs_vel: jnp.ndarray
    obs_sun_pos: jnp.ndarray
    planet_pos: jnp.ndarray

    def __len__(self):
        return int(self.ticks.shape[0])


#: bump when the prepared-array layout changes (invalidates caches)
_CACHE_VERSION = 1


def _tim_hash(timfile, _depth=0):
    """SHA256 over the tim file bytes and any INCLUDEd files."""
    import hashlib

    h = hashlib.sha256()
    with open(timfile, "rb") as f:
        data = f.read()
    h.update(data)
    if _depth < 5:
        base = os.path.dirname(os.path.abspath(timfile))
        for ln in data.decode(errors="replace").splitlines():
            parts = ln.split()
            if len(parts) >= 2 and parts[0].upper() == "INCLUDE":
                inc = os.path.join(base, parts[1])
                if os.path.exists(inc):
                    h.update(_tim_hash(inc, _depth + 1).encode())
    return h.hexdigest()


def save_cache(toas: TOAs, path, src_hash=""):
    """Write the prepared arrays to an npz cache (reference:
    toa.py:373 save_pickle — here a hash-validated npz instead of a
    version-fragile pickle)."""
    import json

    np.savez_compressed(
        path,
        meta=json.dumps({
            "version": _CACHE_VERSION, "ephem": toas.ephem,
            "planets": toas.planets, "src_hash": src_hash,
            "flags": toas.flags, "names": toas.names,
            "obs_names": toas.obs_names,
        }),
        error_us=toas.error_us, freq_mhz=toas.freq_mhz,
        mjd_float=toas.mjd_float, clock_sec=toas.clock_sec,
        ticks=toas.ticks, ssb_obs_pos=toas.ssb_obs_pos,
        ssb_obs_vel=toas.ssb_obs_vel, obs_sun_pos=toas.obs_sun_pos,
        **{f"planet_{b}": p for b, p in toas.planet_pos.items()},
    )


def load_cache(path, src_hash="", ephem=None, planets=None):
    """Load a prepared-TOAs cache; returns None when stale/invalid
    (wrong file hash, cache version, or prepare settings) — mirroring
    the reference's hash check (toa.py:1856 check_hashes)."""
    import json

    try:
        z = np.load(path, allow_pickle=False)
        meta = json.loads(str(z["meta"]))
    except Exception:
        return None
    if (meta.get("version") != _CACHE_VERSION
            or (src_hash and meta.get("src_hash") != src_hash)
            or (ephem is not None and meta.get("ephem") != ephem)
            or (planets is not None and meta.get("planets") != planets)):
        return None
    new = object.__new__(TOAs)
    new.ephem = meta["ephem"]
    new.planets = meta["planets"]
    new.flags = [dict(f) for f in meta["flags"]]
    new.names = list(meta["names"])
    new.obs_names = list(meta["obs_names"])
    for arr in ("error_us", "freq_mhz", "mjd_float", "clock_sec",
                "ticks", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos"):
        setattr(new, arr, z[arr])
    obs_unique = sorted(set(new.obs_names))
    new.obs_index = np.array(
        [obs_unique.index(o) for o in new.obs_names], dtype=np.int64)
    new.obs_list = obs_unique
    new.planet_pos = {
        k[len("planet_"):]: z[k] for k in z.files if k.startswith("planet_")
    }
    return new


def get_TOAs(timfile, ephem="builtin", planets=False, include_clock=True,
             include_bipm=False, bipm_version="BIPM2019",
             use_cache=False) -> TOAs:
    """Parse + prepare TOAs from a .tim file (reference: toa.py:109).

    use_cache: True reads/writes ``<timfile>.pint_tpu_cache.npz``,
    validated against a SHA256 of the tim file (incl. INCLUDEs), the
    cache layout version, and the prepare settings — a stale cache is
    silently rebuilt (reference pickle path, toa.py:333-402)."""
    cache_path = str(timfile) + ".pint_tpu_cache.npz"
    src_hash = ""
    if use_cache:
        # the resolved ephemeris identity is part of the hash: a
        # requested kernel that silently fell back to the builtin (or a
        # kernel/data file installed or updated later) must invalidate
        # the cached positions
        from pint_tpu.ephem import get_ephemeris

        from pint_tpu.obs.clock import clock_data_identity
        from pint_tpu.obs.iers import eop_data_identity

        eph_id = get_ephemeris(ephem).identity
        src_hash = (_tim_hash(timfile)
                    + f"|clock={bool(include_clock)}|eph={eph_id}"
                    + f"|bipm={bipm_version if include_bipm else ''}"
                    + f"|clkdata={clock_data_identity()}"
                    + f"|eopdata={eop_data_identity()}")
        cached = load_cache(cache_path, src_hash=src_hash, ephem=ephem,
                            planets=planets)
        if cached is not None:
            # the src_hash covered these settings; re-attach them so
            # re-reads (pintk tim editor) reproduce the preparation
            cached.include_clock = include_clock
            cached.include_bipm = include_bipm
            cached.bipm_version = bipm_version
            return cached
    toas = TOAs(
        read_tim(timfile), ephem=ephem, planets=planets,
        include_clock=include_clock, include_bipm=include_bipm,
        bipm_version=bipm_version,
    )
    if use_cache:
        try:
            save_cache(toas, cache_path, src_hash=src_hash)
        except OSError:
            pass  # read-only data dir: caching is best-effort
    return toas


def format_toa_line(mjd_str, error_us, freq_mhz, obs_code, flags=None,
                    name="unk"):
    """One tempo2-format TOA line (reference: toa.py:566)."""
    freq = 0.0 if not np.isfinite(freq_mhz) else freq_mhz
    # error at full precision (%.3f silently truncated sub-ns
    # uncertainties, e.g. 1.0625 -> 1.062; caught by the fuzz harness)
    line = f"{name} {freq:.6f} {mjd_str} {error_us:.10g} {obs_code}"
    for k, v in (flags or {}).items():
        line += f" -{k} {v}" if v != "" else f" -{k}"
    return line


def write_tim(toas: TOAs, path, include_info=True):
    """Write TOAs to a tempo2-format .tim file (reference:
    toa.py:2072 write_TOA_file).

    Times are reconstructed from the TDB ticks by inverting the
    UTC->TDB chain with the same clock offsets the TOAs were built
    with, so read -> write -> read round-trips to the tick quantum
    (0.23 ns)."""
    from pint_tpu.time.mjd import (
        ticks_to_mjd_string_tdb,
        ticks_to_mjd_string_utc,
    )

    lines = []
    if include_info:
        from pint_tpu.utils import info_string

        lines.append(info_string(prefix_string="C "))
    lines.append("FORMAT 1")
    for i in range(len(toas)):
        obs = get_observatory(toas.obs_names[i])
        if obs.is_barycenter:
            mjd_s = ticks_to_mjd_string_tdb(
                int(toas.ticks[i])
                - int(round(toas.clock_sec[i] * 2**32))
            )
            code = "@"
        else:
            mjd_s = ticks_to_mjd_string_utc(
                int(toas.ticks[i]), clock_offset_sec=toas.clock_sec[i]
            )
            code = obs.name
        # keep the command-state flags (-to / -padd / -tim_jump):
        # read_tim re-applies them, which is exactly what makes the
        # round-trip exact (the written label is the raw site time,
        # since clock_sec included the TIME offset we just inverted)
        lines.append(
            format_toa_line(
                mjd_s, float(toas.error_us[i]), float(toas.freq_mhz[i]),
                code, toas.flags[i], toas.names[i] or "unk",
            )
        )
    text = "\n".join(lines) + "\n"
    if hasattr(path, "write"):
        path.write(text)
    else:
        with open(path, "w") as f:
            f.write(text)
