"""Ensemble MCMC sampler: Goodman-Weare stretch moves in pure JAX.

Counterpart of the reference sampler layer (reference:
src/pint/sampler.py:7 MCMCSampler / :60 EmceeSampler, which drives the
external emcee package).  TPU redesign: emcee's per-step Python loop
over walkers becomes a ``lax.scan`` over steps of a vmapped stretch
move — the entire chain is ONE compiled XLA program, with the
log-posterior evaluated for all walkers in parallel on device (the
reference's "walker parallelism" via multiprocessing, SURVEY section
2.9 item 3, becomes batch parallelism on the MXU).

The move is the affine-invariant stretch (Goodman & Weare 2010, the
same algorithm emcee implements), with the standard red-black split so
each half updates against the other's current positions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import faults as _faults
from pint_tpu import guard as _guard
from pint_tpu import telemetry

__all__ = ["run_mcmc", "EnsembleSampler", "integrated_autocorr_time",
           "AutocorrCache"]


def integrated_autocorr_time(chain, c=5.0):
    """Per-parameter integrated autocorrelation time tau of an MCMC
    chain (nsteps, nwalkers, ndim), emcee's estimator: mean
    walker-averaged autocorrelation function, FFT-computed, with
    Sokal's adaptive window (smallest M with M >= c * tau(M)).
    (Reference path: event_optimize's run_sampler_autocorr drives
    emcee's get_autocorr_time; here the estimator is owned natively.)"""
    x = np.asarray(chain, np.float64)
    nsteps, nwalkers, ndim = x.shape
    taus = np.empty(ndim)
    for d in range(ndim):
        y = x[:, :, d] - x[:, :, d].mean(axis=0, keepdims=True)
        n2 = 1 << (2 * nsteps - 1).bit_length()
        f = np.fft.rfft(y, n=n2, axis=0)
        acf = np.fft.irfft(f * np.conjugate(f), n=n2, axis=0)[:nsteps]
        acf = acf.mean(axis=1)
        if acf[0] <= 0:
            taus[d] = np.inf
            continue
        rho = acf / acf[0]
        cumsum = 2.0 * np.cumsum(rho) - 1.0  # tau(M) = 1 + 2 sum_1^M rho
        window = np.arange(len(cumsum)) >= c * cumsum
        m = np.argmax(window) if window.any() else len(cumsum) - 1
        taus[d] = max(cumsum[m], 1e-12)
    return taus


class AutocorrCache:
    """Incremental windowed autocorrelation over a chunk-growing chain
    — the quadratic-in-chunk-count fix for
    :meth:`EnsembleSampler.run_mcmc_autocorr`.

    The from-scratch estimator (:func:`integrated_autocorr_time`)
    FFTs the FULL chain every chunk: over K chunks that is
    sum_k O(k n log kn) ~ K^2 work.  Sokal's window only ever reads
    lags up to M ~ c * tau, so this cache keeps the raw lag-product
    prefix sums ``S(l) = sum_t x_t x_{t+l}`` for ``l < L`` (per
    walker per dim) and updates them per chunk with ONE small FFT
    cross-correlation of (tail-buffer + chunk) against the chunk —
    O((L + n) log) per chunk, independent of the total chain length.
    The walker means (which change every chunk) are folded in
    algebraically from cached prefix/suffix/total sums, so the
    windowed acf is EXACTLY the estimator's, not an approximation.

    If the window search needs lags past ``L`` (an unconverged early
    chain), the cache doubles ``L`` and rebuilds from the full chain
    (``sampler.autocorr_rebuilds`` counter) — geometric growth, so
    rebuilds happen O(log) times; every other chunk is incremental
    (``sampler.autocorr_updates``)."""

    def __init__(self, lag0=64):
        self.lag0 = max(4, int(lag0))
        self.n_steps = 0
        self._S = None        # (nw, ndim, L) raw lag-product sums
        self._total = None    # (nw, ndim) running sums
        self._head = None     # first <= L-1 samples (t, nw, ndim)
        self._tail = None     # last <= L-1 samples
        self.updates = 0
        self.rebuilds = 0

    @property
    def max_lag(self):
        return 0 if self._S is None else self._S.shape[2]

    def _delta_S(self, chunk):
        """Raw lag-product contributions of appending ``chunk``:
        ``dS(l) = sum_{pairs spanning the boundary or inside the
        chunk} x_t x_{t+l}`` for every cached lag, via one padded-FFT
        cross-correlation of (tail ++ chunk) against the chunk."""
        L = self.max_lag
        n = chunk.shape[0]
        tail = self._tail if self._tail is not None else chunk[:0]
        m0 = tail.shape[0]
        z = np.concatenate([tail, chunk], axis=0)
        # linear (not circular) correlation for every shift in
        # [-(L-1), m0]: the padded length must clear both the product
        # support and the negative-shift index range 2L
        nfft = 1
        while nfft < max(z.shape[0] + n, 2 * L):
            nfft *= 2
        zf = np.fft.rfft(z, n=nfft, axis=0)
        cf = np.fft.rfft(chunk, n=nfft, axis=0)
        w = np.fft.irfft(zf * np.conjugate(cf), n=nfft, axis=0)
        # dS(l) = sum_j z[m0 - l + j] * chunk[j]  ==  w[(m0 - l) % nfft]
        idx = (m0 - np.arange(L)) % nfft
        return np.transpose(w[idx], (1, 2, 0))  # (nw, ndim, L)

    def update(self, chunk):
        """Fold one appended chunk (n, nwalkers, ndim) into the cache."""
        chunk = np.asarray(chunk, np.float64)
        if self._S is None:
            n, nw, nd = chunk.shape
            L = self.lag0
            self._S = np.zeros((nw, nd, L))
            self._total = np.zeros((nw, nd))
            self._head = chunk[:0]
            self._tail = chunk[:0]
        self._S += self._delta_S(chunk)
        self._total += chunk.sum(axis=0)
        self.n_steps += chunk.shape[0]
        keep = self.max_lag - 1
        if self._head.shape[0] < keep:
            self._head = np.concatenate(
                [self._head, chunk], axis=0)[:keep]
        self._tail = np.concatenate(
            [self._tail, chunk], axis=0)[-keep:] if keep else chunk[:0]
        self.updates += 1
        telemetry.counter_add("sampler.autocorr_updates")

    def _rebuild(self, full, L):
        """From-scratch rebuild at a larger lag window (geometric
        growth — the O(log)-times fallback)."""
        full = np.asarray(full, np.float64)
        T, nw, nd = full.shape
        L = int(min(L, T))
        n2 = 1 << (2 * T - 1).bit_length()
        f = np.fft.rfft(full, n=n2, axis=0)
        acf_raw = np.fft.irfft(f * np.conjugate(f), n=n2, axis=0)[:L]
        self._S = np.transpose(acf_raw, (1, 2, 0))
        self._total = full.sum(axis=0)
        self.n_steps = T
        self._head = full[:L - 1]
        self._tail = full[-(L - 1):] if L > 1 else full[:0]
        self.rebuilds += 1
        telemetry.counter_add("sampler.autocorr_rebuilds")

    def _windowed_tau(self, c):
        """Per-dim tau from the cached window, or None where the
        window search ran off the cached lag range."""
        T = self.n_steps
        Le = min(self.max_lag, T)
        m = self._total / T  # (nw, ndim)
        # prefix(l) = sum of first l samples, suffix(l) = last l
        lags = np.arange(Le)
        pre = np.zeros((Le,) + m.shape)
        pre[1:] = np.cumsum(self._head[:Le - 1], axis=0)
        suf = np.zeros((Le,) + m.shape)
        if Le > 1:
            suf[1:] = np.cumsum(self._tail[::-1][:Le - 1], axis=0)
        g_head = self._total[None] - suf       # (Le, nw, ndim)
        g_tail = self._total[None] - pre
        acf_w = (np.transpose(self._S[:, :, :Le], (2, 0, 1))
                 - m[None] * (g_head + g_tail)
                 + (T - lags)[:, None, None] * m[None] ** 2)
        acf = acf_w.mean(axis=1)               # (Le, ndim)
        ndim = acf.shape[1]
        taus = np.empty(ndim)
        for d in range(ndim):
            if acf[0, d] <= 0:
                taus[d] = np.inf
                continue
            rho = acf[:, d] / acf[0, d]
            cumsum = 2.0 * np.cumsum(rho) - 1.0
            window = np.arange(Le) >= c * cumsum
            if window.any():
                taus[d] = max(cumsum[np.argmax(window)], 1e-12)
            elif Le >= T:
                # the estimator's "no window found" semantics: use
                # the full-length cumsum (we cover every lag)
                taus[d] = max(cumsum[-1], 1e-12)
            else:
                return None  # window ran past the cache — grow
        return taus

    def tau(self, full_chain, c=5.0):
        """Integrated autocorrelation times, growing the lag window
        from ``full_chain`` only when the search needs it.  Matches
        :func:`integrated_autocorr_time` (same estimator, same
        window) to FFT-reordering roundoff."""
        while True:
            got = self._windowed_tau(c)
            if got is not None:
                return got
            self._rebuild(full_chain, max(2 * self.max_lag, 4))


def _stretch_half(key, active, other, lnp_active, lnpost_v, a):
    """One stretch-move update of `active` walkers against `other`."""
    nw, ndim = active.shape
    k_z, k_idx, k_acc = jax.random.split(key, 3)
    # z ~ g(z) prop 1/sqrt(z) on [1/a, a]
    u = jax.random.uniform(k_z, (nw,))
    z = ((a - 1.0) * u + 1.0) ** 2 / a
    idx = jax.random.randint(k_idx, (nw,), 0, other.shape[0])
    proposal = other[idx] + z[:, None] * (active - other[idx])
    lnp_prop = lnpost_v(proposal)
    lnratio = (ndim - 1.0) * jnp.log(z) + lnp_prop - lnp_active
    accept = jnp.log(jax.random.uniform(k_acc, (nw,))) < lnratio
    new = jnp.where(accept[:, None], proposal, active)
    new_lnp = jnp.where(accept, lnp_prop, lnp_active)
    return new, new_lnp, accept


def run_mcmc(lnpost, x0, nsteps, key=None, a=2.0, thin=1, jit_key=None,
             mesh=None):
    """Run an ensemble chain.

    lnpost: f(vec[ndim]) -> scalar log-posterior (jax-traceable).
    x0: (nwalkers, ndim) initial walker positions (nwalkers even).
    Returns (chain (nsteps//thin, nwalkers, ndim), lnp, acceptance_rate).

    The whole chain is ONE jitted scan, resolved through the process
    jit registry (compile_cache.shared_jit) keyed on the posterior's
    identity — by default ``lnpost`` itself (bound methods of the same
    object compare equal, so every chunk of an autocorr run and every
    re-run on the same sampler reuses one trace instead of recompiling
    the full chain program per call), or an explicit ``jit_key`` when
    the caller can vouch for a broader identity (MCMCFitter passes a
    content fingerprint so two identically-configured fitters share).

    mesh: a device mesh (axis ``walker``) — the walker axis is held on
    the mesh via ``with_sharding_constraint`` inside the scanned step,
    so every posterior evaluation of every step runs device-parallel.
    The ensemble is NEVER padded: stretch moves couple walkers (a
    phantom would change real proposals), so nwalkers must be a
    multiple of 2x the walker-axis device count — raise, don't pad.
    The mesh is part of the jit key (it changes the traced program);
    ``mesh=None`` keys and traces exactly as before."""
    from pint_tpu import compile_cache as _cc
    from pint_tpu.parallel import mesh as _mesh

    x0 = jnp.asarray(x0, dtype=jnp.float64)
    nw = x0.shape[0]
    if nw % 2:
        raise ValueError("nwalkers must be even (red-black split)")
    if key is None:
        key = jax.random.PRNGKey(0)
    # the shared chain-axis rule (group=2: each red-black half must
    # shard) — raises, never pads, and is None for mesh=None
    constrain = _mesh.chain_constrainer(
        mesh, nw, group=2, requested_by="run_mcmc: nwalkers")

    lnpost_v = jax.vmap(lnpost)
    half = nw // 2

    def scan_chain(x0, keys):
        def step(carry, k):
            x, lnp = carry
            k1, k2 = jax.random.split(k)
            first, second = x[:half], x[half:]
            lnp1, lnp2 = lnp[:half], lnp[half:]
            first, lnp1, acc1 = _stretch_half(
                k1, first, second, lnp1, lnpost_v, a
            )
            second, lnp2, acc2 = _stretch_half(
                k2, second, first, lnp2, lnpost_v, a
            )
            x = jnp.concatenate([first, second])
            lnp = jnp.concatenate([lnp1, lnp2])
            acc = jnp.concatenate([acc1, acc2])
            if constrain is not None:
                # hold the walker axis on the mesh across scan steps
                # (without the constraint XLA is free to gather the
                # carry onto one device between iterations)
                x = constrain(x)
            return (x, lnp), (x, lnp, jnp.mean(acc))

        if constrain is not None:
            x0 = constrain(x0)
        (xf, lnpf), ys = jax.lax.scan(step, (x0, lnpost_v(x0)), keys)
        # on-device chain health, riding the same compiled program:
        # positions must stay finite, and at least one walker must end
        # with a finite log-posterior (all -inf = the whole ensemble
        # stuck outside the prior support, every proposal NaN-rejected)
        health = (jnp.all(jnp.isfinite(xf)),
                  jnp.any(jnp.isfinite(lnpf)))
        return (xf, lnpf), ys, health

    # nw/a are baked into the stored closure — they must be part of
    # the key, not left to aval-driven retracing of a stale closure;
    # the mesh changes the traced program (the sharding constraint),
    # so it keys too
    runner = _cc.shared_jit(
        scan_chain,
        key=("sampler.run_mcmc", nw, float(a))
            + _mesh.mesh_jit_key(mesh),
        fn_token=jit_key if jit_key is not None else lnpost)
    runner.set_mesh(_mesh.mesh_desc(mesh))
    keys = jax.random.split(key, nsteps)
    # run-ledger scope: a chunked autocorr run opens the outer scope
    # (run_mcmc_autocorr), so its chunks all join one run id
    with telemetry.run_scope("mcmc", nwalkers=nw,
                             nsteps=int(nsteps)):
        (xf, lnpf), (chain, lnps, accs), (pos_ok, lnp_ok) = \
            runner(x0, keys)
        # the health tuple always rides the program (two trailing
        # reductions; keeping it out of the key), but the host-side
        # raise honors the guard gate — PINT_TPU_GUARD=0 restores raw
        # semantics.  Inside the run scope so a diverged chain's run
        # record carries the FitDivergedError status.
        if _guard.enabled():
            telemetry.counter_add("guard.checks")
            if not (bool(pos_ok) and bool(lnp_ok)):
                telemetry.counter_add("guard.trips")
                telemetry.counter_add("guard.trip.sampler")
                raise _guard.FitDivergedError(
                    "sampler.run_mcmc",
                    health={"positions_finite": bool(pos_ok),
                            "any_finite_lnp": bool(lnp_ok)},
                    last_good=np.asarray(x0),
                    detail="chain diverged (non-finite walker "
                           "positions or every walker at lnp=-inf); "
                           ".last_good carries the initial ensemble "
                           "state")
    if thin > 1:
        chain = chain[::thin]
        lnps = lnps[::thin]
    return chain, lnps, float(jnp.mean(accs))


class EnsembleSampler:
    """Object wrapper mirroring the reference's sampler API
    (reference: EmceeSampler, sampler.py:60): hold (lnpost, nwalkers),
    initialize walkers from a ball or from priors, run, expose chains."""

    def __init__(self, lnpost, nwalkers=32, seed=0, jit_key=None,
                 mesh=None):
        self.lnpost = lnpost
        self.nwalkers = int(nwalkers)
        self.key = jax.random.PRNGKey(seed)
        self.jit_key = jit_key  # registry identity override (run_mcmc)
        self.mesh = mesh        # walker-axis device mesh (run_mcmc)
        self.chain = None
        self.lnprob = None
        self.acceptance = None

    def initial_ball(self, center, scale):
        """Walkers in a Gaussian ball around `center` (reference:
        get_initial_pos)."""
        center = jnp.asarray(center)
        scale = jnp.asarray(scale)
        self.key, sub = jax.random.split(self.key)
        return center + scale * jax.random.normal(
            sub, (self.nwalkers, center.shape[0])
        )

    def run_mcmc(self, x0, nsteps, thin=1):
        self.key, sub = jax.random.split(self.key)
        self.chain, self.lnprob, self.acceptance = run_mcmc(
            self.lnpost, x0, int(nsteps), key=sub, thin=thin,
            jit_key=self.jit_key, mesh=self.mesh
        )
        return self.chain

    def _checkpoint_fingerprint(self, x0):
        """Identity a chain checkpoint is validated against: the
        posterior's jit identity (the registry key MCMCFitter
        fingerprints, or the posterior's qualname as a weaker stand-in)
        plus the ensemble geometry — a checkpoint from a different
        posterior or walker layout must never be silently resumed."""
        from pint_tpu import compile_cache as _cc

        ident = (repr(self.jit_key) if self.jit_key is not None
                 else getattr(self.lnpost, "__qualname__",
                              type(self.lnpost).__name__))
        return _cc.fingerprint(
            (ident, self.nwalkers, int(np.shape(x0)[-1])))

    def run_mcmc_autocorr(self, x0, chunk=100, maxsteps=5000,
                          tau_factor=50.0, rtol=0.1, checkpoint=None,
                          checkpoint_meta=None):
        """Run in chunks until converged by the emcee criterion
        (reference: event_optimize run_sampler_autocorr): stop when the
        chain is longer than ``tau_factor`` integrated autocorrelation
        times AND tau changed by < ``rtol`` between chunks; give up at
        exactly ``maxsteps``.  No thinning — tau must be measured in
        raw steps.  Returns (chain, converged, tau).

        checkpoint: optional path — chain state (samples, log-probs,
        rng key, step count) is atomic-written after every chunk, and
        an existing checkpoint at the path resumes the run mid-chain
        (a killed 10^5-step job loses at most one chunk).
        ``checkpoint_meta`` entries (e.g. a serve job's trace id) ride
        the checkpoint header, so a resumed job keeps its trace.  Resume is
        validated against the posterior's jit fingerprint
        (:meth:`_checkpoint_fingerprint`); a mismatch raises
        :class:`pint_tpu.guard.CheckpointMismatchError` rather than
        silently reusing a stale chain."""
        chains = []
        lnprobs = []
        accs = []
        tau_prev = None
        tau = np.array([np.inf])
        converged = False
        x = x0
        total = 0
        fp = None
        # incremental windowed autocorrelation: each chunk folds into
        # the cached lag-product prefix instead of re-FFTing the full
        # chain (AutocorrCache — the quadratic-chunk-count fix)
        acache = AutocorrCache(lag0=max(64, int(chunk)))
        if checkpoint is not None:
            fp = self._checkpoint_fingerprint(x0)
            loaded = _guard.load_checkpoint(checkpoint, fingerprint=fp)
            if loaded is not None:
                arrays, head = loaded
                chains = [arrays["chain"]]
                lnprobs = [arrays["lnprob"]]
                accs = [(float(a), int(n)) for a, n in arrays["accs"]]
                total = int(arrays["total"][()])
                x = jnp.asarray(arrays["chain"][-1])
                self.key = jnp.asarray(arrays["key"])
                acache.update(arrays["chain"])
        # the outer ledger scope: every chunk's run_mcmc joins ONE
        # run id instead of minting one per chunk
        run = telemetry.run_scope("mcmc", chunked=True,
                                  maxsteps=int(maxsteps))
        with run:
            while total < maxsteps:
                step = int(min(chunk, maxsteps - total))
                self.key, sub = jax.random.split(self.key)
                chain, lnprob, acc = run_mcmc(
                    self.lnpost, x, step, key=sub,
                    jit_key=self.jit_key, mesh=self.mesh)
                chains.append(np.asarray(chain))
                lnprobs.append(np.asarray(lnprob))
                accs.append((float(np.mean(np.asarray(acc))), step))
                x = chain[-1]
                total += step
                acache.update(chains[-1])
                full = np.concatenate(chains, axis=0)
                if checkpoint is not None:
                    _guard.save_checkpoint(
                        checkpoint,
                        {"chain": full,
                         "lnprob": np.concatenate(lnprobs, axis=0),
                         "accs": np.asarray(accs, dtype=np.float64),
                         "total": np.int64(total),
                         "key": np.asarray(self.key)},
                        fingerprint=fp,
                        meta={"maxsteps": int(maxsteps),
                              **(checkpoint_meta or {})})
                    _faults.maybe_kill("sampler.chunk")
                tau = acache.tau(full)
                if (np.all(np.isfinite(tau))
                        and total > tau_factor * np.max(tau)
                        and tau_prev is not None
                        and np.all(np.abs(tau - tau_prev)
                                   < rtol * np.maximum(tau, 1e-12))):
                    converged = True
                    break
                tau_prev = tau
        if not np.all(np.isfinite(tau)) and chains:
            # resumed at total >= maxsteps: the loop never ran, so tau
            # is still its placeholder — measure it from the restored
            # chain instead of handing the caller [inf] (converged
            # stays False: the chunk-to-chunk stability criterion
            # cannot be honestly evaluated from a single snapshot)
            tau = integrated_autocorr_time(
                np.concatenate(chains, axis=0))
        self.chain = jnp.asarray(np.concatenate(chains, axis=0))
        self.lnprob = jnp.asarray(np.concatenate(lnprobs, axis=0))
        # whole-run mean acceptance (chunk-length weighted), matching
        # run_mcmc's whole-chain semantics
        self.acceptance = (sum(a * n for a, n in accs)
                           / sum(n for _, n in accs))
        return self.chain, converged, tau

    def flatchain(self, burn=0):
        c = np.asarray(self.chain[burn:])
        return c.reshape(-1, c.shape[-1])

    def max_posterior(self):
        lnp = np.asarray(self.lnprob)
        i, j = np.unravel_index(np.argmax(lnp), lnp.shape)
        return np.asarray(self.chain[i, j]), float(lnp[i, j])
