"""Data-source diagnostic: which accuracy-critical inputs are active.

``python -m pint_tpu.datacheck [EPHEM]`` (or ``datacheck_report()``)
reports, for the current environment, what the timing chain will
actually use — the resolved ephemeris, clock files per observatory,
BIPM realization, and IERS Earth-orientation data — with the accuracy
consequence of each missing input (the ACCURACY.md budget, live).

The reference equivalent is scattered across astropy's download cache
diagnostics and ``pint.observatory.list_last_correction_mjds``; here
offline data installation is the explicit contract, so the check is a
first-class tool.
"""

from __future__ import annotations

import os

__all__ = ["datacheck_report", "main"]


def datacheck_report(ephem="builtin", sites=("gbt", "ao", "jb", "pks",
                                             "vla", "meerkat", "wsrt")):
    """Return the diagnostic as a list of text lines."""
    lines = []

    # probe the backend FIRST, in a subprocess with a hard timeout:
    # with a hung TPU tunnel (known axon failure mode) any in-process
    # jax.devices() touch blocks forever, turning this diagnostic into
    # a second casualty of the exact failure it exists to report.  On a
    # dead probe, the rest of the report runs on the CPU backend.
    from pint_tpu.backend_probe import ensure_live_backend

    backend_live, backend_detail = ensure_live_backend()

    from pint_tpu.ephem import get_ephemeris

    eph = get_ephemeris(ephem)
    lines.append(f"Ephemeris [{ephem!r}]: {eph.identity}")
    if eph.identity.startswith("spk:"):
        lines.append("  -> JPL kernel active (reference-grade)")
    else:
        lines.append(
            "  -> no JPL kernel: builtin/analytic ephemeris "
            "(~10-100 us out-of-window drift; place de440.bsp under "
            "$PINT_TPU_EPHEM_DIR for reference-grade accuracy)")

    from pint_tpu.obs import get_observatory
    from pint_tpu.obs.clock import _clock_dirs, find_clock_chain

    dirs = _clock_dirs()
    lines.append(f"Clock search dirs: {dirs or 'none (set $PINT_TPU_CLOCK_DIR)'}")

    def _is_placeholder(path):
        """Bundled zero-assumption files self-identify in their header
        (tools/make_runtime_data.py writes the marker)."""
        try:
            with open(path) as f:
                for _ in range(6):
                    line = f.readline()
                    if not line.startswith("#"):
                        break
                    if "PLACEHOLDER-ZERO" in line or "APPROXIMATE" in line:
                        return True
        except OSError:
            pass
        return False

    n_real = n_placeholder = n_missing = n_error = 0
    for site in sites:
        try:
            obs = get_observatory(site)
        except KeyError:
            continue
        try:
            chain = find_clock_chain(obs)
        except Exception as e:  # surface, never hide, a broken file
            lines.append(f"  {site}: ERROR {type(e).__name__}: {e}")
            n_error += 1
            continue
        files = [str(getattr(c, "filename", "?")) for c in (chain or [])]
        if not files:
            n_missing += 1
            continue
        tagged = [
            f + (" [placeholder-zero]" if _is_placeholder(f) else "")
            for f in files
        ]
        # classify the site by its *site* file (first chain link); the
        # GPS->UTC link is a shared <=50 ns term either way
        if _is_placeholder(files[0]):
            n_placeholder += 1
        else:
            n_real += 1
        lines.append(f"  {site}: {', '.join(tagged)}")
    n_checked = n_real + n_placeholder + n_missing + n_error
    if n_real + n_placeholder + n_error == 0:
        lines.append(
            "  -> no site clock files: site clocks assumed perfect "
            "(~0.1-1 us dropped)")
    else:
        lines.append(
            f"  -> clock chain complete for {n_real + n_placeholder}"
            f"/{n_checked} sites checked "
            f"({n_real} real tabulation(s), {n_placeholder} documented "
            "placeholder-zero (~0.1-1 us bound; drop real files into "
            "$PINT_TPU_CLOCK_DIR to supersede)"
            + (f", {n_error} BROKEN file(s) — see ERROR lines above"
               if n_error else "") + ")")
    bipm_files = [
        f + (" [approx-constant]" if _is_placeholder(os.path.join(d, f))
             else "")
        for d in dirs for f in sorted(os.listdir(d))
        if f.startswith("tai2tt_bipm")
    ]
    lines.append(
        "BIPM realization: "
        + (f"available ({', '.join(bipm_files)})" if bipm_files
           else "none (CLK TT(BIPMxxxx) pars fall back to TT(TAI))"))

    from pint_tpu.obs.iers import _iers_dirs, get_eop

    eop = get_eop()
    if eop is not None:
        lines.append(
            f"IERS EOP: table of {eop.mjd.size} rows, MJD "
            f"{eop.mjd.min():.0f}-{eop.mjd.max():.0f} "
            f"(polar motion + UT1 active)")
    else:
        lines.append(
            f"IERS EOP: none (searched {_iers_dirs() or ['$PINT_TPU_IERS_DIR']}); "
            "UT1=UTC (~1 us), no polar motion (~30 ns)")

    import jax

    if backend_live:
        lines.append(f"JAX backend: {jax.default_backend()} "
                     f"({len(jax.devices())} device(s))")
    else:
        lines.append(
            f"JAX backend: DEFAULT BACKEND UNRESPONSIVE — "
            f"{backend_detail}; this report ran on the CPU "
            f"backend ({jax.default_backend()}, "
            f"{len(jax.devices())} device(s))")
    from pint_tpu.fixedpoint import backend_f64_is_ieee

    ieee = backend_f64_is_ieee()
    lines.append(
        "f64 semantics: "
        + ("IEEE correctly-rounded (dd arithmetic valid)" if ieee
           else "~49-bit emulated (int64 fixed-point phase path active; "
                "see TPU_PRECISION.md)"))

    # -- telemetry: probe outcome counters + compile stats -------------------
    from pint_tpu import telemetry

    cs = telemetry.compile_stats()
    lines.append(
        "Telemetry: spans "
        + ("enabled" if telemetry.enabled() else
           "disabled (set $PINT_TPU_TRACE=path for a JSONL trace)"))
    lines.append(
        f"  backend probe: {'live' if backend_live else 'UNRESPONSIVE'}; "
        f"attempts {int(telemetry.counter_get('backend_probe.attempts'))}"
        f", timeouts "
        f"{int(telemetry.counter_get('backend_probe.timeouts'))}, "
        f"cpu fallbacks "
        f"{int(telemetry.counter_get('backend_probe.cpu_fallbacks'))}")
    lines.append(
        f"  jit compile: {cs['events']} event(s), "
        f"{cs['seconds']:.2f}s this session (source: {cs['source']}; "
        f"backend compiles {cs['backend_events']} / "
        f"{cs['backend_seconds']:.2f}s, disk-cache hits "
        f"{cs['cache_hits']} saving {cs['cache_saved_seconds']:.2f}s, "
        f"{cs['uncached_backend_events']} uncached)")
    if cs["aot_hits"] or cs["aot_misses"] or cs["aot_rejects"]:
        lines.append(
            f"  AOT executables: {cs['aot_hits']} served, "
            f"{cs['aot_misses']} miss(es), {cs['aot_rejects']} "
            "reject(s) (compile_cache.import_executables)")
    from pint_tpu import guard as _guard

    lines.append(
        f"  numerical guard: {'on' if _guard.enabled() else 'OFF'} "
        f"($PINT_TPU_GUARD); checks "
        f"{int(telemetry.counter_get('guard.checks'))}, trips "
        f"{int(telemetry.counter_get('guard.trips'))}, checkpoints "
        f"{int(telemetry.counter_get('guard.checkpoint_saves'))} "
        f"saved / {int(telemetry.counter_get('guard.checkpoint_resumes'))} "
        "resumed")
    runs = telemetry.runs_summary()
    lines.append(
        f"  run ledger: {runs['completed']} completed / "
        f"{runs['failed']} failed / {runs['in_flight']} in flight "
        "this session ($PINT_TPU_ITER_TRACE for per-iteration "
        "traces; datacheck --runs smokes the join)")
    try:
        from pint_tpu import metrics_http

        mport = metrics_http.port()
    except Exception:
        mport = None
    lines.append(
        "  metrics endpoint: "
        + (f"live on port {mport} (/metrics, /healthz)" if mport
           else "off (set $PINT_TPU_METRICS_PORT for a Prometheus "
                "scrape surface)"))
    for tline in _last_session_compile_lines():
        lines.append(tline)

    # -- compile cache: persistent dir + shared jit registry ------------------
    from pint_tpu import compile_cache

    d = compile_cache.cache_dir()
    if d is None and os.environ.get("PINT_TPU_CACHE_DIR"):
        # env var present but nothing has compiled yet this process
        d = compile_cache.enable_persistent_cache()
    if d:
        lines.append(
            f"Compile cache: {d} ({compile_cache.cache_entries()} "
            "entries on disk)")
    else:
        lines.append(
            "Compile cache: disabled (set $PINT_TPU_CACHE_DIR, or run "
            "pintwarm, to persist XLA compiles across processes)")
    rs = compile_cache.registry_stats()
    lines.append(
        f"  jit registry: {rs['entries']} shared trace(s), "
        f"{rs['hits']} hit(s) / {rs['misses']} miss(es) this session "
        f"(cap {rs['cap']})")

    # -- serving layer: knobs + readiness ------------------------------------
    from pint_tpu import telemetry as _tel
    from pint_tpu.serve.state import serve_config

    scfg = serve_config()
    g = _tel.gauges()
    if "serve.ready" in g:
        state = ("warm" if g.get("serve.aot_warm")
                 else "COLD (a load balancer must gate on /readyz)")
        lines.append(
            f"Serving: replica live ({state}), queue depth "
            f"{int(g.get('serve.queue_depth', 0))}, "
            f"{int(_tel.counter_get('serve.requests'))} request(s) "
            "served this session")
    else:
        lines.append(
            "Serving: no replica in this process (pintserve; "
            "--serve runs the smoke)")
    lines.append(
        f"  knobs: flush {scfg['flush_ms']:g}ms, max_batch "
        f"{scfg['max_batch']}, queue_max {scfg['queue_max']}, "
        f"deadline {scfg['deadline_ms']:g}ms, grid chunk "
        f"{scfg['grid_chunk']} ($PINT_TPU_SERVE_*; docs/serving.md)")

    # -- trace-safety: recompile sanitizer state ------------------------------
    from pint_tpu.lint import sanitizer as _san

    sst = _san.stats()
    if sst["mode"] == "off":
        lines.append(
            "Recompile sanitizer: off "
            "($PINT_TPU_RECOMPILE_SANITIZER=warn|raise; docs/lint.md; "
            "--lint runs the smoke)")
    else:
        lines.append(
            f"Recompile sanitizer: {sst['mode']}"
            + (f", ARMED ({sst['armed_note']})" if sst["armed"]
               else ", unarmed")
            + f" — {sst['compiles']} attributed compile(s), "
              f"{sst['violations']} violation(s) "
              f"({sst['same_shape_recompiles']} same-shape)")

    # -- structure-aware hot path: design partition + hybrid smoke ------------
    lines.extend(_design_section())

    # -- cross-pulsar GW engine: geometry + OS smoke ---------------------------
    lines.extend(_gw_section())
    return lines


#: inline NGC6440E-equivalent par for the hybrid-vs-dense smoke when
#: the reference par file is not installed (isolated pulsar: RAJ/DECJ
#: frozen astrometry, F0/F1/DM free — the classic partition case)
_NGC6440E_FALLBACK_PAR = """PSR  NGC6440E
RAJ  17:48:52.75
DECJ -20:21:29.0
F0   61.485476554 1
F1   -1.181e-15 1
PEPOCH 53750
DM   224.114 1
TZRMJD 53750
TZRSITE @
TZRFRQ 1400
UNITS TDB
EPHEM builtin
"""


def _design_section():
    """Structure-aware hot path diagnostic: the design partition the
    fitters choose for a representative model (n_linear / n_nonlinear
    / n_frozen, structured-U vs dense ECORR), plus a smoke assert that
    the hybrid analytic/AD design matrix agrees with the dense
    full-jacfwd build on NGC6440E (or its inline equivalent when the
    reference par is not installed).  Diagnostic: reports, never
    raises."""
    try:
        import numpy as np

        from pint_tpu.models.builder import get_model
        from pint_tpu.models.timing_model import (frozen_delay_default,
                                                  hybrid_design_default)
        from pint_tpu.residuals import segment_ecorr_default
        from pint_tpu.simulation import make_fake_toas_uniform

        ref = "/root/reference/profiling/NGC6440E.par"
        if os.path.exists(ref):
            model, src = get_model(ref), "NGC6440E.par"
        else:
            model, src = get_model(_NGC6440E_FALLBACK_PAR), \
                "inline NGC6440E-equivalent"
        toas = make_fake_toas_uniform(
            53700.0, 54300.0, 60, model, freq_mhz=1400.0, obs="gbt",
            error_us=1.0, add_noise=True,
            rng=np.random.default_rng(0))
        from pint_tpu.fitter import WLSFitter

        lines = [
            "Design partition (structure-aware hot path): gates "
            f"hybrid={'on' if hybrid_design_default() else 'OFF'} "
            f"frozen-delay={'on' if frozen_delay_default() else 'OFF'} "
            f"segment-ecorr={'on' if segment_ecorr_default() else 'OFF'}"]
        f = WLSFitter(toas, model)
        lin, nl = f._partition
        seg = getattr(f.resids, "ecorr_segment_cols", 0)
        lines.append(
            f"  {model.meta.get('PSR', model.name)} ({src}): "
            f"{len(lin)} linear + {len(nl)} nonlinear columns, "
            f"{len(f._frozen_names)} frozen delay component(s) "
            f"{tuple(f._frozen_names)}, noise "
            f"{'frozen' if f._noise_frozen else 'dynamic'}, ECORR "
            + (f"segment-sum ({seg} epochs)" if seg else
               "dense/none"))
        # hybrid-vs-dense smoke: the analytic columns must match the
        # full jacfwd design to near roundoff
        import jax
        import jax.numpy as jnp

        vec = jnp.asarray([model.values[p] for p in f._traced_free])
        base = f.prepared._values_pytree()
        data = f._fit_data
        _, J = f._rj(vec, base, data)

        def resid_fn(v):
            values = dict(base)
            for i, name in enumerate(f._traced_free):
                values[name] = v[i]
            return f.resids.time_resids_at(values, data)

        J_dense = jax.jacfwd(resid_fn)(vec)
        scale = np.abs(np.asarray(J_dense)).max(axis=0)
        rel = float((np.abs(np.asarray(J) - np.asarray(J_dense))
                     / np.maximum(scale, 1e-300)).max())
        # threshold is 10x the tests' 1e-12 acceptance pin: the
        # column-max scale sits AFTER mean subtraction, which cancels
        # several orders of magnitude on near-constant columns (e.g. a
        # free DM on single-frequency TOAs) and amplifies benign f64
        # ordering differences — a healthy install must not print
        # PROBLEM on ordinary data
        lines.append(
            f"  hybrid vs dense design smoke: max rel {rel:.2e} "
            + ("OK" if rel <= 1e-11 else "PROBLEM (> 1e-11)"))
        return lines
    except Exception as e:  # diagnostic must never take the report down
        return [f"Design partition: ERROR {type(e).__name__}: {e}"]


def _gw_section(n_psr=3, ntoa=24):
    """Sanity of the cross-pulsar GW engine on a tiny synthetic array:
    pair count, ORF matrix symmetry/positive-semidefiniteness, and an
    optimal-statistic smoke evaluation (finite Ahat^2 / S/N).  Any
    failure is reported, never raised — this is a diagnostic."""
    try:
        import numpy as np

        from pint_tpu.gw import OptimalStatistic, orf_matrix
        from pint_tpu.simulation import make_fake_pta

        pairs = make_fake_pta(n_psr, ntoa, start_mjd=54000.0,
                              duration_days=1500.0,
                              name_prefix="GWCHK")
        os_ = OptimalStatistic(pairs, nmodes=3)
        G = np.asarray(orf_matrix(os_.pos))
        sym = float(np.max(np.abs(G - G.T)))
        min_eig = float(np.linalg.eigvalsh(G).min())
        res = os_.compute()
        ok = (np.isfinite(res.ahat2) and np.isfinite(res.snr)
              and sym == 0.0 and min_eig > -1e-12)
        return [
            "GW engine (cross-pulsar OS, tiny synthetic array): "
            + ("OK" if ok else "PROBLEM"),
            f"  {n_psr} pulsars -> {os_.n_pairs} pair(s); HD ORF "
            f"symmetric (max asym {sym:.1e}), min eigenvalue "
            f"{min_eig:.3e} (PSD: {'yes' if min_eig > -1e-12 else 'NO'})",
            f"  OS smoke: Ahat^2 = {res.ahat2:.3e} "
            f"+/- {res.sigma_ahat2:.3e}, S/N = {res.snr:.2f} "
            f"({'finite' if np.isfinite(res.snr) else 'NON-FINITE'})",
        ]
    except Exception as e:  # diagnostic must never take the report down
        return [f"GW engine: ERROR {type(e).__name__}: {e}"]


def _gwb_section(n_psr=3, ntoa=24):
    """GWB kron-likelihood + HMC smoke (--gwb): the kron-structured
    lnlike against the dense (K, K) reference on a tiny array, a
    gradient check against central finite differences, and a
    2-chain/8-draw NUTS smoke (finite chain, adapted step size).
    Diagnostic: reports, never raises."""
    lines = ["GWB kron/HMC (--gwb):"]
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from pint_tpu import compile_cache as _cc
        from pint_tpu.gw import CommonProcess, GWBPosterior, run_nuts
        from pint_tpu.simulation import make_fake_pta

        lines.append("  $PINT_TPU_KRON_PHI gate: "
                     + ("kron (default)" if _cc.kron_phi_default()
                        else "dense (gate off)"))
        pairs = make_fake_pta(
            n_psr, ntoa, start_mjd=54000.0, duration_days=1500.0,
            name_prefix="GWBCHK",
            extra_par="TNRedAmp -13.5\nTNRedGam 4.0\nTNRedC 3\n")
        lk = CommonProcess(pairs, nmodes=3, kron=True).lnlike(
            -14.0, 13.0 / 3.0)
        ld = CommonProcess(pairs, nmodes=3, kron=False).lnlike(
            -14.0, 13.0 / 3.0)
        rel = abs(lk - ld) / abs(ld)
        # kron vs dense on a full-rank HD ORF: 1e-10 is the tested
        # bound; the smoke allows 10x headroom over it
        lines.append(
            f"  kron vs dense lnlike: rel diff {rel:.2e} "
            + ("OK" if rel < 1e-9 else "PROBLEM"))
        post = GWBPosterior(CommonProcess(pairs, nmodes=3))
        data = post.data()
        th = jnp.asarray(post.center())
        g = float(jax.grad(
            lambda q: post.lnprob(q, data))(th)[0])
        h = 1e-5
        up = th.at[0].add(h)
        dn = th.at[0].add(-h)
        fd = (float(post.lnprob(up, data))
              - float(post.lnprob(dn, data))) / (2 * h)
        grel = abs(fd - g) / max(abs(g), 1e-8)
        lines.append(
            f"  d lnp/d log10_A vs central differences: rel "
            f"{grel:.2e} " + ("OK" if grel < 1e-5 else "PROBLEM"))
        res = run_nuts(post, num_warmup=4, num_samples=4, n_chains=2,
                       chunk=4, num_leapfrog=3, seed=0)
        ok = (np.all(np.isfinite(res.samples))
              and np.all(res.step_size > 0))
        lines.append(
            f"  NUTS smoke (2 chains x 8 draws, ndim={post.ndim}): "
            f"accept {res.accept_rate:.2f}, step "
            f"{np.mean(res.step_size):.3g} "
            + ("OK" if ok else "PROBLEM"))
        return lines
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
        return lines


def _corpus_section():
    """Scenario-corpus smoke (--corpus): one clean scenario, one
    correlated-noise scenario, one faulted scenario — realized and
    (for the clean one) pushed through the full oracle-parity
    battery; per-class verdict lines.  Reference-PINT availability is
    reported but not required.  Diagnostic: reports, never raises."""
    lines = ["Scenario corpus (--corpus):"]
    try:
        import numpy as np

        from pint_tpu.corpus.parity import (parity_one,
                                            reference_available)
        from pint_tpu.corpus.spec import CLASSES, build_class

        lines.append(
            f"  registry: {len(CLASSES)} scenario classes "
            f"({', '.join(sorted(CLASSES))})")
        ref = reference_available()
        lines.append("  reference PINT: "
                     + ("available (differential mode on)" if ref
                        else "absent (oracle mode; mount at "
                             "$PINT_TPU_CORPUS_REFERENCE to enable)"))
        picks = [build_class(k, base_seed=0, count=1)[0]
                 for k in ("spin", "rednoise", "faulted")]
        for s in picks:
            model, ds = s.realize()
            ntoa = np.asarray(ds.mjd_float).size
            lines.append(
                f"  {s.klass:<9s} {s.name}: realized {ntoa} TOAs, "
                f"{len(model.free_params)} free params "
                + ("(correlated)" if s.correlated else "")
                + (f"(fault {s.fault})" if s.fault else ""))
        for s in picks:
            v = parity_one(s, mode="oracle")
            bad = {k: c for k, c in (v.checks or {}).items()
                   if not c.get("ok")}
            lines.append(
                f"  parity[{v.mode}] {s.klass:<9s} {v.scenario}: "
                + ("OK" if v.status == "pass"
                   else f"PROBLEM {v.detail or bad}"))
        return lines
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
        return lines


def _mesh_section():
    """Mesh-layer smoke (--mesh): device inventory, mesh construction,
    partition-rule resolution over a REAL stacked PTA-batch pytree
    (every leaf must resolve — unmatched leaves are exactly the bug
    class the rule table exists to catch), and a tiny sharded ==
    unsharded fit comparison over whatever devices this process has
    (1 CPU device still exercises the full path).  Diagnostic:
    reports, never raises."""
    lines = ["Mesh layer (--mesh):"]
    try:
        import jax
        import numpy as np

        from pint_tpu.models.builder import get_model
        from pint_tpu.parallel import (PTA_BATCH_RULES, PTABatch,
                                       make_mesh)
        from pint_tpu.parallel import mesh as _mesh
        from pint_tpu.simulation import make_fake_toas_uniform

        devs = jax.devices()
        plats = sorted({d.platform for d in devs})
        lines.append(f"  devices: {len(devs)} x {'/'.join(plats)}")
        mesh = make_mesh("pulsar")
        lines.append(f"  mesh: {_mesh.mesh_desc(mesh)} "
                     f"(jit key {_mesh.mesh_jit_key(mesh)}): OK")

        def mk(i):
            par = (f"PSR MESHCHK{i}\nRAJ {5 + i}:00:00\n"
                   "DECJ 20:00:00\n"
                   f"F0 {90.0 + 11.0 * i} 1\nF1 -1e-15 1\n"
                   f"PEPOCH 55000\nDM {10.0 + i} 1\nTZRMJD 55000\n"
                   "TZRFRQ 1400\nTZRSITE @\nUNITS TDB\n"
                   "EPHEM builtin\n")
            m = get_model(par)
            t = make_fake_toas_uniform(
                54500, 55500, 24 + 4 * i, m, obs="gbt", error_us=1.0,
                add_noise=True, rng=np.random.default_rng(i))
            m.values["DM"] += 1e-3
            return m, t

        batch = PTABatch([mk(i) for i in range(2)])
        args = {k: v for k, v in batch._base_args().items()
                if v is not None}
        specs = _mesh.match_partition_rules(PTA_BATCH_RULES, args)
        flat = _mesh.tree_paths(specs)
        n_sharded = sum(1 for _, s in flat if tuple(s))
        n_rep = len(flat) - n_sharded
        lines.append(
            f"  rule table over the stacked PTA pytree: {len(flat)} "
            f"leaves all matched ({n_sharded} pulsar-sharded, "
            f"{n_rep} replicated): OK")
        _, chi2_ref, _ = batch.fit_wls(maxiter=2)
        batch2 = PTABatch([mk(i) for i in range(2)])
        _, chi2_sh, _ = batch2.fit_wls(maxiter=2, mesh=mesh)
        delta = float(np.max(np.abs(np.asarray(chi2_ref)
                                    - np.asarray(chi2_sh))
                             / np.maximum(np.abs(np.asarray(chi2_ref)),
                                          1e-300)))
        ok = delta < 1e-6
        lines.append(
            f"  sharded == unsharded fit smoke (2 pulsars over "
            f"{len(devs)} device(s)): rel delta {delta:.1e} -> "
            + ("OK" if ok else "PROBLEM"))

        # 2-D pulsar x grid: mesh construction + rule resolution over
        # the scan pytree (BOTH axes on one data pytree — the pod
        # layout PTABatch.chisq_grid runs), then a tiny sharded ==
        # unsharded scan.  A misconfigured pod slice fails HERE, at
        # diagnosis time, not mid-run.
        from pint_tpu.parallel import PTA_GRID_RULES

        # balanced split so BOTH axes actually shard when devices
        # allow it (8 devices -> (2, 4), 1 device -> (1, 1))
        n_psr_dev = 2 if len(devs) % 2 == 0 else 1
        mesh2d = make_mesh(("pulsar", "grid"),
                           shape=(n_psr_dev, len(devs) // n_psr_dev))
        lines.append(f"  2-d mesh: {_mesh.mesh_desc(mesh2d)} "
                     f"(jit key {_mesh.mesh_jit_key(mesh2d)}): OK")
        pts = np.linspace(-2e-15, -5e-16, 3)[:, None]
        scan_args = {"grid_values": pts, **{
            k: v for k, v in batch._base_args().items()
            if v is not None}}
        specs2 = _mesh.match_partition_rules(PTA_GRID_RULES, scan_args)
        flat2 = _mesh.tree_paths(specs2)
        lines.append(
            f"  2-d rule table over the scan pytree: {len(flat2)} "
            "leaves all matched (grid_values -> grid axis, stacked "
            "batch -> pulsar axis): OK")
        c_ref = batch.chisq_grid(["F1"], pts, n_steps=2)
        c_sh = batch2.chisq_grid(["F1"], pts, n_steps=2, mesh=mesh2d)
        d2 = float(np.max(np.abs(c_ref - c_sh)
                          / np.maximum(np.abs(c_ref), 1e-300)))
        lines.append(
            "  2-d pulsar x grid scan sharded == unsharded: rel "
            f"delta {d2:.1e} -> " + ("OK" if d2 < 1e-6 else "PROBLEM"))

        # TOA-axis Woodbury smoke: the sharded contractions of
        # linalg must reduce to the unsharded answer
        import jax.numpy as jnp

        from pint_tpu.linalg import woodbury_chi2_logdet

        rng = np.random.default_rng(0)
        n_t = 16 * len(devs)
        r = jnp.asarray(rng.normal(size=n_t))
        sigma = jnp.asarray(1.0 + 0.1 * rng.random(n_t))
        U = jnp.asarray(rng.normal(size=(n_t, 5)))
        phi = jnp.asarray(10.0 ** rng.uniform(-2, 0, 5))
        tmesh = make_mesh("toa")
        shard = _mesh.RowShard(tmesh)
        import jax

        # pintlint: allow=PTL101 -- one-shot diagnostic comparing a
        # plain vs TOA-sharded trace; polluting the registry with
        # throwaway smoke programs would skew its stats
        c_plain = jax.jit(woodbury_chi2_logdet)(r, sigma, U, phi)
        # pintlint: allow=PTL101 -- same one-shot diagnostic, sharded arm
        c_shard = jax.jit(
            lambda *a: woodbury_chi2_logdet(*a, toa=shard))(
            r, sigma, U, phi)
        dt = max(abs(float(a) - float(b)) / max(abs(float(a)), 1e-300)
                 for a, b in zip(c_plain, c_shard))
        lines.append(
            f"  toa-axis sharded Woodbury (N={n_t} over {len(devs)} "
            f"device(s)): rel delta {dt:.1e} -> "
            + ("OK" if dt < 1e-8 else "PROBLEM"))
        from pint_tpu import telemetry

        lines.append(
            f"  mesh.sharded_calls = "
            f"{int(telemetry.counter_get('mesh.sharded_calls'))}, "
            f"pad_waste_frac = "
            f"{telemetry.gauges().get('mesh.pad_waste_frac', 0.0)}")
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    return lines


def _faults_section():
    """Chaos smoke: inject each fast fault class and verify the guard
    layer's contract — structured FitDivergedError for bad inputs, a
    documented recovery rung for degenerate priors, a loud parse error
    for corrupted clock tables.  Diagnostic: reports, never raises."""
    from pint_tpu import faults, guard

    lines = ["Fault-injection smoke (--faults):"]

    def record(name, what, ok):
        lines.append(f"  {name}: {what} -> "
                     f"{'OK' if ok else 'PROBLEM'}")

    try:
        import tempfile

        import numpy as np

        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models.builder import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        def tiny_fit():
            m = get_model(WARM_WLS_PAR)
            t = make_fake_toas_uniform(
                53000.0, 54000.0, 40, m, freq_mhz=1400.0, obs="gbt",
                error_us=1.0, add_noise=True,
                rng=np.random.default_rng(0))
            return WLSFitter(t, m)

        for fault in ("nan_resid", "inf_sigma"):
            faults.clear()
            faults.inject(fault, index=3)
            try:
                try:
                    tiny_fit().fit_toas(maxiter=2)
                    record(fault, "fit returned (should have raised)",
                           False)
                except guard.FitDivergedError as e:
                    record(fault,
                           f"structured FitDivergedError, last_good "
                           f"kept ({len(e.last_good or {})} params)",
                           True)
            finally:
                faults.clear()

        from pint_tpu.gw import CommonProcess
        from pint_tpu.simulation import make_fake_pta

        faults.inject("rank_deficient_phi")
        try:
            crn = CommonProcess(
                make_fake_pta(3, 20, start_mjd=54000.0,
                              duration_days=900.0,
                              name_prefix="FLTCHK"), nmodes=3)
            v = crn.lnlike(-14.0, 4.0)
            record("rank_deficient_phi",
                   f"lnlike finite via dense-phi jitter ({v:.1f})",
                   bool(np.isfinite(v)))
        except guard.FitDivergedError:
            record("rank_deficient_phi",
                   "FitDivergedError (jitter rung did not recover)",
                   False)
        finally:
            faults.clear()

        from pint_tpu.obs.clock import ClockFile

        faults.inject("clock_corrupt")
        try:
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".clk", delete=False) as f:
                f.write("# SITE UTC(GPS)\n50000.0 1e-6\n51000.0 2e-6\n")
                path = f.name
            try:
                ClockFile.read_tempo2(path)
                record("clock_corrupt",
                       "parsed silently (should have raised)", False)
            except ValueError:
                record("clock_corrupt",
                       "structured ValueError (no silent NaN "
                       "interpolation)", True)
            os.unlink(path)
        finally:
            faults.clear()
    except Exception as e:  # the smoke must never take the report down
        faults.clear()
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    return lines


def _profile_section():
    """Device-truth profiling smoke (--profile): the per-program
    registry table, histogram sanity (p50 <= p99), memory watermarks,
    a profile-on/profile-off zero-recompile check, and the
    perf-regression sentinel over any BENCH_r*.json rounds in the cwd.
    Diagnostic: reports, never raises."""
    from pint_tpu import compile_cache, profiling, telemetry

    lines = ["Profiling (--profile): gate "
             + ("ON" if profiling.enabled() else
                "off (forced on for this smoke; set "
                "$PINT_TPU_PROFILE=1 to profile real runs)")]
    try:
        import numpy as np

        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models.builder import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        model = get_model(WARM_WLS_PAR)
        toas = make_fake_toas_uniform(
            53000.0, 54000.0, 60, model, freq_mhz=1400.0, obs="gbt",
            error_us=1.0, add_noise=True,
            rng=np.random.default_rng(0))
        # fit 1 with the gate OFF (pays the cold compiles), fit 2 with
        # it ON: flipping the gate must trigger ZERO new XLA compiles
        # — the gate lives outside the traced program by construction
        with profiling.profiled(False):
            f1 = WLSFitter(toas, model)
            f1.fit_toas(maxiter=2)
        telemetry.compile_stats()
        before = telemetry.counter_get("jit.compile_events")
        hits_before = compile_cache.registry_stats()["hits"]
        with profiling.profiled(True):
            f2 = WLSFitter(toas, model)
            f2.fit_toas(maxiter=2)
        d_compiles = int(telemetry.counter_get("jit.compile_events")
                         - before)
        shared = compile_cache.registry_stats()["hits"] > hits_before
        monitoring = telemetry.compile_stats()["source"] \
            == "jax.monitoring"
        ok = shared and (d_compiles == 0 or not monitoring)
        lines.append(
            f"  profile-on/off zero-recompile smoke: "
            f"{d_compiles} new compile event(s), registry "
            f"{'shared' if shared else 'NOT SHARED'} -> "
            + ("OK" if ok else "PROBLEM"))

        lines.append("  per-program registry:")
        lines.extend(profiling.table_lines(indent="    "))

        hists = telemetry.histograms()
        bad = [n for n, s in hists.items()
               if s["n"] and not (s["p50"] <= s["p99"])]
        lines.append(
            f"  histograms: {len(hists)} recorded; p50<=p99 "
            + ("OK" if not bad else f"PROBLEM ({', '.join(bad)})"))

        mem = profiling.sample_memory()
        if mem:
            parts = [f"{k}={v / 1e6:.1f}MB" for k, v in mem.items()]
            lines.append("  memory watermarks: " + ", ".join(parts))
        else:
            lines.append("  memory watermarks: unavailable")
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")

    # perf-regression sentinel readout (printed, never failing here —
    # `pinttrace --check-regression` is the gating entry point)
    try:
        from pint_tpu.scripts.pinttrace import regression_verdict

        got = regression_verdict()
        if got is not None:
            header, vlines, _rc = got
            lines.append(f"  {header}")
            lines.extend(f"    {ln}" for ln in vlines)
        else:
            lines.append("  perf-regression sentinel: no BENCH_r*.json "
                         "rounds in cwd")
    except Exception as e:
        lines.append(f"  perf-regression sentinel: ERROR "
                     f"{type(e).__name__}: {e}")
    return lines


def _runs_section():
    """Run-ledger smoke (--runs): one small fit under a temporary
    trace sink with the flight recorder and profiling on, then the
    ledger join — the fit's run_id must connect >= 4 record types
    (run, span, health, iter_trace, program).  Diagnostic: reports,
    never raises."""
    import json
    import os
    import tempfile

    from pint_tpu import profiling, telemetry

    lines = ["Run ledger (--runs):"]
    prev_gate = os.environ.get("PINT_TPU_ITER_TRACE")
    # the smoke swaps the sink; the user's env-configured sink (and
    # span enablement) must come back afterwards — configure() CLOSES
    # a replaced owned sink, so this is restore-or-destroy
    prev_sink = telemetry.sink_info()
    fd, sink_path = tempfile.mkstemp(prefix="pint_tpu_runs_",
                                     suffix=".jsonl")
    os.close(fd)
    try:
        import numpy as np

        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models.builder import get_model
        from pint_tpu.scripts.pinttrace import (convergence_table,
                                                join_runs)
        from pint_tpu.simulation import make_fake_toas_uniform

        os.environ["PINT_TPU_ITER_TRACE"] = "1"
        model = get_model(WARM_WLS_PAR)
        toas = make_fake_toas_uniform(
            53000.0, 54000.0, 60, model, freq_mhz=1400.0, obs="gbt",
            error_us=1.0, add_noise=True,
            rng=np.random.default_rng(0))
        with open(sink_path, "w") as sink:
            telemetry.configure(sink=sink)
            try:
                with profiling.profiled(True):
                    f = WLSFitter(toas, model)
                    f.fit_toas(maxiter=3)
                telemetry.flush()
            finally:
                if prev_sink["path"] is not None:
                    telemetry.configure(sink=prev_sink["path"],
                                        enabled=prev_sink["enabled"])
                elif prev_sink["sink"] is not None:
                    telemetry.configure(sink=prev_sink["sink"],
                                        enabled=prev_sink["enabled"])
                else:
                    telemetry.configure(sink=None,
                                        enabled=prev_sink["enabled"])
        records = [json.loads(ln) for ln in open(sink_path)
                   if ln.strip()]
        runs = join_runs(records)
        fit_runs = [(rid, info) for rid, info in runs.items()
                    if (info["run"] or {}).get("kind") == "fit"]
        if not fit_runs:
            lines.append("  PROBLEM: no fit run record in the trace")
            return lines
        rid, info = fit_runs[-1]
        joined = set(info["types"])
        # the program record is a cumulative flush mirror — it joins
        # through its per-record `runs` list, not the emit-time tag
        for rec in records:
            if rec.get("type") == "program" \
                    and rid in (rec.get("runs") or ()):
                joined.add("program")
        need = {"run", "span", "health", "iter_trace"}
        ok = need.issubset(joined) and len(joined) >= 4
        lines.append(
            f"  one fit -> run {rid}: record types joined = "
            f"{sorted(joined)} -> "
            + ("OK" if ok else f"PROBLEM (need >= 4 incl. {sorted(need)})"))
        n_iter = info["n_iter"]
        lines.append(f"  iteration trace: {n_iter} entries "
                     + ("OK" if n_iter >= 1 else "PROBLEM"))
        for ln in convergence_table(records, rid):
            lines.append("    " + ln)
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    finally:
        if prev_gate is None:
            os.environ.pop("PINT_TPU_ITER_TRACE", None)
        else:
            os.environ["PINT_TPU_ITER_TRACE"] = prev_gate
        try:
            os.unlink(sink_path)
        except OSError:
            pass
    return lines


def _lint_section():
    """Trace-safety smoke (--lint): the static analyzer over the
    source tree this installation was loaded from (skipped when the
    docs/ tree is absent — an installed wheel), then the runtime
    recompile sanitizer exercised both ways: a warm armed fit must
    pass, and a forced same-shape recompile (registry cleared, same
    fit repeated) must be caught and attributed.  Diagnostic:
    reports, never raises."""
    lines = ["Trace safety (--lint):"]
    try:
        import numpy as np

        from pint_tpu import compile_cache
        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.lint import sanitizer, static
        from pint_tpu.models.builder import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        # -- static half ------------------------------------------------
        root = static.repo_root()
        if os.path.isdir(os.path.join(root, "docs")):
            findings, notes = static.run(root)
            lines.append(
                f"  static analyzer: {len(static.RULES)} rules, "
                f"{len(notes)} key-site tokens verified, "
                f"{len(findings)} finding(s) -> "
                + ("OK" if not findings else "PROBLEM"))
            for f in findings[:5]:
                lines.append(f"    {f.file}:{f.line}: {f.rule} "
                             f"{f.message}")
        else:
            lines.append("  static analyzer: skipped (no source "
                         "tree next to this installation; run "
                         "pintlint from a checkout)")

        # -- runtime half -----------------------------------------------
        model = get_model(WARM_WLS_PAR)
        toas = make_fake_toas_uniform(
            53000.0, 54000.0, 60, model, freq_mhz=1400.0, obs="gbt",
            error_us=1.0, add_noise=True,
            rng=np.random.default_rng(0))
        # seed under an ACTIVE (unarmed) sanitizer from a cleared
        # registry: the cold compiles record their arg-spec
        # fingerprints (benign kind 'first'), so the forced recompile
        # below classifies as the real same_shape_recompile — without
        # this the seeding compiles are invisible and the smoke could
        # only ever demonstrate the weaker armed-'first' path
        prev_mode = sanitizer.mode()
        compile_cache.clear_registry()
        sanitizer.configure("warn")
        try:
            WLSFitter(toas, model).fit_toas(maxiter=3)  # ensure warm
        finally:
            sanitizer.configure(prev_mode)
        v0 = int(_tel_counter("sanitizer.violations"))
        with sanitizer.sanitized(mode="raise"):
            WLSFitter(toas, get_model(WARM_WLS_PAR)).fit_toas(
                maxiter=3)
        lines.append("  warm fit under armed raise-mode sanitizer: "
                     "no violation -> OK")
        compile_cache.clear_registry()
        caught = None
        try:
            with sanitizer.sanitized(mode="raise"):
                WLSFitter(toas, get_model(WARM_WLS_PAR)).fit_toas(
                    maxiter=3)
        except sanitizer.RecompileError as e:
            caught = str(e)
        if caught:
            last = (sanitizer.ledger() or [{}])[-1]
            lines.append(
                "  forced recompile (registry cleared): caught, "
                f"attributed to {last.get('program', '?')} "
                f"(kind {last.get('kind', '?')}) -> OK")
        else:
            lines.append("  forced recompile: NOT caught "
                         "-> PROBLEM (is jax.monitoring available? "
                         f"listener={sanitizer.stats()['listener']})")
        dv = int(_tel_counter("sanitizer.violations")) - v0
        lines.append(f"  sanitizer counters: +{dv} violation(s) "
                     f"during the smoke, ledger depth "
                     f"{sanitizer.stats()['ledger_len']}")
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    return lines


def _tel_counter(name):
    from pint_tpu import telemetry

    return telemetry.counter_get(name)


def _serve_section():
    """Warm-service smoke (--serve): boot a replica on an ephemeral
    port, exercise one request of each type, assert two same-bucket
    requests coalesce into one batched dispatch (``serve.coalesced``
    moves), run a checkpointed grid job to completion, and saturate a
    1-deep queue to see the 429 + Retry-After shed path (and no 500s
    anywhere).  Diagnostic: reports, never raises."""
    import threading
    import time as _time

    from pint_tpu import telemetry

    lines = ["Warm service (--serve):"]
    srv = srv2 = None
    try:
        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.serve.client import request_json
        from pint_tpu.serve.server import Server

        srv = Server(flush_ms=100.0, max_batch=4, queue_max=32,
                     deadline_ms=0)
        port = srv.start(port=0)
        s, doc, _ = request_json("127.0.0.1", port, "GET", "/readyz")
        cold_ok = s == 503
        for i, name in enumerate(("smk0", "smk1")):
            s, info, _ = request_json(
                "127.0.0.1", port, "POST", "/v1/load",
                {"dataset": name, "par": WARM_WLS_PAR,
                 "toas": {"n": 50, "seed": i}})
            assert s == 200, info
        lines.append(f"  datasets: 2 loaded (bucket {info['bucket']},"
                     f" {info['kind']}); cold /readyz 503 -> "
                     + ("OK" if cold_ok else "PROBLEM"))
        srv.warmup("smk0", ops=("fit",), sizes=(1, 2), maxiter=2)
        s, doc, _ = request_json("127.0.0.1", port, "GET", "/readyz")
        lines.append("  explicit warmup: /readyz now "
                     + (f"{s} -> OK" if s == 200
                        else f"{s} -> PROBLEM"))

        # one request of each type
        s1, fit, _ = request_json(
            "127.0.0.1", port, "POST", "/v1/fit",
            {"dataset": "smk0", "maxiter": 2}, timeout=300)
        s2, res, _ = request_json(
            "127.0.0.1", port, "POST", "/v1/residuals",
            {"dataset": "smk0"}, timeout=300)
        s3, lnl, _ = request_json(
            "127.0.0.1", port, "POST", "/v1/lnlike",
            {"dataset": "smk0"}, timeout=300)
        ok = all(x == 200 for x in (s1, s2, s3))
        lines.append(
            f"  fit chi2={fit.get('chi2'):.2f} "
            f"({fit.get('status')}), residual rms "
            f"{res.get('rms_s', 0) * 1e6:.2f}us, lnlike "
            f"{lnl.get('lnlike'):.1f} -> "
            + ("OK" if ok else "PROBLEM"))

        # coalescing: two same-bucket fits inside one flush window
        before = telemetry.counter_get("serve.coalesced")
        out = [None, None]

        def fire(i, name):
            out[i] = request_json(
                "127.0.0.1", port, "POST", "/v1/fit",
                {"dataset": name, "maxiter": 2}, timeout=300)

        ts = [threading.Thread(target=fire, args=(i, n))
              for i, n in enumerate(("smk0", "smk1"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        moved = telemetry.counter_get("serve.coalesced") - before
        both = all(o is not None and o[0] == 200 for o in out)
        occ = (out[0][1].get("batch") or {}).get("occupancy")
        lines.append(
            f"  coalescing: 2 same-bucket fits -> occupancy {occ}, "
            f"serve.coalesced +{moved:g} -> "
            + ("OK" if moved >= 1 and both else "PROBLEM"))

        # request-scoped tracing: every 2xx op response carries a
        # traceparent + Server-Timing phase decomposition, and a
        # client-minted traceparent is continued, not replaced
        tp = out[0][2].get("traceparent", "")
        st_hdr = out[0][2].get("server-timing", "")
        ph = out[0][1].get("phase_s") or {}
        phased = all(k in ph for k in ("queue", "coalesce", "build",
                                       "device", "writeback"))
        cont_id = "ab" * 16
        s, r, _h = request_json(
            "127.0.0.1", port, "POST", "/v1/fit",
            {"dataset": "smk0", "maxiter": 2}, timeout=300,
            headers={"traceparent":
                     f"00-{cont_id}-{'cd' * 8}-01"})
        cont = (s == 200
                and (r.get("trace") or {}).get("trace_id") == cont_id)
        lines.append(
            "  tracing: traceparent "
            + (tp[:16] + "... " if tp else "MISSING ")
            + ("Server-Timing on, " if st_hdr else
               "Server-Timing MISSING, ")
            + f"{len(ph)} phase(s), client trace "
            + ("continued -> OK" if tp and st_hdr and phased and cont
               else "dropped -> PROBLEM"))

        # SLO engine + queue introspection surfaces
        s_slo, slo, _ = request_json("127.0.0.1", port, "GET", "/slo")
        s_st, stats_doc, _ = request_json("127.0.0.1", port, "GET",
                                          "/v1/stats")
        qblock = (stats_doc or {}).get("queue") or {}
        slo_ok = (s_slo == 200 and slo.get("verdict") is not None
                  and s_st == 200 and "depth" in qblock
                  and "slo" in (stats_doc or {}))
        lines.append(
            f"  slo: verdict {slo.get('verdict')!r}, /v1/stats "
            f"queue depth={qblock.get('depth')} "
            f"drain={qblock.get('drain_rate_rps')}/s -> "
            + ("OK" if slo_ok else "PROBLEM"))

        # checkpointed grid job
        s, job, _ = request_json(
            "127.0.0.1", port, "POST", "/v1/jobs",
            {"kind": "grid", "dataset": "smk0", "job": "smokegrid",
             "params": ["F0"], "n_steps": 1, "chunk": 3,
             "axes": {"F0": {"start": 186.4940815669,
                             "stop": 186.4940815671, "n": 6}}})
        deadline = _time.time() + 120
        while _time.time() < deadline:
            s, job, _ = request_json("127.0.0.1", port, "GET",
                                     "/v1/jobs/smokegrid")
            if job.get("state") in ("done", "failed"):
                break
            _time.sleep(0.25)
        lines.append(
            f"  grid job: {job.get('state')} "
            f"({(job.get('progress') or {}).get('done')} pts, "
            f"min chi2 {(job.get('result') or {}).get('min_chi2')}) "
            "-> " + ("OK" if job.get("state") == "done"
                     else f"PROBLEM ({job.get('error')})"))

        # shed path: saturate a 1-deep queue behind a slow flush
        srv2 = Server(flush_ms=500.0, max_batch=2, queue_max=1)
        p2 = srv2.start(port=0)
        srv2.registry.load("shed", par=WARM_WLS_PAR,
                           toas={"n": 50, "seed": 0})
        shed_out = []

        def burst(_):
            shed_out.append(request_json(
                "127.0.0.1", p2, "POST", "/v1/fit",
                {"dataset": "shed", "maxiter": 2}, timeout=300))

        ts = [threading.Thread(target=burst, args=(i,))
              for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        codes = sorted(o[0] for o in shed_out)
        n429 = codes.count(429)
        n5xx = sum(1 for c in codes if c >= 500 and c != 503)
        retry = [o[2].get("retry-after") for o in shed_out
                 if o[0] == 429]
        lines.append(
            f"  load shedding: burst of 4 into queue_max=1 -> "
            f"{codes}, Retry-After {retry[:1]}, "
            f"{n429} shed, {n5xx} server error(s) -> "
            + ("OK" if n429 >= 1 and n5xx == 0 else "PROBLEM"))
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    finally:
        for s_ in (srv, srv2):
            if s_ is not None:
                try:
                    s_.stop()
                except Exception:
                    pass
    return lines


def _stream_section():
    """Streaming-append smoke (--stream): boot a replica, load a
    dataset, push one clean night through ``POST
    /v1/datasets/<id>/append`` (incremental mode, version bump,
    freshness gauge), push a second night under an absurdly tight
    triage threshold to see the quarantine path, and read the
    ``stream.*`` counters back.  Diagnostic: reports, never raises."""
    from pint_tpu import telemetry

    lines = ["Streaming appends (--stream):"]
    srv = None
    try:
        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.serve.client import request_json
        from pint_tpu.serve.server import Server

        srv = Server(flush_ms=100.0, max_batch=4, queue_max=32,
                     deadline_ms=0)
        port = srv.start(port=0)
        s, info, _ = request_json(
            "127.0.0.1", port, "POST", "/v1/load",
            {"dataset": "streamsmk", "par": WARM_WLS_PAR,
             "toas": {"n": 70, "seed": 0}})
        assert s == 200, info
        v0 = info["version"] if "version" in info else 1
        lines.append(f"  dataset: n={info['n_toas']} bucket "
                     f"{info['bucket']} ({info['kind']})")

        # clean night: incremental append + atomic version publish
        s, doc, _ = request_json(
            "127.0.0.1", port, "POST",
            "/v1/datasets/streamsmk/append",
            {"toas": {"n": 5, "seed": 7}}, timeout=600)
        ok = (s == 200 and doc.get("mode") == "incremental"
              and doc.get("verdict") == "clean"
              and doc.get("version", 0) > v0)
        lines.append(
            f"  append: +{doc.get('n_appended')} TOAs -> mode "
            f"{doc.get('mode')!r}, verdict {doc.get('verdict')!r}, "
            f"version {v0} -> {doc.get('version')}, "
            f"{doc.get('latency_ms')} ms -> "
            + ("OK" if ok else "PROBLEM"))

        # triage: a 0.05-sigma threshold flags ordinary noise rows —
        # the quarantine machinery, not the science, is under test
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            s, doc2, _ = request_json(
                "127.0.0.1", port, "POST",
                "/v1/datasets/streamsmk/append",
                {"toas": {"n": 5, "seed": 8},
                 "triage_sigma": 0.05}, timeout=600)
        tri_ok = (s == 200 and doc2.get("verdict") != "clean"
                  and len(doc2.get("quarantined") or ()) >= 1)
        lines.append(
            f"  triage: 0.05-sigma threshold -> verdict "
            f"{doc2.get('verdict')!r}, "
            f"{len(doc2.get('quarantined') or ())} quarantined -> "
            + ("OK" if tri_ok else "PROBLEM"))

        # freshness SLO gauge + counters
        fresh = telemetry.gauges().get("stream.freshness_s")
        counts = {k: _tel_counter(k) for k in
                  ("stream.appends", "stream.refits",
                   "stream.publishes", "stream.quarantined")}
        g_ok = fresh is None or 0.0 <= float(fresh) < 600.0
        lines.append(
            f"  freshness: stream.freshness_s={fresh}, "
            + ", ".join(f"{k.split('.')[1]}={v:g}"
                        for k, v in counts.items())
            + " -> " + ("OK" if g_ok and counts["stream.publishes"]
                        >= 2 else "PROBLEM"))
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    finally:
        if srv is not None:
            try:
                srv.stop()
            except Exception:
                pass
    return lines


def _fleet_section():
    """Fleet-orchestration smoke (--fleet): two in-process replicas
    behind a real router socket — broadcast load fans out and
    journals, requests land on the rendezvous owner, killing the
    owner re-routes with zero 5xx, and an all-drained fleet yields
    the router's structured 503 (never a 500).  The full subprocess
    chaos story (kill mid-batch, rolling deploy, job failover) lives
    in ``pint_tpu.fleet.chaos`` / ``tests/test_fleet.py``.
    Diagnostic: reports, never raises."""
    from pint_tpu import telemetry

    lines = ["Fleet orchestration (--fleet):"]
    srv_a = srv_b = router = None
    try:
        from pint_tpu.compile_cache import WARM_WLS_PAR
        from pint_tpu.fleet.client import RetryClient
        from pint_tpu.fleet.router import Router, rendezvous_order
        from pint_tpu.serve.client import request_json
        from pint_tpu.serve.server import Server

        srv_a = Server(flush_ms=50.0, max_batch=4, queue_max=32)
        srv_b = Server(flush_ms=50.0, max_batch=4, queue_max=32)
        pa, pb = srv_a.start(port=0), srv_b.start(port=0)
        targets = [f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"]
        router = Router(targets=targets, probe_s=30.0)
        rp = router.start(port=0)

        # broadcast load: one POST through the router reaches BOTH
        # replicas and lands in the rejoin journal
        for i, name in enumerate(("flt0", "flt1")):
            s, doc, _ = request_json(
                "127.0.0.1", rp, "POST", "/v1/load",
                {"dataset": name, "par": WARM_WLS_PAR,
                 "toas": {"n": 50, "seed": i}})
            assert s == 200, doc
        fanout = (len(srv_a.registry.ids()) == 2
                  and len(srv_b.registry.ids()) == 2
                  and doc.get("journaled") is True)
        lines.append(
            f"  broadcast load: 2 datasets -> a={srv_a.registry.ids()}"
            f" b={srv_b.registry.ids()}, journaled -> "
            + ("OK" if fanout else "PROBLEM"))

        # warm once (shared in-process jit registry warms both) and
        # place through the router: the rendezvous owner serves it
        srv_a.warmup("flt0", ops=("fit",), sizes=(1,), maxiter=2)
        n_ready = router.probe_now()
        owner = rendezvous_order("flt0", targets)[0]
        before = dict(telemetry.counters())
        s, fit, _ = request_json(
            "127.0.0.1", rp, "POST", "/v1/fit",
            {"dataset": "flt0", "maxiter": 2}, timeout=300)
        lines.append(
            f"  placement: {n_ready}/2 ready, fit via router "
            f"chi2={fit.get('chi2'):.2f} (owner {owner}) -> "
            + ("OK" if s == 200 and n_ready == 2 else "PROBLEM"))

        # kill the owner: the router must re-route to the sibling
        # with ZERO client-visible 5xx
        victim = srv_a if owner.endswith(str(pa)) else srv_b
        victim.stop()
        router.probe_now()
        client = RetryClient("127.0.0.1", rp, timeout=300)
        s, fit, _ = client.post("/v1/fit",
                                {"dataset": "flt0", "maxiter": 2})
        client.close()
        ctr = telemetry.counters()
        rerouted = (ctr.get("router.reroutes", 0)
                    + ctr.get("router.proxy_errors", 0)
                    - before.get("router.reroutes", 0)
                    - before.get("router.proxy_errors", 0))
        lines.append(
            f"  owner death: re-route moved {rerouted:g} "
            f"counter(s), fit {s} chi2={fit.get('chi2'):.2f} -> "
            + ("OK" if s == 200 and rerouted >= 1 else "PROBLEM"))

        # drain the survivor: the fleet is empty and the router's
        # answer is the structured 503 contract, never a 500
        survivor = srv_b if victim is srv_a else srv_a
        sp = pb if victim is srv_a else pa
        s, doc, _ = request_json("127.0.0.1", sp, "POST", "/drain",
                                 {"timeout_s": 10})
        drained = s == 200 and doc.get("draining") is True
        router.probe_now()
        s, doc, h = request_json("127.0.0.1", rp, "POST", "/v1/fit",
                                 {"dataset": "flt0", "maxiter": 2})
        lines.append(
            f"  all drained: /drain {'OK' if drained else 'PROBLEM'},"
            f" router -> {s} {doc.get('error')} "
            f"Retry-After {h.get('retry-after')!r} -> "
            + ("OK" if s == 503 and doc.get("error") == "ServeError"
               else "PROBLEM"))
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    finally:
        if router is not None:
            try:
                router.stop()
            except Exception:
                pass
        for s_ in (srv_a, srv_b):
            if s_ is not None:
                try:
                    s_.stop()
                except Exception:
                    pass
        telemetry.gauge_set("serve.draining", 0.0)
    return lines


def _aot_child(mode, path):
    """Child entry for the --aot smoke (one fresh interpreter per
    probe run): prints the probe record as a JSON line."""
    import json

    from pint_tpu.compile_cache import aot_cold_start_probe

    print(json.dumps(aot_cold_start_probe(
        mode, path, kind="wls", n_toas=64, maxiter=2)), flush=True)
    return 0


def _aot_section():
    """AOT executable-serialization smoke (--aot): export this
    machine's fit executables from one fresh subprocess, import them
    in a second, and verify the served fit is bit-identical with zero
    UNCACHED XLA backend compiles; then exercise the graceful
    per-entry reject on a deliberately version-skewed manifest entry.
    Diagnostic: reports, never raises."""
    import json
    import subprocess
    import sys as _sys
    import tempfile

    lines = ["AOT executable serialization (--aot):"]
    try:
        from pint_tpu import compile_cache

        with tempfile.TemporaryDirectory(
                prefix="pint_tpu_aot_") as d:
            env = dict(os.environ)
            env["PINT_TPU_CACHE_DIR"] = os.path.join(d, "xla")

            def child(mode):
                r = subprocess.run(
                    [_sys.executable, "-m", "pint_tpu.datacheck",
                     "--aot-child", mode, d],
                    capture_output=True, text=True, env=env,
                    timeout=300)
                if r.returncode != 0:
                    raise RuntimeError(
                        f"{mode} child rc={r.returncode}: "
                        f"{(r.stderr or '')[-300:]}")
                recs = [ln for ln in r.stdout.splitlines()
                        if ln.startswith("{")]
                return json.loads(recs[-1])

            exp = child("export")
            lines.append(
                f"  export: {exp['exported']} executable(s) "
                f"serialized ({exp['skipped']} skipped), first fit "
                f"{exp['wall_s']:.1f}s cold")
            imp = child("import")
            identical = imp["chi2"] == exp["chi2"]
            zero = imp["uncached_backend_compiles"] == 0
            served = imp["aot_hits"] > 0
            ok = identical and served and (zero
                                           or not imp["monitoring"])
            lines.append(
                f"  fresh-process import: {imp['loaded']} loaded, "
                f"{imp['aot_hits']} AOT hit(s), "
                f"{imp['uncached_backend_compiles']} uncached backend "
                f"compile(s), first fit {imp['wall_s']:.1f}s")
            lines.append(
                "  fit equality: chi2 "
                + ("bit-identical" if identical else
                   f"DIFFERS ({imp['chi2']!r} != {exp['chi2']!r})")
                + "; zero-uncached-compile contract "
                + ("OK" if zero else "VIOLATED")
                + (" -> OK" if ok else " -> PROBLEM"))

            # graceful reject: clone one manifest entry with a skewed
            # jax version — the import must skip IT (counter ticks)
            # and still load the rest, never raise
            man_path = os.path.join(d, "manifest.json")
            with open(man_path) as fh:
                doc = json.load(fh)
            if doc.get("entries"):
                skew = dict(doc["entries"][0])
                skew["hash"] = "f" * 32
                skew["jax"] = "0.0.0-version-skew"
                doc["entries"].append(skew)
                with open(man_path, "w") as fh:
                    json.dump(doc, fh)
                from pint_tpu import telemetry

                before = telemetry.counter_get(
                    "jit.aot_import_rejects")
                got = compile_cache.import_executables(d)
                ticked = telemetry.counter_get(
                    "jit.aot_import_rejects") - before
                reasons = [w for _, w in got["rejected"]]
                graceful = (got["loaded"] >= 1 and ticked >= 1
                            and any("mismatch" in w for w in reasons))
                compile_cache.clear_aot_store()
                lines.append(
                    f"  version-skewed entry: {len(got['rejected'])} "
                    f"rejected / {got['loaded']} still loaded, "
                    f"reject counter +{int(ticked)} -> "
                    + ("OK (graceful per-entry fallback)"
                       if graceful else "PROBLEM"))
            else:
                lines.append("  version-skew check skipped (nothing "
                             "exported on this backend)")
    except Exception as e:  # diagnostic must never take the report down
        lines.append(f"  ERROR {type(e).__name__}: {e}")
    return lines


def _last_session_compile_lines():
    """Compile/span stats aggregated from the $PINT_TPU_TRACE file, if
    one exists and parses.  The sink appends, so the totals cover every
    session that wrote to the file — including the current one when its
    sink is attached to the same path (the label says so).  Parsing is
    delegated to the pinttrace CLI's loader so the two trace consumers
    can't drift."""
    from pint_tpu.scripts.pinttrace import _load

    path = os.environ.get("PINT_TPU_TRACE")
    if not path or not os.path.exists(path):
        return []
    try:
        records, _ = _load(path)
    except OSError:
        return []
    events = seconds = None
    n_spans = 0
    for rec in records:
        if rec.get("type") == "span":
            n_spans += 1
        elif rec.get("type") == "counter":
            if rec.get("name") == "jit.compile_events":
                events = rec.get("value")
            elif rec.get("name") == "jit.compile_seconds":
                seconds = rec.get("value")
    if events is None and seconds is None and not n_spans:
        return []
    from pint_tpu import telemetry

    live = " incl. this session" if telemetry.enabled() else ""
    out = [f"  trace file ({path}, all sessions{live}): "
           f"{n_spans} span(s)"]
    if events is not None or seconds is not None:
        out[0] += (f", compile {int(events or 0)} event(s) / "
                   f"{float(seconds or 0.0):.2f}s")
    return out


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m pint_tpu.datacheck",
        description="Report active timing data sources + accuracy "
                    "consequences")
    p.add_argument("ephem", nargs="?", default="builtin",
                   help="ephemeris name to resolve (default builtin)")
    p.add_argument("--warm", action="store_true",
                   help="AOT-compile a small standard fit shape into "
                        "the persistent cache after the report "
                        "(pintwarm does the full shape sweep)")
    p.add_argument("--faults", action="store_true",
                   help="run the fault-injection smoke: each fast "
                        "fault class must recover via a documented "
                        "ladder rung or raise a structured error")
    p.add_argument("--profile", action="store_true",
                   help="run the device-truth profiling smoke: "
                        "per-program table, histogram sanity, memory "
                        "watermarks, profile-on/off zero-recompile "
                        "check, perf-regression sentinel readout")
    p.add_argument("--mesh", action="store_true",
                   help="run the mesh-layer smoke: device inventory, "
                        "mesh construction, partition-rule resolution "
                        "over a real PTA batch pytree, sharded == "
                        "unsharded fit comparison")
    p.add_argument("--aot", action="store_true",
                   help="run the AOT executable-serialization smoke: "
                        "export -> fresh-subprocess import -> "
                        "bit-identical fit with zero uncached XLA "
                        "backend compiles, plus the version-skew "
                        "graceful-reject path")
    p.add_argument("--gwb", action="store_true",
                   help="run the GWB kron/HMC smoke: kron-structured "
                        "lnlike vs the dense reference, gradient vs "
                        "central finite differences, tiny NUTS run")
    p.add_argument("--serve", action="store_true",
                   help="run the warm-service smoke: boot a replica "
                        "on an ephemeral port, one request of each "
                        "type, coalescing of two same-bucket "
                        "requests asserted via serve.coalesced, a "
                        "checkpointed grid job, and the 429 shed "
                        "path under a saturated queue")
    p.add_argument("--runs", action="store_true",
                   help="run the run-ledger smoke: one fit under a "
                        "temp trace sink must reconstruct with >= 4 "
                        "record types joined by run_id, and its "
                        "per-iteration convergence table renders")
    p.add_argument("--lint", action="store_true",
                   help="run the trace-safety smoke: the pintlint "
                        "static analyzer over the source tree, a "
                        "warm fit under the armed recompile "
                        "sanitizer, and a forced same-shape "
                        "recompile that must be caught + attributed")
    p.add_argument("--fleet", action="store_true",
                   help="run the fleet smoke: two in-process replicas "
                        "behind the rendezvous router, broadcast load "
                        "+ journal, a routed fit, owner kill with "
                        "re-route to the sibling, and the drained "
                        "all-down structured 503")
    p.add_argument("--corpus", action="store_true",
                   help="run the scenario-corpus smoke: realize a "
                        "clean, a correlated-noise, and a faulted "
                        "scenario, oracle-parity verdicts on each, "
                        "reference-PINT availability readout")
    p.add_argument("--stream", action="store_true",
                   help="run the streaming-append smoke: one clean "
                        "night through POST /v1/datasets/<id>/append "
                        "(incremental mode + version bump), one night "
                        "under a tight triage threshold (quarantine "
                        "path), freshness gauge + stream.* counter "
                        "readout")
    p.add_argument("--aot-child", nargs=2, metavar=("MODE", "DIR"),
                   default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.aot_child is not None:
        return _aot_child(*args.aot_child)
    for line in datacheck_report(args.ephem):
        print(line)
    if args.faults:
        for line in _faults_section():
            print(line)
    if args.gwb:
        for line in _gwb_section():
            print(line)
    if args.runs:
        for line in _runs_section():
            print(line)
    if args.corpus:
        for line in _corpus_section():
            print(line)
    if args.serve:
        for line in _serve_section():
            print(line)
    if args.stream:
        for line in _stream_section():
            print(line)
    if args.fleet:
        for line in _fleet_section():
            print(line)
    if args.profile:
        for line in _profile_section():
            print(line)
    if args.mesh:
        for line in _mesh_section():
            print(line)
    if args.aot:
        for line in _aot_section():
            print(line)
    # last among the smokes: the forced-recompile drill clears the
    # shared-jit registry, which would make every later section
    # re-trace its programs and skew the hit/miss counters it reports
    if args.lint:
        for line in _lint_section():
            print(line)
    if args.warm:
        from pint_tpu import compile_cache

        d = compile_cache.enable_persistent_cache()
        print(f"Warmup (cache {d or 'DISABLED'}):")
        compile_cache.warmup(toa_counts=(500,), kinds=("wls", "gls"),
                             progress=lambda s: print("  " + s))
        if d:
            print(f"  -> {compile_cache.cache_entries()} entries on disk")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
