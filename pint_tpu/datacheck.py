"""Data-source diagnostic: which accuracy-critical inputs are active.

``python -m pint_tpu.datacheck [EPHEM]`` (or ``datacheck_report()``)
reports, for the current environment, what the timing chain will
actually use — the resolved ephemeris, clock files per observatory,
BIPM realization, and IERS Earth-orientation data — with the accuracy
consequence of each missing input (the ACCURACY.md budget, live).

The reference equivalent is scattered across astropy's download cache
diagnostics and ``pint.observatory.list_last_correction_mjds``; here
offline data installation is the explicit contract, so the check is a
first-class tool.
"""

from __future__ import annotations

import os

__all__ = ["datacheck_report", "main"]


def datacheck_report(ephem="builtin", sites=("gbt", "ao", "jb", "pks",
                                             "vla", "meerkat")):
    """Return the diagnostic as a list of text lines."""
    lines = []

    from pint_tpu.ephem import get_ephemeris

    eph = get_ephemeris(ephem)
    lines.append(f"Ephemeris [{ephem!r}]: {eph.identity}")
    if eph.identity.startswith("spk:"):
        lines.append("  -> JPL kernel active (reference-grade)")
    else:
        lines.append(
            "  -> no JPL kernel: builtin/analytic ephemeris "
            "(~10-100 us out-of-window drift; place de440.bsp under "
            "$PINT_TPU_EPHEM_DIR for reference-grade accuracy)")

    from pint_tpu.obs import get_observatory
    from pint_tpu.obs.clock import _clock_dirs, find_clock_chain

    dirs = _clock_dirs()
    lines.append(f"Clock search dirs: {dirs or 'none (set $PINT_TPU_CLOCK_DIR)'}")
    n_found = 0
    for site in sites:
        try:
            obs = get_observatory(site)
        except KeyError:
            continue
        try:
            chain = find_clock_chain(obs)
        except Exception as e:  # surface, never hide, a broken file
            lines.append(f"  {site}: ERROR {type(e).__name__}: {e}")
            n_found += 1
            continue
        files = [getattr(c, "filename", "?") for c in (chain or [])]
        if files:
            n_found += 1
            lines.append(f"  {site}: {', '.join(map(str, files))}")
    if n_found == 0:
        lines.append(
            "  -> no site clock files: site clocks assumed perfect "
            "(~0.1-1 us dropped)")
    bipm_files = [f for d in dirs for f in sorted(os.listdir(d))
                  if f.startswith("tai2tt_bipm")]
    lines.append(
        "BIPM realization: "
        + (f"available ({', '.join(bipm_files)})" if bipm_files
           else "none (CLK TT(BIPMxxxx) pars fall back to TT(TAI))"))

    from pint_tpu.obs.iers import _iers_dirs, get_eop

    eop = get_eop()
    if eop is not None:
        lines.append(
            f"IERS EOP: table of {eop.mjd.size} rows, MJD "
            f"{eop.mjd.min():.0f}-{eop.mjd.max():.0f} "
            f"(polar motion + UT1 active)")
    else:
        lines.append(
            f"IERS EOP: none (searched {_iers_dirs() or ['$PINT_TPU_IERS_DIR']}); "
            "UT1=UTC (~1 us), no polar motion (~30 ns)")

    import jax

    lines.append(f"JAX backend: {jax.default_backend()} "
                 f"({len(jax.devices())} device(s))")
    from pint_tpu.fixedpoint import backend_f64_is_ieee

    ieee = backend_f64_is_ieee()
    lines.append(
        "f64 semantics: "
        + ("IEEE correctly-rounded (dd arithmetic valid)" if ieee
           else "~49-bit emulated (int64 fixed-point phase path active; "
                "see TPU_PRECISION.md)"))
    return lines


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m pint_tpu.datacheck",
        description="Report active timing data sources + accuracy "
                    "consequences")
    p.add_argument("ephem", nargs="?", default="builtin",
                   help="ephemeris name to resolve (default builtin)")
    args = p.parse_args(argv)
    for line in datacheck_report(args.ephem):
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
