"""ctypes bridge to the native (C++) ingest kernels.

The reference keeps its native surface in dependencies (numpy
longdouble, erfa, LAPACK — SURVEY section 2.9); the TPU build's own
native runtime lives in ``native/pint_tpu_native.cpp``: exact tempo2
.tim line parsing and batched SPK Chebyshev evaluation.  Loaded lazily
via ctypes (no pybind11 in the image); built on demand with make/g++;
every caller has a pure-Python fallback, so the library is an
accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings

import numpy as np

__all__ = ["get_lib", "parse_tim_lines_native", "spk_chebyshev_native"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpint_tpu_native.so")

_lib = None
_tried = False


def _build():
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:  # g++/make missing or failing
        return False


def get_lib():
    """The loaded native library, building it on first use; None if
    unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.path.isdir(_NATIVE_DIR):
        # Always run make: a no-op when up to date, and it rebuilds a
        # stale .so when pint_tpu_native.cpp changed (the library is
        # never committed to version control).
        built = _build()
        if not os.path.exists(_LIB_PATH):
            if not built:
                warnings.warn("native ingest build failed (no g++/make?); "
                              "using the pure-Python path")
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    if lib.pint_tpu_native_abi_version() != 1:
        # Do NOT re-dlopen here: dlopen on the same path returns the
        # already-loaded stale handle, so a rebuilt library would never
        # actually be picked up in-process.
        warnings.warn("native library ABI mismatch; "
                      "using the pure-Python path")
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.parse_tim_lines.argtypes = [
        ctypes.c_char_p, i64p, ctypes.c_int64, i64p, i64p, i64p,
        f64p, f64p, ctypes.c_char_p, i32p, i32p,
    ]
    lib.parse_tim_lines.restype = None
    lib.spk_chebyshev_eval.argtypes = [
        f64p, f64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i64p, f64p, ctypes.c_int64, f64p, f64p,
    ]
    lib.spk_chebyshev_eval.restype = None
    _lib = lib
    return _lib


def _ptr(a, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def parse_tim_lines_native(text: bytes, offsets: np.ndarray):
    """Parse tempo2 data lines in one native call.

    text: the raw file bytes; offsets: (n+1,) int64 line-start offsets.
    Returns dict of arrays + per-line status (nonzero = python
    fallback needed), or None if the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(offsets) - 1
    day = np.zeros(n, dtype=np.int64)
    num = np.zeros(n, dtype=np.int64)
    den = np.zeros(n, dtype=np.int64)
    err = np.zeros(n, dtype=np.float64)
    freq = np.zeros(n, dtype=np.float64)
    sites = np.zeros(n, dtype="S16")
    flags_off = np.zeros(n, dtype=np.int32)
    status = np.zeros(n, dtype=np.int32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lib.parse_tim_lines(
        text, _ptr(offsets, ctypes.c_int64), n,
        _ptr(day, ctypes.c_int64), _ptr(num, ctypes.c_int64),
        _ptr(den, ctypes.c_int64), _ptr(err, ctypes.c_double),
        _ptr(freq, ctypes.c_double),
        sites.ctypes.data_as(ctypes.c_char_p),
        _ptr(flags_off, ctypes.c_int32), _ptr(status, ctypes.c_int32),
    )
    return {
        "day": day, "frac_num": num, "frac_den": den, "err_us": err,
        "freq_mhz": freq, "sites": sites, "flags_off": flags_off,
        "status": status,
    }


def spk_chebyshev_native(coeffs, radii, rec_idx, s):
    """(pos, d/dt) for stacked Chebyshev records; None if the library
    is unavailable.  Shapes: coeffs (nrec, ncomp, ncoef) C-contiguous,
    radii (nrec,), rec_idx (nt,) int64, s (nt,) scaled times."""
    lib = get_lib()
    if lib is None:
        return None
    coeffs = np.ascontiguousarray(coeffs, dtype=np.float64)
    radii = np.ascontiguousarray(radii, dtype=np.float64)
    rec_idx = np.ascontiguousarray(rec_idx, dtype=np.int64)
    s = np.ascontiguousarray(s, dtype=np.float64)
    nrec, ncomp, ncoef = coeffs.shape
    nt = s.shape[0]
    pos = np.zeros((nt, ncomp), dtype=np.float64)
    vel = np.zeros((nt, ncomp), dtype=np.float64)
    lib.spk_chebyshev_eval(
        _ptr(coeffs, ctypes.c_double), _ptr(radii, ctypes.c_double),
        nrec, ncomp, ncoef, _ptr(rec_idx, ctypes.c_int64),
        _ptr(s, ctypes.c_double), nt, _ptr(pos, ctypes.c_double),
        _ptr(vel, ctypes.c_double),
    )
    return pos, vel
