"""Compile-amortization subsystem: persistent XLA cache, shared jit
registry, TOA shape bucketing, AOT warmup.

Every recorded bench round shows XLA compile time dwarfing compute on
the fit hot path (PERF.md: ~30 s compiles feeding fits that then run in
milliseconds) — and the seed design paid it again on every process
start, every new ``Fitter`` instance, and every TOA-count change.  This
module is the single place that cost is amortized, in five layers:

1. **Persistent on-disk XLA compilation cache** —
   :func:`enable_persistent_cache` turns on
   ``jax_compilation_cache_dir`` (version-tolerant: falls back to the
   ``jax.experimental.compilation_cache`` API, degrades to a no-op when
   neither exists) so compiled executables survive process restarts.
   Gated by ``PINT_TPU_CACHE_DIR``: the fit path auto-enables only when
   the variable is set; an explicit call (``pintwarm``, ``datacheck
   --warm``) defaults to ``~/.cache/pint_tpu/xla``.  ``0``/``off``/
   ``none`` disable.
2. **Process-level shared jit registry** — :func:`shared_jit` keys a
   jitted callable on (function identity x static-structure key), so
   two fitters on same-shaped problems share ONE trace and ONE
   executable instead of each paying ``jax.jit(self._step)`` from
   scratch.  Correctness rests on the callers' keys covering everything
   their trace bakes in: the fit-path step functions take the per-TOA
   data as *arguments* (pytrees of arrays, like the batched PTA path
   always has), so only model *structure* is baked and the key is
   structural (:func:`model_structure_key`).  Hits/misses feed the
   telemetry counters ``compile_cache.registry_{hits,misses}``.
3. **TOA-count shape bucketing** — :func:`pad_toas` pads a dataset to
   the next geometric bucket (:func:`bucket_size`, 1.25x steps) with
   sentinel TOAs of enormous uncertainty (``PAD_ERROR_US``), whose
   weight ``1/sigma^2 ~ 1e-32`` drops out of every weighted reduction
   to beyond f64 resolution — the exact zero-weight-padding discipline
   of :mod:`pint_tpu.parallel.pta`.  Nearby dataset sizes then share
   one executable instead of forcing a fresh compile per TOA count.
4. **AOT warmup** — :func:`warmup` ``lower().compile()``s the standard
   fit shapes offline (the ``pintwarm`` CLI / ``datacheck --warm``) to
   pre-populate the persistent cache, so the first real fit of a fresh
   process pays a disk read instead of a 30-second compile.
5. **AOT executable serialization** — :func:`export_executables` /
   :func:`import_executables` serialize the compiled registry programs
   themselves (manifest keyed by stable identity x jit key x
   jax-version/backend/topology, per-backend codec), so a fresh
   process serves :func:`shared_jit` lookups from deserialized
   executables: no trace, no lowering, zero uncached backend compiles
   before its first fit (``pintwarm --export/--import``, ``datacheck
   --aot``, bench ``cold_start_s``).

This module also owns the scan-vs-unroll choice for fixed-count GN
iteration loops inside traces (:func:`iterate_fixed`,
``$PINT_TPU_SCAN_ITERS``): scanning the iteration body shrinks the
HLO the backend compiles by roughly the iteration count — the grid
and batched-PTA programs route through it, with the flag in their jit
keys.

The split/merge helpers (:func:`split_ctx` / :func:`merge_ctx`) carry
the prepare-time component ctx across the jit boundary: array leaves
travel as dynamic arguments, static python leaves stay closed over and
are folded into the structural key.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from pint_tpu import profiling, telemetry

__all__ = [
    "enable_persistent_cache", "cache_dir", "cache_entries",
    "shared_jit", "registry_stats", "clear_registry",
    "bucket_size", "pad_toas", "append_toas", "apply_toa_row_plan",
    "PAD_ERROR_US",
    "split_ctx", "merge_ctx", "fingerprint",
    "model_structure_key", "donation_argnums", "warmup",
    "scan_iters_default", "iterate_fixed", "iter_trace_default",
    "gn_trace_record", "decode_gn_trace",
    "export_executables", "import_executables", "aot_store_stats",
    "clear_aot_store", "aot_cold_start_probe",
]

_CACHE_ENV = "PINT_TPU_CACHE_DIR"
_BUCKET_ENV = "PINT_TPU_BUCKET_TOAS"
_SCAN_ENV = "PINT_TPU_SCAN_ITERS"
_ITER_TRACE_ENV = "PINT_TPU_ITER_TRACE"
_KRON_PHI_ENV = "PINT_TPU_KRON_PHI"
_DEFAULT_CACHE_DIR = os.path.join("~", ".cache", "pint_tpu", "xla")
_AOT_MANIFEST = "manifest.json"
_AOT_FORMAT = 1

_lock = threading.RLock()


# --------------------------------------------------------------------------
# layer 1: persistent on-disk XLA compilation cache
# --------------------------------------------------------------------------

#: None = not yet decided; "" = disabled; otherwise the active dir
_cache_dir_state = None


def _disabled_token(raw) -> bool:
    return str(raw).strip().lower() in ("", "0", "off", "none", "disabled")


def enable_persistent_cache(path=None):
    """Enable the on-disk XLA compilation cache; returns the directory
    (or None when disabled/unavailable).  Idempotent.

    path=None resolves ``$PINT_TPU_CACHE_DIR``, falling back to
    ``~/.cache/pint_tpu/xla``.  Set the env var to ``0``/``off`` to
    disable explicitly.  Every jax config knob is applied inside its
    own try/except so a jax version that lacks one still gets the rest
    (version-tolerant fallback, never an import-time crash)."""
    global _cache_dir_state
    with _lock:
        if _cache_dir_state is not None and path is None:
            return _cache_dir_state or None
        raw = path if path is not None else os.environ.get(
            _CACHE_ENV, _DEFAULT_CACHE_DIR)
        if _disabled_token(raw):
            _cache_dir_state = ""
            return None
        resolved = os.path.abspath(os.path.expanduser(os.fspath(raw)))
        try:
            os.makedirs(resolved, exist_ok=True)
        except OSError as e:
            import sys

            print(f"pint_tpu.compile_cache: cannot create cache dir "
                  f"{resolved!r}: {e}; persistent cache disabled",
                  file=sys.stderr)
            _cache_dir_state = ""
            return None
        import jax

        ok = False
        try:
            jax.config.update("jax_compilation_cache_dir", resolved)
            ok = True
        except Exception:
            try:  # pre-config-flag API
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc,
                )

                _cc.set_cache_dir(resolved)
                ok = True
            except Exception:
                pass
        if not ok:
            _cache_dir_state = ""
            return None
        # cache every compile, not just the >1s ones: the whole point
        # is amortizing fit-step compiles across processes
        for knob, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(knob, val)
            except Exception:
                pass
        # a backend initialized before this call holds a cache handle
        # built with the old dir; reset so the new dir takes effect
        try:
            from jax._src import compilation_cache as _icc

            _icc.reset_cache()
        except Exception:
            pass
        _cache_dir_state = resolved
        telemetry.gauge_set("compile_cache.dir", resolved)
        return resolved


def _auto_enable():
    """Fit-path hook: enable the disk cache iff the env var asks for
    it.  (Explicit tools — pintwarm, datacheck --warm — call
    enable_persistent_cache() directly and get the default dir.)"""
    if _cache_dir_state is None and os.environ.get(_CACHE_ENV):
        enable_persistent_cache()


def cache_dir():
    """The active persistent-cache directory, or None."""
    return _cache_dir_state or None


def cache_entries():
    """Number of compiled executables in the persistent cache (0 when
    disabled or empty)."""
    d = cache_dir()
    if not d:
        return 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    # jax's LRU file cache stores '<key>-cache' payloads next to
    # '-atime' bookkeeping files; older layouts store bare keys
    payload = [n for n in names if not n.endswith("-atime")]
    return len(payload)


def _reset_for_tests():
    """Forget the enable decision and empty the registry and the
    imported-executable store (tests)."""
    global _cache_dir_state
    with _lock:
        _cache_dir_state = None
        _registry.clear()
        _aot_store.clear()


# --------------------------------------------------------------------------
# layer 2: process-level shared jit registry
# --------------------------------------------------------------------------

_registry: "OrderedDict" = OrderedDict()


def _registry_cap():
    try:
        return max(1, int(os.environ.get("PINT_TPU_JIT_REGISTRY_CAP",
                                         "128")))
    except ValueError:
        return 128


def _derive_label(fn, key):
    """Program label for the profiling registry: the conventional
    string head of the key (every library key starts with one), else
    the callable's qualname."""
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        return key[0]
    return getattr(fn, "__qualname__", None) or "program"


# --------------------------------------------------------------------------
# AOT executable store (import side; export/import live further down)
# --------------------------------------------------------------------------

#: stable-hash -> {"compiled": jax.stages.Compiled, "label", "file"}
#: populated by import_executables(); consulted by shared_jit on a
#: registry miss
_aot_store: dict = {}


def _stable_identity(identity) -> str:
    """Cross-process-stable form of a registry identity: fn_token
    strings pass through; function objects map to module.qualname
    (stable for the same code version — the manifest's jax/version
    fields gate everything else)."""
    if isinstance(identity, str):
        return identity
    mod = getattr(identity, "__module__", "") or ""
    qual = (getattr(identity, "__qualname__", None)
            or getattr(identity, "__name__", None) or repr(identity))
    return f"{mod}.{qual}"


def _aot_hash(identity, key) -> str:
    """Content hash of (stable identity, key) — the manifest key an
    exported executable is filed (and later matched) under.  Library
    keys are tuples of strings/ints/bools/tuples, so their repr is
    deterministic across processes."""
    return hashlib.blake2b(
        repr((_stable_identity(identity), key)).encode(),
        digest_size=16).hexdigest()


class _AotProgram:
    """A registry entry served by deserialized AOT executables.

    One registry entry serves MULTIPLE shapes (keys are
    structure-only), so the store hands over a LIST of loaded
    executables — one per exported spec.  ``__call__`` tries them
    (move-to-front, so steady-state serving is first-try): a
    shape/aval mismatch (TypeError/ValueError, raised host-side
    before execution) is a SOFT miss — that call falls through to the
    plain jit (``jit.aot_shape_misses``) and the executables stay
    live for the shapes they DO match.  Any other exception is a
    runtime failure: the entry demotes permanently
    (``jit.aot_import_rejects`` + an ``aot_demotion`` telemetry
    record — and if a donated buffer was consumed the jit fallback
    will fail loudly on its own).  Every other attribute (``lower``
    for AOT warmup, etc.) forwards to the underlying jit."""

    __slots__ = ("_compiled", "_jit", "_dead")

    def __init__(self, compiled, jit):
        # accept one executable or a list of them
        self._compiled = (list(compiled)
                          if isinstance(compiled, (list, tuple))
                          else [compiled])
        self._jit = jit
        self._dead = False

    def __call__(self, *args, **kwargs):
        if not self._dead and not kwargs:
            for i, comp in enumerate(self._compiled):
                try:
                    out = comp(*args)
                except (TypeError, ValueError):
                    # aval mismatch, raised before execution: this
                    # executable serves a different shape of the same
                    # program — keep trying / fall through
                    continue
                except Exception as e:
                    self._dead = True
                    telemetry.counter_add("jit.aot_import_rejects")
                    telemetry.emit({
                        "type": "aot_demotion",
                        "error": f"{type(e).__name__}: {e}"[:500],
                    })
                    break
                else:
                    if i:
                        self._compiled.insert(
                            0, self._compiled.pop(i))
                    telemetry.counter_add("jit.aot_served_calls")
                    return out
            else:
                telemetry.counter_add("jit.aot_shape_misses")
        return self._jit(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_jit"), name)


def aot_store_stats() -> dict:
    """{"entries", "hits", "misses", "rejects", "served_calls"} of the
    imported-executable store (datacheck/tests)."""
    return {
        "entries": len(_aot_store),
        "hits": int(telemetry.counter_get("jit.aot_import_hits")),
        "misses": int(telemetry.counter_get("jit.aot_import_misses")),
        "rejects": int(telemetry.counter_get("jit.aot_import_rejects")),
        "shape_misses": int(
            telemetry.counter_get("jit.aot_shape_misses")),
        "served_calls": int(
            telemetry.counter_get("jit.aot_served_calls")),
    }


def clear_aot_store():
    """Drop every imported executable (tests)."""
    with _lock:
        _aot_store.clear()


def shared_jit(fn, *, key, fn_token=None, donate_argnums=None,
               static_argnums=None, label=None):
    """The one jitted callable for (fn identity x key), creating it on
    first use.

    fn identity is ``fn.__func__`` for bound methods (shared across
    instances of a class) or ``fn`` itself; pass ``fn_token`` when the
    callable is constructed fresh per call (vmapped lambdas) and the
    key alone must identify the computation.  ``key`` must cover every
    closed-over static the trace bakes in — abstract avals of the call
    arguments are handled by jax.jit's own cache underneath.

    Every entry is returned wrapped in the profiling proxy
    (:func:`pint_tpu.profiling.wrap_program`): with the
    ``$PINT_TPU_PROFILE`` gate off the proxy is one branch on top of
    the raw call; with it on, each call's trace/dispatch/device phase
    split, byte sizes, and device-time histogram accumulate under
    ``label`` (default: the key's string head).

    The registry holds strong references (an entry keeps its first
    caller's closure alive); it is LRU-bounded by
    ``$PINT_TPU_JIT_REGISTRY_CAP`` (default 128)."""
    _auto_enable()
    identity = fn_token if fn_token is not None else getattr(
        fn, "__func__", fn)
    full_key = (identity, key)
    with _lock:
        got = _registry.get(full_key)
        if got is not None:
            _registry.move_to_end(full_key)
            telemetry.counter_add("compile_cache.registry_hits")
            return got
        telemetry.counter_add("compile_cache.registry_misses")
        import jax

        kwargs = {}
        if donate_argnums is not None:
            kwargs["donate_argnums"] = donate_argnums
        if static_argnums is not None:
            kwargs["static_argnums"] = static_argnums

        # Anchor jax's GLOBAL trace caches to this registry entry, not
        # to `fn`: bound methods compare/hash EQUAL across re-keys of
        # the same instance (f._step == f._step even after the free
        # set changed), and with the previous entry's jit kept alive
        # by the registry, jax's jaxpr cache would hand the new wrapper
        # the STALE trace — the silently-fit-the-old-params bug the
        # fitter's _retrace exists to prevent.  A fresh def per entry
        # has unique identity, so nothing aliases.
        def _entry(*args):
            return fn(*args)

        _entry.__name__ = getattr(fn, "__name__", "shared_jit_entry")
        _entry.__qualname__ = getattr(fn, "__qualname__",
                                      _entry.__name__)
        target = jax.jit(_entry, **kwargs)
        # imported-executable store: a fresh process that ran
        # import_executables() serves this key from a deserialized
        # Compiled — no trace, no backend compile.  Misses are only
        # counted while a store is loaded (a normal session must not
        # tick them on every registry build).
        if _aot_store:
            got_aot = _aot_store.get(_aot_hash(identity, key))
            label_str = label if label is not None \
                else _derive_label(fn, key)
            if got_aot is not None:
                telemetry.counter_add("jit.aot_import_hits")
                telemetry.emit({"type": "aot", "event": "import_hit",
                                "label": label_str})
                target = _AotProgram(got_aot["compiled"], target)
            else:
                telemetry.counter_add("jit.aot_import_misses")
                telemetry.emit({"type": "aot", "event": "import_miss",
                                "label": label_str})
        jitted = profiling.wrap_program(
            target, key=key,
            label=label if label is not None else _derive_label(fn, key))
        _registry[full_key] = jitted
        cap = _registry_cap()
        while len(_registry) > cap:
            _registry.popitem(last=False)
        return jitted


def scan_iters_default() -> bool:
    """Whether fixed-count iteration loops inside traces run as
    ``jax.lax.scan`` (the default — HLO size ~1/n_steps of the
    unrolled trace, which is what the cold-compile budget pays for)
    or as the historical python unroll
    (``$PINT_TPU_SCAN_ITERS=0/off/unroll`` — the per-program escape
    hatch when a backend fuses the unrolled body better).  The choice
    changes the traced program, so every caller folds it into its
    shared-jit key."""
    raw = os.environ.get(_SCAN_ENV)
    if raw is None or not raw.strip():
        return True
    return raw.strip().lower() not in ("0", "off", "false", "no",
                                       "unroll")


def iter_trace_default() -> bool:
    """Whether fixed-count iteration loops additionally materialize a
    per-iteration convergence record out of the scan
    (``$PINT_TPU_ITER_TRACE``, default OFF).

    PR 8 moved the Gauss-Newton iterations inside ``lax.scan``, which
    erased per-iteration visibility exactly where convergence
    pathologies live (guard-ladder escalations, Kepler depth refits,
    near-singular normal equations).  With the gate on, the scan's
    ``ys`` carry a small per-iteration record (chi^2, step norm,
    max |dparam|, an on-device ok bit) as a stacked array, decoded
    host-side lazily (:func:`decode_gn_trace`) and emitted as
    ``iter_trace`` telemetry records.  The gate CHANGES the traced
    program, so — like ``$PINT_TPU_SCAN_ITERS`` and
    ``$PINT_TPU_GUARD`` — it is part of every affected shared-jit key
    (grid per-point refit, the three batched-PTA loops, the fitter
    step keys); gate-off traces are byte-identical to the ungated
    programs and the zero-recompile contract holds per gate value
    (``tools/check_jit_gates.py`` lints the gate->key coverage)."""
    raw = os.environ.get(_ITER_TRACE_ENV, "").strip().lower()
    return raw in ("1", "true", "yes", "on")


def kron_phi_default() -> bool:
    """Whether the stacked-array GWB likelihood routes its dense
    ``kron(ORF, diag(phi_gw))`` prior through the Kronecker-structured
    solver (:class:`pint_tpu.linalg.KronPhi` — per-frequency
    (N_psr, N_psr) blocks and per-pulsar Woodbury reductions instead
    of one O(K^3) dense factorization; default ON) or through the
    historical dense (K, K) path (``$PINT_TPU_KRON_PHI=0/off`` — the
    brute-force reference the kron path is verified against).  The two
    are different traced programs of different argument layouts, so
    the resolved flag is part of every affected shared-jit key
    (``gw/common.py`` — lint-checked by ``tools/check_jit_gates.py``)."""
    raw = os.environ.get(_KRON_PHI_ENV)
    if raw is None or not raw.strip():
        return True
    return raw.strip().lower() not in ("0", "off", "false", "no",
                                       "dense")


def iterate_fixed(body, init, n_steps, scan=None, trace_of=None):
    """Run ``carry = body(carry)`` exactly ``n_steps`` times inside a
    trace — the one implementation of the fixed-count Gauss-Newton
    iteration loop shared by the grid and batched-PTA step programs.

    scan=True: ``lax.scan`` with the iterate as carry (one traced body
    + a loop, so the HLO the backend compiles shrinks by roughly the
    iteration count); scan=False: the python unroll (n_steps copies of
    the body in the HLO — XLA can fuse across iterations, at compile
    cost linear in the count).  ``scan=None`` follows
    :func:`scan_iters_default`.  Callers must resolve the flag at
    trace-BUILD time and put it in their jit key: the two variants are
    different programs.

    trace_of: optional ``fn(prev_carry, new_carry) -> record pytree``
    (the flight-recorder hook, :func:`iter_trace_default`).  When
    given, returns ``(carry, trace)`` where ``trace`` stacks one
    record per iteration along a new leading axis — the scan's ``ys``
    on the scan path, python-side accumulation + ``stack`` on the
    unroll path, so the two modes produce the identical record.
    ``n_steps <= 0`` returns ``(init, None)``."""
    traced = trace_of is not None
    if int(n_steps) <= 0:
        return (init, None) if traced else init
    if scan is None:
        scan = scan_iters_default()
    import jax

    if not scan:
        records = []
        for _ in range(int(n_steps)):
            new = body(init)
            if traced:
                records.append(trace_of(init, new))
            init = new
        if not traced:
            return init
        import jax.numpy as jnp

        return init, jax.tree.map(lambda *xs: jnp.stack(xs), *records)

    def step(carry, _):
        new = body(carry)
        return new, (trace_of(carry, new) if traced else None)

    out, ys = jax.lax.scan(step, init, None, length=int(n_steps))
    return (out, ys) if traced else out


def gn_trace_record(prev_vec, new_vec, chi2):
    """The ONE per-iteration Gauss-Newton trace record (built inside a
    trace; grid and batched-PTA loops pass this to
    :func:`iterate_fixed`'s ``trace_of``): chi^2 at this iteration's
    input point, the step 2-norm, the largest single-parameter move,
    and a cheap on-device ok bit (finite chi^2 AND finite step — the
    in-loop analogue of the Health verdict's hot-path read; the full
    guard record still rides the post-loop solve).  Decoded by
    :func:`decode_gn_trace`."""
    import jax.numpy as jnp

    d = new_vec - prev_vec
    return {
        "chi2": chi2,
        "step_norm": jnp.sqrt(jnp.sum(d * d)),
        "max_dpar": jnp.max(jnp.abs(d)),
        "ok": jnp.logical_and(jnp.isfinite(chi2),
                              jnp.all(jnp.isfinite(new_vec))),
    }


def decode_gn_trace(trace, guard_eps=0.0, rung="baseline"):
    """Decode a stacked on-device iteration trace (the pytree
    :func:`iterate_fixed` returned) into host-side per-iteration
    dicts — called LAZILY, only when a consumer actually wants the
    record (a telemetry sink is attached, or the caller reads
    ``.iter_trace``), because the ``np.asarray`` here is the device
    sync the gated design otherwise avoids.

    Leaves shaped ``(n_steps,)`` (a single fit) decode exactly;
    leaves shaped ``(batch, n_steps)`` (a vmapped grid or PTA batch)
    reduce per iteration — chi^2 median/min/max across the batch, max
    step norm, max |dparam|, all-ok plus the bad-member count — so a
    10^4-point grid's record stays a handful of numbers per
    iteration.  Returns ``[]`` for ``trace=None``."""
    if trace is None:
        return []
    t = {k: np.asarray(v) for k, v in trace.items()}
    chi2, sn, md, ok = t["chi2"], t["step_norm"], t["max_dpar"], t["ok"]
    entries = []
    common = {"guard_eps": float(guard_eps), "rung": rung}
    if chi2.ndim == 1:
        for i in range(chi2.shape[0]):
            entries.append({
                "i": i, "chi2": float(chi2[i]),
                "step_norm": float(sn[i]),
                "max_dpar": float(md[i]), "ok": bool(ok[i]),
                **common,
            })
        return entries
    # batched: reduce the leading axes down to one batch axis
    flat = {k: v.reshape(-1, v.shape[-1]) for k, v in t.items()}
    chi2, sn, md, ok = (flat["chi2"], flat["step_norm"],
                        flat["max_dpar"], flat["ok"])
    for i in range(chi2.shape[-1]):
        c = chi2[:, i]
        finite = c[np.isfinite(c)]
        entries.append({
            "i": i,
            "chi2": float(np.median(finite)) if finite.size else
            float("nan"),
            "chi2_min": float(np.min(finite)) if finite.size else
            float("nan"),
            "chi2_max": float(np.max(finite)) if finite.size else
            float("nan"),
            "step_norm": float(np.max(sn[:, i])),
            "max_dpar": float(np.max(md[:, i])),
            "ok": bool(np.all(ok[:, i])),
            "n_bad": int(np.sum(~ok[:, i])),
            **common,
        })
    return entries


def registry_stats():
    """{"entries", "hits", "misses", "cap"} for datacheck/tests."""
    with _lock:
        entries = len(_registry)
    return {
        "entries": entries,
        "hits": int(telemetry.counter_get("compile_cache.registry_hits")),
        "misses": int(
            telemetry.counter_get("compile_cache.registry_misses")),
        "cap": _registry_cap(),
    }


def clear_registry():
    """Drop every registry entry (tests / memory pressure)."""
    with _lock:
        _registry.clear()


def donation_argnums(argnums):
    """``argnums`` when the backend supports buffer donation, None
    otherwise.  Donation of the iterate-in-place step vector saves one
    buffer per iteration on TPU/GPU; CPU accepts it silently on current
    jax, but older jaxlibs warn per call — gate on the platform."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        return None
    if backend in ("tpu", "gpu", "cuda", "rocm"):
        return tuple(argnums)
    if os.environ.get("PINT_TPU_DONATE_CPU"):
        return tuple(argnums)
    return None


# --------------------------------------------------------------------------
# structural keys and content fingerprints
# --------------------------------------------------------------------------

#: model.meta keys that change the traced computation (everything else
#: in meta — CHI2/TRES/NTOA fit summaries, PSR names — is cosmetic and
#: must NOT break registry sharing between consecutive fits)
_STRUCTURAL_META = ("UNITS", "TRACK", "EPHEM", "CLK", "PLANET_SHAPIRO",
                    "DMDATA", "TZRSITE")


def model_structure_key(model) -> str:
    """A string identifying everything about a TimingModel that a fit
    trace bakes in: component classes and order, their mask selects and
    parameter names, the values-pytree key set, structural meta, and
    superset-inert gating.  Parameter VALUES are excluded — they enter
    the jitted step as dynamic arguments."""
    rows = [type(model).__name__]
    for c in model.components:
        rows.append((
            type(c).__name__,
            repr(getattr(c, "selects", None)),
            tuple(p.name for p in c.params),
            bool(getattr(c, "_use_rn", False)),
        ))
    rows.append(tuple(sorted(model.values.keys())))
    rows.append(tuple((k, model.meta.get(k)) for k in _STRUCTURAL_META))
    rows.append(tuple(sorted(getattr(model, "_superset_inert", ()) or ())))
    return repr(rows)


def fingerprint(tree) -> str:
    """Content fingerprint of a pytree of arrays/scalars/strings —
    for identities derived from data CONTENT (checkpoint validation;
    historically the grid's baked-dataset registry key, retired when
    the grid went data-dynamic).  Hashing is by array bytes, so two
    numerically identical datasets fingerprint equal."""
    h = hashlib.blake2b(digest_size=16)

    def feed(obj):
        if obj is None:
            h.update(b"\x00N")
        elif isinstance(obj, dict):
            h.update(b"\x00D%d" % len(obj))
            for k in sorted(obj, key=repr):
                h.update(repr(k).encode())
                feed(obj[k])
        elif isinstance(obj, (list, tuple)):
            # NamedTuple pytrees (TOABatch) land here too: tuple
            # subclasses, hashed by content like any other sequence
            h.update(b"\x00L%d" % len(obj))
            for v in obj:
                feed(v)
        elif isinstance(obj, (str, bytes, int, float, bool, complex)):
            h.update(repr(obj).encode())
        elif hasattr(obj, "shape"):
            a = np.asarray(obj)
            h.update(b"\x00A" + str(a.dtype).encode()
                     + repr(a.shape).encode())
            h.update(a.tobytes())
        else:
            h.update(repr(obj).encode())

    feed(tree)
    return h.hexdigest()


# --------------------------------------------------------------------------
# ctx split/merge across the jit boundary
# --------------------------------------------------------------------------

def _is_dynamic_leaf(v):
    """Array leaves cross the jit boundary as arguments; python
    scalars/strings/tuples are static jit structure (the partition
    rule of parallel.pta._stack_ctxs).  One deliberate extension:
    numpy 0-d scalars (np.float64 'df' in the Fourier-noise ctx) are
    DYNAMIC here — they are data-derived and differ in the last ulp
    between same-shaped datasets, which must not break trace sharing.
    (pta's stacker instead drops them per-pulsar with a warning; its
    batched trace never reads them.)"""
    if isinstance(v, np.generic):
        return True
    return hasattr(v, "shape") and not isinstance(
        v, (tuple, int, float, bool))


def split_ctx(ctx_map):
    """Split a prepare()-time ``{component: {key: leaf}}`` ctx into
    (dynamic arrays part, static part).  The dynamic part is a pytree
    of arrays to pass as a jit argument; the static part stays closed
    over and must be folded into the registry key (its repr is
    deterministic)."""
    if ctx_map is None:
        return None, {}
    arrays, static = {}, {}
    for comp, ctx in ctx_map.items():
        a, s = {}, {}
        for k, v in ctx.items():
            if _is_dynamic_leaf(v):
                a[k] = v
            else:
                s[k] = v
        arrays[comp] = a
        static[comp] = s
    return arrays, static


def merge_ctx(arrays, static):
    """Reassemble a component ctx from its dynamic and static parts
    (inside OR outside a trace)."""
    return {
        comp: {**static.get(comp, {}), **arrays[comp]}
        for comp in arrays
    }


def static_ctx_key(static) -> str:
    """Deterministic repr of a split_ctx static part for registry
    keys."""
    return repr(sorted(
        (comp, sorted((k, repr(v)) for k, v in d.items()))
        for comp, d in (static or {}).items()
    ))


# --------------------------------------------------------------------------
# layer 3: TOA-count shape bucketing
# --------------------------------------------------------------------------

#: sentinel uncertainty for padded TOAs [us]: sigma = 1e16 s, weight
#: 1/sigma^2 = 1e-32 s^-2 — vanishes against any real TOA weight
#: (~1e12) to far beyond f64 resolution, and sigma^2 = 1e32 stays
#: representable inside the TPU's float32-pair f64 emulation (high
#: word saturates at ~3.4e38; see residuals.MEAN_OFFSET_WEIGHT)
PAD_ERROR_US = 1e22

#: default geometric bucketing: 64, 80, 100, 125, 157, ... (1.25x)
BUCKET_BASE = 64
BUCKET_GROWTH = 1.25


def bucket_size(n, base=BUCKET_BASE, growth=BUCKET_GROWTH):
    """Smallest bucket >= n in geometric steps: datasets whose sizes
    land in the same bucket compile to the SAME executable (<= 25%
    padded compute buys an entire 30-second compile)."""
    n = int(n)
    if n <= base:
        return base
    b = float(base)
    while int(round(b)) < n:
        b *= growth
    return int(round(b))


def bucketing_default():
    """Whether fitters bucket by default (``$PINT_TPU_BUCKET_TOAS``)."""
    raw = os.environ.get(_BUCKET_ENV, "")
    return raw.strip().lower() in ("1", "true", "yes", "on")


def pad_toas(toas, n_target=None):
    """Pad a TOAs object to its bucket size with zero-weight sentinel
    rows; returns the padded object (``.n_real`` records the original
    count) or the input unchanged when already at a bucket boundary.

    The sentinels are copies of the LAST real TOA (so they join its
    noise-mask groups — never adding basis columns; ECORR epoch
    formation skips ``pad``-flagged rows entirely, keeping the epoch
    layout independent of pad placement across streaming appends)
    with uncertainty ``PAD_ERROR_US`` (and ``-pp_dme`` set to
    the same sentinel when the dataset carries wideband DM data), so
    every weighted reduction downstream — chi^2, weighted mean,
    normal equations, Woodbury — drops them to below f64 resolution.
    dof/NTOA accounting uses ``n_real``, never the padded length.
    """
    from pint_tpu.toa import TOAs

    n = len(toas)
    if getattr(toas, "n_real", None) is not None:
        # already padded; an explicit conflicting target must not be
        # silently ignored
        if n_target is not None and int(n_target) != n:
            raise ValueError(
                f"TOAs already padded to {n} (n_real={toas.n_real}); "
                f"cannot re-pad to {n_target}")
        return toas
    target = bucket_size(n) if n_target is None else int(n_target)
    if target < n:
        raise ValueError(f"pad target {target} < {n} TOAs")
    if target == n:
        # at a bucket boundary: return a COPY carrying n_real — never
        # stamp bucketing state onto the caller's object (it would
        # change the structure key of every Residuals later built from
        # it, silently splitting the registry into mask/no-mask
        # variants of the same problem)
        out = toas[np.arange(n)]
        out.n_real = n
        return out
    pad = toas[np.full(target - n, n - 1, dtype=np.int64)]
    pad.error_us = np.full(target - n, PAD_ERROR_US)
    for f in pad.flags:
        f["pad"] = "1"
        if "pp_dm" in f:
            f["pp_dme"] = repr(PAD_ERROR_US)
    padded = TOAs.merge([toas, pad])
    padded.n_real = n
    telemetry.counter_add("compile_cache.toas_padded")
    telemetry.counter_add("compile_cache.pad_rows", float(target - n))
    return padded


def append_toas(toas, delta):
    """Append new TOAs to a (padded) TOAs object, reusing the bucket's
    pad-sentinel rows when they fit: returns ``(merged, in_bucket)``.

    The bucket-interior case (``n_real + len(delta) <= bucket``) is the
    streaming fast path: the merged object is re-padded to the SAME
    bucket, so the append amounts to flipping ``len(delta)`` sentinel
    rows at ``[n_real, n_real + len(delta))`` to real data — identical
    shapes, identical structure key, every shared trace keyed on this
    bucket serves the appended dataset with zero new executables.  The
    layout is bit-identical to a from-scratch ``pad_toas`` over the
    concatenated data (the remaining sentinels become clones of the
    NEW last row — the pad_toas convention), so append-vs-reload
    consistency holds by construction at this layer.

    ``in_bucket=False`` signals the caller to take the full re-prepare
    fallback: the delta overflows the bucket (the merged object comes
    back padded to the NEXT bucket), or the base carries a non-suffix
    ``pad_valid`` row plan (shard-aligned layouts interleave sentinels
    — a suffix flip cannot express the append).  The one interleaved
    layout the fast path DOES keep is the streaming quarantine hole:
    a base stamped with ``n_filled`` (rows ``[0, n_filled)`` occupied
    — valid data or quarantined sentinels — pads strictly beyond) may
    carry interior False ``pad_valid`` entries; the merged object
    re-carries them with the appended rows marked valid.  Host-side
    array surgery only — the expensive per-TOA ingestion (clock
    chains, ephemeris posvels) happened when ``delta`` was built, and
    the base rows' prepared arrays are concatenated as-is, never
    recomputed."""
    from pint_tpu.toa import TOAs

    if len(delta) == 0:
        raise ValueError("append_toas: empty delta")
    if getattr(delta, "n_real", None) is not None:
        raise ValueError("append_toas: delta must be unpadded TOAs")
    n_real = getattr(toas, "n_real", None)
    old_valid = getattr(toas, "pad_valid", None)
    n_filled = getattr(toas, "n_filled", None)
    if old_valid is None:
        suffix_ok = True
        if n_filled is None:
            n_filled = n_real
        hole_valid = None
    else:
        # explicit mask: only the streaming quarantine layout (all
        # pads a suffix past n_filled) keeps the fast path
        ov = np.asarray(old_valid, dtype=bool)
        suffix_ok = n_filled is not None and not ov[n_filled:].any()
        hole_valid = ov[:n_filled] if suffix_ok else None
    if n_filled is None:
        n_filled = len(toas)
        real = toas
        bucket = None
    else:
        real = toas[np.arange(n_filled)]
        bucket = len(toas) if n_real is not None else None
    merged = TOAs.merge([real, delta])
    total = n_filled + len(delta)
    in_bucket = (suffix_ok and bucket is not None and total <= bucket)
    out = pad_toas(merged, n_target=bucket if in_bucket else None)
    out.n_filled = total
    if hole_valid is not None:
        out.pad_valid = np.concatenate(
            [hole_valid, np.ones(len(delta), dtype=bool),
             np.zeros(len(out) - total, dtype=bool)])
    telemetry.counter_add("compile_cache.toas_appended")
    telemetry.counter_add("compile_cache.append_rows", float(len(delta)))
    return out, in_bucket


def apply_toa_row_plan(toas, plan):
    """Re-lay a TOAs object per an epoch-alignment row plan
    (:func:`pint_tpu.parallel.mesh.toa_shard_plan`): entries >= 0 are
    source rows, ``-1`` inserts a zero-weight sentinel row — a clone
    of the nearest PRECEDING source row, so it joins that row's
    noise-mask groups (the :func:`pad_toas` convention; ECORR epoch
    formation skips pad rows, so the inserted rows only push the NEXT
    epoch block past the shard boundary — a shrunken span never
    straddles a boundary the full span did not).

    Because pad rows are no longer a suffix, the returned object
    carries an explicit boolean ``pad_valid`` mask (honored by
    :class:`pint_tpu.residuals.Residuals` in place of the
    ``arange < n_real`` convention) alongside ``n_real``.  Accepts
    suffix-padded input (bucketed/device-padded TOAs): existing pad
    rows keep their invalid status through the plan."""
    plan = np.asarray(plan, dtype=np.int64)
    n = len(toas)
    if plan[plan >= 0].size and (plan.max() >= n or
                                 np.any(np.bincount(
                                     plan[plan >= 0],
                                     minlength=n) != 1)):
        raise ValueError(
            "apply_toa_row_plan: plan must use every source row "
            f"exactly once (n={n})")
    old_valid = getattr(toas, "pad_valid", None)
    if old_valid is None:
        n_real = getattr(toas, "n_real", None)
        old_valid = (np.arange(n) < n_real if n_real is not None
                     else np.ones(n, dtype=bool))
    old_valid = np.asarray(old_valid, dtype=bool)
    src = np.empty(len(plan), dtype=np.int64)
    last = 0
    for i, p in enumerate(plan):
        if p >= 0:
            last = int(p)
        src[i] = last
    out = toas[src]
    inserted = plan < 0
    err = np.asarray(out.error_us, dtype=float).copy()
    err[inserted] = PAD_ERROR_US
    out.error_us = err
    for i in np.flatnonzero(inserted):
        out.flags[i]["pad"] = "1"
        if "pp_dm" in out.flags[i]:
            out.flags[i]["pp_dme"] = repr(PAD_ERROR_US)
    valid = old_valid[src] & ~inserted
    out.pad_valid = valid
    out.n_real = int(np.count_nonzero(valid))
    n_pad = int(np.count_nonzero(inserted))
    if n_pad:
        telemetry.counter_add("compile_cache.toas_padded")
        telemetry.counter_add("compile_cache.pad_rows", float(n_pad))
    return out


# --------------------------------------------------------------------------
# layer 4: AOT warmup
# --------------------------------------------------------------------------

#: standard GLS shape: DD binary + two-receiver EFAC/EQUAD/ECORR masks
#: + power-law red noise — the B1855-class config every bench round
#: measures (bench.py B1855_LIKE_PAR stays the measurement twin)
WARM_GLS_PAR = """PSR  WARMUP-GLS
RAJ 18:57:36.39
DECJ 09:43:17.2
F0 186.49408156698235146 1
F1 -6.2049e-16 1
PEPOCH 54000
DM 13.29984 1
BINARY DD
PB 12.32717119132762 1
A1 9.230780480 1
ECC 0.00002170 1
T0 54000.7262 1
OM 276.55 1
M2 0.26 1
SINI 0.999 1
TZRMJD 54000
TZRSITE @
TZRFRQ 1400
EFAC -f L-wide 1.1
EQUAD -f L-wide 0.3
ECORR -f L-wide 0.5
TNRedAmp -13.5
TNRedGam 3.3
TNRedC 30
UNITS TDB
EPHEM builtin
"""

#: minimal isolated-pulsar WLS shape (fast CPU warmup / smoke tests)
WARM_WLS_PAR = """PSR  WARMUP-WLS
RAJ 18:57:36.39
DECJ 09:43:17.2
F0 186.49408156698235146 1
F1 -6.2049e-16 1
PEPOCH 54000
DM 13.29984 1
TZRMJD 54000
TZRSITE @
TZRFRQ 1400
UNITS TDB
EPHEM builtin
"""


def fitter_class(kind):
    """The fitter class for a warm/verify ``kind`` token — the ONE
    kind->class map shared by :func:`warmup`,
    :func:`aot_cold_start_probe`, and the ``pintwarm`` CLI."""
    from pint_tpu.downhill import DownhillGLSFitter, DownhillWLSFitter
    from pint_tpu.fitter import GLSFitter, WLSFitter

    classes = {
        "wls": WLSFitter,
        "gls": GLSFitter,
        "downhill_wls": DownhillWLSFitter,
        "downhill_gls": DownhillGLSFitter,
    }
    try:
        return classes[kind]
    except KeyError:
        raise ValueError(f"unknown fitter kind {kind!r}; expected one "
                         f"of {sorted(classes)}") from None


def _warm_pairs(n_toas, kind, seed=0):
    from pint_tpu.models.builder import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = WARM_GLS_PAR if kind in ("gls", "downhill_gls") else WARM_WLS_PAR
    model = get_model(par)
    toas = make_fake_toas_uniform(
        53000.0, 56500.0, int(n_toas), model, freq_mhz=1400.0, obs="gbt",
        error_us=1.0, add_noise=True,
        rng=np.random.default_rng(seed),
        flags={"f": "L-wide"})
    return model, toas


def warmup(toa_counts=(500, 1000), kinds=("wls", "gls"), bucket=None,
           progress=None, pairs=None, jobs=None):
    """AOT-compile (``jit.lower().compile()``) the standard fit shapes,
    populating the persistent cache for future processes.  Returns a
    list of {"kind", "n_toas", "bucket", "compile_s"} records.

    bucket=None follows :func:`bucketing_default` — the warmed shapes
    must be the shapes default-configured fits will actually request
    (a 596-row bucketed executable serves nothing when production fits
    trace at exactly 500 TOAs, and vice versa).  Pass True/False to
    warm for an explicitly bucketed/exact deployment.

    pairs: optional explicit [(model, toas), ...] to warm a real
    dataset's shapes instead of the synthetic standards (the
    ``pintwarm --par/--tim`` path).  jobs: prebuilt [(kind, model,
    toas), ...] — overrides toa_counts/kinds/pairs so a caller that
    already built the datasets (pintwarm --export's dress-rehearsal
    pass) never simulates them twice."""
    if bucket is None:
        bucket = bucketing_default()
    out = []
    if jobs is None:
        jobs = []
        if pairs is not None:
            for kind in kinds:
                for model, toas in pairs:
                    jobs.append((kind, model, toas))
        else:
            for kind in kinds:
                for n in toa_counts:
                    model, toas = _warm_pairs(n, kind)
                    jobs.append((kind, model, toas))
    for kind, model, toas in jobs:
        cls = fitter_class(kind)
        n_in = len(toas)
        if bucket:
            toas = pad_toas(toas)
        f = cls(toas, model)
        dt = f.warm_compile()
        rec = {"kind": kind, "n_toas": n_in, "bucket": len(toas),
               "compile_s": round(dt, 3)}
        out.append(rec)
        if progress is not None:
            progress(f"warmed {kind} n_toas={n_in} "
                     f"(bucket {len(toas)}): compile {dt:.1f}s")
    telemetry.counter_add("compile_cache.warmups", len(out))
    return out


def warm_timed(fn):
    """Time one AOT compile call (helper for warm_compile methods)."""
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


# --------------------------------------------------------------------------
# layer 5: AOT executable serialization (zero-retrace cold start)
# --------------------------------------------------------------------------

def _aot_env() -> dict:
    """The version/topology fields an exported executable is valid
    under — per-entry in the manifest, so a partially-stale directory
    rejects entry-by-entry instead of all-or-nothing.

    ``n_processes``/``devices_per_process`` make serialized
    executables per-TOPOLOGY artifacts: a mesh program compiled on an
    8-process pod slice lowers different collectives than the same
    axis layout on one host, so an executable from one must never be
    served to the other (mesh.distributed_init + mesh_jit_key carry
    the same topology into the registry keys)."""
    import jax
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "n_processes": int(jax.process_count()),
        "devices_per_process": len(jax.local_devices()),
    }


def _aot_codec() -> str:
    """Which serialization codec this backend gets.

    - ``pjrt`` (``jax.experimental.serialize_executable`` — the whole
      compiled executable, zero trace AND zero backend compile on
      import) on TPU/GPU, the backends whose PJRT clients implement
      executable deserialization.
    - ``stablehlo`` (``jax.export`` — the lowered module) on CPU:
      XLA:CPU cannot reload its in-process-JITed executables (measured
      on jaxlib 0.4.36: fresh payloads segfault the importing process,
      cache-served ones fail symbol resolution), so the import side
      re-compiles the module instead — skipping the expensive pint_tpu
      python trace/lowering, with the backend compile served from the
      persistent XLA cache that export pre-seeds
      (:func:`export_executables`).

    ``$PINT_TPU_AOT_CODEC`` overrides (testing / a future backend)."""
    raw = os.environ.get("PINT_TPU_AOT_CODEC", "").strip().lower()
    if raw in ("pjrt", "stablehlo"):
        return raw
    import jax

    return ("pjrt" if jax.default_backend() in
            ("tpu", "gpu", "cuda", "rocm") else "stablehlo")


def _unwrap_jit(proxy):
    """The raw ``jax.jit`` object under a registry entry (through the
    profiling proxy and, on an imported entry, the _AotProgram)."""
    target = getattr(proxy, "_jitted", proxy)
    if isinstance(target, _AotProgram):
        target = target._jit
    return target


_aot_pytrees_registered = False


def _register_aot_pytrees():
    """Teach ``jax.export`` to serialize the library's NamedTuple
    pytrees (they ride the arg/result trees of every fit program).
    Idempotent; a jax without the registration API degrades to
    per-entry skip at export (the ValueError lands in the entry's
    ``skipped`` record)."""
    global _aot_pytrees_registered
    if _aot_pytrees_registered:
        return
    _aot_pytrees_registered = True
    try:
        import jax.export as _jexp

        reg = _jexp.register_namedtuple_serialization
    except Exception:
        return
    from pint_tpu.dd import DD
    from pint_tpu.guard import Health, SolveDiag
    from pint_tpu.linalg import StructuredU, WoodburyPre
    from pint_tpu.toa import TOABatch

    for cls in (TOABatch, StructuredU, WoodburyPre, SolveDiag, Health,
                DD):
        try:
            reg(cls,
                serialized_name=f"pint_tpu.{cls.__name__}")
        except Exception:
            pass  # already registered (or an exotic jax): keep going


def _prime_custom_calls():
    """Force-register jaxlib's lazily-registered LAPACK FFI custom-call
    targets by LOWERING (never compiling/running) one tiny instance of
    each decomposition the fit programs use.  Without this, a
    deserialized module whose custom calls were never lowered in this
    process resolves them to garbage — measured as a hard SEGFAULT on
    jaxlib 0.4.36 CPU — so the import path runs it once before the
    first deserialized module is loaded."""
    import jax
    import jax.numpy as jnp
    import jax.scipy.linalg as jsl

    spec = jax.ShapeDtypeStruct((2, 2), jnp.float64)
    for fn in (jnp.linalg.cholesky, jnp.linalg.eigh,
               lambda m: jnp.linalg.svd(m, full_matrices=False),
               jsl.lu, lambda m: jsl.solve_triangular(m, m)):
        try:
            jax.jit(fn).lower(spec)
        except Exception:
            pass  # a missing decomposition just stays unprimed


def _spec_desc(spec):
    """Human-readable (and manifest-stable) summary of an argument
    spec pytree: leaf shapes/dtypes, flattened."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(spec):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append([list(leaf.shape), str(leaf.dtype)])
        else:
            out.append(repr(leaf))
    return out


def export_executables(path, progress=None):
    """Serialize every shared-jit registry program whose argument spec
    has been observed this session (called or ``lower``-ed — the
    profiling proxy records it) into ``path``: one pickled executable
    payload per entry plus a ``manifest.json`` keyed by the stable
    (identity, jit key) hash, stamped with the jax/jaxlib version,
    backend, and device count it is valid under.

    Returns ``{"exported": [records], "skipped": [(label, why)]}``.
    Codec per backend (:func:`_aot_codec`): ``pjrt`` serializes the
    compiled executable itself; ``stablehlo`` serializes the lowered
    module via ``jax.export`` AND pre-seeds the persistent XLA cache
    (when one is active) with exactly the module the import side will
    compile, so its backend compile is a disk read.  Programs the
    backend cannot serialize are skipped per-entry, never fatally.
    Repeated exports into one directory merge by hash as long as the
    environment matches; an environment change rewrites the manifest
    from scratch (stale entries would only ever be rejected at
    import)."""
    import pickle

    import jax

    path = os.path.abspath(os.path.expanduser(os.fspath(path)))
    os.makedirs(path, exist_ok=True)
    env = _aot_env()
    codec = _aot_codec()
    _register_aot_pytrees()
    with _lock:
        entries = list(_registry.items())
    exported, skipped = [], []
    for (identity, key), proxy in entries:
        label = getattr(getattr(proxy, "stats", None), "label", None) \
            or _stable_identity(identity)
        specs = getattr(proxy, "aot_specs", None)
        if not specs:
            skipped.append((label, "no recorded argument spec "
                                   "(never called or lowered)"))
            continue
        ah = _aot_hash(identity, key)
        # one payload per recorded shape: a structure-only registry
        # entry legitimately serves several aval sets (a warm sweep
        # over TOA counts), and each needs its own executable
        for k, spec in enumerate(specs):
            fname = f"aot-{ah}-{k}.bin"
            try:
                if codec == "pjrt":
                    from jax.experimental import (
                        serialize_executable as _se,
                    )

                    compiled = proxy.lower(*spec).compile()
                    payload, in_tree, out_tree = _se.serialize(
                        compiled)
                    blob = pickle.dumps({"payload": payload,
                                         "in_tree": in_tree,
                                         "out_tree": out_tree})
                else:
                    import jax.export as _jexp

                    ex = _jexp.export(_unwrap_jit(proxy))(*spec)
                    blob = bytes(ex.serialize())
                    if cache_dir():
                        # seed the persistent cache with the exact
                        # module the import side will jit — its
                        # backend compile becomes a cache hit, so the
                        # cold replica's uncached-compile count stays
                        # zero
                        jax.jit(_jexp.deserialize(blob).call).lower(
                            *spec).compile()
            except Exception as e:
                skipped.append((label, f"{type(e).__name__}: {e}"))
                continue
            with open(os.path.join(path, fname), "wb") as fh:
                fh.write(blob)
            rec = {"hash": ah,
                   "identity": _stable_identity(identity),
                   "label": label, "file": fname, "bytes": len(blob),
                   "codec": codec, "avals": _spec_desc(spec), **env}
            exported.append(rec)
            if progress is not None:
                progress(f"exported {label} ({codec}, "
                         f"{len(blob)} bytes)")
    _write_manifest(path, env, exported)
    telemetry.counter_add("compile_cache.aot_exports", len(exported))
    return {"exported": exported, "skipped": skipped}


def _write_manifest(path, env, new_entries):
    """Merge ``new_entries`` into the directory manifest (by hash;
    same-environment only) and atomic-write it."""
    import json

    manifest_path = os.path.join(path, _AOT_MANIFEST)
    merged = {}

    def mkey(e):
        # one entry per (program, shape): the hash alone collides
        # across the several aval sets one registry entry serves
        return (e["hash"], repr(e.get("avals")))

    try:
        with open(manifest_path) as fh:
            old = json.load(fh)
        if old.get("format") == _AOT_FORMAT:
            for e in old.get("entries", []):
                # keep only entries this environment could still
                # serve; a version bump invalidates the whole batch
                if all(e.get(k) == env[k] for k in env):
                    merged[mkey(e)] = e
    except (OSError, ValueError):
        pass
    for e in new_entries:
        merged[mkey(e)] = e
    doc = {"format": _AOT_FORMAT, **env,
           "entries": sorted(merged.values(),
                             key=lambda e: (e["hash"], e["file"]))}
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, manifest_path)


def import_executables(path, progress=None):
    """Load AOT-serialized executables from ``path`` into the store
    :func:`shared_jit` consults: a later registry build whose (stable
    identity, key) hash matches serves the deserialized executable —
    no trace, no XLA backend compile.

    Per-entry graceful rejection (``jit.aot_import_rejects``): a
    jax/jaxlib version, backend, or device-count mismatch, an unknown
    or backend-unsupported codec, an unreadable payload, or a failed
    deserialization skips THAT entry and the key retraces as usual.
    A missing/empty directory returns ``{"loaded": 0, ...}`` without
    error.  Returns ``{"loaded", "rejected": [(label, why)],
    "path"}``."""
    import json
    import pickle

    path = os.path.abspath(os.path.expanduser(os.fspath(path)))
    manifest_path = os.path.join(path, _AOT_MANIFEST)
    rejected = []
    try:
        with open(manifest_path) as fh:
            doc = json.load(fh)
    except OSError:
        return {"loaded": 0, "rejected": [], "path": path,
                "detail": "no manifest"}
    except ValueError as e:
        telemetry.counter_add("jit.aot_import_rejects")
        return {"loaded": 0, "rejected": [("manifest", str(e))],
                "path": path}
    if doc.get("format") != _AOT_FORMAT:
        telemetry.counter_add("jit.aot_import_rejects")
        return {"loaded": 0,
                "rejected": [("manifest",
                              f"format {doc.get('format')!r} != "
                              f"{_AOT_FORMAT}")],
                "path": path}
    import jax

    env = _aot_env()
    _register_aot_pytrees()
    pjrt_ok = jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")
    primed = False
    loaded = 0
    for e in doc.get("entries", []):
        label = e.get("label", e.get("hash", "?"))
        mismatch = [k for k in env if e.get(k) != env[k]]
        if mismatch:
            telemetry.counter_add("jit.aot_import_rejects")
            rejected.append(
                (label, "environment mismatch: " + ", ".join(
                    f"{k}={e.get(k)!r}!={env[k]!r}" for k in mismatch)))
            continue
        codec = e.get("codec", "pjrt")
        if codec == "pjrt" and not pjrt_ok:
            # XLA:CPU cannot reload serialized executables (jaxlib
            # 0.4.36: deserialization of a fresh payload SEGFAULTS the
            # process — not even catchable), so a cpu-backend pjrt
            # entry is rejected before any payload bytes are touched
            telemetry.counter_add("jit.aot_import_rejects")
            rejected.append(
                (label, f"pjrt codec unsupported on "
                        f"{jax.default_backend()} backend"))
            continue
        if codec not in ("pjrt", "stablehlo"):
            telemetry.counter_add("jit.aot_import_rejects")
            rejected.append((label, f"unknown codec {codec!r}"))
            continue
        with _lock:
            rec = _aot_store.get(e["hash"])
            if rec is not None and e["file"] in rec["files"]:
                continue  # already loaded (repeated import call)
        try:
            with open(os.path.join(path, e["file"]), "rb") as fh:
                raw = fh.read()
            if codec == "pjrt":
                from jax.experimental import (
                    serialize_executable as _se,
                )

                blob = pickle.loads(raw)
                compiled = _se.deserialize_and_load(
                    blob["payload"], blob["in_tree"],
                    blob["out_tree"])
            else:
                import jax.export as _jexp

                if not primed:
                    # lazily-registered LAPACK custom-call targets
                    # must exist BEFORE a deserialized module runs
                    # (see _prime_custom_calls: unprimed == segfault)
                    _prime_custom_calls()
                    primed = True
                exported = _jexp.deserialize(raw)
                compiled = jax.jit(exported.call)
                # compile NOW, at import time, from the exported
                # avals: a lazy jit would take its backend compile on
                # the first dispatch — which on a replica is after
                # the recompile sanitizer armed, turning every AOT
                # "hit" into a counted violation (and a cold-start
                # latency cliff on the first real request)
                try:
                    specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                             for a in exported.in_avals]
                    cargs, ckw = jax.tree_util.tree_unflatten(
                        exported.in_tree, specs)
                    compiled = compiled.lower(*cargs,
                                              **ckw).compile()
                except Exception:
                    # keep the lazy jit: first dispatch compiles as
                    # before — slower and sanitizer-visible, never
                    # wrong
                    telemetry.counter_add(
                        "jit.aot_import_lazy_fallbacks")
        except Exception as exc:
            telemetry.counter_add("jit.aot_import_rejects")
            rejected.append((label, f"{type(exc).__name__}: {exc}"))
            continue
        with _lock:
            # one store record per program hash, holding EVERY
            # exported shape's executable (the _AotProgram tries them)
            rec = _aot_store.setdefault(
                e["hash"], {"compiled": [], "files": [],
                            "label": label, "codec": codec})
            rec["compiled"].append(compiled)
            rec["files"].append(e["file"])
        loaded += 1
        if progress is not None:
            progress(f"imported {label} ({codec})")
    telemetry.gauge_set("compile_cache.aot_store", len(_aot_store))
    return {"loaded": loaded, "rejected": rejected, "path": path}


def aot_cold_start_probe(mode, path, kind="wls", n_toas=500,
                         maxiter=3, t_start=None):
    """The export/import half of a fresh-process cold-start
    measurement — the ONE implementation behind ``bench.py``'s
    ``cold_start_s`` children and ``datacheck --aot``'s.

    mode="export": build the standard warm (model, toas) pair, run the
    first fit cold, then serialize this process's executables (and, via
    ``$PINT_TPU_CACHE_DIR``, leave the eager-op stragglers in the
    persistent XLA cache).  mode="import": pre-load the executables,
    then build the same pair and run the first fit — the zero-compile
    path under test.  Returns a record with wall seconds, the chi^2
    (json round-trips f64 exactly, so equality checks are
    bit-identity), and the compile/AOT telemetry the caller asserts
    on.

    t_start: a ``time.time()`` taken as early as the child could
    manage (before the jax/pint_tpu imports) so ``wall_s`` covers the
    interpreter+import cost too; None falls back to probe-call-to-fit
    (callers that only compare the two modes).  The headline bench
    metric uses the PARENT-measured subprocess wall regardless — the
    only clock that honestly includes process startup."""
    t0 = time.perf_counter()
    telemetry.compile_stats()  # listener before any compile
    # the persistent cache must be live BEFORE the first eager-op
    # compile (module-level jits fire at import of the fitter stack),
    # or the early stragglers land outside it and the probe's
    # uncached count lies; env-gated like the fit path ($PINT_TPU_CACHE_DIR)
    _auto_enable()
    imported = {"loaded": 0, "rejected": []}
    if mode == "import":
        imported = import_executables(path)
    model, toas = _warm_pairs(n_toas, kind)
    f = fitter_class(kind)(toas, model)
    chi2 = f.fit_toas(maxiter=maxiter)
    wall = (time.time() - t_start if t_start is not None
            else time.perf_counter() - t0)
    rec = {"mode": mode, "kind": kind, "n_toas": int(n_toas),
           "wall_s": round(wall, 3), "chi2": float(chi2),
           "loaded": imported.get("loaded", 0),
           "rejected": len(imported.get("rejected", []))}
    if mode == "export":
        # the fit above compiled (and spec-recorded) everything the
        # import side will need; serialize it
        out = export_executables(path)
        rec["exported"] = len(out["exported"])
        rec["skipped"] = len(out["skipped"])
    cs = telemetry.compile_stats()
    rec.update({
        "backend_compiles": cs["backend_events"],
        "uncached_backend_compiles": cs["uncached_backend_events"],
        "cache_hits": cs["cache_hits"],
        "aot_hits": cs["aot_hits"],
        "aot_rejects": cs["aot_rejects"],
        "monitoring": cs["source"] == "jax.monitoring",
    })
    return rec
