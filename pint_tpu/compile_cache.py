"""Compile-amortization subsystem: persistent XLA cache, shared jit
registry, TOA shape bucketing, AOT warmup.

Every recorded bench round shows XLA compile time dwarfing compute on
the fit hot path (PERF.md: ~30 s compiles feeding fits that then run in
milliseconds) — and the seed design paid it again on every process
start, every new ``Fitter`` instance, and every TOA-count change.  This
module is the single place that cost is amortized, in four layers:

1. **Persistent on-disk XLA compilation cache** —
   :func:`enable_persistent_cache` turns on
   ``jax_compilation_cache_dir`` (version-tolerant: falls back to the
   ``jax.experimental.compilation_cache`` API, degrades to a no-op when
   neither exists) so compiled executables survive process restarts.
   Gated by ``PINT_TPU_CACHE_DIR``: the fit path auto-enables only when
   the variable is set; an explicit call (``pintwarm``, ``datacheck
   --warm``) defaults to ``~/.cache/pint_tpu/xla``.  ``0``/``off``/
   ``none`` disable.
2. **Process-level shared jit registry** — :func:`shared_jit` keys a
   jitted callable on (function identity x static-structure key), so
   two fitters on same-shaped problems share ONE trace and ONE
   executable instead of each paying ``jax.jit(self._step)`` from
   scratch.  Correctness rests on the callers' keys covering everything
   their trace bakes in: the fit-path step functions take the per-TOA
   data as *arguments* (pytrees of arrays, like the batched PTA path
   always has), so only model *structure* is baked and the key is
   structural (:func:`model_structure_key`).  Hits/misses feed the
   telemetry counters ``compile_cache.registry_{hits,misses}``.
3. **TOA-count shape bucketing** — :func:`pad_toas` pads a dataset to
   the next geometric bucket (:func:`bucket_size`, 1.25x steps) with
   sentinel TOAs of enormous uncertainty (``PAD_ERROR_US``), whose
   weight ``1/sigma^2 ~ 1e-32`` drops out of every weighted reduction
   to beyond f64 resolution — the exact zero-weight-padding discipline
   of :mod:`pint_tpu.parallel.pta`.  Nearby dataset sizes then share
   one executable instead of forcing a fresh compile per TOA count.
4. **AOT warmup** — :func:`warmup` ``lower().compile()``s the standard
   fit shapes offline (the ``pintwarm`` CLI / ``datacheck --warm``) to
   pre-populate the persistent cache, so the first real fit of a fresh
   process pays a disk read instead of a 30-second compile.

The split/merge helpers (:func:`split_ctx` / :func:`merge_ctx`) carry
the prepare-time component ctx across the jit boundary: array leaves
travel as dynamic arguments, static python leaves stay closed over and
are folded into the structural key.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from pint_tpu import profiling, telemetry

__all__ = [
    "enable_persistent_cache", "cache_dir", "cache_entries",
    "shared_jit", "registry_stats", "clear_registry",
    "bucket_size", "pad_toas", "PAD_ERROR_US",
    "split_ctx", "merge_ctx", "fingerprint",
    "model_structure_key", "donation_argnums", "warmup",
]

_CACHE_ENV = "PINT_TPU_CACHE_DIR"
_BUCKET_ENV = "PINT_TPU_BUCKET_TOAS"
_DEFAULT_CACHE_DIR = os.path.join("~", ".cache", "pint_tpu", "xla")

_lock = threading.RLock()


# --------------------------------------------------------------------------
# layer 1: persistent on-disk XLA compilation cache
# --------------------------------------------------------------------------

#: None = not yet decided; "" = disabled; otherwise the active dir
_cache_dir_state = None


def _disabled_token(raw) -> bool:
    return str(raw).strip().lower() in ("", "0", "off", "none", "disabled")


def enable_persistent_cache(path=None):
    """Enable the on-disk XLA compilation cache; returns the directory
    (or None when disabled/unavailable).  Idempotent.

    path=None resolves ``$PINT_TPU_CACHE_DIR``, falling back to
    ``~/.cache/pint_tpu/xla``.  Set the env var to ``0``/``off`` to
    disable explicitly.  Every jax config knob is applied inside its
    own try/except so a jax version that lacks one still gets the rest
    (version-tolerant fallback, never an import-time crash)."""
    global _cache_dir_state
    with _lock:
        if _cache_dir_state is not None and path is None:
            return _cache_dir_state or None
        raw = path if path is not None else os.environ.get(
            _CACHE_ENV, _DEFAULT_CACHE_DIR)
        if _disabled_token(raw):
            _cache_dir_state = ""
            return None
        resolved = os.path.abspath(os.path.expanduser(os.fspath(raw)))
        try:
            os.makedirs(resolved, exist_ok=True)
        except OSError as e:
            import sys

            print(f"pint_tpu.compile_cache: cannot create cache dir "
                  f"{resolved!r}: {e}; persistent cache disabled",
                  file=sys.stderr)
            _cache_dir_state = ""
            return None
        import jax

        ok = False
        try:
            jax.config.update("jax_compilation_cache_dir", resolved)
            ok = True
        except Exception:
            try:  # pre-config-flag API
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc,
                )

                _cc.set_cache_dir(resolved)
                ok = True
            except Exception:
                pass
        if not ok:
            _cache_dir_state = ""
            return None
        # cache every compile, not just the >1s ones: the whole point
        # is amortizing fit-step compiles across processes
        for knob, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(knob, val)
            except Exception:
                pass
        # a backend initialized before this call holds a cache handle
        # built with the old dir; reset so the new dir takes effect
        try:
            from jax._src import compilation_cache as _icc

            _icc.reset_cache()
        except Exception:
            pass
        _cache_dir_state = resolved
        telemetry.gauge_set("compile_cache.dir", resolved)
        return resolved


def _auto_enable():
    """Fit-path hook: enable the disk cache iff the env var asks for
    it.  (Explicit tools — pintwarm, datacheck --warm — call
    enable_persistent_cache() directly and get the default dir.)"""
    if _cache_dir_state is None and os.environ.get(_CACHE_ENV):
        enable_persistent_cache()


def cache_dir():
    """The active persistent-cache directory, or None."""
    return _cache_dir_state or None


def cache_entries():
    """Number of compiled executables in the persistent cache (0 when
    disabled or empty)."""
    d = cache_dir()
    if not d:
        return 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    # jax's LRU file cache stores '<key>-cache' payloads next to
    # '-atime' bookkeeping files; older layouts store bare keys
    payload = [n for n in names if not n.endswith("-atime")]
    return len(payload)


def _reset_for_tests():
    """Forget the enable decision and empty the registry (tests)."""
    global _cache_dir_state
    with _lock:
        _cache_dir_state = None
        _registry.clear()


# --------------------------------------------------------------------------
# layer 2: process-level shared jit registry
# --------------------------------------------------------------------------

_registry: "OrderedDict" = OrderedDict()


def _registry_cap():
    try:
        return max(1, int(os.environ.get("PINT_TPU_JIT_REGISTRY_CAP",
                                         "128")))
    except ValueError:
        return 128


def _derive_label(fn, key):
    """Program label for the profiling registry: the conventional
    string head of the key (every library key starts with one), else
    the callable's qualname."""
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        return key[0]
    return getattr(fn, "__qualname__", None) or "program"


def shared_jit(fn, *, key, fn_token=None, donate_argnums=None,
               static_argnums=None, label=None):
    """The one jitted callable for (fn identity x key), creating it on
    first use.

    fn identity is ``fn.__func__`` for bound methods (shared across
    instances of a class) or ``fn`` itself; pass ``fn_token`` when the
    callable is constructed fresh per call (vmapped lambdas) and the
    key alone must identify the computation.  ``key`` must cover every
    closed-over static the trace bakes in — abstract avals of the call
    arguments are handled by jax.jit's own cache underneath.

    Every entry is returned wrapped in the profiling proxy
    (:func:`pint_tpu.profiling.wrap_program`): with the
    ``$PINT_TPU_PROFILE`` gate off the proxy is one branch on top of
    the raw call; with it on, each call's trace/dispatch/device phase
    split, byte sizes, and device-time histogram accumulate under
    ``label`` (default: the key's string head).

    The registry holds strong references (an entry keeps its first
    caller's closure alive); it is LRU-bounded by
    ``$PINT_TPU_JIT_REGISTRY_CAP`` (default 128)."""
    _auto_enable()
    identity = fn_token if fn_token is not None else getattr(
        fn, "__func__", fn)
    full_key = (identity, key)
    with _lock:
        got = _registry.get(full_key)
        if got is not None:
            _registry.move_to_end(full_key)
            telemetry.counter_add("compile_cache.registry_hits")
            return got
        telemetry.counter_add("compile_cache.registry_misses")
        import jax

        kwargs = {}
        if donate_argnums is not None:
            kwargs["donate_argnums"] = donate_argnums
        if static_argnums is not None:
            kwargs["static_argnums"] = static_argnums

        # Anchor jax's GLOBAL trace caches to this registry entry, not
        # to `fn`: bound methods compare/hash EQUAL across re-keys of
        # the same instance (f._step == f._step even after the free
        # set changed), and with the previous entry's jit kept alive
        # by the registry, jax's jaxpr cache would hand the new wrapper
        # the STALE trace — the silently-fit-the-old-params bug the
        # fitter's _retrace exists to prevent.  A fresh def per entry
        # has unique identity, so nothing aliases.
        def _entry(*args):
            return fn(*args)

        _entry.__name__ = getattr(fn, "__name__", "shared_jit_entry")
        _entry.__qualname__ = getattr(fn, "__qualname__",
                                      _entry.__name__)
        jitted = profiling.wrap_program(
            jax.jit(_entry, **kwargs), key=key,
            label=label if label is not None else _derive_label(fn, key))
        _registry[full_key] = jitted
        cap = _registry_cap()
        while len(_registry) > cap:
            _registry.popitem(last=False)
        return jitted


def registry_stats():
    """{"entries", "hits", "misses", "cap"} for datacheck/tests."""
    with _lock:
        entries = len(_registry)
    return {
        "entries": entries,
        "hits": int(telemetry.counter_get("compile_cache.registry_hits")),
        "misses": int(
            telemetry.counter_get("compile_cache.registry_misses")),
        "cap": _registry_cap(),
    }


def clear_registry():
    """Drop every registry entry (tests / memory pressure)."""
    with _lock:
        _registry.clear()


def donation_argnums(argnums):
    """``argnums`` when the backend supports buffer donation, None
    otherwise.  Donation of the iterate-in-place step vector saves one
    buffer per iteration on TPU/GPU; CPU accepts it silently on current
    jax, but older jaxlibs warn per call — gate on the platform."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        return None
    if backend in ("tpu", "gpu", "cuda", "rocm"):
        return tuple(argnums)
    if os.environ.get("PINT_TPU_DONATE_CPU"):
        return tuple(argnums)
    return None


# --------------------------------------------------------------------------
# structural keys and content fingerprints
# --------------------------------------------------------------------------

#: model.meta keys that change the traced computation (everything else
#: in meta — CHI2/TRES/NTOA fit summaries, PSR names — is cosmetic and
#: must NOT break registry sharing between consecutive fits)
_STRUCTURAL_META = ("UNITS", "TRACK", "EPHEM", "CLK", "PLANET_SHAPIRO",
                    "DMDATA", "TZRSITE")


def model_structure_key(model) -> str:
    """A string identifying everything about a TimingModel that a fit
    trace bakes in: component classes and order, their mask selects and
    parameter names, the values-pytree key set, structural meta, and
    superset-inert gating.  Parameter VALUES are excluded — they enter
    the jitted step as dynamic arguments."""
    rows = [type(model).__name__]
    for c in model.components:
        rows.append((
            type(c).__name__,
            repr(getattr(c, "selects", None)),
            tuple(p.name for p in c.params),
            bool(getattr(c, "_use_rn", False)),
        ))
    rows.append(tuple(sorted(model.values.keys())))
    rows.append(tuple((k, model.meta.get(k)) for k in _STRUCTURAL_META))
    rows.append(tuple(sorted(getattr(model, "_superset_inert", ()) or ())))
    return repr(rows)


def fingerprint(tree) -> str:
    """Content fingerprint of a pytree of arrays/scalars/strings —
    for registry keys where data IS baked into the trace (the grid
    path closes over its dataset).  Hashing is by array bytes, so two
    numerically identical datasets fingerprint equal."""
    h = hashlib.blake2b(digest_size=16)

    def feed(obj):
        if obj is None:
            h.update(b"\x00N")
        elif isinstance(obj, dict):
            h.update(b"\x00D%d" % len(obj))
            for k in sorted(obj, key=repr):
                h.update(repr(k).encode())
                feed(obj[k])
        elif isinstance(obj, (list, tuple)):
            # NamedTuple pytrees (TOABatch) land here too: tuple
            # subclasses, hashed by content like any other sequence
            h.update(b"\x00L%d" % len(obj))
            for v in obj:
                feed(v)
        elif isinstance(obj, (str, bytes, int, float, bool, complex)):
            h.update(repr(obj).encode())
        elif hasattr(obj, "shape"):
            a = np.asarray(obj)
            h.update(b"\x00A" + str(a.dtype).encode()
                     + repr(a.shape).encode())
            h.update(a.tobytes())
        else:
            h.update(repr(obj).encode())

    feed(tree)
    return h.hexdigest()


# --------------------------------------------------------------------------
# ctx split/merge across the jit boundary
# --------------------------------------------------------------------------

def _is_dynamic_leaf(v):
    """Array leaves cross the jit boundary as arguments; python
    scalars/strings/tuples are static jit structure (the partition
    rule of parallel.pta._stack_ctxs).  One deliberate extension:
    numpy 0-d scalars (np.float64 'df' in the Fourier-noise ctx) are
    DYNAMIC here — they are data-derived and differ in the last ulp
    between same-shaped datasets, which must not break trace sharing.
    (pta's stacker instead drops them per-pulsar with a warning; its
    batched trace never reads them.)"""
    if isinstance(v, np.generic):
        return True
    return hasattr(v, "shape") and not isinstance(
        v, (tuple, int, float, bool))


def split_ctx(ctx_map):
    """Split a prepare()-time ``{component: {key: leaf}}`` ctx into
    (dynamic arrays part, static part).  The dynamic part is a pytree
    of arrays to pass as a jit argument; the static part stays closed
    over and must be folded into the registry key (its repr is
    deterministic)."""
    if ctx_map is None:
        return None, {}
    arrays, static = {}, {}
    for comp, ctx in ctx_map.items():
        a, s = {}, {}
        for k, v in ctx.items():
            if _is_dynamic_leaf(v):
                a[k] = v
            else:
                s[k] = v
        arrays[comp] = a
        static[comp] = s
    return arrays, static


def merge_ctx(arrays, static):
    """Reassemble a component ctx from its dynamic and static parts
    (inside OR outside a trace)."""
    return {
        comp: {**static.get(comp, {}), **arrays[comp]}
        for comp in arrays
    }


def static_ctx_key(static) -> str:
    """Deterministic repr of a split_ctx static part for registry
    keys."""
    return repr(sorted(
        (comp, sorted((k, repr(v)) for k, v in d.items()))
        for comp, d in (static or {}).items()
    ))


# --------------------------------------------------------------------------
# layer 3: TOA-count shape bucketing
# --------------------------------------------------------------------------

#: sentinel uncertainty for padded TOAs [us]: sigma = 1e16 s, weight
#: 1/sigma^2 = 1e-32 s^-2 — vanishes against any real TOA weight
#: (~1e12) to far beyond f64 resolution, and sigma^2 = 1e32 stays
#: representable inside the TPU's float32-pair f64 emulation (high
#: word saturates at ~3.4e38; see residuals.MEAN_OFFSET_WEIGHT)
PAD_ERROR_US = 1e22

#: default geometric bucketing: 64, 80, 100, 125, 157, ... (1.25x)
BUCKET_BASE = 64
BUCKET_GROWTH = 1.25


def bucket_size(n, base=BUCKET_BASE, growth=BUCKET_GROWTH):
    """Smallest bucket >= n in geometric steps: datasets whose sizes
    land in the same bucket compile to the SAME executable (<= 25%
    padded compute buys an entire 30-second compile)."""
    n = int(n)
    if n <= base:
        return base
    b = float(base)
    while int(round(b)) < n:
        b *= growth
    return int(round(b))


def bucketing_default():
    """Whether fitters bucket by default (``$PINT_TPU_BUCKET_TOAS``)."""
    raw = os.environ.get(_BUCKET_ENV, "")
    return raw.strip().lower() in ("1", "true", "yes", "on")


def pad_toas(toas, n_target=None):
    """Pad a TOAs object to its bucket size with zero-weight sentinel
    rows; returns the padded object (``.n_real`` records the original
    count) or the input unchanged when already at a bucket boundary.

    The sentinels are copies of the LAST real TOA (so they join its
    noise-mask groups and its ECORR epoch — never adding basis
    columns) with uncertainty ``PAD_ERROR_US`` (and ``-pp_dme`` set to
    the same sentinel when the dataset carries wideband DM data), so
    every weighted reduction downstream — chi^2, weighted mean,
    normal equations, Woodbury — drops them to below f64 resolution.
    dof/NTOA accounting uses ``n_real``, never the padded length.
    """
    from pint_tpu.toa import TOAs

    n = len(toas)
    if getattr(toas, "n_real", None) is not None:
        # already padded; an explicit conflicting target must not be
        # silently ignored
        if n_target is not None and int(n_target) != n:
            raise ValueError(
                f"TOAs already padded to {n} (n_real={toas.n_real}); "
                f"cannot re-pad to {n_target}")
        return toas
    target = bucket_size(n) if n_target is None else int(n_target)
    if target < n:
        raise ValueError(f"pad target {target} < {n} TOAs")
    if target == n:
        # at a bucket boundary: return a COPY carrying n_real — never
        # stamp bucketing state onto the caller's object (it would
        # change the structure key of every Residuals later built from
        # it, silently splitting the registry into mask/no-mask
        # variants of the same problem)
        out = toas[np.arange(n)]
        out.n_real = n
        return out
    pad = toas[np.full(target - n, n - 1, dtype=np.int64)]
    pad.error_us = np.full(target - n, PAD_ERROR_US)
    for f in pad.flags:
        f["pad"] = "1"
        if "pp_dm" in f:
            f["pp_dme"] = repr(PAD_ERROR_US)
    padded = TOAs.merge([toas, pad])
    padded.n_real = n
    telemetry.counter_add("compile_cache.toas_padded")
    telemetry.counter_add("compile_cache.pad_rows", float(target - n))
    return padded


# --------------------------------------------------------------------------
# layer 4: AOT warmup
# --------------------------------------------------------------------------

#: standard GLS shape: DD binary + two-receiver EFAC/EQUAD/ECORR masks
#: + power-law red noise — the B1855-class config every bench round
#: measures (bench.py B1855_LIKE_PAR stays the measurement twin)
WARM_GLS_PAR = """PSR  WARMUP-GLS
RAJ 18:57:36.39
DECJ 09:43:17.2
F0 186.49408156698235146 1
F1 -6.2049e-16 1
PEPOCH 54000
DM 13.29984 1
BINARY DD
PB 12.32717119132762 1
A1 9.230780480 1
ECC 0.00002170 1
T0 54000.7262 1
OM 276.55 1
M2 0.26 1
SINI 0.999 1
TZRMJD 54000
TZRSITE @
TZRFRQ 1400
EFAC -f L-wide 1.1
EQUAD -f L-wide 0.3
ECORR -f L-wide 0.5
TNRedAmp -13.5
TNRedGam 3.3
TNRedC 30
UNITS TDB
EPHEM builtin
"""

#: minimal isolated-pulsar WLS shape (fast CPU warmup / smoke tests)
WARM_WLS_PAR = """PSR  WARMUP-WLS
RAJ 18:57:36.39
DECJ 09:43:17.2
F0 186.49408156698235146 1
F1 -6.2049e-16 1
PEPOCH 54000
DM 13.29984 1
TZRMJD 54000
TZRSITE @
TZRFRQ 1400
UNITS TDB
EPHEM builtin
"""


def _warm_pairs(n_toas, kind, seed=0):
    from pint_tpu.models.builder import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = WARM_GLS_PAR if kind in ("gls", "downhill_gls") else WARM_WLS_PAR
    model = get_model(par)
    toas = make_fake_toas_uniform(
        53000.0, 56500.0, int(n_toas), model, freq_mhz=1400.0, obs="gbt",
        error_us=1.0, add_noise=True,
        rng=np.random.default_rng(seed),
        flags={"f": "L-wide"})
    return model, toas


def warmup(toa_counts=(500, 1000), kinds=("wls", "gls"), bucket=None,
           progress=None, pairs=None):
    """AOT-compile (``jit.lower().compile()``) the standard fit shapes,
    populating the persistent cache for future processes.  Returns a
    list of {"kind", "n_toas", "bucket", "compile_s"} records.

    bucket=None follows :func:`bucketing_default` — the warmed shapes
    must be the shapes default-configured fits will actually request
    (a 596-row bucketed executable serves nothing when production fits
    trace at exactly 500 TOAs, and vice versa).  Pass True/False to
    warm for an explicitly bucketed/exact deployment.

    pairs: optional explicit [(model, toas), ...] to warm a real
    dataset's shapes instead of the synthetic standards (the
    ``pintwarm --par/--tim`` path)."""
    from pint_tpu.downhill import DownhillGLSFitter, DownhillWLSFitter
    from pint_tpu.fitter import GLSFitter, WLSFitter

    fitter_of = {
        "wls": WLSFitter,
        "gls": GLSFitter,
        "downhill_wls": DownhillWLSFitter,
        "downhill_gls": DownhillGLSFitter,
    }
    if bucket is None:
        bucket = bucketing_default()
    out = []
    jobs = []
    if pairs is not None:
        for kind in kinds:
            for model, toas in pairs:
                jobs.append((kind, model, toas))
    else:
        for kind in kinds:
            for n in toa_counts:
                model, toas = _warm_pairs(n, kind)
                jobs.append((kind, model, toas))
    for kind, model, toas in jobs:
        cls = fitter_of[kind]
        n_in = len(toas)
        if bucket:
            toas = pad_toas(toas)
        f = cls(toas, model)
        dt = f.warm_compile()
        rec = {"kind": kind, "n_toas": n_in, "bucket": len(toas),
               "compile_s": round(dt, 3)}
        out.append(rec)
        if progress is not None:
            progress(f"warmed {kind} n_toas={n_in} "
                     f"(bucket {len(toas)}): compile {dt:.1f}s")
    telemetry.counter_add("compile_cache.warmups", len(out))
    return out


def warm_timed(fn):
    """Time one AOT compile call (helper for warm_compile methods)."""
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
