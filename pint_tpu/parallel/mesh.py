"""The one mesh/PartitionSpec layer over every batch axis.

The domain's parallelism is data-parallel along four named axes
(SURVEY section 2.9; ROADMAP open item 1): **pulsar** (the PTA batch),
**grid** (chi^2 / likelihood grid points), **walker** (MCMC ensemble
members), and **pair** (optimal-statistic pulsar pairs).  Before this
module each sharded call site hand-rolled its own ``NamedSharding``
plumbing (``gw/os.py`` padded pairs, ``parallel/pta.py`` sniffed
shapes); everything now goes through one registry of *partition
rules* — regex patterns over flattened data-pytree key paths mapped to
:class:`jax.sharding.PartitionSpec` (the ``match_partition_rules``
shape of the pjit exemplars in SNIPPETS.md [2]):

- scalar / single-element leaves are replicated (``PS()``) without
  consulting the table;
- the first rule whose pattern ``re.search``-matches the ``/``-joined
  key path wins;
- a non-scalar leaf no rule matches is an explicit :class:`ValueError`
  naming the path — silent replication of a batch-axis array is how
  sharding bugs hide;
- call sites can prepend ``overrides`` without touching the base
  table.

Padding follows the repo's existing sentinel/zero-weight masking
conventions per axis (documented in docs/sharding.md):

==========  ==============================================================
axis        pad-to-device-multiple contract
==========  ==============================================================
``pulsar``  phantom members cloned from the last real pulsar with their
            ``free_mask`` row zeroed (no parameter moves); results are
            sliced back to ``n_real`` rows on the host before any
            merge/write-back/checkpoint path sees them
``grid``    grid points edge-repeated; chi^2/fitted outputs sliced back
``pair``    zero-index pairs with ``wmask=False`` zero weights (the
            gw/os convention), inert in every weighted reduction
``walker``  **never padded** — stretch moves couple walkers, so a
            phantom walker would change real proposals; the ensemble
            size must divide the device count (raise, don't pad)
==========  ==============================================================

Sharding participates in every jit key through :func:`mesh_jit_key`
without breaking the zero-recompile contract: a mesh resolves to one
extra registry entry (a second same-shaped sharded call performs zero
new XLA compiles), and ``mesh=None`` keys exactly as before, so the
single-device program is bit-identical to the pre-mesh behavior.

Beyond the four batch axes, a fifth named axis — ``toa`` — shards the
SEQUENCE dimension inside a single pulsar: the Woodbury contractions
of :mod:`pint_tpu.linalg` reduce their O(N (P+K)^2) gram assembly as
per-shard partial contractions plus a small-K cross-device reduction
(the rank-reduced decomposition of arXiv 1210.0584), expressed as
sharding constraints (:class:`RowShard`) that GSPMD lowers to
psum-style all-reduces.  Segment-sum ECORR epoch blocks must not
straddle shard boundaries — :func:`toa_shard_plan` computes the
pad-row insertion that aligns them (or reports the dense fallback).

Multi-process pods initialize through :func:`distributed_init`
(inert in a single process); the process topology participates in
:func:`mesh_jit_key` — and, through it, in the AOT manifest — so
serialized executables are per-topology artifacts.

Telemetry: ``mesh.sharded_calls`` counts :func:`shard_args`
invocations that actually placed data on a mesh;
``mesh.pad_waste_frac`` gauges the phantom-row overhead of the most
recent padded batch (see docs/telemetry.md).
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from pint_tpu import telemetry

__all__ = [
    "AXIS_NAMES", "make_mesh", "mesh_desc", "mesh_jit_key",
    "resolve_axis", "axis_size", "match_partition_rules",
    "named_tree_map", "tree_paths", "pad_to_multiple", "pad_leading",
    "record_pad_waste", "shard_args", "replicate",
    "distributed_init", "process_topology", "RowShard",
    "shard_toa_data", "toa_epochs_aligned", "toa_shard_plan",
    "chain_constrainer",
]

#: the canonical batch axes of this codebase (a mesh may use any
#: subset, and other names are allowed for experiments).  ``toa`` is
#: the in-pulsar sequence axis (linalg Woodbury reductions), not a
#: batch axis — it never vmaps, it shards the N dimension itself.
AXIS_NAMES = ("pulsar", "grid", "walker", "pair", "toa")


# --------------------------------------------------------------------------
# multi-process scaffolding
# --------------------------------------------------------------------------

#: record of the last distributed_init() call (None = never called)
_DISTRIBUTED: Optional[dict] = None


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Initialize the multi-process JAX runtime for pod-spanning
    meshes — the ``jax.distributed.initialize`` entry of this layer.

    On a multi-host pod slice, call this ONCE per process before any
    jax computation; afterwards ``jax.devices()`` spans every process
    and :func:`make_mesh` builds process-spanning meshes (the pjit
    contract of SNIPPETS.md [1]: "pjit can run computations across
    all available devices across processes").  With no arguments and
    no cluster environment (the single-process case — every CPU dev
    box and single-host TPU VM), this is INERT: no collective setup
    is attempted, and the returned topology record simply says
    ``processes=1``.

    The returned record ``{"processes", "process_id",
    "local_devices", "devices", "initialized"}`` is also what
    :func:`mesh_jit_key` folds into every sharded jit key (and,
    through ``compile_cache._aot_env``, into the AOT manifest): a
    serialized executable is a per-topology artifact — an 8-process
    pod program must never be served to a 4-process slice.
    Idempotent: a second call returns the existing record."""
    global _DISTRIBUTED
    import jax

    explicit = any(v is not None for v in
                   (coordinator_address, num_processes, process_id))
    if _DISTRIBUTED is not None:
        if explicit and not _DISTRIBUTED["initialized"]:
            # an earlier no-arg call ran inert; silently returning the
            # stale single-process record would swallow the pod setup
            # (meshes stay single-host, the AOT manifest records the
            # wrong topology) with no error anywhere
            raise ValueError(
                "distributed_init already ran inert in this process "
                "(single-process record cached); pass the coordinator "
                "arguments on the FIRST call, before any jax "
                "computation")
        return _DISTRIBUTED
    import os as _os

    cluster_env = any(_os.environ.get(k) for k in
                      ("JAX_COORDINATOR_ADDRESS",
                       "COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID",
                       "TPU_WORKER_HOSTNAMES"))
    initialized = False
    if explicit or cluster_env:
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = int(num_processes)
        if process_id is not None:
            kwargs["process_id"] = int(process_id)
        if local_device_ids is not None:
            kwargs["local_device_ids"] = local_device_ids
        jax.distributed.initialize(**kwargs)
        initialized = True
    _DISTRIBUTED = {
        "processes": int(jax.process_count()),
        "process_id": int(jax.process_index()),
        "local_devices": len(jax.local_devices()),
        "devices": len(jax.devices()),
        "initialized": initialized,
    }
    telemetry.gauge_set("mesh.processes", _DISTRIBUTED["processes"])
    return _DISTRIBUTED


def process_topology() -> dict:
    """The process topology every sharded jit key (and the AOT
    manifest) records: ``{"processes": P, "local_devices": D}``.
    Works without :func:`distributed_init` (a plain single process
    reports ``processes=1``); after it, reflects the pod."""
    import jax

    return {"processes": int(jax.process_count()),
            "local_devices": len(jax.local_devices())}


# --------------------------------------------------------------------------
# mesh construction
# --------------------------------------------------------------------------

def make_mesh(axes="pulsar", n_devices=None, shape=None):
    """A device mesh with named axes.

    axes: one axis name or a sequence of names (``("pulsar", "grid")``
    for a 2-d mesh).  n_devices: cap on the devices used (default:
    all).  shape: per-axis device counts for multi-axis meshes; for a
    1-d mesh it defaults to every selected device.  The product of
    ``shape`` must equal the selected device count."""
    import jax
    from jax.sharding import Mesh

    if isinstance(axes, str):
        axes = (axes,)
    axes = tuple(axes)
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < int(n_devices):
            raise ValueError(
                f"make_mesh: asked for {n_devices} devices, have "
                f"{len(devs)}")
        devs = devs[: int(n_devices)]
    if shape is None:
        if len(axes) != 1:
            raise ValueError(
                "make_mesh: a multi-axis mesh needs an explicit shape")
        shape = (len(devs),)
    shape = tuple(int(s) for s in shape)
    if len(shape) != len(axes):
        raise ValueError(
            f"make_mesh: shape {shape} does not match axes {axes}")
    n = int(np.prod(shape))
    if n != len(devs):
        raise ValueError(
            f"make_mesh: shape {shape} needs {n} devices, selected "
            f"{len(devs)}")
    return Mesh(np.array(devs).reshape(shape), axes)


def mesh_desc(mesh) -> Optional[dict]:
    """Structured record of a mesh for bench metrics and the profiling
    program registry: ``{"devices": N, "axes": {name: size, ...}}``
    (+ ``processes`` on a multi-process topology; None for no
    mesh)."""
    if mesh is None:
        return None
    out = {
        "devices": int(mesh.devices.size),
        "axes": {str(name): int(size)
                 for name, size in zip(mesh.axis_names,
                                       mesh.devices.shape)},
    }
    topo = process_topology()
    if topo["processes"] > 1:
        out["processes"] = topo["processes"]
        out["local_devices"] = topo["local_devices"]
    return out


def mesh_jit_key(mesh) -> tuple:
    """The sharding part of a shared_jit key: ``()`` for no mesh (so
    single-device keys are unchanged from the pre-mesh layout), else a
    stable ``("mesh", ((axis, size), ...))`` tuple.  One mesh = one
    registry entry = zero new XLA compiles on the second same-shaped
    sharded call.

    On a multi-process runtime (:func:`distributed_init`) the key
    additionally carries ``("procs", process_count,
    devices_per_process)``: the SAME axis layout cut across a
    different process topology lowers to different collectives, so a
    pod program and a single-host program must occupy separate
    registry entries — and separate AOT manifest entries (the
    manifest records the topology through ``compile_cache._aot_env``).
    Single-process keys are byte-identical to the pre-pod layout."""
    if mesh is None:
        return ()
    key = ("mesh", tuple(
        (str(name), int(size))
        for name, size in zip(mesh.axis_names, mesh.devices.shape)))
    topo = process_topology()
    if topo["processes"] > 1:
        key = key + (("procs", topo["processes"],
                      topo["local_devices"]),)
    return key


def resolve_axis(mesh, axis: str, requested_by: Optional[str] = None) \
        -> str:
    """The mesh axis a canonical axis name rides.  An exact name match
    wins; a 1-d mesh serves ANY axis under its own name (the gw/os
    contract: "the axis name is immaterial, pairs ride it", so a
    ``pulsar_mesh`` can shard the pair axis); a multi-axis mesh
    missing the name is an error — guessing which axis to ride would
    silently mis-shard.  ``requested_by`` names the partition rule /
    data leaf that asked, so a misconfigured pod mesh is diagnosed at
    the rule that tripped it, not from a bare axis name."""
    names = tuple(str(n) for n in mesh.axis_names)
    if axis in names:
        return axis
    if len(names) == 1:
        return names[0]
    raise ValueError(
        f"mesh axes {names} do not include axis {axis!r}"
        + (f" (requested by {requested_by})" if requested_by else "")
        + f"; available axes on this {len(names)}-d mesh are "
        + ", ".join(repr(n) for n in names)
        + " — name one of them in the rule, or build the mesh with "
        f"make_mesh(axes=(..., {axis!r}), shape=...)")


def axis_size(mesh, axis: str) -> int:
    """Device count along a (resolved) canonical axis; 1 for no mesh."""
    if mesh is None:
        return 1
    name = resolve_axis(mesh, axis)
    return int(mesh.devices.shape[list(
        str(n) for n in mesh.axis_names).index(name)])


# --------------------------------------------------------------------------
# key-path walking
# --------------------------------------------------------------------------

def _is_leaf(v):
    # arrays and scalars are leaves; containers recurse.  None is a
    # structural hole (absent tzr batch) — kept as a leaf so rebuilt
    # trees keep their shape, never matched against rules.  A
    # PartitionSpec is a tuple SUBCLASS but is a resolved rule, not a
    # container (match_partition_rules returns trees of them).
    if type(v).__name__ == "PartitionSpec":
        return True
    return not isinstance(v, (dict, list, tuple))


def _items(tree):
    """(key, child) pairs of one container level.  Dict keys and
    NamedTuple field names keep their names; plain sequences use
    indices — so a rule can say ``^batch/ticks`` instead of
    ``^2/0``."""
    if isinstance(tree, dict):
        return list(tree.items())
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return list(zip(tree._fields, tree))
    return list(enumerate(tree))


def _rebuild(tree, children):
    if isinstance(tree, dict):
        return type(tree)(zip([k for k, _ in _items(tree)], children))
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return type(tree)(*children)
    return type(tree)(children)


def named_tree_map(fn, tree, prefix=""):
    """Map ``fn(path, leaf) -> new_leaf`` over a pytree of
    dicts/(named)tuples/lists, with ``path`` the ``/``-joined key
    chain (the SNIPPETS.md [2] ``named_tree_map`` shape).  ``None``
    leaves pass through untouched."""
    if _is_leaf(tree):
        return tree if tree is None else fn(prefix, tree)
    children = [
        named_tree_map(fn, child,
                       f"{prefix}/{key}" if prefix else str(key))
        for key, child in _items(tree)
    ]
    return _rebuild(tree, children)


def tree_paths(tree) -> list:
    """Flattened ``(path, leaf)`` list (non-None leaves only)."""
    out = []

    def visit(path, leaf):
        out.append((path, leaf))
        return leaf

    named_tree_map(visit, tree)
    return out


# --------------------------------------------------------------------------
# the partition-rule table
# --------------------------------------------------------------------------

def replicate():
    """An explicitly-replicated PartitionSpec (``PS()``)."""
    from jax.sharding import PartitionSpec as PS

    return PS()


def _is_scalar_leaf(leaf) -> bool:
    shape = np.shape(leaf)
    return len(shape) == 0 or int(np.prod(shape)) == 1


def _rule_resolver(rules, overrides=None):
    """``resolve(path, leaf) -> PartitionSpec`` over a rule table.
    Overrides are consulted first (the per-call-site escape hatch);
    scalar and single-element leaves replicate without consulting the
    table (SNIPPETS.md [2]); any other unmatched leaf raises."""
    table = list(overrides or ()) + list(rules)
    compiled = [(re.compile(pat), spec) for pat, spec in table]

    def resolve(path, leaf):
        if _is_scalar_leaf(leaf):
            return replicate()
        for pat, spec in compiled:
            if pat.search(path) is not None:
                return replicate() if spec is None else spec
        raise ValueError(
            f"no partition rule matches data leaf {path!r} "
            f"(shape {np.shape(leaf)}); add a rule or an explicit "
            "replicate() entry — silent replication of a batch-axis "
            "array is how sharding bugs hide")

    return resolve


def match_partition_rules(rules, tree, *, overrides=None):
    """Resolve a rule table over a data pytree.

    rules / overrides: sequences of ``(pattern, PartitionSpec)``.
    Returns a same-structure pytree of PartitionSpecs (see
    :func:`_rule_resolver` for the matching semantics)."""
    return named_tree_map(_rule_resolver(rules, overrides), tree)


def _resolve_spec(mesh, spec, requested_by=None):
    """A rule's PartitionSpec with canonical axis names mapped onto
    the mesh's real axes (:func:`resolve_axis`).  ``requested_by``
    flows into the absent-axis diagnostic so the error names the data
    leaf whose rule asked for the missing axis."""
    from jax.sharding import PartitionSpec as PS

    parts = []
    for entry in spec:
        if entry is None:
            parts.append(None)
        elif isinstance(entry, (list, tuple)):
            parts.append(tuple(
                resolve_axis(mesh, a, requested_by=requested_by)
                for a in entry))
        else:
            parts.append(resolve_axis(mesh, str(entry),
                                      requested_by=requested_by))
    return PS(*parts)


def shard_args(mesh, rules, tree, *, overrides=None):
    """Resolve the rule table over ``tree`` and ``device_put`` every
    leaf onto the mesh (NamedSharding).  ``mesh=None`` returns the
    tree unchanged — the single-device path stays bit-identical.

    Every sharded-axis length must already be a device-count multiple
    (use :func:`pad_to_multiple` / :func:`pad_leading` first); a
    non-divisible axis is reported with its path rather than jax's
    anonymous shape error."""
    if mesh is None:
        return tree
    import jax
    from jax.sharding import NamedSharding

    resolve = _rule_resolver(rules, overrides)
    sizes = dict(zip((str(n) for n in mesh.axis_names),
                     (int(s) for s in mesh.devices.shape)))

    def put(path, leaf):
        resolved = _resolve_spec(
            mesh, resolve(path, leaf),
            requested_by=f"the rule for data leaf {path!r}")
        for dim, entry in enumerate(resolved):
            axes = (entry,) if isinstance(entry, str) else (entry or ())
            need = int(np.prod([sizes[a] for a in axes])) if axes else 1
            if need > 1 and np.shape(leaf)[dim] % need:
                raise ValueError(
                    f"leaf {path!r} axis {dim} (length "
                    f"{np.shape(leaf)[dim]}) is not a multiple of the "
                    f"{entry!r} mesh extent {need}; pad it first "
                    "(mesh.pad_to_multiple / pad_leading)")
        return jax.device_put(leaf, NamedSharding(mesh, resolved))

    out = named_tree_map(put, tree)
    telemetry.counter_add("mesh.sharded_calls")
    return out


# --------------------------------------------------------------------------
# chain/walker-axis sharding (the ensemble/HMC samplers)
# --------------------------------------------------------------------------

def chain_constrainer(mesh, n, *, group=1, axis="walker",
                      requested_by="chains"):
    """The ONE chain-axis rule of the sampler layer: a
    ``with_sharding_constraint`` closure holding a leading
    chain/walker axis on the mesh's ``walker`` axis across scanned
    steps (without it XLA is free to gather the scan carry onto one
    device between iterations), shared by the ensemble sampler's
    walkers (:func:`pint_tpu.sampler.run_mcmc`) and gw/hmc's vmapped
    NUTS chains — the two batch-of-chains programs must not grow
    separate sharding conventions.

    The chain axis is NEVER padded: ensemble stretch moves couple
    walkers (a phantom would change real proposals) and an HMC chain
    is a logical unit of the returned posterior, so ``n`` must be a
    multiple of ``group`` x the walker-axis device count (``group=2``
    expresses the stretch move's red-black split — each HALF shards).
    Raises (naming ``requested_by``) rather than padding.  Returns
    None for ``mesh=None`` — single-device traces stay byte-identical."""
    if mesh is None:
        return None
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndev = axis_size(mesh, axis)
    n, group = int(n), max(1, int(group))
    if n % (group * ndev):
        raise ValueError(
            f"{requested_by}: n={n} must be a multiple of "
            f"{group}x the walker-axis device count ({ndev}); the "
            "chain axis cannot be padded — a phantom member would "
            "change real results (stretch moves couple walkers; an "
            "HMC chain is a returned posterior unit)")
    sharding = NamedSharding(
        mesh, P(resolve_axis(mesh, axis, requested_by=requested_by)))

    def constrain(arr):
        return jax.lax.with_sharding_constraint(arr, sharding)

    return constrain


# --------------------------------------------------------------------------
# pad-to-device-multiple helpers
# --------------------------------------------------------------------------

def pad_to_multiple(n: int, multiple: int) -> int:
    """Smallest count >= n divisible by ``multiple``."""
    n, multiple = int(n), max(1, int(multiple))
    return n + (-n) % multiple


def pad_leading(arr, n_target: int, mode: str = "edge", fill=None):
    """Pad an array's leading axis up to ``n_target`` rows.

    mode="edge" repeats the final row (the TOA-axis convention of
    ``parallel/pta._pad_batch`` — a clone is always finite);
    mode="zero" appends zeros (inert under zero-weight masking);
    ``fill=`` overrides with a constant (the gw/os pair-index
    convention, e.g. ``jj`` pads with 1 so pad pairs stay valid
    index pairs)."""
    import jax.numpy as jnp

    arr = jnp.asarray(arr)
    pad = int(n_target) - arr.shape[0]
    if pad < 0:
        raise ValueError(
            f"pad_leading: target {n_target} < length {arr.shape[0]}")
    if pad == 0:
        return arr
    if fill is not None:
        tail = jnp.full((pad,) + arr.shape[1:], fill, dtype=arr.dtype)
    elif mode == "edge":
        tail = jnp.repeat(arr[-1:], pad, axis=0)
    elif mode == "zero":
        tail = jnp.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
    else:
        raise ValueError(f"pad_leading: unknown mode {mode!r}")
    return jnp.concatenate([arr, tail], axis=0)


# --------------------------------------------------------------------------
# TOA-axis (sequence) sharding
# --------------------------------------------------------------------------

class RowShard:
    """Static sharding context for the leading (TOA) axis of in-trace
    arrays — the object the Woodbury contractions of
    :mod:`pint_tpu.linalg` receive as their ``toa=`` argument.

    ``rows(x)`` pins an array's leading dimension onto the resolved
    mesh axis with ``jax.lax.with_sharding_constraint``; XLA's SPMD
    partitioner then carries the per-shard partial contractions and
    inserts the small-K all-reduce at each ``U^T N^-1 U`` / ``J^T W
    r`` reduction — the psum-over-TOA-axis decomposition of the
    rank-reduced Woodbury algebra (arXiv 1210.0584).  Instances are
    closed over at trace time (never passed through jit), so the mesh
    MUST participate in the caller's jit key (``mesh_jit_key``)."""

    def __init__(self, mesh, axis: str = "toa"):
        self.mesh = mesh
        self.axis = resolve_axis(mesh, axis,
                                 requested_by="RowShard")

    def rows(self, x):
        """Constrain ``x``'s leading axis onto the TOA mesh axis."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as PS

        spec = PS(*((self.axis,) + (None,) * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec))

    def __repr__(self):
        return f"RowShard({mesh_desc(self.mesh)}, axis={self.axis!r})"


def shard_toa_data(mesh, tree, n_toa: int, axis: str = "toa"):
    """Structural TOA-axis sharding of a fit-data pytree: every array
    leaf gets the resolved ``toa`` mesh axis on its FIRST dimension of
    length ``n_toa`` (the same shape-sniffing convention
    ``parallel/pta._pad_ctx`` pads by — component ctx arrays carry the
    TOA axis leading or trailing, batch arrays leading); every other
    leaf replicates.  ``mesh=None`` is the identity.

    ``n_toa`` must already be a multiple of the axis extent
    (:func:`pad_to_multiple` + ``compile_cache.pad_toas`` first)."""
    if mesh is None:
        return tree
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as PS

    name = resolve_axis(mesh, axis, requested_by="shard_toa_data")
    extent = axis_size(mesh, axis)
    if n_toa % extent:
        raise ValueError(
            f"shard_toa_data: TOA count {n_toa} is not a multiple of "
            f"the {name!r} mesh extent {extent}; pad the dataset "
            "first (compile_cache.pad_toas)")

    def put(path, leaf):
        shape = np.shape(leaf)
        dims = [None] * len(shape)
        for d, s in enumerate(shape):
            if s == n_toa:
                dims[d] = name
                break
        return jax.device_put(leaf,
                              NamedSharding(mesh, PS(*dims)))

    out = named_tree_map(put, tree)
    telemetry.counter_add("mesh.sharded_calls")
    return out


def toa_epochs_aligned(seg, n_epoch: int, n_shards: int) -> bool:
    """True when no ECORR epoch's row span straddles a TOA-shard
    boundary (``seg`` length must already be a shard multiple) — the
    condition under which the sharded segment-sum reduction stays
    shard-local."""
    seg = np.asarray(seg)
    n = int(seg.shape[0])
    n_shards = max(1, int(n_shards))
    if n % n_shards:
        return False
    s = n // n_shards
    for e in range(int(n_epoch)):
        rows = np.flatnonzero(seg == e)
        if rows.size and rows[0] // s != rows[-1] // s:
            return False
    return True


def toa_shard_plan(seg, n_epoch: int, n_shards: int,
                   max_grow: int = 16):
    """Row-insertion plan aligning ECORR epoch blocks to TOA-shard
    boundaries.

    ``seg``: per-TOA int epoch ids (``StructuredU.seg`` — ``n_epoch``
    means "no epoch").  A segment-sum epoch block whose rows straddle
    a shard edge forces a cross-device scatter-add; this plan inserts
    zero-weight pad rows (sentinel clones, the ``pad_toas``
    convention) so every epoch's row span lands inside one shard.

    Returns an int array ``plan`` whose entries are source-row
    indices with ``-1`` marking an inserted pad row (clone of the
    nearest preceding source row — which joins that row's epoch, so
    the preceding block extends exactly TO the boundary, never past
    it), ``len(plan)`` a multiple of ``n_shards``; or ``None`` when
    alignment is impossible (an epoch cluster larger than a shard
    even after ``max_grow`` target growths) — the caller falls back
    to the dense basis.  A ``plan`` that is simply
    ``arange(n)`` + tail pads means the layout was already aligned.

    Epochs whose row spans interleave (two receivers observing the
    same night) are merged into one cluster and moved together."""
    seg = np.asarray(seg)
    n = int(seg.shape[0])
    n_shards = max(1, int(n_shards))
    # per-epoch [min_row, max_row] spans -> merged clusters
    spans = []
    for e in range(int(n_epoch)):
        rows = np.flatnonzero(seg == e)
        if rows.size:
            spans.append((int(rows[0]), int(rows[-1])))
    spans.sort()
    clusters = []
    for lo, hi in spans:
        if clusters and lo <= clusters[-1][1]:
            clusters[-1][1] = max(clusters[-1][1], hi)
        else:
            clusters.append([lo, hi])
    # blocks in row order: cluster spans move as units, rows between
    # them are free singletons
    blocks = []
    row = 0
    for lo, hi in clusters:
        while row < lo:
            blocks.append((row, 1))
            row += 1
        blocks.append((lo, hi - lo + 1))
        row = hi + 1
    while row < n:
        blocks.append((row, 1))
        row += 1
    for target in range(pad_to_multiple(n, n_shards),
                        pad_to_multiple(n, n_shards)
                        + max_grow * n_shards + 1, n_shards):
        if target == 0:
            continue
        s = target // n_shards
        if any(length > s for _, length in blocks):
            return None  # a cluster can never fit in one shard
        plan = []
        ok = True
        for start, length in blocks:
            pos = len(plan)
            if length > 1 and pos // s != (pos + length - 1) // s:
                # push the block to the next shard boundary with pads
                plan.extend([-1] * (s - pos % s))
            if len(plan) + length > target:
                ok = False  # ran out of room; grow the target
                break
            plan.extend(range(start, start + length))
        if not ok:
            continue
        plan.extend([-1] * (target - len(plan)))
        return np.asarray(plan, dtype=np.int64)
    return None


def record_pad_waste(axis: str, n_real: int, n_padded: int):
    """Telemetry for phantom-row overhead: the fraction of the padded
    batch that is padding (``mesh.pad_waste_frac`` gauge — the most
    recent sharded batch, honestly 0.0 when it needed no padding;
    ``mesh.pad_rows`` counter, cumulative)."""
    n_real, n_padded = int(n_real), int(n_padded)
    frac = 0.0 if n_padded <= 0 else (n_padded - n_real) / n_padded
    telemetry.gauge_set("mesh.pad_waste_frac", round(frac, 6))
    telemetry.gauge_set(f"mesh.pad_waste_frac.{axis}", round(frac, 6))
    if n_padded > n_real:
        telemetry.counter_add("mesh.pad_rows", float(n_padded - n_real))
    return frac
