"""The one mesh/PartitionSpec layer over every batch axis.

The domain's parallelism is data-parallel along four named axes
(SURVEY section 2.9; ROADMAP open item 1): **pulsar** (the PTA batch),
**grid** (chi^2 / likelihood grid points), **walker** (MCMC ensemble
members), and **pair** (optimal-statistic pulsar pairs).  Before this
module each sharded call site hand-rolled its own ``NamedSharding``
plumbing (``gw/os.py`` padded pairs, ``parallel/pta.py`` sniffed
shapes); everything now goes through one registry of *partition
rules* — regex patterns over flattened data-pytree key paths mapped to
:class:`jax.sharding.PartitionSpec` (the ``match_partition_rules``
shape of the pjit exemplars in SNIPPETS.md [2]):

- scalar / single-element leaves are replicated (``PS()``) without
  consulting the table;
- the first rule whose pattern ``re.search``-matches the ``/``-joined
  key path wins;
- a non-scalar leaf no rule matches is an explicit :class:`ValueError`
  naming the path — silent replication of a batch-axis array is how
  sharding bugs hide;
- call sites can prepend ``overrides`` without touching the base
  table.

Padding follows the repo's existing sentinel/zero-weight masking
conventions per axis (documented in docs/sharding.md):

==========  ==============================================================
axis        pad-to-device-multiple contract
==========  ==============================================================
``pulsar``  phantom members cloned from the last real pulsar with their
            ``free_mask`` row zeroed (no parameter moves); results are
            sliced back to ``n_real`` rows on the host before any
            merge/write-back/checkpoint path sees them
``grid``    grid points edge-repeated; chi^2/fitted outputs sliced back
``pair``    zero-index pairs with ``wmask=False`` zero weights (the
            gw/os convention), inert in every weighted reduction
``walker``  **never padded** — stretch moves couple walkers, so a
            phantom walker would change real proposals; the ensemble
            size must divide the device count (raise, don't pad)
==========  ==============================================================

Sharding participates in every jit key through :func:`mesh_jit_key`
without breaking the zero-recompile contract: a mesh resolves to one
extra registry entry (a second same-shaped sharded call performs zero
new XLA compiles), and ``mesh=None`` keys exactly as before, so the
single-device program is bit-identical to the pre-mesh behavior.

Telemetry: ``mesh.sharded_calls`` counts :func:`shard_args`
invocations that actually placed data on a mesh;
``mesh.pad_waste_frac`` gauges the phantom-row overhead of the most
recent padded batch (see docs/telemetry.md).
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from pint_tpu import telemetry

__all__ = [
    "AXIS_NAMES", "make_mesh", "mesh_desc", "mesh_jit_key",
    "resolve_axis", "axis_size", "match_partition_rules",
    "named_tree_map", "tree_paths", "pad_to_multiple", "pad_leading",
    "record_pad_waste", "shard_args", "replicate",
]

#: the canonical batch axes of this codebase (a mesh may use any
#: subset, and other names are allowed for experiments)
AXIS_NAMES = ("pulsar", "grid", "walker", "pair")


# --------------------------------------------------------------------------
# mesh construction
# --------------------------------------------------------------------------

def make_mesh(axes="pulsar", n_devices=None, shape=None):
    """A device mesh with named axes.

    axes: one axis name or a sequence of names (``("pulsar", "grid")``
    for a 2-d mesh).  n_devices: cap on the devices used (default:
    all).  shape: per-axis device counts for multi-axis meshes; for a
    1-d mesh it defaults to every selected device.  The product of
    ``shape`` must equal the selected device count."""
    import jax
    from jax.sharding import Mesh

    if isinstance(axes, str):
        axes = (axes,)
    axes = tuple(axes)
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < int(n_devices):
            raise ValueError(
                f"make_mesh: asked for {n_devices} devices, have "
                f"{len(devs)}")
        devs = devs[: int(n_devices)]
    if shape is None:
        if len(axes) != 1:
            raise ValueError(
                "make_mesh: a multi-axis mesh needs an explicit shape")
        shape = (len(devs),)
    shape = tuple(int(s) for s in shape)
    if len(shape) != len(axes):
        raise ValueError(
            f"make_mesh: shape {shape} does not match axes {axes}")
    n = int(np.prod(shape))
    if n != len(devs):
        raise ValueError(
            f"make_mesh: shape {shape} needs {n} devices, selected "
            f"{len(devs)}")
    return Mesh(np.array(devs).reshape(shape), axes)


def mesh_desc(mesh) -> Optional[dict]:
    """Structured record of a mesh for bench metrics and the profiling
    program registry: ``{"devices": N, "axes": {name: size, ...}}``
    (None for no mesh)."""
    if mesh is None:
        return None
    return {
        "devices": int(mesh.devices.size),
        "axes": {str(name): int(size)
                 for name, size in zip(mesh.axis_names,
                                       mesh.devices.shape)},
    }


def mesh_jit_key(mesh) -> tuple:
    """The sharding part of a shared_jit key: ``()`` for no mesh (so
    single-device keys are unchanged from the pre-mesh layout), else a
    stable ``("mesh", ((axis, size), ...))`` tuple.  One mesh = one
    registry entry = zero new XLA compiles on the second same-shaped
    sharded call."""
    if mesh is None:
        return ()
    return ("mesh", tuple(
        (str(name), int(size))
        for name, size in zip(mesh.axis_names, mesh.devices.shape)))


def resolve_axis(mesh, axis: str) -> str:
    """The mesh axis a canonical axis name rides.  An exact name match
    wins; a 1-d mesh serves ANY axis under its own name (the gw/os
    contract: "the axis name is immaterial, pairs ride it", so a
    ``pulsar_mesh`` can shard the pair axis); a multi-axis mesh
    missing the name is an error — guessing which axis to ride would
    silently mis-shard."""
    names = tuple(str(n) for n in mesh.axis_names)
    if axis in names:
        return axis
    if len(names) == 1:
        return names[0]
    raise ValueError(
        f"mesh axes {names} do not include {axis!r}; name the axis "
        "explicitly when building a multi-axis mesh")


def axis_size(mesh, axis: str) -> int:
    """Device count along a (resolved) canonical axis; 1 for no mesh."""
    if mesh is None:
        return 1
    name = resolve_axis(mesh, axis)
    return int(mesh.devices.shape[list(
        str(n) for n in mesh.axis_names).index(name)])


# --------------------------------------------------------------------------
# key-path walking
# --------------------------------------------------------------------------

def _is_leaf(v):
    # arrays and scalars are leaves; containers recurse.  None is a
    # structural hole (absent tzr batch) — kept as a leaf so rebuilt
    # trees keep their shape, never matched against rules.  A
    # PartitionSpec is a tuple SUBCLASS but is a resolved rule, not a
    # container (match_partition_rules returns trees of them).
    if type(v).__name__ == "PartitionSpec":
        return True
    return not isinstance(v, (dict, list, tuple))


def _items(tree):
    """(key, child) pairs of one container level.  Dict keys and
    NamedTuple field names keep their names; plain sequences use
    indices — so a rule can say ``^batch/ticks`` instead of
    ``^2/0``."""
    if isinstance(tree, dict):
        return list(tree.items())
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return list(zip(tree._fields, tree))
    return list(enumerate(tree))


def _rebuild(tree, children):
    if isinstance(tree, dict):
        return type(tree)(zip([k for k, _ in _items(tree)], children))
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return type(tree)(*children)
    return type(tree)(children)


def named_tree_map(fn, tree, prefix=""):
    """Map ``fn(path, leaf) -> new_leaf`` over a pytree of
    dicts/(named)tuples/lists, with ``path`` the ``/``-joined key
    chain (the SNIPPETS.md [2] ``named_tree_map`` shape).  ``None``
    leaves pass through untouched."""
    if _is_leaf(tree):
        return tree if tree is None else fn(prefix, tree)
    children = [
        named_tree_map(fn, child,
                       f"{prefix}/{key}" if prefix else str(key))
        for key, child in _items(tree)
    ]
    return _rebuild(tree, children)


def tree_paths(tree) -> list:
    """Flattened ``(path, leaf)`` list (non-None leaves only)."""
    out = []

    def visit(path, leaf):
        out.append((path, leaf))
        return leaf

    named_tree_map(visit, tree)
    return out


# --------------------------------------------------------------------------
# the partition-rule table
# --------------------------------------------------------------------------

def replicate():
    """An explicitly-replicated PartitionSpec (``PS()``)."""
    from jax.sharding import PartitionSpec as PS

    return PS()


def _is_scalar_leaf(leaf) -> bool:
    shape = np.shape(leaf)
    return len(shape) == 0 or int(np.prod(shape)) == 1


def _rule_resolver(rules, overrides=None):
    """``resolve(path, leaf) -> PartitionSpec`` over a rule table.
    Overrides are consulted first (the per-call-site escape hatch);
    scalar and single-element leaves replicate without consulting the
    table (SNIPPETS.md [2]); any other unmatched leaf raises."""
    table = list(overrides or ()) + list(rules)
    compiled = [(re.compile(pat), spec) for pat, spec in table]

    def resolve(path, leaf):
        if _is_scalar_leaf(leaf):
            return replicate()
        for pat, spec in compiled:
            if pat.search(path) is not None:
                return replicate() if spec is None else spec
        raise ValueError(
            f"no partition rule matches data leaf {path!r} "
            f"(shape {np.shape(leaf)}); add a rule or an explicit "
            "replicate() entry — silent replication of a batch-axis "
            "array is how sharding bugs hide")

    return resolve


def match_partition_rules(rules, tree, *, overrides=None):
    """Resolve a rule table over a data pytree.

    rules / overrides: sequences of ``(pattern, PartitionSpec)``.
    Returns a same-structure pytree of PartitionSpecs (see
    :func:`_rule_resolver` for the matching semantics)."""
    return named_tree_map(_rule_resolver(rules, overrides), tree)


def _resolve_spec(mesh, spec):
    """A rule's PartitionSpec with canonical axis names mapped onto
    the mesh's real axes (:func:`resolve_axis`)."""
    from jax.sharding import PartitionSpec as PS

    parts = []
    for entry in spec:
        if entry is None:
            parts.append(None)
        elif isinstance(entry, (list, tuple)):
            parts.append(tuple(resolve_axis(mesh, a) for a in entry))
        else:
            parts.append(resolve_axis(mesh, str(entry)))
    return PS(*parts)


def shard_args(mesh, rules, tree, *, overrides=None):
    """Resolve the rule table over ``tree`` and ``device_put`` every
    leaf onto the mesh (NamedSharding).  ``mesh=None`` returns the
    tree unchanged — the single-device path stays bit-identical.

    Every sharded-axis length must already be a device-count multiple
    (use :func:`pad_to_multiple` / :func:`pad_leading` first); a
    non-divisible axis is reported with its path rather than jax's
    anonymous shape error."""
    if mesh is None:
        return tree
    import jax
    from jax.sharding import NamedSharding

    resolve = _rule_resolver(rules, overrides)
    sizes = dict(zip((str(n) for n in mesh.axis_names),
                     (int(s) for s in mesh.devices.shape)))

    def put(path, leaf):
        resolved = _resolve_spec(mesh, resolve(path, leaf))
        for dim, entry in enumerate(resolved):
            axes = (entry,) if isinstance(entry, str) else (entry or ())
            need = int(np.prod([sizes[a] for a in axes])) if axes else 1
            if need > 1 and np.shape(leaf)[dim] % need:
                raise ValueError(
                    f"leaf {path!r} axis {dim} (length "
                    f"{np.shape(leaf)[dim]}) is not a multiple of the "
                    f"{entry!r} mesh extent {need}; pad it first "
                    "(mesh.pad_to_multiple / pad_leading)")
        return jax.device_put(leaf, NamedSharding(mesh, resolved))

    out = named_tree_map(put, tree)
    telemetry.counter_add("mesh.sharded_calls")
    return out


# --------------------------------------------------------------------------
# pad-to-device-multiple helpers
# --------------------------------------------------------------------------

def pad_to_multiple(n: int, multiple: int) -> int:
    """Smallest count >= n divisible by ``multiple``."""
    n, multiple = int(n), max(1, int(multiple))
    return n + (-n) % multiple


def pad_leading(arr, n_target: int, mode: str = "edge", fill=None):
    """Pad an array's leading axis up to ``n_target`` rows.

    mode="edge" repeats the final row (the TOA-axis convention of
    ``parallel/pta._pad_batch`` — a clone is always finite);
    mode="zero" appends zeros (inert under zero-weight masking);
    ``fill=`` overrides with a constant (the gw/os pair-index
    convention, e.g. ``jj`` pads with 1 so pad pairs stay valid
    index pairs)."""
    import jax.numpy as jnp

    arr = jnp.asarray(arr)
    pad = int(n_target) - arr.shape[0]
    if pad < 0:
        raise ValueError(
            f"pad_leading: target {n_target} < length {arr.shape[0]}")
    if pad == 0:
        return arr
    if fill is not None:
        tail = jnp.full((pad,) + arr.shape[1:], fill, dtype=arr.dtype)
    elif mode == "edge":
        tail = jnp.repeat(arr[-1:], pad, axis=0)
    elif mode == "zero":
        tail = jnp.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
    else:
        raise ValueError(f"pad_leading: unknown mode {mode!r}")
    return jnp.concatenate([arr, tail], axis=0)


def record_pad_waste(axis: str, n_real: int, n_padded: int):
    """Telemetry for phantom-row overhead: the fraction of the padded
    batch that is padding (``mesh.pad_waste_frac`` gauge — the most
    recent sharded batch, honestly 0.0 when it needed no padding;
    ``mesh.pad_rows`` counter, cumulative)."""
    n_real, n_padded = int(n_real), int(n_padded)
    frac = 0.0 if n_padded <= 0 else (n_padded - n_real) / n_padded
    telemetry.gauge_set("mesh.pad_waste_frac", round(frac, 6))
    telemetry.gauge_set(f"mesh.pad_waste_frac.{axis}", round(frac, 6))
    if n_padded > n_real:
        telemetry.counter_add("mesh.pad_rows", float(n_padded - n_real))
    return frac
