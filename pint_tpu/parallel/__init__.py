"""Multi-pulsar / multi-device parallelism.

The domain's parallel axes (SURVEY section 2.9) are embarrassingly
parallel: pulsars, chi^2-grid points, MCMC walkers.  This package maps
the pulsar axis onto a ``jax.sharding.Mesh`` — the PTA-scale analogue
of data parallelism — with the TOA axis as the inner (vectorized)
dimension; XLA inserts the collectives for the normal-equation
reductions.
"""

from pint_tpu.parallel.pta import PTABatch, pulsar_mesh  # noqa: F401
