"""Multi-pulsar / multi-device parallelism.

The domain's parallel axes (SURVEY section 2.9) are embarrassingly
parallel: pulsars, chi^2-grid points, MCMC walkers.  This package maps
the pulsar axis onto a ``jax.sharding.Mesh`` — the PTA-scale analogue
of data parallelism — with the TOA axis as the inner (vectorized)
dimension; XLA inserts the collectives for the normal-equation
reductions.
"""

from pint_tpu.parallel.mesh import (  # noqa: F401
    AXIS_NAMES, RowShard, distributed_init, make_mesh,
    match_partition_rules, mesh_desc, mesh_jit_key, pad_leading,
    pad_to_multiple, process_topology, shard_args, shard_toa_data,
    toa_epochs_aligned, toa_shard_plan)
from pint_tpu.parallel.pta import (  # noqa: F401
    PTA_BATCH_RULES, PTA_GRID_RULES, PTABatch, pulsar_mesh)
