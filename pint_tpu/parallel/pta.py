"""PTA-scale batching: many pulsars, one device program.

Counterpart of the reference's only multi-pulsar story — process-pool
fan-out over independent fits (reference: gridutils.py:166-391 and the
event_optimize_multiple script) — redesigned for the accelerator: the
per-pulsar WLS/GLS Gauss-Newton step is ``vmap``-ped over a padded
pulsar axis and sharded over a ``jax.sharding.Mesh``, so a whole-array
fit is ONE XLA program whose pulsar axis rides ICI (BASELINE config 4,
the 68-pulsar batch).

Padding strategy (SURVEY section 7 hard part #3): every pulsar must be
built with the same component-structure superset (same component
classes, same free-parameter names — build the pars accordingly); the
TOA axis is padded to the batch maximum with zero-weight entries, which
drop out of every weighted reduction exactly.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitter import wls_gn_solve
from pint_tpu.models.timing_model import PreparedModel
from pint_tpu.residuals import Residuals

__all__ = ["PTABatch", "pulsar_mesh"]


def pulsar_mesh(n_devices=None):
    """A 1-d device mesh over the 'pulsar' axis."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices() if n_devices is None
                    else jax.devices()[:n_devices])
    return Mesh(devs, ("pulsar",))


def _pad_batch(batch, n_max):
    """Pad every TOA-axis array of a TOABatch to n_max by repeating the
    final entry (padded entries get zero weight downstream)."""
    n = batch.ticks.shape[0]
    pad = n_max - n

    def pad_arr(a, axis=0):
        if pad == 0:
            return a
        idx = [slice(None)] * a.ndim
        idx[axis] = slice(-1, None)
        tail = jnp.repeat(a[tuple(idx)], pad, axis=axis)
        return jnp.concatenate([a, tail], axis=axis)

    return type(batch)(
        ticks=pad_arr(batch.ticks),
        freq_mhz=pad_arr(batch.freq_mhz),
        error_s=pad_arr(batch.error_s),
        ssb_obs_pos=pad_arr(batch.ssb_obs_pos),
        ssb_obs_vel=pad_arr(batch.ssb_obs_vel),
        obs_sun_pos=pad_arr(batch.obs_sun_pos),
        # (n_bodies, N, 3) — pad the TOA axis even when n_bodies == 0,
        # else ragged batches stack with mismatched trailing shapes
        planet_pos=pad_arr(batch.planet_pos, axis=1),
    )


#: ctx keys holding fixed-shape per-model constants (never TOA-axis
#: arrays); they must not be padded even when a dimension happens to
#: equal the TOA count (e.g. a (3,3) rotation matrix with n=3 TOAs)
_STATIC_SHAPE_CTX_KEYS = {"eq_from_ecl"}


def _pad_ctx(ctx_map, n, n_max):
    """Pad prepare()-time arrays whose trailing/leading axis is the TOA
    axis.  Non-array entries (static python values) pass through."""
    out = {}
    for comp, ctx in ctx_map.items():
        c = {}
        for k, v in ctx.items():
            if not hasattr(v, "shape") or k in _STATIC_SHAPE_CTX_KEYS:
                c[k] = v
                continue
            v = jnp.asarray(v)
            if v.ndim >= 1 and v.shape[-1] == n:
                pad = n_max - n
                if pad:
                    tail = jnp.repeat(v[..., -1:], pad, axis=-1)
                    v = jnp.concatenate([v, tail], axis=-1)
            elif v.ndim >= 1 and v.shape[0] == n:
                pad = n_max - n
                if pad:
                    tail = jnp.repeat(v[:1] * 0 + v[-1:], pad, axis=0)
                    v = jnp.concatenate([v, tail], axis=0)
            c[k] = v
        out[comp] = c
    return out


def _stack_ctxs(ctxs):
    """Split component ctx dicts into (stacked array part, static
    part).  Array leaves gain a leading pulsar axis; non-array leaves
    (tuples, ints — static jit structure) must agree across pulsars and
    stay python values, closed over rather than vmapped."""
    arrays = {}
    static = {}
    for comp in ctxs[0]:
        a, s = {}, {}
        for k, v0 in ctxs[0][comp].items():
            vals = [c[comp][k] for c in ctxs]
            if hasattr(v0, "shape") and getattr(v0, "ndim", 0) >= 0 \
                    and not isinstance(v0, (tuple, int, float, bool)):
                a[k] = jnp.stack([jnp.asarray(v) for v in vals])
            else:
                if any(v != v0 for v in vals[1:]):
                    raise ValueError(
                        f"static ctx entry {comp}.{k} differs across "
                        f"pulsars ({set(map(repr, vals))}) — the batch "
                        "requires identical static structure"
                    )
                s[k] = v0
        arrays[comp] = a
        static[comp] = s
    return arrays, static


def _merge_ctx(arrays, static):
    return {
        comp: {**static.get(comp, {}), **arrays[comp]}
        for comp in arrays
    }


class PTABatch:
    """A batch of independently-fit pulsars evaluated as one program.

    pairs: [(TimingModel, TOAs), ...].  All models must share the same
    component structure and the same free-parameter name list.
    """

    def __init__(self, pairs: Sequence[Tuple]):
        if not pairs:
            raise ValueError("empty PTA batch")
        self.prepareds: List[PreparedModel] = []
        self.resids: List[Residuals] = []
        for model, toas in pairs:
            prep = model.prepare(toas)
            self.prepareds.append(prep)
            self.resids.append(Residuals(toas, prep))
        names0 = tuple(self.prepareds[0].model.free_params)
        structs = {
            tuple(type(c).__name__
                  for c in p.model.components)
            for p in self.prepareds
        }
        if len(structs) != 1:
            raise ValueError(
                "PTA batch needs identical component structure per "
                f"pulsar; got {structs} — build the pars from a common "
                "superset (SURVEY hard part #3)"
            )
        for p in self.prepareds:
            if tuple(p.model.free_params) != names0:
                raise ValueError(
                    "PTA batch needs identical free-parameter lists; "
                    f"{p.model.name} differs"
                )
        self.free_names = list(names0)
        self.n_pulsars = len(self.prepareds)
        self.n_max = max(
            p.batch.ticks.shape[0] for p in self.prepareds
        )
        self.n_toas = jnp.asarray(
            [p.batch.ticks.shape[0] for p in self.prepareds]
        )

        # stack padded batches / ctx / values — one pytree with a
        # leading pulsar axis
        batches = [
            _pad_batch(p.batch, self.n_max) for p in self.prepareds
        ]
        self.batch = jax.tree.map(
            lambda *xs: jnp.stack(xs), *batches
        )
        ctxs = [
            _pad_ctx(p.ctx, p.batch.ticks.shape[0], self.n_max)
            for p in self.prepareds
        ]
        self.ctx, self.static_ctx = _stack_ctxs(ctxs)
        tzr = [p.tzr_batch for p in self.prepareds]
        if all(t is not None for t in tzr):
            self.tzr_batch = jax.tree.map(
                lambda *xs: jnp.stack(xs), *tzr
            )
            self.tzr_ctx, self.static_tzr_ctx = _stack_ctxs(
                [p.tzr_ctx for p in self.prepareds]
            )
        else:
            self.tzr_batch = None
            self.tzr_ctx = None
            self.static_tzr_ctx = {}
        # padded-TOA validity mask
        self.valid = (
            jnp.arange(self.n_max)[None, :] < self.n_toas[:, None]
        )
        self.values0 = jnp.stack(
            [p.values_to_vector() for p in self.prepareds]
        )
        self._full_values = [
            p._values_pytree() for p in self.prepareds
        ]
        self.base_values = jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *self._full_values,
        )

    # -- single-pulsar pure functions (vmapped below) -------------------------
    def _resid_one(self, vec, base_values, batch, ctx, tzr_batch,
                   tzr_ctx, valid):
        p0 = self.prepareds[0]
        values = dict(base_values)
        for i, name in enumerate(self.free_names):
            values[name] = vec[i]
        ctx = _merge_ctx(ctx, self.static_ctx)
        n, frac = p0._phase_sum(values, batch, ctx)
        if tzr_batch is not None:
            tzr_ctx = _merge_ctx(tzr_ctx, self.static_tzr_ctx)
            tn, tfrac = p0._phase_sum(values, tzr_batch, tzr_ctx)
            n = n - tn[0]
            frac = frac - tfrac[0]
        from pint_tpu import fixedpoint as fp

        _, frac = fp.renorm_phase(n, frac)
        resid = frac / values["F0"]
        # weighted mean over valid TOAs only, with EFAC/EQUAD-scaled
        # weights (matching Residuals/WLSFitter semantics)
        sigma = self._sigma_one(values, batch, ctx)
        w = jnp.where(valid, 1.0 / sigma**2, 0.0)
        mean = jnp.sum(resid * w) / jnp.sum(w)
        return jnp.where(valid, resid - mean, 0.0)

    def _sigma_one(self, values, batch, ctx):
        """Noise-scaled per-TOA sigma for ONE pulsar's (batch, ctx) —
        the pure-function form of PreparedModel.scaled_sigma_fn (which
        is bound to its own dataset)."""
        p0 = self.prepareds[0]
        sigma = batch.error_s
        for c in p0.model.noise_components:
            f = getattr(c, "scaled_sigma", None)
            if f is not None:
                sigma = f(values, batch, ctx[type(c).__name__], sigma)
        return sigma

    def _fit_one(self, vec0, base_values, batch, ctx, tzr_batch,
                 tzr_ctx, valid, maxiter):
        merged = _merge_ctx(ctx, self.static_ctx)
        values0 = dict(base_values)
        for i, name in enumerate(self.free_names):
            values0[name] = vec0[i]
        sigma = self._sigma_one(values0, batch, merged)
        err = jnp.where(valid, sigma, 1e30)

        def resid_fn(v):
            return self._resid_one(
                v, base_values, batch, ctx, tzr_batch, tzr_ctx, valid
            )

        def body(carry, _):
            vec, _ = carry
            new_vec, chi2, dpar, cov = wls_gn_solve(resid_fn, vec, err)
            return (new_vec, chi2), None

        (vec, _), _ = jax.lax.scan(
            body, (vec0, jnp.float64(0.0)), None, length=maxiter
        )
        _, chi2, _, cov = wls_gn_solve(resid_fn, vec, err)
        return vec, chi2, cov

    # -- public API -----------------------------------------------------------
    def residuals(self, values=None):
        """(n_pulsars, n_max) padded time residuals, zero where
        invalid."""
        vals = self.values0 if values is None else values
        f = jax.vmap(self._resid_one,
                     in_axes=(0, 0, 0, 0,
                              0 if self.tzr_batch is not None else None,
                              0 if self.tzr_ctx is not None else None,
                              0))
        return f(vals, self.base_values, self.batch, self.ctx,
                 self.tzr_batch, self.tzr_ctx, self.valid)

    def fit_wls(self, maxiter=3, mesh=None):
        """Batched WLS Gauss-Newton fit of every pulsar; returns
        (fitted_values (k, P), chi2 (k,), cov (k, P, P)).

        With a mesh, the pulsar axis is sharded over devices
        (NamedSharding) — the multi-chip path the driver dry-runs."""
        fit = jax.vmap(
            lambda v, b, bt, c, tb, tc, m: self._fit_one(
                v, b, bt, c, tb, tc, m, maxiter
            ),
            in_axes=(0, 0, 0, 0,
                     0 if self.tzr_batch is not None else None,
                     0 if self.tzr_ctx is not None else None,
                     0),
        )
        args = (self.values0, self.base_values, self.batch, self.ctx,
                self.tzr_batch, self.tzr_ctx, self.valid)
        if mesh is None:
            out = jax.jit(
                lambda *a: fit(*a)
            )(*args)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            shard = NamedSharding(mesh, P("pulsar"))
            rep = NamedSharding(mesh, P())

            def shard_tree(tree):
                return jax.tree.map(
                    lambda x: jax.device_put(
                        x, shard if hasattr(x, "ndim") and x.ndim >= 1
                        and x.shape[0] == self.n_pulsars else rep
                    ),
                    tree,
                )

            args = tuple(
                shard_tree(a) if a is not None else None for a in args
            )
            out = jax.jit(lambda *a: fit(*a))(*args)
        vec, chi2, cov = out
        # write back per-pulsar values
        vec_np = np.asarray(vec)
        for k, p in enumerate(self.prepareds):
            for i, name in enumerate(self.free_names):
                p.model.values[name] = float(vec_np[k, i])
        return vec, chi2, cov

    @property
    def dof(self):
        return np.asarray(self.n_toas) - len(self.free_names) - 1