"""PTA-scale batching: many pulsars, one device program.

Counterpart of the reference's only multi-pulsar story — process-pool
fan-out over independent fits (reference: gridutils.py:166-391 and the
event_optimize_multiple script) — redesigned for the accelerator: the
per-pulsar WLS/GLS Gauss-Newton step is ``vmap``-ped over a padded
pulsar axis and sharded over a ``jax.sharding.Mesh``, so a whole-array
fit is ONE XLA program whose pulsar axis rides ICI (BASELINE config 4,
the 68-pulsar batch).

Padding strategy (SURVEY section 7 hard part #3): every pulsar must be
built with the same component-structure superset (same component
classes, same free-parameter names — build the pars accordingly); the
TOA axis is padded to the batch maximum with zero-weight entries, which
drop out of every weighted reduction exactly.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import flops as _flops
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.compile_cache import merge_ctx as _merge_ctx
from pint_tpu.fitter import wls_gn_solve
from pint_tpu.models.timing_model import PreparedModel
from pint_tpu.parallel import mesh as _mesh
from pint_tpu.residuals import Residuals
from pint_tpu.telemetry import span

__all__ = ["PTABatch", "pulsar_mesh", "PTA_BATCH_RULES",
           "PTA_GRID_RULES"]


def pulsar_mesh(n_devices=None):
    """A 1-d device mesh over the 'pulsar' axis
    (:func:`pint_tpu.parallel.mesh.make_mesh`).  Historical clamping
    semantics kept: asking for more devices than exist returns a mesh
    over what is available (``jax.devices()[:n]``), it does not raise
    — a pod-sized count in a laptop smoke run must still work."""
    if n_devices is not None:
        n_devices = min(int(n_devices), len(jax.devices()))
    return _mesh.make_mesh("pulsar", n_devices=n_devices)


from jax.sharding import PartitionSpec as _P

#: the batched-fit partition-rule table: every argument of the vmapped
#: fit carries a leading pulsar axis (the stacked data pytree), so
#: each named root maps to ``PS('pulsar')``; scalars (guard_eps)
#: replicate by the scalar rule.  Named per root rather than one
#: ``.*`` catch-all so a future non-batched argument fails loudly
#: instead of riding the pulsar axis by accident.
PTA_BATCH_RULES = (
    (r"^(values0|base_values|valid|free_mask)(/|$)", _P("pulsar")),
    (r"^(batch|ctx|tzr_batch|tzr_ctx)(/|$)", _P("pulsar")),
    (r"^(U|phi|dm_data|dm_error|dm_valid)(/|$)", _P("pulsar")),
    (r"^guard_eps$", None),
)

#: the 2-D pulsar x grid scan table (PTABatch.chisq_grid): grid-point
#: values ride the ``grid`` mesh axis, every stacked per-pulsar leaf
#: rides ``pulsar`` — BOTH axes resolve over the same data pytree, so
#: a full-PTA hyperparameter scan runs as ONE program on a 2-D mesh
PTA_GRID_RULES = ((r"^grid_values$", _P("grid")),) + PTA_BATCH_RULES


def _pad_batch(batch, n_max):
    """Pad every TOA-axis array of a TOABatch to n_max by repeating the
    final entry (padded entries get zero weight downstream)."""
    n = batch.ticks.shape[0]
    pad = n_max - n

    def pad_arr(a, axis=0):
        if pad == 0:
            return a
        idx = [slice(None)] * a.ndim
        idx[axis] = slice(-1, None)
        tail = jnp.repeat(a[tuple(idx)], pad, axis=axis)
        return jnp.concatenate([a, tail], axis=axis)

    return type(batch)(
        ticks=pad_arr(batch.ticks),
        freq_mhz=pad_arr(batch.freq_mhz),
        error_s=pad_arr(batch.error_s),
        ssb_obs_pos=pad_arr(batch.ssb_obs_pos),
        ssb_obs_vel=pad_arr(batch.ssb_obs_vel),
        obs_sun_pos=pad_arr(batch.obs_sun_pos),
        # (n_bodies, N, 3) — pad the TOA axis even when n_bodies == 0,
        # else ragged batches stack with mismatched trailing shapes
        planet_pos=pad_arr(batch.planet_pos, axis=1),
    )


#: ctx keys holding fixed-shape per-model constants (never TOA-axis
#: arrays); they must not be padded even when a dimension happens to
#: equal the TOA count (e.g. a (3,3) rotation matrix with n=3 TOAs)
_STATIC_SHAPE_CTX_KEYS = {"eq_from_ecl"}


def _pad_ctx(ctx_map, n, n_max):
    """Pad prepare()-time arrays whose trailing/leading axis is the TOA
    axis.  Non-array entries (static python values) pass through."""
    out = {}
    for comp, ctx in ctx_map.items():
        c = {}
        for k, v in ctx.items():
            if not hasattr(v, "shape") or k in _STATIC_SHAPE_CTX_KEYS:
                c[k] = v
                continue
            v = jnp.asarray(v)
            if v.ndim >= 1 and v.shape[-1] == n:
                pad = n_max - n
                if pad:
                    tail = jnp.repeat(v[..., -1:], pad, axis=-1)
                    v = jnp.concatenate([v, tail], axis=-1)
            elif v.ndim >= 1 and v.shape[0] == n:
                pad = n_max - n
                if pad:
                    tail = jnp.repeat(v[:1] * 0 + v[-1:], pad, axis=0)
                    v = jnp.concatenate([v, tail], axis=0)
            c[k] = v
        out[comp] = c
    return out


def _pad_to_shape(arr, shape):
    """Zero-pad an array up to the target shape (every axis)."""
    arr = jnp.asarray(arr)
    if tuple(arr.shape) == tuple(shape):
        return arr
    pads = [(0, t - s) for s, t in zip(arr.shape, shape)]
    return jnp.pad(arr, pads)


def _stack_ctxs(ctxs):
    """Split component ctx dicts into (stacked array part, static
    part).  Array leaves gain a leading pulsar axis; non-array leaves
    (tuples, ints — static jit structure) must agree across pulsars and
    stay python values, closed over rather than vmapped.

    Array leaves whose shapes differ across pulsars (heterogeneous
    noise structure: ECORR epoch counts, Fourier mode counts, mask
    lists) are zero-padded to the per-key elementwise maximum shape —
    zero rows/columns are inert in every mask/basis use."""
    arrays = {}
    static = {}
    for comp in ctxs[0]:
        a, s = {}, {}
        for k, v0 in ctxs[0][comp].items():
            vals = [c[comp][k] for c in ctxs]
            if hasattr(v0, "shape") and not isinstance(
                    v0, (tuple, int, float, bool)):
                shapes = [tuple(np.shape(v)) for v in vals]
                if len(set(len(sh) for sh in shapes)) == 1 \
                        and len(set(shapes)) > 1:
                    target = tuple(max(sh[i] for sh in shapes)
                                   for i in range(len(shapes[0])))
                    vals = [_pad_to_shape(v, target) for v in vals]
            if hasattr(v0, "shape") and getattr(v0, "ndim", 0) >= 0 \
                    and not isinstance(v0, (tuple, int, float, bool)):
                a[k] = jnp.stack([jnp.asarray(v) for v in vals])
            else:
                def _differs(a, b):
                    if a != a and b != b:  # NaN == NaN here (e.g. the
                        return False       # TZR PLRedNoise df sentinel)
                    return a != b

                if any(_differs(v, v0) for v in vals[1:]):
                    # static entries that differ per pulsar are used
                    # only by host-side weights()/basis() construction
                    # (ECORR epoch 'counts', red-noise 'df'); the
                    # batched trace never reads them — drop the key so
                    # a trace that DOES need it fails loudly
                    import warnings

                    warnings.warn(
                        f"per-pulsar static ctx entry {comp}.{k} "
                        "dropped from the batched ctx (host-side "
                        "noise-basis metadata)")
                    continue
                s[k] = v0
        arrays[comp] = a
        static[comp] = s
    return arrays, static


# ctx reassembly is shared with the single-fitter path
# (compile_cache.merge_ctx) so the two split/merge rules cannot drift


#: placeholder values for parameters whose neutral default would divide
#: by zero, produce NaN, or inject variance when a superset component
#: is inert.  Log-amplitude noise params MUST go to a deeply negative
#: value: 0.0 would mean amplitude 10^0 and flood the GLS with ~1e12 s^2
#: of spurious red-noise variance (the __gate__ mechanism covers only
#: delay/phase contributions, not noise bases).
_SUPERSET_PLACEHOLDERS = {
    "PB": 365.25, "T0": 0.0, "TASC": 0.0,
    "TNREDAMP": -100.0, "TNDMAMP": -100.0, "TNCHROMAMP": -100.0,
}


def make_superset_models(pairs):
    """Rebuild every (model, toas) pair onto the union of component
    classes (SURVEY §7 hard part #3): a pulsar missing a component gets
    it with *neutral* values (A1=0 binary contributes zero delay, zero
    glitch amplitudes, empty masks...), all its parameters frozen, so
    an ELL1 + DD + DDK + isolated mix traces as ONE jit program.

    Components whose neutral value would be singular (DDK: 0/tan(KIN)
    at KIN=0) declare ``neutral_overrides`` — the prepare-time 0/1 gate
    zeroes their delay, but the traced graph must stay NaN-free since
    gate * NaN = NaN."""
    import copy

    # donors: one representative instance per component class — copied
    # (not re-built) so per-instance config (glitch indices, FB terms,
    # mask selects) and therefore the values-pytree KEYS are identical
    # across every pulsar in the batch
    donors: dict = {}
    order: List = []
    for model, _ in pairs:
        for c in model.components:
            cls = type(c)
            if cls not in order:
                order.append(cls)
                donors[cls] = c
            elif len(c.params) > len(donors[cls].params):
                donors[cls] = c  # widest family wins
    out = []
    for model, toas in pairs:
        model = copy.deepcopy(model)
        have = {type(c) for c in model.components}
        inert = set()
        for cls in order:
            if cls in have:
                # same class but a narrower family than the donor
                # (fewer glitches, fewer FB terms) still needs key
                # alignment: add the donor's missing params, frozen,
                # at neutral values
                mine = model.component(cls.__name__)
                mine_names = {p.name for p in mine.params}
                for p in donors[cls].params:
                    if p.name not in mine_names:
                        q = copy.deepcopy(p)
                        q.frozen = True
                        mine.add_param(q)
                        model.values.setdefault(
                            p.name,
                            _SUPERSET_PLACEHOLDERS.get(p.name, 0.0))
                continue
            comp = copy.deepcopy(donors[cls])
            model.add_component(comp)  # fills values with defaults
            inert.add(cls.__name__)
            for p in comp.params:
                p.frozen = True
                cur = model.values.get(p.name, np.nan)
                if cur != cur:  # NaN default (e.g. PB) -> placeholder
                    model.values[p.name] = _SUPERSET_PLACEHOLDERS.get(
                        p.name, 0.0)
            # singular-at-zero neutrals (DDK KIN): the gate zeroes the
            # delay but NaN would survive gate multiplication
            for name, val in getattr(comp, "neutral_overrides",
                                     {}).items():
                model.values[name] = val
        # added components must be INERT despite sharing parameter
        # names (PB/A1/...) with the pulsar's real binary: prepare()
        # attaches a 0/1 gate per component (timing_model.py)
        model._superset_inert = inert
        # deterministic order: same-category ties (two binary families)
        # would otherwise keep per-model insertion order and defeat the
        # identical-structure requirement
        from pint_tpu.models.timing_model import DEFAULT_ORDER

        cat_order = {cat: i for i, cat in enumerate(DEFAULT_ORDER)}
        model.components.sort(
            key=lambda c: (cat_order.get(c.category, 99),
                           type(c).__name__))
        out.append((model, toas))
    return out


class PTABatch:
    """A batch of independently-fit pulsars evaluated as one program.

    pairs: [(TimingModel, TOAs), ...].  All models must share the same
    component structure and the same free-parameter name list.
    """

    def __init__(self, pairs: Sequence[Tuple], heterogeneous=True):
        if not pairs:
            raise ValueError("empty PTA batch")
        # structural identity = component classes AND parameter names:
        # two pulsars can share classes but differ in family widths
        # (glitch counts, FB terms) — those need superset alignment too
        structs = {
            (tuple(type(c).__name__ for c in model.components),
             tuple(sorted(model.params)))
            for model, _ in pairs
        }
        if len(structs) != 1:
            if not heterogeneous:
                raise ValueError(
                    "PTA batch needs identical component structure per "
                    f"pulsar; got {len(structs)} distinct structures — "
                    "pass heterogeneous=True for automatic superset "
                    "construction"
                )
            pairs = make_superset_models(pairs)
        prepareds: List[PreparedModel] = []
        resids: List[Residuals] = []
        for model, toas in pairs:
            prep = model.prepare(toas)
            prepareds.append(prep)
            resids.append(Residuals(toas, prep,
                                    track_mode="nearest"))
        self._init_from_prepared(prepareds, resids)

    @classmethod
    def from_prepared(cls, prepareds: Sequence[PreparedModel],
                      resids: Sequence[Residuals]) -> "PTABatch":
        """Build a batch over ALREADY-prepared pulsars, skipping the
        ``model.prepare(toas)`` pass — the serving fast path
        (:mod:`pint_tpu.serve`): a warm replica caches one
        (PreparedModel, Residuals) pair per dataset and stacks a fresh
        batch per coalesced flush, so the per-flush host cost is the
        stacking alone, never a re-prepare.

        Members must share identical component structure and
        free-parameter names (the serving layer groups by structure
        fingerprint; this constructor does NOT run the superset
        alignment of ``__init__``).  The same prepared pair may appear
        several times (occupancy padding) — stacking only reads its
        arrays."""
        self = cls.__new__(cls)
        self._init_from_prepared(list(prepareds), list(resids))
        return self

    def _init_from_prepared(self, prepareds, resids):
        """Shared tail of construction: everything downstream of the
        per-pulsar prepare step (free-union, padding, stacking)."""
        self.prepareds = prepareds
        self.resids = resids
        # free parameters: the union across pulsars, with a per-pulsar
        # 0/1 mask; a parameter outside a pulsar's own free list stays
        # pinned at that pulsar's value (its design column is exactly
        # zero, so the SVD-thresholded solve ignores it)
        union: List[str] = []
        for p in self.prepareds:
            for n in p.model.free_params:
                if n not in union:
                    union.append(n)
        self.free_names = union
        self.free_mask = jnp.asarray(np.array([
            [1.0 if n in p.model.free_params else 0.0 for n in union]
            for p in self.prepareds
        ]))
        self.n_pulsars = len(self.prepareds)
        # hybrid design partition over the union free set (shared model
        # structure, so one partition serves every pulsar); pinned
        # params get their analytic columns zeroed by free_mask in the
        # trace.  Frozen-delay precompute is NOT applied on the batched
        # path: per-pulsar frozen leaves would need their own stacking
        # rule, and the union free set usually keeps the chain live.
        from pint_tpu.models.timing_model import hybrid_design_default

        p0 = self.prepareds[0]
        if hybrid_design_default():
            self._partition = p0.design_partition(self.free_names)
            self._partition_wb = p0.design_partition(self.free_names,
                                                     wideband=True)
        else:
            self._partition = self._partition_wb = \
                ((), tuple(self.free_names))
        self.n_max = max(
            p.batch.ticks.shape[0] for p in self.prepareds
        )
        self.n_toas = jnp.asarray(
            [p.batch.ticks.shape[0] for p in self.prepareds]
        )

        # stack padded batches / ctx / values — one pytree with a
        # leading pulsar axis (fault injection at the same host
        # boundary the single-pulsar path uses, per-pulsar targeted)
        from pint_tpu import faults as _faults

        batches = [
            _pad_batch(
                _faults.corrupt_batch(p.batch, member=k)
                if _faults.any_active() else p.batch, self.n_max)
            for k, p in enumerate(self.prepareds)
        ]
        self.batch = jax.tree.map(
            lambda *xs: jnp.stack(xs), *batches
        )
        # harmonize the static Kepler Newton depth across the batch:
        # the stacked trace closes over ONE python int per component
        # key, and a per-pulsar class mismatch (one circular MSP, one
        # e=0.7 binary) would otherwise drop the key from the static
        # ctx — deepening the shallow members to the batch max is
        # exact, just marginally slower for them
        depth = max((sub["kepler_iters"]
                     for p in self.prepareds
                     for m in (p.ctx, p.tzr_ctx) if m
                     for sub in m.values()
                     if isinstance(sub, dict) and "kepler_iters" in sub),
                    default=0)
        if depth:
            for p in self.prepareds:
                for m in (p.ctx, p.tzr_ctx):
                    if not m:
                        continue
                    for sub in m.values():
                        if isinstance(sub, dict) and "kepler_iters" in sub:
                            sub["kepler_iters"] = depth
        ctxs = [
            _pad_ctx(p.ctx, p.batch.ticks.shape[0], self.n_max)
            for p in self.prepareds
        ]
        self.ctx, self.static_ctx = _stack_ctxs(ctxs)
        tzr = [p.tzr_batch for p in self.prepareds]
        if all(t is not None for t in tzr):
            self.tzr_batch = jax.tree.map(
                lambda *xs: jnp.stack(xs), *tzr
            )
            self.tzr_ctx, self.static_tzr_ctx = _stack_ctxs(
                [p.tzr_ctx for p in self.prepareds]
            )
        else:
            self.tzr_batch = None
            self.tzr_ctx = None
            self.static_tzr_ctx = {}
        # padded-TOA validity mask
        self.valid = (
            jnp.arange(self.n_max)[None, :] < self.n_toas[:, None]
        )
        self.values0 = jnp.asarray(np.array([
            [float(p.model.values[n]) for n in self.free_names]
            for p in self.prepareds
        ]))
        self.base_values = self._stack_values()

    def _stack_values(self):
        """Stacked per-pulsar values pytree ({name: (k,) row}), built
        host-side in ONE numpy pass: values are python floats, and
        stacking ~30 params x k members through eager per-scalar
        ``jnp.float64``/``jnp.stack`` dispatches costs tens of ms per
        batch build — the serving hot path builds a batch per flush."""
        return {
            name: jnp.asarray(np.array(
                [float(p.model.values[name]) for p in self.prepareds],
                dtype=np.float64))
            for name in self.prepareds[0].model.values
        }

    # -- single-pulsar pure functions (vmapped below) -------------------------
    def _values_at(self, vec_or_sub, base_values, free_mask):
        """The per-pulsar values dict at a free-parameter vector (or a
        {name: value} dict): masked-out params stay pinned at this
        pulsar's own value, making their design columns exactly zero."""
        values = dict(base_values)
        for i, name in enumerate(self.free_names):
            v = (vec_or_sub[name] if isinstance(vec_or_sub, dict)
                 else vec_or_sub[i])
            values[name] = jnp.where(free_mask[i], v,
                                     base_values[name])
        return values

    def _resid_one_values(self, values, batch, ctx, tzr_batch,
                          tzr_ctx, valid):
        """Mean-subtracted, pad-masked time residuals for one pulsar at
        a prebuilt values dict (the core both the residual function and
        the hybrid design build evaluate)."""
        p0 = self.prepareds[0]
        ctx = _merge_ctx(ctx, self.static_ctx)
        n, frac = p0._phase_sum(values, batch, ctx)
        if tzr_batch is not None:
            tzr_ctx = _merge_ctx(tzr_ctx, self.static_tzr_ctx)
            tn, tfrac = p0._phase_sum(values, tzr_batch, tzr_ctx)
            n = n - tn[0]
            frac = frac - tfrac[0]
        from pint_tpu import fixedpoint as fp

        _, frac = fp.renorm_phase(n, frac)
        resid = frac / values["F0"]
        # weighted mean over valid TOAs only, with EFAC/EQUAD-scaled
        # weights (matching Residuals/WLSFitter semantics)
        sigma = self._sigma_one(values, batch, ctx)
        w = jnp.where(valid, 1.0 / sigma**2, 0.0)
        mean = jnp.sum(resid * w) / jnp.sum(w)
        return jnp.where(valid, resid - mean, 0.0)

    def _resid_one(self, vec, base_values, batch, ctx, tzr_batch,
                   tzr_ctx, valid, free_mask):
        return self._resid_one_values(
            self._values_at(vec, base_values, free_mask), batch, ctx,
            tzr_batch, tzr_ctx, valid)

    def _linear_cols_one(self, values, batch, ctx, tzr_batch, tzr_ctx,
                         valid, free_mask, lin):
        """Closed-form (n_max, L) time-residual design columns for one
        pulsar — the batched counterpart of Residuals.linear_design_at:
        TZR column subtraction, /F0, the valid-masked weighted mean,
        pad-row zeroing, and the free-mask pinning (a masked-out
        parameter's column is exactly zero, same as the jacfwd of the
        ``where``-pinned residual)."""
        p0 = self.prepareds[0]
        merged = _merge_ctx(ctx, self.static_ctx)
        cols = p0.linear_phase_columns(values, batch, merged, lin)
        if tzr_batch is not None:
            tz = _merge_ctx(tzr_ctx, self.static_tzr_ctx)
            tcols = p0.linear_phase_columns(values, tzr_batch, tz, lin)
            cols = cols - tcols[0:1, :]
        cols = cols / values["F0"]
        sigma = self._sigma_one(values, batch, merged)
        w = jnp.where(valid, 1.0 / sigma**2, 0.0)
        cols = cols - jnp.sum(cols * w[:, None], axis=0) / jnp.sum(w)
        cols = jnp.where(valid[:, None], cols, 0.0)
        lin_idx = jnp.asarray([self.free_names.index(p) for p in lin])
        return cols * free_mask[lin_idx][None, :]

    def _rj_one(self, vec, base_values, batch, ctx, tzr_batch, tzr_ctx,
                valid, free_mask, dm_extra=None):
        """Hybrid (r, J) for one pulsar (fitter.resid_and_design over
        the union free set).  dm_extra = (dm_data, dm_error, dm_valid)
        switches to the stacked wideband [time; DM] system."""
        from pint_tpu.fitter import resid_and_design

        partition = (self._partition if dm_extra is None
                     else self._partition_wb)

        def resid_of(sub):
            values = self._values_at(sub, base_values, free_mask)
            r_t = self._resid_one_values(values, batch, ctx, tzr_batch,
                                         tzr_ctx, valid)
            if dm_extra is None:
                return r_t
            dm_data, _dm_error, dm_valid = dm_extra
            merged = _merge_ctx(ctx, self.static_ctx)
            r_dm = self._dm_resid_one(values, batch, merged, dm_data,
                                      dm_valid)
            return jnp.concatenate([r_t, r_dm])

        def linear_of(sub):
            values = self._values_at(sub, base_values, free_mask)
            lin = partition[0]
            cols = self._linear_cols_one(values, batch, ctx, tzr_batch,
                                         tzr_ctx, valid, free_mask, lin)
            if dm_extra is None:
                return cols
            _dm_data, _dm_error, dm_valid = dm_extra
            merged = _merge_ctx(ctx, self.static_ctx)
            p0 = self.prepareds[0]
            dmc = -p0.linear_dm_columns(values, batch, merged, lin)
            dmc = jnp.where(dm_valid[:, None], dmc, 0.0)
            lin_idx = jnp.asarray(
                [self.free_names.index(p) for p in lin])
            dmc = dmc * free_mask[lin_idx][None, :]
            return jnp.concatenate([cols, dmc], axis=0)

        return resid_and_design(tuple(self.free_names), vec, partition,
                                resid_of, linear_of)

    def _sigma_one(self, values, batch, ctx):
        """Noise-scaled per-TOA sigma for ONE pulsar's (batch, ctx) —
        the pure-function form of PreparedModel.scaled_sigma_fn (which
        is bound to its own dataset)."""
        p0 = self.prepareds[0]
        sigma = batch.error_s
        for c in p0.model.noise_components:
            f = getattr(c, "scaled_sigma", None)
            if f is not None:
                sigma = f(values, batch, ctx[type(c).__name__], sigma)
        return sigma

    def _step_health_one(self, resid_fn, vec, err, sigma, chi2, dpar,
                         cov, diag, batch, valid):
        """One pulsar's guard record: padded rows masked out of every
        input/residual verdict (they carry 1e30 errors by
        construction)."""
        return _guard.step_health(
            resid_fn(vec), sigma, chi2, dpar, cov, diag, valid=valid,
            inputs_ok=_guard.batch_input_finite(batch, valid))

    @staticmethod
    def _iterate_gn(body, vec0, maxiter, scan, trace):
        """Drive one pulsar's fixed-count GN loop through
        :func:`compile_cache.iterate_fixed` — the ONE place the three
        batched fit kinds resolve the flight-recorder gate.  Returns
        ``(vec, tr)`` with ``tr=None`` when the gate is off (the
        gate-off trace is byte-identical to the ungated build)."""
        init = (vec0, jnp.float64(0.0))
        if trace:
            (vec, _), tr = _cc.iterate_fixed(
                body, init, maxiter, scan=scan,
                trace_of=lambda p, n: _cc.gn_trace_record(
                    p[0], n[0], n[1]))
            return vec, tr
        vec, _ = _cc.iterate_fixed(body, init, maxiter, scan=scan)
        return vec, None

    def _fit_one(self, vec0, base_values, batch, ctx, tzr_batch,
                 tzr_ctx, valid, free_mask, guard_eps, maxiter,
                 with_health, scan=True, trace=False):
        merged = _merge_ctx(ctx, self.static_ctx)
        values0 = dict(base_values)
        for i, name in enumerate(self.free_names):
            values0[name] = vec0[i]
        sigma = self._sigma_one(values0, batch, merged)
        err = jnp.where(valid, sigma, 1e30)

        def resid_fn(v):
            return self._resid_one(
                v, base_values, batch, ctx, tzr_batch, tzr_ctx, valid,
                free_mask,
            )

        def rj(v):
            return self._rj_one(v, base_values, batch, ctx, tzr_batch,
                                tzr_ctx, valid, free_mask)

        def body(carry):
            vec, _ = carry
            new_vec, chi2, dpar, cov = wls_gn_solve(
                None, vec, err, rcond=guard_eps, rj=rj(vec))
            return (new_vec, chi2)

        vec, tr = self._iterate_gn(body, vec0, maxiter, scan, trace)
        if not with_health:
            _, chi2, _, cov = wls_gn_solve(None, vec, err,
                                           rcond=guard_eps, rj=rj(vec))
            out = (vec, chi2, cov, ())
            return out + (tr,) if trace else out
        _, chi2, dpar, cov, diag = wls_gn_solve(
            None, vec, err, rcond=guard_eps, with_health=True,
            rj=rj(vec))
        health = self._step_health_one(resid_fn, vec, err, sigma, chi2,
                                       dpar, cov, diag, batch, valid)
        out = (vec, chi2, cov, health)
        return out + (tr,) if trace else out

    def _gather_noise(self):
        """Static per-pulsar noise bases for the batched GLS path:
        (U (k, n_max, nb_max+1), phi (k, nb_max+1)) — each pulsar's
        low-rank basis at its CURRENT noise-parameter values, plus the
        mean-offset ones-column (reference residuals.py:583-585), all
        zero-padded to common shape (zero columns with zero weight are
        inert; gls_normal_solve floors phi)."""
        from pint_tpu.residuals import MEAN_OFFSET_WEIGHT

        Us, phis = [], []
        for p in self.prepareds:
            n_p = p.batch.ticks.shape[0]
            U = np.asarray(p.noise_basis, dtype=np.float64)
            phi = np.asarray(
                p.noise_weights_fn(p._values_pytree()), dtype=np.float64)
            U = np.concatenate([U, np.ones((n_p, 1))], axis=1)
            phi = np.concatenate([phi, [MEAN_OFFSET_WEIGHT]])
            Us.append(U)
            phis.append(phi)
        nb_max = max(u.shape[1] for u in Us)
        U_pad = np.zeros((self.n_pulsars, self.n_max, nb_max))
        phi_pad = np.zeros((self.n_pulsars, nb_max))
        for k, (u, ph) in enumerate(zip(Us, phis)):
            U_pad[k, : u.shape[0], : u.shape[1]] = u
            phi_pad[k, : len(ph)] = ph
        return jnp.asarray(U_pad), jnp.asarray(phi_pad)

    def _fit_one_gls(self, vec0, base_values, batch, ctx, tzr_batch,
                     tzr_ctx, valid, free_mask, U, phi, guard_eps,
                     maxiter, with_health, scan=True, trace=False):
        from pint_tpu.linalg import gls_normal_solve

        merged = _merge_ctx(ctx, self.static_ctx)
        values0 = dict(base_values)
        for i, name in enumerate(self.free_names):
            values0[name] = vec0[i]
        sigma = self._sigma_one(values0, batch, merged)
        err = jnp.where(valid, sigma, 1e30)

        def resid_fn(v):
            return self._resid_one(
                v, base_values, batch, ctx, tzr_batch, tzr_ctx, valid,
                free_mask,
            )

        def rj(v):
            return self._rj_one(v, base_values, batch, ctx, tzr_batch,
                                tzr_ctx, valid, free_mask)

        def body(carry):
            vec, _ = carry
            r, J = rj(vec)
            dpar, cov, _, chi2 = gls_normal_solve(
                r, J, err, U, phi, guard_eps=guard_eps)
            return (vec + dpar, chi2)

        vec, tr = self._iterate_gn(body, vec0, maxiter, scan, trace)
        r, J = rj(vec)
        if not with_health:
            _, cov, ncoef, chi2 = gls_normal_solve(
                r, J, err, U, phi, guard_eps=guard_eps)
            out = (vec, chi2, cov, ())
            return out + (tr,) if trace else out
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, err, U, phi, guard_eps=guard_eps, with_health=True)
        health = self._step_health_one(resid_fn, vec, err, sigma, chi2,
                                       dpar, cov, diag, batch, valid)
        out = (vec, chi2, cov, health)
        return out + (tr,) if trace else out

    # -- wideband (stacked TOA + DM) path -------------------------------------
    def _gather_dm(self):
        """Padded wideband DM measurements: (dm (k, n_max), dme
        (k, n_max), dm_valid (k, n_max)).  A narrowband pulsar
        contributes an all-invalid row, so mixed batches fit its time
        block only — mirroring WidebandTOAFitter vs plain GLS per
        pulsar (reference fitter.py:2292-2640)."""
        dms = np.zeros((self.n_pulsars, self.n_max))
        dmes = np.ones((self.n_pulsars, self.n_max))
        dmv = np.zeros((self.n_pulsars, self.n_max), dtype=bool)
        for k, p in enumerate(self.prepareds):
            toas = self.resids[k].toas
            dm, dme, valid = toas.wideband_dm_data()
            n_p = len(dm)
            dms[k, :n_p] = np.where(valid, dm, 0.0)
            dmes[k, :n_p] = np.where(valid, dme, 1.0)
            dmv[k, :n_p] = valid
        return (jnp.asarray(dms), jnp.asarray(dmes), jnp.asarray(dmv))

    def _dm_resid_one(self, values, batch, ctx, dm_data, dm_valid):
        """Measured-minus-model DM for one pulsar, zero where there is
        no measurement (pure-function form of
        WidebandDMResiduals.dm_resids_fn over the padded batch)."""
        from pint_tpu.models.timing_model import gated_dm_sum

        model_dm = gated_dm_sum(self.prepareds[0].model, values, batch,
                                ctx)
        return jnp.where(dm_valid, dm_data - model_dm, 0.0)

    def _dm_sigma_one(self, values, ctx, dm_error):
        """DMEFAC/DMEQUAD-scaled DM uncertainties for one pulsar."""
        p0 = self.prepareds[0]
        sig = dm_error
        for c in p0.model.noise_components:
            f = getattr(c, "scaled_dm_sigma", None)
            if f is not None:
                sig = f(values, ctx[type(c).__name__], sig)
        return sig

    def _fit_one_wb(self, vec0, base_values, batch, ctx, tzr_batch,
                    tzr_ctx, valid, free_mask, U, phi, dm_data,
                    dm_error, dm_valid, guard_eps, maxiter,
                    with_health, scan=True, trace=False):
        """One pulsar's wideband GLS fit: stacked [time; DM] residual
        with the correlated-noise basis acting on the time block only
        (zero rows under the DM block), same normal equations as
        _fit_one_gls."""
        from pint_tpu.linalg import gls_normal_solve

        merged = _merge_ctx(ctx, self.static_ctx)
        values0 = dict(base_values)
        for i, name in enumerate(self.free_names):
            values0[name] = vec0[i]
        sigma_t = self._sigma_one(values0, batch, merged)
        err_t = jnp.where(valid, sigma_t, 1e30)
        sigma_dm = self._dm_sigma_one(values0, merged, dm_error)
        err_dm = jnp.where(dm_valid, sigma_dm, 1e30)
        err = jnp.concatenate([err_t, err_dm])
        U_wb = jnp.concatenate(
            [U, jnp.zeros((dm_data.shape[0], U.shape[1]))], axis=0)

        def rj(v):
            return self._rj_one(v, base_values, batch, ctx, tzr_batch,
                                tzr_ctx, valid, free_mask,
                                dm_extra=(dm_data, dm_error, dm_valid))

        def body(carry):
            vec, _ = carry
            r, J = rj(vec)
            dpar, cov, _, chi2 = gls_normal_solve(
                r, J, err, U_wb, phi, guard_eps=guard_eps)
            return (vec + dpar, chi2)

        vec, tr = self._iterate_gn(body, vec0, maxiter, scan, trace)
        r, J = rj(vec)
        if not with_health:
            _, cov, _, chi2 = gls_normal_solve(
                r, J, err, U_wb, phi, guard_eps=guard_eps)
            out = (vec, chi2, cov, ())
            return out + (tr,) if trace else out
        dpar, cov, _, chi2, diag = gls_normal_solve(
            r, J, err, U_wb, phi, guard_eps=guard_eps,
            with_health=True)
        stacked_valid = jnp.concatenate([valid, dm_valid])
        health = _guard.step_health(
            r, err, chi2, dpar, cov, diag, valid=stacked_valid,
            inputs_ok=_guard.batch_input_finite(batch, valid))
        out = (vec, chi2, cov, health)
        return out + (tr,) if trace else out

    # -- batched-fit construction (memoized; registry-shared) -----------------
    def _structure_key(self):
        """Everything the batched traces bake in: the superset model
        structure, free-name union, batch geometry, and the static ctx
        parts — all per-pulsar DATA travels as vmapped arguments."""
        got = getattr(self, "_structure_key_cached", None)
        if got is None:
            got = self._structure_key_cached = repr((
                _cc.model_structure_key(self.prepareds[0].model),
                tuple(self.free_names), self.n_pulsars, self.n_max,
                self.tzr_batch is not None, self.tzr_ctx is not None,
                # the hybrid design partition changes the traced
                # per-pulsar step (which columns are analytic)
                self._partition, self._partition_wb,
                _cc.static_ctx_key(self.static_ctx),
                _cc.static_ctx_key(self.static_tzr_ctx),
            ))
        return got

    def _build_fit(self, kind, maxiter, with_health, scan=True,
                   trace=False):
        tzr_ax = 0 if self.tzr_batch is not None else None
        tcx_ax = 0 if self.tzr_ctx is not None else None
        # guard_eps is the LAST argument, broadcast over pulsars
        # (in_axes None) — the ladder escalates it as dynamic data
        # through the one compiled batch program
        if kind == "wls":
            return jax.vmap(
                lambda v, b, bt, c, tb, tc, m, fm, ge: self._fit_one(
                    v, b, bt, c, tb, tc, m, fm, ge, maxiter,
                    with_health, scan=scan, trace=trace
                ),
                in_axes=(0, 0, 0, 0, tzr_ax, tcx_ax, 0, 0, None),
            )
        if kind == "gls":
            return jax.vmap(
                lambda v, b, bt, c, tb, tc, m, fm, uu, ph, ge:
                self._fit_one_gls(v, b, bt, c, tb, tc, m, fm, uu, ph,
                                  ge, maxiter, with_health, scan=scan,
                                  trace=trace),
                in_axes=(0, 0, 0, 0, tzr_ax, tcx_ax, 0, 0, 0, 0, None),
            )
        return jax.vmap(
            lambda v, b, bt, c, tb, tc, m, fm, uu, ph, dd, de, dv, ge:
            self._fit_one_wb(v, b, bt, c, tb, tc, m, fm, uu, ph,
                             dd, de, dv, ge, maxiter, with_health,
                             scan=scan, trace=trace),
            in_axes=(0, 0, 0, 0, tzr_ax, tcx_ax, 0, 0, 0, 0, 0, 0, 0,
                     None),
        )

    def _batched_fit_jit(self, kind, maxiter, mesh=None):
        """ONE jitted batched fit per (kind, maxiter, mesh, iteration
        style), memoized on the instance and shared across
        same-structure batches through the process registry.  Returns
        ``(jitted_fit, iter_trace_flag)`` — the flag is resolved HERE
        (it decides whether the program's outputs carry the 5th,
        iteration-trace element) and threaded to the runner, so one
        env read governs both build and unpack.  This
        replaces the old per-call ``jax.jit(lambda *a: fit(*a))`` — a
        fresh jitted callable (and a full retrace + XLA compile of the
        entire PTA program) on EVERY fit invocation.  The mesh
        participates in the key
        (:func:`pint_tpu.parallel.mesh.mesh_jit_key`): one registry
        entry per mesh layout, so a second same-shaped sharded call
        compiles nothing and the profiler records sharded and
        unsharded runs separately.  So does the scan-vs-unroll GN
        iteration style (``$PINT_TPU_SCAN_ITERS``,
        :func:`pint_tpu.compile_cache.iterate_fixed`): the two are
        different traced programs."""
        with_health = _guard.enabled()
        scan = _cc.scan_iters_default()
        trace = _cc.iter_trace_default()
        mesh_key = _mesh.mesh_jit_key(mesh)
        cache = getattr(self, "_fit_jit_cache", None)
        if cache is None:
            cache = self._fit_jit_cache = {}
        got = cache.get((kind, maxiter, with_health, scan, trace,
                         mesh_key))
        if got is None:
            got = cache[(kind, maxiter, with_health, scan, trace,
                         mesh_key)] = \
                _cc.shared_jit(
                self._build_fit(kind, maxiter, with_health, scan=scan,
                                trace=trace),
                key=("pta.batched", kind, int(maxiter), with_health,
                     scan, trace, self._structure_key()) + mesh_key,
                fn_token="pta.batched_fit",
                label=f"pta.batched_fit:{kind}"
                      + (":sharded" if mesh is not None else ""))
            got.set_mesh(_mesh.mesh_desc(mesh))
            # per-call analytic cost for the profiler's reconciliation:
            # one batched fit = n_psr independent GLS fits
            try:
                got.set_analytic_flops(_flops.pta_batch_flops(
                    self.n_pulsars, self.n_max, len(self.free_names),
                    self._noise_basis_width(), n_iter=int(maxiter),
                    n_lin=len(self._partition_wb[0])))
            except Exception:
                pass  # cost metadata only; never block the fit path
        else:
            telemetry.counter_add("pta.fit_jit_cache_hits")
        return got, trace

    def fit_wideband(self, maxiter=3, mesh=None, checkpoint=None):
        """Batched wideband fit: stacked [time; DM] residuals per
        pulsar, the whole (possibly mixed narrowband+wideband) PTA as
        one XLA program — the batched counterpart of
        WidebandTOAFitter (reference fitter.py:2292-2640).  Sharding
        semantics match fit_wls."""
        while True:
            U, phi = self._gather_noise()
            dm_data, dm_error, dm_valid = self._gather_dm()
            fit, iter_trace = self._batched_fit_jit("wideband",
                                                    maxiter, mesh)
            out = self._run_batched(
                fit, {**self._base_args(), "U": U, "phi": phi,
                      "dm_data": dm_data, "dm_error": dm_error,
                      "dm_valid": dm_valid},
                mesh, checkpoint, n_lin=len(self._partition_wb[0]),
                iter_trace=iter_trace)
            if not self._kepler_depth_guard():
                return out

    def fit_gls(self, maxiter=3, mesh=None, checkpoint=None):
        """Batched GLS fit: every pulsar's timing parameters against
        its own correlated-noise covariance (ECORR / red-noise bases at
        the current noise values), the whole PTA as one XLA program —
        replacing the reference's per-pulsar GLSFitter process fan-out
        (gridutils.py:166-391).  Sharding semantics match fit_wls."""
        while True:
            U, phi = self._gather_noise()
            fit, iter_trace = self._batched_fit_jit("gls", maxiter,
                                                    mesh)
            out = self._run_batched(
                fit, {**self._base_args(), "U": U, "phi": phi},
                mesh, checkpoint, iter_trace=iter_trace)
            if not self._kepler_depth_guard():
                return out

    def _base_args(self):
        """The named stacked-data pytree every batched fit kind shares
        — the keys are what :data:`PTA_BATCH_RULES` patterns match
        against (``batch/ticks``, ``ctx/SpindownPhase/...``)."""
        return {
            "values0": self.values0, "base_values": self.base_values,
            "batch": self.batch, "ctx": self.ctx,
            "tzr_batch": self.tzr_batch, "tzr_ctx": self.tzr_ctx,
            "valid": self.valid, "free_mask": self.free_mask,
        }

    def _run_batched(self, fit, args, mesh, checkpoint=None,
                     n_lin=None, iter_trace=False):
        """Run the jitted batched fit (optionally mesh-sharded over the
        pulsar axis) and write fitted values back (only genuinely-free
        params).  args: the NAMED stacked-data dict (insertion order =
        positional order of the vmapped fit).  n_lin: analytic-column
        count of the partition the traced step actually uses
        (structure-aware FLOP accounting — the wideband step follows
        _partition_wb, not _partition)."""
        with telemetry.run_scope(
                "pta.fit", n_pulsars=self.n_pulsars,
                n_max=self.n_max, sharded=mesh is not None), \
            span("pta.batched_fit", n_pulsars=self.n_pulsars,
                 n_max=self.n_max, n_free=len(self.free_names),
                 sharded=mesh is not None,
                 mesh=_mesh.mesh_desc(mesh)):
            return self._run_batched_inner(fit, args, mesh, checkpoint,
                                           n_lin=n_lin,
                                           iter_trace=iter_trace)

    #: batched-path ladder: same escalation table as the
    #: single-pulsar fitters
    _guard_jitter_rungs = _guard.JITTER_RUNGS

    def _run_batched_inner(self, fit, args, mesh, checkpoint=None,
                           n_lin=None, iter_trace=False):
        n_real = self.n_pulsars
        # iter_trace is the flag _batched_fit_jit resolved when it
        # BUILT the program — one env read governs whether the
        # outputs carry the 5th (iteration trace) element, so a gate
        # flip between build and unpack cannot desynchronize them

        def split(out):
            if iter_trace:
                return out
            return out + (None,)
        if mesh is not None:
            # pad the PULSAR axis to a device multiple (the TOA axis
            # is already padded per pulsar): phantom members are edge
            # clones of the last real pulsar — always finite, so they
            # can't trip the guard — with their free_mask rows zeroed
            # (fully masked: no phantom parameter moves), and every
            # result/health row past n_real is sliced off below before
            # any merge/write-back/checkpoint path can see it
            ndev = _mesh.axis_size(mesh, "pulsar")
            k_pad = _mesh.pad_to_multiple(n_real, ndev)
            args = self._phantom_pad_args(args, k_pad)
            _mesh.record_pad_waste("pulsar", n_real, k_pad)
            args = _mesh.shard_args(mesh, PTA_BATCH_RULES, args)
            if k_pad != n_real:
                raw_fit = fit

                def fit(*a):
                    # slice every output's leading (pulsar) axis back
                    # to the real members — vec/chi2/cov, the health
                    # pytree, and (gate on) the iteration trace alike
                    return jax.tree.map(lambda x: x[:n_real],
                                        raw_fit(*a))
        vec, chi2, cov, health, tr = split(
            fit(*args.values(), jnp.float64(0.0)))
        telemetry.counter_add("guard.checks")
        bad = _guard.batch_bad(health)
        rung = "baseline"
        rung_of = {}  # member index -> serving rung name
        if bad is not None and bad.any():
            # degradation ladder over the WHOLE batch (one compiled
            # program; guard_eps is dynamic): merge per pulsar — keep
            # each pulsar's first healthy result.  Input-class members
            # (non-finite data) are excluded up front: no rung fixes
            # bad data, and a full-batch retry is not free (mirrors
            # run_ladder's immediate input-class abort).
            telemetry.counter_add("guard.trips")
            telemetry.counter_add("guard.trip.pta")
            fixable = bad & ~_guard.batch_input_bad(health)
            for name, eps in self._guard_jitter_rungs:
                if not fixable.any():
                    break
                v2, c2, k2, h2, t2 = split(fit(*args.values(),
                                               jnp.float64(eps)))
                fixed = fixable & ~_guard.batch_bad(h2)
                if fixed.any():
                    telemetry.counter_add(f"guard.rung.{name}",
                                          float(fixed.sum()))
                    m = jnp.asarray(fixed)

                    def merge(old, new):
                        # broadcast the per-pulsar mask over each
                        # leaf's trailing axes
                        return jnp.where(
                            m.reshape(m.shape + (1,) * (old.ndim - 1)),
                            new, old)

                    vec = jnp.where(m[:, None], v2, vec)
                    chi2 = jnp.where(m, c2, chi2)
                    cov = jnp.where(m[:, None, None], k2, cov)
                    # fit_health (and the iteration trace) must
                    # describe the SERVED results — merge the
                    # recovered pulsars' records too
                    health = jax.tree.map(merge, health, h2)
                    if tr is not None:
                        tr = jax.tree.map(merge, tr, t2)
                    rung = name
                    for i in np.flatnonzero(fixed):
                        rung_of[int(i)] = name
                    bad = bad & ~fixed
                    fixable = fixable & ~fixed
        vec_np = np.asarray(vec)
        telemetry.record_transfer(vec_np)
        telemetry.counter_add(
            "fit.flops_est",
            _flops.pta_batch_flops(
                self.n_pulsars, self.n_max, len(self.free_names),
                self._noise_basis_width(),
                n_lin=(len(self._partition[0]) if n_lin is None
                       else n_lin)))
        bad_idx = [] if bad is None else list(np.flatnonzero(bad))
        # write-back reads the mask host-side: per-element jnp
        # indexing here costs ~0.3 ms x (k x P) eager dispatches per
        # batch — measurable at serving rates
        fm = np.asarray(self.free_mask)
        for k, p in enumerate(self.prepareds):
            if k in bad_idx:
                continue  # never write a diverged pulsar's values
            for i, name in enumerate(self.free_names):
                if fm[k, i]:
                    p.model.values[name] = float(vec_np[k, i])
        self.fit_rung = rung
        #: member index -> serving rung name for rung-served members
        #: (the aliasing-safe readout: model.meta is shared when one
        #: model occupies several batch rows, e.g. the serving layer's
        #: occupancy padding/dedup)
        self.fit_rungs = dict(rung_of)
        self.fit_health = _guard.to_record(health)
        telemetry.emit({"type": "health", "context": "PTABatch",
                        "rung": rung, **self.fit_health})
        # flight recorder: keep the stacked (n_pulsars, maxiter)
        # device trace for callers; decode (one sync) only when a
        # sink wants the record
        self.last_iter_trace = tr
        if tr is not None and telemetry.sink_active():
            # a per-member merge has no single honest rung label:
            # "mixed" + the per-member rungs map beats stamping 49
            # baseline-served pulsars with one member's escalation
            telemetry.emit(telemetry.iter_trace_record(
                "pta.batched_fit",
                _cc.decode_gn_trace(
                    tr, rung="mixed" if rung_of else rung),
                kind="pta", n_pulsars=self.n_pulsars,
                rungs={str(k): v for k, v in rung_of.items()} or None))
        # the loudness contract of fitter._record_guard, per pulsar: a
        # rung-served member's exported par file must carry the
        # degradation flag (and the batch warns); a cleanly-served
        # member clears any stale flag from an earlier degraded fit
        if bad is not None:
            for k, p in enumerate(self.prepareds):
                if k in rung_of:
                    p.model.meta["GUARD_RUNG"] = rung_of[k]
                elif k not in bad_idx:
                    p.model.meta.pop("GUARD_RUNG", None)
            if rung_of:
                import warnings

                warnings.warn(
                    "PTABatch: fit served by degradation rung(s) "
                    f"{rung_of} (see model.meta['GUARD_RUNG'] and "
                    "batch.fit_health)")
        if checkpoint is not None:
            # healthy pulsars' progress survives even when the batch
            # partially diverged (the raise below)
            self.save_checkpoint(checkpoint)
        if bad_idx:
            raise _guard.FitDivergedError(
                "PTABatch",
                health=_guard.to_record(health),
                bad_indices=[int(i) for i in bad_idx],
                results=(vec, chi2, cov),
                rungs_tried=["baseline"] + [n for n, _ in
                                            self._guard_jitter_rungs],
                detail="healthy pulsars were written back (and "
                       "checkpointed when requested); the listed "
                       "indices kept their pre-fit values")
        return vec, chi2, cov

    def _phantom_pad_args(self, args, k_pad):
        """Phantom-pad every pulsar-stacked arg of ``args`` to
        ``k_pad`` members: edge clones of the last real pulsar (always
        finite) with their ``free_mask`` rows zeroed.  Shared by the
        batched fits and the 2-D chi^2 scan — the ONE place the
        phantom convention lives."""
        n_real = self.n_pulsars
        if k_pad == n_real:
            return args
        args = {
            k: (None if v is None else _mesh.named_tree_map(
                lambda _p, leaf: _mesh.pad_leading(
                    leaf, k_pad, mode="edge"), v))
            for k, v in args.items()
        }
        args["free_mask"] = args["free_mask"].at[n_real:].set(0.0)
        return args

    def _noise_basis_width(self):
        """Widest per-pulsar noise-basis width (FLOP accounting)."""
        return max(
            int(np.shape(p.noise_basis)[1]) for p in self.prepareds
        )

    def _kepler_depth_guard(self):
        """Batched counterpart of ``Fitter._kepler_depth_guard``:
        after write-back, re-derive every pulsar's eccentricity reach
        at the FITTED values; when any member crossed its prepare-time
        class, the whole batch deepens to the new harmonized max (the
        stacked trace closes over ONE static depth per component key)
        and the caller must rerun the fit — the previous solution came
        from a too-shallow Newton unroll.  Bounded: the depth is
        monotone over four classes."""
        from pint_tpu.models.binary.kepler import newton_iters_for

        reaches = [r for r in (p.kepler_ecc_reach()
                               for p in self.prepareds)
                   if r != float("-inf")]
        if not reaches:
            return False
        # NaN reach (unset ECC) sorts to the full unroll
        worst = max(reaches, key=newton_iters_for)
        # via the Residuals wrappers so their own ctx splits re-key too;
        # list first — any() would short-circuit the remaining members
        changed = [r.ensure_kepler_depth(worst) for r in self.resids]
        if not any(changed):
            return False
        telemetry.counter_add("pta.kepler_depth_refits")
        import warnings

        warnings.warn(
            "batched fit moved an eccentricity reach to %.3g — past "
            "the prepare-time Kepler depth class; deepening the "
            "Newton unroll and refitting the batch" % worst)
        self._restack_after_depth_change()
        return True

    def _restack_after_depth_change(self):
        """Rebuild the stacked ctx pytrees (and their static split)
        after ``ensure_kepler_depth`` mutated the per-pulsar ctxs,
        refresh the starting values from the written-back models, and
        drop every structure-keyed cache — the deeper unroll is a
        different traced program."""
        ctxs = [
            _pad_ctx(p.ctx, p.batch.ticks.shape[0], self.n_max)
            for p in self.prepareds
        ]
        self.ctx, self.static_ctx = _stack_ctxs(ctxs)
        if self.tzr_ctx is not None:
            self.tzr_ctx, self.static_tzr_ctx = _stack_ctxs(
                [p.tzr_ctx for p in self.prepareds]
            )
        self.values0 = jnp.asarray(np.array([
            [float(p.model.values[n]) for n in self.free_names]
            for p in self.prepareds
        ]))
        self.base_values = self._stack_values()
        self._structure_key_cached = None
        self._fit_jit_cache = {}

    # -- public API -----------------------------------------------------------
    def residuals(self, values=None):
        """(n_pulsars, n_max) padded time residuals, zero where
        invalid."""
        vals = self.values0 if values is None else values
        f = jax.vmap(self._resid_one,
                     in_axes=(0, 0, 0, 0,
                              0 if self.tzr_batch is not None else None,
                              0 if self.tzr_ctx is not None else None,
                              0, 0))
        return f(vals, self.base_values, self.batch, self.ctx,
                 self.tzr_batch, self.tzr_ctx, self.valid,
                 self.free_mask)

    # -- no-fit evaluation programs (the serving layer's ops) -----------------
    def _chisq_one(self, vec, base_values, batch, ctx, tzr_batch,
                   tzr_ctx, valid, free_mask):
        """White-noise-weighted chi^2 for one pulsar at a free-param
        vector — no refit; correlated noise enters only through the
        EFAC/EQUAD/ECORR-scaled sigmas.  The pure function under
        :meth:`chisq`."""
        values = self._values_at(vec, base_values, free_mask)
        r = self._resid_one_values(values, batch, ctx, tzr_batch,
                                   tzr_ctx, valid)
        merged = _merge_ctx(ctx, self.static_ctx)
        sigma = self._sigma_one(values, batch, merged)
        err = jnp.where(valid, sigma, 1e30)
        return jnp.sum((r / err) ** 2)

    def _eval_jit(self, which):
        """ONE jitted no-fit evaluation program per (kind, structure):
        ``"resid"`` -> padded residuals, ``"chisq"`` -> per-pulsar
        weighted chi^2.  Routed through the shared registry (keys
        ``pta.resid`` / ``pta.chisq``) so a second same-structure call
        — the serving layer's residual/lnlike ops — performs zero new
        XLA compiles, and the AOT export/import path covers them like
        the batched fits."""
        cache = getattr(self, "_fit_jit_cache", None)
        if cache is None:
            cache = self._fit_jit_cache = {}
        got = cache.get(("eval", which))
        if got is None:
            tzr_ax = 0 if self.tzr_batch is not None else None
            tcx_ax = 0 if self.tzr_ctx is not None else None
            one = (self._resid_one if which == "resid"
                   else self._chisq_one)
            got = cache[("eval", which)] = _cc.shared_jit(
                jax.vmap(one,
                         in_axes=(0, 0, 0, 0, tzr_ax, tcx_ax, 0, 0)),
                key=("pta." + which, self._structure_key()),
                fn_token="pta." + which,
                label="pta." + which)
        else:
            telemetry.counter_add("pta.fit_jit_cache_hits")
        return got

    def _eval_shared(self, which, values=None):
        vals = self.values0 if values is None else jnp.asarray(values)
        fn = self._eval_jit(which)
        with telemetry.run_scope("pta." + which,
                                 n_pulsars=self.n_pulsars), \
                span("pta." + which, n_pulsars=self.n_pulsars):
            out = np.asarray(fn(vals, self.base_values, self.batch,
                                self.ctx, self.tzr_batch, self.tzr_ctx,
                                self.valid, self.free_mask))
        telemetry.record_transfer(out)
        return out

    def chisq(self, values=None):
        """(n_pulsars,) weighted chi^2 at stacked free-parameter rows
        ``values`` ((k, P); default the current ``values0``) through
        one shared program, no fitting — the serving layer's lnlike op
        (``lnlike = -chi2/2`` up to the white-noise normalization)."""
        return self._eval_shared("chisq", values)

    def residuals_shared(self, values=None):
        """(n_pulsars, n_max) padded residuals through the ONE shared
        registry program — the serving layer's residual op (the eager
        :meth:`residuals` stays for ad-hoc/gradient use)."""
        return self._eval_shared("resid", values)

    def fit_wls(self, maxiter=3, mesh=None, checkpoint=None):
        """Batched WLS Gauss-Newton fit of every pulsar; returns
        (fitted_values (k, P), chi2 (k,), cov (k, P, P)).

        With a mesh, the pulsar axis is sharded over devices
        (NamedSharding) — the multi-chip path the driver dry-runs.
        checkpoint: optional path — fitted values are atomic-written
        after the fit (guard.save_checkpoint), validated on restore
        against this batch's structure fingerprint."""
        while True:
            fit, iter_trace = self._batched_fit_jit("wls", maxiter,
                                                    mesh)
            out = self._run_batched(fit, self._base_args(), mesh,
                                    checkpoint,
                                    iter_trace=iter_trace)
            if not self._kepler_depth_guard():
                return out

    # -- 2-D pulsar x grid chi^2 scan -----------------------------------------
    def _build_chisq_grid(self, gnames, gidx, n_steps, kind, scan):
        """The pure (grid-point, pulsar) chi^2 function, vmapped over
        BOTH axes: the inner vmap is the per-pulsar fixed-count GN
        refit (``_fit_one``/``_fit_one_gls`` with the gridded
        parameters pinned — their free_mask entries zeroed, so their
        design columns are exactly zero), the outer vmap runs grid
        points.  Output (n_points, n_pulsars)."""
        tzr_ax = 0 if self.tzr_batch is not None else None
        tcx_ax = 0 if self.tzr_ctx is not None else None
        gidx_j = jnp.asarray(np.asarray(gidx))

        def pin(gvec, vec0, base_values, free_mask):
            vec = vec0.at[gidx_j].set(gvec)
            fmask = free_mask.at[gidx_j].set(0.0)
            base = dict(base_values)
            for j, name in enumerate(gnames):
                base[name] = gvec[j]
            return vec, base, fmask

        if kind == "wls":
            def one(gvec, vec0, base_values, batch, ctx, tzr_b,
                    tzr_c, valid, free_mask, guard_eps):
                vec, base, fmask = pin(gvec, vec0, base_values,
                                       free_mask)
                _, chi2, _, _ = self._fit_one(
                    vec, base, batch, ctx, tzr_b, tzr_c, valid,
                    fmask, guard_eps, n_steps, False, scan=scan)
                return chi2

            in_ax = (None, 0, 0, 0, 0, tzr_ax, tcx_ax, 0, 0, None)
        else:
            def one(gvec, vec0, base_values, batch, ctx, tzr_b,
                    tzr_c, valid, free_mask, U, phi, guard_eps):
                vec, base, fmask = pin(gvec, vec0, base_values,
                                       free_mask)
                _, chi2, _, _ = self._fit_one_gls(
                    vec, base, batch, ctx, tzr_b, tzr_c, valid,
                    fmask, U, phi, guard_eps, n_steps, False,
                    scan=scan)
                return chi2

            in_ax = (None, 0, 0, 0, 0, tzr_ax, tcx_ax, 0, 0, 0, 0,
                     None)
        per_pulsar = jax.vmap(one, in_axes=in_ax)
        return jax.vmap(per_pulsar,
                        in_axes=(0,) + (None,) * (len(in_ax) - 1))

    def _chisq_grid_jit(self, gnames, n_steps, kind, mesh=None):
        """ONE jitted 2-D scan per (grid params, step count, kind,
        mesh layout), memoized on the instance and registry-shared —
        a second same-shaped (possibly 2-D-sharded) scan compiles
        nothing."""
        scan = _cc.scan_iters_default()
        mesh_key = _mesh.mesh_jit_key(mesh)
        cache = getattr(self, "_fit_jit_cache", None)
        if cache is None:
            cache = self._fit_jit_cache = {}
        ck = ("chisq_grid", gnames, int(n_steps), kind, scan,
              mesh_key)
        got = cache.get(ck)
        if got is None:
            gidx = [self.free_names.index(p) for p in gnames]
            got = cache[ck] = _cc.shared_jit(
                self._build_chisq_grid(gnames, gidx, n_steps, kind,
                                       scan),
                key=("pta.chisq_grid", gnames, int(n_steps), kind,
                     scan, self._structure_key()) + mesh_key,
                fn_token="pta.chisq_grid",
                label="pta.chisq_grid:" + "+".join(gnames)
                      + (":sharded" if mesh is not None else ""))
            got.set_mesh(_mesh.mesh_desc(mesh))
        else:
            telemetry.counter_add("pta.fit_jit_cache_hits")
        return got

    def chisq_grid(self, grid_params, grid_values, n_steps=2,
                   mesh=None):
        """Per-pulsar chi^2 over a shared grid of pinned parameter
        values — the whole (pulsar x grid point) scan as ONE XLA
        program.  Returns ``chi2 (n_pulsars, n_points)``.

        grid_params: names from the batch's free union, pinned at
        each grid point's values (their free_mask entries are zeroed
        in-trace, so the remaining per-pulsar parameters refit by
        ``n_steps`` Gauss-Newton iterations around them — the batched
        counterpart of :func:`pint_tpu.grid.grid_chisq_tuple`).
        grid_values: (n_points, len(grid_params)).

        mesh: ``None`` (single program, unsharded), a 1-d mesh (the
        PULSAR axis rides it, grid points replicate), or a 2-D
        ``pulsar x grid`` mesh
        (``make_mesh(("pulsar", "grid"), shape=(P, G))``) — the rule
        table resolves BOTH axes over one data pytree, phantom-pulsar
        padding composes with grid-point edge-padding (each axis's
        overhead lands in its own ``mesh.pad_waste_frac.<axis>``
        gauge), and a 68-pulsar x dense-grid scan runs as one
        program on a pod slice.  The mesh keys the trace: a second
        same-shaped sharded scan performs zero new XLA compiles.

        Models with correlated noise scan through the batched GLS
        step at the CURRENT noise values; gridding a noise-model
        parameter is rejected (its basis/weights are gathered
        host-side per call, so a gridded value would silently not
        take effect)."""
        gnames = tuple(grid_params)
        for p in gnames:
            if p not in self.free_names:
                raise ValueError(
                    f"chisq_grid: {p!r} is not in the batch free-"
                    f"parameter union {tuple(self.free_names)}")
        kind = ("gls" if self.prepareds[0].model.has_correlated_errors
                else "wls")
        # pulsar 0 speaks for the batch: __init__ enforces identical
        # (superset) component structure across members
        noise_owned = {
            par.name
            for c in self.prepareds[0].model.noise_components
            for par in c.params}
        bad = [p for p in gnames if p in noise_owned]
        if bad:
            raise ValueError(
                f"chisq_grid: noise-model parameters {bad} cannot be "
                "gridded on the batched path (their basis/weights "
                "are gathered at current values); use the "
                "single-pulsar grid or gw.common.lnlike_grid")
        gv = np.atleast_2d(np.asarray(grid_values, np.float64))
        if gv.shape[1] != len(gnames):
            raise ValueError(
                f"chisq_grid: grid_values shape {gv.shape} does not "
                f"match {len(gnames)} grid parameter(s)")
        n_pts = gv.shape[0]
        n_real = self.n_pulsars
        fit = self._chisq_grid_jit(gnames, n_steps, kind, mesh)
        args = {"grid_values": jnp.asarray(gv), **self._base_args()}
        if kind == "gls":
            U, phi = self._gather_noise()
            args["U"], args["phi"] = U, phi
        n_pts_pad, k_pad = n_pts, n_real
        if mesh is not None:
            names = tuple(str(n) for n in mesh.axis_names)
            if len(names) == 1:
                # a 1-d mesh serves the PULSAR (batch) axis; the grid
                # axis replicates — sharding both onto one axis would
                # need the product layout a 2-D mesh expresses
                rules = ((r"^grid_values$", None),) + PTA_BATCH_RULES
                grid_dev, psr_dev = 1, _mesh.axis_size(mesh, "pulsar")
            else:
                rules = PTA_GRID_RULES
                grid_dev = _mesh.axis_size(mesh, "grid")
                psr_dev = _mesh.axis_size(mesh, "pulsar")
            n_pts_pad = _mesh.pad_to_multiple(n_pts, grid_dev)
            _mesh.record_pad_waste("grid", n_pts, n_pts_pad)
            args["grid_values"] = _mesh.pad_leading(
                args["grid_values"], n_pts_pad, mode="edge")
            k_pad = _mesh.pad_to_multiple(n_real, psr_dev)
            _mesh.record_pad_waste("pulsar", n_real, k_pad)
            gv_arr = args.pop("grid_values")
            args = self._phantom_pad_args(args, k_pad)
            args = {"grid_values": gv_arr, **args}
            args = _mesh.shard_args(mesh, rules, args)
        with telemetry.run_scope(
                "pta.chisq_grid", n_pulsars=n_real, n_points=n_pts,
                sharded=mesh is not None), \
            span("pta.chisq_grid", n_pulsars=n_real, n_points=n_pts,
                 grid_params=list(gnames), sharded=mesh is not None,
                 mesh=_mesh.mesh_desc(mesh)):
            out = fit(*args.values(), jnp.float64(0.0))
            chi2 = np.asarray(out)
        telemetry.record_transfer(chi2)
        return chi2[:n_pts, :n_real].T.copy()

    # -- checkpoint/resume ----------------------------------------------------
    def _checkpoint_fingerprint(self):
        """Identity a fit checkpoint is validated against: the batched
        trace's structure key (superset model structure, free-name
        union, batch geometry) — values from a different array layout
        must never be silently restored."""
        return _cc.fingerprint(self._structure_key())

    def save_checkpoint(self, path):
        """Atomic-write the batch's fit progress: every pulsar's
        current values for the free-name union (the quantities
        fit_wls/fit_gls write back)."""
        vals = np.array([
            [float(p.model.values[n]) for n in self.free_names]
            for p in self.prepareds
        ])
        return _guard.save_checkpoint(
            path, {"values": vals},
            fingerprint=self._checkpoint_fingerprint(),
            meta={"free_names": list(self.free_names)})

    def restore_checkpoint(self, path):
        """Restore fit progress saved by :meth:`save_checkpoint` into
        the models (free-masked entries only) and ``values0``.
        Validates the structure fingerprint; raises
        :class:`pint_tpu.guard.CheckpointMismatchError` on a stale or
        foreign checkpoint, FileNotFoundError when absent."""
        arrays, _head = _guard.load_checkpoint(
            path, fingerprint=self._checkpoint_fingerprint(),
            missing_ok=False)
        vals = np.asarray(arrays["values"])
        if vals.shape != (self.n_pulsars, len(self.free_names)):
            raise _guard.CheckpointMismatchError(
                f"{path}: values shape {vals.shape} != "
                f"({self.n_pulsars}, {len(self.free_names)})")
        for k, p in enumerate(self.prepareds):
            for i, name in enumerate(self.free_names):
                if float(self.free_mask[k, i]):
                    p.model.values[name] = float(vals[k, i])
        self.values0 = jnp.asarray(vals)
        return vals

    @property
    def dof(self):
        return np.asarray(self.n_toas) - len(self.free_names) - 1

    # -- cross-pulsar GW engine hooks -----------------------------------------
    def sky_positions(self):
        """(n_pulsars, 3) SSB->pulsar unit vectors — the geometry the
        ORF matrices of :mod:`pint_tpu.gw.orf` are built from."""
        from pint_tpu.gw.orf import pulsar_positions

        return pulsar_positions([p.model for p in self.prepareds])

    def optimal_statistic(self, **kwargs):
        """A :class:`pint_tpu.gw.OptimalStatistic` over this batch's
        prepared pulsars (residuals/noise at current values — call
        after :meth:`fit_wls`/:meth:`fit_gls` for post-fit
        statistics).  kwargs: nmodes, gamma, orf, tspan_s,
        marginalize_timing."""
        from pint_tpu.gw.os import OptimalStatistic

        return OptimalStatistic(batch=self, **kwargs)

    def common_process(self, **kwargs):
        """A :class:`pint_tpu.gw.CommonProcess` likelihood over this
        batch (kwargs: nmodes, orf, tspan_s, marginalize_timing)."""
        from pint_tpu.gw.common import CommonProcess

        return CommonProcess(batch=self, **kwargs)