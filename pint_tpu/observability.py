"""Named-stage timing + profiling harness (reference: the profiling/
directory's high_level_benchmark.py extracts named hot stages via
pstats; plus SURVEY §5 metrics/observability gap).

- ``stages = StageTimer(); with stages("Update Resids"): ...`` collects
  wall times per named stage (cumulative over repeats);
- ``stages.report()`` prints the reference-benchmark-style table;
- ``trace(dir)`` context manager wraps ``jax.profiler.trace`` so the
  XLA-level profile (TensorBoard format) lands next to the named-stage
  numbers.

Device-side work is asynchronous: StageTimer calls
``jax.block_until_ready`` on the value you pass to ``tick`` (or
relies on the with-block's own sync) so the walls mean what they say.
"""

from __future__ import annotations

import contextlib
import time
from collections import OrderedDict

__all__ = ["StageTimer", "trace"]


class StageTimer:
    def __init__(self):
        self.totals: "OrderedDict[str, float]" = OrderedDict()
        self.counts: "OrderedDict[str, int]" = OrderedDict()

    @contextlib.contextmanager
    def __call__(self, name, sync=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                import jax

                jax.block_until_ready(sync)
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self, file=None):
        lines = [f"{'Stage':<28s} {'Total [s]':>10s} {'Calls':>6s} "
                 f"{'Per call [s]':>13s}"]
        for name, tot in self.totals.items():
            n = self.counts[name]
            lines.append(f"{name:<28s} {tot:>10.3f} {n:>6d} "
                         f"{tot / n:>13.4f}")
        out = "\n".join(lines)
        print(out, file=file)
        return out

    def as_dict(self):
        return dict(self.totals)


@contextlib.contextmanager
def trace(log_dir):
    """XLA-level profile (TensorBoard trace) around a block."""
    import jax

    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
