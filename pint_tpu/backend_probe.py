"""Hang-proof JAX backend probing.

Why: the known axon/TPU-tunnel failure mode is that even a trivial
``jax.jit`` call blocks forever with no error, so ANY first touch of
``jax.devices()`` / ``jax.default_backend()`` in a diagnostic or CLI
entry point turns the tool into a second casualty of the exact failure
it should be reporting.  The reference never needs this (CUDA either
works or raises); here the probe runs in a *subprocess* with a hard
timeout, so the parent can report a hung tunnel and fall back to the
CPU backend.

Used by ``bench.py`` (per-metric CPU fallback) and
``pint_tpu.datacheck`` (backend line of the data diagnostic).
"""

from __future__ import annotations

import os
import sys
import time

__all__ = ["probe_backend", "probe_with_retry", "ensure_live_backend"]


def probe_backend(timeout_s: float, force_cpu_env: str | None = None):
    """Jit a trivial function in a subprocess.

    Returns ``(ok, backend_or_detail)``: on success the probed backend
    name ("tpu", "cpu", ...); on failure a human-readable detail that
    distinguishes a timeout (hung device tunnel) from a broken
    environment (carries the probe's stderr tail).

    ``force_cpu_env``: name of an env var that, when set, makes the
    probe run on the CPU backend (bench.py's explicit-CPU escape
    hatch).
    """
    import subprocess

    from pint_tpu import telemetry

    telemetry.counter_add("backend_probe.attempts")
    pre = ""
    if force_cpu_env:
        pre = (
            "import os\n"
            f"if os.environ.get({force_cpu_env!r}):\n"
            "    os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        )
    code = (pre + "import jax, jax.numpy as jnp\n"
            + (f"if __import__('os').environ.get({force_cpu_env!r}):\n"
               "    jax.config.update('jax_platforms', 'cpu')\n"
               if force_cpu_env else "")
            + "jax.jit(lambda x: x * 2)(jnp.ones(8))\n"
            "print(jax.default_backend())\n")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
        if r.returncode == 0:
            lines = r.stdout.strip().splitlines()
            if not lines:
                # rc==0 with stdout swallowed (wrapper/sitecustomize):
                # a diagnostic, not an IndexError (ADVICE round 5)
                telemetry.counter_add("backend_probe.failures")
                return False, "probe produced no output"
            telemetry.counter_add("backend_probe.ok")
            return True, lines[-1]
        telemetry.counter_add("backend_probe.failures")
        return False, ("probe exited rc=%d: %s"
                       % (r.returncode, r.stderr.strip()[-300:]))
    except subprocess.TimeoutExpired:
        telemetry.counter_add("backend_probe.timeouts")
        return False, ("probe timed out after %.0fs (hung device "
                       "tunnel)" % timeout_s)


def probe_with_retry(timeout_s: float | None = None,
                     retries: int | None = None,
                     backoff_s: float | None = None,
                     force_cpu_env: str | None = None,
                     probe_fn=None):
    """Bounded-retry probe with exponential backoff: a *transiently*
    hung device tunnel (the BENCH_r03-r05 failure mode, where rounds
    silently fell to a mislabeled CPU floor) gets ``retries`` chances
    to come back before the caller falls back.

    retries: total probe attempts (``$PINT_TPU_PROBE_RETRIES``,
    default 3).  backoff_s: sleep before the second attempt
    (``$PINT_TPU_PROBE_BACKOFF``, default 2.0), doubling each retry,
    capped at 60 s.  probe_fn: ``() -> (ok, detail)`` override for
    tests (the injected always-timeout probe).

    Telemetry: ``probe.attempts`` per attempt, ``probe.backoff_s``
    cumulative sleep, ``probe.recoveries`` when a retry succeeds after
    a failure.  Returns ``(ok, detail)``; detail notes the recovering
    attempt so a recovered run is distinguishable from a first-try
    pass."""
    from pint_tpu import telemetry

    if timeout_s is None:
        timeout_s = float(os.environ.get("PINT_TPU_PROBE_TIMEOUT", "20"))
    if retries is None:
        try:
            retries = int(os.environ.get("PINT_TPU_PROBE_RETRIES", "3"))
        except ValueError:
            retries = 3
    retries = max(1, retries)
    if backoff_s is None:
        try:
            backoff_s = float(
                os.environ.get("PINT_TPU_PROBE_BACKOFF", "2.0"))
        except ValueError:
            backoff_s = 2.0
    if probe_fn is None:
        probe_fn = lambda: probe_backend(  # noqa: E731
            timeout_s, force_cpu_env=force_cpu_env)
    delay = backoff_s
    ok, detail = False, "no probe attempts"
    for attempt in range(1, retries + 1):
        telemetry.counter_add("probe.attempts")
        ok, detail = probe_fn()
        if ok:
            if attempt > 1:
                telemetry.counter_add("probe.recoveries")
                detail = (f"{detail} (recovered on attempt "
                          f"{attempt}/{retries})")
            return ok, detail
        if attempt < retries:
            telemetry.counter_add("probe.backoff_s", delay)
            time.sleep(delay)
            delay = min(delay * 2.0, 60.0)
    return ok, f"{detail} (after {retries} attempt(s))"


def ensure_live_backend(timeout_s: float | None = None,
                        retries: int | None = None,
                        backoff_s: float | None = None,
                        force_cpu_env: str | None = None,
                        probe_fn=None):
    """Probe the default backend (with bounded retry/backoff — see
    :func:`probe_with_retry`); if it stays hung or broken, force the
    in-process JAX config onto the CPU backend so subsequent
    ``jax.devices()`` calls return instead of blocking.

    Must run BEFORE the first in-process backend touch (importing jax
    is fine; initializing a backend is not).  Returns ``(live,
    detail)`` where ``live`` says whether the *default* backend
    answered and ``detail`` carries the probe result either way.
    """
    import jax

    # already pinned to the CPU backend in-process (tests, tools that
    # force cpu before importing): nothing can hang, skip the probe
    if (getattr(jax.config, "jax_platforms", None) or "") == "cpu":
        return True, "cpu (pre-forced in-process)"
    ok, detail = probe_with_retry(timeout_s, retries, backoff_s,
                                  force_cpu_env=force_cpu_env,
                                  probe_fn=probe_fn)
    if not ok:
        from pint_tpu import telemetry

        telemetry.counter_add("backend_probe.cpu_fallbacks")
        os.environ["JAX_PLATFORMS"] = "cpu"

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized; nothing to rescue
    return ok, detail
