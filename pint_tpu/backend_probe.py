"""Hang-proof JAX backend probing.

Why: the known axon/TPU-tunnel failure mode is that even a trivial
``jax.jit`` call blocks forever with no error, so ANY first touch of
``jax.devices()`` / ``jax.default_backend()`` in a diagnostic or CLI
entry point turns the tool into a second casualty of the exact failure
it should be reporting.  The reference never needs this (CUDA either
works or raises); here the probe runs in a *subprocess* with a hard
timeout, so the parent can report a hung tunnel and fall back to the
CPU backend.

Used by ``bench.py`` (per-metric CPU fallback) and
``pint_tpu.datacheck`` (backend line of the data diagnostic).
"""

from __future__ import annotations

import os
import sys

__all__ = ["probe_backend", "ensure_live_backend"]


def probe_backend(timeout_s: float, force_cpu_env: str | None = None):
    """Jit a trivial function in a subprocess.

    Returns ``(ok, backend_or_detail)``: on success the probed backend
    name ("tpu", "cpu", ...); on failure a human-readable detail that
    distinguishes a timeout (hung device tunnel) from a broken
    environment (carries the probe's stderr tail).

    ``force_cpu_env``: name of an env var that, when set, makes the
    probe run on the CPU backend (bench.py's explicit-CPU escape
    hatch).
    """
    import subprocess

    from pint_tpu import telemetry

    telemetry.counter_add("backend_probe.attempts")
    pre = ""
    if force_cpu_env:
        pre = (
            "import os\n"
            f"if os.environ.get({force_cpu_env!r}):\n"
            "    os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        )
    code = (pre + "import jax, jax.numpy as jnp\n"
            + (f"if __import__('os').environ.get({force_cpu_env!r}):\n"
               "    jax.config.update('jax_platforms', 'cpu')\n"
               if force_cpu_env else "")
            + "jax.jit(lambda x: x * 2)(jnp.ones(8))\n"
            "print(jax.default_backend())\n")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
        if r.returncode == 0:
            lines = r.stdout.strip().splitlines()
            if not lines:
                # rc==0 with stdout swallowed (wrapper/sitecustomize):
                # a diagnostic, not an IndexError (ADVICE round 5)
                telemetry.counter_add("backend_probe.failures")
                return False, "probe produced no output"
            telemetry.counter_add("backend_probe.ok")
            return True, lines[-1]
        telemetry.counter_add("backend_probe.failures")
        return False, ("probe exited rc=%d: %s"
                       % (r.returncode, r.stderr.strip()[-300:]))
    except subprocess.TimeoutExpired:
        telemetry.counter_add("backend_probe.timeouts")
        return False, ("probe timed out after %.0fs (hung device "
                       "tunnel)" % timeout_s)


def ensure_live_backend(timeout_s: float | None = None):
    """Probe the default backend; if it is hung or broken, force the
    in-process JAX config onto the CPU backend so subsequent
    ``jax.devices()`` calls return instead of blocking.

    Must run BEFORE the first in-process backend touch (importing jax
    is fine; initializing a backend is not).  Returns ``(live,
    detail)`` where ``live`` says whether the *default* backend
    answered and ``detail`` carries the probe result either way.
    """
    import jax

    # already pinned to the CPU backend in-process (tests, tools that
    # force cpu before importing): nothing can hang, skip the probe
    if (getattr(jax.config, "jax_platforms", None) or "") == "cpu":
        return True, "cpu (pre-forced in-process)"
    if timeout_s is None:
        timeout_s = float(os.environ.get("PINT_TPU_PROBE_TIMEOUT", "20"))
    ok, detail = probe_backend(timeout_s)
    if not ok:
        from pint_tpu import telemetry

        telemetry.counter_add("backend_probe.cpu_fallbacks")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized; nothing to rescue
    return ok, detail
