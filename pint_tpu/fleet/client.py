"""Shared HTTP client with Retry-After-honoring retry/backoff.

Every in-repo load path (the corpus replay stream, the bench serve
load generators, the chaos harness, the supervisor's drain/readyz
calls) speaks to the serve plane through this ONE helper instead of
hand-rolling its own request loop: a bounded retry policy with

- **Retry-After honored**: a 429/503 carries the replica's own hint
  (header seconds, or ``retry_after_ms`` in the body) — sleeping
  exactly that long is the cooperative half of admission control;
- **exponential backoff** for transport failures and hint-less
  refusals (base doubles per attempt, deterministic — no jitter, so
  test traffic replays exactly);
- **per-request budgets**: at most ``$PINT_TPU_FLEET_RETRIES``
  attempts AND ``$PINT_TPU_FLEET_RETRY_BUDGET_S`` wall seconds —
  a retry storm is bounded on both axes by construction.

Retried outcomes: connection errors (the replica died — the fleet
router re-placed its work) and 429/503 (shed / draining / transient).
A 504 deadline miss is returned to the caller — deadline semantics
belong to the client, not the transport.  Fit/residual/lnlike
requests are pure functions of registered data, so a replay after an
ambiguous transport failure is safe by construction.

Telemetry: ``fleet.client.retries`` / ``fleet.client.giveups``.
"""

from __future__ import annotations

import http.client
import os
import time

from pint_tpu import telemetry
from pint_tpu.serve.client import ServeClient

__all__ = ["RetryClient", "request_with_retry", "retry_after_from",
           "RETRIES_ENV", "RETRY_BUDGET_ENV"]

#: host-only knobs (lint/static.py HOST_ONLY): retry policy shapes
#: traffic, never a traced program
RETRIES_ENV = "PINT_TPU_FLEET_RETRIES"
RETRY_BUDGET_ENV = "PINT_TPU_FLEET_RETRY_BUDGET_S"

#: statuses worth retrying: shed (429) and unavailable/draining (503)
RETRY_STATUSES = (429, 503)


def _env_num(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def retry_after_from(headers, body) -> float | None:
    """The replica's own backoff hint, in seconds: the
    ``Retry-After`` header (integral seconds per the spec) or the
    finer-grained ``retry_after_ms`` the structured error body
    carries."""
    ms = None
    if isinstance(body, dict):
        ms = body.get("retry_after_ms")
    if ms is not None:
        try:
            return float(ms) / 1e3
        except (TypeError, ValueError):
            pass
    raw = (headers or {}).get("retry-after")
    if raw is not None:
        try:
            return float(raw)
        except (TypeError, ValueError):
            pass
    return None


class RetryClient:
    """One keep-alive connection with the bounded retry policy on
    top.  ``max_attempts``/``budget_s`` default from the env knobs
    (4 attempts, 15 s)."""

    def __init__(self, host="127.0.0.1", port=8470, timeout=60.0,
                 max_attempts=None, budget_s=None, backoff_s=0.05,
                 retry_statuses=RETRY_STATUSES):
        self._client = ServeClient(host, port, timeout=timeout)
        self.max_attempts = int(max_attempts
                                if max_attempts is not None
                                else _env_num(RETRIES_ENV, 4))
        self.budget_s = float(budget_s if budget_s is not None
                              else _env_num(RETRY_BUDGET_ENV, 15.0))
        self.backoff_s = float(backoff_s)
        self.retry_statuses = tuple(retry_statuses)

    @property
    def host(self):
        return self._client.host

    @property
    def port(self):
        return self._client.port

    def request(self, method, path, body=None, headers=None):
        """Returns the final ``(status, parsed_json, headers_dict)``.
        Raises the last transport error only when EVERY attempt
        failed before receiving any HTTP response."""
        t0 = time.monotonic()
        backoff = self.backoff_s
        last = None
        last_exc = None
        for attempt in range(max(self.max_attempts, 1)):
            if attempt:
                telemetry.counter_add("fleet.client.retries")
            wait = None
            try:
                status, obj, h = self._client.request(
                    method, path, body, headers=headers)
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, OSError) as e:
                last_exc = e
            else:
                last, last_exc = (status, obj, h), None
                if status not in self.retry_statuses:
                    return last
                wait = retry_after_from(h, obj)
            remaining = self.budget_s - (time.monotonic() - t0)
            if attempt >= self.max_attempts - 1 or remaining <= 0:
                break
            time.sleep(max(0.0, min(wait if wait is not None
                                    else backoff, remaining)))
            backoff *= 2.0
        telemetry.counter_add("fleet.client.giveups")
        if last is None:
            raise last_exc
        return last

    # convenience verbs (the ServeClient surface)
    def get(self, path):
        return self.request("GET", path)

    def post(self, path, body, headers=None):
        return self.request("POST", path, body, headers=headers)

    def close(self):
        self._client.close()


def request_with_retry(host, port, method, path, body=None,
                       timeout=60.0, headers=None, **kw):
    """One-shot request through the retry policy (fresh connection,
    closed after)."""
    c = RetryClient(host, port, timeout=timeout, **kw)
    try:
        return c.request(method, path, body, headers=headers)
    finally:
        c.close()
