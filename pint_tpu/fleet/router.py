"""Fleet router/front-proxy: N replicas behind one door.

Stdlib asyncio HTTP/1.1, the same skeleton as
:class:`pint_tpu.serve.server.Server` — the event loop never blocks
on a backend: every proxied call runs on the default executor, so a
slow replica stalls nothing but its own client.

Placement policy (the throughput story):

- **dataset → replica rendezvous hashing.**  The stacked-batch LRU
  and the dataset registry are per-process, so locality IS
  throughput: all requests for one dataset should land on one
  replica (its warm cache) and keep landing there across fleet
  membership changes.  Rendezvous (highest-random-weight) hashing
  gives exactly that: each (dataset, replica) pair gets a stable
  score, the live replica with the highest score owns the dataset,
  and a replica death only re-homes the datasets it owned.
- **same-bucket spread.**  When the owner is saturated (its
  router-side in-flight count reaches
  ``$PINT_TPU_ROUTER_SPREAD_PENDING``), the request spills to the
  next candidate in rendezvous order — bounded locality loss in
  exchange for not queueing behind a hot spot.
- **readiness-gated.**  A background prober polls every target's
  ``/readyz``; only ready replicas are candidates.  A replica that
  transitions down→up (a supervisor restart) gets the **dataset
  journal replayed** (every ``/v1/load`` body this router has seen)
  before it rejoins rotation — a freshly restarted process knows
  nothing, and routing to it before replay would 400.
- **backpressure honored, failures re-routed.**  A 429 shed moves to
  the next candidate; if every candidate sheds, the router returns
  the 429 with the LARGEST Retry-After (the honest fleet-wide hint).
  A 503 or connection error pulls the replica from rotation (the
  probe restores it) and re-routes.  Only when every candidate is
  down does the client see a structured 503 — and **never a 500**.
- **per-request retry budgets.**  At most ``$PINT_TPU_ROUTER_RETRY``
  proxy attempts per request — a bounded error budget, not a retry
  storm.

Streaming appends: ``POST /v1/datasets/<id>/append`` forwards to the
dataset's rendezvous OWNER only (never the spread rule — the
incremental stream session and the versioned dataset live in one
process, and spilling an append to a sibling would fork the dataset's
history).  Successful append bodies are journaled per dataset in
arrival order; a (re)joining replica gets them replayed right after
the dataset journal, so a supervisor-restarted owner — or the NEXT
candidate after an owner death — reconstructs the appended dataset
before it takes traffic.  A re-load of the dataset id clears its
append journal (the appends described TOAs of the replaced data).

Jobs: ``POST /v1/jobs`` routes by dataset and journals the spec
(stamped with its id); when a poll finds the owner has LOST the job —
dead, answering 404 (a deploy-respawned process with a fresh
in-memory store), or reporting ``"interrupted"`` after a drain
checkpointed it — the router resubmits the journaled spec (shared
job dir ⇒ the new run resumes from the checkpoint losing ≤ 1 chunk)
— ``GET /v1/jobs/<id>`` fails over transparently.

The router keeps its OWN :class:`~pint_tpu.obs.slo.SloTracker` (not
the process singleton): its windows measure CLIENT-visible outcomes
(after re-routing), which is the fleet's real SLO; ``/slo`` serves
it and ``/fleet`` serves the merged per-replica view
(:func:`pint_tpu.obs.fleet.fleet_snapshot`).

Telemetry: ``router.requests`` / ``router.reroutes`` /
``router.retries`` / ``router.sheds`` / ``router.all_down`` /
``router.proxy_errors`` / ``router.replays`` /
``router.job_failovers`` / ``router.appends`` /
``router.append_journal`` / ``router.append_replays`` counters;
``router.replicas_ready`` /
``router.replicas_total`` / ``router.inflight`` gauges.  All
``PINT_TPU_ROUTER_*`` knobs are host-only: they shape placement and
retry policy, never a traced program (the router process runs no
device code at all).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import threading
import time

from pint_tpu import faults as _faults
from pint_tpu import telemetry
from pint_tpu.fleet.client import retry_after_from
from pint_tpu.obs import slo as _slo
from pint_tpu.serve.client import request_json

__all__ = ["Router", "rendezvous_order",
           "ROUTER_PORT_ENV", "ROUTER_HOST_ENV", "RETRY_ENV",
           "PROBE_S_ENV", "SPREAD_ENV"]

# host-only knobs (lint/static.py HOST_ONLY)
ROUTER_PORT_ENV = "PINT_TPU_ROUTER_PORT"
ROUTER_HOST_ENV = "PINT_TPU_ROUTER_HOST"
RETRY_ENV = "PINT_TPU_ROUTER_RETRY"
PROBE_S_ENV = "PINT_TPU_ROUTER_PROBE_S"
SPREAD_ENV = "PINT_TPU_ROUTER_SPREAD_PENDING"

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 429: "Too Many Requests",
            503: "Service Unavailable", 504: "Gateway Timeout"}

_MAX_BODY = 8 << 20

#: ops proxied through the coalescing data plane
_OPS = ("fit", "residuals", "lnlike")


def _env_num(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def rendezvous_order(dataset, targets) -> list:
    """Highest-random-weight order of ``targets`` for ``dataset``:
    stable per pair, so membership changes only re-home the dead
    replica's datasets — the property that preserves every OTHER
    replica's warm stacked-batch LRU through a kill or deploy."""
    def score(t):
        h = hashlib.sha256(
            f"{dataset}|{t}".encode("utf-8", "replace")).digest()
        return h
    return sorted(targets, key=score, reverse=True)


class _Replica:
    """Router-side view of one backend."""

    __slots__ = ("target", "ready", "inflight", "replayed",
                 "last_error", "last_probe_ts")

    def __init__(self, target):
        self.target = str(target)
        self.ready = False
        self.inflight = 0
        self.replayed = False      # dataset journal delivered?
        self.last_error = None
        self.last_probe_ts = 0.0

    @property
    def host(self):
        return self.target.rsplit(":", 1)[0]

    @property
    def port(self):
        return int(self.target.rsplit(":", 1)[1])

    def doc(self):
        return {"target": self.target, "ready": self.ready,
                "inflight": self.inflight,
                "replayed": self.replayed,
                "error": self.last_error}


class Router:
    """The front-proxy: readiness-probed replica table + rendezvous
    placement + bounded re-routing + router-side SLO."""

    def __init__(self, targets=(), probe_s=None, retry=None,
                 spread_pending=None, slo_p99_ms=None, slo_avail=None,
                 proxy_timeout=120.0):
        self.probe_s = float(probe_s if probe_s is not None
                             else _env_num(PROBE_S_ENV, 0.5))
        self.retry = int(retry if retry is not None
                         else _env_num(RETRY_ENV, 4))
        self.spread_pending = int(
            spread_pending if spread_pending is not None
            else _env_num(SPREAD_ENV, 8))
        self.proxy_timeout = float(proxy_timeout)
        # the router's OWN tracker: client-visible outcomes after
        # re-routing — deliberately not the process singleton
        self.slo = _slo.SloTracker(p99_ms=slo_p99_ms, avail=slo_avail)
        self._lock = threading.Lock()
        self._replicas: dict = {}      # target -> _Replica
        self._datasets: dict = {}      # dataset id -> /v1/load body
        self._ds_order: list = []      # registration order
        self._appends: dict = {}       # dataset id -> [append bodies]
        self._jobs: dict = {}          # job id -> journaled spec
        self._job_owner: dict = {}     # job id -> target
        for t in targets:
            self._replicas[str(t)] = _Replica(t)
        self._loop = None
        self._aserver = None
        self._thread = None
        self._port = None
        self._started = threading.Event()
        self._stop_probe = threading.Event()
        self._probe_thread = None

    # -- membership ---------------------------------------------------------
    def set_targets(self, targets):
        """Declare the replica set (the supervisor calls this on
        membership changes).  Existing state for kept targets
        survives; removed targets leave rotation immediately."""
        targets = [str(t) for t in targets]
        with self._lock:
            for t in targets:
                if t not in self._replicas:
                    self._replicas[t] = _Replica(t)
            for t in list(self._replicas):
                if t not in targets:
                    del self._replicas[t]
        self._export_gauges()

    def targets(self) -> list:
        with self._lock:
            return list(self._replicas)

    def replica_docs(self) -> list:
        with self._lock:
            return [r.doc() for r in self._replicas.values()]

    def _export_gauges(self):
        with self._lock:
            n_ready = sum(r.ready for r in self._replicas.values())
            n_total = len(self._replicas)
            inflight = sum(r.inflight
                           for r in self._replicas.values())
        telemetry.gauge_set("router.replicas_ready", float(n_ready))
        telemetry.gauge_set("router.replicas_total", float(n_total))
        telemetry.gauge_set("router.inflight", float(inflight))

    # -- readiness probing + journal replay ---------------------------------
    def probe_now(self):
        """One synchronous probe sweep (the background prober's body;
        callable directly so tests and the supervisor can force a
        refresh instead of waiting a period)."""
        for target in self.targets():
            with self._lock:
                rep = self._replicas.get(target)
            if rep is None:
                continue
            try:
                status, doc, _ = request_json(
                    rep.host, rep.port, "GET", "/readyz", timeout=2.0)
            except OSError as e:
                # connection-level death: the PROCESS is likely gone,
                # so a future comeback needs the journal replayed
                with self._lock:
                    rep.ready = False
                    rep.replayed = False
                    rep.last_error = f"{type(e).__name__}: {e}"
                continue
            rep.last_probe_ts = time.monotonic()
            if status == 200:
                if not rep.replayed:
                    self._replay_datasets(rep)
                with self._lock:
                    rep.ready = rep.replayed
                    rep.last_error = None
            else:
                # an HTTP 503 (cold or DRAINING) is the same live
                # process refusing traffic: keep its replayed state —
                # its registry still holds the datasets
                with self._lock:
                    rep.ready = False
                    rep.last_error = (doc or {}).get("detail") \
                        or "not ready"
        self._export_gauges()

    def _replay_datasets(self, rep):
        """Deliver the dataset journal to a (re)joining replica —
        register-before-route, so a supervisor-restarted process
        never sees a request for a dataset it does not know.  Each
        dataset's journaled APPENDS replay right after its load, in
        arrival order: the rejoining process reconstructs the same
        appended, versioned dataset its predecessor (or the old
        owner) served."""
        with self._lock:
            order = list(self._ds_order)
            bodies = {d: self._datasets[d] for d in order}
            appends = {d: list(self._appends.get(d, ()))
                       for d in order}
        ok = True
        for ds in order:
            try:
                status, _, _ = request_json(
                    rep.host, rep.port, "POST", "/v1/load",
                    bodies[ds], timeout=self.proxy_timeout)
                if status != 200:
                    ok = False
                    break
                telemetry.counter_add("router.replays")
                for body in appends[ds]:
                    status, _, _ = request_json(
                        rep.host, rep.port, "POST",
                        f"/v1/datasets/{ds}/append", body,
                        timeout=self.proxy_timeout)
                    if status != 200:
                        ok = False
                        break
                    telemetry.counter_add("router.append_replays")
                if not ok:
                    break
            except OSError:
                ok = False
                break
        with self._lock:
            rep.replayed = ok

    def _probe_loop(self):
        while not self._stop_probe.wait(self.probe_s):
            try:
                self.probe_now()
            except Exception:  # noqa: BLE001 — the prober must
                pass           # survive anything a backend does

    # -- placement ----------------------------------------------------------
    def _candidates(self, dataset) -> list:
        """Ready replicas in rendezvous order for ``dataset``, with
        the spread rule applied: a saturated owner (inflight at the
        spread bound) yields to the next candidate with headroom."""
        with self._lock:
            ready = [t for t, r in self._replicas.items() if r.ready]
            inflight = {t: self._replicas[t].inflight for t in ready}
        order = rendezvous_order(dataset or "", ready)
        if len(order) >= 2 and self.spread_pending > 0 \
                and inflight.get(order[0], 0) >= self.spread_pending:
            spilled = min(order[1:], key=lambda t: inflight.get(t, 0))
            order = [spilled] + [t for t in order if t != spilled]
        return order

    def _mark_down(self, target, err):
        with self._lock:
            rep = self._replicas.get(target)
            if rep is not None:
                rep.ready = False
                rep.replayed = False
                rep.last_error = str(err)
        self._export_gauges()

    # -- proxying -----------------------------------------------------------
    def _proxy_sync(self, target, method, path, body, headers=None):
        """One forwarded request (executor thread).  Raises OSError
        on transport failure — the caller re-routes."""
        _faults.maybe_delay("router.forward")
        with self._lock:
            rep = self._replicas.get(target)
            if rep is not None:
                rep.inflight += 1
        try:
            host, _, port = target.rpartition(":")
            return request_json(host, int(port), method, path, body,
                                timeout=self.proxy_timeout,
                                headers=headers)
        finally:
            with self._lock:
                rep = self._replicas.get(target)
                if rep is not None:
                    rep.inflight = max(rep.inflight - 1, 0)

    async def _proxy(self, target, method, path, body, headers=None):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: self._proxy_sync(target, method, path,
                                           body, headers))

    def _fwd_headers(self, headers):
        """Headers worth forwarding: the trace context continues
        THROUGH the router, so one traceparent names the whole story
        client → router → replica → flush."""
        out = {}
        tp = (headers or {}).get("traceparent")
        if tp:
            out["traceparent"] = tp
        return out or None

    async def _route_op(self, op, params, headers):
        """The re-routing loop for one data-plane request: rendezvous
        candidates, bounded attempts, Retry-After honored between
        passes.  Every terminal outcome is recorded into the
        router-side SLO tracker."""
        t0 = time.perf_counter()
        telemetry.counter_add("router.requests")
        telemetry.counter_add(f"router.requests.{op}")
        dataset = params.get("dataset")
        fwd = self._fwd_headers(headers)
        attempts = 0
        sheds = []          # (retry_after_s, status, obj, hdrs)
        last_err = None
        for sweep in range(2):
            if sweep:
                # every candidate shed in sweep 0: honor the smallest
                # Retry-After (the soonest any replica asked to be
                # retried), bounded by the per-request budget
                if not sheds or attempts >= self.retry:
                    break
                hint = min(ra for ra, *_ in sheds)
                await asyncio.sleep(min(max(hint, 0.0), 5.0))
                sheds = []
            cands = self._candidates(dataset)
            if not cands:
                break
            for target in cands:
                if attempts >= self.retry:
                    break
                attempts += 1
                if attempts > 1:
                    telemetry.counter_add("router.retries")
                try:
                    status, obj, h = await self._proxy(
                        target, "POST", f"/v1/{op}", params, fwd)
                except OSError as e:
                    telemetry.counter_add("router.proxy_errors")
                    telemetry.counter_add("router.reroutes")
                    self._mark_down(target, e)
                    last_err = f"{target}: {type(e).__name__}: {e}"
                    continue
                if status == 429:
                    ra = retry_after_from(h, obj)
                    sheds.append((ra if ra is not None else 0.2,
                                  status, obj, h))
                    telemetry.counter_add("router.reroutes")
                    continue
                if status == 503:
                    # draining or failing: pull it (the probe
                    # restores a live one) and re-route
                    telemetry.counter_add("router.reroutes")
                    self._mark_down(target,
                                    (obj or {}).get("detail", 503))
                    last_err = f"{target}: 503"
                    continue
                # 200, 400, 404, 504...: the client's answer
                self.slo.record(op, time.perf_counter() - t0,
                                ok=(status == 200))
                return status, obj, h
        self.slo.record(op, time.perf_counter() - t0, ok=False)
        if sheds:
            # every candidate shed: the fleet is saturated — tell the
            # client the LARGEST hint (the honest time until capacity)
            telemetry.counter_add("router.sheds")
            ra, status, obj, h = max(sheds, key=lambda s: s[0])
            return status, obj, h
        telemetry.counter_add("router.all_down")
        detail = ("no ready replicas"
                  if last_err is None else
                  f"all candidate replicas failed (last: {last_err})")
        return (503,
                {"error": "ServeError", "detail": detail,
                 "retry_after_ms": 1000},
                {"retry-after": "1"})

    # -- streaming appends: owner-only forwarding + journal ------------------
    def _owner_order(self, dataset) -> list:
        """Ready replicas in STRICT rendezvous order — no spread
        spill.  Appends must land on the dataset's owner: the stream
        session and its versioned history live in one process, and a
        spilled append would fork them.  Position 0 is the owner;
        later entries only matter after the owner leaves rotation
        (they are, in order, its successors)."""
        with self._lock:
            ready = [t for t, r in self._replicas.items() if r.ready]
        return rendezvous_order(dataset or "", ready)

    async def _route_append(self, ds_id, params, headers):
        """Forward one append to the dataset's rendezvous owner; on
        owner death (transport error / 503) the next candidate IS the
        new owner once the probe pulls the dead one, so the bounded
        retry walks the succession order.  A 200 journals the body
        for restart replay."""
        telemetry.counter_add("router.appends")
        fwd = self._fwd_headers(headers)
        cands = self._owner_order(ds_id)
        last_err = None
        for target in cands[:max(self.retry, 1)]:
            try:
                status, obj, h = await self._proxy(
                    target, "POST", f"/v1/datasets/{ds_id}/append",
                    params, fwd)
            except OSError as e:
                telemetry.counter_add("router.proxy_errors")
                telemetry.counter_add("router.reroutes")
                self._mark_down(target, e)
                last_err = f"{target}: {type(e).__name__}: {e}"
                continue
            if status == 503:
                telemetry.counter_add("router.reroutes")
                self._mark_down(target,
                                (obj or {}).get("detail", 503))
                last_err = f"{target}: 503"
                continue
            if status == 200:
                with self._lock:
                    self._appends.setdefault(ds_id, []).append(
                        dict(params))
                telemetry.counter_add("router.append_journal")
            return status, obj, h
        detail = ("no ready replicas" if last_err is None else
                  f"all candidate replicas failed (last: {last_err})")
        return (503, {"error": "ServeError", "detail": detail,
                      "retry_after_ms": 1000},
                {"retry-after": "1"})

    # -- job routing + failover ---------------------------------------------
    async def _route_job_submit(self, params, headers):
        dataset = params.get("dataset")
        fwd = self._fwd_headers(headers)
        cands = self._candidates(dataset)
        last = None
        for target in cands[:max(self.retry, 1)]:
            try:
                status, obj, h = await self._proxy(
                    target, "POST", "/v1/jobs", params, fwd)
            except OSError as e:
                telemetry.counter_add("router.proxy_errors")
                self._mark_down(target, e)
                continue
            if status == 200 and isinstance(obj, dict) \
                    and obj.get("job"):
                job_id = str(obj["job"])
                with self._lock:
                    # journal the spec WITH its id: the failover
                    # resubmit must resume, not mint a fresh job
                    self._jobs[job_id] = {**params, "job": job_id}
                    self._job_owner[job_id] = target
                return status, obj, h
            last = (status, obj, h)
            if status != 503:
                return last
        if last is not None:
            return last
        return (503, {"error": "ServeError",
                      "detail": "no ready replicas",
                      "retry_after_ms": 1000},
                {"retry-after": "1"})

    async def _route_job_status(self, job_id):
        with self._lock:
            owner = self._job_owner.get(job_id)
            spec = self._jobs.get(job_id)
        got = None
        if owner is not None:
            try:
                status, obj, h = await self._proxy(
                    owner, "GET", f"/v1/jobs/{job_id}", None)
                # an owner that ANSWERS can still have lost the job.
                # The document of record lives in the SHARED job dir
                # and outlives its writer, so a respawned owner
                # happily serves its dead predecessor's last
                # "running" write: trust a queued/running doc only
                # when the owner says the job is live IN ITS process
                # (``live`` explicitly False — absent means an older
                # replica, keep the old trust-the-answer behavior).
                # A 404 (no shared dir) or a drain-checkpointed
                # "interrupted" doc is equally lost — resubmit, the
                # checkpoint resume loses at most one chunk.
                lost = (status == 404
                        or (status == 200 and isinstance(obj, dict)
                            and (obj.get("state") == "interrupted"
                                 or (obj.get("state") in
                                     ("queued", "running")
                                     and obj.get("live") is False))))
                if status != 503 and not lost:
                    return status, obj, h
                got = (status, obj, h)
            except OSError as e:
                telemetry.counter_add("router.proxy_errors")
                self._mark_down(owner, e)
        if spec is None:
            return got if got is not None else (
                404, {"error": "NotFound"}, {})
        # the owner is gone: resubmit the journaled spec to a sibling
        # — same id + shared job dir ⇒ checkpoint resume (≤ 1 chunk
        # lost), the document of record survives the replica
        telemetry.counter_add("router.job_failovers")
        resub = await self._route_job_submit(spec, None)
        if resub[0] == 200:
            with self._lock:
                owner = self._job_owner.get(job_id)
            if owner is not None:
                try:
                    return await self._proxy(
                        owner, "GET", f"/v1/jobs/{job_id}", None)
                except OSError:
                    pass
        return resub

    # -- lifecycle (the Server skeleton) ------------------------------------
    def start(self, host="127.0.0.1", port=None) -> int:
        if self._thread is not None:
            return self._port
        if port is None:
            port = int(_env_num(ROUTER_PORT_ENV, 0))
        self._thread = threading.Thread(
            target=self._run_loop, args=(host, int(port)),
            name="pintfleet-router", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("router listener failed to start")
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="pintfleet-probe",
            daemon=True)
        self._probe_thread.start()
        return self._port

    def _run_loop(self, host, port):
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def _boot():
            self._aserver = await asyncio.start_server(
                self._handle, host, port)
            self._port = self._aserver.sockets[0].getsockname()[1]
            telemetry.gauge_set("router.port", self._port)
            self._started.set()

        try:
            loop.run_until_complete(_boot())
            loop.run_forever()
        finally:
            try:
                if self._aserver is not None:
                    self._aserver.close()
                    loop.run_until_complete(
                        self._aserver.wait_closed())
                pending = [t for t in asyncio.all_tasks(loop)
                           if not t.done()]
                for t in pending:
                    t.cancel()
                if pending:
                    loop.run_until_complete(asyncio.gather(
                        *pending, return_exceptions=True))
            finally:
                loop.close()

    def stop(self):
        self._stop_probe.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        loop, self._loop = self._loop, None
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- HTTP plumbing (same wire discipline as the replica) -----------------
    async def _handle(self, reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, path, _ = line.decode(
                        "latin1").split(None, 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                n = int(headers.get("content-length", 0) or 0)
                if n > _MAX_BODY:
                    return
                body = await reader.readexactly(n) if n else b""
                status, payload, ctype, extra = await self._route(
                    method.upper(), path.split("?", 1)[0], body,
                    headers)
                keep = headers.get("connection",
                                   "keep-alive").lower() != "close"
                head = [f"HTTP/1.1 {status} "
                        f"{_REASONS.get(status, 'OK')}",
                        f"Content-Type: {ctype}",
                        f"Content-Length: {len(payload)}"]
                head += [f"{k}: {v}" for k, v in extra]
                head.append("Connection: "
                            + ("keep-alive" if keep else "close"))
                writer.write(("\r\n".join(head) + "\r\n\r\n")
                             .encode() + payload)
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    @staticmethod
    def _json(status, obj, extra=()):
        return (status, json.dumps(obj).encode(), "application/json",
                list(extra))

    def _passthrough(self, status, obj, hdrs):
        """Re-emit a backend response, carrying the headers that
        matter across a hop (Retry-After, traceparent)."""
        extra = []
        for k in ("retry-after", "traceparent", "server-timing"):
            v = (hdrs or {}).get(k)
            if v is not None:
                extra.append((k.title(), v))
        return self._json(status, obj, extra)

    async def _route(self, method, path, body, headers=None):
        try:
            return await self._route_inner(method, path, body,
                                           headers or {})
        except (ValueError, KeyError, TypeError) as e:
            return self._json(400, {"error": "BadRequest",
                                    "detail": str(e)})
        except Exception as e:  # noqa: BLE001 — the no-500 contract
            # holds at the router too: an unexpected failure is a
            # structured, retryable 503
            telemetry.counter_add("router.proxy_errors")
            return self._json(
                503, {"error": "ServeError",
                      "detail": f"{type(e).__name__}: {e}",
                      "retry_after_ms": 1000},
                [("Retry-After", "1")])

    async def _route_inner(self, method, path, body, headers):
        path = path.rstrip("/") or "/"
        if method == "GET":
            if path == "/healthz":
                return self._json(200, self._health_doc())
            if path == "/readyz":
                with self._lock:
                    n_ready = sum(r.ready
                                  for r in self._replicas.values())
                doc = {"ready": n_ready > 0,
                       "replicas_ready": n_ready,
                       "replicas_total": len(self.targets())}
                if n_ready:
                    return self._json(200, doc)
                return self._json(503, doc, [("Retry-After", "1")])
            if path == "/slo":
                return self._json(200, self.slo.snapshot())
            if path == "/metrics":
                from pint_tpu import metrics_http

                self.slo.snapshot()  # refresh slo.* gauges
                self._export_gauges()
                return (200, metrics_http.render_prometheus()
                        .encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                        [])
            if path == "/fleet":
                from pint_tpu.obs import fleet as _fleet

                loop = asyncio.get_running_loop()
                doc = await loop.run_in_executor(
                    None, lambda: _fleet.fleet_snapshot(
                        self.targets()))
                doc.pop("scrapes", None)  # drill-down is pinttrace's
                return self._json(200, doc)
            if path == "/v1/stats":
                return self._json(200, self._stats_doc())
            if path == "/":
                return self._json(200, {"routes": [
                    "POST /v1/load", "POST /v1/fit",
                    "POST /v1/residuals", "POST /v1/lnlike",
                    "POST /v1/datasets/<id>/append",
                    "POST /v1/jobs", "GET /v1/jobs/<id>",
                    "GET /healthz", "GET /readyz", "GET /metrics",
                    "GET /slo", "GET /fleet", "GET /v1/stats",
                ]})
            if path.startswith("/v1/jobs/"):
                return self._passthrough(*await
                                         self._route_job_status(
                                             path.rsplit("/", 1)[1]))
            return self._json(404, {"error": "NotFound"})
        if method != "POST":
            return self._json(405, {"error": "MethodNotAllowed"})
        params = json.loads(body.decode() or "{}")
        if path == "/v1/load":
            return await self._broadcast_load(params)
        if path == "/v1/jobs":
            return self._passthrough(*await self._route_job_submit(
                params, headers))
        if path.startswith("/v1/datasets/") and \
                path.endswith("/append"):
            ds_id = path[len("/v1/datasets/"):-len("/append")]
            if not ds_id or "/" in ds_id:
                return self._json(404, {"error": "NotFound"})
            return self._passthrough(*await self._route_append(
                ds_id, params, headers))
        if path in tuple(f"/v1/{op}" for op in _OPS):
            op = path.rsplit("/", 1)[1]
            return self._passthrough(*await self._route_op(
                op, params, headers))
        return self._json(404, {"error": "NotFound"})

    async def _broadcast_load(self, params):
        """Register a dataset on EVERY ready replica and journal the
        body — late joiners (restarts, scale-ups) get it replayed
        before they rejoin rotation."""
        ds = params.get("dataset")
        if not ds:
            return self._json(400, {"error": "BadRequest",
                                    "detail": "missing 'dataset'"})
        with self._lock:
            if ds not in self._datasets:
                self._ds_order.append(ds)
            self._datasets[ds] = dict(params)
            # the journaled appends described the REPLACED data
            self._appends.pop(ds, None)
        with self._lock:
            ready = [t for t, r in self._replicas.items() if r.ready]
        telemetry.counter_add("router.broadcast_loads")
        results = []
        info = None
        for target in ready:
            try:
                status, obj, _ = await self._proxy(
                    target, "POST", "/v1/load", params)
            except OSError as e:
                self._mark_down(target, e)
                results.append({"target": target, "ok": False,
                                "error": f"{type(e).__name__}"})
                continue
            ok = status == 200
            if ok and info is None:
                info = obj
            results.append({"target": target, "ok": ok})
        n_ok = sum(r["ok"] for r in results)
        if ready and n_ok == 0:
            return self._json(503, {"error": "ServeError",
                                    "detail": "load failed on every "
                                              "ready replica",
                                    "replicas": results},
                              [("Retry-After", "1")])
        doc = dict(info or {})
        doc["replicas"] = results
        doc["journaled"] = True
        return self._json(200, doc)

    # -- documents ----------------------------------------------------------
    def _health_doc(self):
        return {
            "role": "router",
            "replicas": self.replica_docs(),
            "datasets": list(self._ds_order),
            "appends_journaled": {d: len(v) for d, v
                                  in self._appends.items()},
            "jobs_journaled": len(self._jobs),
            "slo": self.slo.verdict_doc(),
        }

    def _stats_doc(self):
        ctr = telemetry.counters()
        return {
            "replicas": self.replica_docs(),
            "datasets": list(self._ds_order),
            "retry": self.retry,
            "spread_pending": self.spread_pending,
            "probe_s": self.probe_s,
            "slo": self.slo.verdict_doc(),
            "counters": {k: v for k, v in ctr.items()
                         if k.startswith("router.")},
        }
