"""Replica supervisor: spawn, watch, restart, roll, and scale N
``pintserve`` processes.

The process-management half of the fleet layer (the router is the
traffic half; :class:`FleetSupervisor` keeps the router's target list
current):

- **spawn/monitor** — each replica slot is one ``pintserve``
  subprocess on its own port, all sharing one job dir (so a sibling
  can resume any replica's checkpointed jobs) and one compile/AOT
  artifact dir (so restarts re-warm from serialized executables, not
  fresh XLA compiles).
- **restarts with exponential backoff** — a crashed replica is
  respawned after ``backoff · 2^crashes`` seconds, and a slot that
  crashes ``$PINT_TPU_FLEET_CRASH_LOOP_K`` times inside the crash
  window is **quarantined**: pulled from the router and left down for
  a human — a crash-looping replica forever cycling through rotation
  is worse than one honestly absent.
- **rolling deploys** — ``rolling_deploy(new_aot_dir)`` walks the
  slots one at a time: ``POST /drain`` (the replica flips
  ``/readyz``, finishes in-flight flushes, checkpoints its running
  job, exits 0), swap in the new artifact, respawn, wait ready, move
  on.  With N ≥ 2 replicas the fleet never has zero ready members —
  measured and returned as ``downtime_s`` (the bench
  ``rolling_deploy_downtime_s`` series asserts it stays ~0).
- **autoscaling** — :func:`autoscale_decision` is a pure function of
  the fleet's queue-depth/shed gauges (scraped from ``/metrics`` via
  :mod:`pint_tpu.obs.fleet`); the tick applies it within
  ``[min_replicas, max_replicas]``.

Every ``PINT_TPU_FLEET_*`` knob is host-only: process counts,
backoffs, and windows shape the harness around the replicas, never a
traced program.  Telemetry: ``fleet.restarts`` / ``fleet.crash_loops``
/ ``fleet.deploys`` / ``fleet.drains`` / ``fleet.scale_ups`` /
``fleet.scale_downs`` counters; ``fleet.replicas`` /
``fleet.target_replicas`` gauges.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

from pint_tpu import telemetry
from pint_tpu.serve.client import request_json

__all__ = ["FleetSupervisor", "autoscale_decision", "free_port",
           "REPLICAS_ENV", "BACKOFF_ENV", "CRASH_LOOP_K_ENV",
           "MIN_REPLICAS_ENV", "MAX_REPLICAS_ENV", "AUTOSCALE_S_ENV"]

# host-only knobs (lint/static.py HOST_ONLY)
REPLICAS_ENV = "PINT_TPU_FLEET_REPLICAS"
BACKOFF_ENV = "PINT_TPU_FLEET_BACKOFF_S"
CRASH_LOOP_K_ENV = "PINT_TPU_FLEET_CRASH_LOOP_K"
MIN_REPLICAS_ENV = "PINT_TPU_FLEET_MIN_REPLICAS"
MAX_REPLICAS_ENV = "PINT_TPU_FLEET_MAX_REPLICAS"
AUTOSCALE_S_ENV = "PINT_TPU_FLEET_AUTOSCALE_S"


def _env_num(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def free_port(host="127.0.0.1") -> int:
    """An OS-assigned free port (bind-then-close; the tiny reuse race
    is acceptable for a supervisor that owns its own port space)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def autoscale_decision(current, queue_depth, sheds_delta,
                       min_replicas, max_replicas,
                       queue_high=32.0, queue_low=2.0) -> int:
    """Pure scaling policy: the fleet-summed ``serve.queue_depth``
    gauge and the shed-counter delta since the last tick decide the
    target replica count.  Sheds mean admission is refusing work NOW
    (scale up even if the queue gauge looks calm — shed work never
    queued); a deep fleet queue means the same; a near-empty queue
    with zero sheds releases one replica per tick (gentle scale-down,
    never a cliff)."""
    current = int(current)
    lo = max(int(min_replicas), 1)
    hi = max(int(max_replicas), lo)
    if current < lo:
        return lo
    if (sheds_delta > 0 or queue_depth > queue_high) and current < hi:
        return current + 1
    if sheds_delta == 0 and queue_depth <= queue_low \
            and current > lo:
        return current - 1
    return min(current, hi)


class _Slot:
    """One replica slot: a port that outlives its processes."""

    __slots__ = ("index", "port", "proc", "aot_dir", "extra_env",
                 "crashes", "crash_times", "quarantined",
                 "next_spawn_ts", "expecting_exit", "log_path")

    def __init__(self, index, port, aot_dir=None, extra_env=None,
                 log_path=None):
        self.index = index
        self.port = port
        self.proc = None
        self.aot_dir = aot_dir
        self.extra_env = dict(extra_env or {})
        self.crashes = 0
        self.crash_times: list = []
        self.quarantined = False
        self.next_spawn_ts = 0.0
        self.expecting_exit = False
        self.log_path = log_path

    @property
    def target(self):
        return f"127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def doc(self):
        return {"index": self.index, "target": self.target,
                "alive": self.alive(),
                "pid": (None if self.proc is None
                        else self.proc.pid),
                "crashes": self.crashes,
                "quarantined": self.quarantined}


class FleetSupervisor:
    """Own N replica subprocesses; keep a :class:`Router` fed with
    the live target list.

    ``replica_cmd`` is injectable for tests: a callable
    ``(slot) -> argv`` returning the subprocess command (the default
    builds the real ``pintserve`` invocation).  ``datasets`` is a
    list of ``(id, par_path, tim_path_or_None)`` registered at every
    replica boot via ``--dataset``."""

    def __init__(self, n_replicas=None, datasets=(), aot_dir=None,
                 job_dir=None, base_env=None, replica_cmd=None,
                 backoff_s=None, crash_loop_k=None,
                 crash_window_s=30.0, min_replicas=None,
                 max_replicas=None, router=None, warm=False,
                 serve_args=(), log_dir=None, tick_s=0.2,
                 slot_env=None):
        self.n_replicas = int(n_replicas if n_replicas is not None
                              else _env_num(REPLICAS_ENV, 2))
        self.datasets = list(datasets)
        self.aot_dir = aot_dir
        self.job_dir = (job_dir
                        or tempfile.mkdtemp(prefix="pintfleet_jobs_"))
        self.base_env = dict(base_env if base_env is not None
                             else os.environ)
        self.replica_cmd = replica_cmd or self._default_cmd
        self.backoff_s = float(backoff_s if backoff_s is not None
                               else _env_num(BACKOFF_ENV, 0.5))
        self.crash_loop_k = int(
            crash_loop_k if crash_loop_k is not None
            else _env_num(CRASH_LOOP_K_ENV, 3))
        self.crash_window_s = float(crash_window_s)
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _env_num(MIN_REPLICAS_ENV, 1))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _env_num(MAX_REPLICAS_ENV, 8))
        self.router = router
        self.warm = bool(warm)
        self.serve_args = list(serve_args)
        self.log_dir = log_dir or tempfile.mkdtemp(
            prefix="pintfleet_logs_")
        self.tick_s = float(tick_s)
        #: per-slot-index extra env (chaos uses this to aim a
        #: PINT_TPU_FAULTS kill at ONE replica)
        self.slot_env = {int(k): dict(v)
                         for k, v in (slot_env or {}).items()}
        self._slots: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor = None
        self._sheds_seen = 0.0

    # -- process plumbing ---------------------------------------------------
    def _default_cmd(self, slot) -> list:
        argv = [sys.executable, "-m", "pint_tpu.serve.cli",
                "--host", "127.0.0.1", "--port", str(slot.port),
                "--job-dir", self.job_dir]
        if slot.aot_dir:
            # --warm alongside --import: over the AOT store the warm
            # sweep is a cheap pre-arm dress rehearsal of every
            # registered dataset — it absorbs the serving path's
            # first-use eager compiles BEFORE the recompile sanitizer
            # arms, so a steady-state replica really is violation-free
            argv += ["--import", slot.aot_dir, "--warm"]
        elif self.warm:
            argv += ["--warm"]
        for name, par, tim in self.datasets:
            spec = f"{name}={par}" + (f",{tim}" if tim else "")
            argv += ["--dataset", spec]
        argv += self.serve_args
        return argv

    def _spawn(self, slot):
        env = {**self.base_env, **slot.extra_env,
               "PINT_TPU_SERVE_JOB_DIR": self.job_dir}
        log = open(os.path.join(
            self.log_dir, f"replica{slot.index}.log"), "ab")
        try:
            slot.proc = subprocess.Popen(
                self.replica_cmd(slot), env=env,
                stdout=log, stderr=log,
                stdin=subprocess.DEVNULL)
        finally:
            log.close()  # the child holds its own descriptor
        slot.expecting_exit = False

    def _notify_router(self):
        if self.router is not None:
            self.router.set_targets(self.targets())
        telemetry.gauge_set("fleet.replicas",
                            float(len(self._slots)))

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> list:
        """Spawn every slot; returns the target list.  Readiness is
        the router's business (probe + journal replay) — callers that
        need a warm fleet use :meth:`wait_ready`."""
        with self._lock:
            for i in range(self.n_replicas):
                slot = _Slot(i, free_port(), aot_dir=self.aot_dir,
                             extra_env=self.slot_env.get(i))
                self._slots.append(slot)
                self._spawn(slot)
        telemetry.gauge_set("fleet.target_replicas",
                            float(self.n_replicas))
        self._notify_router()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="pintfleet-monitor",
            daemon=True)
        self._monitor.start()
        return self.targets()

    def stop(self):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        with self._lock:
            slots = list(self._slots)
        for slot in slots:
            if slot.proc is not None and slot.proc.poll() is None:
                slot.proc.terminate()
        deadline = time.monotonic() + 5.0
        for slot in slots:
            if slot.proc is None:
                continue
            left = max(deadline - time.monotonic(), 0.1)
            try:
                slot.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                slot.proc.kill()
                slot.proc.wait(timeout=5.0)

    def targets(self) -> list:
        """Routable targets: every non-quarantined slot (a briefly
        dead slot stays listed — the router's probe marks it down and
        restores it after the supervisor restart)."""
        with self._lock:
            return [s.target for s in self._slots
                    if not s.quarantined]

    def slot_docs(self) -> list:
        with self._lock:
            return [s.doc() for s in self._slots]

    def wait_ready(self, timeout=300.0, min_ready=None) -> bool:
        """Block until ``min_ready`` (default: all) replicas answer
        ``/readyz`` 200."""
        want = (len(self.targets()) if min_ready is None
                else int(min_ready))
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            n = 0
            for t in self.targets():
                host, _, port = t.rpartition(":")
                try:
                    status, _, _ = request_json(
                        host, int(port), "GET", "/readyz",
                        timeout=2.0)
                    n += status == 200
                except OSError:
                    pass
            if n >= want:
                if self.router is not None:
                    self.router.probe_now()
                return True
            time.sleep(0.25)
        return False

    # -- crash supervision --------------------------------------------------
    def _monitor_loop(self):
        while not self._stop.wait(self.tick_s):
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — the monitor survives
                pass           # anything a child does

    def poll(self):
        """One supervision tick: reap crashes, schedule/execute
        backoff restarts, quarantine crash-loopers."""
        now = time.monotonic()
        changed = False
        with self._lock:
            slots = list(self._slots)
        for slot in slots:
            if slot.quarantined or slot.proc is None:
                continue
            rc = slot.proc.poll()
            if rc is None:
                continue
            if slot.expecting_exit:
                # drain-initiated exit (rolling deploy / scale-down):
                # the deployer owns the respawn
                continue
            # a crash (or an unsupervised clean exit: a replica that
            # stops serving is down either way)
            slot.proc = None
            slot.crashes += 1
            slot.crash_times = [t for t in slot.crash_times
                                if now - t <= self.crash_window_s]
            slot.crash_times.append(now)
            if len(slot.crash_times) >= self.crash_loop_k:
                slot.quarantined = True
                telemetry.counter_add("fleet.crash_loops")
                changed = True
                continue
            slot.next_spawn_ts = now + self.backoff_s * (
                2.0 ** (len(slot.crash_times) - 1))
        # execute due restarts
        for slot in slots:
            if (slot.proc is None and not slot.quarantined
                    and now >= slot.next_spawn_ts):
                self._spawn(slot)
                telemetry.counter_add("fleet.restarts")
        if changed:
            self._notify_router()

    # -- rolling deploy -----------------------------------------------------
    def drain_slot(self, slot, timeout=120.0) -> bool:
        """Drain one replica and wait for its process to exit 0.  A
        connection drop on the drain response counts as success when
        the process exits — the exit IS the acknowledgement."""
        from pint_tpu.fleet.client import request_with_retry

        slot.expecting_exit = True
        telemetry.counter_add("fleet.drains")
        try:
            request_with_retry(
                "127.0.0.1", slot.port, "POST", "/drain",
                {"timeout_s": timeout}, timeout=timeout,
                max_attempts=1)
        except OSError:
            pass  # judged by the exit below
        if slot.proc is None:
            return True
        try:
            slot.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            slot.proc.terminate()
            try:
                slot.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                slot.proc.kill()
                slot.proc.wait(timeout=5.0)
            return False
        return True

    def _ready_count(self) -> int:
        n = 0
        for t in self.targets():
            host, _, port = t.rpartition(":")
            try:
                status, _, _ = request_json(host, int(port), "GET",
                                            "/readyz", timeout=1.0)
                n += status == 200
            except OSError:
                pass
        return n

    def rolling_deploy(self, aot_dir=None, drain_timeout=120.0,
                       ready_timeout=300.0) -> dict:
        """Zero-downtime artifact swap: slot by slot — drain (readyz
        flips, in-flight work finishes, job checkpoints, process
        exits 0), respawn on the new AOT dir, wait ready, next.
        Returns the deploy record including measured ``downtime_s``:
        seconds during the deploy with ZERO ready replicas (0.0 is
        the zero-downtime claim, sampled at 50 ms)."""
        t0 = time.monotonic()
        if aot_dir is not None:
            self.aot_dir = aot_dir
        downtime = [0.0]
        stop_sampler = threading.Event()

        def _sample():
            last = time.monotonic()
            while not stop_sampler.wait(0.05):
                now = time.monotonic()
                if self._ready_count() == 0:
                    downtime[0] += now - last
                last = now

        sampler = threading.Thread(target=_sample, daemon=True)
        sampler.start()
        swapped = []
        try:
            with self._lock:
                slots = [s for s in self._slots if not s.quarantined]
            for slot in slots:
                drained = self.drain_slot(slot,
                                          timeout=drain_timeout)
                slot.proc = None
                slot.aot_dir = self.aot_dir
                self._spawn(slot)
                telemetry.counter_add("fleet.restarts")
                deadline = time.monotonic() + ready_timeout
                ready = False
                while time.monotonic() < deadline:
                    try:
                        status, _, _ = request_json(
                            "127.0.0.1", slot.port, "GET",
                            "/readyz", timeout=2.0)
                        if status == 200:
                            ready = True
                            break
                    except OSError:
                        pass
                    time.sleep(0.2)
                if self.router is not None:
                    self.router.probe_now()
                swapped.append({"target": slot.target,
                                "drained": drained,
                                "ready": ready})
        finally:
            stop_sampler.set()
            sampler.join(timeout=2.0)
        telemetry.counter_add("fleet.deploys")
        return {"replicas": swapped,
                "aot_dir": self.aot_dir,
                "downtime_s": round(downtime[0], 3),
                "wall_s": round(time.monotonic() - t0, 3)}

    # -- autoscaling --------------------------------------------------------
    def scale_to(self, n) -> list:
        """Grow/shrink to ``n`` slots (grow spawns; shrink drains the
        highest-index slots and removes them)."""
        n = max(1, int(n))
        with self._lock:
            current = len(self._slots)
        if n > current:
            with self._lock:
                for i in range(current, n):
                    slot = _Slot(i, free_port(),
                                 aot_dir=self.aot_dir,
                                 extra_env=self.slot_env.get(i))
                    self._slots.append(slot)
                    self._spawn(slot)
            telemetry.counter_add("fleet.scale_ups", n - current)
        elif n < current:
            with self._lock:
                victims = self._slots[n:]
                self._slots = self._slots[:n]
            for slot in victims:
                if slot.alive():
                    self.drain_slot(slot, timeout=60.0)
            telemetry.counter_add("fleet.scale_downs", current - n)
        telemetry.gauge_set("fleet.target_replicas", float(n))
        self._notify_router()
        return self.targets()

    def autoscale_tick(self) -> dict:
        """Scrape the fleet, apply :func:`autoscale_decision`, and
        act on it.  Returns the decision record."""
        from pint_tpu.obs import fleet as _fleet

        doc = _fleet.fleet_snapshot(self.targets(), timeout=2.0)
        g = doc.get("gauges") or {}
        depth = (g.get("pint_tpu_serve_queue_depth") or {}).get(
            "sum", 0.0)
        sheds = (doc.get("counters") or {}).get(
            "pint_tpu_serve_sheds_total", 0.0)
        delta = max(sheds - self._sheds_seen, 0.0)
        self._sheds_seen = sheds
        with self._lock:
            current = len(self._slots)
        target = autoscale_decision(
            current, depth, delta,
            self.min_replicas, self.max_replicas)
        if target != current:
            self.scale_to(target)
        return {"current": current, "target": target,
                "queue_depth": depth, "sheds_delta": delta}
