"""``pintfleet``: boot a supervised replica fleet behind one router.

::

    # 4 supervised replicas + router on :8080, one shared AOT artifact
    pintfleet --replicas 4 --port 8080 --import /fast/aot \\
        --dataset J1909=J1909.par,J1909.tim

    # rolling-deploy a new artifact into a running fleet: re-run the
    # supervisor with the new --import dir (or drive
    # FleetSupervisor.rolling_deploy from code / the chaos harness)

The router listens on ``--port`` (or ``$PINT_TPU_ROUTER_PORT``; 0
picks an ephemeral port, printed at boot).  Replica count defaults
from ``$PINT_TPU_FLEET_REPLICAS``.  ``--autoscale SECONDS`` enables
the queue-depth/shed-rate autoscaler between ``--min-replicas`` and
``--max-replicas``.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    from pint_tpu.fleet.supervisor import (
        AUTOSCALE_S_ENV, MAX_REPLICAS_ENV, MIN_REPLICAS_ENV,
        REPLICAS_ENV,
    )

    p = argparse.ArgumentParser(
        prog="pintfleet",
        description="supervised pintserve fleet behind a "
                    "rendezvous-hashing router")
    p.add_argument("--replicas", type=int, default=None,
                   help=f"replica count (default ${REPLICAS_ENV} "
                        "or 2)")
    p.add_argument("--host", default="127.0.0.1",
                   help="router bind host")
    p.add_argument("--port", type=int, default=None,
                   help="router port (default $PINT_TPU_ROUTER_PORT "
                        "or 0 = ephemeral)")
    p.add_argument("--import", dest="import_dir", metavar="DIR",
                   default=None,
                   help="AOT manifest every replica imports at boot")
    p.add_argument("--warm", action="store_true",
                   help="explicit warmup at each replica boot")
    p.add_argument("--job-dir", default=None,
                   help="SHARED job directory (sibling replicas "
                        "resume each other's checkpointed jobs)")
    p.add_argument("--dataset", action="append", default=[],
                   metavar="ID=PAR[,TIM]",
                   help="dataset registered on every replica at "
                        "boot (repeatable)")
    p.add_argument("--autoscale", type=float, default=None,
                   metavar="SECONDS",
                   help="autoscaler tick period (default "
                        f"${AUTOSCALE_S_ENV}; unset/0 = off)")
    p.add_argument("--min-replicas", type=int, default=None,
                   help=f"autoscale floor (default "
                        f"${MIN_REPLICAS_ENV} or 1)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help=f"autoscale ceiling (default "
                        f"${MAX_REPLICAS_ENV} or 8)")
    return p


def _parse_datasets(specs) -> list:
    out = []
    for spec in specs:
        name, _, paths = spec.partition("=")
        if not name or not paths:
            raise SystemExit(
                f"--dataset {spec!r}: expected ID=PAR[,TIM]")
        par, _, tim = paths.partition(",")
        out.append((name, par, tim or None))
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from pint_tpu.fleet.router import Router
    from pint_tpu.fleet.supervisor import (
        AUTOSCALE_S_ENV, FleetSupervisor,
    )

    autoscale_s = args.autoscale
    if autoscale_s is None:
        raw = os.environ.get(AUTOSCALE_S_ENV, "").strip()
        autoscale_s = float(raw) if raw else 0.0

    router = Router()
    sup = FleetSupervisor(
        n_replicas=args.replicas,
        datasets=_parse_datasets(args.dataset),
        aot_dir=args.import_dir, job_dir=args.job_dir,
        warm=args.warm, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas, router=router)
    try:
        targets = sup.start()
        port = router.start(host=args.host, port=args.port)
        print(f"pintfleet: router on {args.host}:{port}  "
              f"replicas: {', '.join(targets)}", flush=True)
        print(f"pintfleet: logs under {sup.log_dir}  "
              f"jobs under {sup.job_dir}", flush=True)
        if sup.wait_ready(timeout=600.0, min_ready=1):
            print("pintfleet: fleet ready", flush=True)
        else:
            print("pintfleet: WARNING no replica became ready "
                  "within 600s", file=sys.stderr, flush=True)
        while True:
            time.sleep(autoscale_s if autoscale_s > 0 else 3600)
            if autoscale_s > 0:
                d = sup.autoscale_tick()
                if d["target"] != d["current"]:
                    print(f"pintfleet: autoscale "
                          f"{d['current']} -> {d['target']} "
                          f"(queue={d['queue_depth']:.0f} "
                          f"sheds={d['sheds_delta']:.0f})",
                          flush=True)
    except KeyboardInterrupt:
        print("pintfleet: shutting down", flush=True)
    finally:
        router.stop()
        sup.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
