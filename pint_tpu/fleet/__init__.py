"""Fleet orchestration: N serve replicas as one fault-tolerant
service (ROADMAP item 2).

The layer above :mod:`pint_tpu.serve`: one replica is a hardened
process; production is N of them behind a router, supervised, rolled,
and chaos-tested as a unit.

- :mod:`pint_tpu.fleet.client` — the shared HTTP helper every in-repo
  load path uses: bounded retry/backoff that honors 429/503
  ``Retry-After`` hints, with a per-request attempt + wall-clock
  budget.
- :mod:`pint_tpu.fleet.router` — the front-proxy: dataset→replica
  rendezvous hashing (stacked-batch LRU locality), same-bucket load
  spreading, ``/readyz``-gated placement, backpressure-aware
  re-routing, and a router-side SLO tracker over client-visible
  outcomes.
- :mod:`pint_tpu.fleet.supervisor` — spawns/monitors N ``pintserve``
  subprocesses: exponential-backoff restarts, crash-loop quarantine,
  zero-downtime rolling deploys of a new AOT artifact (drain → swap →
  re-warm), queue-depth/shed-rate autoscaling.
- :mod:`pint_tpu.fleet.chaos` — the standing soak: the corpus mix
  streamed through the router while replicas are killed and deployed,
  asserting bounded error budgets, job resume on siblings, and a
  violation-free sanitizer fleet-wide.

``pintfleet`` (:mod:`pint_tpu.fleet.cli`) boots a supervised fleet +
router as one command.  See docs/fleet.md.
"""

from __future__ import annotations

__all__ = ["RetryClient", "request_with_retry", "Router",
           "FleetSupervisor", "chaos_soak"]


def __getattr__(name):  # lazy: keep `import pint_tpu.fleet` cheap
    if name in ("RetryClient", "request_with_retry"):
        from pint_tpu.fleet import client as _m

        return getattr(_m, name)
    if name == "Router":
        from pint_tpu.fleet.router import Router

        return Router
    if name == "FleetSupervisor":
        from pint_tpu.fleet.supervisor import FleetSupervisor

        return FleetSupervisor
    if name == "chaos_soak":
        from pint_tpu.fleet.chaos import chaos_soak

        return chaos_soak
    raise AttributeError(name)
