"""Fleet chaos harness: the corpus mix through the router while
replicas die and deploy.

The fleet-level twin of :mod:`pint_tpu.corpus.replay`: the same
deterministic scenario mix and 70/20/10 op stream, but fired through a
:class:`~pint_tpu.fleet.router.Router` fronting N REAL ``pintserve``
subprocesses under a :class:`~pint_tpu.fleet.supervisor
.FleetSupervisor` — and then the harness breaks things on purpose:

- **mid-batch replica death** — the victim (the rendezvous owner of
  the first dataset, so it is guaranteed traffic) is respawned with
  ``PINT_TPU_FAULTS=kill:site=serve.flush:after=K``: its Kth batch
  flush or grid chunk hard-exits the process mid-work, exactly the
  fault :mod:`pint_tpu.faults` injects everywhere else.  The router
  must re-route (clients see retries, never 5xx) and the supervisor
  must restart the replica (the fault env is cleared on first death
  so the respawn is clean).
- **checkpointed-job failover** — a grid job is submitted through the
  router onto the victim before the kill; after the death the poll
  path resubmits it to a sibling, which resumes from the shared
  job-dir checkpoint losing at most one chunk.
- **rolling deploy under load** (opt-in) — the supervisor walks the
  fleet mid-stream; the measured zero-ready downtime rides the stats.
- **sanitizer fleet-wide** — every replica runs with
  ``$PINT_TPU_RECOMPILE_SANITIZER`` armed over an AOT artifact
  exported by an in-process rehearsal (same datasets, same op set,
  same grid geometry), so any post-warm compile anywhere in the fleet
  is a counted violation in the final scrape.

Returns one structured stats dict (stream outcomes, router counters,
SLO verdict, job document, deploy record, fleet-summed sanitizer
violations) — consumed by ``bench_fleet``, ``datacheck --fleet`` and
the chaos tests.  Telemetry: ``fleet.chaos.requests`` /
``fleet.chaos.errors`` counters.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time

from pint_tpu import telemetry

__all__ = ["chaos_soak", "KILL_SPEC"]

#: the injected fault: Kth ``serve.flush`` call (batch flush OR grid
#: chunk) hard-exits the replica mid-work
KILL_SPEC = "kill:site=serve.flush:after={after}"


def _mixed_op(i):
    from pint_tpu.corpus.replay import _mixed_op as m

    return m(i)


def _rehearse(scenarios, files, aot_dir, maxiter, grid_spec,
              job_chunk):
    """In-process AOT rehearsal: warm every (op, dataset) program —
    and the grid-chunk program when a job rides the soak — then
    export the executables as the fleet's deploy artifact."""
    from pint_tpu import compile_cache as _cc
    from pint_tpu.serve import jobs as _jobs
    from pint_tpu.serve.server import Server

    srv = Server(queue_max=4096, deadline_ms=0)
    try:
        for s in scenarios:
            par_path, tim_path = files[s.name]
            srv.registry.load(s.name, par=par_path, tim=tim_path)
        f0 = float(srv.registry.get(scenarios[0].name)
                   .model.values["F0"])
        for s in scenarios:
            srv.warmup(s.name, ops=("fit", "lnlike", "residuals"),
                       maxiter=maxiter)
        if grid_spec is not None:
            grid_spec = dict(grid_spec)
            a = grid_spec["axes"]["F0"]
            a.setdefault("start", f0 - 1e-10)
            a.setdefault("stop", f0 + 1e-10)
            with tempfile.TemporaryDirectory(
                    prefix="pintchaos_rehearse_") as jd:
                doc = {"job": "rehearsal", "kind": "grid",
                       "spec": grid_spec}
                _jobs.run_job(srv.registry, doc, jd,
                              grid_chunk=job_chunk)
        out = _cc.export_executables(aot_dir)
    finally:
        srv.stop()
    return {"exported": len(out.get("exported", ())), "f0": f0,
            "grid_spec": grid_spec}


def chaos_soak(n_replicas=2, n_requests=120,
               classes=("spin", "binary"), kill=True, kill_after=4,
               deploy=False, job=True, grid_points=16, job_chunk=4,
               maxiter=2, slo_p99_ms=None, slo_avail=None,
               base_seed=0, ready_timeout=600.0, request_timeout=120.0,
               keep_dirs=False, workdir=None) -> dict:
    """Run one chaos soak; returns the stats dict (never raises for
    in-stream failures — they are counted).  ``kill``/``deploy``/
    ``job`` toggle the three fault stories independently so the lean
    tier-1 test and the full acceptance soak share this one body."""
    from pint_tpu.corpus.replay import default_mix
    from pint_tpu.fleet.client import RetryClient
    from pint_tpu.fleet.router import Router, rendezvous_order
    from pint_tpu.fleet.supervisor import FleetSupervisor
    from pint_tpu.obs import fleet as _obs_fleet

    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pintchaos_")
    data_dir = os.path.join(workdir, "data")
    aot_dir = os.path.join(workdir, "aot")
    job_dir = os.path.join(workdir, "jobs")
    log_dir = os.path.join(workdir, "logs")
    for d in (data_dir, aot_dir, job_dir, log_dir):
        os.makedirs(d, exist_ok=True)

    scenarios = default_mix(base_seed=base_seed, classes=classes)
    files = {}
    for s in scenarios:
        files[s.name] = s.write(data_dir)
    ids = [s.name for s in scenarios]

    grid_spec = None
    if job:
        grid_spec = {"kind": "grid", "dataset": ids[0],
                     "job": "chaosjob", "params": ["F0"],
                     "n_steps": 1, "chunk": int(job_chunk),
                     "axes": {"F0": {"n": int(grid_points)}}}
    rehearsal = _rehearse(scenarios, files, aot_dir, maxiter,
                          grid_spec, job_chunk)
    grid_spec = rehearsal.pop("grid_spec", None)

    env = dict(os.environ)
    env.setdefault("PINT_TPU_RECOMPILE_SANITIZER", "warn")
    env.setdefault("PINT_TPU_CACHE_DIR",
                   os.path.join(workdir, "cache"))
    env.pop("PINT_TPU_FAULTS", None)  # only the victim gets faults

    router = Router(slo_p99_ms=slo_p99_ms, slo_avail=slo_avail)
    sup = FleetSupervisor(
        n_replicas=n_replicas,
        datasets=[(n, files[n][0], files[n][1]) for n in ids],
        aot_dir=aot_dir, job_dir=job_dir, base_env=env,
        router=router, log_dir=log_dir)
    stats = {"replicas": int(n_replicas),
             "requests": int(n_requests), "datasets": ids,
             "rehearsal": rehearsal}
    client = None
    try:
        sup.start()
        router.start(port=0)
        if not sup.wait_ready(timeout=ready_timeout):
            raise RuntimeError(
                f"fleet not ready within {ready_timeout}s "
                f"(logs under {log_dir})")

        victim_slot = None
        if kill and n_replicas >= 2:
            # the victim must be guaranteed traffic: the rendezvous
            # owner of the first dataset.  Its fault env only exists
            # at spawn time, so bounce it (expected exit, direct
            # respawn — not a counted crash) with the kill armed.
            victim = rendezvous_order(ids[0], sup.targets())[0]
            for s in sup._slots:
                if s.target == victim:
                    victim_slot = s
                    break
            victim_slot.extra_env["PINT_TPU_FAULTS"] = \
                KILL_SPEC.format(after=int(kill_after))
            victim_slot.expecting_exit = True
            victim_slot.proc.terminate()
            victim_slot.proc.wait(timeout=30)
            sup._spawn(victim_slot)
            if not sup.wait_ready(timeout=ready_timeout):
                raise RuntimeError("victim respawn never ready")

            def _clear_fault():
                # first death disarms the fault, so the supervisor's
                # restart comes back clean instead of crash-looping
                while victim_slot.proc is not None \
                        and victim_slot.proc.poll() is None:
                    time.sleep(0.02)
                victim_slot.extra_env.pop("PINT_TPU_FAULTS", None)

            threading.Thread(target=_clear_fault,
                             daemon=True).start()
        router.probe_now()

        job_doc = None
        if grid_spec is not None:
            client = RetryClient("127.0.0.1", router._port,
                                 timeout=request_timeout,
                                 max_attempts=6, budget_s=60.0)
            status, job_doc, _ = client.post("/v1/jobs", grid_spec)
            stats["job_submit_status"] = status

        ok = 0
        errors = 0
        five_xx = 0
        statuses: dict = {}
        deploy_doc: dict = {}

        def _deploy():
            deploy_doc.update(sup.rolling_deploy())

        deploy_thread = None
        client = client or RetryClient(
            "127.0.0.1", router._port, timeout=request_timeout,
            max_attempts=6, budget_s=60.0)
        t0 = time.time()
        for i in range(int(n_requests)):
            if deploy and deploy_thread is None \
                    and i >= int(n_requests) * 0.25:
                deploy_thread = threading.Thread(target=_deploy,
                                                 daemon=True)
                deploy_thread.start()
            op = _mixed_op(i)
            body = {"dataset": ids[i % len(ids)]}
            if op == "fit":
                body["maxiter"] = maxiter
            try:
                status, r, _ = client.post(f"/v1/{op}", body)
            except OSError:
                errors += 1
                statuses["conn_error"] = \
                    statuses.get("conn_error", 0) + 1
                client.close()
                continue
            statuses[status] = statuses.get(status, 0) + 1
            if status == 200 and r.get("status") == "ok":
                ok += 1
            else:
                errors += 1
                if status >= 500:
                    five_xx += 1
            telemetry.counter_add("fleet.chaos.requests")
        wall = time.time() - t0
        if deploy_thread is not None:
            deploy_thread.join(timeout=600)

        if grid_spec is not None:
            # poll THROUGH the router: if the owner died this is the
            # failover path (resubmit to a sibling, checkpoint resume)
            deadline = time.time() + 300
            while time.time() < deadline:
                status, job_doc, _ = client.get(
                    f"/v1/jobs/{grid_spec['job']}")
                if status == 200 and job_doc.get("state") in (
                        "done", "failed"):
                    break
                time.sleep(0.25)
            stats["job"] = job_doc

        # settle, then scrape every replica for the fleet-wide
        # sanitizer verdict and merged counters
        sup.wait_ready(timeout=60)
        fleet_doc = _obs_fleet.fleet_snapshot(sup.targets(),
                                              timeout=5.0)
        ctr = telemetry.counters()
        if errors:
            telemetry.counter_add("fleet.chaos.errors", errors)
        stats.update({
            "ok": ok, "errors": errors, "client_5xx": five_xx,
            "statuses": {str(k): v for k, v in statuses.items()},
            "wall_s": round(wall, 3),
            "rps": round(int(n_requests) / wall, 3) if wall else 0.0,
            "kill": {"armed": bool(victim_slot is not None),
                     "victim": (victim_slot.target
                                if victim_slot else None),
                     "crashes": (victim_slot.crashes
                                 if victim_slot else 0)},
            "deploy": deploy_doc or None,
            "sanitizer_violations": (fleet_doc.get("counters") or {})
            .get("pint_tpu_sanitizer_violations_total", 0.0),
            "fleet": {"replicas_up": fleet_doc.get("replicas_up"),
                      "replicas_total": fleet_doc.get("replicas")},
            "router_counters": {k: v for k, v in ctr.items()
                                if k.startswith("router.")},
            "slo": router.slo.verdict_doc(),
        })
        telemetry.emit({"type": "fleet_chaos", **{
            k: stats[k] for k in ("replicas", "requests", "ok",
                                  "errors", "client_5xx", "wall_s",
                                  "rps", "sanitizer_violations")}})
        return stats
    finally:
        if client is not None:
            client.close()
        router.stop()
        sup.stop()
        if own_workdir and not keep_dirs:
            shutil.rmtree(workdir, ignore_errors=True)
