"""Residuals: observed-minus-model phase and time residuals.

Counterpart of the reference Residuals (reference: src/pint/residuals.py:40,
``calc_phase_resids`` at :314-425, ``calc_time_resids`` at :483,
``calc_chi2`` at :669).  Phase residuals come out of the jitted model as
an (int64 turns, f64 frac) pair; 'nearest' tracking is the frac part by
construction, 'pulse_number' tracking differences the integer part against
tracked pulse numbers.  Mean subtraction is weighted (1/err^2) and skipped
when the model carries an explicit PHOFF (reference :372-425 semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.timing_model import PreparedModel, TimingModel

__all__ = ["Residuals"]


def weighted_mean_phase(frac, weights):
    return jnp.sum(frac * weights) / jnp.sum(weights)


class Residuals:
    """Residuals bound to (toas, model); evaluation is jit-compiled."""

    def __init__(self, toas, model, subtract_mean=None, track_mode="nearest"):
        self.toas = toas
        if isinstance(model, TimingModel):
            self.prepared = model.prepare(toas)
        else:
            self.prepared = model
        self.model = self.prepared.model
        if subtract_mean is None:
            subtract_mean = not self.model.has_component("PhaseOffset")
        self.subtract_mean = subtract_mean
        if track_mode not in ("nearest", "pulse_number"):
            raise ValueError(f"unknown track_mode {track_mode!r}")
        if track_mode == "pulse_number":
            raise NotImplementedError(
                "pulse_number tracking lands with the pulse-number column "
                "(-pn flags / track_pulse_numbers) milestone"
            )
        self.track_mode = track_mode
        self._weights = jnp.asarray(1.0 / self.toas.error_us**2)
        self._phase_resids_jit = jax.jit(self.phase_resids_fn)
        self._time_resids_jit = jax.jit(self.time_resids_fn)
        self._chi2_jit = jax.jit(self.chi2_fn)

    # -- pure functions (values pytree -> arrays), jit-safe ------------------
    def phase_resids_fn(self, values):
        _, frac = self.prepared._phase_raw(values)
        resid = frac
        if self.subtract_mean:
            resid = resid - weighted_mean_phase(resid, self._weights)
        return resid

    def time_resids_fn(self, values):
        return self.phase_resids_fn(values) / values["F0"]

    def chi2_fn(self, values):
        r = self.time_resids_fn(values)
        err = self.prepared.batch.error_s
        return jnp.sum((r / err) ** 2)

    # -- convenience numpy accessors -----------------------------------------
    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def phase_resids(self):
        return np.asarray(self._phase_resids_jit(self._values()))

    @property
    def time_resids(self):
        return np.asarray(self._time_resids_jit(self._values()))

    @property
    def chi2(self):
        return float(self._chi2_jit(self._values()))

    @property
    def dof(self):
        return len(self.toas) - len(self.model.free_params) - int(
            self.subtract_mean
        )

    @property
    def reduced_chi2(self):
        return self.chi2 / self.dof

    def rms_weighted(self):
        """Weighted RMS of time residuals [s]."""
        r = self.time_resids
        w = 1.0 / (self.toas.error_us * 1e-6) ** 2
        return float(np.sqrt(np.sum(r**2 * w) / np.sum(w)))
