"""Residuals: observed-minus-model phase and time residuals.

Counterpart of the reference Residuals (reference: src/pint/residuals.py:40,
``calc_phase_resids`` at :314-425, ``calc_time_resids`` at :483,
``calc_chi2`` at :669, ``lnlikelihood`` at :713).  Phase residuals come
out of the jitted model as an (int64 turns, f64 frac) pair; 'nearest'
tracking is the frac part by construction, 'pulse_number' tracking
differences the integer part against tracked pulse numbers.  Mean
subtraction is weighted (1/sigma^2, noise-scaled) and skipped when the
model carries an explicit PHOFF (reference :372-425 semantics).

chi^2 dispatch mirrors the reference: plain WLS sum when the model has
no correlated noise; Woodbury over the low-rank noise basis otherwise,
with a unit basis column at weight 1e40 absorbing the subtracted mean
(reference :567-636, the 1e40 column at :583-585).

Compile-amortization contract (:mod:`pint_tpu.compile_cache`): every
evaluation function exists in ``*_at(values, data)`` form, where
``data`` is the dataset pytree (:meth:`Residuals._data` — TOA batch,
prepare-time ctx arrays, noise basis, pulse numbers) passed as a
DYNAMIC jit argument.  A trace of an ``_at`` function bakes in only
model *structure* (:meth:`Residuals._structure_key`), so the process
jit registry can share one trace — and one XLA executable — across
fitter instances and across same-bucket datasets.  The classic
closure-style ``*_fn(values)`` functions remain as thin delegates that
bind this instance's concrete data (the grid path still wants data
constant-folded into its one big program).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import os

from pint_tpu import compile_cache as _cc
from pint_tpu import faults as _faults
from pint_tpu import telemetry
from pint_tpu.linalg import (StructuredU, structured_from_dense_blocks,
                             woodbury_chi2_logdet)
from pint_tpu.models.timing_model import PreparedModel, TimingModel, \
    _env_on
from pint_tpu.telemetry import span

__all__ = ["Residuals", "WidebandDMResiduals", "WidebandTOAResiduals",
           "segment_ecorr_default"]


def segment_ecorr_default() -> bool:
    """Whether eligible ECORR bases are carried as segment ids
    (``$PINT_TPU_SEGMENT_ECORR``, default on; 0/off forces the dense
    fallback everywhere)."""
    return _env_on("PINT_TPU_SEGMENT_ECORR")

#: weight given to the synthetic constant-offset basis column when the
#: mean is subtracted (reference residuals.py:583-585 uses 1e40; we use
#: 1e30 because TPU emulates f64 as a float32 pair whose high word
#: saturates at ~3.4e38 — 1e40 silently becomes inf on device and NaNs
#: the Cholesky.  1e30 s^2 of prior variance is equally "infinite" for
#: any real dataset)
MEAN_OFFSET_WEIGHT = 1e30


def weighted_mean_phase(frac, weights):
    return jnp.sum(frac * weights) / jnp.sum(weights)


class Residuals:
    """Residuals bound to (toas, model); evaluation is jit-compiled."""

    def __init__(self, toas, model, subtract_mean=None, track_mode=None,
                 use_weighted_mean=True):
        self.toas = toas
        if isinstance(model, TimingModel):
            self.prepared = model.prepare(toas)
        else:
            self.prepared = model
        self.model = self.prepared.model
        if subtract_mean is None:
            subtract_mean = not self.model.has_component("PhaseOffset")
        self.subtract_mean = subtract_mean
        self.use_weighted_mean = use_weighted_mean
        # track-mode resolution (reference residuals.py:133-149):
        # explicit arg > par TRACK -2/0 > presence of complete -pn flags
        pn = toas.get_pulse_numbers() if hasattr(
            toas, "get_pulse_numbers") else None
        if track_mode is None or track_mode == "auto":
            track = self.model.meta.get("TRACK", "")
            if track == "-2":
                track_mode = "use_pulse_numbers"
            elif track == "0":
                track_mode = "nearest"
            elif pn is not None and not np.any(np.isnan(pn)):
                track_mode = "use_pulse_numbers"
            else:
                track_mode = "nearest"
        if track_mode == "pulse_number":  # accept both spellings
            track_mode = "use_pulse_numbers"
        if track_mode not in ("nearest", "use_pulse_numbers"):
            raise ValueError(f"unknown track_mode {track_mode!r}")
        if track_mode == "use_pulse_numbers":
            if pn is None:
                raise ValueError(
                    "track_mode requires pulse numbers but the TOAs "
                    "carry no -pn flags (use toas.compute_pulse_numbers)"
                )
            if np.any(np.isnan(pn)):
                raise ValueError("Pulse numbers are missing on some TOAs")
            self._pulse_numbers = jnp.asarray(pn, dtype=jnp.int64)
        else:
            self._pulse_numbers = None
        dpn = (toas.get_delta_pulse_numbers() if hasattr(
            toas, "get_delta_pulse_numbers") else np.zeros(0))
        self._delta_pn = (jnp.asarray(dpn) if np.any(dpn != 0.0)
                          else None)
        self.track_mode = track_mode
        # extended Woodbury basis (mean-offset column appended), built
        # eagerly OUTSIDE any trace — see _noise_basis_phi.  Always
        # built: the wideband solve uses it even with a width-0 basis.
        U = self.prepared.noise_basis
        if self.subtract_mean:
            U = jnp.concatenate([U, jnp.ones((U.shape[0], 1))], axis=1)
        self._U_ext = U
        # structure-aware ECORR: when the model's single EcorrNoise
        # block is a disjoint 0/1 epoch-indicator matrix (each TOA in
        # at most one epoch — always true of create_quantization_matrix
        # output under disjoint selects), carry it as per-TOA segment
        # ids so the Woodbury contractions run through segment_sum
        # instead of dense (N, K_e) matmuls.  Overlapping masks, a
        # non-indicator basis, multiple ECORR components, or the env
        # gate keep the dense fallback.
        su = self._build_structured_U(U) if segment_ecorr_default() \
            else None
        if su is not None:
            self._U_ext = su
        # TOA-count bucketing (compile_cache.pad_toas): sentinel rows
        # beyond n_real carry ~zero weight; dof/NTOA/lnlike accounting
        # uses the real count, and the lnlike logdet masks pad rows
        # (their log sigma would otherwise bias noise fitting)
        self.n_real = getattr(toas, "n_real", None) or len(toas)
        # bucketed datasets ALWAYS carry the mask (all-true at a bucket
        # boundary) so every member of a bucket shares one trace
        # structure; unbucketed datasets carry none.  A dataset whose
        # pad rows are NOT a suffix (epoch-aligned TOA sharding
        # inserts sentinel rows at shard boundaries —
        # compile_cache.apply_toa_row_plan) carries an explicit
        # ``pad_valid`` mask instead of the arange convention.
        self._pad_valid = None
        explicit_mask = getattr(toas, "pad_valid", None)
        if explicit_mask is not None:
            mask = np.asarray(explicit_mask, dtype=bool)
            self._pad_valid = jnp.asarray(mask)
            self.n_real = int(np.count_nonzero(mask))
        elif getattr(toas, "n_real", None) is not None:
            self._pad_valid = jnp.asarray(
                np.arange(len(toas)) < self.n_real)
        # dataset pytree split: array leaves travel as jit arguments,
        # static python leaves stay closed over (and keyed)
        self._ctx_dyn, self._ctx_static = _cc.split_ctx(self.prepared.ctx)
        self._tzr_ctx_dyn, self._tzr_ctx_static = _cc.split_ctx(
            self.prepared.tzr_ctx)
        self._data_cached = None
        self._structure_key_cached = None
        # jit wrappers are resolved lazily through the process-level
        # shared registry: a 14-component GLS model costs tens of
        # seconds of XLA compile per function on CPU, most callers
        # touch only one of the four, and a second same-structure
        # Residuals must reuse the first one's traces
        self._jit_cache: dict = {}

    def _build_structured_U(self, U_ext):
        """StructuredU over the dense extended basis, or None when the
        dataset/model is ineligible (dense fallback)."""
        ecorrs = [c for c in self.prepared._noise_basis_comps
                  if getattr(c, "category", "") == "ecorr_noise"]
        if len(ecorrs) != 1:
            return None
        dims = self.prepared.noise_dimensions()
        start, nb = dims[type(ecorrs[0]).__name__]
        if nb == 0:
            return None
        B = np.asarray(U_ext[:, start:start + nb])
        if not np.isin(B, (0.0, 1.0)).all():
            return None
        rowsum = B.sum(axis=1)
        if rowsum.max(initial=0.0) > 1.0:
            return None  # overlapping epochs: dense fallback
        seg = np.where(rowsum > 0, B.argmax(axis=1), nb)
        return structured_from_dense_blocks(
            U_ext[:, :start], seg, nb, U_ext[:, start + nb:])

    # -- dataset pytree / structural identity --------------------------------
    def _data(self):
        """The dataset as a pytree of arrays — the dynamic argument of
        every shared-trace evaluation function."""
        if self._data_cached is None:
            batch = self.prepared.batch
            if _faults.any_active():
                # fault injection happens HERE, at the host boundary
                # where concrete arrays become the dynamic dataset — a
                # corrupted dataset is ordinary data under the shared
                # traces and can never poison the jit registry
                batch = _faults.corrupt_batch(batch)
            self._data_cached = {
                "batch": batch,
                "ctx": self._ctx_dyn,
                "tzr_batch": self.prepared.tzr_batch,
                "tzr_ctx": self._tzr_ctx_dyn,
                "U_ext": self._U_ext,
                "pn": self._pulse_numbers,
                "dpn": self._delta_pn,
                "valid": self._pad_valid,
                # dynamic, NOT read from self inside a trace: a shared
                # trace serves instances with different real counts
                # (both across plain datasets of different lengths and
                # across members of one bucket)
                "n_real": np.float64(self.n_real),
            }
        return self._data_cached

    def _structure_key(self):
        """Everything a trace of the ``*_at`` functions bakes in."""
        if self._structure_key_cached is None:
            self._structure_key_cached = repr((
                _cc.model_structure_key(self.model),
                self.subtract_mean, self.use_weighted_mean,
                self.track_mode,
                self._pulse_numbers is not None,
                self._delta_pn is not None,
                self._pad_valid is not None,
                # segment-ECORR vs dense basis changes every Woodbury
                # trace; two same-model datasets can differ (epoch
                # overlap forces the dense fallback on one)
                isinstance(self._U_ext, StructuredU),
                _cc.static_ctx_key(self._ctx_static),
                _cc.static_ctx_key(self._tzr_ctx_static),
            ))
        return self._structure_key_cached

    def _ctx_at(self, data):
        return _cc.merge_ctx(data["ctx"], self._ctx_static)

    def _tzr_ctx_at(self, data):
        if data["tzr_ctx"] is None:
            return None
        return _cc.merge_ctx(data["tzr_ctx"], self._tzr_ctx_static)

    def ensure_kepler_depth(self, ecc_max):
        """Raise the binary ctx's static Kepler Newton depth to cover
        ``ecc_max`` (NaN -> full unroll; see
        PreparedModel.ensure_kepler_depth) and, when anything changed,
        re-split the ctx and drop the cached structure key / jit
        wrappers — the deeper unroll is a different traced program and
        must re-key every shared trace.  Returns True on change."""
        if not self.prepared.ensure_kepler_depth(ecc_max):
            return False
        self._rekey_after_ctx_change()
        return True

    def _rekey_after_ctx_change(self):
        """Re-split the (mutated) prepared ctx and drop every
        structure-keyed cache."""
        self._ctx_dyn, self._ctx_static = _cc.split_ctx(
            self.prepared.ctx)
        self._tzr_ctx_dyn, self._tzr_ctx_static = _cc.split_ctx(
            self.prepared.tzr_ctx)
        self._data_cached = None
        self._structure_key_cached = None
        self._jit_cache = {}

    def _jitted(self, name, fn):
        got = self._jit_cache.get(name)
        if got is None:
            telemetry.counter_add("residuals.jit_cache_misses")
            got = self._jit_cache[name] = _cc.shared_jit(
                fn, key=("residuals", name, self._structure_key()))
        else:
            telemetry.counter_add("residuals.jit_cache_hits")
        return got

    @property
    def _phase_resids_jit(self):
        return self._jitted("phase", self.phase_resids_at)

    @property
    def _time_resids_jit(self):
        return self._jitted("time", self.time_resids_at)

    @property
    def _chi2_jit(self):
        return self._jitted("chi2", self.chi2_at)

    @property
    def _lnlike_jit(self):
        return self._jitted("lnlike", self.lnlikelihood_at)

    # -- pure functions of (values, data), jit-safe and shareable ------------
    def sigma_at(self, values, data):
        """Noise-scaled per-TOA uncertainty [s]."""
        return self.prepared.scaled_sigma_fn(
            values, batch=data["batch"], ctx=self._ctx_at(data))

    def phase_resids_at(self, values, data):
        # data may carry precomputed frozen-component delays (the fit
        # hot path's "frozen"/"tzr_frozen" leaves; accessor datasets
        # don't) — the chain folds them in as data at their position
        n, frac = self.prepared._phase_raw_at(
            values, data["batch"], self._ctx_at(data),
            data["tzr_batch"], self._tzr_ctx_at(data),
            frozen=data.get("frozen"),
            tzr_frozen=data.get("tzr_frozen"))
        if self._pulse_numbers is not None:
            # TRACK -2 semantics (reference residuals.py:368-392):
            # residual = absolute model phase - assigned pulse number;
            # integer arithmetic first so 4e11-turn counts stay exact
            resid = (n - data["pn"]).astype(jnp.float64) + frac
            if self._delta_pn is not None:
                resid = resid + data["dpn"]
        else:
            resid = frac
            if self._delta_pn is not None:
                # PHASE commands shift the phase before the nearest-
                # integer assignment (reference residuals.py:394-406)
                resid = resid + data["dpn"]
                resid = resid - jnp.round(resid)
        if self.subtract_mean:
            if self.use_weighted_mean:
                w = 1.0 / self.sigma_at(values, data) ** 2
                resid = resid - weighted_mean_phase(resid, w)
            else:
                resid = resid - jnp.mean(resid)
        return resid

    def time_resids_at(self, values, data):
        return self.phase_resids_at(values, data) / values["F0"]

    def linear_design_at(self, values, data, names):
        """(N, L) time-residual design columns for the phase-linear
        parameters ``names`` — the analytic half of the hybrid design
        matrix (see PreparedModel.design_partition).  Applies exactly
        the transformations ``jacfwd`` of time_resids_at would: the TZR
        column subtraction, the /F0 turns-to-seconds conversion, and
        the (weighted-)mean subtraction with parameter-independent
        weights.  Honors the same frozen-delay data leaves as the
        residual evaluation."""
        prep = self.prepared
        cols = prep.linear_phase_columns(
            values, data["batch"], self._ctx_at(data), names,
            frozen=data.get("frozen"))
        if data["tzr_batch"] is not None:
            tcols = prep.linear_phase_columns(
                values, data["tzr_batch"], self._tzr_ctx_at(data),
                names, frozen=data.get("tzr_frozen"))
            cols = cols - tcols[0:1, :]
        cols = cols / values["F0"]
        if self.subtract_mean:
            if self.use_weighted_mean:
                w = 1.0 / self.sigma_at(values, data) ** 2
                cols = cols - jnp.sum(cols * w[:, None], axis=0) \
                    / jnp.sum(w)
            else:
                cols = cols - jnp.mean(cols, axis=0)
        return cols

    def _noise_basis_phi_at(self, values, data):
        """(U, phi) for the Woodbury paths, with the mean-offset column
        appended when applicable.

        The extended U is values-independent and prebuilt EAGERLY in
        __init__ (never inside a trace): concatenating in the traced
        function re-created the (n_toa, n_basis) matrix as a fresh
        constant-folded literal on every jit compile (XLA's
        constant-folding alarm fired on the f64[8161,402] pad), and a
        lazily-cached version leaks a tracer — jnp.ones under an
        active trace is staged, not concrete."""
        phi = self.prepared.noise_weights_fn(values, ctx=self._ctx_at(data))
        if self.subtract_mean:
            phi = jnp.concatenate([phi, jnp.array([MEAN_OFFSET_WEIGHT])])
        return data["U_ext"], phi

    def chi2_at(self, values, data):
        r = self.time_resids_at(values, data)
        sigma = self.sigma_at(values, data)
        if not self.model.has_correlated_errors:
            return jnp.sum((r / sigma) ** 2)
        U, phi = self._noise_basis_phi_at(values, data)
        chi2, _ = woodbury_chi2_logdet(r, sigma, U, phi)
        return chi2

    def lnlikelihood_at(self, values, data):
        """Gaussian log-likelihood of the residuals under the full noise
        covariance (reference residuals.py:713); differentiable wrt
        noise parameters for gradient-based noise fitting.  Bucketing
        pad rows are masked out of the white logdet (their
        EFAC-dependent log sigma would otherwise bias noise fits); the
        2*pi normalization counts real TOAs only."""
        r = self.time_resids_at(values, data)
        sigma = self.sigma_at(values, data)
        valid = data["valid"]
        n = data["n_real"]
        if not self.model.has_correlated_errors:
            chi2 = jnp.sum((r / sigma) ** 2)
            logs = jnp.log(sigma)
            if valid is not None:
                logs = jnp.where(valid, logs, 0.0)
            logdet = 2.0 * jnp.sum(logs)
        else:
            U, phi = self._noise_basis_phi_at(values, data)
            chi2, logdet = woodbury_chi2_logdet(r, sigma, U, phi,
                                                valid=valid)
        return -0.5 * (chi2 + logdet) - 0.5 * n * jnp.log(2.0 * jnp.pi)

    # -- classic closure forms (this dataset constant-folded) ----------------
    def sigma_fn(self, values):
        """Noise-scaled per-TOA uncertainty [s]."""
        return self.sigma_at(values, self._data())

    def phase_resids_fn(self, values):
        return self.phase_resids_at(values, self._data())

    def time_resids_fn(self, values):
        return self.time_resids_at(values, self._data())

    def _noise_basis_phi(self, values):
        return self._noise_basis_phi_at(values, self._data())

    def chi2_fn(self, values):
        return self.chi2_at(values, self._data())

    def lnlikelihood_fn(self, values):
        return self.lnlikelihood_at(values, self._data())

    def warm_compile(self):
        """AOT-compile the accessor programs a fit's epilogue touches
        (chi^2 and time residuals) — the other half of the cold-start
        cost next to the fitter step itself.  Returns compile
        seconds."""
        values = self._values()
        data = self._data()
        total = 0.0
        for name, fn in (("chi2", self.chi2_at),
                         ("time", self.time_resids_at)):
            lowered = self._jitted(name, fn).lower(values, data)
            total += _cc.warm_timed(lowered.compile)
        return total

    # -- convenience numpy accessors -----------------------------------------
    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def phase_resids(self):
        with span("residuals.calc", kind="phase",
                  n_toa=len(self.toas)):
            out = np.asarray(
                self._phase_resids_jit(self._values(), self._data()))
        telemetry.record_transfer(out)
        return out

    @property
    def time_resids(self):
        with span("residuals.calc", kind="time", n_toa=len(self.toas)):
            out = np.asarray(
                self._time_resids_jit(self._values(), self._data()))
        telemetry.record_transfer(out)
        return out

    @property
    def chi2(self):
        with span("residuals.calc", kind="chi2", n_toa=len(self.toas)):
            return float(self._chi2_jit(self._values(), self._data()))

    def lnlikelihood(self, values=None):
        with span("residuals.calc", kind="lnlike",
                  n_toa=len(self.toas)):
            return float(
                self._lnlike_jit(self._values(values), self._data()))

    @property
    def scaled_errors(self):
        """Noise-scaled uncertainties [s] at current parameter values."""
        return np.asarray(self.sigma_fn(self._values()))

    @property
    def ecorr_segment_cols(self):
        """Epoch count carried through segment-sums (0 on the dense
        fallback) — feeds the structure-aware FLOP cost model."""
        if isinstance(self._U_ext, StructuredU):
            return int(self._U_ext.eslot.shape[0])
        return 0

    @property
    def dof(self):
        return self.n_real - len(self.model.free_params) - int(
            self.subtract_mean
        )

    @property
    def reduced_chi2(self):
        return self.chi2 / self.dof

    def ecorr_average(self, use_noise_model=True):
        """Epoch-averaged residuals using the ECORR time binning
        (reference: residuals.py:842).

        Returns {"mjds", "freqs", "time_resids", "errors", "indices"}
        with one entry per ECORR epoch; with use_noise_model the
        weights use the scaled uncertainties and the ECORR variance is
        added to the averaged errors."""
        comp = None
        for c in self.model.noise_components:
            if getattr(c, "category", "") == "ecorr_noise":
                comp = c
        if comp is None or not comp.selects:
            raise ValueError("ECORR not present in noise model")
        ctx = self.prepared.ctx[type(comp).__name__]
        U = np.asarray(ctx["basis"])  # (N, n_epochs) 0/1
        values = self._values()
        ecorr_err2 = np.asarray(comp.weights(values, ctx))
        if use_noise_model:
            err = np.asarray(
                self._jitted("sigma", self.sigma_at)(values, self._data()))
        else:
            err = np.asarray(self.toas.error_us) * 1e-6
            ecorr_err2 = ecorr_err2 * 0.0
        wt = 1.0 / err**2
        a_norm = U.T @ wt

        def wtsum(x):
            return (U.T @ (wt * np.asarray(x))) / a_norm

        return {
            "mjds": wtsum(self.toas.mjd_float),
            "freqs": wtsum(np.where(np.isfinite(self.toas.freq_mhz),
                                    self.toas.freq_mhz, 0.0)),
            "time_resids": wtsum(self.time_resids),
            "errors": np.sqrt(1.0 / a_norm + ecorr_err2),
            "indices": [np.flatnonzero(U[:, j]).tolist()
                        for j in range(U.shape[1])],
        }

    def rms_weighted(self):
        """Weighted RMS of time residuals [s]."""
        r = self.time_resids
        w = 1.0 / self.scaled_errors**2
        return float(np.sqrt(np.sum(r**2 * w) / np.sum(w)))


class WidebandDMResiduals:
    """Wideband DM residuals: measured DM (``-pp_dm`` flags) minus the
    model's total DM (reference: WidebandDMResiduals,
    residuals.py:908-1077).  No mean subtraction by default (DM is an
    absolute measurement, reference :33)."""

    def __init__(self, toas, model, subtract_mean=False):
        self.toas = toas
        if isinstance(model, TimingModel):
            self.prepared = model.prepare(toas)
        else:
            self.prepared = model
        self.model = self.prepared.model
        dm, dme, valid = toas.wideband_dm_data()
        if not valid.any():
            raise ValueError(
                "no wideband DM data: TOAs lack -pp_dm flags"
            )
        self.valid = valid
        self.valid_idx = jnp.asarray(np.flatnonzero(valid))
        self.dm_data = jnp.asarray(np.where(valid, dm, 0.0))
        self.dm_error = jnp.asarray(np.where(valid, dme, 1.0))
        self.subtract_mean = subtract_mean
        # bucketing: pad rows carry sentinel -pp_dme (zero weight); the
        # dof counts only real measurements
        self.n_real_toas = getattr(toas, "n_real", None) or len(toas)
        self._n_valid_real = int(
            np.count_nonzero(valid[: self.n_real_toas]))
        self._ctx_dyn, self._ctx_static = _cc.split_ctx(self.prepared.ctx)
        self._data_cached = None
        self._structure_key_cached = None
        self._jit_cache: dict = {}

    # -- dataset pytree / structural identity --------------------------------
    def _data(self):
        if self._data_cached is None:
            self._data_cached = {
                "batch": self.prepared.batch,
                "ctx": self._ctx_dyn,
                "dm_data": self.dm_data,
                "dm_error": self.dm_error,
                "valid_idx": self.valid_idx,
            }
        return self._data_cached

    def _structure_key(self):
        if self._structure_key_cached is None:
            self._structure_key_cached = repr((
                "wb_dm", _cc.model_structure_key(self.model),
                self.subtract_mean,
                _cc.static_ctx_key(self._ctx_static),
            ))
        return self._structure_key_cached

    def _ctx_at(self, data):
        return _cc.merge_ctx(data["ctx"], self._ctx_static)

    def ensure_kepler_depth(self, ecc_max):
        """Wideband counterpart of Residuals.ensure_kepler_depth (no
        TZR ctx on this layout)."""
        if not self.prepared.ensure_kepler_depth(ecc_max):
            return False
        self._rekey_after_ctx_change()
        return True

    def _rekey_after_ctx_change(self):
        self._ctx_dyn, self._ctx_static = _cc.split_ctx(
            self.prepared.ctx)
        self._data_cached = None
        self._structure_key_cached = None
        self._jit_cache = {}

    def _jitted(self, name, fn):
        got = self._jit_cache.get(name)
        if got is None:
            telemetry.counter_add("residuals.jit_cache_misses")
            got = self._jit_cache[name] = _cc.shared_jit(
                fn, key=("residuals", name, self._structure_key()))
        else:
            telemetry.counter_add("residuals.jit_cache_hits")
        return got

    # -- pure functions ------------------------------------------------------
    def sigma_at(self, values, data):
        """DMEFAC/DMEQUAD-scaled DM uncertainties, valid TOAs only."""
        sig = self.prepared.scaled_dm_sigma_fn(
            values, data["dm_error"], ctx=self._ctx_at(data))
        return sig[data["valid_idx"]]

    def dm_resids_at(self, values, data):
        model_dm = self.prepared.total_dm_fn(
            values, batch=data["batch"], ctx=self._ctx_at(data))
        r = (data["dm_data"] - model_dm)[data["valid_idx"]]
        if self.subtract_mean:
            sig = self.sigma_at(values, data)
            w = 1.0 / sig**2
            r = r - jnp.sum(r * w) / jnp.sum(w)
        return r

    def linear_dm_design_at(self, values, data, names):
        """(n_valid, L) DM-residual design columns for the phase-linear
        parameters — the DM block of the wideband hybrid design.
        dm_resid = measured - modeled, so the column is minus the
        modeled-DM derivative; parameters without a dm_value
        contribution get exact zero columns."""
        cols = -self.prepared.linear_dm_columns(
            values, data["batch"], self._ctx_at(data), names)
        cols = cols[data["valid_idx"]]
        if self.subtract_mean:
            sig = self.sigma_at(values, data)
            w = 1.0 / sig**2
            cols = cols - jnp.sum(cols * w[:, None], axis=0) \
                / jnp.sum(w)
        return cols

    def chi2_at(self, values, data):
        r = self.dm_resids_at(values, data)
        return jnp.sum((r / self.sigma_at(values, data)) ** 2)

    def sigma_fn(self, values):
        return self.sigma_at(values, self._data())

    def dm_resids_fn(self, values):
        return self.dm_resids_at(values, self._data())

    def chi2_fn(self, values):
        return self.chi2_at(values, self._data())

    # -- numpy accessors -----------------------------------------------------
    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def dm_resids(self):
        return np.asarray(self._jitted("dm_resids", self.dm_resids_at)(
            self._values(), self._data()))

    @property
    def chi2(self):
        return float(self._jitted("chi2", self.chi2_at)(
            self._values(), self._data()))

    @property
    def scaled_errors(self):
        return np.asarray(self.sigma_fn(self._values()))

    @property
    def dof(self):
        return self._n_valid_real

    def rms_weighted(self):
        r = self.dm_resids
        w = 1.0 / self.scaled_errors**2
        return float(np.sqrt(np.sum(r**2 * w) / np.sum(w)))


class WidebandTOAResiduals:
    """Stacked TOA + DM residuals sharing one PreparedModel (reference:
    WidebandTOAResiduals / CombinedResiduals, residuals.py:1079-1272).
    chi^2 is the sum of the two blocks; dof counts both data vectors."""

    def __init__(self, toas, model, subtract_mean=None,
                 track_mode="nearest"):
        if isinstance(model, TimingModel):
            prepared = model.prepare(toas)
        else:
            prepared = model
        self.toas = toas
        self.prepared = prepared
        self.model = prepared.model
        self.toa = Residuals(toas, prepared, subtract_mean=subtract_mean,
                             track_mode=track_mode)
        self.dm = WidebandDMResiduals(toas, prepared)
        self.n_real = self.toa.n_real
        self._jit_cache: dict = {}

    def _data(self):
        return {"toa": self.toa._data(), "dm": self.dm._data()}

    def _structure_key(self):
        return repr(("wb", self.toa._structure_key(),
                     self.dm._structure_key()))

    def ensure_kepler_depth(self, ecc_max):
        """Stacked-layout counterpart of
        Residuals.ensure_kepler_depth: ONE mutation of the shared
        PreparedModel, then BOTH blocks re-key.  (Forwarding to the
        blocks' own ``ensure_kepler_depth`` would short-circuit the
        second — the shared prepared reports the change only once.)"""
        if not self.prepared.ensure_kepler_depth(ecc_max):
            return False
        self.toa._rekey_after_ctx_change()
        self.dm._rekey_after_ctx_change()
        self._jit_cache = {}
        return True

    def chi2_at(self, values, data):
        return (self.toa.chi2_at(values, data["toa"])
                + self.dm.chi2_at(values, data["dm"]))

    def chi2_fn(self, values):
        return self.chi2_at(values, self._data())

    def warm_compile(self):
        """AOT-compile the wideband fit epilogue: the stacked chi^2
        plus the time-block accessors (see Residuals.warm_compile)."""
        got = self._jit_cache.get("chi2")
        if got is None:
            got = self._jit_cache["chi2"] = _cc.shared_jit(
                self.chi2_at, key=("residuals", "chi2",
                                   self._structure_key()))
        lowered = got.lower(self._values(), self._data())
        return _cc.warm_timed(lowered.compile) + self.toa.warm_compile()

    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def chi2(self):
        got = self._jit_cache.get("chi2")
        if got is None:
            got = self._jit_cache["chi2"] = _cc.shared_jit(
                self.chi2_at, key=("residuals", "chi2",
                                   self._structure_key()))
        return float(got(self._values(), self._data()))

    @property
    def ecorr_segment_cols(self):
        """Structure-aware FLOP accounting: the time block's segment
        ECORR column count (the DM block sees no noise basis)."""
        return self.toa.ecorr_segment_cols

    @property
    def dof(self):
        return (
            self.n_real + self.dm.dof
            - len(self.model.free_params) - int(self.toa.subtract_mean)
        )

    @property
    def reduced_chi2(self):
        return self.chi2 / self.dof

    def rms_weighted(self):
        """Weighted RMS of the *time* block [s] (for fit summaries)."""
        return self.toa.rms_weighted()
