"""Residuals: observed-minus-model phase and time residuals.

Counterpart of the reference Residuals (reference: src/pint/residuals.py:40,
``calc_phase_resids`` at :314-425, ``calc_time_resids`` at :483,
``calc_chi2`` at :669, ``lnlikelihood`` at :713).  Phase residuals come
out of the jitted model as an (int64 turns, f64 frac) pair; 'nearest'
tracking is the frac part by construction, 'pulse_number' tracking
differences the integer part against tracked pulse numbers.  Mean
subtraction is weighted (1/sigma^2, noise-scaled) and skipped when the
model carries an explicit PHOFF (reference :372-425 semantics).

chi^2 dispatch mirrors the reference: plain WLS sum when the model has
no correlated noise; Woodbury over the low-rank noise basis otherwise,
with a unit basis column at weight 1e40 absorbing the subtracted mean
(reference :567-636, the 1e40 column at :583-585).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import telemetry
from pint_tpu.linalg import woodbury_chi2_logdet
from pint_tpu.models.timing_model import PreparedModel, TimingModel
from pint_tpu.telemetry import span

__all__ = ["Residuals", "WidebandDMResiduals", "WidebandTOAResiduals"]

#: weight given to the synthetic constant-offset basis column when the
#: mean is subtracted (reference residuals.py:583-585 uses 1e40; we use
#: 1e30 because TPU emulates f64 as a float32 pair whose high word
#: saturates at ~3.4e38 — 1e40 silently becomes inf on device and NaNs
#: the Cholesky.  1e30 s^2 of prior variance is equally "infinite" for
#: any real dataset)
MEAN_OFFSET_WEIGHT = 1e30


def weighted_mean_phase(frac, weights):
    return jnp.sum(frac * weights) / jnp.sum(weights)


class Residuals:
    """Residuals bound to (toas, model); evaluation is jit-compiled."""

    def __init__(self, toas, model, subtract_mean=None, track_mode=None,
                 use_weighted_mean=True):
        self.toas = toas
        if isinstance(model, TimingModel):
            self.prepared = model.prepare(toas)
        else:
            self.prepared = model
        self.model = self.prepared.model
        if subtract_mean is None:
            subtract_mean = not self.model.has_component("PhaseOffset")
        self.subtract_mean = subtract_mean
        self.use_weighted_mean = use_weighted_mean
        # track-mode resolution (reference residuals.py:133-149):
        # explicit arg > par TRACK -2/0 > presence of complete -pn flags
        pn = toas.get_pulse_numbers() if hasattr(
            toas, "get_pulse_numbers") else None
        if track_mode is None or track_mode == "auto":
            track = self.model.meta.get("TRACK", "")
            if track == "-2":
                track_mode = "use_pulse_numbers"
            elif track == "0":
                track_mode = "nearest"
            elif pn is not None and not np.any(np.isnan(pn)):
                track_mode = "use_pulse_numbers"
            else:
                track_mode = "nearest"
        if track_mode == "pulse_number":  # accept both spellings
            track_mode = "use_pulse_numbers"
        if track_mode not in ("nearest", "use_pulse_numbers"):
            raise ValueError(f"unknown track_mode {track_mode!r}")
        if track_mode == "use_pulse_numbers":
            if pn is None:
                raise ValueError(
                    "track_mode requires pulse numbers but the TOAs "
                    "carry no -pn flags (use toas.compute_pulse_numbers)"
                )
            if np.any(np.isnan(pn)):
                raise ValueError("Pulse numbers are missing on some TOAs")
            self._pulse_numbers = jnp.asarray(pn, dtype=jnp.int64)
        else:
            self._pulse_numbers = None
        dpn = (toas.get_delta_pulse_numbers() if hasattr(
            toas, "get_delta_pulse_numbers") else np.zeros(0))
        self._delta_pn = (jnp.asarray(dpn) if np.any(dpn != 0.0)
                          else None)
        self.track_mode = track_mode
        # extended Woodbury basis (mean-offset column appended), built
        # eagerly OUTSIDE any trace — see _noise_basis_phi.  Always
        # built: the wideband solve uses it even with a width-0 basis.
        U = self.prepared.noise_basis
        if self.subtract_mean:
            U = jnp.concatenate([U, jnp.ones((U.shape[0], 1))], axis=1)
        self._U_ext = U
        # jit wrappers are built lazily on first use: a 14-component GLS
        # model costs tens of seconds of XLA compile per function on
        # CPU, and most callers touch only one of the four
        self._jit_cache: dict = {}

    def _jitted(self, name, fn):
        got = self._jit_cache.get(name)
        if got is None:
            telemetry.counter_add("residuals.jit_cache_misses")
            got = self._jit_cache[name] = jax.jit(fn)
        else:
            telemetry.counter_add("residuals.jit_cache_hits")
        return got

    @property
    def _phase_resids_jit(self):
        return self._jitted("phase", self.phase_resids_fn)

    @property
    def _time_resids_jit(self):
        return self._jitted("time", self.time_resids_fn)

    @property
    def _chi2_jit(self):
        return self._jitted("chi2", self.chi2_fn)

    @property
    def _lnlike_jit(self):
        return self._jitted("lnlike", self.lnlikelihood_fn)

    # -- pure functions (values pytree -> arrays), jit-safe ------------------
    def sigma_fn(self, values):
        """Noise-scaled per-TOA uncertainty [s]."""
        return self.prepared.scaled_sigma_fn(values)

    def phase_resids_fn(self, values):
        n, frac = self.prepared._phase_raw(values)
        if self._pulse_numbers is not None:
            # TRACK -2 semantics (reference residuals.py:368-392):
            # residual = absolute model phase - assigned pulse number;
            # integer arithmetic first so 4e11-turn counts stay exact
            resid = (n - self._pulse_numbers).astype(jnp.float64) + frac
            if self._delta_pn is not None:
                resid = resid + self._delta_pn
        else:
            resid = frac
            if self._delta_pn is not None:
                # PHASE commands shift the phase before the nearest-
                # integer assignment (reference residuals.py:394-406)
                resid = resid + self._delta_pn
                resid = resid - jnp.round(resid)
        if self.subtract_mean:
            if self.use_weighted_mean:
                w = 1.0 / self.sigma_fn(values) ** 2
                resid = resid - weighted_mean_phase(resid, w)
            else:
                resid = resid - jnp.mean(resid)
        return resid

    def time_resids_fn(self, values):
        return self.phase_resids_fn(values) / values["F0"]

    def _noise_basis_phi(self, values):
        """(U, phi) for the Woodbury paths, with the mean-offset column
        appended when applicable.

        The extended U is values-independent and prebuilt EAGERLY in
        __init__ (never inside a trace): concatenating in the traced
        function re-created the (n_toa, n_basis) matrix as a fresh
        constant-folded literal on every jit compile (XLA's
        constant-folding alarm fired on the f64[8161,402] pad), and a
        lazily-cached version leaks a tracer — jnp.ones under an
        active trace is staged, not concrete."""
        phi = self.prepared.noise_weights_fn(values)
        if self.subtract_mean:
            phi = jnp.concatenate([phi, jnp.array([MEAN_OFFSET_WEIGHT])])
        return self._U_ext, phi

    def chi2_fn(self, values):
        r = self.time_resids_fn(values)
        sigma = self.sigma_fn(values)
        if not self.model.has_correlated_errors:
            return jnp.sum((r / sigma) ** 2)
        U, phi = self._noise_basis_phi(values)
        chi2, _ = woodbury_chi2_logdet(r, sigma, U, phi)
        return chi2

    def lnlikelihood_fn(self, values):
        """Gaussian log-likelihood of the residuals under the full noise
        covariance (reference residuals.py:713); differentiable wrt
        noise parameters for gradient-based noise fitting."""
        r = self.time_resids_fn(values)
        sigma = self.sigma_fn(values)
        n = r.shape[0]
        if not self.model.has_correlated_errors:
            chi2 = jnp.sum((r / sigma) ** 2)
            logdet = 2.0 * jnp.sum(jnp.log(sigma))
        else:
            U, phi = self._noise_basis_phi(values)
            chi2, logdet = woodbury_chi2_logdet(r, sigma, U, phi)
        return -0.5 * (chi2 + logdet) - 0.5 * n * jnp.log(2.0 * jnp.pi)

    # -- convenience numpy accessors -----------------------------------------
    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def phase_resids(self):
        with span("residuals.calc", kind="phase",
                  n_toa=len(self.toas)):
            out = np.asarray(self._phase_resids_jit(self._values()))
        telemetry.record_transfer(out)
        return out

    @property
    def time_resids(self):
        with span("residuals.calc", kind="time", n_toa=len(self.toas)):
            out = np.asarray(self._time_resids_jit(self._values()))
        telemetry.record_transfer(out)
        return out

    @property
    def chi2(self):
        with span("residuals.calc", kind="chi2", n_toa=len(self.toas)):
            return float(self._chi2_jit(self._values()))

    def lnlikelihood(self, values=None):
        with span("residuals.calc", kind="lnlike",
                  n_toa=len(self.toas)):
            return float(self._lnlike_jit(self._values(values)))

    @property
    def scaled_errors(self):
        """Noise-scaled uncertainties [s] at current parameter values."""
        return np.asarray(self.sigma_fn(self._values()))

    @property
    def dof(self):
        return len(self.toas) - len(self.model.free_params) - int(
            self.subtract_mean
        )

    @property
    def reduced_chi2(self):
        return self.chi2 / self.dof

    def ecorr_average(self, use_noise_model=True):
        """Epoch-averaged residuals using the ECORR time binning
        (reference: residuals.py:842).

        Returns {"mjds", "freqs", "time_resids", "errors", "indices"}
        with one entry per ECORR epoch; with use_noise_model the
        weights use the scaled uncertainties and the ECORR variance is
        added to the averaged errors."""
        comp = None
        for c in self.model.noise_components:
            if getattr(c, "category", "") == "ecorr_noise":
                comp = c
        if comp is None or not comp.selects:
            raise ValueError("ECORR not present in noise model")
        ctx = self.prepared.ctx[type(comp).__name__]
        U = np.asarray(ctx["basis"])  # (N, n_epochs) 0/1
        values = self._values()
        ecorr_err2 = np.asarray(comp.weights(values, ctx))
        if use_noise_model:
            err = np.asarray(self._jitted("sigma", self.sigma_fn)(values))
        else:
            err = np.asarray(self.toas.error_us) * 1e-6
            ecorr_err2 = ecorr_err2 * 0.0
        wt = 1.0 / err**2
        a_norm = U.T @ wt

        def wtsum(x):
            return (U.T @ (wt * np.asarray(x))) / a_norm

        return {
            "mjds": wtsum(self.toas.mjd_float),
            "freqs": wtsum(np.where(np.isfinite(self.toas.freq_mhz),
                                    self.toas.freq_mhz, 0.0)),
            "time_resids": wtsum(self.time_resids),
            "errors": np.sqrt(1.0 / a_norm + ecorr_err2),
            "indices": [np.flatnonzero(U[:, j]).tolist()
                        for j in range(U.shape[1])],
        }

    def rms_weighted(self):
        """Weighted RMS of time residuals [s]."""
        r = self.time_resids
        w = 1.0 / self.scaled_errors**2
        return float(np.sqrt(np.sum(r**2 * w) / np.sum(w)))


class WidebandDMResiduals:
    """Wideband DM residuals: measured DM (``-pp_dm`` flags) minus the
    model's total DM (reference: WidebandDMResiduals,
    residuals.py:908-1077).  No mean subtraction by default (DM is an
    absolute measurement, reference :33)."""

    def __init__(self, toas, model, subtract_mean=False):
        self.toas = toas
        if isinstance(model, TimingModel):
            self.prepared = model.prepare(toas)
        else:
            self.prepared = model
        self.model = self.prepared.model
        dm, dme, valid = toas.wideband_dm_data()
        if not valid.any():
            raise ValueError(
                "no wideband DM data: TOAs lack -pp_dm flags"
            )
        self.valid = valid
        self.valid_idx = jnp.asarray(np.flatnonzero(valid))
        self.dm_data = jnp.asarray(np.where(valid, dm, 0.0))
        self.dm_error = jnp.asarray(np.where(valid, dme, 1.0))
        self.subtract_mean = subtract_mean
        self._resids_jit = jax.jit(self.dm_resids_fn)
        self._chi2_jit = jax.jit(self.chi2_fn)

    # -- pure functions ------------------------------------------------------
    def sigma_fn(self, values):
        """DMEFAC/DMEQUAD-scaled DM uncertainties, valid TOAs only."""
        sig = self.prepared.scaled_dm_sigma_fn(values, self.dm_error)
        return sig[self.valid_idx]

    def dm_resids_fn(self, values):
        model_dm = self.prepared.total_dm_fn(values)
        r = (self.dm_data - model_dm)[self.valid_idx]
        if self.subtract_mean:
            sig = self.sigma_fn(values)
            w = 1.0 / sig**2
            r = r - jnp.sum(r * w) / jnp.sum(w)
        return r

    def chi2_fn(self, values):
        r = self.dm_resids_fn(values)
        return jnp.sum((r / self.sigma_fn(values)) ** 2)

    # -- numpy accessors -----------------------------------------------------
    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def dm_resids(self):
        return np.asarray(self._resids_jit(self._values()))

    @property
    def chi2(self):
        return float(self._chi2_jit(self._values()))

    @property
    def scaled_errors(self):
        return np.asarray(self.sigma_fn(self._values()))

    @property
    def dof(self):
        return int(np.count_nonzero(self.valid))

    def rms_weighted(self):
        r = self.dm_resids
        w = 1.0 / self.scaled_errors**2
        return float(np.sqrt(np.sum(r**2 * w) / np.sum(w)))


class WidebandTOAResiduals:
    """Stacked TOA + DM residuals sharing one PreparedModel (reference:
    WidebandTOAResiduals / CombinedResiduals, residuals.py:1079-1272).
    chi^2 is the sum of the two blocks; dof counts both data vectors."""

    def __init__(self, toas, model, subtract_mean=None,
                 track_mode="nearest"):
        if isinstance(model, TimingModel):
            prepared = model.prepare(toas)
        else:
            prepared = model
        self.toas = toas
        self.prepared = prepared
        self.model = prepared.model
        self.toa = Residuals(toas, prepared, subtract_mean=subtract_mean,
                             track_mode=track_mode)
        self.dm = WidebandDMResiduals(toas, prepared)
        self._chi2_jit = jax.jit(self.chi2_fn)

    def chi2_fn(self, values):
        return self.toa.chi2_fn(values) + self.dm.chi2_fn(values)

    def _values(self, values=None):
        return self.prepared._values_pytree(values)

    @property
    def chi2(self):
        return float(self._chi2_jit(self._values()))

    @property
    def dof(self):
        return (
            len(self.toas) + self.dm.dof
            - len(self.model.free_params) - int(self.toa.subtract_mean)
        )

    @property
    def reduced_chi2(self):
        return self.chi2 / self.dof

    def rms_weighted(self):
        """Weighted RMS of the *time* block [s] (for fit summaries)."""
        return self.toa.rms_weighted()
