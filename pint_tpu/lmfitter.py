"""Levenberg-Marquardt and derivative-free Powell fitters.

Counterparts of the reference LMFitter / PowellFitter (reference:
src/pint/fitter.py:2642 LMFitter — explicit LM damping on the GLS
normal equations; :1902 PowellFitter — scipy Powell on the chi^2
closure ``minimize_func`` :794).

TPU redesign: the damped normal-equation solve at a given lambda is one
jitted function; the lambda-adaptation loop stays in Python (few
iterations, negligible).  Powell drives the jitted chi^2 directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.fitter import Fitter

__all__ = ["LMFitter", "PowellFitter"]


class LMFitter(Fitter):
    """Levenberg-Marquardt on the whitened (noise-augmented) system.

    lambda adaptation follows the reference LMFitter: accept a step
    that lowers chi^2 and divide lambda by `down`; otherwise multiply
    by `up` and retry (fitter.py:2642-2765).
    """

    lambda0 = 1e-3
    up = 10.0
    down = 10.0
    max_tries = 12

    def __init__(self, toas, model, residuals=None, bucket=None):
        super().__init__(toas, model, residuals, bucket=bucket)
        self._retrace()

    def _retrace(self):
        # base _retrace jits self._step, which LM replaces wholesale;
        # both LM functions resolve through the shared registry with
        # the dataset as a dynamic argument (fitter.py contract)
        self._traced_free = tuple(self.model.free_timing_params)
        self._guard_on = _guard.enabled()
        leaves = self._partition_setup()
        self._fit_data = self._inject_frozen(
            {**self.resids._data(), "guard_eps": np.float64(0.0)},
            leaves)
        key = (type(self).__name__, self._traced_free, self._guard_on,
               self._partition, self._frozen_names, self._noise_frozen,
               self.resids._structure_key())
        self._lm_jit = _cc.shared_jit(
            self._lm_solve, key=("lm.solve",) + key)
        self._chi2_vec_jit = _cc.shared_jit(
            self._chi2_of_vec, key=("lm.chi2",) + key)

    def _chi2_of_vec(self, vec, base_values, data):
        values = self._merged(base_values, vec)
        resid_fn = self._lm_resid_fn(base_values, data)
        r = resid_fn(vec)
        return jnp.sum((r / self._lm_sigma(values, data)) ** 2)

    # hooks the wideband subclass overrides with the stacked system
    def _lm_resid_fn(self, base_values, data):
        return self._resid_fn_of(base_values, data)

    def _lm_sigma(self, values, data):
        if self._noise_frozen:
            return data["noise_sigma"]  # frozen-noise data leaf
        return self.resids.sigma_at(values, data)

    def _lm_solve(self, vec, base_values, lam, data):
        """One damped step at fixed lambda: (J^T W J + lam diag) d =
        -J^T W r on the whitened residuals.  Returns (dpar, chi2, cov,
        health) — health empty with the guard off."""
        values = self._merged(base_values, vec)
        sigma = self._lm_sigma(values, data)
        # hybrid analytic/AD design (fitter.Fitter._rj): the tangent
        # chain runs only over the nonlinear partition
        r, J = self._rj(vec, base_values, data)
        w = 1.0 / sigma
        rw = r * w
        Jw = J * w[:, None]
        A = Jw.T @ Jw
        g = Jw.T @ rw
        damped = A + lam * jnp.diag(jnp.diag(A))
        cut = (1e-16 if not self._guard_on
               else jnp.maximum(1e-16, data["guard_eps"]))
        # eigh solve (TPU-safe; see linalg.gls_normal_solve)
        norm = jnp.sqrt(jnp.diag(damped))
        norm = jnp.where(norm == 0, 1.0, norm)
        dn = damped / jnp.outer(norm, norm)
        ww, Q = jnp.linalg.eigh(dn)
        w_inv = jnp.where(ww > cut * jnp.max(ww), 1.0 / ww, 0.0)
        dpar = -(Q @ (w_inv * (Q.T @ (g / norm)))) / norm
        # covariance from the undamped system
        An = A / jnp.outer(norm, norm)
        wa, Qa = jnp.linalg.eigh(An)
        wa_inv = jnp.where(wa > cut * jnp.max(wa), 1.0 / wa, 0.0)
        cov = (Qa * wa_inv[None, :]) @ Qa.T / jnp.outer(norm, norm)
        chi2 = jnp.sum(rw * rw)
        if not self._guard_on:
            return dpar, chi2, cov, ()
        wmax = jnp.max(ww)
        kept_min = jnp.min(jnp.where(w_inv > 0.0, ww, wmax))
        diag = _guard.SolveDiag(
            n_truncated=jnp.sum(w_inv == 0.0).astype(jnp.int32),
            cond_log10=jnp.log10(wmax / jnp.maximum(kept_min, 1e-300)))
        b = data["toa"]["batch"] if "toa" in data else data["batch"]
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=data.get("valid"),
            inputs_ok=_guard.batch_input_finite(b, data.get("valid")))
        return dpar, chi2, cov, health

    def _iterate(self, maxiter, guard_eps=0.0, min_chi2_decrease=1e-2):
        """One ladder rung of the LM loop (fitter.Fitter._iterate
        contract minus extras)."""
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        data = self._guard_data(guard_eps)
        lam = self.lambda0
        cov = None
        health = ()
        n_iter = 0
        self.converged = False
        last_good = np.array(
            [self.model.values[k] for k in self._traced_free])

        def checked(out):
            dpar, chi2, cov, health = out
            self._check_step_health(health, last_good, n_iter)
            return dpar, chi2, cov, health

        for _ in range(maxiter):
            if np.all(np.isfinite(np.asarray(vec))):
                last_good = np.asarray(vec)
            dpar, chi2_old, cov, health = checked(
                self._lm_jit(vec, base, lam, data))
            n_iter += 1
            accepted = False
            for _try in range(self.max_tries):
                chi2_new = float(
                    self._chi2_vec_jit(vec + dpar, base, data)
                )
                if chi2_new < float(chi2_old):
                    vec = vec + dpar
                    lam = max(lam / self.down, 1e-12)
                    accepted = True
                    break
                lam = lam * self.up
                dpar, chi2_old, cov, health = checked(
                    self._lm_jit(vec, base, lam, data))
            if not accepted:
                self.converged = True
                break
            if float(chi2_old) - chi2_new < min_chi2_decrease:
                self.converged = True
                break
        return vec, cov, (), n_iter, health

    def fit_toas(self, maxiter=20, min_chi2_decrease=1e-2):
        if not self.model.free_timing_params:
            raise ValueError("no free timing parameters to fit")
        if tuple(self.model.free_timing_params) != getattr(
                self, "_traced_free", ()):
            self._retrace()
        else:
            self._refresh_frozen()
        def rungs_fn():
            rungs = [("baseline",
                      lambda: self._iterate(
                          maxiter, min_chi2_decrease=min_chi2_decrease))]
            if self._guard_on:
                for name, eps in self._guard_jitter_rungs:
                    rungs.append((name, lambda e=eps: self._iterate(
                        maxiter, guard_eps=e,
                        min_chi2_decrease=min_chi2_decrease)))
            return rungs

        _vec, _cov, _n_iter, health, rung = \
            self._fit_with_depth_guard(rungs_fn)
        self._record_guard(rung, health, None)
        self._update_fit_meta()
        return float(self.resids.chi2)


class PowellFitter(Fitter):
    """Derivative-free Powell minimization of chi^2 (reference
    PowellFitter, fitter.py:1902) — the escape hatch when the problem
    is too nonlinear for Gauss-Newton steps."""

    def __init__(self, toas, model, residuals=None, bucket=None):
        super().__init__(toas, model, residuals, bucket=bucket)
        self._retrace()

    def _retrace(self):
        self._traced_free = tuple(self.model.free_timing_params)
        # Powell needs no design matrix, but the frozen-delay leaves
        # still cut the traced chi^2 chain down to live components
        leaves = self._partition_setup()
        self._fit_data = self._inject_frozen(self.resids._data(),
                                             leaves)
        self._chi2_jit = _cc.shared_jit(
            lambda vec, base, data: self.resids.chi2_at(
                self._merged(base, vec), data
            ),
            key=("powell.chi2", self._traced_free, self._frozen_names,
                 self.resids._structure_key()),
            fn_token="powell.chi2")

    def fit_toas(self, maxiter=2000):
        from scipy.optimize import minimize

        if not self.model.free_timing_params:
            raise ValueError("no free timing parameters to fit")
        if tuple(self.model.free_timing_params) != getattr(
                self, "_traced_free", ()):
            self._retrace()
        else:
            self._refresh_frozen()
        # bounded: the Kepler depth guard escalates through at most
        # three classes (fitter._kepler_depth_guard)
        for _depth_try in range(4):
            base = self.prepared._values_pytree()
            x0 = np.array(
                [self.model.values[k] for k in self._traced_free],
                dtype=np.float64,
            )
            # scale the search by par uncertainties when available
            # (Powell is scale-sensitive; F1 ~ 1e-15 in raw units)
            scales = np.array([
                self.model.params[k].uncertainty or max(abs(v), 1e-12)
                for k, v in zip(self._traced_free, x0)
            ])

            def fun(z, x0=x0, scales=scales, base=base):
                return float(self._chi2_jit(
                    jnp.asarray(x0 + z * scales), base, self._fit_data))

            res = minimize(fun, np.zeros_like(x0), method="Powell",
                           options={"maxiter": maxiter, "xtol": 1e-10})
            vec = x0 + res.x * scales
            if not (np.all(np.isfinite(vec)) and np.isfinite(res.fun)):
                telemetry.counter_add("guard.trips")
                telemetry.counter_add("guard.trip.powell")
                raise _guard.FitDivergedError(
                    type(self).__name__,
                    last_good={n: float(x0[i])
                               for i, n in enumerate(self._traced_free)},
                    detail=f"Powell returned non-finite optimum "
                           f"(fun={res.fun!r})")
            for i, name in enumerate(self._traced_free):
                self.model.values[name] = float(vec[i])
            if not self._kepler_depth_guard():
                break
        self.converged = bool(res.success)
        self.covariance = None
        self._update_fit_meta()
        return float(self.resids.chi2)

class WidebandLMFitter(LMFitter):
    """Levenberg-Marquardt on the wideband stacked [time; DM] system
    (reference: WidebandLMFitter, fitter.py:2766)."""

    def __init__(self, toas, model, residuals=None, bucket=None):
        from pint_tpu.residuals import WidebandTOAResiduals

        if residuals is None:
            if bucket is None:
                bucket = _cc.bucketing_default()
            if bucket:
                toas = _cc.pad_toas(toas)
            residuals = WidebandTOAResiduals(toas, model)
        super().__init__(toas, model, residuals=residuals, bucket=False)

    def _lm_resid_fn(self, base_values, data):
        free = self._traced_free
        toa_r = self.resids.toa
        dm_r = self.resids.dm

        def resid_fn(v):
            values = dict(base_values)
            for i, name in enumerate(free):
                values[name] = v[i]
            return jnp.concatenate(
                [toa_r.time_resids_at(values, data["toa"]),
                 dm_r.dm_resids_at(values, data["dm"])]
            )

        return resid_fn

    def _lm_sigma(self, values, data):
        return jnp.concatenate(
            [self.resids.toa.sigma_at(values, data["toa"]),
             self.resids.dm.sigma_at(values, data["dm"])]
        )

    def _rj(self, vec, base_values, data):
        from pint_tpu.fitter import wideband_resid_and_design

        return wideband_resid_and_design(
            self.resids, base_values, data, self._traced_free, vec,
            self._partition)
