"""Photon light-curve templates: wrapped mixture models + ML fitting.

Counterpart of the reference template subsystem (reference:
src/pint/templates/ — ``LCPrimitive`` gaussians at lcprimitives.py,
``LCTemplate`` mixtures at lctemplate.py:27, ML fitting at
lcfitters.py; 4819 LoC).  TPU redesign: a template is a pure jax
function of (phases, params); the photon log-likelihood

    lnL = sum_i log( w_i f(phi_i) + (1 - w_i) )      (Kerr 2011)

and its exact gradient/Hessian come from autodiff, so the fitter is
L-BFGS on device gradients instead of the reference's hand-coded
per-primitive derivative chains.

Primitives: wrapped Gaussian and wrapped Lorentzian (the reference's
workhorses).  A template is k primitives with amplitudes norms_k plus
the uniform background 1 - sum(norms).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "LCGaussian", "LCLorentzian", "LCVonMises", "LCTopHat",
    "LCHarmonic", "LCGaussian2", "LCLorentzian2", "LCSkewGaussian",
    "LCKing",
    "LCEmpiricalFourier", "LCKernelDensity",
    "LCTemplate", "LCFitter", "NormAngles",
    "LCEGaussian", "LCETemplate", "LCEFitter", "ENormAngles",
    "LCEWrapped", "LCESkewGaussian", "LCELorentzian",
    "LCELorentzian2", "LCEGaussian2", "LCEVonMises",
    "read_template", "write_template", "prof_string",
    "read_gaussfitfile", "convert_primitive",
]

#: FWHM = _FWHM_SIGMA * sigma for a Gaussian
_FWHM_SIGMA = 2.3548200450309493

#: numpy 2 renamed trapz; support both (jax floor allows numpy 1.x)
_trapezoid = getattr(np, "trapezoid", None) or np.trapz

#: wraps to include in the wrapped-gaussian sum: exp(-(1/2)(k/sigma)^2)
#: is < 1e-12 for |k| > 2 at sigma <= 0.3, the widest sane peak
_NWRAP = 3


@dataclass
class LCGaussian:
    """Wrapped Gaussian peak: width sigma, location loc (turns)."""

    sigma: float = 0.03
    loc: float = 0.5

    n_params = 2
    loc_index = 1

    def density(self, phi, p):
        sigma, loc = p[0], p[1]
        k = jnp.arange(-_NWRAP, _NWRAP + 1)
        z = (phi[..., None] - loc + k[None, :]) / sigma
        return jnp.sum(
            jnp.exp(-0.5 * z**2), axis=-1
        ) / (sigma * jnp.sqrt(2.0 * jnp.pi))

    def init_params(self):
        return [self.sigma, self.loc]

    def param_bounds(self):
        return [(1e-3, 0.5), (None, None)]


@dataclass
class LCLorentzian:
    """Wrapped Lorentzian peak: HWHM gamma, location loc (turns).
    The infinite wrap sum has the closed form sinh(2 pi g) /
    (cosh(2 pi g) - cos(2 pi (phi - loc)))."""

    gamma: float = 0.03
    loc: float = 0.5

    n_params = 2
    loc_index = 1

    def density(self, phi, p):
        g, loc = p[0], p[1]
        two_pi = 2.0 * jnp.pi
        return jnp.sinh(two_pi * g) / (
            jnp.cosh(two_pi * g) - jnp.cos(two_pi * (phi - loc))
        )

    def init_params(self):
        return [self.gamma, self.loc]

    def param_bounds(self):
        return [(1e-3, 0.5), (None, None)]


@dataclass
class LCVonMises:
    """Von Mises (circular normal) peak: concentration kappa, location
    loc (reference lcprimitives LCVonMises).  Naturally periodic — no
    wrap sum needed: f = exp(kappa cos(2 pi (phi-loc))) / I0(kappa)."""

    kappa: float = 100.0
    loc: float = 0.5

    n_params = 2
    loc_index = 1

    def density(self, phi, p):
        from jax.scipy.special import i0e

        kappa, loc = p[0], p[1]
        ang = 2.0 * jnp.pi * (jnp.asarray(phi) - loc)
        # exp(k cos a)/I0(k) = exp(k (cos a - 1)) / i0e(k)
        return jnp.exp(kappa * (jnp.cos(ang) - 1.0)) / i0e(kappa)

    def init_params(self):
        return [self.kappa, self.loc]

    def param_bounds(self):
        return [(1e-1, 1e7), (None, None)]


@dataclass
class LCTopHat:
    """Top hat of full width ``width`` (turns) centered at loc
    (reference lcprimitives LCTopHat)."""

    width: float = 0.1
    loc: float = 0.5

    n_params = 2
    loc_index = 1

    def density(self, phi, p):
        width, loc = p[0], p[1]
        d = jnp.abs((jnp.asarray(phi) - loc + 0.5) % 1.0 - 0.5)
        return jnp.where(d <= width / 2.0, 1.0 / width, 0.0)

    def init_params(self):
        return [self.width, self.loc]

    def param_bounds(self):
        return [(1e-3, 1.0), (None, None)]


@dataclass
class LCHarmonic:
    """Pure cosine harmonic of fixed order: f = 1 + cos(2 pi n
    (phi - loc)) (reference lcprimitives LCHarmonic)."""

    order: int = 1
    loc: float = 0.0

    n_params = 1
    loc_index = 0

    def density(self, phi, p):
        loc = p[0]
        return 1.0 + jnp.cos(2.0 * jnp.pi * self.order
                             * (jnp.asarray(phi) - loc))

    def init_params(self):
        return [self.loc]

    def param_bounds(self):
        return [(None, None)]


def _two_sided(core_density):
    """Two-sided wrapper: width1 left of the peak, width2 right —
    normalized (the two half-profiles each carry weight 1/2)."""

    def density(phi, loc, w1, w2):
        d = (jnp.asarray(phi) - loc + 0.5) % 1.0 - 0.5  # [-0.5, 0.5)
        left = core_density(d, w1)
        right = core_density(d, w2)
        # each half-density integrates to 1/2 of its symmetric form
        return jnp.where(d < 0, 2.0 * w1 / (w1 + w2) * left,
                         2.0 * w2 / (w1 + w2) * right)

    return density


@dataclass
class LCGaussian2:
    """Two-sided wrapped Gaussian: sigma1 (leading), sigma2 (trailing)
    (reference lcprimitives LCGaussian2)."""

    sigma1: float = 0.03
    sigma2: float = 0.03
    loc: float = 0.5

    n_params = 3
    loc_index = 2

    def density(self, phi, p):
        s1, s2, loc = p[0], p[1], p[2]

        def core(d, s):
            k = jnp.arange(-_NWRAP, _NWRAP + 1)
            z = (d[..., None] + k[None, :]) / s
            return jnp.sum(jnp.exp(-0.5 * z**2), axis=-1) / (
                s * jnp.sqrt(2.0 * jnp.pi))

        return _two_sided(core)(phi, loc, s1, s2)

    def init_params(self):
        return [self.sigma1, self.sigma2, self.loc]

    def param_bounds(self):
        return [(1e-3, 0.5), (1e-3, 0.5), (None, None)]


@dataclass
class LCLorentzian2:
    """Two-sided wrapped Lorentzian: gamma1/gamma2 HWHM (reference
    lcprimitives LCLorentzian2)."""

    gamma1: float = 0.03
    gamma2: float = 0.03
    loc: float = 0.5

    n_params = 3
    loc_index = 2

    def density(self, phi, p):
        g1, g2, loc = p[0], p[1], p[2]
        two_pi = 2.0 * jnp.pi

        def core(d, g):
            return jnp.sinh(two_pi * g) / (
                jnp.cosh(two_pi * g) - jnp.cos(two_pi * d))

        return _two_sided(core)(phi, loc, g1, g2)

    def init_params(self):
        return [self.gamma1, self.gamma2, self.loc]

    def param_bounds(self):
        return [(1e-3, 0.5), (1e-3, 0.5), (None, None)]


@dataclass
class LCSkewGaussian:
    """Wrapped skew-normal peak (reference lcprimitives
    LCSkewGaussian, :858): density 2 phi(z) Phi(shape * z) / sigma
    with z = (x - loc)/sigma, wrapped over +-_NWRAP turns.  shape=0
    reduces exactly to LCGaussian; sign(shape) sets the skew
    direction."""

    sigma: float = 0.03
    shape: float = 2.0
    loc: float = 0.5

    n_params = 3
    loc_index = 2

    def density(self, phi, p):
        from jax.scipy.stats import norm

        sigma, shape, loc = p[0], p[1], p[2]
        k = jnp.arange(-_NWRAP, _NWRAP + 1)
        z = (jnp.asarray(phi)[..., None] - loc + k[None, :]) / sigma
        f = 2.0 * norm.pdf(z) * norm.cdf(shape * z) / sigma
        return jnp.sum(f, axis=-1)

    def init_params(self):
        return [self.sigma, self.shape, self.loc]

    def param_bounds(self):
        return [(1e-3, 0.5), (-30.0, 30.0), (None, None)]


@dataclass
class LCKing:
    """Wrapped King-function (modified-Lorentzian) peak (reference
    lcprimitives LCKing, :1250 — the XMM/Chandra PSF radial profile
    restricted to 1D): f(x) = N (1 + x^2/(2 sigma^2 gamma))^(-gamma),
    gamma > 1, normalized over the real line then wrapped.

    N = Gamma(gamma) / (Gamma(gamma-1/2) sqrt(2 pi gamma) sigma)
    normalizes the unwrapped profile (student-t with nu = 2 gamma - 1
    in disguise), so the wrap sum integrates to 1 per turn."""

    sigma: float = 0.03
    gamma: float = 3.0
    loc: float = 0.5

    n_params = 3
    loc_index = 2

    def density(self, phi, p):
        from jax.scipy.special import gammaln

        sigma, gamma, loc = p[0], p[1], p[2]
        norm = jnp.exp(gammaln(gamma) - gammaln(gamma - 0.5)) / (
            jnp.sqrt(2.0 * jnp.pi * gamma) * sigma)
        # power-law tails fall much slower than gaussian: widen the
        # wrap sum accordingly
        k = jnp.arange(-3 * _NWRAP, 3 * _NWRAP + 1)
        z = (jnp.asarray(phi)[..., None] - loc + k[None, :]) / sigma
        f = norm * (1.0 + z**2 / (2.0 * gamma)) ** (-gamma)
        return jnp.sum(f, axis=-1)

    def init_params(self):
        return [self.sigma, self.gamma, self.loc]

    def param_bounds(self):
        return [(1e-3, 0.5), (1.01, 50.0), (None, None)]


class LCEmpiricalFourier:
    """Non-parametric Fourier light curve (reference lcprimitives
    LCEmpiricalFourier, :1361): harmonic coefficients measured from a
    photon phase sample (or read from a ``# fourier`` file); the single
    fit parameter is an overall phase shift, applied via the shift
    theorem.  Density = 1 + 2 sum_k (a_k cos + b_k sin), which
    integrates to 1 over a turn by construction.

    Like the reference, it stands alone: use it as the only primitive
    of a template with norm 1 (the background is already inside the
    empirical coefficients).
    """

    shift: float = 0.0
    n_params = 1
    loc_index = 0

    def __init__(self, phases=None, input_file=None, nharm=20):
        self.nharm = int(nharm)
        self.shift = 0.0
        self.alphas = np.zeros(self.nharm)
        self.betas = np.zeros(self.nharm)
        if input_file is not None:
            self.from_file(input_file)
        if phases is not None:
            self.from_phases(phases)

    def from_phases(self, phases):
        phases = np.asarray(phases, np.float64) % 1.0
        k = np.arange(1, self.nharm + 1) * 2.0 * np.pi
        self.alphas = np.cos(k[:, None] * phases[None, :]).mean(axis=1)
        self.betas = np.sin(k[:, None] * phases[None, :]).mean(axis=1)

    def from_file(self, path):
        rows = []
        with open(path, "r") as f:
            for line in f:
                if "#" in line:
                    continue
                toks = line.split()
                if len(toks) == 2:
                    try:
                        rows.append((float(toks[0]), float(toks[1])))
                    except ValueError:
                        pass
        if not rows:
            raise ValueError(f"no fourier coefficients in {path}")
        self.alphas = np.array([r[0] for r in rows])
        self.betas = np.array([r[1] for r in rows])
        self.nharm = len(rows)

    def to_file(self, path):
        with open(path, "w") as f:
            f.write("# fourier\n")
            for a, b in zip(self.alphas, self.betas):
                f.write(f"{float(a)!r}\t{float(b)!r}\n")

    def density(self, phi, p):
        shift = p[0]
        k = jnp.arange(1, self.nharm + 1) * 2.0 * jnp.pi
        c, s = jnp.cos(k * shift), jnp.sin(k * shift)
        a = c * self.alphas - s * self.betas
        b = s * self.alphas + c * self.betas
        ph = jnp.asarray(phi)[..., None] * k
        return 1.0 + 2.0 * jnp.sum(a * jnp.cos(ph) + b * jnp.sin(ph),
                                   axis=-1)

    def init_params(self):
        return [self.shift]

    def param_bounds(self):
        return [(None, None)]


class LCKernelDensity:
    """Non-parametric kernel-density light curve (reference
    lcprimitives LCKernelDensity, :1456): a wrapped-Gaussian KDE of a
    photon phase sample, pre-evaluated on a phase grid and linearly
    interpolated on device; the single fit parameter is an overall
    shift.  Stands alone like LCEmpiricalFourier."""

    n_params = 1
    loc_index = 0

    def __init__(self, phases=None, bw=None, resolution=0.001):
        self.shift = 0.0
        self.resolution = float(resolution)
        self.bw = bw
        self.grid = np.linspace(0.0, 1.0,
                                int(round(1.0 / self.resolution)) + 1)
        self.vals = np.ones_like(self.grid)
        if phases is not None:
            self.from_phases(phases)

    def from_phases(self, phases):
        phases = np.asarray(phases, np.float64) % 1.0
        n = len(phases)
        # Silverman-style circular bandwidth when not given
        bw = self.bw if self.bw is not None else 1.06 * min(
            np.std(phases), 0.2) * n ** (-0.2)
        bw = max(float(bw), 1e-3)
        self.bw = bw
        # wrapped-Gaussian KDE on the grid (host-side, once)
        d = self.grid[:, None] - phases[None, :]
        acc = np.zeros(len(self.grid))
        for k in (-1, 0, 1):
            acc += np.exp(-0.5 * ((d + k) / bw) ** 2).sum(axis=1)
        vals = acc / (n * bw * np.sqrt(2 * np.pi))
        # enforce exact unit integral on the trapezoid grid
        self.vals = vals / _trapezoid(vals, self.grid)

    def density(self, phi, p):
        ph = (jnp.asarray(phi) - p[0]) % 1.0
        return jnp.interp(ph, jnp.asarray(self.grid),
                          jnp.asarray(self.vals))

    def init_params(self):
        return [self.shift]

    def param_bounds(self):
        return [(None, None)]


class NormAngles:
    """Constrained normalization parameterization (reference
    lcnorm.py NormAngles): k component amplitudes expressed through
    angles so that every norm is in (0,1) and their sum stays < 1 for
    any unconstrained angle values — the fitter can then move freely
    without a barrier."""

    def __init__(self, k):
        self.k = k

    def to_norms(self, angles):
        """angles (k,) -> norms (k,): norm_i = sin^2(a_0) *
        prod_{j<i} cos^2(a_j) * sin^2(a_i) style stick-breaking."""
        angles = jnp.asarray(angles)
        total = jnp.sin(angles[0]) ** 2  # total pulsed fraction
        rest = angles[1:]
        parts = []
        remaining = total
        for i in range(self.k - 1):
            frac = jnp.sin(rest[i]) ** 2
            parts.append(remaining * frac)
            remaining = remaining * (1.0 - frac)
        parts.append(remaining)
        return jnp.stack(parts)

    def from_norms(self, norms):
        norms = np.asarray(norms, dtype=np.float64)
        total = norms.sum()
        angles = [np.arcsin(np.sqrt(np.clip(total, 1e-9, 1 - 1e-9)))]
        remaining = total
        for i in range(self.k - 1):
            frac = norms[i] / max(remaining, 1e-12)
            angles.append(np.arcsin(np.sqrt(np.clip(frac, 1e-9,
                                                    1 - 1e-9))))
            remaining -= norms[i]
        return np.array(angles)


class LCTemplate:
    """Mixture of primitives + uniform background (reference:
    lctemplate.py:27).  Parameter vector layout:
    [norm_1..norm_k, prim1_params..., prim2_params...]."""

    def __init__(self, primitives: List, norms=None):
        self.primitives = list(primitives)
        k = len(self.primitives)
        if norms is None:
            norms = [0.5 / k] * k
        self.params = np.array(
            list(norms)
            + [v for p in self.primitives for v in p.init_params()],
            dtype=np.float64,
        )

    @property
    def n_params(self):
        return len(self.params)

    def _split(self, params):
        k = len(self.primitives)
        norms = params[:k]
        out = []
        i = k
        for p in self.primitives:
            out.append(params[i:i + p.n_params])
            i += p.n_params
        return norms, out

    def density(self, phi, params=None):
        """Normalized profile f(phi) (integrates to 1 over a turn)."""
        params = self.params if params is None else params
        params = jnp.asarray(params)
        norms, prim_params = self._split(params)
        out = 1.0 - jnp.sum(norms)
        for p, pp, n in zip(self.primitives, prim_params,
                            jnp.atleast_1d(norms)):
            out = out + n * p.density(jnp.asarray(phi), pp)
        return out

    def __call__(self, phi, params=None):
        return self.density(phi, params)

    def lnlike_fn(self, phases, weights=None):
        """Pure function params -> photon log-likelihood (Kerr 2011
        weighted form; reference lcfitters loglikelihood)."""
        phases = jnp.asarray(phases)
        w = None if weights is None else jnp.asarray(weights)

        def lnlike(params):
            f = self.density(phases, params)
            if w is None:
                return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
            return jnp.sum(
                jnp.log(jnp.maximum(w * f + (1.0 - w), 1e-300))
            )

        return lnlike


def _norm_barrier(k):
    """Soft barrier keeping sum(norms) <= 1 (a negative uniform
    background is unphysical and its log-clamp has zero gradient, so
    L-BFGS could otherwise settle there with k >= 2 peaks).  Exactly 1
    is legitimate — standalone empirical templates (fourier/kernel)
    carry their background inside the density — so the penalty is zero
    at and below 1 and unbiased there.  Shared by LCFitter/LCEFitter."""
    # pintlint: allow=PTL101 -- photon-template fitters close over
    # per-instance template data (the event-analysis side, not the
    # shared fit path); registry keys would need a template
    # fingerprint for zero reuse across instances
    return jax.jit(jax.value_and_grad(
        lambda p: 1e10 * jnp.maximum(jnp.sum(p[:k]) - 1.0, 0.0) ** 2
    ))


class LCFitter:
    """Maximum-likelihood template fitting with autodiff gradients
    (reference: lcfitters.py:1-1085)."""

    def __init__(self, template: LCTemplate, phases, weights=None):
        self.template = template
        self.phases = np.asarray(phases, dtype=np.float64) % 1.0
        self.weights = weights
        self._lnlike = template.lnlike_fn(self.phases, weights)
        # pintlint: allow=PTL101 -- closes over this instance's photon
        # phases/weights (see lnlike_fn note above): per-instance by
        # construction, no cross-instance reuse for a registry to win
        self._val_grad = jax.jit(
            jax.value_and_grad(lambda p: -self._lnlike(p))
        )

    def lnlike(self, params=None):
        p = self.template.params if params is None else params
        return float(self._lnlike(jnp.asarray(p)))

    def fit(self, maxiter=200):
        """L-BFGS-B with bounds keeping norms/widths physical; returns
        (params, lnlike).  Updates the template in place."""
        from scipy.optimize import minimize

        k = len(self.template.primitives)
        x0 = np.array(self.template.params)
        bounds = [(1e-4, 1.0)] * k
        for p in self.template.primitives:
            bounds += p.param_bounds()

        barrier = _norm_barrier(k)

        def fun(x):
            xj = jnp.asarray(x)
            v, g = self._val_grad(xj)
            vb, gb = barrier(xj)
            return float(v + vb), np.asarray(g + gb, dtype=np.float64)

        res = minimize(fun, x0, jac=True, method="L-BFGS-B",
                       bounds=bounds, options={"maxiter": maxiter})
        self.template.params = np.asarray(res.x)
        # wrap peak locations into [0, 1)
        i = k
        for p in self.template.primitives:
            self.template.params[i + p.loc_index] %= 1.0
            i += p.n_params
        return self.template.params, -float(res.fun)

    def param_uncertainties(self):
        """1-sigma uncertainties from the inverse Hessian of -lnL."""
        H = np.asarray(
            jax.hessian(lambda p: -self._lnlike(p))(
                jnp.asarray(self.template.params)
            )
        )
        try:
            cov = np.linalg.inv(H)
            return np.sqrt(np.clip(np.diag(cov), 0, None))
        except np.linalg.LinAlgError:
            return np.full(self.template.n_params, np.nan)


# --- energy-dependent templates (reference: lceprimitives.py /
# lcetemplate — primitive parameters evolve with photon energy) -------------

class LCEWrapped:
    """Generic energy-dependent primitive: EVERY parameter of a base
    (energy-independent) primitive evolves linearly in
    x = log10(E) - log10(E0), the reference's LCEPrimitive pattern
    (reference lceprimitives.py:30-180; concrete zoo :204-336).

    Parameter layout: [p_1..p_n, dp_1..dp_n] (values at E0, then
    slopes per decade).  The base density is evaluated per photon via
    vmap — each photon sees its own parameter vector — and base
    lower bounds (widths, concentrations) are enforced at every
    energy so a steep slope cannot push a width negative at the
    spectrum edges."""

    def __init__(self, base, slopes=None, log10_e0=2.0):
        self.base = base
        self.log10_e0 = log10_e0
        self.slopes = list(slopes) if slopes is not None \
            else [0.0] * base.n_params
        if len(self.slopes) != base.n_params:
            raise ValueError(
                f"{len(self.slopes)} slopes for a {base.n_params}-"
                "parameter base primitive")
        lo = [b[0] for b in base.param_bounds()]
        self._lo = np.array([-np.inf if v is None else v for v in lo])

    @property
    def n_params(self):
        return 2 * self.base.n_params

    def density(self, phi, p, log10_en):
        p = jnp.asarray(p)
        n = self.base.n_params
        phi = jnp.asarray(phi)
        # scalar energy with a phase grid (profile plotting at one
        # fixed E) broadcasts like the pre-round-5 implementation
        x = jnp.broadcast_to(
            jnp.asarray(log10_en) - self.log10_e0, phi.shape)
        lo = jnp.asarray(self._lo)

        def one(phi_i, x_i):
            q = jnp.maximum(p[:n] + p[n:] * x_i, lo)
            # squeeze: wrap-sum bases return shape (1,) for scalar
            # phi; an (n, 1) vmap output would broadcast the mixture
            # against (n,) norms into an O(n^2) matrix (measured: 16 s
            # per likelihood eval on the 7k-photon Fermi set, and a
            # silently wrong lnL)
            return jnp.squeeze(self.base.density(phi_i, q))

        return jax.vmap(one)(phi, x)

    def init_params(self):
        return list(self.base.init_params()) + list(self.slopes)


class LCEGaussian(LCEWrapped):
    """Energy-dependent wrapped Gaussian (reference lceprimitives
    LCEGaussian): sigma(E) = sigma + dsigma*x, loc(E) = loc + dloc*x,
    x = log10(E) - log10(E0).  Parameter layout follows the zoo-wide
    LCEWrapped convention [sigma, loc, dsigma, dloc]."""

    def __init__(self, sigma=0.03, loc=0.5, dsigma=0.0, dloc=0.0,
                 log10_e0=2.0):
        super().__init__(LCGaussian(sigma, loc), [dsigma, dloc],
                         log10_e0)


class LCESkewGaussian(LCEWrapped):
    """Energy-dependent wrapped skew Gaussian (reference
    lceprimitives.py:204 LCESkewGaussian)."""

    def __init__(self, sigma=0.03, shape=2.0, loc=0.5, dsigma=0.0,
                 dshape=0.0, dloc=0.0, log10_e0=2.0):
        super().__init__(LCSkewGaussian(sigma, shape, loc),
                         [dsigma, dshape, dloc], log10_e0)


class LCELorentzian(LCEWrapped):
    """Energy-dependent wrapped Lorentzian (reference
    lceprimitives.py:235 LCELorentzian)."""

    def __init__(self, gamma=0.03, loc=0.5, dgamma=0.0, dloc=0.0,
                 log10_e0=2.0):
        super().__init__(LCLorentzian(gamma, loc), [dgamma, dloc],
                         log10_e0)


class LCELorentzian2(LCEWrapped):
    """Energy-dependent two-sided Lorentzian (reference
    lceprimitives.py:252 LCELorentzian2)."""

    def __init__(self, gamma1=0.03, gamma2=0.03, loc=0.5,
                 dgamma1=0.0, dgamma2=0.0, dloc=0.0, log10_e0=2.0):
        super().__init__(LCLorentzian2(gamma1, gamma2, loc),
                         [dgamma1, dgamma2, dloc], log10_e0)


class LCEGaussian2(LCEWrapped):
    """Energy-dependent two-sided Gaussian (reference
    lceprimitives.py:294 LCEGaussian2)."""

    def __init__(self, sigma1=0.03, sigma2=0.03, loc=0.5,
                 dsigma1=0.0, dsigma2=0.0, dloc=0.0, log10_e0=2.0):
        super().__init__(LCGaussian2(sigma1, sigma2, loc),
                         [dsigma1, dsigma2, dloc], log10_e0)


class LCEVonMises(LCEWrapped):
    """Energy-dependent von Mises peak (reference
    lceprimitives.py:336 LCEVonMises)."""

    def __init__(self, kappa=100.0, loc=0.5, dkappa=0.0, dloc=0.0,
                 log10_e0=2.0):
        super().__init__(LCVonMises(kappa, loc), [dkappa, dloc],
                         log10_e0)


class ENormAngles:
    """Energy-dependent constrained normalizations (reference:
    lcenorm.py ENormAngles): the NormAngles stick-breaking angles
    evolve linearly in x = log10(E) - log10(E0), so every component
    amplitude stays in (0,1) and their sum stays < 1 at EVERY photon
    energy for any unconstrained parameter values.

    Parameter layout: [a_1..a_k, da_1..da_k] (angles, slopes)."""

    def __init__(self, k, log10_e0=2.0):
        self.k = k
        self.log10_e0 = log10_e0
        self._base = NormAngles(k)

    @property
    def n_params(self):
        return 2 * self.k

    def to_norms(self, p, log10_en):
        """p (2k,), log10_en (nphot,) -> norms (nphot, k)."""
        p = jnp.asarray(p)
        x = jnp.asarray(log10_en) - self.log10_e0
        angles = p[None, : self.k] + x[:, None] * p[None, self.k:]
        total = jnp.sin(angles[:, 0]) ** 2
        rest = angles[:, 1:]
        parts = []
        remaining = total
        for i in range(self.k - 1):
            frac = jnp.sin(rest[:, i]) ** 2
            parts.append(remaining * frac)
            remaining = remaining * (1.0 - frac)
        parts.append(remaining)
        return jnp.stack(parts, axis=-1)

    def init_params(self, norms=None):
        """Angles reproducing ``norms`` at E0, zero energy slopes."""
        if norms is None:
            norms = [0.5 / self.k] * self.k
        return list(self._base.from_norms(np.asarray(norms))) \
            + [0.0] * self.k


class LCETemplate:
    """Energy-dependent mixture: density(phi, log10_en, params).

    With ``enorms`` (an :class:`ENormAngles`), component amplitudes
    evolve with photon energy too (reference lcenorm.py); otherwise
    norms are energy-independent scalars.  Parameter layout:
    [norm block, prim1 params, prim2 params, ...] where the norm block
    is either k plain norms or the 2k ENormAngles (angle, slope)
    parameters."""

    def __init__(self, primitives, norms=None, enorms=None):
        self.primitives = list(primitives)
        self.enorms = enorms
        k = len(self.primitives)
        if enorms is not None:
            if enorms.k != k:
                raise ValueError(
                    f"ENormAngles has k={enorms.k} but "
                    f"{k} primitives")
            norm_block = enorms.init_params(norms)
        else:
            norm_block = list(norms) if norms is not None \
                else [0.5 / k] * k
        self.params = np.array(
            list(norm_block)
            + [v for p in self.primitives for v in p.init_params()],
            dtype=np.float64,
        )

    @property
    def n_params(self):
        return len(self.params)

    @property
    def _n_norm(self):
        return (self.enorms.n_params if self.enorms is not None
                else len(self.primitives))

    def _split(self, params):
        nn = self._n_norm
        out, i = [], nn
        for p in self.primitives:
            out.append(params[i:i + p.n_params])
            i += p.n_params
        return params[:nn], out

    def density(self, phi, log10_en, params=None):
        params = jnp.asarray(self.params if params is None else params)
        norm_block, pp = self._split(params)
        if self.enorms is not None:
            norms = self.enorms.to_norms(norm_block, log10_en)
            out = 1.0 - jnp.sum(norms, axis=-1)
            for i, (p, q) in enumerate(zip(self.primitives, pp)):
                out = out + norms[:, i] * p.density(
                    jnp.asarray(phi), q, jnp.asarray(log10_en))
            return out
        norms = norm_block
        out = 1.0 - jnp.sum(norms)
        for p, q, n in zip(self.primitives, pp, jnp.atleast_1d(norms)):
            out = out + n * p.density(jnp.asarray(phi), q,
                                      jnp.asarray(log10_en))
        return out


# --- template file IO (reference: lctemplate.py:1009 prim_io,
# :609 prof_string; scripts/event_optimize.py:33 read_gaussfitfile) ----------

def _fwhm_loc(kind, width_param, loc):
    """(FWHM, loc) of a peaked primitive from its width parameter —
    shared between prof_string and convert_primitive."""
    if kind is LCGaussian:
        return _FWHM_SIGMA * width_param, loc
    if kind is LCLorentzian:
        return 2.0 * width_param, loc
    if kind is LCVonMises:
        # FWHM of exp(k(cos a - 1)): cos a = 1 + ln(1/2)/k
        return (np.arccos(max(1.0 - np.log(2.0) / width_param, -1.0))
                / np.pi, loc)
    raise ValueError(
        f"need a gaussian-like peaked primitive, not {kind.__name__}")


def prof_string(template: LCTemplate) -> str:
    """pygaussfit-compatible text for a gaussian-mixture template
    (reference lctemplate prof_string: phas/fwhm/ampl rows + const)."""
    norms, prim_params = template._split(np.asarray(template.params))
    lines = []
    total = 0.0
    for i, (prim, pp) in enumerate(zip(template.primitives, prim_params),
                                   start=1):
        width, loc = _fwhm_loc(type(prim), pp[0], pp[1])
        ampl = float(norms[i - 1])
        total += ampl
        lines += [f"phas{i} = {loc % 1.0:.5f} +/- 0.00000",
                  f"fwhm{i} = {width:.5f} +/- 0.00000",
                  f"ampl{i} = {ampl:.5f} +/- 0.00000"]
    dashes = "-" * 25
    return "\n".join([dashes, f"const = {1.0 - total:.5f} +/- 0.00000"]
                     + lines + [dashes])


def write_template(template: LCTemplate, path):
    """Write a ``# gauss`` template file readable by read_template
    (reference lcfitters write_template)."""
    with open(path, "w") as f:
        f.write("# gauss\n")
        f.write(prof_string(template) + "\n")


def read_template(path) -> LCTemplate:
    """Read a template file into an LCTemplate (reference prim_io):
    header line says ``gauss`` (phas/fwhm/ampl rows), ``fourier``
    (alpha beta rows), or ``kernel`` (raw photon phases, one per
    line)."""
    with open(path, "r") as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    if not lines:
        raise ValueError(f"empty template file {path}")
    label, body = lines[0].lower(), lines[1:]
    toks = [ln.split() for ln in body]
    if "gauss" in label:
        # two-pass: collect all rows by peak index first, so row order
        # (phas/fwhm/ampl interleaved or grouped) cannot matter
        locs, fwhms, ampls = {}, {}, {}
        for tok in toks:
            if not tok or "=" not in tok:
                continue
            key, val = tok[0].lower(), float(tok[2])
            if key.startswith("phas"):
                locs[int(key[4:] or 1)] = val
            elif key.startswith("fwhm"):
                fwhms[int(key[4:] or 1)] = val
            elif key.startswith("ampl"):
                ampls[int(key[4:] or 1)] = val
        if not fwhms or sorted(fwhms) != sorted(locs) \
                or sorted(fwhms) != sorted(ampls):
            raise ValueError(
                f"unbalanced gauss template in {path}: peaks "
                f"{sorted(locs)} / widths {sorted(fwhms)} / "
                f"amplitudes {sorted(ampls)}")
        idx = sorted(fwhms)
        prims = [LCGaussian(sigma=fwhms[i] / _FWHM_SIGMA, loc=locs[i])
                 for i in idx]
        return LCTemplate(prims, norms=[ampls[i] for i in idx])
    if "fourier" in label:
        return LCTemplate([LCEmpiricalFourier(input_file=path)],
                          norms=[1.0])
    if "kernel" in label:
        phases = [float(t[0]) for t in toks if t]
        prim = LCKernelDensity(phases=phases)
        return LCTemplate([prim], norms=[1.0])
    raise ValueError(f"unrecognized template format header {label!r}")


def read_gaussfitfile(path, proflen):
    """Binned profile (length ``proflen``, unit mean) from a
    pygaussfit.py output file (reference
    scripts/event_optimize.py:33) — the binned-template path of
    MCMCFitter consumes exactly this array."""
    tmpl = read_template(path)
    grid = (np.arange(proflen) + 0.5) / proflen
    return np.asarray(tmpl.density(grid))


def convert_primitive(prim, ptype=LCLorentzian):
    """Convert one peak to another kind, preserving location and FWHM
    (reference lcprimitives convert_primitive:1607)."""
    if type(prim) not in (LCGaussian, LCLorentzian, LCVonMises):
        raise ValueError(f"cannot convert {type(prim).__name__}")
    fwhm, loc = _fwhm_loc(type(prim), prim.init_params()[0], prim.loc)
    if ptype is LCGaussian:
        return LCGaussian(sigma=fwhm / _FWHM_SIGMA, loc=loc)
    if ptype is LCLorentzian:
        return LCLorentzian(gamma=fwhm / 2.0, loc=loc)
    if ptype is LCVonMises:
        half = np.cos(np.pi * fwhm)
        return LCVonMises(kappa=np.log(2.0) / max(1.0 - half, 1e-12),
                          loc=loc)
    raise ValueError(f"cannot convert to {ptype}")


class LCEFitter:
    """ML fitting of an energy-dependent template (reference
    lcfitters with lceprimitives)."""

    def __init__(self, template: LCETemplate, phases, log10_ens,
                 weights=None):
        self.template = template
        self.phases = np.asarray(phases, np.float64) % 1.0
        self.log10_ens = np.asarray(log10_ens, np.float64)
        self.weights = weights
        phi = jnp.asarray(self.phases)
        en = jnp.asarray(self.log10_ens)
        w = None if weights is None else jnp.asarray(weights)

        def lnlike(params):
            f = template.density(phi, en, params)
            if w is None:
                return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
            return jnp.sum(jnp.log(jnp.maximum(w * f + (1.0 - w),
                                               1e-300)))

        self._lnlike = lnlike
        # pintlint: allow=PTL101 -- same per-instance closure as
        # LCFitter above (weighted variant)
        self._val_grad = jax.jit(jax.value_and_grad(
            lambda p: -lnlike(p)))

    def lnlike(self, params=None):
        p = self.template.params if params is None else params
        return float(self._lnlike(jnp.asarray(p)))

    def fit(self, maxiter=200):
        from scipy.optimize import minimize

        nn = self.template._n_norm
        x0 = np.array(self.template.params)
        if self.template.enorms is not None:
            # ENormAngles: unconstrained angles/slopes, simplex valid
            # at every energy by construction — no bounds, no barrier
            bounds = [(None, None)] * len(x0)
            barrier = None
        else:
            bounds = [(1e-4, 1.0)] * nn \
                + [(None, None)] * (len(x0) - nn)
            barrier = _norm_barrier(nn)

        def fun(x):
            xj = jnp.asarray(x)
            v, g = self._val_grad(xj)
            if barrier is None:
                return float(v), np.asarray(g, np.float64)
            vb, gb = barrier(xj)
            return float(v + vb), np.asarray(g + gb, np.float64)

        res = minimize(fun, x0, jac=True, method="L-BFGS-B",
                       bounds=bounds, options={"maxiter": maxiter})
        self.template.params = np.asarray(res.x)
        return self.template.params, -float(res.fun)
