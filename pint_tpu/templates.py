"""Photon light-curve templates: wrapped mixture models + ML fitting.

Counterpart of the reference template subsystem (reference:
src/pint/templates/ — ``LCPrimitive`` gaussians at lcprimitives.py,
``LCTemplate`` mixtures at lctemplate.py:27, ML fitting at
lcfitters.py; 4819 LoC).  TPU redesign: a template is a pure jax
function of (phases, params); the photon log-likelihood

    lnL = sum_i log( w_i f(phi_i) + (1 - w_i) )      (Kerr 2011)

and its exact gradient/Hessian come from autodiff, so the fitter is
L-BFGS on device gradients instead of the reference's hand-coded
per-primitive derivative chains.

Primitives: wrapped Gaussian and wrapped Lorentzian (the reference's
workhorses).  A template is k primitives with amplitudes norms_k plus
the uniform background 1 - sum(norms).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LCGaussian", "LCLorentzian", "LCTemplate", "LCFitter"]

#: wraps to include in the wrapped-gaussian sum: exp(-(1/2)(k/sigma)^2)
#: is < 1e-12 for |k| > 2 at sigma <= 0.3, the widest sane peak
_NWRAP = 3


@dataclass
class LCGaussian:
    """Wrapped Gaussian peak: width sigma, location loc (turns)."""

    sigma: float = 0.03
    loc: float = 0.5

    n_params = 2

    def density(self, phi, p):
        sigma, loc = p[0], p[1]
        k = jnp.arange(-_NWRAP, _NWRAP + 1)
        z = (phi[..., None] - loc + k[None, :]) / sigma
        return jnp.sum(
            jnp.exp(-0.5 * z**2), axis=-1
        ) / (sigma * jnp.sqrt(2.0 * jnp.pi))

    def init_params(self):
        return [self.sigma, self.loc]


@dataclass
class LCLorentzian:
    """Wrapped Lorentzian peak: HWHM gamma, location loc (turns).
    The infinite wrap sum has the closed form sinh(2 pi g) /
    (cosh(2 pi g) - cos(2 pi (phi - loc)))."""

    gamma: float = 0.03
    loc: float = 0.5

    n_params = 2

    def density(self, phi, p):
        g, loc = p[0], p[1]
        two_pi = 2.0 * jnp.pi
        return jnp.sinh(two_pi * g) / (
            jnp.cosh(two_pi * g) - jnp.cos(two_pi * (phi - loc))
        )

    def init_params(self):
        return [self.gamma, self.loc]


class LCTemplate:
    """Mixture of primitives + uniform background (reference:
    lctemplate.py:27).  Parameter vector layout:
    [norm_1..norm_k, prim1_params..., prim2_params...]."""

    def __init__(self, primitives: List, norms=None):
        self.primitives = list(primitives)
        k = len(self.primitives)
        if norms is None:
            norms = [0.5 / k] * k
        self.params = np.array(
            list(norms)
            + [v for p in self.primitives for v in p.init_params()],
            dtype=np.float64,
        )

    @property
    def n_params(self):
        return len(self.params)

    def _split(self, params):
        k = len(self.primitives)
        norms = params[:k]
        out = []
        i = k
        for p in self.primitives:
            out.append(params[i:i + p.n_params])
            i += p.n_params
        return norms, out

    def density(self, phi, params=None):
        """Normalized profile f(phi) (integrates to 1 over a turn)."""
        params = self.params if params is None else params
        params = jnp.asarray(params)
        norms, prim_params = self._split(params)
        out = 1.0 - jnp.sum(norms)
        for p, pp, n in zip(self.primitives, prim_params,
                            jnp.atleast_1d(norms)):
            out = out + n * p.density(jnp.asarray(phi), pp)
        return out

    def __call__(self, phi, params=None):
        return self.density(phi, params)

    def lnlike_fn(self, phases, weights=None):
        """Pure function params -> photon log-likelihood (Kerr 2011
        weighted form; reference lcfitters loglikelihood)."""
        phases = jnp.asarray(phases)
        w = None if weights is None else jnp.asarray(weights)

        def lnlike(params):
            f = self.density(phases, params)
            if w is None:
                return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
            return jnp.sum(
                jnp.log(jnp.maximum(w * f + (1.0 - w), 1e-300))
            )

        return lnlike


class LCFitter:
    """Maximum-likelihood template fitting with autodiff gradients
    (reference: lcfitters.py:1-1085)."""

    def __init__(self, template: LCTemplate, phases, weights=None):
        self.template = template
        self.phases = np.asarray(phases, dtype=np.float64) % 1.0
        self.weights = weights
        self._lnlike = template.lnlike_fn(self.phases, weights)
        self._val_grad = jax.jit(
            jax.value_and_grad(lambda p: -self._lnlike(p))
        )

    def lnlike(self, params=None):
        p = self.template.params if params is None else params
        return float(self._lnlike(jnp.asarray(p)))

    def fit(self, maxiter=200):
        """L-BFGS-B with bounds keeping norms/widths physical; returns
        (params, lnlike).  Updates the template in place."""
        from scipy.optimize import minimize

        k = len(self.template.primitives)
        x0 = np.array(self.template.params)
        bounds = [(1e-4, 1.0)] * k
        for p in self.template.primitives:
            bounds += [(1e-3, 0.5), (None, None)]  # width, location

        # soft barrier keeping sum(norms) < 1 (a negative uniform
        # background is unphysical and its log-clamp has zero gradient,
        # so L-BFGS could otherwise settle there with k >= 2 peaks)
        barrier = jax.jit(jax.value_and_grad(
            lambda p: 1e8 * jnp.maximum(jnp.sum(p[:k]) - 0.995, 0.0) ** 2
        ))

        def fun(x):
            xj = jnp.asarray(x)
            v, g = self._val_grad(xj)
            vb, gb = barrier(xj)
            return float(v + vb), np.asarray(g + gb, dtype=np.float64)

        res = minimize(fun, x0, jac=True, method="L-BFGS-B",
                       bounds=bounds, options={"maxiter": maxiter})
        self.template.params = np.asarray(res.x)
        # wrap peak locations into [0, 1)
        norms, _ = self.template._split(self.template.params)
        i = k + 1
        for p in self.template.primitives:
            self.template.params[i] %= 1.0
            i += p.n_params
        return self.template.params, -float(res.fun)

    def param_uncertainties(self):
        """1-sigma uncertainties from the inverse Hessian of -lnL."""
        H = np.asarray(
            jax.hessian(lambda p: -self._lnlike(p))(
                jnp.asarray(self.template.params)
            )
        )
        try:
            cov = np.linalg.inv(H)
            return np.sqrt(np.clip(np.diag(cov), 0, None))
        except np.linalg.LinAlgError:
            return np.full(self.template.n_params, np.nan)
