"""Photon light-curve templates: wrapped mixture models + ML fitting.

Counterpart of the reference template subsystem (reference:
src/pint/templates/ — ``LCPrimitive`` gaussians at lcprimitives.py,
``LCTemplate`` mixtures at lctemplate.py:27, ML fitting at
lcfitters.py; 4819 LoC).  TPU redesign: a template is a pure jax
function of (phases, params); the photon log-likelihood

    lnL = sum_i log( w_i f(phi_i) + (1 - w_i) )      (Kerr 2011)

and its exact gradient/Hessian come from autodiff, so the fitter is
L-BFGS on device gradients instead of the reference's hand-coded
per-primitive derivative chains.

Primitives: wrapped Gaussian and wrapped Lorentzian (the reference's
workhorses).  A template is k primitives with amplitudes norms_k plus
the uniform background 1 - sum(norms).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "LCGaussian", "LCLorentzian", "LCVonMises", "LCTopHat",
    "LCHarmonic", "LCGaussian2", "LCLorentzian2",
    "LCTemplate", "LCFitter", "NormAngles",
    "LCEGaussian", "LCETemplate", "LCEFitter",
]

#: wraps to include in the wrapped-gaussian sum: exp(-(1/2)(k/sigma)^2)
#: is < 1e-12 for |k| > 2 at sigma <= 0.3, the widest sane peak
_NWRAP = 3


@dataclass
class LCGaussian:
    """Wrapped Gaussian peak: width sigma, location loc (turns)."""

    sigma: float = 0.03
    loc: float = 0.5

    n_params = 2

    def density(self, phi, p):
        sigma, loc = p[0], p[1]
        k = jnp.arange(-_NWRAP, _NWRAP + 1)
        z = (phi[..., None] - loc + k[None, :]) / sigma
        return jnp.sum(
            jnp.exp(-0.5 * z**2), axis=-1
        ) / (sigma * jnp.sqrt(2.0 * jnp.pi))

    def init_params(self):
        return [self.sigma, self.loc]


@dataclass
class LCLorentzian:
    """Wrapped Lorentzian peak: HWHM gamma, location loc (turns).
    The infinite wrap sum has the closed form sinh(2 pi g) /
    (cosh(2 pi g) - cos(2 pi (phi - loc)))."""

    gamma: float = 0.03
    loc: float = 0.5

    n_params = 2

    def density(self, phi, p):
        g, loc = p[0], p[1]
        two_pi = 2.0 * jnp.pi
        return jnp.sinh(two_pi * g) / (
            jnp.cosh(two_pi * g) - jnp.cos(two_pi * (phi - loc))
        )

    def init_params(self):
        return [self.gamma, self.loc]


@dataclass
class LCVonMises:
    """Von Mises (circular normal) peak: concentration kappa, location
    loc (reference lcprimitives LCVonMises).  Naturally periodic — no
    wrap sum needed: f = exp(kappa cos(2 pi (phi-loc))) / I0(kappa)."""

    kappa: float = 100.0
    loc: float = 0.5

    n_params = 2

    def density(self, phi, p):
        from jax.scipy.special import i0e

        kappa, loc = p[0], p[1]
        ang = 2.0 * jnp.pi * (jnp.asarray(phi) - loc)
        # exp(k cos a)/I0(k) = exp(k (cos a - 1)) / i0e(k)
        return jnp.exp(kappa * (jnp.cos(ang) - 1.0)) / i0e(kappa)

    def init_params(self):
        return [self.kappa, self.loc]


@dataclass
class LCTopHat:
    """Top hat of full width ``width`` (turns) centered at loc
    (reference lcprimitives LCTopHat)."""

    width: float = 0.1
    loc: float = 0.5

    n_params = 2

    def density(self, phi, p):
        width, loc = p[0], p[1]
        d = jnp.abs((jnp.asarray(phi) - loc + 0.5) % 1.0 - 0.5)
        return jnp.where(d <= width / 2.0, 1.0 / width, 0.0)

    def init_params(self):
        return [self.width, self.loc]


@dataclass
class LCHarmonic:
    """Pure cosine harmonic of fixed order: f = 1 + cos(2 pi n
    (phi - loc)) (reference lcprimitives LCHarmonic)."""

    order: int = 1
    loc: float = 0.0

    n_params = 1

    def density(self, phi, p):
        loc = p[0]
        return 1.0 + jnp.cos(2.0 * jnp.pi * self.order
                             * (jnp.asarray(phi) - loc))

    def init_params(self):
        return [self.loc]


def _two_sided(core_density):
    """Two-sided wrapper: width1 left of the peak, width2 right —
    normalized (the two half-profiles each carry weight 1/2)."""

    def density(phi, loc, w1, w2):
        d = (jnp.asarray(phi) - loc + 0.5) % 1.0 - 0.5  # [-0.5, 0.5)
        left = core_density(d, w1)
        right = core_density(d, w2)
        # each half-density integrates to 1/2 of its symmetric form
        return jnp.where(d < 0, 2.0 * w1 / (w1 + w2) * left,
                         2.0 * w2 / (w1 + w2) * right)

    return density


@dataclass
class LCGaussian2:
    """Two-sided wrapped Gaussian: sigma1 (leading), sigma2 (trailing)
    (reference lcprimitives LCGaussian2)."""

    sigma1: float = 0.03
    sigma2: float = 0.03
    loc: float = 0.5

    n_params = 3

    def density(self, phi, p):
        s1, s2, loc = p[0], p[1], p[2]

        def core(d, s):
            k = jnp.arange(-_NWRAP, _NWRAP + 1)
            z = (d[..., None] + k[None, :]) / s
            return jnp.sum(jnp.exp(-0.5 * z**2), axis=-1) / (
                s * jnp.sqrt(2.0 * jnp.pi))

        return _two_sided(core)(phi, loc, s1, s2)

    def init_params(self):
        return [self.sigma1, self.sigma2, self.loc]


@dataclass
class LCLorentzian2:
    """Two-sided wrapped Lorentzian: gamma1/gamma2 HWHM (reference
    lcprimitives LCLorentzian2)."""

    gamma1: float = 0.03
    gamma2: float = 0.03
    loc: float = 0.5

    n_params = 3

    def density(self, phi, p):
        g1, g2, loc = p[0], p[1], p[2]
        two_pi = 2.0 * jnp.pi

        def core(d, g):
            return jnp.sinh(two_pi * g) / (
                jnp.cosh(two_pi * g) - jnp.cos(two_pi * d))

        return _two_sided(core)(phi, loc, g1, g2)

    def init_params(self):
        return [self.gamma1, self.gamma2, self.loc]


class NormAngles:
    """Constrained normalization parameterization (reference
    lcnorm.py NormAngles): k component amplitudes expressed through
    angles so that every norm is in (0,1) and their sum stays < 1 for
    any unconstrained angle values — the fitter can then move freely
    without a barrier."""

    def __init__(self, k):
        self.k = k

    def to_norms(self, angles):
        """angles (k,) -> norms (k,): norm_i = sin^2(a_0) *
        prod_{j<i} cos^2(a_j) * sin^2(a_i) style stick-breaking."""
        angles = jnp.asarray(angles)
        total = jnp.sin(angles[0]) ** 2  # total pulsed fraction
        rest = angles[1:]
        parts = []
        remaining = total
        for i in range(self.k - 1):
            frac = jnp.sin(rest[i]) ** 2
            parts.append(remaining * frac)
            remaining = remaining * (1.0 - frac)
        parts.append(remaining)
        return jnp.stack(parts)

    def from_norms(self, norms):
        norms = np.asarray(norms, dtype=np.float64)
        total = norms.sum()
        angles = [np.arcsin(np.sqrt(np.clip(total, 1e-9, 1 - 1e-9)))]
        remaining = total
        for i in range(self.k - 1):
            frac = norms[i] / max(remaining, 1e-12)
            angles.append(np.arcsin(np.sqrt(np.clip(frac, 1e-9,
                                                    1 - 1e-9))))
            remaining -= norms[i]
        return np.array(angles)


class LCTemplate:
    """Mixture of primitives + uniform background (reference:
    lctemplate.py:27).  Parameter vector layout:
    [norm_1..norm_k, prim1_params..., prim2_params...]."""

    def __init__(self, primitives: List, norms=None):
        self.primitives = list(primitives)
        k = len(self.primitives)
        if norms is None:
            norms = [0.5 / k] * k
        self.params = np.array(
            list(norms)
            + [v for p in self.primitives for v in p.init_params()],
            dtype=np.float64,
        )

    @property
    def n_params(self):
        return len(self.params)

    def _split(self, params):
        k = len(self.primitives)
        norms = params[:k]
        out = []
        i = k
        for p in self.primitives:
            out.append(params[i:i + p.n_params])
            i += p.n_params
        return norms, out

    def density(self, phi, params=None):
        """Normalized profile f(phi) (integrates to 1 over a turn)."""
        params = self.params if params is None else params
        params = jnp.asarray(params)
        norms, prim_params = self._split(params)
        out = 1.0 - jnp.sum(norms)
        for p, pp, n in zip(self.primitives, prim_params,
                            jnp.atleast_1d(norms)):
            out = out + n * p.density(jnp.asarray(phi), pp)
        return out

    def __call__(self, phi, params=None):
        return self.density(phi, params)

    def lnlike_fn(self, phases, weights=None):
        """Pure function params -> photon log-likelihood (Kerr 2011
        weighted form; reference lcfitters loglikelihood)."""
        phases = jnp.asarray(phases)
        w = None if weights is None else jnp.asarray(weights)

        def lnlike(params):
            f = self.density(phases, params)
            if w is None:
                return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
            return jnp.sum(
                jnp.log(jnp.maximum(w * f + (1.0 - w), 1e-300))
            )

        return lnlike


class LCFitter:
    """Maximum-likelihood template fitting with autodiff gradients
    (reference: lcfitters.py:1-1085)."""

    def __init__(self, template: LCTemplate, phases, weights=None):
        self.template = template
        self.phases = np.asarray(phases, dtype=np.float64) % 1.0
        self.weights = weights
        self._lnlike = template.lnlike_fn(self.phases, weights)
        self._val_grad = jax.jit(
            jax.value_and_grad(lambda p: -self._lnlike(p))
        )

    def lnlike(self, params=None):
        p = self.template.params if params is None else params
        return float(self._lnlike(jnp.asarray(p)))

    def fit(self, maxiter=200):
        """L-BFGS-B with bounds keeping norms/widths physical; returns
        (params, lnlike).  Updates the template in place."""
        from scipy.optimize import minimize

        k = len(self.template.primitives)
        x0 = np.array(self.template.params)
        bounds = [(1e-4, 1.0)] * k
        for p in self.template.primitives:
            bounds += [(1e-3, 0.5), (None, None)]  # width, location

        # soft barrier keeping sum(norms) < 1 (a negative uniform
        # background is unphysical and its log-clamp has zero gradient,
        # so L-BFGS could otherwise settle there with k >= 2 peaks)
        barrier = jax.jit(jax.value_and_grad(
            lambda p: 1e8 * jnp.maximum(jnp.sum(p[:k]) - 0.995, 0.0) ** 2
        ))

        def fun(x):
            xj = jnp.asarray(x)
            v, g = self._val_grad(xj)
            vb, gb = barrier(xj)
            return float(v + vb), np.asarray(g + gb, dtype=np.float64)

        res = minimize(fun, x0, jac=True, method="L-BFGS-B",
                       bounds=bounds, options={"maxiter": maxiter})
        self.template.params = np.asarray(res.x)
        # wrap peak locations into [0, 1)
        norms, _ = self.template._split(self.template.params)
        i = k + 1
        for p in self.template.primitives:
            self.template.params[i] %= 1.0
            i += p.n_params
        return self.template.params, -float(res.fun)

    def param_uncertainties(self):
        """1-sigma uncertainties from the inverse Hessian of -lnL."""
        H = np.asarray(
            jax.hessian(lambda p: -self._lnlike(p))(
                jnp.asarray(self.template.params)
            )
        )
        try:
            cov = np.linalg.inv(H)
            return np.sqrt(np.clip(np.diag(cov), 0, None))
        except np.linalg.LinAlgError:
            return np.full(self.template.n_params, np.nan)


# --- energy-dependent templates (reference: lceprimitives.py /
# lcetemplate — primitive parameters evolve with photon energy) -------------

@dataclass
class LCEGaussian:
    """Wrapped Gaussian whose width and location evolve linearly in
    log10(E/E0) (reference lceprimitives LCEGaussian):
    sigma(E) = sigma + dsigma*x, loc(E) = loc + dloc*x,
    x = log10(E) - log10(E0)."""

    sigma: float = 0.03
    dsigma: float = 0.0
    loc: float = 0.5
    dloc: float = 0.0
    log10_e0: float = 2.0  # 100 MeV in the Fermi convention

    n_params = 4

    def density(self, phi, p, log10_en):
        x = jnp.asarray(log10_en) - self.log10_e0
        sigma = jnp.maximum(p[0] + p[1] * x, 1e-4)
        loc = p[2] + p[3] * x
        k = jnp.arange(-_NWRAP, _NWRAP + 1)
        z = (jnp.asarray(phi)[..., None] - loc[..., None]
             + k[None, :]) / sigma[..., None]
        return jnp.sum(jnp.exp(-0.5 * z**2), axis=-1) / (
            sigma * jnp.sqrt(2.0 * jnp.pi))

    def init_params(self):
        return [self.sigma, self.dsigma, self.loc, self.dloc]


class LCETemplate:
    """Energy-dependent mixture: density(phi, params, log10_en).
    Norms are energy-independent (the reference's lcenorm energy
    evolution can ride the same pattern)."""

    def __init__(self, primitives, norms=None):
        self.primitives = list(primitives)
        k = len(self.primitives)
        if norms is None:
            norms = [0.5 / k] * k
        self.params = np.array(
            list(norms)
            + [v for p in self.primitives for v in p.init_params()],
            dtype=np.float64,
        )

    @property
    def n_params(self):
        return len(self.params)

    def _split(self, params):
        k = len(self.primitives)
        out, i = [], k
        for p in self.primitives:
            out.append(params[i:i + p.n_params])
            i += p.n_params
        return params[:k], out

    def density(self, phi, log10_en, params=None):
        params = jnp.asarray(self.params if params is None else params)
        norms, pp = self._split(params)
        out = 1.0 - jnp.sum(norms)
        for p, q, n in zip(self.primitives, pp, jnp.atleast_1d(norms)):
            out = out + n * p.density(jnp.asarray(phi), q,
                                      jnp.asarray(log10_en))
        return out


class LCEFitter:
    """ML fitting of an energy-dependent template (reference
    lcfitters with lceprimitives)."""

    def __init__(self, template: LCETemplate, phases, log10_ens,
                 weights=None):
        self.template = template
        self.phases = np.asarray(phases, np.float64) % 1.0
        self.log10_ens = np.asarray(log10_ens, np.float64)
        self.weights = weights
        phi = jnp.asarray(self.phases)
        en = jnp.asarray(self.log10_ens)
        w = None if weights is None else jnp.asarray(weights)

        def lnlike(params):
            f = template.density(phi, en, params)
            if w is None:
                return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
            return jnp.sum(jnp.log(jnp.maximum(w * f + (1.0 - w),
                                               1e-300)))

        self._lnlike = lnlike
        self._val_grad = jax.jit(jax.value_and_grad(
            lambda p: -lnlike(p)))

    def lnlike(self, params=None):
        p = self.template.params if params is None else params
        return float(self._lnlike(jnp.asarray(p)))

    def fit(self, maxiter=200):
        from scipy.optimize import minimize

        k = len(self.template.primitives)
        x0 = np.array(self.template.params)
        bounds = [(1e-4, 1.0)] * k + [(None, None)] * (len(x0) - k)
        barrier = jax.jit(jax.value_and_grad(
            lambda p: 1e8 * jnp.maximum(jnp.sum(p[:k]) - 0.995,
                                        0.0) ** 2))

        def fun(x):
            xj = jnp.asarray(x)
            v, g = self._val_grad(xj)
            vb, gb = barrier(xj)
            return float(v + vb), np.asarray(g + gb, np.float64)

        res = minimize(fun, x0, jac=True, method="L-BFGS-B",
                       bounds=bounds, options={"maxiter": maxiter})
        self.template.params = np.asarray(res.x)
        return self.template.params, -float(res.fun)
