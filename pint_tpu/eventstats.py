"""Pulsation test statistics: Z^2_m, H-test, sigma conversions.

Counterpart of the reference eventstats module (reference:
src/pint/eventstats.py:1-346 ``z2m``/``hm``/``sf_*``): Rayleigh-family
statistics on photon phases, with optional weights (Kerr 2011).
Significance of the H-test follows de Jager & Buesching (2010):
sf = exp(-0.398405 H).
"""

from __future__ import annotations

import numpy as np

__all__ = ["z2m", "hm", "hmw", "sf_z2m", "sf_hm", "sig2sigma",
           "sigma2sig"]


def z2m(phases, m=2, weights=None):
    """Z^2_m statistics for harmonics 1..m; returns an array of the
    cumulative statistic at each m (reference eventstats.z2m)."""
    phases = np.asarray(phases, dtype=np.float64)
    w = np.ones_like(phases) if weights is None else np.asarray(weights)
    norm = np.sum(w**2)
    ks = np.arange(1, m + 1)
    arg = 2.0 * np.pi * np.outer(ks, phases)
    c = (np.cos(arg) * w).sum(axis=1)
    s = (np.sin(arg) * w).sum(axis=1)
    return 2.0 / norm * np.cumsum(c**2 + s**2)


def hm(phases, m=20):
    """H-test statistic (de Jager, Raubenheimer & Swanepoel 1989):
    max over m of Z^2_m - 4m + 4 (reference eventstats.hm)."""
    z = z2m(phases, m=m)
    return float(np.max(z - 4.0 * np.arange(1, m + 1) + 4.0))


def hmw(phases, weights, m=20):
    """Weighted H-test (Kerr 2011; reference eventstats.hmw)."""
    z = z2m(phases, m=m, weights=weights)
    return float(np.max(z - 4.0 * np.arange(1, m + 1) + 4.0))


def sf_z2m(z2, m=2):
    """Survival function of Z^2_m: chi^2 with 2m dof."""
    from scipy.stats import chi2

    return float(chi2.sf(z2, 2 * m))


def sf_hm(h, m=20):
    """H-test survival function, exp(-0.398405 H) (de Jager &
    Buesching 2010; reference eventstats.sf_hm).  The calibration was
    derived for the standard m=20 harmonic search; other m warn and
    use the same formula as an approximation."""
    if m != 20:
        import warnings

        warnings.warn(
            "sf_hm's exp(-0.398405 H) null calibration is for the "
            f"m=20 H-test; m={m} significance is approximate")
    return float(np.exp(-0.398405 * h))


def sig2sigma(sf):
    """Survival probability -> equivalent Gaussian sigma."""
    from scipy.stats import norm

    return float(norm.isf(sf))


def sigma2sig(sigma):
    from scipy.stats import norm

    return float(norm.sf(sigma))
