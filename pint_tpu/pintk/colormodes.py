"""TOA coloring modes for the plk panel (reference:
src/pint/pintk/colormodes.py — DefaultMode, FreqMode, ObsMode,
NameMode, JumpMode).

Headless: each mode maps the current Pulsar state to one matplotlib
color per (non-deleted) TOA plus a legend dict, so modes are unit
testable without Tk.  Register new modes by subclassing
:class:`ColorMode`; the plk widget lists ``COLOR_MODES`` by name.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ColorMode", "COLOR_MODES", "get_color_mode"]

# a colorblind-reasonable cycle for categorical modes
_CYCLE = [
    "#0072B2", "#D55E00", "#009E73", "#CC79A7", "#E69F00",
    "#56B4E9", "#F0E442", "#8B4513", "#666666", "#9400D3",
]


class ColorMode:
    """Base: subclasses implement ``colors(pulsar) -> (colors, legend)``
    where ``colors`` is a list of color strings aligned with
    ``pulsar.selected_toas`` and ``legend`` maps label -> color."""

    name = "base"

    def colors(self, pulsar):
        raise NotImplementedError


class DefaultMode(ColorMode):
    """All TOAs one color (pre-fit grey, post-fit blue like the
    reference's default look)."""

    name = "default"

    def colors(self, pulsar):
        c = "#0072B2" if pulsar.fitted else "#666666"
        n = len(pulsar.selected_toas)
        return [c] * n, {"TOAs": c}


class _CategoricalMode(ColorMode):
    """Color by a per-TOA category string."""

    def categories(self, pulsar):
        raise NotImplementedError

    def colors(self, pulsar):
        cats = self.categories(pulsar)
        labels = sorted(set(cats))
        cmap = {lab: _CYCLE[i % len(_CYCLE)] for i, lab in enumerate(labels)}
        return [cmap[c] for c in cats], cmap


class ObsMode(_CategoricalMode):
    """One color per observatory."""

    name = "obs"

    def categories(self, pulsar):
        return [str(o) for o in pulsar.selected_toas.obs_names]


class NameMode(_CategoricalMode):
    """One color per ``-name`` flag value (data-file / backend name)."""

    name = "name"

    def categories(self, pulsar):
        return [str(f.get("name", f.get("f", "unflagged")))
                for f in pulsar.selected_toas.flags]


class JumpMode(_CategoricalMode):
    """Color the TOAs under each JUMP selector; un-jumped TOAs grey."""

    name = "jump"

    def categories(self, pulsar):
        from pint_tpu.models.component import mask_from_select

        toas = pulsar.selected_toas
        cats = ["no jump"] * len(toas)
        model = pulsar.model
        # index runs across components so a PhaseJump and a DelayJump
        # never share a legend label (and therefore a color category)
        i = 0
        for comp_name in ("PhaseJump", "DelayJump"):
            if not model.has_component(comp_name):
                continue
            comp = model.component(comp_name)
            for sel in comp.selects:
                i += 1
                mask = np.asarray(mask_from_select(sel, toas))
                for j in np.flatnonzero(mask):
                    cats[int(j)] = f"JUMP{i}"
        return cats

    def colors(self, pulsar):
        cats = self.categories(pulsar)
        labels = sorted(set(cats) - {"no jump"})
        cmap = {lab: _CYCLE[i % len(_CYCLE)] for i, lab in enumerate(labels)}
        cmap["no jump"] = "#bbbbbb"
        return [cmap[c] for c in cats], cmap


class FreqMode(ColorMode):
    """Color by radio-frequency band (reference FreqMode bands)."""

    name = "freq"

    #: (upper edge MHz, label, color) — evaluated in order
    BANDS = [
        (300.0, "<300 MHz", "#9400D3"),
        (500.0, "300-500 MHz", "#0072B2"),
        (1000.0, "500-1000 MHz", "#009E73"),
        (1800.0, "1000-1800 MHz", "#E69F00"),
        (3000.0, "1800-3000 MHz", "#D55E00"),
        (np.inf, ">3000 MHz", "#CC79A7"),
    ]

    def colors(self, pulsar):
        freqs = np.asarray(pulsar.selected_toas.freq_mhz, np.float64)
        out = []
        used = {}
        for f in freqs:
            for hi, label, color in self.BANDS:
                if f < hi:
                    out.append(color)
                    used[label] = color
                    break
            else:  # inf frequency (barycentered photon TOAs)
                out.append("#666666")
                used["infinite"] = "#666666"
        return out, used


COLOR_MODES = {m.name: m for m in
               (DefaultMode(), FreqMode(), ObsMode(), NameMode(), JumpMode())}


def get_color_mode(name):
    try:
        return COLOR_MODES[name]
    except KeyError:
        raise ValueError(
            f"unknown color mode {name!r}; have {sorted(COLOR_MODES)}"
        ) from None
