"""GUI-facing pulsar state wrapper (reference: src/pint/pintk/pulsar.py
— the Tk plk panel drives this object, and so can scripts/tests,
headlessly).

Holds (parfile, timfile) -> model/TOAs/fit state and exposes the
operations the plk-style interface needs: fit, reset, delete/restore
TOAs, toggle parameter fit flags, add/remove phase jumps on a TOA
selection, random-model envelopes, and residual views (pre/post fit,
vs MJD / orbital phase / serial)."""

from __future__ import annotations

import copy

import numpy as np

from pint_tpu.fitter import Fitter
from pint_tpu.models.builder import (
    get_model,
    get_model_and_toas,
    model_to_parfile,
)
from pint_tpu.residuals import Residuals


class Pulsar:
    def __init__(self, parfile, timfile, ephem=None):
        self.parfile = parfile
        self.timfile = timfile
        kw = {}
        if ephem is not None:
            kw["ephem"] = ephem
        self.model_init, self.all_toas = get_model_and_toas(
            parfile, timfile, **kw)
        self.model = copy.deepcopy(self.model_init)
        self.deleted = np.zeros(len(self.all_toas), dtype=bool)
        self.fitter = None
        self.fitted = False
        self._postfit = None
        self._undo_stack = []
        #: bumped on every state mutation; keys the prefit-residuals
        #: memo so GUI redraws don't re-prepare/re-jit the model
        self._state_version = 0
        self._prefit_cache = None

    def _bump(self):
        self._state_version += 1

    # -- selection ------------------------------------------------------------
    @property
    def selected_toas(self):
        if not self.deleted.any():
            return self.all_toas
        return self.all_toas[~self.deleted]

    def delete_toas(self, indices):
        """Mark TOAs deleted (indices into the full set); undoable."""
        self._undo_stack.append(("deleted", self.deleted.copy()))
        self.deleted[np.asarray(indices, dtype=int)] = True
        self.fitted = False
        self._bump()

    def restore_all(self):
        self._undo_stack.append(("deleted", self.deleted.copy()))
        self.deleted[:] = False
        self.fitted = False
        self._bump()

    def undo(self):
        """Undo the most recent deletion / restore / phase wrap
        (reference plk 'u' key behavior).  Returns a description of
        what was undone, or None if the stack is empty."""
        if not self._undo_stack:
            return None
        kind, state = self._undo_stack.pop()
        if kind == "deleted":
            self.deleted = state
        elif kind == "padd":
            for i, old in state:
                if old is None:
                    self.all_toas.flags[i].pop("padd", None)
                else:
                    self.all_toas.flags[i]["padd"] = old
        self.fitted = False
        self._bump()
        return kind

    # -- phase wraps (reference pulsar.py add_phase_wrap: integer turns
    # via the delta_pulse_number column; here the -padd flag, which
    # Residuals folds into the phase assignment) -------------------------
    def add_phase_wrap(self, indices, wrap):
        """Add ``wrap`` (signed integer) turns to the selected TOAs
        (indices into the full set); undoable."""
        indices = np.asarray(indices, dtype=int)
        prior = []
        for i in indices:
            f = self.all_toas.flags[i]
            prior.append((int(i), f.get("padd")))
            f["padd"] = repr(float(f.get("padd", 0.0)) + float(wrap))
        self._undo_stack.append(("padd", prior))
        self.fitted = False
        self._bump()

    # -- parameters -----------------------------------------------------------
    def fit_params(self):
        return list(self.model.free_params)

    def set_fit_flag(self, name, fit: bool):
        self.model.params[name].frozen = not fit

    # -- jumps (reference pulsar.py add_phase_jump analogue) ------------------
    def add_jump(self, indices):
        """JUMP the selected TOAs via a per-TOA flag selector, then
        materialize the parameter through the shared
        ``TimingModel.jump_flags_to_params`` (reference
        timing_model.py:1727).  The flag value is one past the largest
        in use — values survive jump deletion, so reusing
        ``len(selects)+1`` after a delete would collide with a live
        flag and silently merge two jumps."""
        indices = np.asarray(indices, dtype=int)
        used = set()
        for f in self.all_toas.flags:
            if "gui_jump" in f:
                try:
                    used.add(int(str(f["gui_jump"])))
                except ValueError:
                    pass
        if self.model.has_component("PhaseJump"):
            for s in self.model.component("PhaseJump").selects:
                if s and s[0] == "flag" and s[1] == "gui_jump":
                    try:
                        used.add(int(str(s[2])))
                    except ValueError:
                        pass
        flagval = str(max(used, default=0) + 1)
        for i in indices:
            self.all_toas.flags[i]["gui_jump"] = flagval
        added = self.model.jump_flags_to_params(self.all_toas)
        self.fitted = False
        self._bump()
        return added[-1]

    # -- fitting ---------------------------------------------------------------
    #: fit-method menu entries (reference plk fitter selector)
    FIT_METHODS = ("auto", "wls", "gls", "downhill wls", "downhill gls")

    def fit(self, downhill=True, method="auto"):
        """Fit the non-deleted TOAs.  ``method`` is one of
        ``FIT_METHODS``: 'auto' (reference Fitter.auto dispatch) or an
        explicitly chosen fitter."""
        toas = self.selected_toas
        if method == "auto":
            self.fitter = Fitter.auto(toas, self.model, downhill=downhill)
        elif method == "wls":
            from pint_tpu.fitter import WLSFitter

            self.fitter = WLSFitter(toas, self.model)
        elif method == "gls":
            from pint_tpu.fitter import GLSFitter

            self.fitter = GLSFitter(toas, self.model)
        elif method == "downhill wls":
            from pint_tpu.downhill import DownhillWLSFitter

            self.fitter = DownhillWLSFitter(toas, self.model)
        elif method == "downhill gls":
            from pint_tpu.downhill import DownhillGLSFitter

            self.fitter = DownhillGLSFitter(toas, self.model)
        else:
            raise ValueError(f"unknown fit method {method!r}")
        self.fitter.fit_toas()
        self.model = self.fitter.model
        self._postfit = Residuals(toas, self.model)
        self.fitted = True
        self._bump()
        return self.fitter

    def reset_model(self):
        self.model = copy.deepcopy(self.model_init)
        self.fitted = False
        self._bump()

    def write_par(self, path):
        with open(path, "w") as f:
            f.write(model_to_parfile(self.model))

    def write_tim(self, path):
        from pint_tpu.toa import write_tim

        write_tim(self.all_toas, path)

    # -- residual views ---------------------------------------------------------
    def prefit_resids(self):
        """Pre-fit residuals, memoized on the state version (redraws
        would otherwise re-prepare + re-jit the model every time)."""
        if (self._prefit_cache is None
                or self._prefit_cache[0] != self._state_version):
            self._prefit_cache = (
                self._state_version,
                Residuals(self.selected_toas, self.model_init),
            )
        return self._prefit_cache[1]

    def postfit_resids(self):
        if not self.fitted:
            raise ValueError("not fitted yet")
        return self._postfit

    def active_resids(self):
        """The residuals the GUI is displaying: post-fit when fitted,
        else pre-fit — all y-axis views derive from this one object so
        they cannot mix models."""
        return self.postfit_resids() if self.fitted else self.prefit_resids()

    def xaxis(self, kind="mjd"):
        toas = self.selected_toas
        if kind == "mjd":
            return np.asarray(toas.mjd_float)
        if kind == "serial":
            return np.arange(len(toas), dtype=float)
        if kind == "orbital phase":
            from pint_tpu.derived_quantities import orbital_phase

            return orbital_phase(self.model, toas.ticks)
        if kind == "year":
            return 2000.0 + (np.asarray(toas.mjd_float) - 51544.5) / 365.25
        if kind == "day of year":
            # true calendar day-of-year (host-side; GUI axis only)
            import datetime

            mjd = np.asarray(toas.mjd_float)
            base = datetime.date(1858, 11, 17).toordinal()  # MJD 0
            doy = np.array([
                float(datetime.date.fromordinal(base + d).timetuple().tm_yday)
                for d in np.floor(mjd).astype(int)
            ])
            return doy + (mjd - np.floor(mjd))
        if kind == "frequency":
            return np.asarray(toas.freq_mhz)
        if kind == "TOA error":
            return np.asarray(toas.error_us)
        raise ValueError(f"unknown x-axis {kind!r}")

    XAXIS_CHOICES = ("mjd", "year", "day of year", "serial",
                     "orbital phase", "frequency", "TOA error")

    YAXIS_CHOICES = ("residual (us)", "residual (phase)", "pulse number")

    def yvals(self, kind="residual (us)"):
        """(values, errors-or-None, label) for the plk y axis
        (reference plk y-axis choices).  All views derive from
        ``active_resids()`` — one Residuals object, one model."""
        r = self.active_resids()
        if kind == "residual (us)":
            res = np.asarray(r.time_resids) * 1e6
            return res, np.asarray(r.scaled_errors) * 1e6, "residual [us]"
        if kind == "residual (phase)":
            res = np.asarray(r.phase_resids)
            f0 = float(r.model.values["F0"])
            return (res, np.asarray(r.scaled_errors) * f0,
                    "residual [turns]")
        if kind == "pulse number":
            # same model + cached jit as the residual views; -padd
            # wraps shift the displayed counts like they shift phase
            n, _ = r.prepared.phase()
            n = np.asarray(n, dtype=np.float64)
            dpn = self.selected_toas.get_delta_pulse_numbers()
            return n + dpn, None, "pulse number"
        raise ValueError(f"unknown y-axis {kind!r}")

    def random_models(self, n=16):
        """Residual spread envelope from the post-fit covariance
        (reference pintk random models panel / random_models.py)."""
        from pint_tpu.simulation import calculate_random_models

        if not self.fitted:
            raise ValueError("fit first")
        return calculate_random_models(self.fitter, self.selected_toas,
                                       n_models=n)


def grouped_fit_params(model):
    """Fittable parameters grouped by owning component, in component
    order: [(component_name, [param, ...]), ...] (reference pintk
    groups the fit checkboxes per component).  A parameter owned by
    several components (superset name collisions) appears only under
    the one whose Param object wins ``model.params``."""
    owner = model.params  # name -> winning Param object
    groups = []
    seen = set()
    for comp in model.components:
        names = [p.name for p in comp.params
                 if p.fittable and owner.get(p.name) is p
                 and p.name not in seen]
        if names:
            seen.update(names)
            groups.append((type(comp).__name__, names))
    return groups
