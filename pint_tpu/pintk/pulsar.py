"""GUI-facing pulsar state wrapper (reference: src/pint/pintk/pulsar.py
— the Tk plk panel drives this object, and so can scripts/tests,
headlessly).

Holds (parfile, timfile) -> model/TOAs/fit state and exposes the
operations the plk-style interface needs: fit, reset, delete/restore
TOAs, toggle parameter fit flags, add/remove phase jumps on a TOA
selection, random-model envelopes, and residual views (pre/post fit,
vs MJD / orbital phase / serial)."""

from __future__ import annotations

import copy

import numpy as np

from pint_tpu.fitter import Fitter
from pint_tpu.models.builder import (
    get_model,
    get_model_and_toas,
    model_to_parfile,
)
from pint_tpu.residuals import Residuals


class Pulsar:
    def __init__(self, parfile, timfile, ephem=None):
        self.parfile = parfile
        self.timfile = timfile
        kw = {}
        if ephem is not None:
            kw["ephem"] = ephem
        self.model_init, self.all_toas = get_model_and_toas(
            parfile, timfile, **kw)
        self.model = copy.deepcopy(self.model_init)
        self.deleted = np.zeros(len(self.all_toas), dtype=bool)
        self.fitter = None
        self.fitted = False
        self._postfit = None

    # -- selection ------------------------------------------------------------
    @property
    def selected_toas(self):
        if not self.deleted.any():
            return self.all_toas
        return self.all_toas[~self.deleted]

    def delete_toas(self, indices):
        """Mark TOAs deleted (indices into the full set)."""
        self.deleted[np.asarray(indices, dtype=int)] = True
        self.fitted = False

    def restore_all(self):
        self.deleted[:] = False
        self.fitted = False

    # -- parameters -----------------------------------------------------------
    def fit_params(self):
        return list(self.model.free_params)

    def set_fit_flag(self, name, fit: bool):
        self.model.params[name].frozen = not fit

    # -- jumps (reference pulsar.py add_phase_jump analogue) ------------------
    def add_jump(self, indices):
        """JUMP the selected TOAs via a per-TOA flag selector (the GUI
        convention: reference timing_model.py:1727 jump_flags_to_params
        wires -gui_jump flags into a JUMP maskParameter)."""
        from pint_tpu.models.jump import PhaseJump

        indices = np.asarray(indices, dtype=int)
        if not self.model.has_component("PhaseJump"):
            self.model.add_component(PhaseJump())
        comp = self.model.component("PhaseJump")
        njump = 1 + len(comp.selects)
        flagval = str(njump)
        for i in indices:
            self.all_toas.flags[i]["gui_jump"] = flagval
        from pint_tpu.models.parameter import Param

        sel = ("flag", "gui_jump", flagval)
        comp.selects = comp.selects + (sel,)
        name = f"JUMP{njump}"
        comp.add_param(Param(name, units="s", select=sel, frozen=False,
                             description="GUI phase jump"))
        self.model.values[name] = 0.0
        self.fitted = False
        return name

    # -- fitting ---------------------------------------------------------------
    def fit(self, downhill=True):
        toas = self.selected_toas
        self.fitter = Fitter.auto(toas, self.model, downhill=downhill)
        self.fitter.fit_toas()
        self.model = self.fitter.model
        self._postfit = Residuals(toas, self.model)
        self.fitted = True
        return self.fitter

    def reset_model(self):
        self.model = copy.deepcopy(self.model_init)
        self.fitted = False

    def write_par(self, path):
        with open(path, "w") as f:
            f.write(model_to_parfile(self.model))

    def write_tim(self, path):
        from pint_tpu.toa import write_tim

        write_tim(self.all_toas, path)

    # -- residual views ---------------------------------------------------------
    def prefit_resids(self):
        return Residuals(self.selected_toas, self.model_init)

    def postfit_resids(self):
        if not self.fitted:
            raise ValueError("not fitted yet")
        return self._postfit

    def xaxis(self, kind="mjd"):
        toas = self.selected_toas
        if kind == "mjd":
            return np.asarray(toas.mjd_float)
        if kind == "serial":
            return np.arange(len(toas), dtype=float)
        if kind == "orbital phase":
            vals = self.model.values
            if "PB" in vals:
                pb = float(vals["PB"])
                t0 = float(vals.get("T0", vals.get("TASC", 0.0)))
                # T0/TASC are stored as seconds since J2000 internally
                sec = toas.ticks / 2**32
                return ((sec - t0) / (pb * 86400.0)) % 1.0
            raise ValueError("model has no binary component")
        if kind == "year":
            return 2000.0 + (np.asarray(toas.mjd_float) - 51544.5) / 365.25
        raise ValueError(f"unknown x-axis {kind!r}")

    def random_models(self, n=16):
        """Residual spread envelope from the post-fit covariance
        (reference pintk random models panel / random_models.py)."""
        from pint_tpu.simulation import calculate_random_models

        if not self.fitted:
            raise ValueError("fit first")
        return calculate_random_models(self.fitter, self.selected_toas,
                                       n_models=n)
