"""Interactive fitting GUI (reference: src/pint/pintk/).

:mod:`pint_tpu.pintk.pulsar` is the headless state wrapper;
:mod:`pint_tpu.pintk.plk` is the Tk shell around it."""

from pint_tpu.pintk.pulsar import Pulsar  # noqa: F401
