"""Tk "plk"-style interactive fitting panel (reference:
src/pint/pintk/plk.py, 1707 LoC Tk widget).

Layout: a notebook with the plk canvas plus par/tim editor tabs
(paredit.py / timedit.py); the plk tab holds a matplotlib residual
canvas (pre/post fit), parameter fit-flag checkboxes, x-axis and
color-mode selectors (colormodes.py), fit-method menu, and action
buttons (Fit, Reset, Undo, Random models, Delete, Jump, phase wraps,
Write par/tim).  All state operations live in
:class:`pint_tpu.pintk.pulsar.Pulsar`, so the GUI is a thin shell and
the logic is testable headlessly.

Key bindings (reference plk helpstring analogues):
  f fit · r reset · u undo · d delete selection · j jump selection ·
  + / - add ±1 phase wrap to selection · c clear selection
"""

from __future__ import annotations

import numpy as np

from pint_tpu.pintk.colormodes import COLOR_MODES, get_color_mode


class PlkWidget:
    def __init__(self, root, pulsar):
        import tkinter as tk
        from tkinter import ttk
        from matplotlib.backends.backend_tkagg import (
            FigureCanvasTkAgg,
            NavigationToolbar2Tk,
        )
        from matplotlib.figure import Figure
        from matplotlib.widgets import RectangleSelector

        self.tk = tk
        self.root = root
        self.psr = pulsar
        self.selected = np.zeros(len(pulsar.all_toas), dtype=bool)
        #: 'z' toggles: the right-drag box zooms instead of selecting
        self.zoom_mode = False
        self._zoom_lims = None  # (xlim, ylim) or None = autoscale

        notebook = ttk.Notebook(root)
        notebook.pack(fill="both", expand=True)
        main = tk.Frame(notebook)
        notebook.add(main, text="plk")

        # par / tim editor tabs (lazy import keeps plk usable alone)
        from pint_tpu.pintk.paredit import ParWidget
        from pint_tpu.pintk.timedit import TimWidget

        partab = tk.Frame(notebook)
        notebook.add(partab, text="par")
        self.paredit = ParWidget(partab, pulsar, on_apply=self.on_model_change)
        timtab = tk.Frame(notebook)
        notebook.add(timtab, text="tim")
        self.timedit = TimWidget(timtab, pulsar, on_apply=self.on_toas_change)

        # left: parameter panel
        left = tk.Frame(main)
        left.pack(side="left", fill="y")
        tk.Label(left, text="Fit parameters").pack()
        self.param_frame = tk.Frame(left)
        self.param_frame.pack(fill="y")
        self.fit_vars = {}
        self._build_param_panel()

        # right: canvas + controls
        right = tk.Frame(main)
        right.pack(side="right", fill="both", expand=True)
        self.fig = Figure(figsize=(9, 5))
        self.ax = self.fig.add_subplot(111)
        self.canvas = FigureCanvasTkAgg(self.fig, master=right)
        self.canvas.get_tk_widget().pack(fill="both", expand=True)
        NavigationToolbar2Tk(self.canvas, right)
        self.canvas.mpl_connect("button_press_event", self._on_click)
        self.canvas.mpl_connect("key_press_event", self._on_key)
        self.box = RectangleSelector(
            self.ax, self._on_box, useblit=True, button=[3],
            minspanx=1e-12, minspany=1e-12)

        ctrl = tk.Frame(right)
        ctrl.pack(fill="x")
        self.xaxis = tk.StringVar(value="mjd")
        tk.OptionMenu(ctrl, self.xaxis, *self.psr.XAXIS_CHOICES,
                      command=lambda *_: self.update_plot()).pack(side="left")
        self.yaxis = tk.StringVar(value="residual (us)")
        tk.OptionMenu(ctrl, self.yaxis, *self.psr.YAXIS_CHOICES,
                      command=lambda *_: self.update_plot()).pack(side="left")
        self.colormode = tk.StringVar(value="default")
        tk.OptionMenu(ctrl, self.colormode, *sorted(COLOR_MODES),
                      command=lambda *_: self.update_plot()).pack(side="left")
        self.fitmethod = tk.StringVar(value="auto")
        tk.OptionMenu(ctrl, self.fitmethod,
                      *self.psr.FIT_METHODS).pack(side="left")
        for label, cmd in [
            ("Fit", self.do_fit), ("Reset", self.do_reset),
            ("Undo", self.do_undo),
            ("Random models", self.do_random),
            ("Delete selected", self.do_delete),
            ("Jump selected", self.do_jump),
            ("Wrap +1", lambda: self.do_wrap(+1)),
            ("Wrap -1", lambda: self.do_wrap(-1)),
            ("Write par", self.do_write_par),
            ("Write tim", self.do_write_tim),
        ]:
            tk.Button(ctrl, text=label, command=cmd).pack(side="left")
        self.status = tk.Label(right, anchor="w")
        self.status.pack(fill="x")
        self.update_plot()

    # -- panel builders --------------------------------------------------------
    def _build_param_panel(self):
        from pint_tpu.pintk.pulsar import grouped_fit_params

        for w in self.param_frame.winfo_children():
            w.destroy()
        self.fit_vars = {}
        for comp_name, names in grouped_fit_params(self.psr.model):
            self.tk.Label(self.param_frame, text=comp_name,
                          font=("TkDefaultFont", 9, "bold")
                          ).pack(anchor="w")
            for name in names:
                par = self.psr.model.params[name]
                v = self.tk.BooleanVar(value=not par.frozen)
                self.tk.Checkbutton(
                    self.param_frame, text=name, variable=v,
                    command=self._sync_fit_flags).pack(anchor="w",
                                                       padx=12)
                self.fit_vars[name] = v

    def on_model_change(self):
        """Par editor applied a new model."""
        self._build_param_panel()
        self.update_plot()

    def on_toas_change(self):
        """Tim editor applied a new TOA set."""
        self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
        self.update_plot()

    # -- actions ---------------------------------------------------------------
    def _sync_fit_flags(self):
        for name, v in self.fit_vars.items():
            self.psr.set_fit_flag(name, v.get())

    def do_fit(self):
        self._sync_fit_flags()
        self.psr.fit(method=self.fitmethod.get())
        r = self.psr.postfit_resids()
        self.status.config(
            text=f"chi2 = {r.chi2:.2f} / dof {r.dof} ; "
                 f"wrms = {r.rms_weighted()*1e6:.3f} us")
        self.update_plot()

    def do_reset(self):
        self.psr.reset_model()
        self.update_plot()

    def do_undo(self):
        kind = self.psr.undo()
        self.status.config(text=f"undid {kind}" if kind else "nothing to undo")
        self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
        self.update_plot()

    def do_random(self):
        if not self.psr.fitted:
            self.status.config(text="fit first")
            return
        if self.yaxis.get() != "residual (us)":
            self.status.config(
                text="random-model envelopes draw in residual (us) view")
            return
        spread = self.psr.random_models(16)
        x = self.psr.xaxis(self.xaxis.get())
        order = np.argsort(x)
        for row in np.asarray(spread):
            self.ax.plot(x[order], row[order] * 1e6, alpha=0.2,
                         color="gray", zorder=0)
        self.canvas.draw_idle()

    def do_delete(self):
        idx = np.flatnonzero(self.selected)
        if idx.size:
            self.psr.delete_toas(idx)
            self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
            self.update_plot()

    def do_jump(self):
        idx = np.flatnonzero(self.selected)
        if idx.size:
            name = self.psr.add_jump(idx)
            self.status.config(text=f"added {name}")
            self.update_plot()

    def do_wrap(self, sign):
        idx = np.flatnonzero(self.selected)
        if idx.size:
            self.psr.add_phase_wrap(idx, sign)
            self.status.config(text=f"phase wrap {sign:+d} on {idx.size} TOAs")
            self.update_plot()

    def do_write_par(self):
        from tkinter import filedialog

        path = filedialog.asksaveasfilename(defaultextension=".par")
        if path:
            self.psr.write_par(path)
            self.status.config(text=f"wrote {path}")

    def do_write_tim(self):
        from tkinter import filedialog

        path = filedialog.asksaveasfilename(defaultextension=".tim")
        if path:
            self.psr.write_tim(path)
            self.status.config(text=f"wrote {path}")

    # -- selection -------------------------------------------------------------
    def _visible_to_full(self, vis_idx):
        return np.flatnonzero(~self.psr.deleted)[vis_idx]

    def _on_click(self, event):
        if event.inaxes is not self.ax or event.xdata is None:
            return
        x = self.psr.xaxis(self.xaxis.get())
        i = int(np.argmin(np.abs(x - event.xdata)))
        full = self._visible_to_full(i)
        self.selected[full] = not self.selected[full]
        self.update_plot()

    def _on_box(self, eclick, erelease):
        """Right-drag box: selection, or zoom when zoom mode is on
        ('z'; reference plk zoom-area)."""
        x0, x1 = sorted((eclick.xdata, erelease.xdata))
        y0, y1 = sorted((eclick.ydata, erelease.ydata))
        if self.zoom_mode:
            self._zoom_lims = ((x0, x1), (y0, y1))
            self.update_plot()
            return
        x = self.psr.xaxis(self.xaxis.get())
        res, _, _ = self.psr.yvals(self.yaxis.get())
        inside = (x >= x0) & (x <= x1) & (res >= y0) & (res <= y1)
        if inside.any():
            self.selected[self._visible_to_full(np.flatnonzero(inside))] = True
            self.update_plot()

    HELP_TEXT = """plk key bindings (reference pintk helpPopup):
  f  fit          r  reset        u  undo
  d  delete sel   j  jump sel     c  clear selection
  +/- wrap sel by one turn
  z  toggle zoom mode (right-drag box zooms)
  o  zoom out (autoscale)
  h  this help
Mouse: left-click select TOA, right-drag box select/zoom."""

    def do_help(self):
        from tkinter import messagebox

        messagebox.showinfo("pintk help", self.HELP_TEXT,
                            parent=self.root)

    def do_zoom_reset(self):
        self._zoom_lims = None
        self.update_plot()

    def _on_key(self, event):
        key = (event.key or "").lower()
        if key == "f":
            self.do_fit()
        elif key == "r":
            self.do_reset()
        elif key == "u":
            self.do_undo()
        elif key == "d":
            self.do_delete()
        elif key == "j":
            self.do_jump()
        elif key in ("+", "="):
            self.do_wrap(+1)
        elif key == "-":
            self.do_wrap(-1)
        elif key == "c":
            self.selected[:] = False
            self.update_plot()
        elif key == "z":
            self.zoom_mode = not self.zoom_mode
            self.status.config(
                text=f"zoom mode {'ON' if self.zoom_mode else 'off'}")
        elif key == "o":
            self.do_zoom_reset()
        elif key == "h":
            self.do_help()

    # -- drawing ----------------------------------------------------------------
    def update_plot(self):
        self.ax.clear()
        x = self.psr.xaxis(self.xaxis.get())
        res, err, ylabel = self.psr.yvals(self.yaxis.get())
        colors, legend = get_color_mode(self.colormode.get()).colors(self.psr)
        if err is not None:
            self.ax.errorbar(x, res, yerr=err, fmt="none",
                             ecolor="#cccccc", zorder=1)
        self.ax.scatter(x, res, c=colors, s=16, zorder=2)
        if len(legend) > 1:
            import matplotlib.lines as mlines

            self.ax.legend(
                handles=[mlines.Line2D([], [], color=c, marker="o", ls="",
                                       label=lab)
                         for lab, c in sorted(legend.items())],
                loc="best", fontsize=8)
        sel = self.selected[~self.psr.deleted]
        if sel.any():
            self.ax.plot(x[sel], res[sel], "o", mfc="none", mec="red",
                         ms=9, zorder=3)
        self.ax.set_xlabel(self.xaxis.get())
        self.ax.set_ylabel(ylabel)
        self.ax.set_title(
            ("post-fit" if self.psr.fitted else "pre-fit")
            + f"  ({len(res)} TOAs)")
        if self._zoom_lims is not None:
            self.ax.set_xlim(*self._zoom_lims[0])
            self.ax.set_ylim(*self._zoom_lims[1])
        self.canvas.draw_idle()


def run(parfile, timfile, ephem=None):
    import tkinter as tk

    from pint_tpu.pintk.pulsar import Pulsar

    psr = Pulsar(parfile, timfile, ephem=ephem)
    root = tk.Tk()
    root.title(f"pintk (pint_tpu): {parfile}")
    PlkWidget(root, psr)
    root.mainloop()
