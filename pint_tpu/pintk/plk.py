"""Tk "plk"-style interactive fitting panel (reference:
src/pint/pintk/plk.py, 1707 LoC Tk widget).

Layout: matplotlib residual canvas (pre/post fit), parameter fit-flag
checkboxes, x-axis selector, and action buttons (Fit, Reset, Random
models, Delete selection, Jump selection, Write par/tim).  All state
operations live in :class:`pint_tpu.pintk.pulsar.Pulsar`, so the GUI is
a thin shell (and the logic is testable headlessly)."""

from __future__ import annotations

import numpy as np


class PlkWidget:
    def __init__(self, root, pulsar):
        import tkinter as tk
        from matplotlib.backends.backend_tkagg import (
            FigureCanvasTkAgg,
            NavigationToolbar2Tk,
        )
        from matplotlib.figure import Figure

        self.tk = tk
        self.root = root
        self.psr = pulsar
        self.selected = np.zeros(len(pulsar.all_toas), dtype=bool)

        main = tk.Frame(root)
        main.pack(fill="both", expand=True)

        # left: parameter panel
        left = tk.Frame(main)
        left.pack(side="left", fill="y")
        tk.Label(left, text="Fit parameters").pack()
        self.fit_vars = {}
        for name, par in pulsar.model.params.items():
            if not par.fittable:
                continue
            v = tk.BooleanVar(value=not par.frozen)
            tk.Checkbutton(left, text=name, variable=v,
                           command=self._sync_fit_flags).pack(anchor="w")
            self.fit_vars[name] = v

        # right: canvas + controls
        right = tk.Frame(main)
        right.pack(side="right", fill="both", expand=True)
        self.fig = Figure(figsize=(9, 5))
        self.ax = self.fig.add_subplot(111)
        self.canvas = FigureCanvasTkAgg(self.fig, master=right)
        self.canvas.get_tk_widget().pack(fill="both", expand=True)
        NavigationToolbar2Tk(self.canvas, right)
        self.canvas.mpl_connect("button_press_event", self._on_click)

        ctrl = tk.Frame(right)
        ctrl.pack(fill="x")
        self.xaxis = tk.StringVar(value="mjd")
        tk.OptionMenu(ctrl, self.xaxis, "mjd", "year", "serial",
                      "orbital phase",
                      command=lambda *_: self.update_plot()).pack(
            side="left")
        for label, cmd in [
            ("Fit", self.do_fit), ("Reset", self.do_reset),
            ("Random models", self.do_random),
            ("Delete selected", self.do_delete),
            ("Jump selected", self.do_jump),
            ("Write par", self.do_write_par),
        ]:
            tk.Button(ctrl, text=label, command=cmd).pack(side="left")
        self.status = tk.Label(right, anchor="w")
        self.status.pack(fill="x")
        self.update_plot()

    # -- actions ---------------------------------------------------------------
    def _sync_fit_flags(self):
        for name, v in self.fit_vars.items():
            self.psr.set_fit_flag(name, v.get())

    def do_fit(self):
        self._sync_fit_flags()
        f = self.psr.fit()
        r = self.psr.postfit_resids()
        self.status.config(
            text=f"chi2 = {r.chi2:.2f} / dof {r.dof} ; "
                 f"wrms = {r.rms_weighted()*1e6:.3f} us")
        self.update_plot()

    def do_reset(self):
        self.psr.reset_model()
        self.update_plot()

    def do_random(self):
        if not self.psr.fitted:
            self.status.config(text="fit first")
            return
        spread = self.psr.random_models(16)
        x = self.psr.xaxis(self.xaxis.get())
        order = np.argsort(x)
        for row in np.asarray(spread):
            self.ax.plot(x[order], row[order] * 1e6, alpha=0.2,
                         color="gray", zorder=0)
        self.canvas.draw_idle()

    def do_delete(self):
        idx = np.flatnonzero(self.selected)
        if idx.size:
            self.psr.delete_toas(idx)
            self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
            self.update_plot()

    def do_jump(self):
        idx = np.flatnonzero(self.selected)
        if idx.size:
            name = self.psr.add_jump(idx)
            self.status.config(text=f"added {name}")
            self.update_plot()

    def do_write_par(self):
        from tkinter import filedialog

        path = filedialog.asksaveasfilename(defaultextension=".par")
        if path:
            self.psr.write_par(path)
            self.status.config(text=f"wrote {path}")

    def _on_click(self, event):
        if event.inaxes is not self.ax or event.xdata is None:
            return
        x = self.psr.xaxis(self.xaxis.get())
        i = int(np.argmin(np.abs(x - event.xdata)))
        full = np.flatnonzero(~self.psr.deleted)[i]
        self.selected[full] = not self.selected[full]
        self.update_plot()

    # -- drawing ----------------------------------------------------------------
    def update_plot(self):
        self.ax.clear()
        r = (self.psr.postfit_resids() if self.psr.fitted
             else self.psr.prefit_resids())
        x = self.psr.xaxis(self.xaxis.get())
        res = np.asarray(r.time_resids) * 1e6
        err = np.asarray(r.scaled_errors) * 1e6
        self.ax.errorbar(x, res, yerr=err, fmt=".", ms=4)
        sel = self.selected[~self.psr.deleted]
        if sel.any():
            self.ax.plot(x[sel], res[sel], "o", mfc="none", mec="red")
        self.ax.set_xlabel(self.xaxis.get())
        self.ax.set_ylabel("residual [us]")
        self.ax.set_title(
            ("post-fit" if self.psr.fitted else "pre-fit")
            + f"  ({len(res)} TOAs)")
        self.canvas.draw_idle()


def run(parfile, timfile, ephem=None):
    import tkinter as tk

    from pint_tpu.pintk.pulsar import Pulsar

    psr = Pulsar(parfile, timfile, ephem=ephem)
    root = tk.Tk()
    root.title(f"pintk (pint_tpu): {parfile}")
    PlkWidget(root, psr)
    root.mainloop()
