"""Par-file editor pane (reference: src/pint/pintk/paredit.py).

The reference couples a Tk Text widget to the Pulsar state; here the
text-editing state machine is a headless :class:`ParEditor` (testable)
and :class:`ParWidget` is the thin Tk shell around it.

Semantics match the reference: the editor holds par text seeded from
the current model; Apply re-parses the text into a fresh model and
swaps it into the Pulsar (keeping TOAs); Reset re-seeds from the
model; Open/Write do file IO.
"""

from __future__ import annotations

import copy


class ParEditor:
    """Headless par-text editing core."""

    def __init__(self, pulsar):
        self.psr = pulsar
        self.text = ""
        self.reset()

    def reset(self):
        """Seed the buffer from the Pulsar's current model."""
        from pint_tpu.models.builder import model_to_parfile

        self.text = model_to_parfile(self.psr.model)

    def apply(self):
        """Parse the buffer into a model and swap it into the Pulsar.
        Raises on parse errors, leaving the Pulsar untouched."""
        from pint_tpu.models.builder import get_model

        model = get_model(self.text)
        self.psr.model = model
        self.psr.model_init = copy.deepcopy(model)
        self.psr.fitted = False
        self.psr._bump()
        return model

    def load(self, path):
        with open(path, "r") as f:
            self.text = f.read()

    def write(self, path):
        with open(path, "w") as f:
            f.write(self.text)


class ParWidget:
    """Tk shell: Text pane + Apply/Reset/Open/Write buttons."""

    def __init__(self, parent, pulsar, on_apply=None):
        import tkinter as tk
        from tkinter import filedialog

        self.editor = ParEditor(pulsar)
        self.on_apply = on_apply
        self._filedialog = filedialog

        frame = tk.Frame(parent)
        frame.pack(fill="both", expand=True)
        self.textbox = tk.Text(frame, width=60)
        self.textbox.pack(fill="both", expand=True)
        self.textbox.insert("1.0", self.editor.text)
        ctrl = tk.Frame(frame)
        ctrl.pack(fill="x")
        for label, cmd in [
            ("Apply", self.do_apply), ("Reset", self.do_reset),
            ("Open par...", self.do_open), ("Write par...", self.do_write),
        ]:
            tk.Button(ctrl, text=label, command=cmd).pack(side="left")
        self.status = tk.Label(frame, anchor="w")
        self.status.pack(fill="x")

    def _sync_from_box(self):
        self.editor.text = self.textbox.get("1.0", "end-1c")

    def _sync_to_box(self):
        self.textbox.delete("1.0", "end")
        self.textbox.insert("1.0", self.editor.text)

    def do_apply(self):
        self._sync_from_box()
        try:
            self.editor.apply()
        except Exception as e:  # surface parse errors in the status bar
            self.status.config(text=f"par error: {e}")
            return
        self.status.config(text="applied")
        if self.on_apply:
            self.on_apply()

    def do_reset(self):
        self.editor.reset()
        self._sync_to_box()
        self.status.config(text="reset from model")

    def do_open(self):
        path = self._filedialog.askopenfilename(
            filetypes=[("par files", "*.par"), ("all", "*")])
        if path:
            self.editor.load(path)
            self._sync_to_box()

    def do_write(self):
        self._sync_from_box()
        path = self._filedialog.asksaveasfilename(defaultextension=".par")
        if path:
            self.editor.write(path)
            self.status.config(text=f"wrote {path}")
