"""Tim-file editor pane (reference: src/pint/pintk/timedit.py).

Same split as paredit: headless :class:`TimEditor` core + thin Tk
:class:`TimWidget`.  Apply re-reads the edited tim text into a fresh
TOAs set (same ephemeris settings as the Pulsar's current TOAs) and
swaps it in, resetting deletions and fit state.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


class TimEditor:
    """Headless tim-text editing core."""

    def __init__(self, pulsar):
        self.psr = pulsar
        self.text = ""
        self.reset()

    def reset(self):
        """Seed the buffer from the Pulsar's tim file on disk (the
        reference seeds from the file, not the in-memory TOAs, so
        comments and commands survive)."""
        with open(self.psr.timfile, "r") as f:
            self.text = f.read()

    def apply(self):
        """Re-read the buffer into TOAs and swap into the Pulsar."""
        from pint_tpu.toa import get_TOAs

        old = self.psr.all_toas
        # prefer the source tim's directory so relative INCLUDEs still
        # resolve, but fall back to the system temp dir when that
        # directory is read-only (e.g. a mounted data tree)
        tim_dir = os.path.dirname(os.path.abspath(self.psr.timfile)) or None
        if tim_dir is not None and not os.access(tim_dir, os.W_OK):
            tim_dir = None
        with tempfile.NamedTemporaryFile(
            "w", suffix=".tim", delete=False, dir=tim_dir,
        ) as f:
            f.write(self.text)
            tmp = f.name
        try:
            toas = get_TOAs(tmp, ephem=old.ephem, planets=old.planets,
                            include_clock=old.include_clock,
                            include_bipm=old.include_bipm,
                            bipm_version=old.bipm_version,
                            use_cache=False)
        finally:
            os.unlink(tmp)
        self.psr.all_toas = toas
        self.psr.deleted = np.zeros(len(toas), dtype=bool)
        # undo entries index the old TOA set; they cannot survive a swap
        self.psr._undo_stack.clear()
        self.psr.fitted = False
        self.psr._bump()
        return toas

    def load(self, path):
        with open(path, "r") as f:
            self.text = f.read()

    def write(self, path):
        with open(path, "w") as f:
            f.write(self.text)


class TimWidget:
    """Tk shell: Text pane + Apply/Reset/Open/Write buttons."""

    def __init__(self, parent, pulsar, on_apply=None):
        import tkinter as tk
        from tkinter import filedialog

        self.editor = TimEditor(pulsar)
        self.on_apply = on_apply
        self._filedialog = filedialog

        frame = tk.Frame(parent)
        frame.pack(fill="both", expand=True)
        self.textbox = tk.Text(frame, width=80)
        self.textbox.pack(fill="both", expand=True)
        self.textbox.insert("1.0", self.editor.text)
        ctrl = tk.Frame(frame)
        ctrl.pack(fill="x")
        for label, cmd in [
            ("Apply", self.do_apply), ("Reset", self.do_reset),
            ("Open tim...", self.do_open), ("Write tim...", self.do_write),
        ]:
            tk.Button(ctrl, text=label, command=cmd).pack(side="left")
        self.status = tk.Label(frame, anchor="w")
        self.status.pack(fill="x")

    def _sync_from_box(self):
        self.editor.text = self.textbox.get("1.0", "end-1c")

    def _sync_to_box(self):
        self.textbox.delete("1.0", "end")
        self.textbox.insert("1.0", self.editor.text)

    def do_apply(self):
        self._sync_from_box()
        try:
            self.editor.apply()
        except Exception as e:
            self.status.config(text=f"tim error: {e}")
            return
        self.status.config(text=f"applied ({len(self.psr_toas())} TOAs)")
        if self.on_apply:
            self.on_apply()

    def psr_toas(self):
        return self.editor.psr.all_toas

    def do_reset(self):
        self.editor.reset()
        self._sync_to_box()

    def do_open(self):
        path = self._filedialog.askopenfilename(
            filetypes=[("tim files", "*.tim"), ("all", "*")])
        if path:
            self.editor.load(path)
            self._sync_to_box()

    def do_write(self):
        self._sync_from_box()
        path = self._filedialog.asksaveasfilename(defaultextension=".tim")
        if path:
            self.editor.write(path)
            self.status.config(text=f"wrote {path}")
