"""Polycos: TEMPO-style polynomial phase predictors.

Counterpart of the reference polycos module (reference:
src/pint/polycos.py:678 ``generate_polycos``, :921 ``eval_abs_phase``,
:231/:359 tempo-format read/write).  Convention (polycos.py:10):

    dt    = (t - TMID) * 1440          [minutes]
    phase = RPHASE + dt*60*F0 + c_0 + c_1 dt + ... + c_{n-1} dt^{n-1}
    freq  = F0 + (1/60) (c_1 + 2 c_2 dt + ...)                   [Hz]

TPU redesign: each segment's coefficients come from one least-squares
fit of the jitted model phase evaluated at Chebyshev-spaced nodes — all
segments' node phases are computed in a single batched device call, and
the giant integer part is differenced exactly (int64) against the
segment midpoint before any float work.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

from pint_tpu import SECS_PER_DAY
from pint_tpu.toa import TOA, TOAs

__all__ = ["PolycoEntry", "Polycos", "generate_polycos"]

MIN_PER_DAY = 1440.0


@dataclass
class PolycoEntry:
    tmid_mjd: float  # UTC-ish MJD of segment midpoint (site time)
    mjdspan_min: float
    rphase_int: int
    rphase_frac: float
    f0: float
    obs_code: str
    obsfreq_mhz: float
    coeffs: np.ndarray  # (ncoeff,)

    @property
    def ncoeff(self):
        return len(self.coeffs)

    def covers(self, t_mjd):
        half = self.mjdspan_min / MIN_PER_DAY / 2.0
        return (t_mjd >= self.tmid_mjd - half) & (
            t_mjd <= self.tmid_mjd + half
        )

    def evalabsphase(self, t_mjd):
        """(int_turns, frac) at site MJD t (reference evalabsphase)."""
        t_mjd = np.asarray(t_mjd, dtype=np.float64)
        dt = (t_mjd - self.tmid_mjd) * MIN_PER_DAY
        poly = np.polynomial.polynomial.polyval(dt, self.coeffs)
        total = self.rphase_frac + dt * 60.0 * self.f0 + poly
        n = np.floor(total)
        return self.rphase_int + n.astype(np.int64), total - n

    def evalphase(self, t_mjd):
        return self.evalabsphase(t_mjd)[1]

    def evalfreq(self, t_mjd):
        """Apparent spin frequency [Hz] (reference evalfreq)."""
        dt = (np.asarray(t_mjd, np.float64) - self.tmid_mjd) * MIN_PER_DAY
        dcoef = np.polynomial.polynomial.polyder(self.coeffs)
        return self.f0 + np.polynomial.polynomial.polyval(dt, dcoef) / 60.0


class Polycos:
    """Ordered entry collection + tempo-format IO (reference:
    Polycos class, polycos.py:411)."""

    def __init__(self, entries: List[PolycoEntry], psrname=""):
        self.entries = sorted(entries, key=lambda e: e.tmid_mjd)
        self.psrname = psrname

    def find_entry(self, t_mjd):
        """Index of the covering entry for each time (nearest TMID among
        covering segments; raises if any time is uncovered)."""
        t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
        tmids = np.array([e.tmid_mjd for e in self.entries])
        idx = np.clip(
            np.searchsorted(tmids, t), 0, len(self.entries) - 1
        )
        # candidate could be the one before
        prev = np.clip(idx - 1, 0, len(self.entries) - 1)
        d_idx = np.abs(tmids[idx] - t)
        d_prev = np.abs(tmids[prev] - t)
        best = np.where(d_prev < d_idx, prev, idx)
        for i, ti in zip(best, t):
            if not self.entries[i].covers(ti):
                raise ValueError(f"MJD {ti} not covered by any polyco")
        return best

    def eval_abs_phase(self, t_mjd):
        """(int64 turns, f64 frac) at site MJDs (reference :921)."""
        t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
        idx = self.find_entry(t)
        n = np.zeros(len(t), dtype=np.int64)
        frac = np.zeros(len(t))
        for i in np.unique(idx):
            m = idx == i
            ni, fi = self.entries[i].evalabsphase(t[m])
            n[m] = ni
            frac[m] = fi
        return n, frac

    def eval_phase(self, t_mjd):
        return self.eval_abs_phase(t_mjd)[1]

    def eval_spin_freq(self, t_mjd):
        t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
        idx = self.find_entry(t)
        out = np.zeros(len(t))
        for i in np.unique(idx):
            m = idx == i
            out[m] = self.entries[i].evalfreq(t[m])
        return out

    # -- tempo format IO -----------------------------------------------------
    def write_polyco_file(self, path):
        """TEMPO-style polyco.dat (reference write_polyco_file:359).

        Layout per entry: header (name, date, utc, TMID, DM, doppler,
        rms), data line (RPHASE int.frac9, F0, site, span[min], ncoeff,
        obsfreq), then coefficients 3 per line in D-exponent form."""
        with open(path, "w") as f:
            for e in self.entries:
                f.write(
                    f"{self.psrname:<10s} {'DATE':>9s}{0.0:11.2f}"
                    f"{e.tmid_mjd:20.11f}{0.0:21.6f} {0.0:6.3f}"
                    f"{0.0:7.3f}\n"
                )
                # carry: frac >= 0.9999999995 rounds to 10^9, which must
                # increment the integer part (a 10-digit fraction field
                # would read back as 0.1 — a ~0.9-turn error)
                rph_i, rph_f9 = e.rphase_int, int(round(e.rphase_frac * 1e9))
                if rph_f9 >= 10**9:
                    rph_i, rph_f9 = rph_i + 1, rph_f9 - 10**9
                rph = f"{rph_i:d}.{rph_f9:09d}"
                f.write(
                    f"{rph:<24s}{e.f0:18.12f} {e.obs_code:>4s}"
                    f"{e.mjdspan_min:10.1f}{e.ncoeff:5d}"
                    f"{e.obsfreq_mhz:10.3f}\n"
                )
                for i in range(0, e.ncoeff, 3):
                    row = e.coeffs[i:i + 3]
                    f.write(
                        " ".join(f"{c:23.17E}".replace("E", "D")
                                 for c in row) + "\n"
                    )

    @classmethod
    def read_polyco_file(cls, path):
        """Parse the tempo polyco format written above (reference
        read_polyco_file:231)."""
        entries = []
        psrname = ""
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f if ln.strip()]
        i = 0
        while i < len(lines):
            toks = lines[i].split()
            psrname = toks[0]
            tmid = float(toks[3])
            t2 = lines[i + 1].split()
            ip, fp = t2[0].split(".")
            f0 = float(t2[1])
            obs = t2[2]
            span = float(t2[3])
            ncoeff = int(t2[4])
            obsfreq = float(t2[5])
            ncoefflines = (ncoeff + 2) // 3
            coeffs = []
            for j in range(ncoefflines):
                coeffs += [
                    float(c.upper().replace("D", "E"))
                    for c in lines[i + 2 + j].split()
                ]
            entries.append(
                PolycoEntry(
                    tmid_mjd=tmid, mjdspan_min=span,
                    rphase_int=int(ip),
                    rphase_frac=float("0." + fp),
                    f0=f0, obs_code=obs, obsfreq_mhz=obsfreq,
                    coeffs=np.array(coeffs),
                )
            )
            i += 2 + ncoefflines
        return cls(entries, psrname=psrname)


def generate_polycos(
    model,
    mjd_start,
    mjd_end,
    obs,
    segment_length_min=60.0,
    ncoeff=12,
    obsfreq_mhz=1400.0,
    nodes_per_segment=None,
):
    """Fit polyco segments to the full timing model (reference
    generate_polycos:678).

    Least-squares polynomial fit (numpy polyfit on Chebyshev-spaced
    nodes) of the model's absolute phase minus the RPHASE + 60 F0 dt
    ramp; one batched model evaluation covers every node of every
    segment."""
    span_days = segment_length_min / MIN_PER_DAY
    nseg = int(np.ceil((mjd_end - mjd_start) / span_days))
    nodes = nodes_per_segment or max(2 * ncoeff, 24)
    # Chebyshev nodes avoid Runge oscillation at the segment edges
    cheb = np.cos(np.pi * (2 * np.arange(nodes) + 1) / (2.0 * nodes))
    all_mjds = []
    tmids = []
    for k in range(nseg):
        t0 = mjd_start + k * span_days
        tmid = t0 + span_days / 2.0
        tmids.append(tmid)
        all_mjds.append(tmid + cheb * span_days / 2.0)
    all_mjds = np.concatenate(all_mjds)
    order = np.argsort(all_mjds)
    inv = np.argsort(order)

    toa_list = []
    quantized = []
    den = 10**13  # node-time quantum 8.6 ns => F0 * dt ~ 1e-6 turns max
    for mjd in all_mjds[order]:
        day = int(np.floor(mjd))
        num = int(round((mjd - day) * den))
        toa_list.append(
            TOA(day, num, den, 1.0, float(obsfreq_mhz), obs, {}, "poly")
        )
        # fit against the time the model actually saw, not the requested
        # one — otherwise the quantization becomes phase noise
        quantized.append(day + num / den)
    all_mjds = np.asarray(quantized)[inv]
    toas = TOAs(toa_list, ephem=model.meta.get("EPHEM", "builtin"))
    prepared = model.prepare(toas)
    n, frac = prepared.phase()
    n = np.asarray(n)[inv]
    frac = np.asarray(frac)[inv]

    f0 = float(model.values["F0"])
    entries = []
    for k in range(nseg):
        sl = slice(k * nodes, (k + 1) * nodes)
        dt_min = (all_mjds[sl] - tmids[k]) * MIN_PER_DAY
        # exact integer differencing against the node nearest tmid
        imid = np.argmin(np.abs(dt_min))
        dn = (n[sl] - n[sl][imid]).astype(np.float64)
        dphase = dn + (frac[sl] - frac[sl][imid])
        resid = dphase - dt_min * 60.0 * f0
        # fit in the scaled [-1, 1] domain (a raw degree-11 Vandermonde
        # over dt in [-30, 30] min has condition ~1e16), then convert
        # exactly to the power basis the tempo format requires
        p = np.polynomial.Polynomial.fit(dt_min, resid, ncoeff - 1)
        coeffs = p.convert().coef
        if len(coeffs) < ncoeff:
            coeffs = np.pad(coeffs, (0, ncoeff - len(coeffs)))
        # move the fitted constant into RPHASE's fractional part
        rphase_frac = frac[sl][imid] + coeffs[0]
        rph_i = int(n[sl][imid])
        coeffs = coeffs.copy()
        coeffs[0] = 0.0
        # renormalize frac into [0, 1)
        extra = np.floor(rphase_frac)
        rph_i += int(extra)
        rphase_frac -= extra
        from pint_tpu.obs import get_observatory

        code = getattr(get_observatory(obs), "tempo_code", None) or obs
        entries.append(
            PolycoEntry(
                tmid_mjd=tmids[k], mjdspan_min=segment_length_min,
                rphase_int=rph_i, rphase_frac=float(rphase_frac),
                f0=f0, obs_code=str(code), obsfreq_mhz=float(obsfreq_mhz),
                coeffs=coeffs,
            )
        )
    return Polycos(entries, psrname=model.meta.get("PSR", ""))
