"""SLO engine: rolling-window latency quantiles, availability, and
error-budget burn for the serve plane.

Objectives are declared by environment (host-only knobs — they shape
admission policy, never a traced program):

- ``$PINT_TPU_SLO_P99_MS`` — per-op p99 latency objective in ms
  (unset/0 disables the latency objective),
- ``$PINT_TPU_SLO_AVAIL`` — availability objective as a fraction
  (e.g. ``0.999``; unset disables).

Every served request outcome is recorded into per-second buckets of
geometric latency counts (the same bucket geometry as
:class:`pint_tpu.telemetry.LogHistogram`, so fleet aggregation can
merge replica histograms bucket-wise).  Three rolling windows —
**1 m / 10 m / 1 h** — are merged on demand from those buckets:
per-op p50/p95/p99, availability, and the **burn rate** = fraction of
the error budget consumed per unit of budget:

- availability burn = ``err_fraction / (1 - avail_objective)``,
- latency burn = ``slow_fraction / 0.01`` (a p99 objective grants a
  1% slow budget by definition).

A burn of 1.0 spends the budget exactly at the rate it accrues;
sustained burn >= :data:`DEGRADE_BURN` on the 1-minute window trips
the **degrade hook**: admission shrinks ``queue_max`` (see
:func:`effective_queue_max`) so the replica sheds early instead of
queueing work it will miss the objective on — trading 429s (cheap,
retryable) for deadline misses (wasted device work).  The hook
releases once the fast-window burn falls back under 1.0.

Verdicts: ``ok`` (objectives met), ``violated`` (an objective missed
in some window with data), ``no_data`` (nothing recorded / no
objectives declared).  ``/slo`` serves the full snapshot; the verdict
and burn gauges ride ``/metrics`` and ``/v1/stats``.
"""

from __future__ import annotations

import math
import os
import threading
import time

from pint_tpu import telemetry

__all__ = [
    "SloTracker", "tracker", "reset", "record", "objectives",
    "effective_queue_max", "quantiles_from_buckets",
    "P99_ENV", "AVAIL_ENV", "WINDOWS", "DEGRADE_BURN",
]

P99_ENV = "PINT_TPU_SLO_P99_MS"
AVAIL_ENV = "PINT_TPU_SLO_AVAIL"

#: (label, seconds) rolling windows, fastest first — the 1 m window
#: drives the degrade hook, the slower ones catch slow burns.
WINDOWS = (("1m", 60), ("10m", 600), ("1h", 3600))

#: 1-minute burn rate that trips the admission degrade hook.  2x is
#: the classic fast-burn page threshold: at 2x the whole budget is
#: gone in half the objective period, so acting early is cheap
#: relative to waiting.
DEGRADE_BURN = 2.0

#: queue_max multiplier while degraded (see effective_queue_max).
DEGRADE_QUEUE_SCALE = 0.5

_BASE = telemetry.LogHistogram.BASE
_LOG_GROWTH = math.log(telemetry.LogHistogram.GROWTH)


def _bucket_idx(latency_s):
    v = float(latency_s)
    if v <= _BASE:
        return 0
    return 1 + int(math.log(v / _BASE) / _LOG_GROWTH)


def _bucket_value(idx):
    if idx <= 0:
        return _BASE
    return _BASE * math.exp((idx - 0.5) * _LOG_GROWTH)


def quantiles_from_buckets(buckets, qs=(50, 95, 99)):
    """Percentile estimates (seconds) from a ``{idx: count}`` table
    in LogHistogram geometry — shared by the tracker and by fleet
    aggregation, so a bucket-wise merged fleet histogram reads out
    through the exact same estimator as a single replica's."""
    items = sorted((int(i), int(c)) for i, c in buckets.items())
    n = sum(c for _, c in items)
    if n == 0:
        return {q: None for q in qs}
    out = {}
    for q in sorted(qs):
        rank = max(1, math.ceil(q / 100.0 * n))
        cum = 0
        est = _bucket_value(items[-1][0])
        for idx, c in items:
            cum += c
            if cum >= rank:
                est = _bucket_value(idx)
                break
        out[q] = est
    return out


def objectives():
    """The declared objectives: ``{"p99_ms": float|None,
    "avail": float|None}`` (None = objective not declared)."""
    def _f(env):
        raw = os.environ.get(env, "").strip()
        if not raw:
            return None
        try:
            v = float(raw)
        except ValueError:
            return None
        return v if v > 0 else None
    avail = _f(AVAIL_ENV)
    if avail is not None and avail >= 1.0:
        avail = None  # a 100% objective has a zero budget: undefined burn
    return {"p99_ms": _f(P99_ENV), "avail": avail}


class _SecBucket:
    """One second's outcomes: per-op (count, errors, slow, latency
    bucket table)."""

    __slots__ = ("ops",)

    def __init__(self):
        self.ops = {}  # op -> [n, err, slow, {idx: count}]


class SloTracker:
    """Rolling-window SLO accounting.  ``time_fn`` is injectable so
    tests can drive the windows with a fake clock."""

    def __init__(self, p99_ms=None, avail=None, time_fn=time.time):
        if p99_ms is None and avail is None:
            obj = objectives()
            p99_ms, avail = obj["p99_ms"], obj["avail"]
        self.p99_ms = p99_ms
        self.avail = avail
        self._time = time_fn
        self._lock = threading.Lock()
        self._buckets = {}  # int(second) -> _SecBucket
        self._horizon = WINDOWS[-1][1]
        self._degraded = False
        self._verdict_cache = (None, -1.0)  # (snapshot, asof)

    # -- recording ----------------------------------------------------------
    def record(self, op, latency_s, ok=True):
        """One request outcome.  Failed requests (sheds, deadline
        misses, serve errors) count against availability; their
        latency is excluded from the quantiles (a shed's 0 ms must
        not improve p99)."""
        now = int(self._time())
        slow = (ok and self.p99_ms is not None
                and latency_s * 1e3 > self.p99_ms)
        idx = _bucket_idx(latency_s) if ok else None
        with self._lock:
            sec = self._buckets.get(now)
            if sec is None:
                sec = self._buckets[now] = _SecBucket()
                self._prune_locked(now)
            cell = sec.ops.get(op)
            if cell is None:
                cell = sec.ops[op] = [0, 0, 0, {}]
            cell[0] += 1
            if not ok:
                cell[1] += 1
            if slow:
                cell[2] += 1
            if idx is not None:
                cell[3][idx] = cell[3].get(idx, 0) + 1
        telemetry.counter_add("slo.requests")
        if not ok:
            telemetry.counter_add("slo.errors")

    def _prune_locked(self, now):
        if len(self._buckets) <= self._horizon + 2:
            return
        cutoff = now - self._horizon
        for sec in [s for s in self._buckets if s < cutoff]:
            del self._buckets[sec]

    # -- windows ------------------------------------------------------------
    def _window_locked(self, now, seconds):
        """Merged per-op cells over the last ``seconds``."""
        cutoff = now - seconds
        ops = {}
        for sec, bucket in self._buckets.items():
            if sec <= cutoff or sec > now:
                continue
            for op, (n, err, slow, hist) in bucket.ops.items():
                cell = ops.get(op)
                if cell is None:
                    cell = ops[op] = [0, 0, 0, {}]
                cell[0] += n
                cell[1] += err
                cell[2] += slow
                for idx, c in hist.items():
                    cell[3][idx] = cell[3].get(idx, 0) + c
        return ops

    def _window_doc(self, ops):
        doc = {"ops": {}, "n": 0, "errors": 0, "slow": 0}
        total = [0, 0, 0, {}]
        for op, (n, err, slow, hist) in sorted(ops.items()):
            qs = quantiles_from_buckets(hist)
            doc["ops"][op] = {
                "n": n, "errors": err, "slow": slow,
                "p50_ms": None if qs[50] is None else qs[50] * 1e3,
                "p95_ms": None if qs[95] is None else qs[95] * 1e3,
                "p99_ms": None if qs[99] is None else qs[99] * 1e3,
                "buckets": {str(i): c for i, c in sorted(hist.items())},
            }
            total[0] += n
            total[1] += err
            total[2] += slow
            for idx, c in hist.items():
                total[3][idx] = total[3].get(idx, 0) + c
        n, err, slow, hist = total
        qs = quantiles_from_buckets(hist)
        doc["n"], doc["errors"], doc["slow"] = n, err, slow
        doc["p99_ms"] = None if qs[99] is None else qs[99] * 1e3
        doc["availability"] = None if n == 0 else 1.0 - err / n
        doc["buckets"] = {str(i): c for i, c in sorted(hist.items())}
        # burn rates against the declared objectives
        burns = []
        if n:
            if self.avail is not None:
                burns.append((err / n) / (1.0 - self.avail))
            if self.p99_ms is not None:
                ok_n = n - err
                if ok_n:
                    burns.append((slow / ok_n) / 0.01)
        doc["burn_rate"] = max(burns) if burns else 0.0
        # verdict for this window
        if n == 0 or (self.avail is None and self.p99_ms is None):
            doc["verdict"] = "no_data"
        else:
            bad = False
            if self.avail is not None \
                    and doc["availability"] < self.avail:
                bad = True
            if self.p99_ms is not None and doc["p99_ms"] is not None \
                    and doc["p99_ms"] > self.p99_ms:
                bad = True
            doc["verdict"] = "violated" if bad else "ok"
        return doc

    def snapshot(self) -> dict:
        """The full ``/slo`` document: per-window per-op quantiles,
        availability, burn rates, objectives, overall verdict, and
        the raw geometric buckets fleet aggregation merges."""
        now = int(self._time())
        with self._lock:
            windows = {label: self._window_locked(now, seconds)
                       for label, seconds in WINDOWS}
            degraded = self._degraded
        doc = {"objectives": {"p99_ms": self.p99_ms,
                              "avail": self.avail},
               "windows": {}, "degraded": degraded, "ts": now}
        worst = "no_data"
        rank = {"no_data": 0, "ok": 1, "violated": 2}
        for label, ops in windows.items():
            wdoc = self._window_doc(ops)
            doc["windows"][label] = wdoc
            if rank[wdoc["verdict"]] > rank[worst]:
                worst = wdoc["verdict"]
        doc["verdict"] = worst
        self._export_gauges(doc)
        with self._lock:
            self._verdict_cache = (doc, self._time())
        return doc

    def _export_gauges(self, doc):
        w1 = doc["windows"].get("1m", {})
        if w1.get("p99_ms") is not None:
            telemetry.gauge_set("slo.p99_ms", w1["p99_ms"])
        if w1.get("availability") is not None:
            telemetry.gauge_set("slo.availability",
                                w1["availability"])
        for label, wdoc in doc["windows"].items():
            telemetry.gauge_set(f"slo.burn_rate.{label}",
                                wdoc.get("burn_rate", 0.0))
        telemetry.gauge_set("slo.degraded",
                            1.0 if doc["degraded"] else 0.0)
        telemetry.gauge_set(
            "slo.queue_scale",
            DEGRADE_QUEUE_SCALE if doc["degraded"] else 1.0)

    # -- degrade hook -------------------------------------------------------
    def maybe_degrade(self) -> bool:
        """Refresh the degrade verdict from the 1 m burn rate —
        rate-limited to once per second so the admission hot path
        stays O(1).  Returns the current degraded flag."""
        now = self._time()
        with self._lock:
            cached, asof = self._verdict_cache
            fresh = cached is not None and now - asof < 1.0
            degraded = self._degraded
        if fresh:
            return degraded
        snap = self.snapshot()  # refreshes cache + gauges
        burn_1m = snap["windows"]["1m"]["burn_rate"]
        with self._lock:
            was = self._degraded
            if not was and burn_1m >= DEGRADE_BURN:
                self._degraded = True
            elif was and burn_1m < 1.0:
                self._degraded = False
            now_deg = self._degraded
        if now_deg and not was:
            telemetry.counter_add("slo.degrades")
            telemetry.gauge_set("slo.degraded", 1.0)
        elif was and not now_deg:
            telemetry.counter_add("slo.recoveries")
            telemetry.gauge_set("slo.degraded", 0.0)
        return now_deg

    def effective_queue_max(self, queue_max) -> int:
        """Admission's queue bound under the degrade hook: shrunk to
        ``DEGRADE_QUEUE_SCALE`` of the configured bound while the
        1-minute burn is hot, restored on recovery.  0 (unbounded)
        degrades to a bound of 8 — an unbounded queue is exactly the
        failure mode the hook exists to prevent."""
        if not self.maybe_degrade():
            return int(queue_max)
        if not queue_max:
            return 8
        return max(1, int(int(queue_max) * DEGRADE_QUEUE_SCALE))

    def verdict_doc(self) -> dict:
        """The compact form riding ``/v1/stats``."""
        snap = self.snapshot()
        return {
            "verdict": snap["verdict"],
            "degraded": snap["degraded"],
            "burn_rate": {label: w["burn_rate"]
                          for label, w in snap["windows"].items()},
            "objectives": snap["objectives"],
        }


_tracker = None
_tracker_lock = threading.Lock()


def tracker() -> SloTracker:
    """The process singleton (objectives read from env at first
    use)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = SloTracker()
        return _tracker


def reset(p99_ms=None, avail=None, time_fn=time.time) -> SloTracker:
    """Replace the singleton (tests; objective changes)."""
    global _tracker
    with _tracker_lock:
        _tracker = SloTracker(p99_ms=p99_ms, avail=avail,
                              time_fn=time_fn)
        return _tracker


def record(op, latency_s, ok=True):
    tracker().record(op, latency_s, ok=ok)


def effective_queue_max(queue_max) -> int:
    return tracker().effective_queue_max(queue_max)
