"""IERS Earth-orientation parameters (polar motion, UT1-UTC).

The reference delegates EOP handling to astropy's auto-downloaded IERS
tables, consumed by erfa inside ``gcrs_posvel_from_itrf`` (reference:
src/pint/erfautils.py:1-85).  Here the table layer is owned natively:
standard IERS products dropped into ``$PINT_TPU_IERS_DIR`` (or
``./iers``) are parsed host-side and applied in the ITRF->GCRS chain
(pint_tpu/obs/erot.py) as

    r_GCRS = P . N . R3(-GAST(UT1)) . W(xp, yp) . r_ITRF

With no data present the EOP are zero (UT1 = UTC, no polar motion) —
exactly the documented ~1.4 us (UT1) and ~30 ns (polar motion) builtin
accuracy terms in ACCURACY.md; installing a finals file removes them.

Supported formats, auto-detected by filename:

- ``finals2000A*`` / ``finals.*`` — IERS Bulletin A fixed-width
  (the standard rapid-service file): MJD in cols 8-15, PM-x in 19-27,
  PM-y in 38-46, UT1-UTC in 59-68 (1-based); rows without a UT1 value
  (far-future predictions) are dropped.
- ``eopc04*`` — IERS EOP C04 whitespace columns
  (yr mo dy MJD xp yp UT1-UTC ...), comment/header lines skipped.
- ``eop*`` (e.g. ``eop.dat``) — simple whitespace table
  ``MJD  xp_arcsec  yp_arcsec  ut1_minus_utc_sec``.  (Discovery is by
  filename prefix — finals*/eopc04*/eop* — so other names are only
  reachable through ``EOPTable.from_file`` directly.)

UT1-UTC contains 1 s leap-second steps, so interpolating it directly
would smear each step over a day.  The table converts to the continuous
UT1-TAI at load time, interpolates that, and adds TAI-UTC back at the
query epoch.
"""

from __future__ import annotations

import os
import warnings

import numpy as np

from pint_tpu.time.scales import tai_minus_utc

__all__ = ["EOPTable", "get_eop", "eop_data_identity"]


class EOPTable:
    """Tabulated (xp, yp, UT1-UTC) vs UTC MJD with linear interpolation.

    Attributes are plain float64 arrays: ``mjd`` (UTC), ``xp``/``yp``
    [arcsec], ``dut1`` [s].  Queries outside the tabulated span clamp to
    the end values (matching the reference's astropy behavior of using
    the last available EOP rather than discontinuously dropping to 0).
    """

    #: first MJD of the leap-second era (1972-01-01); earlier rows (the
    #: C04 series starts in 1962) are dropped — no supported TOA can
    #: fall there (pint_tpu.time.scales rejects pre-1972 UTC).
    MIN_MJD = 41317.0

    def __init__(self, mjd, xp, yp, dut1):
        mjd = np.asarray(mjd, np.float64)
        keep = mjd >= self.MIN_MJD
        order = np.argsort(mjd[keep])
        self.mjd = mjd[keep][order]
        self.xp = np.asarray(xp, np.float64)[keep][order]
        self.yp = np.asarray(yp, np.float64)[keep][order]
        self.dut1 = np.asarray(dut1, np.float64)[keep][order]
        if self.mjd.size == 0:
            raise ValueError("empty EOP table (after dropping pre-1972 rows)")
        # continuous realization for interpolation across leap seconds
        self._ut1_tai = self.dut1 - tai_minus_utc(
            np.floor(self.mjd).astype(np.int64)
        )

    def at(self, mjd_utc):
        """(xp [arcsec], yp [arcsec], UT1-UTC [s]) at UTC MJD(s)."""
        m = np.asarray(mjd_utc, np.float64)
        xp = np.interp(m, self.mjd, self.xp)
        yp = np.interp(m, self.mjd, self.yp)
        ut1_tai = np.interp(m, self.mjd, self._ut1_tai)
        dut1 = ut1_tai + tai_minus_utc(np.floor(m).astype(np.int64))
        return xp, yp, dut1

    # -- parsers ---------------------------------------------------------

    @classmethod
    def from_finals2000a(cls, path):
        """Parse the fixed-width IERS Bulletin A ``finals2000A`` layout."""
        mjd, xp, yp, dut1 = [], [], [], []
        with open(path, "r", errors="replace") as f:
            for line in f:
                if len(line) < 68:
                    continue
                try:
                    m = float(line[7:15])
                    x = line[18:27].strip()
                    y = line[37:46].strip()
                    u = line[58:68].strip()
                    if not (x and y and u):
                        continue  # prediction rows without values
                    mjd.append(m)
                    xp.append(float(x))
                    yp.append(float(y))
                    dut1.append(float(u))
                except ValueError:
                    continue
        if not mjd:
            raise ValueError(f"no EOP rows parsed from {path}")
        return cls(mjd, xp, yp, dut1)

    @classmethod
    def from_eopc04(cls, path):
        """Parse the whitespace-column IERS EOP C04 layout.  Both the
        classic ``yr mo dy MJD xp yp UT1-UTC ...`` and the v2 (2023+)
        ``yr mo dy hh MJD xp yp UT1-UTC ...`` layouts are accepted: the
        MJD column is located by value (the first entry after the
        calendar date that looks like an MJD)."""
        mjd, xp, yp, dut1 = [], [], [], []
        with open(path, "r", errors="replace") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 7:
                    continue
                try:
                    vals = [float(p) for p in parts[:8]]
                except ValueError:
                    continue  # header
                i_mjd = next(
                    (i for i in (3, 4) if 10000.0 < vals[i] < 100000.0), None
                )
                if i_mjd is None or len(vals) < i_mjd + 4:
                    continue
                mjd.append(vals[i_mjd])
                xp.append(vals[i_mjd + 1])
                yp.append(vals[i_mjd + 2])
                dut1.append(vals[i_mjd + 3])
        if not mjd:
            raise ValueError(f"no EOP rows parsed from {path}")
        return cls(mjd, xp, yp, dut1)

    @classmethod
    def from_simple(cls, path):
        """Parse ``MJD xp yp dut1`` whitespace rows (# comments ok)."""
        rows = []
        with open(path, "r", errors="replace") as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                if len(parts) >= 4:
                    try:
                        rows.append([float(p) for p in parts[:4]])
                    except ValueError:
                        continue
        if not rows:
            raise ValueError(f"no EOP rows parsed from {path}")
        arr = np.asarray(rows, np.float64)
        return cls(arr[:, 0], arr[:, 1], arr[:, 2], arr[:, 3])

    @classmethod
    def from_file(cls, path):
        name = os.path.basename(path).lower()
        if name.startswith("finals"):
            return cls.from_finals2000a(path)
        if name.startswith("eopc04") or name.startswith("eop_c04"):
            return cls.from_eopc04(path)
        return cls.from_simple(path)


def _iers_dirs():
    from pint_tpu.obs.datadirs import search_dirs

    return search_dirs("PINT_TPU_IERS_DIR", "iers")


def _find_eop_file():
    """First EOP file in the search dirs, by preference order."""
    for d in _iers_dirs():
        names = sorted(os.listdir(d))
        for want in ("finals", "eopc04", "eop_c04", "eop"):
            for n in names:
                if n.lower().startswith(want):
                    return os.path.join(d, n)
    return None


def eop_data_identity():
    """Provenance string over the EOP search dirs (name, mtime, size) —
    part of the prepared-TOA cache hash, same contract as
    ``pint_tpu.obs.clock.clock_data_identity``."""
    from pint_tpu.obs.datadirs import data_identity

    return data_identity(_iers_dirs())


_cached = None  # (identity, EOPTable-or-None)


def get_eop():
    """The active EOP table, or None (zero EOP).  Memoized on data
    provenance so installing/updating a finals file mid-process takes
    effect on the next prepared dataset."""
    global _cached
    ident = eop_data_identity()
    if _cached is not None and _cached[0] == ident:
        return _cached[1]
    path = _find_eop_file()
    table = None
    if path is not None:
        try:
            table = EOPTable.from_file(path)
        except (OSError, ValueError) as e:
            warnings.warn(f"failed to parse EOP file {path}: {e}; "
                          "proceeding with zero EOP (UT1=UTC, no polar motion)")
    _cached = (ident, table)
    return table
