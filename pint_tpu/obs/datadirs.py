"""Shared runtime-data directory discovery + provenance.

Clock files ($PINT_TPU_CLOCK_DIR / ./clock) and IERS EOP files
($PINT_TPU_IERS_DIR / ./iers) use the same two-location search and the
same (name, mtime, size) provenance string that feeds the prepared-TOA
cache hash (reference analogue: the astropy download cache +
``check_hashes`` at src/pint/toa.py:1856; here data is local-only).
"""

from __future__ import annotations

import os

__all__ = ["search_dirs", "data_identity"]


def search_dirs(env_var, default_subdir):
    """Existing directories to search: $env_var (if set) then
    ./default_subdir."""
    dirs = []
    env = os.environ.get(env_var)
    if env:
        dirs.append(env)
    dirs.append(default_subdir)
    return [d for d in dirs if os.path.isdir(d)]


def data_identity(dirs):
    """Provenance string over every file in ``dirs`` (name, mtime,
    size) — changing, adding, or removing any file changes the string,
    which invalidates prepared-TOA caches hashed over it."""
    parts = []
    for d in dirs:
        for f in sorted(os.listdir(d)):
            p = os.path.join(d, f)
            try:
                st = os.stat(p)
            except OSError:
                continue
            parts.append(f"{f}:{st.st_mtime_ns}:{st.st_size}")
    return ";".join(parts)
